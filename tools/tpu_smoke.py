"""Real-chip smoke: the end-to-end θ-θ drive on actual J0437 data,
jax-vs-numpy (the .claude/skills/verify recipe). Run SOLO on the chip
after the tunnel recovers, before benching.

Covers the surfaces CPU tests can't: complex-transfer discipline at
program boundaries, the Pallas warm-start batch kernel, and this
round's whole-grid retrieval — all on the axon TPU.

Run:  python tools/tpu_smoke.py
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

J0437 = os.environ.get(
    "SCINTOOLS_SMOKE_DATA",
    "/root/reference/scintools/examples/data/J0437-4715/"
    "p111220_074112.rf.pcm.dynspec")


def run(backend):
    from scintools_tpu.dynspec import Dynspec

    ds = Dynspec(filename=J0437, process=False, verbose=False,
                 backend=backend)
    ds.crop_dyn(1270, 1500)
    ds.refill()
    ds.prep_thetatheta(cwf=128, cwt=60, eta_min=0.05, eta_max=5.0,
                       neta=120, nedge=128)
    t0 = time.perf_counter()
    ds.fit_thetatheta()
    t_fit = time.perf_counter() - t0
    t0 = time.perf_counter()
    ds.calc_wavefield()
    t_wave = time.perf_counter() - t0
    return ds, t_fit, t_wave


def main():
    if not os.path.exists(J0437):
        raise SystemExit(
            f"sample epoch not found: {J0437}\n"
            "set SCINTOOLS_SMOKE_DATA to a psrflux dynspec file")
    import jax

    print(f"platform: {jax.default_backend()}")
    ds_j, tj_fit, tj_wave = run("jax")
    print(f"jax:   ththeta={ds_j.ththeta:.4f} ± {ds_j.ththetaerr:.4f}"
          f"  fit={tj_fit:.2f}s  wavefield={tj_wave:.2f}s")
    ds_n, tn_fit, tn_wave = run("numpy")
    print(f"numpy: ththeta={ds_n.ththeta:.4f} ± {ds_n.ththetaerr:.4f}"
          f"  fit={tn_fit:.2f}s  wavefield={tn_wave:.2f}s")
    rel = abs(ds_j.ththeta - ds_n.ththeta) / abs(ds_n.ththeta)
    print(f"cross-backend ththeta rel diff: {rel:.2%} "
          f"(expect <1%; skill-recorded value ~0.0595)")
    # both finite-eta grids should agree where both fitted
    both = np.isfinite(ds_j.eta_evo) & np.isfinite(ds_n.eta_evo)
    if both.any():
        d = np.abs(ds_j.eta_evo[both] - ds_n.eta_evo[both])
        s = np.maximum(ds_j.eta_evo_err[both], 1e-12)
        print(f"per-chunk |Δη|/σ: median "
              f"{np.median(d / s):.3f} over {both.sum()} chunks")
    # wavefield power sanity: |W|² lives on the dynspec scale
    wf = ds_j.wavefield
    dyn_crop = ds_j.dyn[:wf.shape[0], :wf.shape[1]]
    ratio = float(np.mean(np.abs(wf) ** 2) / np.mean(dyn_crop))
    print(f"wavefield {wf.shape}, mean |W|^2 / mean dyn = {ratio:.3g}")
    assert 0.01 < ratio < 100, "wavefield power scale is off"
    assert rel < 0.01, "cross-backend curvature disagrees >1%"
    # full retrieval + mosaic cross-backend intensity check (the
    # end-to-end guard for the complex-transfer ban on the chip),
    # gated at a COMMON curvature: each backend's own fitted η
    # differs by up to the 1% gate above, and feeding different η
    # into the θ-θ gather legitimately moves the intensity by ~1e-2
    # (measured 1.68e-2 on-chip for a 0.36% Δη) — that spread is the
    # η-fit's, already gated. With η pinned to the numpy fit, what
    # remains is pure retrieval numerics: jax f32 BY DESIGN (TPU) vs
    # the f64 numpy path floors at ~1e-3 here (measured 1.052e-3
    # both jax-on-CPU and on-chip, correlation 0.999999); gate 5e-3.
    Ij_own = np.abs(np.asarray(ds_j.wavefield)) ** 2
    In = np.abs(np.asarray(ds_n.wavefield)) ** 2
    rel_own = float(np.linalg.norm(Ij_own - In) / np.linalg.norm(In))
    print(f"wavefield intensity (each backend's own η): rel L2 "
          f"{rel_own:.3e} [informational — tracks Δη]")
    ds_j.ththeta = ds_n.ththeta
    ds_j.ththetaerr = ds_n.ththetaerr
    ds_j.thetatheta_chunks()
    ds_j.calc_wavefield()
    Ij = np.abs(np.asarray(ds_j.wavefield)) ** 2
    rel_int = float(np.linalg.norm(Ij - In) / np.linalg.norm(In))
    corr = float(np.corrcoef(Ij.ravel(), In.ravel())[0, 1])
    print(f"wavefield intensity cross-backend at common η: rel L2 "
          f"{rel_int:.3e}, corr {corr:.6f}")
    assert rel_int < 5e-3, "wavefield intensity diverges across backends"
    assert corr > 0.9999, "wavefield intensity decorrelated"
    # Gerchberg–Saxton on the chip (one fori_loop program; ri-stacks
    # at the boundary): after GS both backends carry √dyn amplitudes
    # at good pixels, so the informative comparison is the PHASE —
    # align the arbitrary global phase, then compare complex fields
    gs_j = ds_j.gerchberg_saxton(niter=3)
    gs_n = ds_n.gerchberg_saxton(niter=3)
    ph = np.vdot(gs_n.ravel(), gs_j.ravel())
    ph /= abs(ph)
    rel_gs = float(np.linalg.norm(gs_j / ph - gs_n)
                   / np.linalg.norm(gs_n))
    print(f"gerchberg_saxton cross-backend (phase-aligned): rel L2 "
          f"{rel_gs:.3e}")
    assert rel_gs < 5e-2, "GS wavefield diverges across backends"
    smoke_round5_device_paths(ds_n)
    print("TPU smoke OK")


def smoke_round5_device_paths(ds_n):
    """Round-5 device programs on the real chip: the whole-fit survey
    arc program (ops/fitarc_device.py — savgol/walk-out/parabola as
    device math), the scattered-image cubic gather (ops/scatim.py),
    and the batched VLBI composite retrieval. Each is gated against
    its f64 host oracle on the SAME data."""
    from scintools_tpu.ops.fitarc import fit_arc_batch
    from scintools_tpu.ops.scatim import scattered_image_interp
    from scintools_tpu.thth.retrieval import (vlbi_chunk_retrieval,
                                              vlbi_retrieval_batch)

    # --- survey arc fit: J0437 sspec, device vs host tail ------------
    ds_n.calc_sspec(prewhite=False, lamsteps=False, window="hanning",
                    window_frac=0.1)
    sspecs = np.stack([np.asarray(ds_n.sspec, float)] * 2)
    tdel = np.asarray(ds_n.tdel)
    fdop = np.asarray(ds_n.fdop)
    dev = fit_arc_batch(sspecs, tdel, fdop, numsteps=2000,
                        on_device=True)[0]
    host = fit_arc_batch(sspecs, tdel, fdop, numsteps=2000,
                         on_device=False)[0]
    rel_arc = abs(dev.eta - host.eta) / abs(host.eta)
    print(f"device arc fit: eta={dev.eta:.5g} vs host {host.eta:.5g} "
          f"(rel {rel_arc:.2e})")
    assert rel_arc < 1e-3, "device arc-fit tail diverges from host"

    # --- scattered image: device gather vs host gather ---------------
    lin = 10 ** (sspecs[0] / 10)
    ny, nx = 33, 65
    fx = np.linspace(-fdop.max(), fdop.max(), nx)
    fy = np.linspace(0, fdop.max(), ny)
    FX, FY = np.meshgrid(fx, fy)
    eta_si = float(tdel[-1] / fdop.max() ** 2)
    tq = (FX ** 2 + FY ** 2) * eta_si
    im_j = np.asarray(scattered_image_interp(lin, tdel, fdop, tq, FX,
                                             backend="jax"))
    im_n = scattered_image_interp(lin, tdel, fdop, tq, FX,
                                  backend="numpy")
    scale = np.abs(im_n).max()
    rel_si = float(np.max(np.abs(im_j - im_n)) / scale)
    print(f"scattered image: device vs host max rel {rel_si:.2e}")
    assert rel_si < 1e-3, "scattered-image gather diverges"

    # --- OPT-IN arc-profile Pallas kernel (ops/arc_pallas.py):
    # compile + parity + timing on the real chip, NON-FATAL — this
    # decides whether SCINTOOLS_ARC_PALLAS=1 becomes the default ----
    try:
        import time as _t

        import jax.numpy as jnp

        from scintools_tpu.ops.normsspec import (
            make_arc_profile_batch_fn)

        kwp = dict(startbin=3, cutmid=3, numsteps=2000, fold=True)
        etas2 = np.full(2, 2e-5)
        f_xla = make_arc_profile_batch_fn(tdel, fdop, pallas=False,
                                          **kwp)
        f_plk = make_arc_profile_batch_fn(tdel, fdop, pallas=True,
                                          **kwp)
        sd = jnp.asarray(sspecs, jnp.float32)
        ed = jnp.asarray(etas2)
        a = np.asarray(f_xla(sd, ed))        # compile + run
        b = np.asarray(f_plk(sd, ed))
        perr = float(np.max(np.abs(a - b))
                     / (np.max(np.abs(a)) + 1e-30))
        t0 = _t.perf_counter()
        np.asarray(f_xla(sd, ed + 1e-9))
        t_x = _t.perf_counter() - t0
        t0 = _t.perf_counter()
        np.asarray(f_plk(sd, ed + 1e-9))
        t_p = _t.perf_counter() - t0
        print(f"arc-profile pallas kernel: max rel diff {perr:.2e}, "
              f"xla {t_x:.3f}s vs pallas {t_p:.3f}s "
              f"[opt-in SCINTOOLS_ARC_PALLAS=1]")
        assert perr < 1e-4
    except Exception as e:                   # noqa: BLE001
        print(f"arc-profile pallas kernel: FAILED ({e}) — leave "
              "SCINTOOLS_ARC_PALLAS unset")

    # --- VLBI composite: batched device vs host ----------------------
    dyn = np.asarray(ds_n.dyn, float)[:64, :64]
    times = np.asarray(ds_n.times)[:64]
    freqs = np.asarray(ds_n.freqs)[:64]
    dfd_pad = 1e3 / (2 * 64 * (times[1] - times[0]))
    edges = np.arange(-16.5, 17.5) * dfd_pad
    eta_v = float(ds_n.ththeta)
    host_E, _, _ = vlbi_chunk_retrieval([dyn, dyn + 0j, dyn], edges,
                                        times, freqs, eta_v, npad=1,
                                        n_dish=2, backend="numpy")
    dev_E = vlbi_retrieval_batch(
        np.stack([np.stack([dyn, dyn + 0j, dyn])]), edges, eta_v,
        float(times[1] - times[0]), float(freqs[1] - freqs[0]),
        n_dish=2, npad=1)
    c = abs(np.vdot(host_E[0], dev_E[0, 0])) / (
        np.linalg.norm(host_E[0]) * np.linalg.norm(dev_E[0, 0])
        + 1e-30)
    print(f"vlbi composite: device-vs-host correlation {c:.6f}")
    assert c > 0.99, "VLBI batched retrieval diverges from host"


if __name__ == "__main__":
    main()
