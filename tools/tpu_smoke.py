"""Real-chip smoke: the end-to-end θ-θ drive on actual J0437 data,
jax-vs-numpy (the .claude/skills/verify recipe). Run SOLO on the chip
after the tunnel recovers, before benching.

Covers the surfaces CPU tests can't: complex-transfer discipline at
program boundaries, the Pallas warm-start batch kernel, and this
round's whole-grid retrieval — all on the axon TPU.

Run:  python tools/tpu_smoke.py
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

J0437 = os.environ.get(
    "SCINTOOLS_SMOKE_DATA",
    "/root/reference/scintools/examples/data/J0437-4715/"
    "p111220_074112.rf.pcm.dynspec")


def run(backend):
    from scintools_tpu.dynspec import Dynspec

    ds = Dynspec(filename=J0437, process=False, verbose=False,
                 backend=backend)
    ds.crop_dyn(1270, 1500)
    ds.refill()
    ds.prep_thetatheta(cwf=128, cwt=60, eta_min=0.05, eta_max=5.0,
                       neta=120, nedge=128)
    t0 = time.perf_counter()
    ds.fit_thetatheta()
    t_fit = time.perf_counter() - t0
    t0 = time.perf_counter()
    ds.calc_wavefield()
    t_wave = time.perf_counter() - t0
    return ds, t_fit, t_wave


def main():
    if not os.path.exists(J0437):
        raise SystemExit(
            f"sample epoch not found: {J0437}\n"
            "set SCINTOOLS_SMOKE_DATA to a psrflux dynspec file")
    import jax

    print(f"platform: {jax.default_backend()}")
    ds_j, tj_fit, tj_wave = run("jax")
    print(f"jax:   ththeta={ds_j.ththeta:.4f} ± {ds_j.ththetaerr:.4f}"
          f"  fit={tj_fit:.2f}s  wavefield={tj_wave:.2f}s")
    ds_n, tn_fit, tn_wave = run("numpy")
    print(f"numpy: ththeta={ds_n.ththeta:.4f} ± {ds_n.ththetaerr:.4f}"
          f"  fit={tn_fit:.2f}s  wavefield={tn_wave:.2f}s")
    rel = abs(ds_j.ththeta - ds_n.ththeta) / abs(ds_n.ththeta)
    print(f"cross-backend ththeta rel diff: {rel:.2%} "
          f"(expect <1%; skill-recorded value ~0.0595)")
    # both finite-eta grids should agree where both fitted
    both = np.isfinite(ds_j.eta_evo) & np.isfinite(ds_n.eta_evo)
    if both.any():
        d = np.abs(ds_j.eta_evo[both] - ds_n.eta_evo[both])
        s = np.maximum(ds_j.eta_evo_err[both], 1e-12)
        print(f"per-chunk |Δη|/σ: median "
              f"{np.median(d / s):.3f} over {both.sum()} chunks")
    # wavefield power sanity: |W|² lives on the dynspec scale
    wf = ds_j.wavefield
    dyn_crop = ds_j.dyn[:wf.shape[0], :wf.shape[1]]
    ratio = float(np.mean(np.abs(wf) ** 2) / np.mean(dyn_crop))
    print(f"wavefield {wf.shape}, mean |W|^2 / mean dyn = {ratio:.3g}")
    assert 0.01 < ratio < 100, "wavefield power scale is off"
    assert rel < 0.01, "cross-backend curvature disagrees >1%"
    # full retrieval + mosaic cross-backend intensity check (the
    # end-to-end guard for the complex-transfer ban on the chip),
    # gated at a COMMON curvature: each backend's own fitted η
    # differs by up to the 1% gate above, and feeding different η
    # into the θ-θ gather legitimately moves the intensity by ~1e-2
    # (measured 1.68e-2 on-chip for a 0.36% Δη) — that spread is the
    # η-fit's, already gated. With η pinned to the numpy fit, what
    # remains is pure retrieval numerics: jax f32 BY DESIGN (TPU) vs
    # the f64 numpy path floors at ~1e-3 here (measured 1.052e-3
    # both jax-on-CPU and on-chip, correlation 0.999999); gate 5e-3.
    Ij_own = np.abs(np.asarray(ds_j.wavefield)) ** 2
    In = np.abs(np.asarray(ds_n.wavefield)) ** 2
    rel_own = float(np.linalg.norm(Ij_own - In) / np.linalg.norm(In))
    print(f"wavefield intensity (each backend's own η): rel L2 "
          f"{rel_own:.3e} [informational — tracks Δη]")
    ds_j.ththeta = ds_n.ththeta
    ds_j.ththetaerr = ds_n.ththetaerr
    ds_j.thetatheta_chunks()
    ds_j.calc_wavefield()
    Ij = np.abs(np.asarray(ds_j.wavefield)) ** 2
    rel_int = float(np.linalg.norm(Ij - In) / np.linalg.norm(In))
    corr = float(np.corrcoef(Ij.ravel(), In.ravel())[0, 1])
    print(f"wavefield intensity cross-backend at common η: rel L2 "
          f"{rel_int:.3e}, corr {corr:.6f}")
    assert rel_int < 5e-3, "wavefield intensity diverges across backends"
    assert corr > 0.9999, "wavefield intensity decorrelated"
    # Gerchberg–Saxton on the chip (one fori_loop program; ri-stacks
    # at the boundary): after GS both backends carry √dyn amplitudes
    # at good pixels, so the informative comparison is the PHASE —
    # align the arbitrary global phase, then compare complex fields
    gs_j = ds_j.gerchberg_saxton(niter=3)
    gs_n = ds_n.gerchberg_saxton(niter=3)
    ph = np.vdot(gs_n.ravel(), gs_j.ravel())
    ph /= abs(ph)
    rel_gs = float(np.linalg.norm(gs_j / ph - gs_n)
                   / np.linalg.norm(gs_n))
    print(f"gerchberg_saxton cross-backend (phase-aligned): rel L2 "
          f"{rel_gs:.3e}")
    assert rel_gs < 5e-2, "GS wavefield diverges across backends"
    print("TPU smoke OK")


if __name__ == "__main__":
    main()
