"""Minimal dimensional astropy shim — JUST enough to import and run
the reference scintools' numpy-only compute paths offline for golden
generation (tools/make_golden.py). NOT a general astropy replacement.

Everything the reference's ththmod/scint_sim/dynspec sspec-ACF paths
touch dimensionally is a power of seconds (us = 1e-6·s¹,
mHz = 1e-3·s⁻¹, s³ = s³), so a unit here is (scale_to_SI, power).
Faithfulness matters only insofar as a WRONG shim would make the
goldens disagree with our independent implementation — i.e. a shim bug
shows up as a test failure, never as false confidence.
"""

from __future__ import annotations

import sys
import types

import numpy as np


class Unit:
    """Dimensional unit: value_SI = value * scale · s^power."""

    # make ndarray binary ops defer to our __r*__ (incl. in-place
    # `arr *= unit` falling back to `arr = arr * unit`)
    __array_ufunc__ = None

    def __init__(self, scale, power, name="unit"):
        self.scale = float(scale)
        # float: np.sqrt of a quantity halves the power (e.g.
        # sqrt(us/s³) → s⁻¹-like), and halves of ints are binary-exact
        self.power = float(power)
        self.name = name

    # -- unit algebra ---------------------------------------------------
    def __mul__(self, other):
        if isinstance(other, Unit):
            return Unit(self.scale * other.scale,
                        self.power + other.power,
                        f"{self.name}*{other.name}")
        return Quantity(np.asarray(other), self)

    __rmul__ = __mul__

    def __truediv__(self, other):
        if isinstance(other, Unit):
            return Unit(self.scale / other.scale,
                        self.power - other.power,
                        f"{self.name}/{other.name}")
        return Quantity(1.0 / np.asarray(other), self)

    def __rtruediv__(self, other):
        return Quantity(np.asarray(other),
                        Unit(1 / self.scale, -self.power,
                             f"1/{self.name}"))

    def __pow__(self, n):
        return Unit(self.scale ** n, self.power * n,
                    f"{self.name}**{n}")

    def is_equivalent(self, other):
        if isinstance(other, Quantity):
            other = other.unit
        return self.power == other.power

    def to(self, other):
        if not self.is_equivalent(other):
            raise UnitConversionError(f"{self.name} vs {other.name}")
        return self.scale / other.scale

    def __repr__(self):
        return f"Unit({self.name})"


class UnitConversionError(Exception):
    pass


dimensionless_unscaled = Unit(1.0, 0, "")


class Quantity(np.ndarray):
    __array_priority__ = 10000.0

    def __new__(cls, value, unit):
        obj = np.asarray(value).view(cls)
        obj.unit = unit
        return obj

    def __array_finalize__(self, obj):
        self.unit = getattr(obj, "unit", dimensionless_unscaled)

    def __getitem__(self, key):
        out = super().__getitem__(key)
        if not isinstance(out, Quantity):   # int index → bare scalar
            out = Quantity(out, self.unit)
        return out

    # -- astropy API surface used by the reference ---------------------
    @property
    def value(self):
        v = self.view(np.ndarray)
        return v[()] if v.ndim == 0 else v

    def to(self, unit):
        return Quantity(self.value * self.unit.to(unit), unit)

    def to_value(self, unit):
        return self.value * self.unit.to(unit)

    def _factor_from(self, other):
        """Conversion factor bringing ``other`` into self's unit."""
        if isinstance(other, Quantity):
            return other.value * other.unit.to(self.unit)
        if isinstance(other, Unit):
            raise TypeError("cannot add a bare unit")
        return np.asarray(other)  # dimensionless numbers

    # -- arithmetic with correct unit algebra --------------------------
    def __mul__(self, other):
        if isinstance(other, Quantity):
            return Quantity(self.value * other.value,
                            self.unit * other.unit)
        if isinstance(other, Unit):
            return Quantity(self.value, self.unit * other)
        return Quantity(self.value * np.asarray(other), self.unit)

    __rmul__ = __mul__
    __imul__ = __mul__          # `q *= unit` rebinds (astropy-like)

    def __truediv__(self, other):
        if isinstance(other, Quantity):
            return Quantity(self.value / other.value,
                            self.unit / other.unit)
        if isinstance(other, Unit):
            return Quantity(self.value, self.unit / other)
        return Quantity(self.value / np.asarray(other), self.unit)

    def __rtruediv__(self, other):
        inv = Unit(1 / self.unit.scale, -self.unit.power)
        return Quantity(np.asarray(other) / self.value, inv)

    def __pow__(self, n):
        return Quantity(self.value ** n, self.unit ** n)

    def __add__(self, other):
        return Quantity(self.value + self._factor_from(other),
                        self.unit)

    __radd__ = __add__

    def __sub__(self, other):
        return Quantity(self.value - self._factor_from(other),
                        self.unit)

    def __rsub__(self, other):
        return Quantity(self._factor_from(other) - self.value,
                        self.unit)

    def __neg__(self):
        return Quantity(-self.value, self.unit)

    def __floordiv__(self, other):
        if isinstance(other, Quantity):
            if self.unit.is_equivalent(other.unit):
                return np.asarray(
                    self.value * self.unit.to(other.unit)
                    // other.value)
            return np.asarray(self.value // other.value)
        return Quantity(self.value // np.asarray(other), self.unit)

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        """np.sqrt gets true unit algebra (the reference compares
        sqrt(tau/eta) against mHz quantities, ththmod.py:1625-1629);
        every other ufunc keeps the previous subclass passthrough
        (compute on raw values, re-attach the first input's unit) so
        already-verified golden paths are bit-unchanged."""
        if (ufunc is np.sqrt and method == "__call__"
                and len(inputs) == 1):
            q = inputs[0]
            return Quantity(np.sqrt(q.view(np.ndarray)),
                            Unit(q.unit.scale ** 0.5,
                                 q.unit.power / 2,
                                 f"({q.unit.name})**0.5"))
        arrays = [x.view(np.ndarray) if isinstance(x, Quantity) else x
                  for x in inputs]
        # unwrap any Quantity in out= (ndarray.mean passes its interim
        # result as out) or the call re-dispatches here forever
        if kwargs.get("out") is not None:
            kwargs["out"] = tuple(
                o.view(np.ndarray) if isinstance(o, Quantity) else o
                for o in kwargs["out"])
        result = getattr(ufunc, method)(*arrays, **kwargs)
        unit = next((x.unit for x in inputs
                     if isinstance(x, Quantity)),
                    dimensionless_unscaled)
        # numpy scalars too: reductions (q.max() → np.maximum.reduce)
        # must stay Quantities, as the pre-__array_ufunc__ subclass
        # wrapping made them
        if isinstance(result, (np.ndarray, np.generic)):
            return Quantity(np.asarray(result), unit)
        return result

    def _cmp(self, other, op):
        return op(self.value, self._factor_from(other))

    def __lt__(self, other):
        return self._cmp(other, np.less)

    def __le__(self, other):
        return self._cmp(other, np.less_equal)

    def __gt__(self, other):
        return self._cmp(other, np.greater)

    def __ge__(self, other):
        return self._cmp(other, np.greater_equal)

    def __eq__(self, other):
        return self._cmp(other, np.equal)

    def __ne__(self, other):
        return self._cmp(other, np.not_equal)

    def __hash__(self):
        return object.__hash__(self)


def install():
    """Register shim modules in sys.modules (idempotent)."""
    if "astropy" in sys.modules:
        return sys.modules["astropy.units"]

    units = types.ModuleType("astropy.units")
    units.Unit = Unit
    units.Quantity = Quantity
    units.UnitConversionError = UnitConversionError
    units.dimensionless_unscaled = dimensionless_unscaled
    units.s = Unit(1.0, 1, "s")
    units.us = Unit(1e-6, 1, "us")
    units.ms = Unit(1e-3, 1, "ms")
    units.Hz = Unit(1.0, -1, "Hz")
    units.mHz = Unit(1e-3, -1, "mHz")
    units.MHz = Unit(1e6, -1, "MHz")
    units.minute = Unit(60.0, 1, "min")
    units.min = units.minute
    units.hour = Unit(3600.0, 1, "hour")
    units.day = Unit(86400.0, 1, "day")
    units.m = Unit(1.0, 0, "m")          # length: dimensionless slot
    units.km = Unit(1e3, 0, "km")
    units.kpc = Unit(3.0857e19, 0, "kpc")
    units.pc = Unit(3.0857e16, 0, "pc")
    units.deg = Unit(np.pi / 180, 0, "deg")
    units.rad = Unit(1.0, 0, "rad")
    units.mas = Unit(np.pi / 180 / 3.6e6, 0, "mas")
    units.yr = Unit(3.1557e7, 1, "yr")

    sys.modules["astropy.units"] = units

    def _placeholder(name, **attrs):
        m = types.ModuleType(name)
        for k, v in attrs.items():
            setattr(m, k, v)
        sys.modules[name] = m
        return m

    class _Unavailable:
        def __init__(self, *a, **k):
            raise RuntimeError("astropy shim: not implemented — the "
                               "golden generator must not reach this")

    astropy = types.ModuleType("astropy")
    astropy.units = units
    sys.modules["astropy"] = astropy
    _placeholder("astropy.time", Time=_Unavailable)
    _placeholder("astropy.coordinates", SkyCoord=_Unavailable,
                 get_body_barycentric=_Unavailable,
                 get_body_barycentric_posvel=_Unavailable,
                 BarycentricTrueEcliptic=_Unavailable,
                 EarthLocation=_Unavailable, ICRS=_Unavailable)
    consts = _placeholder("astropy.constants")
    for name, val in (("c", 299792458.0), ("au", 1.495978707e11),
                      ("pc", 3.0857e16), ("G", 6.674e-11),
                      ("M_sun", 1.989e30)):
        setattr(consts, name, type("C", (), {"value": val})())
    _placeholder("astropy.io", fits=_Unavailable)
    _placeholder("astropy.io.fits", open=_Unavailable)

    # lmfit: module-level imports only (fits are never run here)
    _placeholder("lmfit", Parameters=_Unavailable,
                 Minimizer=_Unavailable, fit_report=_Unavailable,
                 conf_interval=_Unavailable, minimize=_Unavailable)
    _placeholder("emcee", EnsembleSampler=_Unavailable)
    return units
