"""jaxlint — the repo's unified AST static-analysis framework.

One parse per file, a registry of rule plugins over the shared tree
(ISSUE 8). Replaces the four standalone lints
(``tools/lint_excepts.py``, ``lint_import_jit.py``,
``lint_syncpoints.py``, ``lint_obs_events.py`` — kept as thin shims)
and adds three analyzers for this codebase's proven failure modes:

========  ===============  ==========================================
id        rule             catches
========  ===============  ==========================================
JL001     excepts          bare ``except:`` / silent swallow-alls
JL002     import-jit       ``jax.jit`` reachable at import time
JL003     syncpoints       premature device fences in hot paths
JL004     obs-events       undocumented slog event names
JL101     retrace-hazard   per-call jit-wrapper construction outside
                           a recognized cache; unhashable cache keys
JL102     lock-discipline  unlocked shared-state writes in threaded
                           modules
JL103     jit-boundary     host-only calls inside traced bodies
========  ===============  ==========================================

Plus the JP2xx PROGRAM-LEVEL pass (ISSUE 9, ``program.py``): every
``record_build`` jit-cache site is traced via its registered
abstract probe (``scintools_tpu/obs/programs.py``) and the resulting
jaxpr audited — probe coverage (JP200), dtype policy (JP201),
closure-constant budgets (JP202), host callbacks in hot paths
(JP203), donation-vs-formulation consistency (JP204), and the
program-fingerprint regression gate against the committed
``program_baseline.json`` (JP205).

CLI::

    python -m tools.jaxlint [paths] [--format text|json|sarif]
                            [--rules r1,r2] [--baseline FILE]
                            [--write-baseline FILE]
                            [--write-fingerprints [FILE]]
                            [--list-rules]

Exit codes: 0 clean, 1 findings, 2 usage/internal error. Escape
hatch: ``# lint-ok: <rule>: <reason>`` (legacy ``sync-ok`` /
``broad-except-ok`` / ``obs-event-ok`` markers stay honored). Full
rule catalog: docs/static-analysis.md.
"""

from .framework import (Config, FileContext, Finding, Report, Rule,  # noqa: F401
                        RULES, load_baseline, package_rel, register,
                        run, write_baseline, __version__)
from . import rules as _rules  # noqa: F401  (populates the registry)
from . import program as _program  # noqa: F401  (JP2xx rules)
