"""Output renderers for the jaxlint CLI: text, json, sarif.

The JSON document is the machine interface the tier-1 self-check
reads (``files_scanned`` / ``packages`` must be nonzero — a broken
rule or an empty scan fails loudly). The SARIF output is minimal
valid SARIF 2.1.0 for code-scanning UIs.
"""

from __future__ import annotations

import json

from .framework import RULES

SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def render_text(report):
    lines = []
    for f in report.findings:
        rule = RULES.get(f.rule)
        rid = rule.id if rule else f.rule
        lines.append(f"{f.path}:{f.line}: [{rid} {f.rule}] "
                     f"{f.message}")
    lines.append(
        f"jaxlint: {len(report.findings)} finding(s) in "
        f"{report.files_scanned} file(s) "
        f"({report.baselined} baselined, {report.suppressed} "
        f"marker-suppressed) in {report.wall_time_s:.2f}s")
    return "\n".join(lines)


def render_json(report):
    return json.dumps(report.as_dict(), indent=1, sort_keys=False)


def render_sarif(report):
    rules_meta = []
    for name in report.rules:
        rule = RULES.get(name)
        if rule is None:
            continue
        rules_meta.append({
            "id": rule.id,
            "name": rule.name,
            "shortDescription": {"text": rule.short or rule.name},
        })
    results = []
    for f in report.findings:
        rule = RULES.get(f.rule)
        results.append({
            "ruleId": rule.id if rule else f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.rel.replace("\\", "/")},
                    "region": {"startLine": max(1, f.line)},
                },
            }],
        })
    doc = {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "jaxlint",
                "informationUri":
                    "docs/static-analysis.md",
                "rules": rules_meta,
            }},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=1)


RENDERERS = {"text": render_text, "json": render_json,
             "sarif": render_sarif}
