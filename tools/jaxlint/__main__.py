"""CLI entry: ``python -m tools.jaxlint`` (see package docstring)."""

from __future__ import annotations

import argparse
import os
import sys

from .framework import (Config, RULES, load_baseline, run,
                        write_baseline)
from .formats import RENDERERS


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def build_parser():
    p = argparse.ArgumentParser(
        prog="python -m tools.jaxlint",
        description="Unified AST static analysis for scintools_tpu "
                    "(rule catalog: docs/static-analysis.md)")
    p.add_argument("paths", nargs="*",
                   help="files/directories to scan (default: the "
                        "scintools_tpu package)")
    p.add_argument("--format", choices=sorted(RENDERERS),
                   default="text", dest="fmt")
    p.add_argument("--rules",
                   help="comma-separated rule names to run "
                        "(default: all)")
    p.add_argument("--baseline",
                   help="JSON baseline of grandfathered findings to "
                        "suppress")
    p.add_argument("--write-baseline", metavar="FILE",
                   help="write current findings as a new baseline "
                        "and exit 0 (stale entries that no longer "
                        "fire are pruned and the pruned count "
                        "reported; --baseline is ignored for the "
                        "scan so still-firing grandfathered findings "
                        "are retained)")
    p.add_argument("--write-fingerprints", metavar="FILE", nargs="?",
                   const="", default=None,
                   help="write the JP205 program-fingerprint "
                        "baseline from the current program pass and "
                        "exit 0 (default FILE: "
                        "tools/jaxlint/program_baseline.json; prunes "
                        "entries for vanished sites and reports the "
                        "count)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("-o", "--output", help="write report here instead "
                                          "of stdout")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule in RULES.values():
            scope = ", ".join(rule.scope) if rule.scope else "package"
            print(f"{rule.id}  {rule.name:<16} [{scope}]  "
                  f"{rule.short}")
        return 0

    targets = args.paths or [os.path.join(_repo_root(),
                                          "scintools_tpu")]
    for t in targets:
        if not os.path.exists(t):
            print(f"jaxlint: no such path: {t}", file=sys.stderr)
            return 2

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(f"jaxlint: unknown rule(s): {', '.join(unknown)} "
                  f"(have: {', '.join(RULES)})", file=sys.stderr)
            return 2

    baseline = None
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError, KeyError) as e:
            print(f"jaxlint: cannot read baseline {args.baseline}: "
                  f"{e}", file=sys.stderr)
            return 2

    try:
        # --write-baseline snapshots the FULL current findings, so
        # the scan ignores any --baseline (else still-firing
        # grandfathered findings would silently drop from the new
        # file and regress un-gated)
        report = run(targets, rules=rules,
                     config=Config(repo_root=_repo_root()),
                     baseline=None if args.write_baseline
                     else baseline)
    except Exception as e:   # an internal rule crash must be LOUD
        print(f"jaxlint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.write_baseline:
        pruned = 0
        if os.path.exists(args.write_baseline):
            old = load_baseline(args.write_baseline)
            pruned = len(old - {f.fingerprint()
                                for f in report.findings})
        write_baseline(args.write_baseline, report.findings)
        print(f"jaxlint: wrote {len(report.findings)} finding(s) to "
              f"baseline {args.write_baseline} "
              f"({pruned} stale entr{'y' if pruned == 1 else 'ies'} "
              f"pruned)")
        return 0

    if args.write_fingerprints is not None:
        from .program import baseline_path, write_program_baseline

        if report.program is None:
            print("jaxlint: program pass did not run (no "
                  "record_build sites in the scanned targets or no "
                  "JP rules active)", file=sys.stderr)
            return 2
        path = args.write_fingerprints or baseline_path(
            Config(repo_root=_repo_root()))
        written, pruned = write_program_baseline(
            path, report.program["summaries"])
        print(f"jaxlint: wrote {written} program fingerprint(s) to "
              f"{path} ({pruned} stale site(s) pruned, "
              f"{report.program['sites']} site(s) scanned)")
        return 0

    out = RENDERERS[args.fmt](report)
    if args.output:
        with open(args.output, "w", encoding="utf-8") as fh:
            fh.write(out + "\n")
    else:
        print(out)
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
