"""Core of the jaxlint unified AST analysis framework.

The four standalone repo lints (``tools/lint_excepts.py``,
``lint_import_jit.py``, ``lint_syncpoints.py``, ``lint_obs_events.py``)
each parsed every file themselves — four grep-adjacent passes with four
marker syntaxes and four exit conventions. This module replaces the
plumbing with one framework:

- :class:`FileContext` — ONE ``ast.parse`` per file per run (pinned by
  the ``FileContext.parse_count`` probe in tests), plus the shared
  derived analyses every rule needs (parent links, enclosing-function
  chains, per-line escape-hatch markers);
- :class:`Rule` + :func:`register` — rule plugins declare an id, a
  package-relative scope, and a ``check(ctx, config)``; the registry
  is what ``--list-rules`` and the CLI ``--rules`` filter see;
- :func:`run` — walks the targets once, builds one context per file,
  runs every applicable rule over the shared tree, applies marker
  suppression and the ``--baseline`` grandfather file, and returns a
  :class:`Report` carrying findings + scan accounting (files scanned
  per package, parse count, wall time) so a broken rule or an empty
  scan fails loudly instead of silently passing.

Escape hatch: one unified marker ::

    ...offending line...  # lint-ok: <rule>: <reason>

suppresses findings of ``<rule>`` on that line. The three legacy
markers stay honored and map onto rules: ``# sync-ok: <reason>``
(syncpoints), ``# broad-except-ok: <reason>`` (excepts),
``# obs-event-ok: <name>`` (obs-events). For ``obs-events`` the first
token of the reason names the emitted event (which is then
catalog-checked like any literal).
"""

from __future__ import annotations

import ast
import json
import os
import re
import time

__version__ = "1.0"

#: unified escape hatch: ``# lint-ok: rule[,rule2]: reason``
MARKER_RE = re.compile(
    r"#\s*lint-ok:\s*([A-Za-z0-9_-]+(?:\s*,\s*[A-Za-z0-9_-]+)*)"
    r"\s*(?::\s*(.*))?")

#: legacy marker → rule name (kept working forever; annotated lines
#: from ISSUEs 2/4/5 must not need a rewrite)
LEGACY_MARKERS = {
    "sync-ok": "syncpoints",
    "broad-except-ok": "excepts",
    "obs-event-ok": "obs-events",
}
_LEGACY_RE = re.compile(
    r"#\s*(sync-ok|broad-except-ok|obs-event-ok)\s*:?\s*([^#]*)")

PACKAGE = "scintools_tpu"


class Finding:
    """One rule violation at ``path:line``.

    ``data`` carries rule-specific extras (e.g. the event name for
    obs-events). The :meth:`fingerprint` is line-number-insensitive
    (rule, package-relative path, stripped source line) so a baseline
    survives unrelated edits above the finding.
    """

    __slots__ = ("rule", "path", "rel", "line", "message", "data",
                 "code")

    def __init__(self, rule, path, line, message, rel=None, data=None,
                 code=""):
        self.rule = rule
        self.path = path
        self.rel = rel or path
        self.line = int(line)
        self.message = message
        self.data = data or {}
        self.code = code

    def fingerprint(self):
        return (self.rule, self.rel.replace(os.sep, "/"),
                self.code.strip())

    def as_dict(self):
        return {"rule": self.rule, "path": self.path, "rel": self.rel,
                "line": self.line, "message": self.message,
                "code": self.code, **(
                    {"data": self.data} if self.data else {})}

    def __repr__(self):
        return (f"Finding({self.rule}, {self.rel}:{self.line}, "
                f"{self.message!r})")

    # tuple-compat for the legacy shims: (line, message)
    def legacy(self):
        return (self.line, self.message)


class FileContext:
    """One parsed file shared by every rule in a run.

    ``parse_count`` is a class-level probe: tests pin that a full-tree
    run parses each file exactly once (the whole point of unifying the
    four lints).
    """

    parse_count = 0

    def __init__(self, path, source=None, rel=None):
        self.path = path
        if source is None:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
        self.source = source
        self.lines = source.splitlines()
        self.rel = (rel if rel is not None
                    else package_rel(path) or os.path.basename(path))
        self.syntax_error = None
        try:
            self.tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            self.tree = ast.Module(body=[], type_ignores=[])
            self.syntax_error = e
        FileContext.parse_count += 1
        self._markers = None
        self._parents = None
        self._nodes = None
        self._functions = None

    # ---- escape-hatch markers ---------------------------------------
    @property
    def markers(self):
        """``{lineno: [(rule_name, payload), ...]}`` for every
        unified ``# lint-ok:`` and legacy marker in the file."""
        if self._markers is None:
            out = {}
            for i, line in enumerate(self.lines, start=1):
                if "#" not in line:
                    continue
                m = MARKER_RE.search(line)
                if m:
                    rules = [r.strip() for r in m.group(1).split(",")]
                    payload = (m.group(2) or "").strip()
                    out.setdefault(i, []).extend(
                        (r, payload) for r in rules)
                lm = _LEGACY_RE.search(line)
                if lm:
                    out.setdefault(i, []).append(
                        (LEGACY_MARKERS[lm.group(1)],
                         lm.group(2).strip()))
            self._markers = out
        return self._markers

    def marked(self, lineno, rule):
        """Payload string when ``lineno`` carries a marker for
        ``rule`` (empty string for a bare marker), else None. A
        marker may sit on the flagged line itself or in the block of
        comment-only lines immediately above it (long flagged lines
        stay within the line-length budget)."""
        candidates = [lineno]
        i = lineno - 1
        while i >= 1 and self.line_at(i).lstrip().startswith("#"):
            candidates.append(i)
            i -= 1
        for ln in candidates:
            for name, payload in self.markers.get(ln, ()):
                if name == rule:
                    return payload
        return None

    def line_at(self, lineno):
        if 0 < lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    # ---- shared derived analyses ------------------------------------
    @property
    def nodes(self):
        """Every AST node, walked once and shared by all rules —
        ``ast.walk`` re-runs ``iter_child_nodes`` per call, which is
        the bulk of a full-tree scan's cost at seven rules."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes

    @property
    def parents(self):
        """``{id(node): parent_node}`` over the whole tree (built
        once, shared by every rule that needs lexical context)."""
        if self._parents is None:
            par = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    par[id(child)] = node
            self._parents = par
        return self._parents

    def ancestors(self, node):
        """Lexical ancestor chain of ``node``, innermost first."""
        out = []
        cur = self.parents.get(id(node))
        while cur is not None:
            out.append(cur)
            cur = self.parents.get(id(cur))
        return out

    @property
    def functions(self):
        """Every function/lambda node, shared across rules."""
        if self._functions is None:
            self._functions = [
                n for n in self.nodes
                if isinstance(n, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda))]
        return self._functions

    def enclosing_functions(self, node):
        """Enclosing FunctionDef/AsyncFunctionDef/Lambda chain,
        innermost first (empty at module level). Computed by line
        interval containment — function extents are disjoint or
        nested, so containment is exact and avoids materialising a
        full parent map per file. A node on a function's own
        decorator lines is (correctly) OUTSIDE that function."""
        ln = getattr(node, "lineno", None)
        if ln is None:
            return []
        end = getattr(node, "end_lineno", None) or ln
        col = getattr(node, "col_offset", 0)
        out = []
        for fn in self.functions:
            if fn is node:
                continue
            fln = fn.lineno
            fend = getattr(fn, "end_lineno", None) or fln
            if fln < ln or (fln == ln
                            and fn.col_offset <= col):
                if fend > end or (fend == end and fln <= ln):
                    out.append(fn)
        out.sort(key=lambda f: (
            ((getattr(f, "end_lineno", None) or f.lineno)
             - f.lineno),
            -f.col_offset))
        return out


def package_rel(path):
    """Path relative to the ``scintools_tpu`` package root
    ('/'-separated), or None when the file is outside the package.
    Rule scopes are expressed against this."""
    parts = os.path.abspath(path).replace(os.sep, "/").split("/")
    if PACKAGE not in parts:
        return None
    idx = len(parts) - 1 - parts[::-1].index(PACKAGE)
    rel = "/".join(parts[idx + 1:])
    return rel or None


# ---------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------

RULES = {}          # name -> rule instance, in registration order


def register(cls):
    """Class decorator adding one instance of ``cls`` to the
    registry."""
    RULES[cls.name] = cls()
    return cls


class Rule:
    """Base class for rule plugins.

    Subclasses set ``id`` (stable SARIF id, ``JLxxx``), ``name``
    (marker / CLI name), ``short`` (one-liner for --list-rules),
    ``scope`` (package-relative path prefixes the rule applies to;
    None = whole package) and ``exclude`` (package-relative suffixes
    exempt because their JOB is the flagged behavior), then implement
    ``check(ctx, config) -> iterable[Finding]``.

    ``self_markers=True`` opts the rule out of the runner's generic
    line-marker suppression (obs-events consumes its marker payload
    itself: the named event is still catalog-checked).
    """

    id = "JL000"
    name = "rule"
    short = ""
    scope = None
    exclude = ()
    self_markers = False

    def applies(self, rel):
        if rel is None:
            return True
        rel = rel.replace(os.sep, "/")
        if any(rel.endswith(e) for e in self.exclude):
            return False
        if self.scope is None:
            return True
        return any(rel == s or rel.startswith(s) for s in self.scope)

    def check(self, ctx, config):
        raise NotImplementedError

    def finding(self, ctx, line, message, data=None):
        return Finding(self.name, ctx.path, line, message, rel=ctx.rel,
                       data=data, code=ctx.line_at(line))

    # ---- direct (fixture/test) API ----------------------------------
    def scan_source(self, source, filename="<string>", config=None):
        """Run just this rule over one source blob, with marker
        suppression applied — the golden-corpus entry point."""
        ctx = FileContext(filename, source=source, rel=filename)
        config = config or Config()
        if ctx.syntax_error is not None:
            e = ctx.syntax_error
            return [Finding(self.name, filename, e.lineno or 0,
                            f"syntax error: {e.msg}", rel=filename)]
        out = []
        for f in self.check(ctx, config):
            if not self.self_markers \
                    and ctx.marked(f.line, self.name) is not None:
                continue
            out.append(f)
        return sorted(out, key=lambda f: (f.line, f.message))


class Config:
    """Run-wide configuration shared by every rule."""

    def __init__(self, repo_root=None, obs_docs=None):
        self.repo_root = repo_root or _default_repo_root()
        self._obs_docs = obs_docs
        self._obs_catalog = None
        self._metric_catalog = None

    @property
    def obs_docs(self):
        if self._obs_docs is None:
            docs = os.path.join(self.repo_root, "docs")
            self._obs_docs = [
                p for p in (os.path.join(docs, "observability.md"),
                            os.path.join(docs, "serving.md"),
                            os.path.join(docs, "fleet.md"))
                if os.path.exists(p)]
        return self._obs_docs

    @property
    def obs_catalog(self):
        """Backtick-quoted dotted names across the obs event-catalog
        docs (cached once per run)."""
        if self._obs_catalog is None:
            names = set()
            for path in self.obs_docs:
                with open(path, encoding="utf-8") as fh:
                    names |= set(re.findall(
                        r"`([a-z0-9_]+(?:\.[a-z0-9_]+)+)`", fh.read()))
            self._obs_catalog = names
        return self._obs_catalog

    @property
    def metric_catalog(self):
        """Backtick-quoted snake_case identifiers (optionally with a
        ``{label=...}`` suffix) across the same catalog docs — the
        documented-metric set the JL005 ``metric-hygiene`` rule
        checks registrations against. Underscore-free identifiers
        are excluded (they are ordinary code words, not metric
        names)."""
        if self._metric_catalog is None:
            names = set()
            for path in self.obs_docs:
                with open(path, encoding="utf-8") as fh:
                    names |= set(re.findall(
                        r"`([a-z][a-z0-9_]*)(?:\{[^`]*\})?`",
                        fh.read()))
            self._metric_catalog = {n for n in names if "_" in n}
        return self._metric_catalog


def _default_repo_root():
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


# ---------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------

class Report:
    """Outcome of one run: surviving findings + scan accounting."""

    def __init__(self):
        self.findings = []
        self.suppressed = 0       # marker-suppressed
        self.baselined = 0        # baseline-suppressed
        self.files_scanned = 0
        self.parse_count = 0
        self.packages = {}        # first path component -> file count
        self.rules = []
        self.wall_time_s = 0.0
        self.program = None       # JP2xx pass stats (when it ran)

    @property
    def exit_code(self):
        return 1 if self.findings else 0

    def as_dict(self):
        doc = {
            "tool": "jaxlint",
            "version": __version__,
            "wall_time_s": round(self.wall_time_s, 4),
            "files_scanned": self.files_scanned,
            "parse_count": self.parse_count,
            "packages": dict(sorted(self.packages.items())),
            "rules": list(self.rules),
            "n_findings": len(self.findings),
            "suppressed": self.suppressed,
            "baselined": self.baselined,
            "findings": [f.as_dict() for f in self.findings],
        }
        if self.program is not None:
            doc["program"] = {k: v for k, v in self.program.items()
                              if k != "summaries"}
        return doc


def iter_py_files(target):
    """Yield ``.py`` files under ``target`` (a file or directory), in
    sorted deterministic order."""
    if os.path.isfile(target):
        yield target
        return
    for base, dirs, names in sorted(os.walk(target)):
        dirs.sort()
        for name in sorted(names):
            if name.endswith(".py"):
                yield os.path.join(base, name)


def load_baseline(path):
    """Baseline file → set of finding fingerprints. The file is JSON:
    ``{"version": 1, "entries": [{"rule", "path", "code"}, ...]}``."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    return {(e["rule"], e["path"].replace(os.sep, "/"),
             e["code"].strip()) for e in doc.get("entries", ())}


def write_baseline(path, findings):
    entries = [{"rule": f.rule, "path": f.rel.replace(os.sep, "/"),
                "code": f.code.strip()} for f in findings]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "entries": entries}, fh, indent=1,
                  sort_keys=True)
        fh.write("\n")


def run(targets, rules=None, config=None, baseline=None,
        respect_scope=True):
    """Run the framework over ``targets`` (files/directories).

    ``rules`` — iterable of rule names (default: every registered
    rule); ``baseline`` — set of fingerprints (or a path) to
    grandfather; ``respect_scope=False`` applies every rule to every
    file regardless of its declared package scope (fixture runs).
    """
    from . import rules as _rules_pkg  # noqa: F401  (registers rules)
    from . import program as _program  # registers the JP2xx rules

    t0 = time.perf_counter()
    config = config or Config()
    if isinstance(baseline, str):
        baseline = load_baseline(baseline)
    baseline = baseline or set()
    active = [RULES[n] for n in (rules or RULES.keys())]
    report = Report()
    report.rules = [r.name for r in active]
    p0 = FileContext.parse_count
    program_rules = [r for r in active
                     if getattr(r, "program", False)]
    site_map = {}

    seen = set()
    for target in targets:
        for path in iter_py_files(target):
            apath = os.path.abspath(path)
            if apath in seen:
                continue
            seen.add(apath)
            ctx = FileContext(path)
            report.files_scanned += 1
            rel = ctx.rel.replace(os.sep, "/")
            pkg = rel.split("/")[0] if "/" in rel else "."
            report.packages[pkg] = report.packages.get(pkg, 0) + 1
            if ctx.syntax_error is not None:
                e = ctx.syntax_error
                report.findings.append(Finding(
                    "parse", path, e.lineno or 0,
                    f"syntax error: {e.msg}", rel=ctx.rel))
                continue
            if program_rules:
                _program.collect_sites(ctx, site_map)
            for rule in active:
                if respect_scope and not rule.applies(ctx.rel):
                    continue
                for f in rule.check(ctx, config):
                    if not rule.self_markers and \
                            ctx.marked(f.line, rule.name) is not None:
                        report.suppressed += 1
                        continue
                    if f.fingerprint() in baseline:
                        report.baselined += 1
                        continue
                    report.findings.append(f)

    # JP2xx program pass: runs once over the statically-collected
    # site map (skipped entirely — no jax import — when the scanned
    # targets contain no record_build sites, e.g. fixture runs)
    if program_rules and site_map:
        pfindings, pstats = _program.run_program_pass(
            site_map, program_rules, config)
        report.program = pstats
        for f in pfindings:
            if f.fingerprint() in baseline:
                report.baselined += 1
                continue
            report.findings.append(f)

    report.parse_count = FileContext.parse_count - p0
    report.findings.sort(key=lambda f: (f.rel, f.line, f.rule,
                                        f.message))
    report.wall_time_s = time.perf_counter() - t0
    return report
