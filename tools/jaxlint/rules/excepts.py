"""JL001 ``excepts`` — exception hygiene (ported from
tools/lint_excepts.py, ISSUE 2).

Two patterns defeat the robustness layer by hiding failures the
survey runner / fallback ladder is supposed to see and report:

- bare ``except:`` — catches SystemExit/KeyboardInterrupt too, so a
  survey cannot even be stopped cleanly;
- ``except Exception:`` (or BaseException) whose body is ONLY
  ``pass``/``...`` — the classic swallow-all that turns a corrupt
  epoch into silent garbage.

Broad handlers that *do something* (log, return a fallback, re-raise)
are allowed. Escape hatch: ``# lint-ok: excepts: <reason>`` (legacy
``# broad-except-ok: <reason>`` still honored) on the ``except``
line.
"""

from __future__ import annotations

import ast

from ..framework import Rule, register

_BROAD = ("Exception", "BaseException")


def _is_broad(node):
    """``except Exception``/``BaseException`` (bound or not),
    including tuple forms containing one."""
    t = node.type
    if t is None:
        return False
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    return any(isinstance(e, ast.Name) and e.id in _BROAD
               for e in elts)


def _swallows(node):
    """Handler body is only ``pass``/``...`` — nothing logged,
    nothing returned, nothing re-raised."""
    for stmt in node.body:
        if isinstance(stmt, ast.Pass):
            continue
        if (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)
                and stmt.value.value is Ellipsis):
            continue
        return False
    return True


@register
class ExceptsRule(Rule):
    id = "JL001"
    name = "excepts"
    short = ("bare 'except:' or silent 'except Exception: pass' "
             "swallow-alls")
    scope = None                      # whole package

    def check(self, ctx, config):
        for node in ctx.nodes:
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield self.finding(
                    ctx, node.lineno,
                    "bare 'except:' (catches KeyboardInterrupt/"
                    "SystemExit; name the exceptions)")
            elif _is_broad(node) and _swallows(node):
                yield self.finding(
                    ctx, node.lineno,
                    "'except Exception: pass' swallows all failures "
                    "silently (log it, narrow it, or mark "
                    "'# lint-ok: excepts: <reason>')")
