"""JL006 ``fsops-seam`` — raw filesystem mutation in ``fleet/``
outside the retrying seam.

ISSUE 17 routed every fleet queue/lease/heartbeat/journal filesystem
operation through ONE seam (``fleet/fsops.py:FsOps``): bounded
retry/backoff on transient errors (EIO/ESTALE/ETIMEDOUT/ENOSPC),
per-op deadlines, chaos injection, the injectable clock, and the
degraded-park escape hatch. A raw ``os.rename`` / ``os.replace`` /
open-for-write added anywhere else in ``fleet/`` silently bypasses
all of that — it neither retries, nor degrades, nor faults under the
chaos harness, so the byte-identity soak stops covering it. This
rule makes the seam structural: zero grandfathers.

Flagged, in ``fleet/`` only:

- ``os.rename(...)`` / ``os.replace(...)`` calls — route through
  ``fs.rename`` / ``fs.replace`` (or ``claim_by_rename(...,
  fs=...)``);
- ``open(...)`` / ``os.fdopen(...)`` with a write-capable mode —
  a string-literal mode containing ``w``/``a``/``x``/``+``, or a
  NON-literal mode (conservative: an unreadable mode in ``fleet/``
  is a seam question, not a pass) — route through
  ``fs.write_bytes`` / ``fs.write_json`` / ``fs.append_text`` /
  ``fs.open_write`` / ``fs.fdopen``;
- ``os.unlink`` / ``os.remove`` calls — route through
  ``fs.unlink`` (lease drops must see the same retry/deadline
  policy as the renames that created the lease).

Not flagged: read-mode opens (the default ``open(p)`` included),
everything in ``fleet/fsops.py`` (the seam IS the raw-op site) and
``fleet/chaos.py`` (the injector tears bytes beneath the seam by
design — its job is the flagged behavior).

Escape hatch: ``# lint-ok: fsops-seam: <reason>`` — for ops that
must deliberately bypass retry/injection; the reason should say why
a fault there cannot lose queue state.
"""

from __future__ import annotations

import ast

from ..framework import Rule, register

#: os.<attr> calls that mutate directory entries
_OS_MUTATORS = {"rename", "replace", "unlink", "remove"}
#: characters in an ``open`` mode string that make it write-capable
_WRITE_CHARS = set("wax+")


def _os_attr(func):
    """``os.<attr>`` attribute callee → attr name, else None."""
    if isinstance(func, ast.Attribute) \
            and isinstance(func.value, ast.Name) \
            and func.value.id == "os":
        return func.attr
    return None


def _mode_arg(call, pos):
    """The ``mode`` argument of an open-like call: positional index
    ``pos`` or the ``mode=`` keyword; None when absent."""
    if len(call.args) > pos:
        return call.args[pos]
    for kw in call.keywords:
        if kw.arg == "mode":
            return kw.value
    return None


def _mode_verdict(mode):
    """(is_write, shown) for one mode argument: a missing mode is
    read-only, a literal decides by its characters, anything else is
    conservatively write-capable."""
    if mode is None:
        return False, "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return bool(_WRITE_CHARS & set(mode.value)), repr(mode.value)
    return True, "<non-literal>"


@register
class FsopsSeamRule(Rule):
    id = "JL006"
    name = "fsops-seam"
    short = ("raw filesystem mutation in fleet/ bypassing the "
             "retrying fsops seam")
    scope = ("fleet/",)
    # the seam itself and the fault injector beneath it are the only
    # legitimate raw-op sites
    exclude = ("fleet/fsops.py", "fleet/chaos.py")

    def check(self, ctx, config):
        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            attr = _os_attr(node.func)
            if attr in _OS_MUTATORS:
                yield self.finding(
                    ctx, node.lineno,
                    f"`os.{attr}()` in fleet/ bypasses the fsops "
                    f"seam (no retry/backoff, no chaos injection, "
                    f"no degraded-park) — use `fs.{attr}()` "
                    "(fleet/fsops.py) or mark `# lint-ok: "
                    "fsops-seam: <why a fault here is safe>`",
                    data={"call": f"os.{attr}"})
                continue
            if attr == "fdopen":
                is_write, shown = _mode_verdict(_mode_arg(node, 1))
                if is_write:
                    yield self.finding(
                        ctx, node.lineno,
                        f"`os.fdopen(..., {shown})` opens for write "
                        "in fleet/ outside the fsops seam — use "
                        "`fs.fdopen()` so the write path retries "
                        "and faults under chaos, or mark "
                        "`# lint-ok: fsops-seam: <reason>`",
                        data={"call": "os.fdopen", "mode": shown})
                continue
            if isinstance(node.func, ast.Name) \
                    and node.func.id == "open":
                is_write, shown = _mode_verdict(_mode_arg(node, 1))
                if is_write:
                    yield self.finding(
                        ctx, node.lineno,
                        f"`open(..., {shown})` opens for write in "
                        "fleet/ outside the fsops seam — use "
                        "`fs.write_bytes`/`fs.write_json`/"
                        "`fs.append_text`/`fs.open_write` "
                        "(fleet/fsops.py), or mark `# lint-ok: "
                        "fsops-seam: <reason>`",
                        data={"call": "open", "mode": shown})
