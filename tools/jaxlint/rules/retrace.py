"""JL101 ``retrace-hazard`` — per-call jit-wrapper construction.

The regression that has bitten this repo twice (thth fused search
pre-PR-1; ``fit/batch.py:make_acf1d_batch`` pre-PR-4): ``jax.jit``
caches compiled programs on FUNCTION IDENTITY, so a function that
constructs a fresh ``jax.jit(...)`` / ``partial(jit, ...)`` /
``jit(vmap(...))`` wrapper on every call retraces (and on a cold XLA
cache recompiles) every call — ~320 ms/epoch measured on the CPU
host, pure compile noise on the per-epoch survey path.

The rule flags any jit-wrapper construction inside a function body
that is NOT routed through one of the codebase's recognized caching
idioms. A construction is **recognized** when any enclosing function:

1. is a **module-cache guard** (the ``_SOLVER_CACHE`` /
   ``_ACF1D_BATCH_CACHE`` pattern): the same name is both read with
   ``X.get(...)`` and stored with ``X[key] = ...`` in the function
   body — covers ``thth.core.keyed_jit_cache`` itself and every
   dict-cached factory;
2. is a **global-singleton builder** (the
   ``sim/simulation.py:_jax_screen_program`` pattern): declares
   ``global X`` and assigns one of those names;
3. calls ``keyed_jit_cache(...)`` — the construction is the cache's
   own builder plumbing;
4. calls ``record_build(...)`` (obs/retrace.py) — a deliberate,
   retrace-accounted factory whose every build is visible to the
   tier-1 ``retrace_guard`` gate (the ``parallel/survey.py`` sharded
   factories: cached by their callers, accounted at build);
5. routes through the formulation registry's measured-build path
   (``measure_formulation(...)``), which times and pins candidates
   once per (op, platform).

Also flagged: **unhashable cache keys** — a cache-guard function
whose key expression contains a list/dict/set display (or a
``list()``/``dict()``/``set()`` call): the first ``cache.get(key)``
raises ``TypeError`` at runtime, or silently never hits if repr'd.

Escape hatch: ``# lint-ok: retrace-hazard: <reason>`` on the
construction line — for genuine one-shot builds (a user-facing API
that compiles once per call by design, not an epoch path).
"""

from __future__ import annotations

import ast

from ..framework import Rule, register
from .import_jit import is_jit_callee

#: calls whose presence in an enclosing function marks a recognized
#: routing (cases 3–5 in the module docstring)
_ROUTED_CALLS = {"keyed_jit_cache", "record_build",
                 "measure_formulation"}


def _called_names(fn):
    """Bare / attribute callee names invoked anywhere in ``fn``'s
    body (one level — the lexical body, including nested defs)."""
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                out.add(f.id)
            elif isinstance(f, ast.Attribute):
                out.add(f.attr)
    return out


def _base_name(node):
    """The root Name id of a possibly-dotted expression, else None."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _cache_guard_names(fn):
    """Names that look like dict caches in ``fn``: read via
    ``X.get(...)`` / ``X[key]`` / ``key in X`` AND stored via
    ``X[...] = ...`` (or ``X.setdefault``). Returns
    ``{name: [key_expr, ...]}`` with the key expressions (for the
    unhashable-key check)."""
    reads = {}
    stores = set()
    store_targets = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript):
                    base = _base_name(t.value)
                    if base:
                        stores.add(base)
                        store_targets.add(id(t))
    plain_reads = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("get", "setdefault") \
                and node.args:
            base = _base_name(node.func.value)
            if base:
                reads.setdefault(base, []).append(node.args[0])
                if node.func.attr == "setdefault":
                    stores.add(base)
        elif isinstance(node, ast.Compare) \
                and len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.In, ast.NotIn)):
            base = _base_name(node.comparators[0])
            if base:
                reads.setdefault(base, []).append(node.left)
        elif isinstance(node, ast.Subscript) \
                and id(node) not in store_targets:
            base = _base_name(node.value)
            if base:
                # a plain ``X[key]`` read recognizes the guard but is
                # NOT subjected to the unhashable-key check (numpy
                # fancy indexing uses list literals legitimately)
                plain_reads.add(base)
    out = {n: keys for n, keys in reads.items() if n in stores}
    for n in plain_reads & stores:
        out.setdefault(n, [])
    return out


def _global_singleton_names(fn):
    """Global names declared AND assigned in ``fn`` (the cached
    module-singleton builder pattern)."""
    declared = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            declared.update(node.names)
    if not declared:
        return set()
    assigned = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name) \
                            and sub.id in declared:
                        assigned.add(sub.id)
    return assigned


def _is_recognized(fn):
    """True when ``fn`` routes its jit construction through a
    recognized cache (docstring cases 1–5)."""
    if _cache_guard_names(fn):
        return True
    if _global_singleton_names(fn):
        return True
    if _called_names(fn) & _ROUTED_CALLS:
        return True
    return False


_HASHING_CALLS = {"tuple", "frozenset", "bytes", "str", "repr",
                  "hash", "int", "float", "bool", "len", "id"}
_UNHASHABLE_CALLS = {"list", "dict", "set", "sorted", "bytearray"}


def _unhashable(expr):
    """True when the key expression is structurally unhashable: a
    list/dict/set display or comprehension, or a
    ``list()``/``dict()``/``set()``/``sorted()`` call — at any tuple
    nesting depth. Conversions that PRODUCE hashables
    (``tuple(...)``, ``frozenset(...)``, ``.tobytes()``, arbitrary
    calls) are not descended into: ``tuple(d.id for d in devs)`` is a
    fine key."""
    if isinstance(expr, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.SetComp, ast.DictComp)):
        return True
    if isinstance(expr, ast.Tuple):
        return any(_unhashable(e) for e in expr.elts)
    if isinstance(expr, ast.Starred):
        return _unhashable(expr.value)
    if isinstance(expr, ast.Call):
        name = None
        if isinstance(expr.func, ast.Name):
            name = expr.func.id
        if name in _UNHASHABLE_CALLS:
            return True
        # tuple()/frozenset()/.tobytes()/unknown calls: trust the
        # conversion
        return False
    return False


def _key_assignments(fn, name):
    """Value expressions assigned to ``name`` inside ``fn`` (simple
    single-target assignments)."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name:
            yield node.value, node.lineno


@register
class RetraceHazardRule(Rule):
    id = "JL101"
    name = "retrace-hazard"
    short = ("jit wrapper constructed per call outside a recognized "
             "cache; unhashable cache keys")
    scope = None

    MSG = ("jit wrapper constructed per call — jax.jit caches on "
           "function identity, so this retraces every invocation "
           "(~0.3 s/epoch measured, the PR-4 fit/batch.py trap); "
           "route it through a keyed cache (keyed_jit_cache / "
           "_SOLVER_CACHE pattern), account it with "
           "obs.retrace.record_build, or mark a deliberate one-shot "
           "build with `# lint-ok: retrace-hazard: <reason>`")

    def check(self, ctx, config):
        seen = set()
        recognized = {}      # id(fn) -> bool, memoized per run

        def chain_ok(site):
            for fn in ctx.enclosing_functions(site):
                if isinstance(fn, ast.Lambda):
                    continue
                ok = recognized.get(id(fn))
                if ok is None:
                    ok = recognized[id(fn)] = _is_recognized(fn)
                if ok:
                    return True
            return False

        # functions containing a subscript store are the only
        # cache-guard candidates — gates the per-function sub-walks
        guard_candidates = []
        for node in ctx.nodes:
            call = None
            if isinstance(node, ast.Call):
                if is_jit_callee(node.func):
                    call = node
                elif (isinstance(node.func, ast.Name)
                      and node.func.id == "partial"
                      and any(is_jit_callee(a) for a in node.args)):
                    call = node
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                # a bare @jax.jit decorator on a NESTED def is a
                # per-call wrapper too (module-level ones are
                # import-jit's territory)
                for dec in node.decorator_list:
                    if not is_jit_callee(dec):
                        continue
                    if not ctx.enclosing_functions(node):
                        continue
                    if chain_ok(node):
                        continue
                    key = (dec.lineno, "dec")
                    if key not in seen:
                        seen.add(key)
                        yield self.finding(ctx, dec.lineno, self.MSG)
            elif isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Subscript)
                    for t in node.targets):
                fns = ctx.enclosing_functions(node)
                if fns and not isinstance(fns[0], ast.Lambda):
                    guard_candidates.append(fns[0])
            if call is None:
                continue
            if not ctx.enclosing_functions(call):
                continue              # module level → import-jit rule
            if chain_ok(call):
                continue
            key = (call.lineno, "call")
            if key not in seen:
                seen.add(key)
                yield self.finding(ctx, call.lineno, self.MSG)

        # unhashable cache keys in cache-guard functions
        checked = set()
        for node in guard_candidates:
            if id(node) in checked:
                continue
            checked.add(id(node))
            for cache, key_exprs in _cache_guard_names(node).items():
                for key_expr in key_exprs:
                    exprs = [(key_expr, key_expr.lineno)]
                    if isinstance(key_expr, ast.Name):
                        exprs = list(_key_assignments(node,
                                                      key_expr.id))
                    for expr, lineno in exprs:
                        if _unhashable(expr) \
                                and (lineno, "key") not in seen:
                            seen.add((lineno, "key"))
                            yield self.finding(
                                ctx, lineno,
                                f"cache key for `{cache}` contains an "
                                "unhashable list/dict/set — the "
                                "cache lookup raises TypeError (or "
                                "never hits); use tuples / "
                                ".tobytes() for array-valued keys")
