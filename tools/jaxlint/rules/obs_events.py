"""JL004 ``obs-events`` — every slog event name must be in the
documented catalog (ported from tools/lint_obs_events.py, ISSUE 5).

The observability layer is only useful if the event stream is a
stable, documented interface — a dashboard or grep that works today
must not silently miss next month's renamed event. The rule walks
every ``slog.log_event(...)`` / ``slog.log_failure(...)`` /
``slog.span(...)`` call and checks the event name against the catalog
(backtick-quoted dotted names in docs/observability.md +
docs/serving.md):

- a **literal** first argument (or ``event=`` keyword) is resolved
  directly;
- a plain **variable** is resolved through the enclosing function's
  default for that parameter (the ``def log_summary(self, event=
  "survey.pipeline_timeline")`` pattern);
- anything else (attributes, f-strings, arbitrary expressions) must
  carry an ``# lint-ok: obs-events: <name>`` marker (legacy
  ``# obs-event-ok: <name>`` still honored) naming the event it
  emits — the named event is then catalog-checked like any other. No
  marker → violation ("drive-by unnamed event").

``span`` names are cataloged by their base name (the
``.start``/``.end`` suffix convention is documented once);
``utils/slog.py`` itself is exempt (it builds the suffixed names).
"""

from __future__ import annotations

import ast

from ..framework import Rule, register

_CALLS = {"log_event", "log_failure", "span"}
# literal defaults of slog.log_failure's own ``event`` parameter —
# calls that omit the argument emit this name
_IMPLICIT = {"log_failure": "robust.failure"}


def _is_slog_call(node):
    """``slog.log_event(...)`` / ``slog.span(...)`` — the attribute
    form requires the receiver to be named ``slog`` (``span`` is a
    common method name: ``StageTimeline.span`` records stage spans,
    not events). Bare imported ``log_event``/``log_failure`` names
    are distinctive enough to match directly."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _CALLS \
            and isinstance(f.value, ast.Name) and f.value.id == "slog":
        return f.attr
    if isinstance(f, ast.Name) and f.id in _CALLS and f.id != "span":
        return f.id
    return None


def _event_arg(node):
    """The AST node holding the event name (first positional or the
    ``event=`` keyword), or None when omitted."""
    if node.args:
        return node.args[0]
    for kw in node.keywords:
        if kw.arg == "event":
            return kw.value
    return None


def _fn_defaults(node):
    """``{param: literal-string-default}`` of one function def."""
    out = {}
    args = node.args
    pos = args.posonlyargs + args.args
    for a, d in zip(pos[len(pos) - len(args.defaults):],
                    args.defaults):
        if isinstance(d, ast.Constant) and isinstance(d.value, str):
            out[a.arg] = d.value
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if d is not None and isinstance(d, ast.Constant) \
                and isinstance(d.value, str):
            out[a.arg] = d.value
    return out


def _collect(ctx, rule):
    """``(events, violations)``: emissions as ``[(lineno, name)]``,
    violations as ``[(lineno, message)]``. Variable names resolve
    through the nearest enclosing function's literal parameter
    default; anything else needs the line marker naming the event."""
    events, violations = [], []
    defaults_cache = {}
    for node in ctx.nodes:
        if not isinstance(node, ast.Call):
            continue
        which = _is_slog_call(node)
        if which is None:
            continue
        arg = _event_arg(node)
        name = None
        if arg is None:
            name = _IMPLICIT.get(which)
        elif isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                          str):
            name = arg.value
        elif isinstance(arg, ast.Name):
            for fn in ctx.enclosing_functions(node):
                if isinstance(fn, ast.Lambda):
                    continue
                d = defaults_cache.get(id(fn))
                if d is None:
                    d = defaults_cache[id(fn)] = _fn_defaults(fn)
                if arg.id in d:
                    name = d[arg.id]
                    break
        if name is None:
            payload = ctx.marked(node.lineno, rule.name)
            if payload:
                name = payload.split()[0].rstrip(",;")
        if name is None:
            violations.append((
                node.lineno,
                f"slog.{which} with unresolvable event name — use "
                "a literal, a literal parameter default, or an "
                "'# lint-ok: obs-events: <name>' marker"))
            continue
        events.append((node.lineno, name))
    return events, violations


@register
class ObsEventsRule(Rule):
    id = "JL004"
    name = "obs-events"
    short = ("slog event names must be resolvable and in the "
             "documented catalog")
    scope = None
    exclude = ("utils/slog.py",)      # builds the suffixed names
    self_markers = True               # marker NAMES the event; the
    #                                   named event is still checked

    def collect(self, ctx):
        """``(events, violations)`` without the catalog check — the
        legacy ``scan_source`` contract."""
        return _collect(ctx, self)

    def check(self, ctx, config):
        events, violations = self.collect(ctx)
        for ln, msg in violations:
            yield self.finding(ctx, ln, msg)
        catalog = config.obs_catalog
        doc_names = ", ".join(
            __import__("os").path.basename(p)
            for p in config.obs_docs) or "<no catalog docs>"
        for ln, name in events:
            if name not in catalog:
                yield self.finding(
                    ctx, ln,
                    f"event {name!r} not in the catalog ({doc_names})"
                    " — document it or rename to a documented event",
                    data={"event": name})
