"""JL003 ``syncpoints`` — no premature device-sync points in library
hot paths (ported from tools/lint_syncpoints.py, ISSUE 4).

The pipelined survey engine (parallel/pipeline.py + robust/runner.py)
only overlaps host work with device compute if the dispatch chain
stays ASYNC: a stray ``.block_until_ready()`` or an eager
``np.asarray(...)`` on an in-flight device value inside a library hot
path fences the whole device queue and silently serialises the
pipeline.

Flagged patterns:

1. ANY ``.block_until_ready`` use (method call or
   ``jax.block_until_ready(x)``) — fencing belongs to profiling
   (utils/profiling.py, excluded) and bench timing, never library
   code;
2. ``jax.device_get(...)`` / ``x.device_get(...)`` — same;
3. ``np.asarray(f(...))`` / ``float(f(...))`` / ``int(f(...))``
   where the wrapped call FEEDS DEVICE INPUTS (its argument subtree
   contains ``jnp.asarray`` / ``device_put``): dispatch-and-fetch in
   one expression, the classic hidden sync;
4. ``np.asarray(g(...))`` / ``float(g(...))`` where ``g`` is a name
   bound from ``jax.jit(...)`` (or ``*.jit(...)``) in the same
   module — fetching a jitted program's result eagerly.

Escape hatch: ``# lint-ok: syncpoints: <reason>`` (legacy
``# sync-ok: <reason>`` still honored) marks a deliberate
result-consumption boundary.
"""

from __future__ import annotations

import ast

from ..framework import Rule, register

# callee names that fetch/force a value to host
_FETCHERS = ("asarray", "device_get", "to_numpy")
_CASTS = ("float", "int")
# attribute names marking an expression as producing device inputs
_DEVICE_FEEDERS = ("device_put",)


def _attr_name(func):
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _is_jnp_asarray(node):
    """True for ``jnp.asarray(...)`` / ``jax.numpy.asarray`` calls —
    the device-staging idiom (vs plain ``np.asarray``)."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)):
        return False
    if node.func.attr not in ("asarray",) + _DEVICE_FEEDERS:
        return False
    base = node.func.value
    base_name = base.id if isinstance(base, ast.Name) else (
        base.attr if isinstance(base, ast.Attribute) else None)
    if node.func.attr in _DEVICE_FEEDERS:
        return True                      # jax.device_put(...)
    return base_name in ("jnp", "jaxnp")


def _feeds_device(call):
    """True when any argument subtree of ``call`` stages device
    inputs (jnp.asarray / device_put)."""
    for arg in list(call.args) + [k.value for k in call.keywords]:
        for sub in ast.walk(arg):
            if _is_jnp_asarray(sub):
                return True
    return False


def _jit_bound_names(tree):
    """Names assigned (anywhere in the module) from a ``*.jit(...)``
    or bare ``jit(...)`` call — simple single-target assignments
    only."""
    names = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        value = node.value
        if isinstance(value, ast.Call) \
                and _attr_name(value.func) == "jit":
            names.add(node.targets[0].id)
    return names


@register
class SyncpointsRule(Rule):
    id = "JL003"
    name = "syncpoints"
    short = ("premature device fences (.block_until_ready / eager "
             "fetch of in-flight values) in hot paths")
    # the library hot paths the pipelined engine flows through; the
    # scan list grew with ISSUEs 4→7 (see tests/test_lint.py history)
    scope = ("ops/", "fit/", "thth/", "parallel/", "serve/",
             "fleet/", "robust/", "obs/", "detect/", "mcmc/",
             "dynspec.py")
    # profiling's whole JOB is fencing
    exclude = ("utils/profiling.py",)

    def check(self, ctx, config):
        jit_names = _jit_bound_names(ctx.tree)
        seen = set()
        for node in ctx.nodes:
            # rule 1/2: block_until_ready / device_get anywhere
            if isinstance(node, ast.Attribute) \
                    and node.attr in ("block_until_ready",
                                      "device_get"):
                key = (node.lineno, node.attr)
                if key not in seen:
                    seen.add(key)
                    yield self.finding(
                        ctx, node.lineno,
                        f"`.{node.attr}` fences the device queue — "
                        "library hot paths must stay async (profile "
                        "with utils/profiling.py; mark a deliberate "
                        "consumption boundary with "
                        "`# lint-ok: syncpoints: <reason>`)")
                continue
            if not isinstance(node, ast.Call):
                continue
            name = _attr_name(node.func)
            if name not in _FETCHERS + _CASTS or not node.args:
                continue
            inner = node.args[0]
            if not isinstance(inner, ast.Call):
                continue
            inner_name = _attr_name(inner.func)
            flagged = None
            if isinstance(inner.func, ast.Name) \
                    and inner.func.id in jit_names:
                flagged = (f"fetching the jit-bound `{inner.func.id}` "
                           "result eagerly")
            elif _feeds_device(inner):
                flagged = (f"`{name}({inner_name or '<call>'}(...))` "
                           "dispatches device inputs and fetches the "
                           "result in one expression")
            if flagged:
                key = (node.lineno, flagged)
                if key not in seen:
                    seen.add(key)
                    yield self.finding(
                        ctx, node.lineno,
                        flagged + " — a hidden sync point; keep the "
                        "value in flight or mark the consumption "
                        "boundary with "
                        "`# lint-ok: syncpoints: <reason>`")
