"""JL002 ``import-jit`` — no import-time ``jax.jit`` (ported from
tools/lint_import_jit.py, ISSUE 3).

A ``jax.jit(...)`` (or ``@jax.jit`` decorator / ``partial(jax.jit)``)
executed at module import time forces the jax backend to initialise
before any work is requested: cold-start of every CLI entry and test
collection pays it, and on the tunneled TPU an import can then HANG
on a dead link (backend.py:force_cpu_platform docstring). Compiled
programs must be built lazily inside cached factories
(fit/acf2d.py:_SOLVER_CACHE, thth/core.py:keyed_jit_cache).

Flagged: any call whose callee is named ``jit`` (``jax.jit``,
``get_jax().jit``, bare ``jit``) or ``partial(...jit...)`` reachable
at IMPORT TIME — module body, class bodies, module-level decorator
lists, and function default arguments. Calls inside function bodies
(deferred to call time) are fine — and are rule ``retrace-hazard``'s
territory instead.

Scope: the whole package (the legacy script defaulted to ``fit/``;
the rest of the tree is clean, so the unified rule pins it globally).
"""

from __future__ import annotations

import ast

from ..framework import Rule, register


def is_jit_callee(node):
    """True when a Call's func resolves to a name ending in
    ``jit``."""
    if isinstance(node, ast.Attribute):
        return node.attr == "jit"
    if isinstance(node, ast.Name):
        return node.id == "jit"
    return False


def jit_calls(node):
    """Yield Call nodes invoking jit anywhere under ``node``."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        if is_jit_callee(sub.func):
            yield sub
        elif (isinstance(sub.func, ast.Name)
              and sub.func.id == "partial"
              and any(is_jit_callee(a) for a in sub.args)):
            yield sub


def _import_time_nodes(body):
    """Yield ``(node, is_decorator)`` pairs for AST nodes whose code
    executes when the module is imported: statements in module/class
    bodies, decorators and argument defaults of (possibly
    nested-in-class) function defs — but NOT function bodies. A BARE
    jit decorator (``@jax.jit`` — an Attribute, not a Call) still
    invokes jit at def time, so decorators are flagged."""
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield from ((d, True) for d in stmt.decorator_list)
            yield from ((d, False) for d in stmt.args.defaults)
            yield from ((d, False) for d in stmt.args.kw_defaults
                        if d is not None)
        elif isinstance(stmt, ast.ClassDef):
            yield from ((d, True) for d in stmt.decorator_list)
            yield from _import_time_nodes(stmt.body)
        else:
            yield stmt, False


@register
class ImportJitRule(Rule):
    id = "JL002"
    name = "import-jit"
    short = "jax.jit reachable at module import time"
    scope = None                      # whole package

    MSG = ("jax.jit at import time (build compiled programs lazily "
           "inside a cached factory — fit/acf2d.py:_SOLVER_CACHE "
           "pattern)")

    def check(self, ctx, config):
        seen = set()
        for node, is_decorator in _import_time_nodes(ctx.tree.body):
            if is_decorator and is_jit_callee(node):
                if node.lineno not in seen:       # bare @jax.jit
                    seen.add(node.lineno)
                    yield self.finding(ctx, node.lineno, self.MSG)
                continue
            for call in jit_calls(node):
                if call.lineno not in seen:
                    seen.add(call.lineno)
                    yield self.finding(ctx, call.lineno, self.MSG)
