"""JL103 ``jit-boundary`` — host-only calls inside traced function
bodies.

Code passed to ``jit``/``vmap``/``pmap``/``lax.scan``/``while_loop``/
``cond``/``fori_loop``/``lax.map`` runs at TRACE time, not call time:
a ``print``, an ``open``, an ``slog.log_event`` or a metrics
increment inside a traced body fires once per (re)trace — silently
absent from steady-state runs, misleadingly present during compiles —
and an eager ``np.asarray(<traced arg>)`` either raises
``TracerArrayConversionError`` under jit or silently pins the value
to host on the un-jitted oracle path. PR 7 swept bare prints out of
the retrieval path; this rule keeps all traced bodies clean
structurally.

Detection: within one module, a function is **traced** when it (a
``def`` or ``lambda``) is passed to a trace consumer (``jit``,
``vmap``, ``pmap``, ``grad``, ``value_and_grad``, ``checkpoint``,
``remat``, ``lax.scan``/``map``/``cond``/``while_loop``/
``fori_loop``/``switch``/``associative_scan``), positionally OR
through a branch/body keyword (``cond_fun=``/``body_fun=``/``f=``/
``true_fun=``/``false_fun=``/``branches=`` — the keyword form was
the known blind spot closed in ISSUE 9), directly or through the
module-local call graph (a helper called from a traced body is
traced too; resolution is name-based within the file).

Flagged inside traced bodies:

- ``print(...)`` — use ``jax.debug.print`` (trace-staged) or log at
  the call site after the fence;
- ``open(...)`` — host I/O cannot run per device element;
- ``slog.log_event`` / ``log_failure`` / ``slog.span`` — events must
  be emitted at the host boundary (they would fire per trace, not
  per call);
- metrics mutation (``metrics.*`` calls, or ``.inc()``/
  ``.observe()``/``.set()``/``.dec()`` on a ``counter``/``gauge``/
  ``histogram`` chain) — same;
- ``np.save``/``savez``/``savetxt`` and ``np.asarray``/``np.array``
  of a traced function PARAMETER — host materialisation of a tracer.

Escape hatch: ``# lint-ok: jit-boundary: <reason>`` on the offending
line (e.g. a debug helper deliberately kept behind a static flag).
"""

from __future__ import annotations

import ast

from ..framework import Rule, register

#: callee names whose first functional argument is traced
_WRAPPERS = {"jit", "vmap", "pmap", "grad", "value_and_grad",
             "checkpoint", "remat"}
#: keyword names a wrapper's traced callable may arrive through
_WRAPPER_KWARGS = {"fun", "f"}
#: lax-style consumers — every function-valued argument is traced
_LAX_CONSUMERS = {"scan", "while_loop", "fori_loop", "cond", "switch",
                  "map", "associative_scan"}
#: keyword names lax consumers accept their branch/body callables
#: through (``lax.while_loop(cond_fun=..., body_fun=...)``,
#: ``lax.scan(f=...)``, ``lax.cond(pred, true_fun=..., ...)``) — the
#: keyword-passed form was the known AST blind spot before ISSUE 9
_LAX_CALLABLE_KWARGS = {"f", "fun", "fn", "cond_fun", "body_fun",
                        "true_fun", "false_fun", "branches"}
_ALL_CONSUMERS = _WRAPPERS | _LAX_CONSUMERS

_NP_WRITERS = {"save", "savez", "savez_compressed", "savetxt"}
_METRIC_MUTATORS = {"inc", "dec", "observe", "set"}
_METRIC_FACTORIES = {"counter", "gauge", "histogram"}
_SLOG_CALLS = {"log_event", "log_failure", "span"}


def _callee_name(func):
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


class _Scope:
    """One lexical function scope: local ``def`` names → nodes."""

    def __init__(self, node):
        self.node = node
        self.defs = {}


def _build_scopes(ctx):
    """``{id(fn_node): _Scope}`` for the module plus every function,
    each mapping locally-defined function names to their nodes."""
    scopes = {id(ctx.tree): _Scope(ctx.tree)}

    def visit(owner, node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                scopes[id(owner)].defs[child.name] = child
                scopes[id(child)] = _Scope(child)
                visit(child, child)
            elif isinstance(child, ast.Lambda):
                scopes[id(child)] = _Scope(child)
                visit(child, child)
            elif isinstance(child, ast.ClassDef):
                # methods resolve within the class body only; skip —
                # traced fns are module/function-local in practice
                for sub in ast.iter_child_nodes(child):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        scopes[id(sub)] = _Scope(sub)
                        visit(sub, sub)
            else:
                visit(owner, child)

    visit(ctx.tree, ctx.tree)
    return scopes


def _resolve(ctx, scopes, site, name):
    """Nearest function def named ``name`` visible from ``site``
    (enclosing-scope chain, innermost first)."""
    for fn in ctx.enclosing_functions(site):
        sc = scopes.get(id(fn))
        if sc and name in sc.defs:
            return sc.defs[name]
    sc = scopes.get(id(ctx.tree))
    if sc and name in sc.defs:
        return sc.defs[name]
    return None


def _functional_args(call):
    """Argument expressions of ``call`` that may be traced functions
    — positional AND keyword (``lax.while_loop(cond_fun=c,
    body_fun=b, init_val=x)`` traces ``c``/``b`` exactly like the
    positional form)."""
    name = _callee_name(call.func)
    if name in _WRAPPERS:
        return call.args[:1] + [kw.value for kw in call.keywords
                                if kw.arg in _WRAPPER_KWARGS]
    if name in _LAX_CONSUMERS:
        return list(call.args) + [kw.value for kw in call.keywords
                                  if kw.arg in _LAX_CALLABLE_KWARGS]
    return []


def traced_functions(ctx):
    """``(direct, all_traced)`` function nodes (def or Lambda) traced
    in this module: ``direct`` are trace-consumer arguments plus defs
    nested inside them (their parameters ARE tracers); ``all_traced``
    adds the transitive module-local call closure (helpers called
    from traced bodies run at trace time too, but their arguments may
    be static — the dual-backend host helpers)."""
    consumers = [n for n in ctx.nodes
                 if isinstance(n, ast.Call)
                 and _callee_name(n.func) in _ALL_CONSUMERS]
    if not consumers:
        return [], []         # no trace consumers → skip scope build
    scopes = _build_scopes(ctx)
    roots = []
    for node in consumers:
        for arg in _functional_args(node):
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Lambda):
                    roots.append(sub)
                elif isinstance(sub, ast.Name):
                    fn = _resolve(ctx, scopes, node, sub.id)
                    if fn is not None:
                        roots.append(fn)

    direct = {}
    work = list(roots)
    while work:
        fn = work.pop()
        if id(fn) in direct:
            continue
        direct[id(fn)] = fn
        for sub in ast.walk(fn):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)) and sub is not fn \
                    and id(sub) not in direct:
                work.append(sub)

    traced = dict(direct)
    work = list(direct.values())
    while work:
        fn = work.pop()
        traced[id(fn)] = fn
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Name):
                callee = _resolve(ctx, scopes, sub, sub.func.id)
                if callee is not None and id(callee) not in traced:
                    traced[id(callee)] = callee
                    work.append(callee)
    return list(direct.values()), list(traced.values())


def _params(fn):
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def _is_metrics_mutation(call):
    f = call.func
    if not isinstance(f, ast.Attribute):
        return False
    # metrics.<anything>(...)
    if isinstance(f.value, ast.Name) and f.value.id == "metrics":
        return True
    # counter(...).labels(...).inc() style chains
    if f.attr in _METRIC_MUTATORS:
        for sub in ast.walk(f.value):
            if isinstance(sub, ast.Call):
                n = _callee_name(sub.func)
                if n in _METRIC_FACTORIES:
                    return True
    return False


@register
class JitBoundaryRule(Rule):
    id = "JL103"
    name = "jit-boundary"
    short = ("host-only calls (print/open/slog/metrics/np "
             "materialisation) inside traced function bodies")
    scope = None

    def check(self, ctx, config):
        direct, traced = traced_functions(ctx)
        if not traced:
            return
        direct_ids = {id(f) for f in direct}
        seen = set()
        for fn in traced:
            # tracer-materialisation checks only apply where the
            # parameters are KNOWN to be tracers: functions passed
            # straight to a trace consumer (call-graph helpers may
            # receive static closure values — the dual-backend host
            # helpers)
            params = _params(fn) if id(fn) in direct_ids else set()
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                msg = self._hostile(node, params)
                if msg and node.lineno not in seen:
                    seen.add(node.lineno)
                    yield self.finding(
                        ctx, node.lineno,
                        msg + " inside a traced function body — it "
                        "runs at TRACE time (once per compile), not "
                        "per call; move it to the host boundary or "
                        "mark `# lint-ok: jit-boundary: <reason>`")

    def _hostile(self, call, params):
        f = call.func
        if isinstance(f, ast.Name):
            if f.id == "print":
                return ("`print` (use jax.debug.print for staged "
                        "output)")
            if f.id == "open":
                return "`open` (host I/O)"
            if f.id in _SLOG_CALLS and f.id != "span":
                return f"`{f.id}` (slog event emission)"
            return None
        if not isinstance(f, ast.Attribute):
            return None
        recv = f.value
        recv_name = recv.id if isinstance(recv, ast.Name) else None
        if recv_name == "slog" and f.attr in _SLOG_CALLS:
            return f"`slog.{f.attr}` (slog event emission)"
        if _is_metrics_mutation(call):
            return f"`{recv_name or '...'}.{f.attr}` (metrics mutation)"
        if recv_name == "np":
            if f.attr in _NP_WRITERS:
                return f"`np.{f.attr}` (host file write)"
            if f.attr in ("asarray", "array") and call.args \
                    and isinstance(call.args[0], ast.Name) \
                    and call.args[0].id in params:
                return (f"`np.{f.attr}({call.args[0].id})` "
                        "materialises a traced argument on host")
        return None
