"""JL102 ``lock-discipline`` — unlocked writes to shared mutable
state in threaded modules.

The daemon/fleet tier (serve/, the pipelined loader/writer, the obs
registries) is threaded: the ingest loop, the HTTP telemetry handlers,
the prefetch workers, and the journal writer all touch the same
objects. A write to shared state outside the owning lock is a race
that no test reliably catches — this rule makes the discipline
structural.

Flagged, in the **threaded modules only** (``serve/``, ``fleet/``,
``parallel/pipeline.py``, ``parallel/checkpoint.py``, ``obs/``,
``utils/slog.py``, ``utils/profiling.py``):

- in any class that OWNS a lock (``self._lock = threading.Lock()`` /
  ``RLock`` / ``Condition``): a write to a shared mutable attribute —
  one assigned in ``__init__`` and mutated in **two or more** other
  methods — reached outside a ``with self._lock:`` block;
- at module level: a module that owns a lock (``_LOCK =
  threading.Lock()``) and mutates a module-level mutable (dict / list
  / set / deque display or constructor) outside ``with _LOCK:``.

Recognized conventions (not flagged):

- mutations inside ``with <lock>:`` for ANY lock the class/module
  owns (nested blocks count — lexical containment);
- methods/functions whose name ends in ``_locked`` — the codebase
  convention for "caller holds the lock"
  (``utils/slog.py:_close_sink_locked``);
- attributes holding synchronisation primitives themselves
  (``threading.Event`` — ``.set()``/``.clear()`` are atomic —
  ``queue.Queue``, locks);
- attributes mutated in zero or one non-init methods (single-writer
  pattern: the owning thread's loop).

Escape hatch: ``# lint-ok: lock-discipline: <reason>`` — for writes
that are deliberately lock-free (GIL-atomic deque appends, monotonic
flags read racily by design). The reason should say WHY it is safe.
"""

from __future__ import annotations

import ast

from ..framework import Rule, register

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_SYNC_CTORS = _LOCK_CTORS | {"Event", "Semaphore", "BoundedSemaphore",
                             "Barrier", "Queue", "SimpleQueue",
                             "LifoQueue", "PriorityQueue"}
_MUTABLE_CTORS = {"dict", "list", "set", "deque", "OrderedDict",
                  "defaultdict", "Counter"}
#: method calls that mutate their receiver in place
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
             "pop", "popleft", "popitem", "remove", "discard", "add",
             "clear", "update", "setdefault", "sort", "reverse",
             "rotate"}


def _ctor_name(value):
    """Callee name of a Call expression (``threading.Lock()`` →
    ``Lock``), else None."""
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _self_attr(node):
    """``self.<name>`` → name, else None."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _methods(cls):
    for stmt in cls.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield stmt


class _Mutation:
    __slots__ = ("attr", "lineno", "kind", "method", "node")

    def __init__(self, attr, node, kind, method):
        self.attr = attr
        self.node = node
        self.lineno = node.lineno
        self.kind = kind
        self.method = method


def _attr_mutations(method):
    """Yield mutations of ``self.<attr>`` in ``method``'s body."""
    for node in ast.walk(method):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                attr = _self_attr(t)
                if attr:
                    yield _Mutation(attr, node, "assign", method)
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr:
                        yield _Mutation(attr, node, "setitem", method)
                elif isinstance(t, ast.Tuple):
                    for elt in t.elts:
                        attr = _self_attr(elt)
                        if attr:
                            yield _Mutation(attr, node, "assign",
                                            method)
        elif isinstance(node, ast.AugAssign):
            attr = _self_attr(node.target)
            if attr:
                yield _Mutation(attr, node, "augassign", method)
            if isinstance(node.target, ast.Subscript):
                attr = _self_attr(node.target.value)
                if attr:
                    yield _Mutation(attr, node, "setitem", method)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr:
                        yield _Mutation(attr, node, "delitem", method)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            attr = _self_attr(node.func.value)
            if attr:
                yield _Mutation(attr, node, f".{node.func.attr}()",
                                method)


def _under_lock(ctx, lineno_node, lock_exprs):
    """True when ``lineno_node`` sits lexically inside a ``with``
    block over one of ``lock_exprs`` (predicate on the context
    expression)."""
    for anc in ctx.ancestors(lineno_node):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                if lock_exprs(item.context_expr):
                    return True
    return False


@register
class LockDisciplineRule(Rule):
    id = "JL102"
    name = "lock-discipline"
    short = ("shared mutable state written outside the owning lock "
             "in threaded modules")
    # the threaded tier only — flagging single-threaded code would be
    # all noise
    scope = ("detect/", "mcmc/", "serve/", "fleet/",
             "parallel/pipeline.py", "parallel/checkpoint.py",
             "obs/", "utils/slog.py", "utils/profiling.py")

    def check(self, ctx, config):
        yield from self._check_classes(ctx)
        yield from self._check_module(ctx)

    # ---- classes ----------------------------------------------------
    def _check_classes(self, ctx):
        for cls in ctx.nodes:
            if not isinstance(cls, ast.ClassDef):
                continue
            lock_attrs, sync_attrs = set(), set()
            init = None
            init_attrs = set()
            for m in _methods(cls):
                if m.name == "__init__":
                    init = m
                for node in ast.walk(m):
                    if not isinstance(node, ast.Assign):
                        continue
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr is None:
                            continue
                        ctor = _ctor_name(node.value)
                        if ctor in _LOCK_CTORS:
                            lock_attrs.add(attr)
                        if ctor in _SYNC_CTORS:
                            sync_attrs.add(attr)
                        if m.name == "__init__":
                            init_attrs.add(attr)
            if not lock_attrs or init is None:
                continue

            shared = init_attrs - sync_attrs
            # collect mutations per attr across non-init methods
            by_attr = {}
            for m in _methods(cls):
                if m.name == "__init__":
                    continue
                for mut in _attr_mutations(m):
                    if mut.attr in shared:
                        by_attr.setdefault(mut.attr, []).append(mut)

            def is_lock(expr, _la=lock_attrs):
                return _self_attr(expr) in _la

            for attr, muts in sorted(by_attr.items()):
                writers = {m.method.name for m in muts}
                if len(writers) < 2:
                    continue          # single-writer pattern
                for mut in muts:
                    if mut.method.name.endswith("_locked"):
                        continue      # caller-holds-lock convention
                    if _under_lock(ctx, mut.node, is_lock):
                        continue
                    yield self.finding(
                        ctx, mut.lineno,
                        f"`self.{attr}` ({mut.kind}) written outside "
                        f"`with self.{sorted(lock_attrs)[0]}:` — "
                        f"shared state mutated in {len(writers)} "
                        f"methods of lock-owning class `{cls.name}`; "
                        "hold the lock, rename the method "
                        "`*_locked`, or mark `# lint-ok: "
                        "lock-discipline: <why safe>`",
                        data={"attr": attr, "class": cls.name})

    # ---- module level -----------------------------------------------
    def _check_module(self, ctx):
        lock_names, mutable_names = set(), set()
        for stmt in ctx.tree.body:
            if not isinstance(stmt, ast.Assign):
                continue
            ctor = _ctor_name(stmt.value)
            is_mut = (ctor in _MUTABLE_CTORS
                      or isinstance(stmt.value,
                                    (ast.Dict, ast.List, ast.Set)))
            for t in stmt.targets:
                if not isinstance(t, ast.Name):
                    continue
                if ctor in _LOCK_CTORS:
                    lock_names.add(t.id)
                elif is_mut:
                    mutable_names.add(t.id)
        if not lock_names or not mutable_names:
            return

        def is_lock(expr, _ln=lock_names):
            return isinstance(expr, ast.Name) and expr.id in _ln

        for fn in ctx.nodes:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if fn.name.endswith("_locked"):
                continue
            if ctx.enclosing_functions(fn):
                continue              # visit each function once
            for mut in self._module_mutations(fn, mutable_names):
                if _under_lock(ctx, mut.node, is_lock):
                    continue
                yield self.finding(
                    ctx, mut.lineno,
                    f"module-level mutable `{mut.attr}` ({mut.kind}) "
                    f"mutated outside `with "
                    f"{sorted(lock_names)[0]}:` in a lock-owning "
                    "module; hold the lock, use a `*_locked` helper, "
                    "or mark `# lint-ok: lock-discipline: "
                    "<why safe>`",
                    data={"name": mut.attr})

    def _module_mutations(self, fn, names):
        for node in ast.walk(fn):
            mut = None
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id in names:
                        mut = _Mutation(t.value.id, node, "setitem",
                                        fn)
            elif isinstance(node, ast.AugAssign):
                t = node.target
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in names:
                    mut = _Mutation(t.value.id, node, "setitem", fn)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id in names:
                        mut = _Mutation(t.value.id, node, "delitem",
                                        fn)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id in names:
                mut = _Mutation(node.func.value.id, node,
                                f".{node.func.attr}()", fn)
            if mut is not None:
                yield mut
