"""JL005 ``metric-hygiene`` — metric names must be snake_case, carry
the conventional unit suffix, and appear in the documented catalog
(ISSUE 13).

The metrics registry (obs/metrics.py) is a stable operator
interface the same way the slog event stream is (JL004): a dashboard
or recording rule written against today's names must not silently
miss next month's drive-by ``fleetQueueDepth``. The rule walks every
``counter(...)`` / ``gauge(...)`` / ``histogram(...)`` registration
in the package (the module helpers and any registry/module attribute
form — ``_metrics.counter("x")``, ``reg.gauge("y")``) and enforces:

- **snake_case** — ``^[a-z][a-z0-9_]*$``;
- **unit suffixes where applicable** — counters end ``_total``
  (the Prometheus monotonic-counter convention); histograms end in a
  unit (``_seconds`` / ``_bytes`` — every histogram in this codebase
  measures one or the other); gauges must NOT end ``_total`` (that
  suffix promises a counter);
- **documented** — the name appears backtick-quoted in the metric
  catalog docs (the same three files the obs-events catalog spans:
  docs/observability.md, docs/serving.md, docs/fleet.md).

A **non-literal** name (the shared HTTP handler's
``f"{prefix}_requests_total"``) must carry a marker naming the
metric(s) it registers — ``# lint-ok: metric-hygiene: <name>
[<name>...]`` — and each named metric is then checked like a
literal. A marker on a LITERAL registration grandfathers it
(triage escape hatch; the reason should say why the name cannot
follow the convention).

Receivers named for array/plotting libraries (``np.histogram``,
``jnp.histogram``, ``plt.hist``…) are ignored — those are math, not
metrics.
"""

from __future__ import annotations

import ast
import re

from ..framework import Rule, register

_FACTORIES = {"counter", "gauge", "histogram"}
#: receiver names whose ``histogram`` attribute is a math routine
_NOT_A_REGISTRY = {"np", "numpy", "jnp", "jax", "plt", "scipy"}
_SNAKE = re.compile(r"^[a-z][a-z0-9_]*$")
_HIST_UNITS = ("_seconds", "_bytes")


def _factory_kind(node):
    """``counter``/``gauge``/``histogram`` when ``node`` is a metric
    registration call, else None."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _FACTORIES:
        if isinstance(f.value, ast.Name) \
                and f.value.id in _NOT_A_REGISTRY:
            return None
        return f.attr
    if isinstance(f, ast.Name) and f.id in _FACTORIES:
        return f.id
    return None


def _name_arg(node):
    """The AST node carrying the metric name (first positional or
    the ``name=`` keyword), or None."""
    if node.args:
        return node.args[0]
    for kw in node.keywords:
        if kw.arg == "name":
            return kw.value
    return None


def _name_problems(name, kind, catalog):
    """The convention violations of one (name, kind) registration."""
    out = []
    if not _SNAKE.match(name):
        out.append(f"metric {name!r} is not snake_case")
        return out                    # suffix checks are meaningless
    if kind == "counter" and not name.endswith("_total"):
        out.append(f"counter {name!r} must end '_total'")
    if kind == "histogram" and not name.endswith(_HIST_UNITS):
        out.append(f"histogram {name!r} must end in a unit suffix "
                   f"({' / '.join(_HIST_UNITS)})")
    if kind == "gauge" and name.endswith("_total"):
        out.append(f"gauge {name!r} must not end '_total' (that "
                   "suffix promises a monotonic counter)")
    if name not in catalog:
        out.append(f"metric {name!r} not in the documented catalog "
                   "(docs/observability.md / serving.md / fleet.md) "
                   "— add a catalog table row or rename to a "
                   "documented metric")
    return out


@register
class MetricHygieneRule(Rule):
    id = "JL005"
    name = "metric-hygiene"
    short = ("metric names: snake_case, unit suffix "
             "(_total/_seconds/_bytes), documented catalog")
    scope = None
    # the registry itself builds names generically (pass-through
    # module helpers); its own process_uptime_seconds IS checked at
    # the call sites that touch it
    exclude = ("obs/metrics.py",)
    self_markers = True     # the marker NAMES the metric(s) on
    #                         non-literal registrations; on literal
    #                         ones it grandfathers

    def check(self, ctx, config):
        catalog = config.metric_catalog
        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            kind = _factory_kind(node)
            if kind is None:
                continue
            arg = _name_arg(node)
            if arg is None:
                continue              # not a registration form
            payload = ctx.marked(node.lineno, self.name)
            if isinstance(arg, ast.Constant) \
                    and isinstance(arg.value, str):
                if payload is not None:
                    continue          # grandfathered literal
                names = [arg.value]
            else:
                names = [t.rstrip(",;") for t in (payload or "")
                         .split() if _SNAKE.match(t.rstrip(",;"))]
                if not names:
                    yield self.finding(
                        ctx, node.lineno,
                        f"{kind} registration with a non-literal "
                        "name — use a literal or a '# lint-ok: "
                        "metric-hygiene: <name> [...]' marker "
                        "naming the metric(s) it registers")
                    continue
            for name in names:
                for problem in _name_problems(name, kind, catalog):
                    yield self.finding(ctx, node.lineno, problem,
                                       data={"metric": name,
                                             "kind": kind})
