"""JL005 ``metric-hygiene`` — metric names must be snake_case, carry
the conventional unit suffix, and appear in the documented catalog
(ISSUE 13).

The metrics registry (obs/metrics.py) is a stable operator
interface the same way the slog event stream is (JL004): a dashboard
or recording rule written against today's names must not silently
miss next month's drive-by ``fleetQueueDepth``. The rule walks every
``counter(...)`` / ``gauge(...)`` / ``histogram(...)`` registration
in the package (the module helpers and any registry/module attribute
form — ``_metrics.counter("x")``, ``reg.gauge("y")``) and enforces:

- **snake_case** — ``^[a-z][a-z0-9_]*$``;
- **unit suffixes where applicable** — counters end ``_total``
  (the Prometheus monotonic-counter convention); histograms end in a
  unit (``_seconds`` / ``_bytes`` — every histogram in this codebase
  measures one or the other); gauges must NOT end ``_total`` (that
  suffix promises a counter);
- **documented** — the name appears backtick-quoted in the metric
  catalog docs (the same three files the obs-events catalog spans:
  docs/observability.md, docs/serving.md, docs/fleet.md).

A **non-literal** name (the shared HTTP handler's
``f"{prefix}_requests_total"``) must carry a marker naming the
metric(s) it registers — ``# lint-ok: metric-hygiene: <name>
[<name>...]`` — and each named metric is then checked like a
literal. A marker on a LITERAL registration grandfathers it
(triage escape hatch; the reason should say why the name cannot
follow the convention).

**Label cardinality** (ISSUE 20): a ``.labels(...)`` call on a
registration whose label VALUE is a non-literal expression mints a
new metric child per distinct runtime string — a scanner probing
random URLs or a tenant-id flood becomes an unbounded label space
and an unbounded registry. Such a value must either come from a
**bounding helper** — a call whose function name contains
``bounded`` or ends ``_label`` (``_bounded_path(...)``,
``self._tenant_label(...)``) — or the line must carry a
``bounded=<label>`` token in its metric-hygiene marker::

    .labels(site=site).inc()  # lint-ok: metric-hygiene: bounded=site

``bounded=`` tokens are recognised anywhere in the registration
chain's line range (a chained ``.labels()`` call starts, in AST
terms, at the receiver's first line). They are NOT metric names and
NOT grandfather reasons: a marker whose payload is only ``bounded=``
tokens does not exempt the name checks.

Receivers named for array/plotting libraries (``np.histogram``,
``jnp.histogram``, ``plt.hist``…) are ignored — those are math, not
metrics.
"""

from __future__ import annotations

import ast
import re

from ..framework import Rule, register

_FACTORIES = {"counter", "gauge", "histogram"}
#: receiver names whose ``histogram`` attribute is a math routine
_NOT_A_REGISTRY = {"np", "numpy", "jnp", "jax", "plt", "scipy"}
_SNAKE = re.compile(r"^[a-z][a-z0-9_]*$")
_HIST_UNITS = ("_seconds", "_bytes")


def _factory_kind(node):
    """``counter``/``gauge``/``histogram`` when ``node`` is a metric
    registration call, else None."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _FACTORIES:
        if isinstance(f.value, ast.Name) \
                and f.value.id in _NOT_A_REGISTRY:
            return None
        return f.attr
    if isinstance(f, ast.Name) and f.id in _FACTORIES:
        return f.id
    return None


def _name_arg(node):
    """The AST node carrying the metric name (first positional or
    the ``name=`` keyword), or None."""
    if node.args:
        return node.args[0]
    for kw in node.keywords:
        if kw.arg == "name":
            return kw.value
    return None


def _split_payload(payload):
    """Split a marker payload into ``(bounded_labels, rest_tokens)``:
    ``bounded=<label>`` tokens declare label-cardinality triage, the
    rest are metric names / grandfather reasons."""
    bounded, rest = set(), []
    for tok in (payload or "").split():
        tok = tok.rstrip(",;")
        if tok.startswith("bounded="):
            bounded.add(tok[len("bounded="):])
        elif tok:
            rest.append(tok)
    return bounded, rest


def _bounded_helper_call(value):
    """True when a label value comes from a bounding helper — a call
    whose function name contains ``bounded`` or ends ``_label``
    (``_bounded_path(path, routes)``, ``self._tenant_label(t)``) —
    the code-shape guarantee that the runtime string was folded into
    a finite label set."""
    if not isinstance(value, ast.Call):
        return False
    f = value.func
    if isinstance(f, ast.Attribute):
        name = f.attr
    elif isinstance(f, ast.Name):
        name = f.id
    else:
        return False
    return "bounded" in name or name.endswith("_label")


def _name_problems(name, kind, catalog):
    """The convention violations of one (name, kind) registration."""
    out = []
    if not _SNAKE.match(name):
        out.append(f"metric {name!r} is not snake_case")
        return out                    # suffix checks are meaningless
    if kind == "counter" and not name.endswith("_total"):
        out.append(f"counter {name!r} must end '_total'")
    if kind == "histogram" and not name.endswith(_HIST_UNITS):
        out.append(f"histogram {name!r} must end in a unit suffix "
                   f"({' / '.join(_HIST_UNITS)})")
    if kind == "gauge" and name.endswith("_total"):
        out.append(f"gauge {name!r} must not end '_total' (that "
                   "suffix promises a monotonic counter)")
    if name not in catalog:
        out.append(f"metric {name!r} not in the documented catalog "
                   "(docs/observability.md / serving.md / fleet.md) "
                   "— add a catalog table row or rename to a "
                   "documented metric")
    return out


@register
class MetricHygieneRule(Rule):
    id = "JL005"
    name = "metric-hygiene"
    short = ("metric names: snake_case, unit suffix "
             "(_total/_seconds/_bytes), documented catalog")
    scope = None
    # the registry itself builds names generically (pass-through
    # module helpers); its own process_uptime_seconds IS checked at
    # the call sites that touch it
    exclude = ("obs/metrics.py",)
    self_markers = True     # the marker NAMES the metric(s) on
    #                         non-literal registrations; on literal
    #                         ones it grandfathers

    def check(self, ctx, config):
        catalog = config.metric_catalog
        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "labels" \
                    and isinstance(node.func.value, ast.Call) \
                    and _factory_kind(node.func.value) is not None \
                    and _name_arg(node.func.value) is not None:
                yield from self._check_labels(ctx, node)
            kind = _factory_kind(node)
            if kind is None:
                continue
            arg = _name_arg(node)
            if arg is None:
                continue              # not a registration form
            payload = ctx.marked(node.lineno, self.name)
            bounded_only = payload is not None \
                and not _split_payload(payload)[1]
            if isinstance(arg, ast.Constant) \
                    and isinstance(arg.value, str):
                if payload is not None and not bounded_only:
                    continue          # grandfathered literal — a
                    # payload of only bounded= tokens is label
                    # triage, not a name-check exemption
                names = [arg.value]
            else:
                names = [t for t in _split_payload(payload)[1]
                         if _SNAKE.match(t)]
                if not names:
                    yield self.finding(
                        ctx, node.lineno,
                        f"{kind} registration with a non-literal "
                        "name — use a literal or a '# lint-ok: "
                        "metric-hygiene: <name> [...]' marker "
                        "naming the metric(s) it registers")
                    continue
            for name in names:
                for problem in _name_problems(name, kind, catalog):
                    yield self.finding(ctx, node.lineno, problem,
                                       data={"metric": name,
                                             "kind": kind})

    def _check_labels(self, ctx, node):
        """The label-cardinality check of one ``<factory>(...)
        .labels(...)`` chain (see the module docstring): every
        non-literal label value needs a bounding-helper call or a
        ``bounded=<label>`` marker token somewhere on the chain's
        lines (a chained call's ``lineno`` is the RECEIVER's first
        line, so the trailing marker lives at ``end_lineno``)."""
        bounded = set()
        for ln in range(node.lineno,
                        (node.end_lineno or node.lineno) + 1):
            bounded |= _split_payload(
                ctx.marked(ln, self.name))[0]
        for kw in node.keywords:
            if kw.arg is None:
                yield self.finding(
                    ctx, node.lineno,
                    ".labels(**...) hides the label names from "
                    "cardinality review — pass labels as explicit "
                    "keywords")
                continue
            value = kw.value
            if isinstance(value, ast.Constant):
                continue              # a literal value is bounded
            if _bounded_helper_call(value) or kw.arg in bounded:
                continue
            yield self.finding(
                ctx, node.lineno,
                f"label {kw.arg!r} takes a non-literal value — "
                "every distinct runtime string mints a new metric "
                "child (unbounded cardinality); fold it through a "
                "bounding helper (function name containing "
                "'bounded' or ending '_label') or, after verifying "
                "the value set is finite, mark the line "
                f"'# lint-ok: metric-hygiene: bounded={kw.arg}'",
                data={"label": kw.arg})
