"""Rule plugins. Importing this package registers every rule with the
framework registry (``framework.RULES``), in catalog order: the four
ported legacy lints first, the metric-hygiene rule (ISSUE 13), the
fsops-seam rule (ISSUE 17), then the three analyzers new in ISSUE 8.
"""

from . import (excepts, import_jit, syncpoints, obs_events,  # noqa: F401
               metrics_hygiene, fsops_seam, retrace, locks,
               jit_boundary)
