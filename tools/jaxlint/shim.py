"""Legacy-contract adapters for the four standalone lint scripts.

``tools/lint_excepts.py`` / ``lint_import_jit.py`` /
``lint_syncpoints.py`` / ``lint_obs_events.py`` are kept as thin
shims over the unified framework (same function shapes, same CLI
exit codes) so existing callers — and muscle memory — keep working.
Each shim's ``scan_source`` returns the legacy ``[(line, message)]``
tuples, ``scan_tree`` the legacy ``[(path, line, message)]``.
"""

from __future__ import annotations

import os
import sys

from .framework import RULES, Config, FileContext, iter_py_files
from . import rules as _rules  # noqa: F401  (populate registry)


def _excluded(rule, path, root):
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    return any(rel.endswith(e) for e in rule.exclude)


def scan_source(rule_name, source, filename="<string>"):
    """Legacy ``[(line, message)]`` for one source blob (marker
    suppression applied)."""
    rule = RULES[rule_name]
    return sorted({f.legacy() for f in
                   rule.scan_source(source, filename)})


def scan_file(rule_name, path):
    with open(path, encoding="utf-8") as fh:
        return scan_source(rule_name, fh.read(), filename=path)


def scan_tree(rule_name, root):
    """Legacy ``[(path, line, message)]`` over every ``*.py`` under
    ``root`` (the rule's own exclude list — e.g. the syncpoints
    profiling allowlist — is honored)."""
    rule = RULES[rule_name]
    out = []
    for path in iter_py_files(root):
        if _excluded(rule, path, root):
            continue
        out.extend((path, line, msg)
                   for line, msg in scan_file(rule_name, path))
    return out


def main(rule_name, argv, default_targets, label):
    """Legacy CLI driver: scan the targets, print ``path:line:
    message`` lines, exit 1 on violations."""
    args = list(sys.argv[1:] if argv is None else argv)
    if not args:
        args = default_targets()
    violations = []
    for target in args:
        if os.path.isdir(target):
            violations.extend(scan_tree(rule_name, target))
        else:
            violations.extend((target, line, msg) for line, msg
                              in scan_file(rule_name, target))
    for path, line, msg in violations:
        print(f"{path}:{line}: {msg}")
    if violations:
        print(f"{len(violations)} {label} violation(s)",
              file=sys.stderr)
        return 1
    return 0


# ---- obs-events legacy contract (events + catalog) ------------------

def obs_collect(source, filename="<src>"):
    """Legacy ``(events, violations)`` — event emissions as
    ``[(lineno, name)]`` (markers resolve names), violations as
    ``[(lineno, message)]``; no catalog check. Raises SyntaxError
    like the legacy scanner."""
    ctx = FileContext(filename, source=source, rel=filename)
    if ctx.syntax_error is not None:
        raise ctx.syntax_error
    return RULES["obs-events"].collect(ctx)


def obs_scan_tree(root, doc_path):
    """Legacy obs-events tree scan against the catalog at
    ``doc_path`` (one path or several) →
    ``[(path, lineno, message)]``."""
    rule = RULES["obs-events"]
    paths = [doc_path] if isinstance(doc_path, (str, os.PathLike)) \
        else list(doc_path)
    config = Config(obs_docs=[os.fspath(p) for p in paths])
    out = []
    for path in iter_py_files(root):
        if _excluded(rule, path, root):
            continue
        ctx = FileContext(path)
        if ctx.syntax_error is not None:
            raise ctx.syntax_error
        out.extend((path, f.line, f.message)
                   for f in rule.check(ctx, config))
    return out
