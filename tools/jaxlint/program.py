"""JP2xx — program-level contract rules over every cached jit site.

The AST rules (JL1xx) see source text; the failure modes that cost
this repo the most are only visible in the *traced program*. PR 7
found the bench silently timing the staged ``sspec_thth`` path
(stamped 0.31x while the fused path measured 2.36x): the source was
clean, the wrong PROGRAM was compiled. This pass audits the programs
themselves — for every site in the ``obs/retrace.py`` ``record_build``
registry it traces the site's registered abstract probe
(``scintools_tpu/obs/programs.py``: ``jax.make_jaxpr`` over
``ShapeDtypeStruct`` inputs — no execution, no compile, CPU-only,
~5 s for the whole registry) and checks the resulting program against
per-site contracts:

========  ====================  ===================================
id        rule                  catches
========  ====================  ===================================
JP200     program-coverage      a ``record_build`` site with no
                                registered probe (an unaudited
                                program), or a probe that fails to
                                trace
JP201     program-dtype         f64/c128 leaks in a float32-policy
                                program: wide avals, or wide closure
                                constants above the site budget
JP202     program-consts        closure-captured array constants
                                baked into the program above the
                                site's byte budget (compile bloat
                                the AST retrace rule cannot see)
JP203     program-hostcalls     host-callback primitives
                                (pure_callback / io_callback /
                                debug_callback) in hot-path sites
JP204     program-donation      observed buffer donation
                                inconsistent with the declared
                                argnums under the 'jit.donate'
                                formulation, or donated buffers no
                                output can reuse
JP205     program-fingerprint   the site's program fingerprint
                                (avals + primitive multiset + consts
                                + formulations + donation) differs
                                from the committed baseline
                                (``tools/jaxlint/program_baseline
                                .json``) — the PR-7 regression class,
                                failed loudly with a readable diff
========  ====================  ===================================

Sites are discovered STATICALLY during the normal file scan
(:func:`collect_sites`: literal first arguments of ``record_build``
calls plus literal ``site=`` keywords of ``keyed_jit_cache``-style
calls), then cross-checked against the probe registry — so a new
cached jit site without a probe fails tier-1 loudly (JP200), and a
probe whose site vanished is reported stale. Summaries are memoised
per process (obs/programs.py), so repeated ``run()`` calls after the
first pay only the rule checks.

Baseline workflow::

    python -m tools.jaxlint --write-fingerprints   # refresh baseline
    git diff tools/jaxlint/program_baseline.json   # REVIEW the flip

A fingerprint change is a formulation/program change: review it like
a semantics change, not like churn.
"""

from __future__ import annotations

import ast
import json
import os

from .framework import Finding, Rule, package_rel, register

#: default committed fingerprint baseline, relative to the repo root
BASELINE_RELPATH = os.path.join("tools", "jaxlint",
                                "program_baseline.json")

#: primitive names that cross the host boundary at run time
_HOST_CALLBACK_MARKER = "callback"


def _callee_name(func):
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def collect_sites(ctx, into):
    """Accumulate ``{site: (rel, line)}`` from one FileContext:
    literal first arguments of ``record_build(...)`` calls and
    literal ``site="..."`` keywords anywhere (the
    ``keyed_jit_cache(site=...)`` convention). Non-literal site names
    are reported by the retrace-hazard AST machinery, not here."""
    for node in ctx.nodes:
        if not isinstance(node, ast.Call):
            continue
        name = _callee_name(node.func)
        if name == "record_build" and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                            str):
                into.setdefault(arg.value, (ctx.rel, arg.lineno))
        for kw in node.keywords:
            if kw.arg == "site" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                into.setdefault(kw.value.value,
                                (ctx.rel, kw.value.lineno))


class ProgramAudit:
    """One site's audit input: its static location, registered probe
    (or None) and traced summary (or the trace error)."""

    __slots__ = ("site", "rel", "line", "spec", "summary", "error")

    def __init__(self, site, rel, line, spec=None, summary=None,
                 error=None):
        self.site = site
        self.rel = rel
        self.line = line
        self.spec = spec
        self.summary = summary
        self.error = error

    def anchor(self):
        """(rel, line) for findings: the probe registration when one
        exists (the contract lives there), else the record_build call
        site."""
        if self.spec is not None:
            rel = package_rel(self.spec.path)
            if rel is not None:
                return rel, self.spec.lineno
        return self.rel, self.line


class ProgramRule(Rule):
    """Base for JP rules: no per-file findings; the runner calls
    :meth:`check_program` once per audited site after the file scan.
    Line markers cannot apply (there is no flagged source line), so
    suppression is baseline-only; ``code`` carries the site name to
    keep baseline fingerprints line-insensitive AND content-stable."""

    program = True
    self_markers = True
    scope = None

    def check(self, ctx, config):
        return ()

    def check_program(self, audit, config):
        raise NotImplementedError

    def site_finding(self, audit, message, data=None):
        rel, line = audit.anchor()
        return Finding(self.name, rel, line, message, rel=rel,
                       data=data, code=f"site:{audit.site}")


@register
class ProgramCoverageRule(ProgramRule):
    id = "JP200"
    name = "program-coverage"
    short = ("record_build sites without a registered abstract probe "
             "(unaudited programs), or probes that fail to trace")

    def check_program(self, audit, config):
        if audit.spec is None:
            yield self.site_finding(
                audit,
                f"jit-cache site '{audit.site}' has no registered "
                f"abstract probe — its compiled program is unaudited. "
                f"Register one next to the site with "
                f"@obs.programs.register_probe({audit.site!r}) (and "
                f"list the module in obs.programs.PROBE_MODULES)")
        elif audit.error is not None:
            yield self.site_finding(
                audit,
                f"probe for site '{audit.site}' failed to trace: "
                f"{type(audit.error).__name__}: {audit.error}")


@register
class ProgramDtypeRule(ProgramRule):
    id = "JP201"
    name = "program-dtype"
    short = ("f64/c128 leaks in float32-policy programs (wide avals "
             "or oversized wide closure constants)")

    def check_program(self, audit, config):
        s = audit.summary
        if s is None or audit.spec.policy != "float32":
            return
        if s["wide_avals"]:
            yield self.site_finding(
                audit,
                f"site '{audit.site}' (float32 policy) computes wide "
                f"intermediates: {', '.join(s['wide_avals'][:4])} — "
                f"a mixed-precision leak; cast at the program "
                f"boundary or declare policy='float64' on the probe")
        budget = audit.spec.f64_const_budget
        if s["wide_const_bytes"] > budget:
            yield self.site_finding(
                audit,
                f"site '{audit.site}' (float32 policy) bakes "
                f"{s['wide_const_bytes']} bytes of f64/c128 closure "
                f"constants (budget {budget}) — host geometry should "
                f"be cast to float32 before capture",
                data={"wide_const_bytes": s["wide_const_bytes"]})


@register
class ProgramConstsRule(ProgramRule):
    id = "JP202"
    name = "program-consts"
    short = ("closure-captured array constants baked into a program "
             "above the site's byte budget (compile bloat)")

    def check_program(self, audit, config):
        s = audit.summary
        if s is None:
            return
        budget = audit.spec.const_budget
        if s["const_bytes"] > budget:
            yield self.site_finding(
                audit,
                f"site '{audit.site}' bakes {s['const_bytes']} bytes "
                f"of closure constants into the program (budget "
                f"{budget}, largest {s['max_const_bytes']}) — pass "
                f"large arrays as traced arguments so they are not "
                f"re-embedded (and re-hashed) per compile",
                data={"const_bytes": s["const_bytes"]})


@register
class ProgramHostcallsRule(ProgramRule):
    id = "JP203"
    name = "program-hostcalls"
    short = ("host-callback primitives (pure_callback/io_callback/"
             "debug_callback) inside hot-path programs")

    def check_program(self, audit, config):
        s = audit.summary
        if s is None or not audit.spec.hot:
            return
        hits = {p: n for p, n in s["primitives"].items()
                if _HOST_CALLBACK_MARKER in p}
        if hits:
            yield self.site_finding(
                audit,
                f"hot-path site '{audit.site}' stages host callbacks "
                f"{hits} — each fences the device per call; remove "
                f"it or mark the probe hot=False with a reason",
                data={"callbacks": hits})


@register
class ProgramDonationRule(ProgramRule):
    id = "JP204"
    name = "program-donation"
    short = ("observed buffer donation inconsistent with the "
             "declared argnums under the 'jit.donate' formulation")

    def check_program(self, audit, config):
        s = audit.summary
        if s is None:
            return
        from scintools_tpu.backend import formulation

        active = formulation("jit.donate", platform="cpu") == "on"
        expected = sorted(audit.spec.donate) if active else []
        observed = sorted(s["donated"])
        if observed != expected:
            yield self.site_finding(
                audit,
                f"site '{audit.site}' donates argnums {observed} but "
                f"the 'jit.donate' formulation "
                f"({'on' if active else 'off'} on this platform) "
                f"implies {expected} — donation must route through "
                f"backend.donation_argnums(), never be hardcoded",
                data={"observed": observed, "expected": expected})
            return
        out_avals = set(s["out_avals"])
        for argnum in observed:
            if argnum < len(s["in_avals"]) \
                    and s["in_avals"][argnum] not in out_avals:
                yield self.site_finding(
                    audit,
                    f"site '{audit.site}' donates argnum {argnum} "
                    f"({s['in_avals'][argnum]}) but no output matches "
                    f"its shape/dtype — XLA cannot reuse the buffer "
                    f"and warns on every compile")


@register
class ProgramFingerprintRule(ProgramRule):
    id = "JP205"
    name = "program-fingerprint"
    short = ("program fingerprint differs from the committed "
             "baseline — the compiler picked a different program")

    def check_program(self, audit, config):
        s = audit.summary
        if s is None:
            return
        baseline = load_program_baseline(config)
        if baseline is None:
            yield self.site_finding(
                audit,
                f"no committed program-fingerprint baseline at "
                f"{BASELINE_RELPATH} — run `python -m tools.jaxlint "
                f"--write-fingerprints` and commit it")
            return
        entry = baseline.get("sites", {}).get(audit.site)
        if entry is None:
            yield self.site_finding(
                audit,
                f"site '{audit.site}' has no committed fingerprint "
                f"(new program) — run `python -m tools.jaxlint "
                f"--write-fingerprints`, review and commit the diff")
            return
        if entry.get("fingerprint") == s["fingerprint"]:
            return
        yield self.site_finding(
            audit,
            f"site '{audit.site}' compiles a DIFFERENT program than "
            f"the committed baseline ({entry.get('fingerprint')} -> "
            f"{s['fingerprint']}): {summary_diff(entry, s)} — if "
            f"deliberate, refresh with --write-fingerprints and "
            f"commit the reviewed diff",
            data={"diff": summary_diff(entry, s)})


def summary_diff(old, new):
    """Readable one-line structural diff between a baseline entry and
    a live summary — what changed, not just that something did."""
    parts = []
    po, pn = old.get("primitives", {}), new.get("primitives", {})
    prim_delta = []
    for p in sorted(set(po) | set(pn)):
        a, b = po.get(p, 0), pn.get(p, 0)
        if a != b:
            prim_delta.append(f"{p}:{a}->{b}")
    if prim_delta:
        parts.append("primitives{" + ", ".join(prim_delta[:8])
                     + (", ..." if len(prim_delta) > 8 else "") + "}")
    for key in ("in_avals", "out_avals", "formulations", "donated",
                "policy", "const_count", "const_dtypes"):
        a, b = old.get(key), new.get(key)
        if a != b:
            parts.append(f"{key}: {a} -> {b}")
    return "; ".join(parts) or "identity fields unchanged (hash " \
                                "inputs reordered?)"


# ---------------------------------------------------------------------
# pass runner + baseline I/O
# ---------------------------------------------------------------------

_BASELINE_CACHE = {}


def baseline_path(config):
    return os.path.join(config.repo_root, BASELINE_RELPATH)


def load_program_baseline(config):
    """The committed fingerprint baseline document, or None when the
    file does not exist (cached per path per process)."""
    path = baseline_path(config)
    if path not in _BASELINE_CACHE:
        try:
            with open(path, encoding="utf-8") as fh:
                _BASELINE_CACHE[path] = json.load(fh)
        except FileNotFoundError:
            _BASELINE_CACHE[path] = None
    return _BASELINE_CACHE[path]


def write_program_baseline(path, summaries):
    """Write ``{site: summary}`` as the new fingerprint baseline;
    returns ``(written, pruned)`` counts vs any previous file. The
    stored entries keep the full identity fields so JP205 diffs stay
    readable offline."""
    from scintools_tpu.obs.programs import FINGERPRINT_FIELDS

    old_sites = set()
    try:
        with open(path, encoding="utf-8") as fh:
            old_sites = set(json.load(fh).get("sites", {}))
    except (OSError, ValueError):
        pass
    sites = {}
    for site, s in sorted(summaries.items()):
        entry = {k: s[k] for k in FINGERPRINT_FIELDS if k in s}
        entry["fingerprint"] = s["fingerprint"]
        sites[site] = entry
    doc = {
        "version": 1,
        "note": ("program fingerprints per jit-cache site — traced "
                 "CPU-canonical over a fixed AbstractMesh "
                 "(obs/programs.py); refresh with `python -m "
                 "tools.jaxlint --write-fingerprints` and REVIEW the "
                 "diff like a semantics change"),
        "sites": sites,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    _BASELINE_CACHE.pop(path, None)
    return len(sites), len(old_sites - set(sites))


def run_program_pass(site_map, rules, config):
    """Audit every site in ``site_map`` with the active program
    ``rules``. Returns ``(findings, stats)`` where ``stats`` also
    carries the traced summaries (for ``--write-fingerprints``)."""
    findings = []
    stats = {"sites": len(site_map), "probed": 0, "traced": 0,
             "stale_probes": [], "summaries": {}}
    if not site_map:
        return findings, stats

    from scintools_tpu.obs import programs

    registry = programs.probes()
    audits = []
    for site, (rel, line) in sorted(site_map.items()):
        spec = registry.get(site)
        audit = ProgramAudit(site, rel, line, spec=spec)
        if spec is not None:
            stats["probed"] += 1
            try:
                audit.summary = programs.summary(site)
                stats["traced"] += 1
                stats["summaries"][site] = audit.summary
            except Exception as e:  # surfaced as a JP200 finding
                audit.error = e
        audits.append(audit)

    # probes whose site vanished from the tree: report as stale so a
    # renamed site cannot keep shipping a green-but-dead audit
    stats["stale_probes"] = sorted(set(registry) - set(site_map))

    for audit in audits:
        for rule in rules:
            findings.extend(rule.check_program(audit, config))
    return findings, stats
