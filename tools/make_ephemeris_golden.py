"""Generate the ephemeris golden fixture (tests/data/ephemeris_golden.json).

The package ephemeris (scintools_tpu/utils/ephemeris.py) computes
Earth's barycentric position/velocity from the JPL approximate
Keplerian elements. A silent transcription typo there would bias every
veff fit while passing all sanity tests (VERDICT r3 weak #3). This
tool produces an INDEPENDENT tabulation to pin absolute accuracy
against, built offline (no network, no astropy in this image) from a
*different published theory, transcribed separately*:

- Sun:   Meeus, *Astronomical Algorithms* (2nd ed.) ch. 25 — FK5
  geometric solar coordinates (L0/M/e/equation-of-center/R). This is
  an EMB-level solar theory (no monthly lunar terms), stated accuracy
  0.01 deg in longitude.
- Moon:  Meeus ch. 47, truncated to the dominant periodic terms
  (lunar position to ~0.1%), to place the TRUE geocenter relative to
  the Earth-Moon barycenter: offset = -moon_geo / 82.30057. This term
  (±4670 km, ±12.6 m/s) is deliberately absent from the package
  ephemeris, so the fixture carries the honest truth and the tests'
  tolerances (<20 m/s, <0.1 s) include it.
- Sun wobble: Kepler orbits of Jupiter-Neptune about the Sun from
  mean elements as tabulated by Meeus ch. 31 (a second, independent
  transcription of essentially the same element set the package
  uses); the wobble is ±0.005 AU ≈ ±2.5 s of Roemer delay and must
  be present on both sides.

The generator self-checks its own theory against hard almanac facts
(perihelion timing/distance, aphelion distance, mean orbital speed)
before writing anything — a transcription typo HERE fails those
checks rather than silently poisoning the fixture.

Run:  python tools/make_ephemeris_golden.py
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

AU_KM = 149597870.700
C_KM_S = 299792.458
DAY_S = 86400.0
OBLIQUITY_DEG = 23.4392911          # IAU 2006, J2000
EARTH_MOON_MASS_RATIO = 81.30057
D2R = np.pi / 180.0


def _kepler(M, e, iters=10):
    E = M + e * np.sin(M)
    for _ in range(iters):
        E = E - (E - e * np.sin(E) - M) / (1 - e * np.cos(E))
    return E


# ---------------------------------------------------------------------------
# Sun (Meeus ch. 25, geometric, mean equinox of date ~ J2000 for our use)
# ---------------------------------------------------------------------------

def sun_geocentric_ecliptic(T):
    """Geometric geocentric solar ecliptic (lon [rad], R [AU]) at T
    Julian centuries from J2000.0 (Meeus 25.2-25.5). EMB-level: the
    monthly geocenter wiggle is not in this theory."""
    L0 = (280.46646 + 36000.76983 * T + 0.0003032 * T ** 2) * D2R
    M = (357.52911 + 35999.05029 * T - 0.0001537 * T ** 2) * D2R
    e = 0.016708634 - 0.000042037 * T - 0.0000001267 * T ** 2
    C = ((1.914602 - 0.004817 * T - 0.000014 * T ** 2) * np.sin(M)
         + (0.019993 - 0.000101 * T) * np.sin(2 * M)
         + 0.000289 * np.sin(3 * M)) * D2R
    lon = L0 + C
    nu = M + C
    R = 1.000001018 * (1 - e ** 2) / (1 + e * np.cos(nu))
    return lon, R


# ---------------------------------------------------------------------------
# Moon (Meeus ch. 47, dominant terms only — plenty for a 4670 km offset)
# ---------------------------------------------------------------------------

# (D, M, M', F, coeff_lon [1e-6 deg], coeff_dist [1e-3 km])
_LUNAR_LR = [
    (0, 0, 1, 0, 6288774, -20905355),
    (2, 0, -1, 0, 1274027, -3699111),
    (2, 0, 0, 0, 658314, -2955968),
    (0, 0, 2, 0, 213618, -569925),
    (0, 1, 0, 0, -185116, 48888),
    (0, 0, 0, 2, -114332, -3149),
    (2, 0, -2, 0, 58793, 246158),
    (2, -1, -1, 0, 57066, -152138),
    (2, 0, 1, 0, 53322, -170733),
    (2, -1, 0, 0, 45758, -204586),
]
# (D, M, M', F, coeff_lat [1e-6 deg])
_LUNAR_B = [
    (0, 0, 0, 1, 5128122),
    (0, 0, 1, 1, 280602),
    (0, 0, 1, -1, 277693),
    (2, 0, 0, -1, 173237),
    (2, 0, -1, 1, 55413),
    (2, 0, -1, -1, 46271),
]


def moon_geocentric_ecliptic(T):
    """Geocentric lunar ecliptic (lon [rad], lat [rad], dist [km])
    (Meeus ch. 47 truncated)."""
    Lp = (218.3164477 + 481267.88123421 * T - 0.0015786 * T ** 2) * D2R
    D = (297.8501921 + 445267.1114034 * T - 0.0018819 * T ** 2) * D2R
    M = (357.5291092 + 35999.0502909 * T) * D2R
    Mp = (134.9633964 + 477198.8675055 * T + 0.0087414 * T ** 2) * D2R
    F = (93.2720950 + 483202.0175233 * T - 0.0036539 * T ** 2) * D2R
    E = 1 - 0.002516 * T - 0.0000074 * T ** 2

    sl, sr = 0.0, 0.0
    for d, m, mp, f, cl, cr in _LUNAR_LR:
        arg = d * D + m * M + mp * Mp + f * F
        ef = E ** abs(m)
        sl = sl + cl * ef * np.sin(arg)
        sr = sr + cr * ef * np.cos(arg)
    sb = 0.0
    for d, m, mp, f, cb in _LUNAR_B:
        arg = d * D + m * M + mp * Mp + f * F
        sb = sb + cb * E ** abs(m) * np.sin(arg)
    lon = Lp + sl * 1e-6 * D2R
    lat = sb * 1e-6 * D2R
    dist = 385000.56 + sr * 1e-3
    return lon, lat, dist


# ---------------------------------------------------------------------------
# Giant planets (heliocentric Kepler orbits, J2000 mean elements —
# Meeus ch. 31 tabulation, transcribed independently of the package)
# ---------------------------------------------------------------------------

# a [AU], e, I [deg], L [deg] + rate [deg/cy], varpi [deg], Omega [deg]
_GIANTS = {
    "jupiter": (5.202603, 0.048498, 1.30327, 34.35148, 3034.90567,
                14.33121, 100.46444, 1047.3486),
    "saturn": (9.554910, 0.055548, 2.48888, 50.07757, 1222.11494,
               93.05679, 113.66552, 3497.898),
    "uranus": (19.218446, 0.046381, 0.77320, 314.05501, 429.86356,
               173.00516, 74.00595, 22902.98),
    "neptune": (30.110387, 0.009456, 1.76995, 304.34867, 219.88581,
                48.12370, 131.78406, 19412.24),
}


def planet_heliocentric_ecliptic(name, T):
    """Of-date ecliptic position: the tabulated L rate is of-date
    (includes precession), so varpi/Omega must drift with the
    precession rate too or the mean anomaly L - varpi picks up a
    spurious 1.4 deg/cy. The frame is unwound to J2000 downstream."""
    a, e, I, L0, Lr, varpi, Omega, _ = _GIANTS[name]
    L = (L0 + Lr * T) * D2R
    varpi = (varpi + 1.3969713 * T) * D2R
    Omega = (Omega + 1.3969713 * T) * D2R
    I = I * D2R
    omega = varpi - Omega
    M = np.mod(L - varpi + np.pi, 2 * np.pi) - np.pi
    E = _kepler(M, e)
    xp = a * (np.cos(E) - e)
    yp = a * np.sqrt(1 - e ** 2) * np.sin(E)
    co, so = np.cos(omega), np.sin(omega)
    cO, sO = np.cos(Omega), np.sin(Omega)
    cI, sI = np.cos(I), np.sin(I)
    return np.stack([
        (co * cO - so * sO * cI) * xp + (-so * cO - co * sO * cI) * yp,
        (co * sO + so * cO * cI) * xp + (-so * sO + co * cO * cI) * yp,
        (so * sI) * xp + (co * sI) * yp], axis=-1)


def sun_barycentric_ecliptic(T):
    """Sun's position relative to the solar-system barycenter [AU]."""
    mtot = 1.0 + sum(1.0 / g[7] for g in _GIANTS.values())
    r = 0.0
    for name, g in _GIANTS.items():
        r = r - planet_heliocentric_ecliptic(name, T) / g[7]
    return r / mtot


# ---------------------------------------------------------------------------
# Assembly: true-Earth barycentric equatorial position / velocity
# ---------------------------------------------------------------------------

def _ecl_to_equ(xyz):
    eps = OBLIQUITY_DEG * D2R
    x, y, z = xyz[..., 0], xyz[..., 1], xyz[..., 2]
    return np.stack([x, y * np.cos(eps) - z * np.sin(eps),
                     y * np.sin(eps) + z * np.cos(eps)], axis=-1)


def _precess_to_j2000(xyz, T):
    """Rotate ecliptic-of-date coordinates to the J2000 ecliptic
    frame. The Meeus solar/lunar/planetary longitudes above are
    referred to the mean equinox of DATE; the general precession in
    longitude (5029.0966 arcsec/cy) must be unwound or the frame
    drifts ~1.4 deg/century against J2000 (≈190 m/s of spurious
    velocity by 2026)."""
    p = (1.3969713 + 0.0003086 * T) * T * D2R
    cp, sp = np.cos(p), np.sin(p)
    x, y, z = xyz[..., 0], xyz[..., 1], xyz[..., 2]
    return np.stack([cp * x + sp * y, -sp * x + cp * y, z], axis=-1)


def earth_bary_equatorial(mjd):
    """True-geocenter barycentric equatorial (J2000) position [AU]
    at MJD(TT)."""
    T = (np.asarray(mjd, dtype=float) - 51544.5) / 36525.0
    lon, R = sun_geocentric_ecliptic(T)
    # heliocentric EMB-level Earth = antipode of the geocentric Sun
    emb = np.stack([-R * np.cos(lon), -R * np.sin(lon),
                    np.zeros_like(R)], axis=-1)
    # true geocenter: Earth sits opposite the Moon about the EMB
    mlon, mlat, mdist = moon_geocentric_ecliptic(T)
    moon = (mdist / AU_KM)[..., None] * np.stack(
        [np.cos(mlat) * np.cos(mlon), np.cos(mlat) * np.sin(mlon),
         np.sin(mlat)], axis=-1)
    geo = emb - moon / (1.0 + EARTH_MOON_MASS_RATIO)
    bary = geo + sun_barycentric_ecliptic(T)
    return _ecl_to_equ(_precess_to_j2000(bary, T))


def earth_vel_equatorial(mjd, dt_days=0.1):
    """Barycentric equatorial velocity [km/s] by central differences."""
    mjd = np.asarray(mjd, dtype=float)
    dpos = earth_bary_equatorial(mjd + dt_days) \
        - earth_bary_equatorial(mjd - dt_days)
    return dpos * AU_KM / (2 * dt_days * DAY_S)


def project(mjd, ra, dec):
    """The package API's projections: (v_ra, v_dec, v_r) [km/s] and
    Roemer delay [s] toward (ra, dec) [rad]."""
    v = earth_vel_equatorial(mjd)
    vx, vy, vz = v[..., 0], v[..., 1], v[..., 2]
    v_ra = -vx * np.sin(ra) + vy * np.cos(ra)
    v_dec = (-vx * np.sin(dec) * np.cos(ra)
             - vy * np.sin(dec) * np.sin(ra) + vz * np.cos(dec))
    v_r = (vx * np.cos(dec) * np.cos(ra)
           + vy * np.cos(dec) * np.sin(ra) + vz * np.sin(dec))
    n = np.array([np.cos(dec) * np.cos(ra), np.cos(dec) * np.sin(ra),
                  np.sin(dec)])
    delay = earth_bary_equatorial(mjd) @ n * AU_KM / C_KM_S
    return v_ra, v_dec, v_r, delay


# ---------------------------------------------------------------------------
# Self-checks against hard almanac facts (fail loudly on typos here)
# ---------------------------------------------------------------------------

def _true_earth_sun_dist(mjd):
    """True geocenter-to-Sun distance [AU] (EMB-level solar theory
    plus the lunar geocenter offset — the almanac's perihelion/
    aphelion times refer to THIS distance; the Moon shifts them by
    up to ±30 h relative to the EMB orbit)."""
    T = (np.asarray(mjd, dtype=float) - 51544.5) / 36525.0
    lon, R = sun_geocentric_ecliptic(T)
    emb = np.stack([-R * np.cos(lon), -R * np.sin(lon),
                    np.zeros_like(R)], axis=-1)
    mlon, mlat, mdist = moon_geocentric_ecliptic(T)
    moon = (mdist / AU_KM)[..., None] * np.stack(
        [np.cos(mlat) * np.cos(mlon), np.cos(mlat) * np.sin(mlon),
         np.sin(mlat)], axis=-1)
    return np.linalg.norm(emb - moon / (1.0 + EARTH_MOON_MASS_RATIO),
                          axis=-1)


def self_check():
    # true Earth-Sun distance extrema in 2020: perihelion Jan 5
    # ~07:48 UTC at 0.9832436 AU, aphelion Jul 4 ~11:35 UTC at
    # 1.0166943 AU (USNO/Astronomical Almanac). Timing within ~0.3
    # day; distance within 6e-5 AU (~9000 km ≈ 0.03 s of Roemer —
    # the low-accuracy solar theory omits planetary radius
    # perturbations of a few 1e-5 AU, which is exactly the
    # year-to-year spread of the tabulated extrema).
    mjd = np.linspace(58840.0, 59030.0, 40001)      # Dec 2019-Jun 2020
    R = _true_earth_sun_dist(mjd)
    i = int(np.argmin(R))
    assert abs(mjd[i] - 58853.33) < 0.3, f"perihelion at {mjd[i]}"
    assert abs(R[i] - 0.9832436) < 6e-5, f"perihelion R {R[i]}"
    mjd2 = np.linspace(59000.0, 59100.0, 20001)     # around Jul 2020
    R2 = _true_earth_sun_dist(mjd2)
    j = int(np.argmax(R2))
    assert abs(mjd2[j] - 59034.48) < 0.3, f"aphelion at {mjd2[j]}"
    assert abs(R2[j] - 1.0166943) < 6e-5, f"aphelion R {R2[j]}"
    # mean heliocentric speed over one anomalistic year ≈ 29.78 km/s
    mjd3 = np.linspace(58853.0, 58853.0 + 365.2596, 2000)
    v = earth_vel_equatorial(mjd3)
    speed = np.linalg.norm(v, axis=-1)
    # extrema are BARYCENTRIC: Sun wobble (±13 m/s) + lunar wobble
    # (±12.6 m/s) widen the heliocentric 29.29-30.29 km/s range
    assert abs(speed.mean() - 29.7827) < 0.02, speed.mean()
    assert 30.26 < speed.max() < 30.34, speed.max()
    assert 29.24 < speed.min() < 29.32, speed.min()
    # lunar distance range sanity (perigee ~356500, apogee ~406700 km)
    _, _, dist = moon_geocentric_ecliptic(
        (np.linspace(57000, 62000, 20000) - 51544.5) / 36525.0)
    assert 355000 < dist.min() < 358500, dist.min()
    assert 404500 < dist.max() < 407500, dist.max()
    # giant-planet perihelion passages bracket the known dates
    # (Jupiter 2023-01-21, Saturn 2003-07-26; allow ±40 d — phase at
    # the 0.5 deg level, far better than the wobble budget needs)
    mjd4 = np.linspace(59700, 60400, 7001)          # 2022-2024
    rj = np.linalg.norm(planet_heliocentric_ecliptic(
        "jupiter", (mjd4 - 51544.5) / 36525.0), axis=-1)
    assert abs(mjd4[int(np.argmin(rj))] - 59965.0) < 40.0
    mjd5 = np.linspace(52400, 53200, 8001)          # 2002-2004
    rs = np.linalg.norm(planet_heliocentric_ecliptic(
        "saturn", (mjd5 - 51544.5) / 36525.0), axis=-1)
    assert abs(mjd5[int(np.argmin(rs))] - 52846.0) < 40.0
    print("self-checks OK")


# ---------------------------------------------------------------------------

# fixture sightlines: the archival pulsar the repo's tests use, plus a
# near-ecliptic and a high-declination line to exercise the geometry
PULSARS = {
    "J0437-4715": ("04:37:15.8961737", "-47:15:09.110714"),
    "J1939+2134": ("19:39:38.561224", "+21:34:59.12570"),
    "J0030+0451": ("00:30:27.42843", "+04:51:39.7069"),
}

# twelve epochs spanning 2015-2030, spread across the annual phase
MJDS = [57050.0, 57400.3, 57750.6, 58420.9, 58791.2, 59161.5,
        59531.8, 60202.1, 60572.4, 60942.7, 61313.0, 62683.3]


def main():
    self_check()
    from scintools_tpu.io.parfile import _hms_to_rad, _dms_to_rad

    fix = {"obliquity_deg": OBLIQUITY_DEG, "mjds": MJDS,
           "source": ("Meeus solar theory ch.25 + truncated lunar "
                      "theory ch.47 + giant-planet Kepler wobble; "
                      "independent transcription, see "
                      "tools/make_ephemeris_golden.py"),
           "pulsars": {}}
    for name, (raj, decj) in PULSARS.items():
        ra, dec = _hms_to_rad(raj), _dms_to_rad(decj)
        v_ra, v_dec, v_r, delay = project(np.array(MJDS), ra, dec)
        fix["pulsars"][name] = {
            "raj": raj, "decj": decj,
            "vearth_ra_kms": [round(float(x), 6) for x in v_ra],
            "vearth_dec_kms": [round(float(x), 6) for x in v_dec],
            "vearth_r_kms": [round(float(x), 6) for x in v_r],
            "ssb_delay_s": [round(float(x), 4) for x in delay],
        }
    out = os.path.join(os.path.dirname(__file__), "..", "tests",
                       "data", "ephemeris_golden.json")
    with open(out, "w") as f:
        json.dump(fix, f, indent=1)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
