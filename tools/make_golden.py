"""Generate golden fixtures from the ACTUAL reference package
(/root/reference/scintools), run offline once; output committed as
tests/data/golden_reference.npz (VERDICT r2 item 9).

The reference's heavy deps (astropy/lmfit/emcee) are absent in this
image; tools/astropy_shim.py provides a minimal dimensional shim that
lets the reference's numpy-only compute paths run UNMODIFIED:

- ``Simulation`` (scint_sim.py:23-414): numpy-global-RNG phase screen
  + Fresnel propagation → dynspec (seed-exact golden);
- ``Dynspec.calc_sspec``/``calc_acf`` (dynspec.py:3584-3814) on one
  real J0437-4715 epoch (psrflux parse + trim included);
- ``Dynspec.fit_arc`` curvature/errors + the ``norm_sspec`` scrunched
  profile on the λ-scaled path (dynspec.py:970-1311, :1920-2281);
- ``ththmod.Eval_calc`` η-curve (ththmod.py:371-401) on a chunk of
  the simulated dynspec;
- ``ththmod.thth_map``/``rev_map`` raw matrices (ththmod.py:56-271);
- the Rickett-2014 analytic ``ACF`` grid with anisotropy and phase
  gradient (scint_sim.py:417-678).

A shim bug cannot create false confidence: it would make the goldens
DISAGREE with this repo's independent implementation and fail the
test (tests/test_golden_reference.py).

Run:  python tools/make_golden.py
"""

import os
import sys
import warnings

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)
import astropy_shim  # noqa: E402

astropy_shim.install()
sys.path.insert(0, "/root/reference")
warnings.filterwarnings("ignore")

# the reference predates NumPy 2 (np.complex_ was removed;
# scint_sim.py:589,634) — restore the alias for its unmodified code
if not hasattr(np, "complex_"):
    np.complex_ = np.complex128

OUT = os.path.join(HERE, "..", "tests", "data",
                   "golden_reference.npz")
J0437 = ("/root/reference/scintools/examples/data/J0437-4715/"
         "p111220_074112.rf.pcm.dynspec")


def main():
    out = {}

    # ---- 1. Simulation golden (seed-exact numpy RNG) ----------------
    import scintools.scint_sim as ss

    sim = ss.Simulation(mb2=2, rf=1, ds=0.01, alpha=5 / 3, ar=1,
                        psi=0, inner=0.001, ns=128, nf=64, dlam=0.25,
                        seed=42)
    out["sim_dyn"] = np.asarray(sim.spi, dtype=np.float32)
    out["sim_seed"] = 42
    out["sim_ns"], out["sim_nf"] = 128, 64
    # anisotropic screen (exercises the spectral-weight cross terms,
    # scint_sim.py:276-292) — seed-exact like the isotropic case
    sim_a = ss.Simulation(mb2=4, rf=1, ds=0.01, alpha=5 / 3, ar=2,
                          psi=30, inner=0.001, ns=64, nf=32,
                          dlam=0.25, seed=7)
    out["sim_aniso_dyn"] = np.asarray(sim_a.spi, dtype=np.float32)

    # ---- 2. J0437 epoch: load + sspec + ACF -------------------------
    from scintools.dynspec import Dynspec

    d = Dynspec(filename=J0437, process=False, verbose=False)
    out["j0437_dyn"] = d.dyn.astype(np.float32)
    out["j0437_freqs"] = d.freqs.astype(np.float64)
    out["j0437_times"] = d.times.astype(np.float64)
    out["j0437_dt"], out["j0437_df"] = d.dt, d.df
    d.calc_sspec(prewhite=False, lamsteps=False, window="hanning",
                 window_frac=0.1)
    out["j0437_sspec"] = d.sspec.astype(np.float32)
    out["j0437_fdop"] = d.fdop.astype(np.float64)
    out["j0437_tdel"] = d.tdel.astype(np.float64)
    d.calc_acf()
    out["j0437_acf"] = d.acf.astype(np.float32)

    # ---- 2b. fit_arc + norm_sspec goldens on the same epoch ---------
    # (dynspec.py:970-1311 Hough η search; :1920-2281 normalisation) —
    # the η-search workhorse pinned behaviourally against upstream, on
    # the standard λ-scaled path (the reference's fit_arc needs
    # self.beta even for lamsteps=False — upstream quirk at :1089)
    d.calc_sspec(prewhite=False, lamsteps=True, window="hanning",
                 window_frac=0.1)
    out["j0437_lamsspec"] = d.lamsspec.astype(np.float32)
    out["j0437_beta"] = np.asarray(d.beta, dtype=np.float64)
    d.fit_arc(plot=False, lamsteps=True, logsteps=False,
              weighted=False, noise_error=True)
    out["j0437_arc_betaeta"] = float(d.betaeta)
    out["j0437_arc_betaetaerr"] = float(d.betaetaerr)
    out["j0437_arc_betaetaerr2"] = float(d.betaetaerr2)
    d.norm_sspec(eta=d.betaeta, lamsteps=True, plot=False,
                 scrunched=True, weighted=True, numsteps=200,
                 maxnormfac=2)
    out["j0437_norm_avg"] = np.asarray(d.normsspecavg,
                                       dtype=np.float64)
    out["j0437_norm_fdop"] = np.asarray(d.normsspec_fdop,
                                        dtype=np.float64)

    # ---- 2c. preprocessing-chain golden on a fresh J0437 load -------
    # (dynspec.py:259-308 trim_edges, :3816-3854 crop_dyn, :3856-3881
    # zap, :3273-3323 refill [linear — skimage absent upstream too
    # falls back], :3325-3379 correct_dyn SVD bandpass) — the exact
    # preprocessing semantics pinned end-to-end as a chain
    d2 = Dynspec(filename=J0437, process=False, verbose=False)
    d2.trim_edges()
    out["prep_trimmed"] = d2.dyn.astype(np.float64)
    d2.crop_dyn(fmin=1270, fmax=1500)
    out["prep_cropped"] = d2.dyn.astype(np.float64)
    out["prep_cropped_freqs"] = np.asarray(d2.freqs, dtype=np.float64)
    d2.zap(sigma=7)
    out["prep_zapped"] = d2.dyn.astype(np.float64)
    d2.refill(method="linear")
    out["prep_refilled"] = d2.dyn.astype(np.float64)
    d2.correct_dyn(svd=True, nmodes=1, frequency=False, time=True)
    out["prep_corrected"] = d2.dyn.astype(np.float64)
    # psrflux writer bytes on the processed state (dynspec.py write
    # loop below :3470 region) — deterministic text, pinnable exactly
    import tempfile

    with tempfile.NamedTemporaryFile("r", suffix=".dynspec") as tf:
        d2.write_file(filename=tf.name, verbose=False)
        out["prep_written"] = np.frombuffer(
            open(tf.name, "rb").read(), dtype=np.uint8)

    # ---- 2d. concatenation, segmenting, prewhitened sspec -----------
    # (__add__ dynspec.py:81-142; cut_dyn :3158-3271 incl. its
    # default-args calc_sspec/calc_acf on every tile; calc_sspec with
    # prewhite/postdark ON — the reference default — :3584 region)
    J0437_B = J0437.replace("074112", "084944")
    e1 = Dynspec(filename=J0437, process=False, verbose=False)
    e2 = Dynspec(filename=J0437_B, process=False, verbose=False)
    cat = e1 + e2
    out["cat_dyn"] = cat.dyn.astype(np.float64)
    out["cat_times"] = np.asarray(cat.times, dtype=np.float64)
    out["cat_mjd"] = float(cat.mjd)
    e1.cut_dyn(tcuts=1, fcuts=1, plot=False)
    out["cut_dyn"] = np.asarray(e1.cutdyn, dtype=np.float64)
    out["cut_sspec"] = np.asarray(e1.cutsspec, dtype=np.float64)
    e1.calc_sspec(prewhite=True, lamsteps=False, window="hanning",
                  window_frac=0.1)
    out["j0437_sspec_prewhite"] = e1.sspec.astype(np.float64)

    # ---- 2e. results-CSV schema (scint_utils.py write_results) ------
    # two appends of a fitted-epoch record: header logic + row text
    import tempfile

    import scintools.scint_utils as su

    class _FakeDyn:
        pass

    fd_rec = _FakeDyn()
    fd_rec.name, fd_rec.mjd, fd_rec.freq = "ep1", 55915.3, 1382.0
    fd_rec.bw, fd_rec.tobs, fd_rec.dt, fd_rec.df = (400.0, 3600.0,
                                                    8.0, 0.78)
    fd_rec.tau, fd_rec.tauerr = 1234.5, 56.7
    fd_rec.dnu, fd_rec.dnuerr = 33.1, 0.34
    fd_rec.scint_param_method = "acf1d"
    fd_rec.betaeta, fd_rec.betaetaerr = 0.139, 0.0007
    with tempfile.TemporaryDirectory() as td:
        fcsv = os.path.join(td, "r.csv")
        su.write_results(fcsv, dyn=fd_rec)
        su.write_results(fcsv, dyn=fd_rec)
        out["results_csv"] = np.frombuffer(
            open(fcsv, "rb").read(), dtype=np.uint8)

    # ---- 3. θ-θ eigenvalue curve on a simulated chunk ---------------
    import astropy.units as u
    import scintools.ththmod as thth

    chunk = np.asarray(sim.spi, dtype=float)[:64, :64]
    chunk = chunk - chunk.mean()
    npad = 1
    pad = np.pad(chunk, ((0, npad * 64), (0, npad * 64)),
                 constant_values=chunk.mean())
    CS = np.fft.fftshift(np.fft.fft2(pad))
    times = np.arange(64) * 2.0 * u.s
    freqs = (1400.0 + np.arange(64) * 0.05) * u.MHz
    fd = thth.fft_axis(times, u.mHz, npad)
    tau = thth.fft_axis(freqs, u.us, npad)
    eta_c = (tau.max().value / (fd.max().value / 4) ** 2)
    etas = np.linspace(0.5 * eta_c, 2.0 * eta_c, 32)
    th_lim = 0.95 * min(np.sqrt(tau.max().value / etas.max()),
                        fd.max().value / 2)
    edges = np.linspace(-th_lim, th_lim, 40) * u.mHz
    eigs = np.array([
        thth.Eval_calc(CS, tau, fd, eta * u.s ** 3, edges)
        for eta in etas])
    out["thth_tau"] = np.asarray(tau.value, dtype=np.float64)
    out["thth_fd"] = np.asarray(fd.value, dtype=np.float64)
    out["thth_etas"] = etas
    out["thth_edges"] = np.asarray(edges.value, dtype=np.float64)
    out["thth_eigs"] = eigs
    out["thth_npad"] = npad

    # ---- 3b. thin-screen goldens: two_curve_map + singular values ---
    # (ththmod.py:1557-1612 two-curve θ-θ; :496-513 largest singular
    # value with the centre cut) — the kernel behind single_search_thin
    # and this repo's make_thin_eval_fn / SPMD thin grid
    arclet_edges = edges[np.abs(edges.value) < 0.6 * th_lim]
    center_cut = float(2 * (edges[1] - edges[0]).value) * u.mHz
    sigs = np.array([
        thth.singularvalue_calc(CS, tau, fd, eta * u.s ** 3, edges,
                                eta * u.s ** 3, arclet_edges,
                                center_cut)
        for eta in etas])
    out["thin_arclet_edges"] = np.asarray(arclet_edges.value,
                                          dtype=np.float64)
    out["thin_center_cut"] = float(center_cut.value)
    out["thin_sigs"] = sigs
    tcm, tcm_e1, tcm_e2 = thth.two_curve_map(
        CS, tau, fd, etas[len(etas) // 2] * u.s ** 3, edges,
        etas[len(etas) // 2] * u.s ** 3, arclet_edges)
    out["thin_map_re"] = np.real(np.asarray(tcm)).astype(np.float64)
    out["thin_map_im"] = np.imag(np.asarray(tcm)).astype(np.float64)
    out["thin_map_e1"] = np.asarray(
        getattr(tcm_e1, "value", tcm_e1), dtype=np.float64)
    out["thin_map_e2"] = np.asarray(
        getattr(tcm_e2, "value", tcm_e2), dtype=np.float64)

    # ---- 3c. retrieval-core goldens: modeler + chisq_calc -----------
    # (ththmod.py:274-368) — the rank-1 phase-retrieval heart; the
    # eigenvector's arbitrary phase cancels in the V·Vᴴ outer product,
    # so model and |recov| are deterministic
    eta_mid_q = etas[len(etas) // 2] * u.s ** 3
    (thth_red_g, thth2_red_g, recov_g, model_g, edges_red_g, w_g,
     V_g) = thth.modeler(CS, tau, fd, eta_mid_q, edges)
    out["modeler_model"] = np.asarray(model_g, dtype=np.float64)
    out["modeler_recov_abs"] = np.abs(
        np.asarray(recov_g)).astype(np.float64)
    out["modeler_w"] = float(np.abs(w_g))
    out["modeler_chisq"] = float(thth.chisq_calc(
        chunk, CS, tau, fd, eta_mid_q, edges, 1.0))

    # ---- 3d. scint_utils numerics: svd_model / interp_nan_2d --------
    # (scint_utils.py:705-767, :769-784). slow_FT is NOT pinnable: the
    # upstream function crashes on any call (scint_utils.py:679 passes
    # ``axis=`` to np.fft.fftshift, whose keyword is ``axes=``)
    import scintools.scint_utils as su

    rng = np.random.default_rng(99)
    small = rng.standard_normal((24, 20)) ** 2
    sv_in = small + 5.0
    sv_arr, sv_model = su.svd_model(sv_in.copy(), nmodes=1)
    out["svdmodel_in"] = sv_in
    out["svdmodel_arr"] = np.asarray(sv_arr, dtype=np.float64)
    out["svdmodel_model"] = np.abs(np.asarray(sv_model)
                                   ).astype(np.float64)
    nan_in = small.copy()
    nan_in[rng.random(small.shape) < 0.15] = np.nan
    out["interpnan_in"] = nan_in
    out["interpnan_out"] = np.asarray(su.interp_nan_2d(nan_in.copy()),
                                      dtype=np.float64)

    # ---- 4. θ-θ map-level goldens: thth_map + rev_map ---------------
    eta_mid = etas[len(etas) // 2]
    tm = thth.thth_map(CS, tau, fd, eta_mid * u.s ** 3, edges)
    out["thth_map_eta"] = eta_mid
    out["thth_map_re"] = np.real(tm).astype(np.float64)
    out["thth_map_im"] = np.imag(tm).astype(np.float64)
    rm = thth.rev_map(tm, tau, fd, eta_mid * u.s ** 3, edges,
                      hermetian=True)
    out["rev_map_re"] = np.real(np.asarray(rm)).astype(np.float64)
    out["rev_map_im"] = np.imag(np.asarray(rm)).astype(np.float64)

    # ---- 5. Rickett-2014 analytic ACF (numpy-only class) ------------
    acf_obj = ss.ACF(psi=30, phasegrad=0.2, theta=0, ar=2, alpha=5 / 3,
                     taumax=4, dnumax=4, nf=25, nt=25, amp=1)
    out["rickett_acf"] = np.asarray(acf_obj.acf, dtype=np.float64)
    out["rickett_tn"] = np.asarray(acf_obj.tn, dtype=np.float64)
    out["rickett_fn"] = np.asarray(acf_obj.fn, dtype=np.float64)

    # ---- 6. Brightness delay-Doppler spectrum (scipy griddata) ------
    br = ss.Brightness(ar=2.0, psi=30, alpha=1.67, thetagx=0.3,
                       thetagy=0.3, thetarx=0.3, thetary=0.3,
                       df=0.05, dt=0.2, dx=0.2, nf=4, nt=16, nx=10,
                       plot=False)
    out["bright_SS"] = np.asarray(br.SS, dtype=np.float64)
    out["bright_fd"] = np.asarray(br.fd, dtype=np.float64)
    out["bright_td"] = np.asarray(br.td, dtype=np.float64)
    out["bright_acf"] = np.asarray(br.acf, dtype=np.float64)

    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    np.savez_compressed(OUT, **out)
    size = os.path.getsize(OUT) / 1e6
    print(f"wrote {OUT} ({size:.2f} MB) with keys: {sorted(out)}")


if __name__ == "__main__":
    main()
