"""Repo tooling package.

Makes ``tools/`` importable so the unified static-analysis framework
can be run as ``python -m tools.jaxlint`` from the repo root. The
standalone scripts in this directory (``lint_*.py``, ``make_golden.py``,
...) still run directly; the four legacy lint scripts are thin shims
over :mod:`tools.jaxlint`.
"""
