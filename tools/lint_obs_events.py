#!/usr/bin/env python
"""Thin shim — obs-event-catalog lint, now rule ``obs-events``
(JL004) in the unified framework (``python -m tools.jaxlint``; rule
catalog: docs/static-analysis.md).

Every ``slog.log_event(...)`` / ``slog.log_failure(...)`` /
``slog.span(...)`` event name in scintools_tpu/ must appear
backtick-quoted in the documented catalog (docs/observability.md +
docs/serving.md) — the event stream is a stable interface, not a
place for drive-by unnamed events (ISSUE 5). Non-literal names carry
``# obs-event-ok: <name>`` (or the unified
``# lint-ok: obs-events: <name>``); the named event is then
catalog-checked like any other.

Legacy API preserved: ``catalog_names(doc_path)``,
``scan_source(src)`` → ``(events, violations)`` (no catalog check),
``scan_tree(root, doc_path)`` → ``[(path, line, message)]``,
``main(sys.argv-style)``.
"""

from __future__ import annotations

import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.jaxlint import shim as _shim  # noqa: E402

MARKER = "obs-event-ok"

_EXEMPT = (os.path.join("utils", "slog.py"),)


def catalog_names(doc_path):
    """Backtick-quoted dotted names in the event-catalog doc(s) —
    ``doc_path`` is one path or an iterable of paths."""
    paths = [doc_path] if isinstance(doc_path, (str, os.PathLike)) \
        else list(doc_path)
    names = set()
    for path in paths:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
        names |= set(re.findall(r"`([a-z0-9_]+(?:\.[a-z0-9_]+)+)`",
                                text))
    return names


def scan_source(src, filename="<src>"):
    """``(events, violations)`` for one source blob: events as
    ``[(lineno, name)]``, violations as ``[(lineno, message)]``."""
    return _shim.obs_collect(src, filename)


def scan_tree(root, doc_path):
    """Violations (unresolvable names + catalog misses) as
    ``[(path, lineno, message)]``."""
    return _shim.obs_scan_tree(root, doc_path)


def main(argv):
    repo = _REPO
    root = argv[1] if len(argv) > 1 else os.path.join(repo,
                                                      "scintools_tpu")
    docs = argv[2:] if len(argv) > 2 else [
        os.path.join(repo, "docs", "observability.md"),
        os.path.join(repo, "docs", "serving.md"),
        os.path.join(repo, "docs", "fleet.md")]
    violations = scan_tree(root, docs)
    for path, ln, msg in violations:
        print(f"{path}:{ln}: {msg}")
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
