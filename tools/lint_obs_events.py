#!/usr/bin/env python
"""Repo lint: every slog event name must be in the documented catalog.

The observability layer (ISSUE 5) is only useful if the event stream
is a stable, documented interface — a dashboard or grep that works
today must not silently miss next month's renamed event. This lint
walks ``scintools_tpu/`` for every ``slog.log_event(...)`` /
``slog.log_failure(...)`` / ``slog.span(...)`` call and checks the
event name against the catalog in ``docs/observability.md``:

- a **literal** first argument (or ``event=`` keyword) is resolved
  directly;
- a plain **variable** is resolved through the enclosing function's
  default for that parameter (the ``def log_summary(self, event=
  "survey.pipeline_timeline")`` pattern);
- anything else (attributes, f-strings, arbitrary expressions) must
  carry an ``# obs-event-ok: <name>`` marker on the call line naming
  the event it emits — the named event is then catalog-checked like
  any other. No marker → violation ("drive-by unnamed event").

A name is "documented" when it appears backtick-quoted in
docs/observability.md. ``span`` names are cataloged by their base
name (the ``.start``/``.end`` suffix convention is documented once).
``utils/slog.py`` itself is exempt (it builds the suffixed names).

Run as a script (exit 1 on violations) or via tests/test_lint.py,
which makes it part of the tier-1 gate.
"""

from __future__ import annotations

import ast
import os
import re
import sys

MARKER = "obs-event-ok"
_CALLS = {"log_event", "log_failure", "span"}
# literal defaults of slog.log_failure's own ``event`` parameter —
# calls that omit the argument emit this name
_IMPLICIT = {"log_failure": "robust.failure"}

_EXEMPT = (os.path.join("utils", "slog.py"),)


def catalog_names(doc_path):
    """Backtick-quoted dotted names in the event-catalog doc(s) —
    ``doc_path`` is one path or an iterable of paths (the catalog
    spans docs/observability.md and docs/serving.md)."""
    paths = [doc_path] if isinstance(doc_path, (str, os.PathLike)) \
        else list(doc_path)
    names = set()
    for path in paths:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
        names |= set(re.findall(r"`([a-z0-9_]+(?:\.[a-z0-9_]+)+)`",
                                text))
    return names


def _is_slog_call(node):
    """``slog.log_event(...)`` / ``slog.span(...)`` — the attribute
    form requires the receiver to be named ``slog`` (``span`` is a
    common method name: ``StageTimeline.span`` records stage spans,
    not events). Bare imported ``log_event``/``log_failure`` names
    are distinctive enough to match directly."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _CALLS \
            and isinstance(f.value, ast.Name) and f.value.id == "slog":
        return f.attr
    if isinstance(f, ast.Name) and f.id in _CALLS and f.id != "span":
        return f.id
    return None


def _event_arg(node):
    """The AST node holding the event name (first positional or the
    ``event=`` keyword), or None when omitted."""
    if node.args:
        return node.args[0]
    for kw in node.keywords:
        if kw.arg == "event":
            return kw.value
    return None


class _Scanner(ast.NodeVisitor):
    """Collects (lineno, event_name) emissions and (lineno, message)
    violations, resolving variable names through enclosing-function
    parameter defaults."""

    def __init__(self, lines):
        self.lines = lines
        self.events = []
        self.violations = []
        self._defaults = [{}]      # stack of {param: literal-default}

    def _fn_defaults(self, node):
        out = {}
        args = node.args
        pos = args.posonlyargs + args.args
        for a, d in zip(pos[len(pos) - len(args.defaults):],
                        args.defaults):
            if isinstance(d, ast.Constant) and isinstance(d.value, str):
                out[a.arg] = d.value
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is not None and isinstance(d, ast.Constant) \
                    and isinstance(d.value, str):
                out[a.arg] = d.value
        return out

    def visit_FunctionDef(self, node):
        self._defaults.append(self._fn_defaults(node))
        self.generic_visit(node)
        self._defaults.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _marker_name(self, lineno):
        line = self.lines[lineno - 1] if lineno <= len(self.lines) \
            else ""
        m = re.search(MARKER + r":\s*([\w.]+)", line)
        return m.group(1) if m else None

    def visit_Call(self, node):
        which = _is_slog_call(node)
        if which is None:
            self.generic_visit(node)
            return
        arg = _event_arg(node)
        name = None
        if arg is None:
            name = _IMPLICIT.get(which)
        elif isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                          str):
            name = arg.value
        elif isinstance(arg, ast.Name):
            for scope in reversed(self._defaults):
                if arg.id in scope:
                    name = scope[arg.id]
                    break
        if name is None:
            name = self._marker_name(node.lineno)
            if name is None:
                self.violations.append((
                    node.lineno,
                    f"slog.{which} with unresolvable event name — use "
                    f"a literal, a literal parameter default, or an "
                    f"'# {MARKER}: <name>' marker"))
                self.generic_visit(node)
                return
        self.events.append((node.lineno, name))
        self.generic_visit(node)


def scan_source(src, filename="<src>"):
    """``(events, violations)`` for one source blob: events as
    ``[(lineno, name)]``, violations as ``[(lineno, message)]``."""
    tree = ast.parse(src, filename=filename)
    sc = _Scanner(src.splitlines())
    sc.visit(tree)
    return sc.events, sc.violations


def scan_tree(root, doc_path):
    """Walk ``root`` for python files; return ``[(path, lineno,
    message)]`` violations — unresolvable event names plus any
    emitted name missing from the catalog at ``doc_path`` (one path
    or several)."""
    catalog = catalog_names(doc_path)
    doc_names = ", ".join(
        os.path.basename(p) for p in
        ([doc_path] if isinstance(doc_path, (str, os.PathLike))
         else doc_path))
    out = []
    for dirpath, _, files in os.walk(root):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root)
            if rel in _EXEMPT:
                continue
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
            events, violations = scan_source(src, filename=path)
            out.extend((path, ln, msg) for ln, msg in violations)
            for ln, name in events:
                if name not in catalog:
                    out.append((
                        path, ln,
                        f"event {name!r} not in the catalog "
                        f"({doc_names}) — document "
                        f"it or rename to a documented event"))
    return out


def main(argv):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = argv[1] if len(argv) > 1 else os.path.join(repo,
                                                      "scintools_tpu")
    docs = argv[2:] if len(argv) > 2 else [
        os.path.join(repo, "docs", "observability.md"),
        os.path.join(repo, "docs", "serving.md")]
    violations = scan_tree(root, docs)
    for path, ln, msg in violations:
        print(f"{path}:{ln}: {msg}")
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
