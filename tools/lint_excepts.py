#!/usr/bin/env python
"""Repo lint: forbid silent exception swallowing in scintools_tpu/.

Two patterns defeat the robustness layer (ISSUE 2) by hiding failures
the survey runner / fallback ladder is supposed to see and report:

- bare ``except:`` — catches SystemExit/KeyboardInterrupt too, so a
  survey cannot even be stopped cleanly;
- ``except Exception:`` (or BaseException) whose body is ONLY
  ``pass``/``...`` — the classic swallow-all that turns a corrupt
  epoch into silent garbage.

Broad handlers that *do something* (log, return a fallback, re-raise)
are allowed — the codebase legitimately guards best-effort paths that
way. A genuinely unavoidable swallow-all can be exempted with a
``broad-except-ok: <reason>`` comment on the ``except`` line.

Run as a script (exit 1 on violations) or via tests/test_lint.py,
which makes it part of the tier-1 gate.
"""

from __future__ import annotations

import ast
import os
import sys

MARKER = "broad-except-ok"

_BROAD = ("Exception", "BaseException")


def _is_broad(node):
    """True for ``except Exception``/``BaseException`` (bound or
    not), including tuple forms containing one."""
    t = node.type
    if t is None:
        return False
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    return any(isinstance(e, ast.Name) and e.id in _BROAD
               for e in elts)


def _swallows(node):
    """True when the handler body is only ``pass``/``...`` — nothing
    logged, nothing returned, nothing re-raised."""
    for stmt in node.body:
        if isinstance(stmt, ast.Pass):
            continue
        if (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)
                and stmt.value.value is Ellipsis):
            continue
        return False
    return True


def scan_source(source, filename="<string>"):
    """Lint one source string → list of ``(line, message)``."""
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [(e.lineno or 0, f"syntax error: {e.msg}")]
    lines = source.splitlines()
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) \
            else ""
        if MARKER in line:
            continue
        if node.type is None:
            out.append((node.lineno,
                        "bare 'except:' (catches KeyboardInterrupt/"
                        "SystemExit; name the exceptions)"))
        elif _is_broad(node) and _swallows(node):
            out.append((node.lineno,
                        "'except Exception: pass' swallows all "
                        "failures silently (log it, narrow it, or "
                        f"mark '{MARKER}: <reason>')"))
    return sorted(out)


def scan_file(path):
    with open(path, encoding="utf-8") as fh:
        return scan_source(fh.read(), filename=path)


def scan_tree(root):
    """Lint every ``*.py`` under ``root`` → list of
    ``(path, line, message)``."""
    out = []
    for base, _, names in sorted(os.walk(root)):
        for name in sorted(names):
            if not name.endswith(".py"):
                continue
            path = os.path.join(base, name)
            out.extend((path, line, msg)
                       for line, msg in scan_file(path))
    return out


def main(argv=None):
    args = list(sys.argv[1:] if argv is None else argv)
    if not args:
        args = [os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "..", "scintools_tpu")]
    violations = []
    for target in args:
        if os.path.isdir(target):
            violations.extend(scan_tree(target))
        else:
            violations.extend((target, line, msg)
                              for line, msg in scan_file(target))
    for path, line, msg in violations:
        print(f"{path}:{line}: {msg}")
    if violations:
        print(f"{len(violations)} exception-hygiene violation(s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
