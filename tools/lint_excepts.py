#!/usr/bin/env python
"""Thin shim — exception-hygiene lint, now rule ``excepts`` (JL001)
in the unified framework (``python -m tools.jaxlint``; rule catalog:
docs/static-analysis.md).

Forbids bare ``except:`` and silent ``except Exception: pass`` in
scintools_tpu/ — the two patterns that defeat the robustness layer
(ISSUE 2) by hiding failures the survey runner / fallback ladder is
supposed to see and report. Escape hatch:
``# broad-except-ok: <reason>`` (or the unified
``# lint-ok: excepts: <reason>``) on the ``except`` line.

Legacy API preserved: ``scan_source`` → ``[(line, message)]``,
``scan_tree`` → ``[(path, line, message)]``, ``main`` exits 1 on
violations.
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.jaxlint import shim as _shim  # noqa: E402

MARKER = "broad-except-ok"
_RULE = "excepts"


def scan_source(source, filename="<string>"):
    return _shim.scan_source(_RULE, source, filename)


def scan_file(path):
    return _shim.scan_file(_RULE, path)


def scan_tree(root):
    return _shim.scan_tree(_RULE, root)


def main(argv=None):
    return _shim.main(
        _RULE, argv,
        lambda: [os.path.join(_REPO, "scintools_tpu")],
        "exception-hygiene")


if __name__ == "__main__":
    sys.exit(main())
