#!/usr/bin/env python
"""Thin shim — import-time-jit lint, now rule ``import-jit`` (JL002)
in the unified framework (``python -m tools.jaxlint``; rule catalog:
docs/static-analysis.md).

Forbids ``jax.jit`` (calls, ``@jax.jit`` decorators,
``partial(jax.jit)``) reachable at module import time — compiled
programs must be built lazily inside cached factories
(fit/acf2d.py:_SOLVER_CACHE, thth/core.py:keyed_jit_cache) so
cold-start and test collection stay fast and cannot hang on a dead
accelerator tunnel (ISSUE 3). The unified rule now scans the whole
package; this shim's CLI keeps the legacy ``scintools_tpu/fit``
default target.

Legacy API preserved: ``scan_source`` → ``[(line, message)]``,
``scan_tree`` → ``[(path, line, message)]``, ``main`` exits 1 on
violations.
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.jaxlint import shim as _shim  # noqa: E402

_RULE = "import-jit"


def scan_source(source, filename="<string>"):
    return _shim.scan_source(_RULE, source, filename)


def scan_file(path):
    return _shim.scan_file(_RULE, path)


def scan_tree(root):
    return _shim.scan_tree(_RULE, root)


def main(argv=None):
    return _shim.main(
        _RULE, argv,
        lambda: [os.path.join(_REPO, "scintools_tpu", "fit")],
        "import-time-jit")


if __name__ == "__main__":
    sys.exit(main())
