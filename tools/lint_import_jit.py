#!/usr/bin/env python
"""Repo lint: forbid import-time ``jax.jit`` in the fit layer.

A ``jax.jit(...)`` (or ``@jax.jit`` decorator / ``partial(jax.jit)``)
executed at module import time forces the jax backend to initialise
before any fit is requested: cold-start of every CLI entry and test
collection pays it, and on the tunneled TPU an import can then HANG on
a dead link (backend.py:force_cpu_platform docstring). The fit layer's
contract is that compiled programs are built lazily inside factory
functions and cached on their static configuration
(fit/acf2d.py:_SOLVER_CACHE, thth/core.py:keyed_jit_cache) — this lint
keeps that true structurally.

Flagged: any call whose callee is named ``jit`` (``jax.jit``,
``get_jax().jit``, bare ``jit``) or ``partial(...jit...)`` reachable
at IMPORT TIME — module body, class bodies, module-level decorator
lists, and function default arguments. Calls inside function bodies
(deferred to call time) are fine.

Run as a script (exit 1 on violations) or via tests/test_lint.py,
which makes it part of the tier-1 gate over ``scintools_tpu/fit/``.
"""

from __future__ import annotations

import ast
import os
import sys


def _is_jit_callee(node):
    """True when a Call's func resolves to a name ending in ``jit``."""
    if isinstance(node, ast.Attribute):
        return node.attr == "jit"
    if isinstance(node, ast.Name):
        return node.id == "jit"
    return False


def _jit_calls(node):
    """Yield Call nodes invoking jit anywhere under ``node``."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        if _is_jit_callee(sub.func):
            yield sub
        elif (isinstance(sub.func, ast.Name)
              and sub.func.id == "partial"
              and any(_is_jit_callee(a) for a in sub.args)):
            yield sub


def _import_time_nodes(body):
    """Yield ``(node, is_decorator)`` pairs for AST nodes whose code
    executes when the module is imported: statements in module/class
    bodies, decorators and argument defaults of (possibly
    nested-in-class) function defs — but NOT function bodies. A BARE
    jit decorator (``@jax.jit`` — an Attribute, not a Call) still
    invokes jit at def time, so decorators are flagged."""
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield from ((d, True) for d in stmt.decorator_list)
            yield from ((d, False) for d in stmt.args.defaults)
            yield from ((d, False) for d in stmt.args.kw_defaults
                        if d is not None)
        elif isinstance(stmt, ast.ClassDef):
            yield from ((d, True) for d in stmt.decorator_list)
            yield from _import_time_nodes(stmt.body)
        else:
            yield stmt, False


def scan_source(source, filename="<string>"):
    """Lint one source string → list of ``(line, message)``."""
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [(e.lineno or 0, f"syntax error: {e.msg}")]
    msg = ("jax.jit at import time (build compiled programs lazily "
           "inside a cached factory — fit/acf2d.py:_SOLVER_CACHE "
           "pattern)")
    out = []
    for node, is_decorator in _import_time_nodes(tree.body):
        if is_decorator and _is_jit_callee(node):
            out.append((node.lineno, msg))     # bare @jax.jit
            continue
        for call in _jit_calls(node):
            out.append((call.lineno, msg))
    return sorted(set(out))


def scan_file(path):
    with open(path, encoding="utf-8") as fh:
        return scan_source(fh.read(), filename=path)


def scan_tree(root):
    out = []
    for base, _, names in sorted(os.walk(root)):
        for name in sorted(names):
            if not name.endswith(".py"):
                continue
            path = os.path.join(base, name)
            out.extend((path, line, msg)
                       for line, msg in scan_file(path))
    return out


def main(argv=None):
    args = list(sys.argv[1:] if argv is None else argv)
    if not args:
        args = [os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "..", "scintools_tpu", "fit")]
    violations = []
    for target in args:
        if os.path.isdir(target):
            violations.extend(scan_tree(target))
        else:
            violations.extend((target, line, msg)
                              for line, msg in scan_file(target))
    for path, line, msg in violations:
        print(f"{path}:{line}: {msg}")
    if violations:
        print(f"{len(violations)} import-time-jit violation(s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
