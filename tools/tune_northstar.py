"""Sweep the north-star jax pipeline's HBM group size on the real
chip (no numpy baseline pass — that's ~4 min of wall per run and
unchanged by the knob). Prints one line per (group, method) with the
best wall time so the default in bench.py:bench_north_star can be set
from data.

Problem AND pipeline come from bench.py (make_north_star_problem /
make_north_star_pipeline), so this times exactly the benched program.

Run (solo on the chip!):  python tools/tune_northstar.py [--size 4096]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=4096)
    ap.add_argument("--groups", default="4,8,16,32")
    ap.add_argument("--methods", default="auto")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU platform (env vars alone are "
                         "not honoured once the axon plugin registers)")
    args = ap.parse_args()

    if args.cpu:
        from scintools_tpu.backend import force_cpu_platform

        force_cpu_platform()

    import jax
    import jax.numpy as jnp

    from bench import make_north_star_problem, make_north_star_pipeline

    print(f"platform: {jax.default_backend()}")
    nf = nt = args.size
    # one extra variant beyond reps: the warm-up call gets its own
    # buffers, so no timed rep ever reuses a bit-identical input (the
    # tunneled TPU serves such repeats from a cache in ~0 ms)
    prob = make_north_star_problem(nf, nt, n_variants=args.reps + 1)
    n_chunks = (nf // prob["cf"]) * (nt // prob["ct"])
    e_j = jnp.asarray(prob["etas"])
    jvariants = [(jnp.asarray(d, dtype=jnp.float32), e_j)
                 for d in prob["dyns"]]

    for method in args.methods.split(","):
        for group in [int(g) for g in args.groups.split(",")]:
            if n_chunks % group:
                print(f"method={method:6s} group={group:3d}  skipped "
                      f"(does not divide the {n_chunks}-chunk grid)")
                continue
            # the EXACT program bench_north_star times
            pipe = make_north_star_pipeline(
                jax, jnp, nf, nt, prob["cf"], prob["ct"], prob["npad"],
                prob["wins"], prob["tau"], prob["fd"], prob["edges"],
                group, method=method)

            # force execution by FETCHING the small eigenvalue output:
            # block_until_ready does not block on the tunneled TPU
            # (bench.py module docstring)
            try:
                t0 = time.perf_counter()
                np.asarray(pipe(*jvariants[-1])[1])      # warm-up only
                compile_s = time.perf_counter() - t0
                best = np.inf
                for r in range(args.reps):
                    a = jvariants[r % (len(jvariants) - 1)]
                    t0 = time.perf_counter()
                    np.asarray(pipe(*a)[1])
                    best = min(best, time.perf_counter() - t0)
            except Exception as e:                       # noqa: BLE001
                # a too-large group OOMs HBM (ResourceExhausted) —
                # report it and keep sweeping instead of losing the
                # groups already measured. NOTE an OOM can wedge the
                # tunnel (observed live 2026-07-31: group 64 OOM'd
                # and even trivial ops hung afterwards) — if the next
                # group stalls, restart the sweep without the fat one.
                # Only swallow genuine runtime/resource failures: a
                # programming error (bad args, shape bug) must not
                # masquerade as an OOM-skipped group.
                runtime_err = "XlaRuntimeError" in type(e).__name__ \
                    or "RESOURCE_EXHAUSTED" in str(e).upper()
                if not runtime_err:
                    raise
                msg = (str(e).splitlines() or [""])[0][:80]
                print(f"method={method:6s} group={group:3d}  FAILED "
                      f"({type(e).__name__}: {msg})")
                continue
            print(f"method={method:6s} group={group:3d}  "
                  f"compile={compile_s:6.1f}s  best={best:7.3f}s  "
                  f"({nf * nt / best:,.0f} px/s)")


if __name__ == "__main__":
    main()
