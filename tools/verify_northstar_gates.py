"""Verify the north-star ACCURACY gates at full 4096² scale.

The bench's CPU fallback runs the north-star pipeline at 1024² to fit
the driver budget, so the <1% η gates (cross-backend and vs the known
synthetic curvature) were only checked at reduced scale off-chip
(VERDICT r3 weak #5). This tool runs BOTH pipelines once at the full
4096² geometry — no repeats, accuracy only, timings reported but not
the point — and prints one JSON line with the gate results. ~30-40
min on the host CPU; run on the chip it also serves as a full-scale
correctness pass before benching.

Run:  python tools/verify_northstar_gates.py [--size 4096] [--cpu]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=4096)
    ap.add_argument("--group", type=int, default=None,
                    help="HBM group size (default: bench's default)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU platform")
    args = ap.parse_args()
    if args.cpu:
        from scintools_tpu.backend import force_cpu_platform

        force_cpu_platform()

    import jax
    import jax.numpy as jnp

    from bench import make_north_star_problem, make_north_star_pipeline
    from scintools_tpu.ops.sspec import secondary_spectrum_power
    from scintools_tpu.thth.core import eval_calc_batch
    from scintools_tpu.thth.search import fit_eig_peak

    nf = nt = args.size
    prob = make_north_star_problem(nf, nt, n_variants=1)
    cf, ct, npad = prob["cf"], prob["ct"], prob["npad"]
    tau, fd = prob["tau"], prob["fd"]
    etas, edges, wins = prob["etas"], prob["edges"], prob["wins"]
    dyn, eta_true = prob["dyns"][0], prob["eta_true"]
    ncf, nct = nf // cf, nt // ct
    n_chunks = ncf * nct
    # largest group ≤ 8 that divides the chunk grid (1 always does),
    # validated BEFORE the multi-minute numpy pass
    group = args.group or next(g for g in (8, 4, 2, 1)
                               if n_chunks % g == 0)
    if n_chunks % group:
        raise SystemExit(f"--group {group} does not divide the "
                         f"{n_chunks}-chunk grid")

    print(f"platform={jax.default_backend()} size={nf} "
          f"chunks={n_chunks} group={group}", file=sys.stderr)

    t0 = time.perf_counter()
    eigs_np = []
    for icf in range(ncf):
        for ict in range(nct):
            chunk = dyn[icf * cf:(icf + 1) * cf,
                        ict * ct:(ict + 1) * ct]
            CS = np.fft.fftshift(np.fft.fft2(
                np.pad(chunk, ((0, npad * cf), (0, npad * ct)),
                       constant_values=chunk.mean())))
            eigs_np.append(eval_calc_batch(CS, tau, fd, etas, edges,
                                           backend="numpy"))
    secondary_spectrum_power(dyn, window_arrays=wins, backend="numpy")
    t_np = time.perf_counter() - t0
    print(f"numpy pass {t_np:.0f}s", file=sys.stderr)

    pipe = make_north_star_pipeline(jax, jnp, nf, nt, cf, ct, npad,
                                    wins, tau, fd, edges, group,
                                    method="auto")
    t0 = time.perf_counter()
    # the fetch is INSIDE the timed region: block_until_ready does
    # not block on the tunneled platform (bench.py module docstring)
    _, eigs_j = pipe(jnp.asarray(dyn, dtype=jnp.float32),
                     jnp.asarray(etas))
    eigs_j = np.asarray(eigs_j)
    t_jax = time.perf_counter() - t0
    print(f"jax pass {t_jax:.0f}s (incl. compile)", file=sys.stderr)

    mismatches, true_errs, xerrs = [], [], []
    for b in range(n_chunks):
        eta_np, sig_np = fit_eig_peak(etas, np.asarray(eigs_np[b]),
                                      fw=0.2)
        eta_jx, _ = fit_eig_peak(etas, eigs_j[b], fw=0.2)
        if np.isfinite(eta_np) and np.isfinite(eta_jx) and eta_np != 0:
            deta = abs(eta_jx - eta_np)
            xerrs.append(deta / abs(eta_np))
            if deta > 0.01 * abs(eta_np) and not (
                    np.isfinite(sig_np) and deta < 0.5 * sig_np):
                mismatches.append(b)
        if np.isfinite(eta_jx):
            true_errs.append(abs(eta_jx - eta_true) / eta_true)
    out = {
        "size": f"{nf}x{nt}", "n_chunks": n_chunks,
        "platform": jax.default_backend(),
        "eta_mismatch_chunks": mismatches,
        "cross_backend_median_pct":
            round(100 * float(np.median(xerrs)), 4) if xerrs else None,
        "cross_backend_max_pct":
            round(100 * float(np.max(xerrs)), 4) if xerrs else None,
        "eta_vs_truth_median_pct":
            round(100 * float(np.median(true_errs)), 4)
            if true_errs else None,
        "eta_vs_truth_max_pct":
            round(100 * float(np.max(true_errs)), 4)
            if true_errs else None,
        "fitted_chunks": len(true_errs),
        "numpy_s": round(t_np, 1), "jax_s_with_compile": round(t_jax, 1),
    }
    print(json.dumps(out))
    ok = (not mismatches and out["eta_vs_truth_median_pct"] is not None
          and out["eta_vs_truth_median_pct"] < 1.0)
    print(f"gates {'OK' if ok else 'FAILED'}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
