#!/usr/bin/env python
"""Thin shim — sync-point lint, now rule ``syncpoints`` (JL003) in
the unified framework (``python -m tools.jaxlint``; rule catalog:
docs/static-analysis.md).

Forbids premature device-sync points (``.block_until_ready``,
``jax.device_get``, eager ``np.asarray``/``float``/``int`` on
in-flight device values) in the library hot paths — the pipelined
survey engine (ISSUE 4) only overlaps host and device work if the
dispatch chain stays async. Deliberate result-consumption boundaries
carry ``# sync-ok: <reason>`` (or the unified
``# lint-ok: syncpoints: <reason>``); utils/profiling.py, whose job
IS fencing, is allowlisted.

Legacy API preserved: ``scan_source`` → ``[(line, message)]``,
``scan_tree`` → ``[(path, line, message)]``, ``_allowlisted``,
``main`` exits 1 on violations.
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.jaxlint import shim as _shim  # noqa: E402

MARKER = "sync-ok:"
_RULE = "syncpoints"

# kept for callers scanning wider roots (legacy contract)
ALLOWLIST_FILES = (
    "utils/profiling.py",
    "scintools_tpu/utils/profiling.py",
)


def _allowlisted(path, root):
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    return any(rel.endswith(a) for a in ALLOWLIST_FILES)


def scan_source(source, filename="<string>"):
    return _shim.scan_source(_RULE, source, filename)


def scan_file(path):
    return _shim.scan_file(_RULE, path)


def scan_tree(root):
    return _shim.scan_tree(_RULE, root)


def main(argv=None):
    def defaults():
        pkg = os.path.join(_REPO, "scintools_tpu")
        return [os.path.join(pkg, d)
                for d in ("ops", "fit", "thth", "parallel")]

    return _shim.main(_RULE, argv, defaults, "sync-point")


if __name__ == "__main__":
    sys.exit(main())
