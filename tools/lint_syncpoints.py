#!/usr/bin/env python
"""Repo lint: forbid premature device-sync points in library hot paths.

The pipelined survey engine (parallel/pipeline.py + robust/runner.py)
only overlaps host work with device compute if the dispatch chain
stays ASYNC: a stray ``.block_until_ready()`` or an eager
``np.asarray(...)`` on an in-flight device value inside a library hot
path fences the whole device queue and silently serialises the
pipeline. This lint keeps the hot paths (``ops/``, ``fit/``,
``thth/``, ``parallel/``) structurally free of such syncs.

Flagged patterns:

1. ANY ``.block_until_ready`` use (method call or
   ``jax.block_until_ready(x)``) — fencing belongs to profiling
   (utils/profiling.py, allowlisted) and bench timing, never library
   code;
2. ``jax.device_get(...)`` / ``x.device_get(...)`` — same;
3. ``np.asarray(f(...))`` / ``float(f(...))`` / ``int(f(...))``
   where the wrapped call FEEDS DEVICE INPUTS (its argument subtree
   contains ``jnp.asarray`` / ``device_put``): dispatch-and-fetch in
   one expression, the classic hidden sync;
4. ``np.asarray(g(...))`` / ``float(g(...))`` where ``g`` is a name
   bound from ``jax.jit(...)`` (or ``*.jit(...)``) in the same
   module — fetching a jitted program's result eagerly.

Escape hatches (the pipelined engine still needs SOME fences):

- a trailing ``# sync-ok: <reason>`` comment on the flagged line
  marks a deliberate result-consumption boundary (e.g. the host API
  edge of ``multi_chunk_search``, where numpy results are the
  contract);
- ``ALLOWLIST_FILES`` exempts whole files whose JOB is fencing
  (utils/profiling.py — outside the scanned dirs but listed for
  completeness and for callers scanning wider roots).

Run as a script (exit 1 on violations) or via tests/test_lint.py,
which makes it part of the tier-1 gate over the four hot-path
packages.
"""

from __future__ import annotations

import ast
import os
import sys

# paths (relative to the scan root, '/'-separated) whose whole file is
# exempt: their job IS synchronisation
ALLOWLIST_FILES = (
    "utils/profiling.py",
    "scintools_tpu/utils/profiling.py",
)

MARKER = "sync-ok:"

# callee names that fetch/force a value to host
_FETCHERS = ("asarray", "device_get", "to_numpy")
_CASTS = ("float", "int")
# attribute names marking an expression as producing device inputs
_DEVICE_FEEDERS = ("device_put",)


def _attr_name(func):
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _is_jnp_asarray(node):
    """True for ``jnp.asarray(...)`` / ``jax.numpy.asarray`` calls —
    the device-staging idiom (vs plain ``np.asarray``)."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)):
        return False
    if node.func.attr not in ("asarray",) + _DEVICE_FEEDERS:
        return False
    base = node.func.value
    base_name = base.id if isinstance(base, ast.Name) else (
        base.attr if isinstance(base, ast.Attribute) else None)
    if node.func.attr in _DEVICE_FEEDERS:
        return True                      # jax.device_put(...)
    return base_name in ("jnp", "jaxnp")


def _feeds_device(call):
    """True when any argument subtree of ``call`` stages device
    inputs (jnp.asarray / device_put)."""
    for arg in list(call.args) + [k.value for k in call.keywords]:
        for sub in ast.walk(arg):
            if _is_jnp_asarray(sub):
                return True
    return False


def _jit_bound_names(tree):
    """Names assigned (anywhere in the module) from a ``*.jit(...)``
    or bare ``jit(...)`` call — simple single-target assignments
    only."""
    names = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        value = node.value
        if isinstance(value, ast.Call) \
                and _attr_name(value.func) == "jit":
            names.add(node.targets[0].id)
    return names


def scan_source(source, filename="<string>"):
    """Lint one source string → list of ``(line, message)``."""
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [(e.lineno or 0, f"syntax error: {e.msg}")]
    lines = source.splitlines()

    def marked(lineno):
        line = lines[lineno - 1] if 0 < lineno <= len(lines) else ""
        return MARKER in line

    jit_names = _jit_bound_names(tree)
    out = []
    for node in ast.walk(tree):
        # rule 1/2: block_until_ready / device_get anywhere
        if isinstance(node, ast.Attribute) \
                and node.attr in ("block_until_ready", "device_get"):
            if not marked(node.lineno):
                out.append((node.lineno,
                            f"`.{node.attr}` fences the device queue "
                            "— library hot paths must stay async "
                            "(profile with utils/profiling.py; mark "
                            "a deliberate consumption boundary with "
                            "`# sync-ok: <reason>`)"))
            continue
        if not isinstance(node, ast.Call):
            continue
        name = _attr_name(node.func)
        if name not in _FETCHERS + _CASTS or not node.args:
            continue
        inner = node.args[0]
        if not isinstance(inner, ast.Call):
            continue
        inner_name = _attr_name(inner.func)
        flagged = None
        if isinstance(inner.func, ast.Name) \
                and inner.func.id in jit_names:
            flagged = (f"fetching the jit-bound `{inner.func.id}` "
                       "result eagerly")
        elif _feeds_device(inner):
            flagged = (f"`{name}({inner_name or '<call>'}(...))` "
                       "dispatches device inputs and fetches the "
                       "result in one expression")
        if flagged and not marked(node.lineno):
            out.append((node.lineno,
                        flagged + " — a hidden sync point; keep the "
                        "value in flight or mark the consumption "
                        "boundary with `# sync-ok: <reason>`"))
    return sorted(set(out))


def scan_file(path):
    with open(path, encoding="utf-8") as fh:
        return scan_source(fh.read(), filename=path)


def _allowlisted(path, root):
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    return any(rel.endswith(a) for a in ALLOWLIST_FILES)


def scan_tree(root):
    out = []
    for base, _, names in sorted(os.walk(root)):
        for name in sorted(names):
            if not name.endswith(".py"):
                continue
            path = os.path.join(base, name)
            if _allowlisted(path, root):
                continue
            out.extend((path, line, msg)
                       for line, msg in scan_file(path))
    return out


def main(argv=None):
    args = list(sys.argv[1:] if argv is None else argv)
    if not args:
        pkg = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "..", "scintools_tpu")
        args = [os.path.join(pkg, d)
                for d in ("ops", "fit", "thth", "parallel")]
    violations = []
    for target in args:
        if os.path.isdir(target):
            violations.extend(scan_tree(target))
        else:
            violations.extend((target, line, msg)
                              for line, msg in scan_file(target))
    for path, line, msg in violations:
        print(f"{path}:{line}: {msg}")
    if violations:
        print(f"{len(violations)} sync-point violation(s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
