"""Minimal FITS primary-HDU image reader (astropy-free).

Supports the simple files the HoloDyn adapter needs
(dynspec.py:4329-4338): primary HDU, BITPIX in {-64,-32,8,16,32,64},
2-D data, optional BSCALE/BZERO. Also a writer for ``save_fits``
(scint_utils.py:260-267).
"""

from __future__ import annotations

import numpy as np

_BITPIX_DTYPE = {
    8: ">u1", 16: ">i2", 32: ">i4", 64: ">i8",
    -32: ">f4", -64: ">f8",
}


def _parse_header(fh):
    header = {}
    while True:
        block = fh.read(2880)
        if len(block) < 2880:
            raise ValueError("truncated FITS header")
        for i in range(0, 2880, 80):
            card = block[i:i + 80].decode("ascii", errors="replace")
            key = card[:8].strip()
            if key == "END":
                return header
            if "=" not in card:
                continue
            val = card[9:].split("/")[0].strip()
            try:
                header[key] = int(val)
            except ValueError:
                try:
                    header[key] = float(val)
                except ValueError:
                    header[key] = val.strip("' ")


def read_fits_image(path, survey=False):
    """Read the primary-HDU image of a simple FITS file → ndarray.

    ``survey=True`` maps any parse failure (truncated header or data,
    unsupported BITPIX, missing NAXIS cards) to the epoch-skipping
    :class:`~scintools_tpu.io.psrflux.MalformedInputError` so a
    survey loop quarantines the file instead of dying on an opaque
    KeyError/ValueError."""
    if survey:
        from .psrflux import MalformedInputError

        try:
            return read_fits_image(path, survey=False)
        except (OSError, ValueError, KeyError, IndexError) as e:
            raise MalformedInputError(path, repr(e)) from e
    with open(path, "rb") as fh:
        header = _parse_header(fh)
        bitpix = header["BITPIX"]
        naxis = header["NAXIS"]
        shape = tuple(header[f"NAXIS{i}"]
                      for i in range(naxis, 0, -1))
        count = int(np.prod(shape))
        dtype = np.dtype(_BITPIX_DTYPE[bitpix])
        data = np.frombuffer(fh.read(count * dtype.itemsize),
                             dtype=dtype).reshape(shape)
        data = data.astype(float)
        bscale = header.get("BSCALE", 1.0)
        bzero = header.get("BZERO", 0.0)
        if bscale != 1.0 or bzero != 0.0:
            data = data * bscale + bzero
        return data


def _card(key, value):
    if isinstance(value, bool):
        v = "T" if value else "F"
        return f"{key:<8}= {v:>20}".ljust(80)
    if isinstance(value, (int, float)):
        return f"{key:<8}= {value:>20}".ljust(80)
    return f"{key:<8}= '{value}'".ljust(80)


def write_fits_image(path, data):
    """Write a 2-D float64 array as a simple FITS primary HDU."""
    data = np.asarray(data, dtype=">f8")
    cards = [
        _card("SIMPLE", True),
        _card("BITPIX", -64),
        _card("NAXIS", data.ndim),
    ]
    for i, n in enumerate(reversed(data.shape), start=1):
        cards.append(_card(f"NAXIS{i}", n))
    cards.append("END".ljust(80))
    header = "".join(cards)
    header += " " * (2880 * int(np.ceil(len(header) / 2880))
                     - len(header))
    with open(path, "wb") as fh:
        fh.write(header.encode("ascii"))
        raw = data.tobytes()
        fh.write(raw)
        pad = 2880 * int(np.ceil(len(raw) / 2880)) - len(raw)
        fh.write(b"\x00" * pad)


def save_fits(filename, dyn):
    """Reference save_fits semantics (scint_utils.py:260-267)."""
    write_fits_image(filename,
                     np.flip(np.transpose(np.flip(dyn.dyn, axis=1)),
                             axis=0))
