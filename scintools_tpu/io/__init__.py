"""I/O: psrflux dynamic spectra, tempo2 .par files, results CSV,
FITS."""

from .psrflux import load_psrflux, write_psrflux
from .parfile import read_par, pars_to_params
from .results import write_results, read_results, float_array_from_dict

__all__ = ["load_psrflux", "write_psrflux", "read_par",
           "pars_to_params", "write_results", "read_results",
           "float_array_from_dict"]
