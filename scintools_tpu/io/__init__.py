"""I/O: psrflux dynamic spectra, tempo2 .par files, results CSV,
FITS. Survey-mode loaders (``survey=True``) raise the epoch-skipping
:class:`MalformedInputError` on corrupt files; result writers are
atomic (temp + rename)."""

from .psrflux import load_psrflux, write_psrflux, MalformedInputError
from .parfile import read_par, pars_to_params
from .results import write_results, read_results, float_array_from_dict

__all__ = ["load_psrflux", "write_psrflux", "MalformedInputError",
           "read_par", "pars_to_params", "write_results",
           "read_results", "float_array_from_dict"]
