"""psrflux-format dynamic-spectrum I/O (host-side).

Format: '#'-comment header containing 'MJD0: <mjd>', then whitespace rows
``isub ichan time(min) freq(MHz) flux [flux_err]``. Parsing semantics
follow ``Dynspec.load_file`` (/root/reference/scintools/dynspec.py:144-230):
reshape to (nsub, nchan), transpose to (nchan, nsub), flip to ascending
frequency, estimate dt/df/bw the same way.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, replace

import numpy as np


class MalformedInputError(ValueError):
    """A dynamic-spectrum input file that cannot be parsed (truncated,
    wrong format, inconsistent shape). In survey mode this is the
    *epoch-skipping* error: the robust runner (robust/runner.py)
    quarantines the epoch with a structured record and moves on, and
    the fallback ladder does not descend tiers for it (no tier can
    fix a corrupt file). Carries the filename and the parse-stage
    detail."""

    def __init__(self, filename, detail):
        self.filename = os.fspath(filename) if filename else None
        self.detail = str(detail)
        super().__init__(
            f"malformed dynamic-spectrum input {self.filename!r}: "
            f"{self.detail} — epoch should be skipped in survey mode")


@dataclass
class RawDynSpec:
    """Plain container for a loaded dynamic spectrum (host numpy arrays).

    dyn has shape (nchan, nsub): frequency × time, ascending frequency.
    times are seconds since obs start; freqs in MHz; dt s; df MHz.
    """

    dyn: np.ndarray
    times: np.ndarray
    freqs: np.ndarray
    mjd: float = 60000.0
    name: str = "dynspec"
    header: list = field(default_factory=list)
    filename: str | None = None

    # derived quantities, populated in __post_init__ if left None
    dt: float | None = None
    df: float | None = None
    bw: float | None = None
    freq: float | None = None
    tobs: float | None = None

    def __post_init__(self):
        self.dyn = np.asarray(self.dyn)
        self.times = np.asarray(self.times, dtype=float)
        self.freqs = np.asarray(self.freqs, dtype=float)
        if self.dt is None:
            self.dt = float(np.mean(np.diff(self.times))) if len(self.times) > 1 else 1.0
        if self.df is None:
            self.df = float(np.mean(np.diff(self.freqs))) if len(self.freqs) > 1 else 1.0
        if self.bw is None:
            self.bw = float(self.freqs[-1] - self.freqs[0] + self.df)
        if self.freq is None:
            self.freq = float(round(np.mean(self.freqs), 2))
        if self.tobs is None:
            self.tobs = float(np.max(self.times) + self.dt - np.min(self.times))

    @property
    def nchan(self):
        return self.dyn.shape[0]

    @property
    def nsub(self):
        return self.dyn.shape[1]

    def copy(self, **kwargs):
        out = replace(self, **kwargs) if kwargs else replace(self)
        out.dyn = np.array(out.dyn)
        return out


def load_psrflux(filename, mjd=None, survey=False):
    """Parse a psrflux file → RawDynSpec. Mirrors dynspec.py:169-218.

    ``survey=True`` converts any parse failure (truncated file, wrong
    column count, inconsistent nsub×nchan shape, non-numeric rows)
    into :class:`MalformedInputError` — the clear, epoch-skipping
    error the robust survey runner quarantines on — instead of
    whatever numpy/reshape exception the corruption happens to
    trigger. The default (non-survey) path keeps raw exceptions for
    interactive debugging."""
    if survey:
        try:
            return load_psrflux(filename, mjd=mjd, survey=False)
        except MalformedInputError:
            raise
        except (OSError, ValueError, IndexError, KeyError) as e:
            raise MalformedInputError(filename, repr(e)) from e
    head = []
    file_mjd = None
    with open(filename, "r") as fh:
        for line in fh:
            if line.startswith("#"):
                headline = line[1:].strip()
                head.append(headline)
                parts = headline.split()
                if parts and parts[0] == "MJD0:" and file_mjd is None:
                    file_mjd = float(parts[1])
    raw = np.loadtxt(filename).transpose()
    times = np.unique(raw[2] * 60)  # minutes → seconds, leading edges
    if mjd is not None:
        mjd0 = mjd
    else:
        mjd0 = (file_mjd if file_mjd is not None else 60000.0) + times[0] / 86400
    times = times - times[0]
    freqs = raw[3]
    fluxes = raw[4]
    nchan = int(np.max(raw[1])) + 1
    bw = freqs[-1] - freqs[0]
    df = round(bw / nchan, 5)
    bw = round(bw + df, 2)
    nsub = int(np.max(raw[0])) + 1
    dt = float(np.mean(np.diff(times)))
    tobs = float(np.max(times) + dt)

    freqs = np.unique(freqs)
    fluxes = fluxes.reshape([nsub, nchan]).transpose()
    if df < 0:  # stored descending: flip to ascending frequency
        df, bw = -df, -bw
        fluxes = np.flip(fluxes, 0)

    return RawDynSpec(
        dyn=fluxes, times=times, freqs=freqs, mjd=float(mjd0),
        name=os.path.basename(filename), header=head, filename=filename,
        dt=dt, df=df, bw=float(bw), freq=float(round(np.mean(freqs), 2)),
        tobs=tobs,
    )


def write_psrflux(ds, filename, note=None):
    """Write RawDynSpec (or any object with the same attrs) to a psrflux
    file, with provenance header (dynspec.py:330-376 semantics).
    Written atomically (temp + rename) so an interrupted survey never
    leaves a half-epoch file that poisons a later :func:`load_psrflux`.
    """
    # header text matches the reference byte-for-byte
    # (tests/test_golden_reference.py pins the written file), so
    # files produced here are indistinguishable downstream
    lines = ["# Scintools-modified dynamic spectrum "
             "in psrflux format",
             "# Created using write_file method in Dynspec class"]
    if note is not None:
        lines.append(f"# Note: {note}")
    lines.append(f"# MJD0: {ds.mjd}")
    lines.append("# Original header begins below:")
    has_isub = False
    for line in ds.header:
        lines.append(f"# {line} ")
        if "isub" in line:
            has_isub = True
    if not has_isub:
        lines.append("# isub ichan time(min) freq(MHz) flux flux_err")
    for i, ti in enumerate(np.asarray(ds.times) / 60):
        for j, fi in enumerate(ds.freqs):
            lines.append(f"{i} {j} {ti} {fi} {ds.dyn[j, i]} {0}")
    from ..parallel.checkpoint import atomic_write_bytes

    atomic_write_bytes(filename, ("\n".join(lines) + "\n").encode())


def concatenate_time(ds1, ds2):
    """Time-concatenate two dynamic spectra, zero-filling the MJD gap
    (Dynspec.__add__ semantics, dynspec.py:81-142)."""
    timegap = round((ds2.mjd - ds1.mjd) * 86400 - ds1.tobs, 1)
    extratimes = np.arange(0, timegap, ds1.dt)
    nextra = 0 if timegap < ds1.dt else len(extratimes)
    gap = np.zeros([ds1.dyn.shape[0], nextra])
    nsub = ds1.nsub + nextra + ds2.nsub
    tobs = ds1.tobs + timegap + ds2.tobs
    times = np.linspace(0, tobs, nsub)
    newdyn = np.concatenate((ds1.dyn, gap, ds2.dyn), axis=1)
    name = (ds1.name.split(".")[0] + "+" + ds2.name.split(".")[0]
            + ".dynspec")
    return RawDynSpec(
        dyn=newdyn, times=times, freqs=ds1.freqs,
        mjd=min(ds1.mjd, ds2.mjd), name=name,
        header=list(ds1.header) + list(ds2.header),
        dt=ds1.dt, df=ds1.df, bw=ds1.bw, freq=ds1.freq, tobs=tobs,
    )
