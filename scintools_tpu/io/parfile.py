"""tempo2 .par pulsar-parameter file reader (host-side).

Semantics follow ``read_par`` (/root/reference/scintools/scint_utils.py:
398-450): each parameter gets a value, optional ``<name>_ERR`` and a
``<name>_TYPE`` ('d' int, 'f' float, 'e' scientific, 's' string).
"""

from __future__ import annotations

from decimal import Decimal, InvalidOperation

import numpy as np

IGNORE = ['DMMODEL', 'DMOFF', 'DM_', 'CM_', 'CONSTRAIN', 'JUMP', 'NITS',
          'NTOA', 'CORRECT_TROPOSPHERE', 'PLANET_SHAPIRO', 'DILATEFREQ',
          'TIMEEPH', 'MODE', 'TZRMJD', 'TZRSITE', 'TZRFRQ', 'EPHVER',
          'T2CMETHOD']


def read_par(parfile):
    """Read a .par file → dict of parameter names/values."""
    par = {}
    with open(parfile, "r") as fh:
        for line in fh.readlines():
            err = None
            p_type = None
            sline = line.split()
            if (len(sline) == 0 or line[0] == "#" or line[0:2] == "C "
                    or sline[0] in IGNORE):
                continue
            param = sline[0]
            if param == "E":
                param = "ECC"
            val = sline[1]
            if len(sline) == 3 and sline[2] not in ['0', '1']:
                err = sline[2].replace('D', 'E')
            elif len(sline) == 4:
                err = sline[3].replace('D', 'E')
            try:
                val = int(val)
                p_type = 'd'
            except ValueError:
                try:
                    val = float(Decimal(val.replace('D', 'E')))
                    if 'e' in sline[1] or 'E' in sline[1].replace('D', 'E'):
                        p_type = 'e'
                    else:
                        p_type = 'f'
                except InvalidOperation:
                    p_type = 's'
            par[param] = val
            if err:
                par[param + "_ERR"] = float(err)
            if p_type:
                par[param + "_TYPE"] = p_type
    return par


def _hms_to_rad(s):
    """'hh:mm:ss.s' hourangle string → radians."""
    parts = [float(p) for p in str(s).split(":")]
    while len(parts) < 3:
        parts.append(0.0)
    h, m, sec = parts[:3]
    sign = -1.0 if str(s).strip().startswith("-") else 1.0
    return sign * (abs(h) + m / 60 + sec / 3600) * np.pi / 12


def _dms_to_rad(s):
    """'dd:mm:ss.s' degree string → radians."""
    parts = [float(p) for p in str(s).split(":")]
    while len(parts) < 3:
        parts.append(0.0)
    d, m, sec = parts[:3]
    sign = -1.0 if str(s).strip().startswith("-") else 1.0
    return sign * (abs(d) + m / 60 + sec / 3600) * np.pi / 180


def pars_to_params(pars, params=None):
    """Convert a read_par() dict to a fitting Parameters object
    (scint_utils.py:480-506 semantics; RAJ/DECJ → radians).

    Parameters are added with vary=False by default.
    """
    from ..fit.parameters import Parameters

    if params is None:
        params = Parameters()
    for key, value in pars.items():
        if key in ("RAJ", "RA"):
            params.add("RAJ", value=_hms_to_rad(pars["RAJ"]), vary=False)
            params.add("DECJ", value=_dms_to_rad(pars["DECJ"]), vary=False)
            continue
        if isinstance(value, str):
            continue
        params.add(key, value=value, vary=False)
    return params
