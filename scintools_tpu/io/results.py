"""Results CSV I/O — drop-in compatible with the reference schema
(scint_utils.py:103-218)."""

from __future__ import annotations

import csv
import os

import numpy as np

# (attribute, columns) in the reference's exact order
# (scint_utils.py:113-193)
_FIELDS = [
    ("tau", ["tau", "tauerr"]),
    ("dnu", ["dnu", "dnuerr"]),
    ("fse_tau", ["fse_tau", "fse_dnu"]),
    ("scint_param_method", ["scint_param_method"]),
    ("dnu_est", ["dnu_est"]),
    ("nscint", ["nscint"]),
    ("ar", ["ar", "arerr"]),
    ("acf_tilt", ["acf_tilt", "acf_tilt_err"]),
    ("fse_tilt", ["fse_tilt"]),
    ("phasegrad", ["phasegrad", "phasegraderr"]),
    ("fse_phasegrad", ["fse_phasegrad"]),
    ("theta", ["theta", "thetaerr"]),
    ("psi", ["psi", "psierr"]),
    ("eta", ["eta", "etaerr"]),
    ("betaeta", ["betaeta", "betaetaerr"]),
    ("eta_left", ["eta_left", "etaerr_left"]),
    ("betaeta_left", ["betaeta_left", "betaetaerr_left"]),
    ("eta_right", ["eta_right", "etaerr_right"]),
    ("betaeta_right", ["betaeta_right", "betaetaerr_right"]),
    ("norm_delmax", ["delmax"]),
]

_ATTR_FOR_COL = {
    "tauerr": "tauerr", "dnuerr": "dnuerr", "fse_dnu": "fse_dnu",
    "arerr": "arerr", "acf_tilt_err": "acf_tilt_err",
    "phasegraderr": "phasegraderr", "thetaerr": "thetaerr",
    "psierr": "psierr", "etaerr": "etaerr", "betaetaerr": "betaetaerr",
    "etaerr_left": "etaerr_left", "betaetaerr_left": "betaetaerr_left",
    "etaerr_right": "etaerr_right",
    "betaetaerr_right": "betaetaerr_right", "delmax": "norm_delmax",
}


def write_results(filename, dyn=None):
    """Append a results row, writing the header if the file is new
    (scint_utils.py:103-202).

    The write is ATOMIC (full-content temp + rename,
    parallel/checkpoint.py:atomic_write_bytes): a survey killed
    mid-append leaves either the previous intact CSV or the new one,
    never a torn row that poisons every later ``read_results`` of the
    accumulated survey output."""
    header = "name,mjd,freq,bw,tobs,dt,df"
    row = (f"{dyn.name},{dyn.mjd},{dyn.freq},{dyn.bw},{dyn.tobs},"
           f"{dyn.dt},{dyn.df}")
    for attr, cols in _FIELDS:
        if not hasattr(dyn, attr):
            continue
        header += "," + ",".join(cols)
        vals = []
        for col in cols:
            a = _ATTR_FOR_COL.get(col, col)
            vals.append(str(getattr(dyn, a, None)))
        row += "," + ",".join(vals)
    from ..parallel.checkpoint import atomic_write_bytes

    existing = b""
    if os.path.exists(filename) and os.stat(filename).st_size > 0:
        with open(filename, "rb") as fh:
            existing = fh.read()
    if not existing:
        existing = (header + "\n").encode()
    atomic_write_bytes(filename, existing + (row + "\n").encode())


def read_results(filename):
    """CSV → dict of lists (scint_utils.py:205-218)."""
    with open(filename, "r") as fh:
        data = list(csv.reader(fh, delimiter=","))
    keys = data[0]
    out = {k: [] for k in keys}
    for row in data[1:]:
        for i, val in enumerate(row):
            out[keys[i]].append(val)
    return out


def float_array_from_dict(dictionary, key):
    """dict column → float array, 'None' → nan
    (scint_utils.py:245-257)."""
    arr = ["nan" if v == "None" else v for v in dictionary[key]]
    return np.array(list(map(float, arr))).squeeze()


def read_dynlist(file_path):
    """List of dynspec filenames from a text file
    (scint_utils.py:94-100)."""
    with open(file_path) as fh:
        return fh.read().splitlines()
