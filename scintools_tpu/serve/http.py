"""Live telemetry HTTP listener for the streaming daemon — and, since
ISSUE 13, the shared serving machinery of the pod-level telemetry
plane (obs/plane.py).

The PR-5 observability surfaces were in-process (metrics registry)
or write-at-exit (run_report.json, trace files). A deployable
service is scraped and probed from OUTSIDE while it runs; this
module is that edge — a stdlib :class:`ThreadingHTTPServer` (no new
dependencies) whose request routing is a **handler table**
(:func:`daemon_routes`) rather than an if-chain, so the daemon
surface and the fleet plane surface share one dispatch path and
cannot drift: both get the same ``/`` index, the same 404-with-path-
listing, the same per-path request counter + latency histogram, and
the same crash-to-500 containment.

The daemon table:

==========  =====================================================
path        answer
==========  =====================================================
/           index: the paths this surface serves
/metrics    Prometheus text exposition of the process registry
            (``Content-Type: text/plain; version=0.0.4`` — what a
            Prometheus scraper requires), uptime gauge refreshed
            per scrape
/healthz    liveness — 200 when the ingest loop and the spool
            watcher are alive and recently ticking, 503 otherwise
            (an autoscaler restarts on sustained 503)
/readyz     readiness — 200 only when additionally the device
            program is WARM (a compile-stall on the first routed
            epoch is not "ready") and the daemon is not stopping
/report     the live RunReport snapshot (schema v1, identical to
            the end-of-run ``run_report.json``, plus
            ``in_progress``/latency/backlog extras)
/state      per-epoch status map: queued / in_flight / ok /
            quarantined / resumed / duplicate, with latency and
            backlog
/ledger     the program cost ledger snapshot (obs/ledger.py):
            per-(site, platform, shape, formulation) compile
            totals and steady-time stats
==========  =====================================================

A route is ``path -> fn(service) -> (status, body, content_type)``;
``content_type=None`` means "JSON-encode body". Handler threads only
READ service state through the snapshot methods (every one takes the
service's lock or tolerates racy scalar reads) and never touch
in-flight device values — no host syncs, no stalls on the pipeline
(the bench's scrape-under-load config pins the overhead).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..obs import metrics as _metrics
from ..utils import slog


def metrics_route(service):
    """``/metrics``: the process registry, uptime freshened per
    scrape."""
    _metrics.touch_process_metrics()
    return (200, _metrics.REGISTRY.to_prometheus(),
            _metrics.PROMETHEUS_CONTENT_TYPE)


def probe_route(method_name):
    """A liveness/readiness probe route: the service method returns a
    detail dict whose ``ok`` decides 200 vs 503."""

    def route(service):
        detail = getattr(service, method_name)()
        return (200 if detail.get("ok") else 503), detail, None

    return route


def snapshot_route(method_name):
    """A JSON snapshot route bound to one service method."""

    def route(service):
        return 200, getattr(service, method_name)(), None

    return route


def ledger_route(service):
    """``/ledger``: the program cost ledger snapshot (ISSUE 20). A
    view object may supply its own ``ledger_snapshot()``; otherwise
    the process-wide ledger answers — the daemon's ledger IS the
    process ledger."""
    fn = getattr(service, "ledger_snapshot", None)
    if fn is not None:
        return 200, fn(), None
    from ..obs import ledger as _ledger

    return 200, _ledger.snapshot(), None


def daemon_routes():
    """The streaming daemon's handler table (the docs/serving.md
    endpoint table is this dict, rendered)."""
    return {
        "/metrics": metrics_route,
        "/healthz": probe_route("healthy"),
        "/readyz": probe_route("ready"),
        "/report": snapshot_route("report_snapshot"),
        "/state": snapshot_route("state_snapshot"),
        "/ledger": ledger_route,
    }


class TelemetryServer:
    """Owns the listener socket (bound at construction, so an
    ephemeral ``port=0`` is known before the daemon starts) and the
    serving thread. ``start()``/``close()`` are idempotent.

    ``routes`` defaults to the daemon table; the telemetry plane
    passes its own table plus a distinct ``metric_prefix`` so the two
    surfaces' request counters stay separable."""

    def __init__(self, service, host="127.0.0.1", port=0, routes=None,
                 metric_prefix="serve_http", thread_name="serve-http"):
        self.service = service
        self.routes = dict(routes) if routes is not None \
            else daemon_routes()
        handler = _make_handler(service, self.routes, metric_prefix)
        self._httpd = ThreadingHTTPServer((host, int(port)), handler)
        self._httpd.daemon_threads = True
        self.host = self._httpd.server_address[0]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            kwargs={"poll_interval": 0.1}, name=thread_name)
        self._started = False

    def start(self):
        if not self._started:
            self._started = True
            self._thread.start()
            slog.log_event("serve.http", state="started",
                           host=self.host, port=self.port,
                           paths=sorted(self.routes))
        return self

    def close(self):
        if self._started:
            self._httpd.shutdown()
            self._thread.join(timeout=10)
            slog.log_event("serve.http", state="stopped",
                           port=self.port)
        self._httpd.server_close()
        self._started = False

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"


def _bounded_path(path, routes):
    """The bounded ``path`` metric label for a request: known route
    paths (and ``/``) keep their own label, anything else folds into
    ``"other"`` — request paths are caller-controlled strings, and
    before this bound every scanner probing random URLs minted a new
    label child (JL005 unbounded-cardinality)."""
    return path if path == "/" or path in routes else "other"


def _make_handler(service, routes, metric_prefix):
    """A request-handler class bound to one service instance and its
    route table."""

    class Handler(BaseHTTPRequestHandler):
        # access logs belong in metrics, not stderr noise
        def log_message(self, fmt, *args):
            return

        def _send(self, code, body, content_type="application/json"):
            data = body if isinstance(body, bytes) else body.encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _send_json(self, code, obj):
            self._send(code, json.dumps(obj, indent=1))

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
            path = self.path.split("?", 1)[0].rstrip("/") or "/"
            t0 = time.perf_counter()
            # the prefix keeps daemon vs plane request accounting
            # separable under one handler implementation
            # lint-ok: metric-hygiene: serve_http_requests_total plane_http_requests_total
            _metrics.counter(
                f"{metric_prefix}_requests_total",
                help="telemetry requests served",
            ).labels(path=_bounded_path(path, routes)).inc()
            try:
                route = routes.get(path)
                if path == "/":
                    self._send_json(200, {
                        "service": type(service).__name__,
                        "paths": ["/"] + sorted(routes)})
                elif route is None:
                    self._send_json(404, {
                        "error": f"unknown path {path!r}",
                        "paths": ["/"] + sorted(routes)})
                else:
                    code, body, ctype = route(service)
                    if ctype is None:
                        self._send_json(code, body)
                    else:
                        self._send(code, body, ctype)
            except Exception as e:  # noqa: BLE001 — a handler crash
                # must answer 500 and never take the serving thread
                # (or the daemon) down with it
                slog.log_failure("serve.http_error", stage=path,
                                 error=e)
                try:
                    self._send_json(500, {"error": repr(e)[:300]})
                except OSError:
                    pass  # broad-except-ok: client hung up mid-error
            finally:
                # lint-ok: metric-hygiene: serve_http_request_seconds plane_http_request_seconds
                _metrics.histogram(
                    f"{metric_prefix}_request_seconds",
                    help="telemetry request handling wall time",
                ).labels(path=_bounded_path(path, routes)).observe(
                    time.perf_counter() - t0)

    return Handler
