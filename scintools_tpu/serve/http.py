"""Live telemetry HTTP listener for the streaming daemon.

The PR-5 observability surfaces were in-process (metrics registry)
or write-at-exit (run_report.json, trace files). A deployable
service is scraped and probed from OUTSIDE while it runs; this
module is that edge — a stdlib :class:`ThreadingHTTPServer` (no new
dependencies) serving:

==========  =====================================================
path        answer
==========  =====================================================
/metrics    Prometheus text exposition of the process registry
            (``Content-Type: text/plain; version=0.0.4`` — what a
            Prometheus scraper requires), uptime gauge refreshed
            per scrape
/healthz    liveness — 200 when the ingest loop and the spool
            watcher are alive and recently ticking, 503 otherwise
            (an autoscaler restarts on sustained 503)
/readyz     readiness — 200 only when additionally the device
            program is WARM (a compile-stall on the first routed
            epoch is not "ready") and the daemon is not stopping
/report     the live RunReport snapshot (schema v1, identical to
            the end-of-run ``run_report.json``, plus
            ``in_progress``/latency/backlog extras)
/state      per-epoch status map: queued / in_flight / ok /
            quarantined / resumed / duplicate, with latency and
            backlog
==========  =====================================================

Handler threads only READ daemon state through the snapshot methods
(every one takes the daemon's lock or tolerates racy scalar reads)
and never touch in-flight device values — no host syncs, no stalls
on the pipeline (the bench's scrape-under-load config pins the
overhead).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..obs import metrics as _metrics
from ..utils import slog


class TelemetryServer:
    """Owns the listener socket (bound at construction, so an
    ephemeral ``port=0`` is known before the daemon starts) and the
    serving thread. ``start()``/``close()`` are idempotent."""

    def __init__(self, service, host="127.0.0.1", port=0):
        self.service = service
        handler = _make_handler(service)
        self._httpd = ThreadingHTTPServer((host, int(port)), handler)
        self._httpd.daemon_threads = True
        self.host = self._httpd.server_address[0]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            kwargs={"poll_interval": 0.1}, name="serve-http")
        self._started = False

    def start(self):
        if not self._started:
            self._started = True
            self._thread.start()
            slog.log_event("serve.http", state="started",
                           host=self.host, port=self.port)
        return self

    def close(self):
        if self._started:
            self._httpd.shutdown()
            self._thread.join(timeout=10)
            slog.log_event("serve.http", state="stopped",
                           port=self.port)
        self._httpd.server_close()
        self._started = False

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"


def _make_handler(service):
    """A request-handler class bound to one daemon instance."""

    class Handler(BaseHTTPRequestHandler):
        # access logs belong in metrics, not stderr noise
        def log_message(self, fmt, *args):
            return

        def _send(self, code, body, content_type="application/json"):
            data = body if isinstance(body, bytes) else body.encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _send_json(self, code, obj):
            self._send(code, json.dumps(obj, indent=1))

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
            path = self.path.split("?", 1)[0].rstrip("/") or "/"
            _metrics.counter(
                "serve_http_requests_total",
                help="telemetry requests served",
            ).labels(path=path).inc()
            try:
                if path == "/metrics":
                    _metrics.touch_process_metrics()
                    self._send(200, _metrics.REGISTRY.to_prometheus(),
                               _metrics.PROMETHEUS_CONTENT_TYPE)
                elif path == "/healthz":
                    detail = service.healthy()
                    self._send_json(200 if detail["ok"] else 503,
                                    detail)
                elif path == "/readyz":
                    detail = service.ready()
                    self._send_json(200 if detail["ok"] else 503,
                                    detail)
                elif path == "/report":
                    self._send_json(200, service.report_snapshot())
                elif path == "/state":
                    self._send_json(200, service.state_snapshot())
                else:
                    self._send_json(404, {
                        "error": f"unknown path {path!r}",
                        "paths": ["/metrics", "/healthz", "/readyz",
                                  "/report", "/state"]})
            except Exception as e:  # noqa: BLE001 — a handler crash
                # must answer 500 and never take the serving thread
                # (or the daemon) down with it
                slog.log_failure("serve.http_error", stage=path,
                                 error=e)
                try:
                    self._send_json(500, {"error": repr(e)[:300]})
                except OSError:
                    pass  # broad-except-ok: client hung up mid-error

    return Handler
