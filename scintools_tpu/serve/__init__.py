"""Survey-as-a-service: streaming ingest daemon + live telemetry
(ISSUE 6 tentpole; ROADMAP item 2).

The batch stack (robust/runner.py + parallel/pipeline.py) wants the
full epoch list up front and reports at exit. This package turns the
same engine into a deployable long-lived process:

- :mod:`~scintools_tpu.serve.watch` — epoch sources: a torn-file-safe
  polling :class:`SpoolWatcher` over a spool directory, and an
  in-process :class:`QueueSource` for tests/embedding;
- :mod:`~scintools_tpu.serve.daemon` — :class:`SurveyService`, the
  streaming ingest loop: bounded-latency PrefetchLoader →
  dispatch-ahead processing, content-hash dedupe, per-epoch
  ingest→dispatch→fence→publish latency accounting;
- :mod:`~scintools_tpu.serve.store` — :class:`ResultsStore`, the
  append-only atomically-readable results store on the PR-2
  CRC-JSONL journal (SIGKILL + restart resumes with no duplicate
  publishes);
- :mod:`~scintools_tpu.serve.http` — :class:`TelemetryServer`, the
  stdlib HTTP listener serving ``/metrics`` (Prometheus), ``/healthz``
  / ``/readyz`` probes, the live ``/report`` RunReport snapshot, and
  per-epoch ``/state``.

- :mod:`~scintools_tpu.serve.lanes` — the batched service mode's
  host half (ISSUE 16): :class:`AdaptiveBatchController` (backlog →
  batch-size target, track-up / decay-down), :class:`TenantPolicy`
  (admission control + fair-share lane quotas), and
  :class:`LaneAssembler` (per-geometry, tenant-round-robin group
  formation with power-of-two bucket padding).

``dynspec.serve_psrflux_survey`` / ``dynspec.serve_fits_survey`` are
the file-format entry points; docs/serving.md is the operator
walkthrough.
"""

from .daemon import SurveyService  # noqa: F401
from .http import TelemetryServer  # noqa: F401
from .lanes import (AdaptiveBatchController, LaneAssembler,  # noqa: F401
                    TenantPolicy)
from .store import ResultsStore, content_hash  # noqa: F401
from .watch import ArrivedEpoch, QueueSource, SpoolWatcher  # noqa: F401
