"""Append-only, atomically-readable results store for the daemon.

The streaming daemon publishes one record per completed epoch. The
storage is the PR-2 CRC-JSONL epoch journal
(:class:`~scintools_tpu.parallel.checkpoint.EpochJournal`): every
line is fsynced (directly or through the group-commit
:class:`~scintools_tpu.parallel.pipeline.AsyncJournalWriter`) and
CRC-stamped, so

- a concurrent reader — or a resume after SIGKILL — sees only
  complete, verified records (``EpochJournal.valid_lines`` skips a
  torn tail), which is the store's **atomic read API**;
- a restarted daemon takes journaled epochs verbatim and publishes
  nothing twice (the PR-2 resume contract, unchanged);
- two stores are **byte-consistent** when their valid lines match —
  the serving acceptance gate compares a SIGKILL-resumed store
  against an uninterrupted run's store line for line.

On top of the journal the store keeps the **content-hash index** the
stream dedupe needs: each published record carries the epoch's
payload ``sha`` (hex digest stamped by the spool watcher), so a
duplicate file arriving under a new name — today or after a
restart — is recognised and dropped instead of republished.
"""

from __future__ import annotations

import hashlib
import os
import threading

from ..parallel.checkpoint import EpochJournal


def content_hash(data):
    """Canonical content hash of an epoch payload (hex sha256).
    Bytes are hashed directly; anything else is hashed via its
    ``repr`` (good enough for the in-process test source — the spool
    watcher always hashes file bytes)."""
    if not isinstance(data, (bytes, bytearray)):
        data = repr(data).encode()
    return hashlib.sha256(data).hexdigest()


class ResultsStore:
    """The daemon's published-results surface over one
    :class:`EpochJournal`.

    ``records()``/``valid_lines()`` are the atomic read API (only
    CRC-intact lines); ``known_content(sha)`` answers the dedupe
    question; ``note_published(key, sha)`` keeps the in-memory hash
    index current as the daemon records fresh epochs (the journal
    line itself carries the ``sha`` field, so the index rebuilds from
    disk on restart).
    """

    def __init__(self, workdir, name="results.jsonl"):
        os.makedirs(os.fspath(workdir), exist_ok=True)
        self.journal = EpochJournal(os.path.join(os.fspath(workdir),
                                                 name))
        self._lock = threading.Lock()
        self._hash_to_key = {}
        for key, rec in self.journal.records().items():
            sha = rec.get("sha")
            if sha:
                self._hash_to_key[sha] = key

    # ---- read side (atomic) -----------------------------------------
    def records(self):
        """``{epoch_id: record}`` of every intact published line."""
        return self.journal.records()

    def valid_lines(self):
        """Intact raw lines in publish order (byte-consistency
        view)."""
        return self.journal.valid_lines()

    def __len__(self):
        return len(self.records())

    # ---- dedupe index -----------------------------------------------
    def known_content(self, sha):
        """Epoch key already published with this content hash, or
        None. ``sha=None`` (no hash available) never matches."""
        if not sha:
            return None
        with self._lock:
            return self._hash_to_key.get(sha)

    def note_published(self, key, sha=None):
        """Record that ``key`` (with payload hash ``sha``) is now
        published, keeping the dedupe index current without a disk
        re-scan."""
        if sha:
            with self._lock:
                self._hash_to_key.setdefault(sha, str(key))
