"""Epoch sources for the streaming daemon: spool directory + queue.

A long-lived survey service does not get its epoch list up front —
epochs ARRIVE: a telescope backend (or an rsync from one) drops
psrflux/FITS files into a spool directory, a test pushes payloads
into an in-process queue. Both are the same small interface the
daemon (serve/daemon.py) consumes:

- ``get(timeout)`` → the next :class:`ArrivedEpoch` or None (nothing
  arrived within the deadline — the daemon uses the idle tick to
  drain its dispatch window, so ingest→publish latency stays bounded
  while the spool is quiet);
- ``backlog()`` → epochs arrived but not yet taken;
- ``alive()`` / ``last_activity()`` → liveness wiring for the
  ``/healthz`` probe;
- ``close()`` → stop producing.

:class:`SpoolWatcher` hardens the filesystem edge against the stream
fault classes (robust/faults.py injects them in tests):

- **torn files** — a file still being written (size changing between
  polls, or empty) is NOT admitted; it is picked up on a later poll
  once its size has been stable for ``settle_polls`` consecutive
  polls. Writers that rename-into-place (io/psrflux.py's atomic
  ``write_psrflux``) are admitted on first sight of the rename.
- **duplicates** — every admitted file is content-hashed (sha256 of
  the file bytes); the daemon checks the hash against the results
  store and drops epochs whose content was already published under
  another name.
- **out-of-order arrival** — each poll admits newly stable files in
  sorted-name order, but across polls the stream order is arrival
  order; the daemon journals in completion order and resumes by
  epoch key, so ordering is a throughput concern, not a correctness
  one.
- **malformed files** — admitted as-is; parsing happens in the
  pipeline's loader, whose MalformedInputError quarantines the epoch
  (robust/runner.py semantics) without stalling the stream.
"""

from __future__ import annotations

import fnmatch
import os
import queue
import threading
import time
from dataclasses import dataclass, field

from ..utils import slog
from .store import content_hash


@dataclass
class ArrivedEpoch:
    """One arrival out of a source: ``epoch`` is the stable key (file
    basename / caller-chosen id), ``payload`` what the pipeline
    loader receives (a path for the spool, anything for the queue),
    ``sha`` the content hash when the source could compute one, and
    ``t_arrive`` the perf-counter instant the source admitted it (the
    start of the epoch's ingest→publish latency span)."""

    epoch: str
    payload: object
    sha: str = None
    t_arrive: float = field(default_factory=time.perf_counter)
    #: multi-tenant namespace the arrival belongs to (ISSUE 16):
    #: admission control, fair-share lane quotas, and per-tenant
    #: metrics key off this; None = the daemon's default tenant
    tenant: str = None


class QueueSource:
    """In-process epoch source for tests and embedded use: ``put``
    epochs from any thread, the daemon ``get``s them. ``sha`` is
    optional (content dedupe only happens when the producer supplies
    one or ``hash_payloads=True`` hashes the payload repr)."""

    def __init__(self, hash_payloads=False):
        self._q = queue.Queue()
        self._hash = bool(hash_payloads)
        self._closed = threading.Event()
        self._last = time.time()

    def put(self, epoch, payload, sha=None, tenant=None):
        if sha is None and self._hash:
            sha = content_hash(payload)
        self._q.put(ArrivedEpoch(str(epoch), payload, sha=sha,
                                 tenant=tenant))

    def get(self, timeout=None):
        try:
            item = self._q.get(timeout=timeout) if timeout \
                else self._q.get_nowait()
        except queue.Empty:
            return None
        self._last = time.time()
        return item

    def backlog(self):
        return self._q.qsize()

    def alive(self):
        return not self._closed.is_set()

    def last_activity(self):
        return self._last

    def close(self):
        self._closed.set()


class SpoolWatcher:
    """Polling spool-directory source.

    A background thread scans ``spool_dir`` for files matching
    ``pattern`` every ``poll_s`` seconds. A file is ADMITTED — content
    hashed, wrapped in an :class:`ArrivedEpoch`, queued for the
    daemon — once its size is positive and unchanged for
    ``settle_polls`` consecutive polls (the torn-file guard: a writer
    mid-stream keeps moving the size, so the file is only picked up
    complete). Each file is admitted at most once per process; a
    restarted daemon re-admits everything and relies on the results
    store to skip what was already published (resume) or already seen
    under another name (content dedupe).

    **Tenant attribution** (ISSUE 16): a first-level subdirectory of
    the spool is a tenant namespace — ``<spool>/<tenant>/<file>``
    arrives with ``tenant=<tenant>`` and epoch key
    ``<tenant>/<file>`` (two tenants may drop the same filename
    without colliding), while top-level files keep ``tenant=None``
    (the daemon's default tenant). ``tenant_of(rel_name, path)``
    overrides the mapping (return None for the default tenant). The
    daemon's admission control and fair-share lane quotas key off
    this attribution.

    **Claim mode** (``claim=True`` — the shared-spool fleet shape,
    ROADMAP item 2): N daemons watching ONE spool directory must
    never fit the same epoch twice. Before admitting a stable file,
    the watcher claims it with the fleet queue's rename primitive
    (``fleet/queue.py:claim_by_rename``): the file atomically moves
    into this watcher's own claim directory
    (``<spool>/.claims/<owner>/``) — exactly one of N racing watchers
    wins the rename, the losers see the file vanish and drop it
    (counted in ``serve_spool_claims_lost_total``). The admitted
    payload is the file's CLAIMED path, and a restarted daemon
    re-admits whatever is already in its own claim directory (its
    results store then resumes/dedupes as usual), so a crash between
    claim and publish loses nothing.
    """

    def __init__(self, spool_dir, pattern="*.dynspec", poll_s=0.2,
                 settle_polls=1, start=True, claim=False,
                 owner=None, tenant_of=None):
        self.spool_dir = os.fspath(spool_dir)
        self.pattern = pattern
        self.tenant_of = tenant_of
        self.poll_s = max(0.01, float(poll_s))
        self.settle_polls = max(1, int(settle_polls))
        self.claim = bool(claim)
        self.owner = str(owner) if owner else f"d{os.getpid()}"
        self.claim_dir = os.path.join(self.spool_dir, ".claims",
                                      self.owner)
        self._q = queue.Queue()
        self._seen = {}          # name -> (size, stable_polls)
        self._admitted = set()
        self._closed = threading.Event()
        self._last_poll = time.time()
        if self.claim:
            # crash recovery: files claimed by a previous incarnation
            # of this owner but never published — re-admit them (the
            # results store skips what was already published)
            try:
                for name in sorted(os.listdir(self.claim_dir)):
                    if fnmatch.fnmatch(name, self.pattern):
                        self._admit(name, os.path.join(self.claim_dir,
                                                       name))
            except FileNotFoundError:
                pass
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="spool-watcher")
        if start:
            self._thread.start()

    # ---- background poll loop ---------------------------------------
    def _run(self):
        while not self._closed.is_set():
            try:
                self._poll_once()
            except OSError as e:
                # a transient filesystem error (NFS blip, dir swap)
                # must not kill the watcher; surface and keep polling
                slog.log_failure("serve.watch_error", stage="poll",
                                 error=e)
            self._last_poll = time.time()
            self._closed.wait(self.poll_s)

    def _scan_names(self):
        """Spool-relative names of candidate files: top-level matches
        plus one level of tenant-namespace subdirectories
        (``<tenant>/<file>``), sorted."""
        names = []
        for n in os.listdir(self.spool_dir):
            if n.startswith("."):
                continue
            if fnmatch.fnmatch(n, self.pattern):
                names.append(n)
                continue
            sub = os.path.join(self.spool_dir, n)
            if not os.path.isdir(sub):
                continue
            try:
                names.extend(
                    f"{n}/{m}" for m in os.listdir(sub)
                    if not m.startswith(".")
                    and fnmatch.fnmatch(m, self.pattern))
            except OSError:
                continue                 # tenant dir vanished mid-poll
        return sorted(names)

    def _poll_once(self):
        try:
            names = self._scan_names()
        except FileNotFoundError:
            return                       # spool not created yet
        for name in names:
            if name in self._admitted:
                continue
            path = os.path.join(self.spool_dir, name)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue                 # vanished mid-poll
            prev_size, stable = self._seen.get(name, (None, 0))
            if size <= 0 or size != prev_size:
                self._seen[name] = (size, 0)
                continue
            stable += 1
            self._seen[name] = (size, stable)
            if stable < self.settle_polls:
                continue
            self._admit(name, path)

    def _admit(self, name, path):
        if self.claim and os.path.dirname(path) != self.claim_dir:
            from ..fleet.queue import claim_by_rename
            from ..obs import metrics as _metrics

            won = claim_by_rename(path, self.claim_dir)
            if won is None:
                # another daemon renamed it away first — theirs now;
                # remember the name so we stop re-sizing it
                _metrics.counter(
                    "serve_spool_claims_lost_total",
                    help="stable spool files lost to another "
                         "daemon's claim").inc()
                self._admitted.add(name)
                self._seen.pop(name, None)
                return
            _metrics.counter(
                "serve_spool_claims_won_total",
                help="stable spool files claimed by this daemon"
            ).inc()
            path = won
        try:
            with open(path, "rb") as fh:
                sha = content_hash(fh.read())
        except OSError as e:
            slog.log_failure("serve.watch_error", stage="admit",
                             error=e, epoch=name)
            return
        self._admitted.add(name)
        self._seen.pop(name, None)
        if self.tenant_of is not None:
            tenant = self.tenant_of(name, path)
        else:
            tenant = name.split("/", 1)[0] if "/" in name else None
        self._q.put(ArrivedEpoch(name, path, sha=sha, tenant=tenant))
        slog.log_event("serve.ingest", epoch=name, path=path,
                       sha=sha[:12], tenant=tenant)

    # ---- source interface -------------------------------------------
    def get(self, timeout=None):
        try:
            return self._q.get(timeout=timeout) if timeout \
                else self._q.get_nowait()
        except queue.Empty:
            return None

    def backlog(self):
        return self._q.qsize()

    def alive(self):
        return self._thread.is_alive() and not self._closed.is_set()

    def last_activity(self):
        """Wall time of the last completed poll (the /healthz
        staleness input: a wedged watcher stops advancing this)."""
        return self._last_poll

    def close(self):
        self._closed.set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
