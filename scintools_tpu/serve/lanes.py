"""Lane assembly for the batched service mode (ISSUE 16).

The daemon's single-epoch dispatch window pays one device program per
arrival; every engine since PR 10 (factory, retrieval, detect, mcmc)
amortises dispatch 5-11x by making epochs LANES of one batched
program. This module is the host-side half of doing the same to the
serving tier: it decides *when* arrivals become a batch and *which*
arrivals share it.

Three pieces, all single-threaded (owned by the daemon loop thread —
serve/daemon.py drives them between polls):

- :class:`AdaptiveBatchController` — maps the live backlog gauge to a
  batch-size target B. The law: **track-up, decay-down**. On the way
  up B follows the backlog directly (clipped to ``max_batch``), so a
  burst is met with a full-width batch within one assembly; on the
  way down B decays geometrically (``decay`` per observation), so a
  one-tick lull does not collapse an ongoing burst back to B=1, but a
  real idle drains to single-epoch dispatch in O(log B) ticks and
  low-cadence latency stays bounded.

- :class:`TenantPolicy` — per-tenant admission control (an over-quota
  tenant's arrivals are REJECTED at admission, before they cost a
  load or a lane) and fair-share lane quotas (a cap on the fraction
  of any one batch a single tenant may fill).

- :class:`LaneAssembler` — the staging buffer: admitted + loaded
  epochs wait here keyed by geometry and tenant, and ``take(B)``
  forms one group per device geometry, interleaving tenants
  round-robin (FIFO within a tenant) so a flooding tenant cannot
  starve a quiet one out of lanes.

Batch-size bucketing lives here too (:func:`bucket_size` /
:func:`pad_group`): an adaptive B would retrace the device program at
every distinct group size, so groups are padded up to power-of-two
buckets with copies of a real payload — the padded lanes' results are
discarded after the program returns. Steady-state service therefore
compiles O(log max_batch) programs once and then holds zero retraces
(the bench pins this under ``retrace_guard``).
"""

from __future__ import annotations

from collections import OrderedDict, deque


def amortisation_factor(t1, tb, b):
    """How much batching amortises, from measured service times:
    ``t1`` — median seconds of a 1-lane batch, ``tb`` — median
    seconds of a ``b``-lane batch (the program cost ledger's
    ``serve.batch`` site supplies both).

    Returns a factor in [0, 1]: 1 when the batch costs the same as a
    single dispatch (fixed dispatch cost dominates — lanes are free,
    batch as wide as possible), 0 when the batch costs ``b`` single
    dispatches (compute-bound — lanes are marginal cost, and padding
    up to power-of-two buckets burns real seconds). Derived from the
    marginal-lane-cost ratio ``rho = (tb / b) / t1`` normalised so
    perfect amortisation (``rho = 1/b``) maps to 1 and none
    (``rho = 1``) to 0. None when the inputs can't support the
    estimate (missing samples, b <= 1)."""
    try:
        t1, tb, b = float(t1), float(tb), int(b)
    except (TypeError, ValueError):
        return None
    if t1 <= 0.0 or tb <= 0.0 or b <= 1:
        return None
    rho = (tb / b) / t1
    factor = (1.0 - rho) / (1.0 - 1.0 / b)
    return min(1.0, max(0.0, factor))


def bucket_size(n, cap):
    """Smallest power-of-two >= ``n``, clipped to ``cap`` (``cap``
    itself is always a valid bucket, power of two or not)."""
    n = max(1, int(n))
    cap = max(1, int(cap))
    if n >= cap:
        return cap
    b = 1
    while b < n:
        b <<= 1
    return min(b, cap)


def pad_group(payloads, cap):
    """Pad a group's payload list up to its bucket size with copies
    of the first payload. Returns ``(padded, n_real)`` — callers
    slice the program's results back to ``n_real`` lanes."""
    payloads = list(payloads)
    n = len(payloads)
    b = bucket_size(n, cap)
    return payloads + [payloads[0]] * (b - n), n


class AdaptiveBatchController:
    """Backlog-adaptive batch-size target (the ``serve_backlog_depth``
    feedback loop).

    ``observe(backlog)`` returns the new target B:

    - growth: ``B = min(max_batch, ceil(gain * backlog))`` whenever
      that exceeds the current target — B tracks the backlog up;
    - decay: otherwise ``B = max(that, floor(decay * B))`` — geometric
      drain toward 1 at idle (``decay`` in [0, 1), default 0.5).

    Deterministic and side-effect free apart from the retained
    target, so the step response is unit-testable without a daemon.
    """

    def __init__(self, max_batch=16, gain=1.0, decay=0.5,
                 min_gain=0.25, min_decay=0.25):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1: {max_batch}")
        if not 0.0 <= decay < 1.0:
            raise ValueError(f"decay must be in [0, 1): {decay}")
        self.max_batch = int(max_batch)
        self.gain = float(gain)
        self.decay = float(decay)
        # the configured law is the ceiling the scheduler works under
        self._base_gain = float(gain)
        self._base_decay = float(decay)
        self.min_gain = float(min_gain)
        self.min_decay = float(min_decay)
        self._b = 1

    @property
    def current(self):
        return self._b

    def reschedule(self, t1, tb, b):
        """Gain-schedule the law from measured batch service time
        (ISSUE 20, ROADMAP item 2d): interpolate ``gain``/``decay``
        between the configured values (fully-amortised batching —
        the constant-lane-cost assumption holds) and
        ``min_gain``/``min_decay`` (compute-bound — each lane costs
        real seconds, so B should under-track the backlog to cut
        power-of-two padding waste and drain faster at lulls).

        ``t1``/``tb``/``b`` as in :func:`amortisation_factor`;
        typically the ledger's ``serve.batch`` steady medians for
        bucket 1 and the widest observed bucket ``b``. Returns the
        factor applied, or None (law untouched) when the measurement
        can't support one."""
        factor = amortisation_factor(t1, tb, b)
        if factor is None:
            return None
        lo_g = min(self.min_gain, self._base_gain)
        lo_d = min(self.min_decay, self._base_decay)
        self.gain = lo_g + (self._base_gain - lo_g) * factor
        self.decay = lo_d + (self._base_decay - lo_d) * factor
        return factor

    def observe(self, backlog):
        target = int(-(-self.gain * max(0, backlog) // 1))  # ceil
        target = min(self.max_batch, target)
        if target >= self._b:
            self._b = max(1, target)
        else:
            self._b = max(1, target, int(self.decay * self._b))
        return self._b


class TenantPolicy:
    """Admission control + fair-share lane quotas per tenant.

    ``max_pending`` — admission cap: a tenant with that many epochs
    already admitted-but-unpublished has further arrivals rejected
    (status ``"rejected"``; the epoch is never loaded). ``None``
    disables admission control.

    ``quotas`` — per-tenant fraction of any single batch the tenant
    may fill (default ``default_quota``, 1.0 = no cap). The effective
    per-batch lane cap is ``max(1, floor(quota * B))``: even a
    heavily-capped tenant always gets at least one lane per batch it
    has pending work for, and the round-robin assembler gives every
    pending tenant its turn before anyone gets seconds — so a
    flooding tenant cannot crowd a quiet one out of lanes either way.
    """

    def __init__(self, max_pending=None, quotas=None,
                 default_quota=1.0):
        self.max_pending = None if max_pending is None \
            else int(max_pending)
        self.quotas = dict(quotas or {})
        self.default_quota = float(default_quota)

    def admit(self, tenant, pending):
        """True when ``tenant`` (with ``pending`` epochs in flight)
        may admit one more."""
        return self.max_pending is None or pending < self.max_pending

    def lane_cap(self, tenant, b):
        """Max lanes of a ``b``-wide batch this tenant may fill."""
        q = float(self.quotas.get(tenant, self.default_quota))
        return max(1, min(int(b), int(q * int(b))))


class LaneAssembler:
    """Staging buffer turning admitted arrivals into device groups.

    Entries are staged under ``(geometry, tenant)``; ``take(b)``
    picks the geometry with the most staged work (one batched program
    per geometry — mixed shapes never share a batch) and fills up to
    ``b`` lanes from it, visiting that geometry's tenants round-robin
    starting after the last tenant served, FIFO within each tenant,
    honoring ``policy.lane_cap``. Returns ``(geometry, entries)`` or
    ``None`` when empty.
    """

    def __init__(self, policy=None):
        self.policy = policy
        # geometry -> OrderedDict(tenant -> deque of entries);
        # insertion order of the tenant map IS the round-robin order
        self._staged = OrderedDict()
        self._count = 0
        self._rr_last = None

    def __len__(self):
        return self._count

    def stage(self, entry, tenant, geometry):
        tenants = self._staged.setdefault(geometry, OrderedDict())
        tenants.setdefault(tenant, deque()).append(entry)
        self._count += 1

    def staged_tenants(self, geometry=None):
        """Tenants with staged work (for one geometry, or overall)."""
        geoms = [geometry] if geometry is not None \
            else list(self._staged)
        out = set()
        for g in geoms:
            for t, q in self._staged.get(g, {}).items():
                if q:
                    out.add(t)
        return out

    def take(self, b):
        b = max(1, int(b))
        geometry, found, best = None, False, 0
        for g, tenants in self._staged.items():
            n = sum(len(q) for q in tenants.values())
            if n > best:
                geometry, found, best = g, True, n
        if not found:
            return None
        tenants = self._staged[geometry]
        order = [t for t, q in tenants.items() if q]
        # resume the wheel after the last tenant served so repeated
        # small batches don't always favor the first-staged tenant
        if self._rr_last in order:
            i = order.index(self._rr_last) + 1
            order = order[i:] + order[:i]
        caps = {t: (self.policy.lane_cap(t, b) if self.policy
                    else b) for t in order}
        picked = []
        taken = {t: 0 for t in order}
        while len(picked) < b:
            progressed = False
            for t in order:
                if len(picked) >= b:
                    break
                q = tenants[t]
                if not q or taken[t] >= caps[t]:
                    continue
                picked.append(q.popleft())
                taken[t] += 1
                self._rr_last = t
                progressed = True
            if not progressed:
                break
        self._count -= len(picked)
        for t in [t for t, q in tenants.items() if not q]:
            del tenants[t]
        if not tenants:
            del self._staged[geometry]
        return geometry, picked
