"""Long-lived streaming survey daemon (ISSUE 6 tentpole).

``run_survey`` is batch-shaped: the full epoch list up front, one
report at exit. The serving tier the roadmap asks for is a PROCESS —
it watches a spool (or an in-process queue) for arriving epochs,
feeds them incrementally through the same PrefetchLoader →
dispatch-ahead pipeline the batch runner uses
(parallel/pipeline.py + robust/runner.py's shared per-epoch engine),
publishes each result to an append-only, atomically-readable results
store (serve/store.py, the PR-2 CRC-JSONL journal), and exposes its
observability surface LIVE over HTTP (serve/http.py) instead of
write-at-exit. The real-time GPU pulsar pipelines this repo models on
(Dimoudi et al. arXiv:1711.10855; Adámek et al. arXiv:1804.05335)
are judged on sustained streaming latency under load; this daemon is
what lets the process measure and publish that latency while it is
happening.

Guarantees, all pinned by tests/test_serve.py:

- **bounded ingest→publish latency** — the loop never parks behind
  the stream: an idle poll tick drains the dispatch-ahead window, so
  a lull in arrivals fences and publishes everything in flight
  instead of waiting for the window to fill;
- **per-epoch end-to-end latency accounting** — every epoch carries
  an ``ingest → dispatch → fence → publish`` span chain through the
  shared trace-ID machinery (obs/trace.py tracks), an
  ``serve_e2e_latency_seconds`` histogram, and p50/p95 percentiles
  in heartbeats and the live RunReport;
- **crash = restart** — results are journaled exactly like a PR-2
  batch run: a SIGKILL loses at most the un-fsynced tail, a
  restarted daemon re-admits the spool, takes journaled epochs
  verbatim (nothing published twice), and converges to a
  byte-consistent results store;
- **stream fault-hardening** — torn files wait for completion
  (SpoolWatcher settle logic), duplicates are dropped by content
  hash (counted in ``serve_duplicates_total``), malformed files
  quarantine through the fallback ladder, out-of-order arrival is
  just arrival order.
"""

from __future__ import annotations

import collections
import os
import queue
import threading
import time

import numpy as np

from ..obs import heartbeat as _hb
from ..obs import ledger as _ledger
from ..obs import metrics as _metrics
from ..obs import report as _report
from ..parallel.pipeline import AsyncJournalWriter, PrefetchLoader
from ..robust import runner as _runner
from ..robust.runner import EpochOutcome
from ..utils import slog
from ..utils.profiling import StageTimeline
from . import lanes as _lanes
from .store import ResultsStore

_STOP = object()

#: e2e latency buckets [seconds]: a streaming epoch should publish
#: within tens of ms (in-process) to seconds (real fits + spool I/O).
LATENCY_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                   2.5, 5.0, 15.0, 60.0)


class _ServeRecorder(_runner._Recorder):
    """The runner's recorder with a content-hash column: every
    journal line the daemon publishes carries the epoch's ``sha``
    field, so the store's dedupe index survives restart."""

    def __init__(self, journal, writer, tiers, heartbeat=None):
        super().__init__(journal, writer, tiers, heartbeat=heartbeat)
        self._sha = {}

    def set_sha(self, key, sha):
        if sha:
            self._sha[str(key)] = sha

    def _append(self, key, **fields):
        sha = self._sha.pop(str(key), None)
        if sha:
            fields["sha"] = sha
        super()._append(key, **fields)


class SurveyService:
    """The streaming survey daemon.

    ``source`` is an epoch source (serve/watch.py:
    :class:`SpoolWatcher` / :class:`QueueSource`); ``process(payload,
    tier=...)`` is the per-epoch worker exactly as in
    :func:`~scintools_tpu.robust.runner.run_survey` (tiered fallback,
    deferred device values, validator hook all behave identically —
    the daemon drives the runner's own engine); ``load_fn`` maps the
    arrived payload (a spool path) to the process payload in the
    background prefetch workers. Results journal to
    ``workdir/results.jsonl``; rerunning the same workdir resumes.

    Lifecycle: ``start()`` launches the ingest loop (and the
    telemetry HTTP listener when ``http`` is not False —
    ``http=(host, port)``, port 0 = ephemeral, see
    :attr:`http_port`); ``stop()`` finishes everything admitted,
    drains the journal writer (durability barrier), writes the final
    RunReport, and shuts the listener. Use as a context manager for
    the same pair.
    """

    def __init__(self, source, process, workdir,
                 tiers=_runner._DEFAULT_TIERS, retries=1,
                 validate=None, defer_validate=False, load_fn=None,
                 prefetch=4, inflight=2, loader_workers=2,
                 journal_name="results.jsonl", http=("127.0.0.1", 0),
                 heartbeat=True, warmup=None, stale_after_s=5.0,
                 report=True, on_published=None, process_batch=None,
                 max_batch=16, controller=None, tenant_policy=None,
                 geometry_fn=None, bucket_lanes=True,
                 on_published_group=None, gain_schedule=True,
                 tenant_label_cap=8):
        self.source = source
        self.process = process
        self.workdir = os.fspath(workdir)
        self.tiers = tuple(tiers)
        self.retries = retries
        self.validate = validate
        self.load_fn = load_fn
        self.prefetch = max(1, int(prefetch))
        self.inflight = max(1, int(inflight))
        if validate is not None and not defer_validate:
            self.inflight = 0        # runner semantics: fence per epoch
        self.loader_workers = max(1, int(loader_workers))
        self.stale_after_s = float(stale_after_s)
        self.report = bool(report)
        self._warmup_fn = warmup
        # post-publish consumers (ISSUE 14): ``fn(service, epoch_id,
        # loaded_payload, outcome)`` runs in the loop thread AFTER
        # the epoch's result is journaled — the hook point the online
        # arc detector (detect/online.py) registers through, instead
        # of forking or monkeypatching _consume_one
        self._hooks = list(on_published or [])
        self._group_hooks = list(on_published_group or [])

        # batched service mode (ISSUE 16): when ``process_batch``
        # is given, loaded arrivals STAGE in the lane assembler and
        # dispatch as ONE batched device program per geometry; the
        # controller maps the live backlog to the batch-size target
        # (track-up / decay-down — serve/lanes.py), the optional
        # tenant policy adds admission control + fair-share quotas,
        # and groups pad up to power-of-two buckets so steady-state
        # service never retraces.
        self.process_batch = process_batch
        self.max_batch = max(1, int(max_batch))
        self.geometry_fn = geometry_fn
        self.bucket_lanes = bool(bucket_lanes)
        self.tenant_policy = tenant_policy
        self._assembler = None
        self._controller = None
        if process_batch is not None:
            self._assembler = _lanes.LaneAssembler(policy=tenant_policy)
            self._controller = controller \
                or _lanes.AdaptiveBatchController(max_batch=self.max_batch)
            self.max_batch = self._controller.max_batch
        self._tenant_pending = {}    # tenant -> admitted-not-published
        self._staged_t = {}          # key -> staging-entry instant

        # program cost ledger (ISSUE 20): batch service times feed
        # the controller's gain scheduling, and the accumulated
        # ledger persists per workdir (loaded here, saved at loop
        # exit) so a restarted daemon resumes its cost model
        self.gain_schedule = bool(gain_schedule)
        self._buckets_seen = set()
        self._ledger_path = _ledger.workdir_path(self.workdir)
        _ledger.load(self._ledger_path)

        # per-tenant SLO accounting (ISSUE 20): the first
        # ``tenant_label_cap`` distinct tenants (by ingest order) get
        # dedicated metric labels, later ones fold into "other" —
        # tenant names are user-controlled strings, so every
        # tenant-labeled metric goes through _tenant_label to keep
        # label cardinality bounded (JL005)
        self.tenant_label_cap = max(1, int(tenant_label_cap))
        self._tenant_labels = {}
        self._lat_by_tenant = {}     # label -> deque of latencies

        os.makedirs(self.workdir, exist_ok=True)
        self.store = ResultsStore(self.workdir, name=journal_name)
        self._done_records = self.store.records()
        self.timeline = StageTimeline(device_stage="dispatch")
        self._writer = AsyncJournalWriter(self.store.journal,
                                          timeline=self.timeline)
        self._rec = _ServeRecorder(
            self.store.journal, self._writer, self.tiers,
            heartbeat=self._make_heartbeat(heartbeat))
        self._builder = _report.RunReportBuilder(runner="serve_survey")

        self._lock = threading.Lock()
        self._inflight_sha = {}
        self._states = collections.OrderedDict()
        self._lat = collections.deque(maxlen=4096)
        self._window = collections.deque()
        self._fresh_q = queue.Queue()
        self._index = 0
        self._warm = False
        self._stopping = threading.Event()
        self._done = threading.Event()
        self._stop_sent = False
        self._last_tick = time.time()
        self._error = None

        self._loader = PrefetchLoader(
            self._fresh_stream(), depth=self.prefetch,
            workers=self.loader_workers, load_fn=self.load_fn,
            timeline=self.timeline)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-loop")
        self._http = None
        if http:
            from .http import TelemetryServer

            host, port = http if isinstance(http, (tuple, list)) \
                else ("127.0.0.1", int(http) if http is not True else 0)
            self._http = TelemetryServer(self, host=host, port=port)

    # ---- lifecycle --------------------------------------------------
    def start(self):
        if self._http is not None:
            self._http.start()
        self._thread.start()
        return self

    def stop(self, timeout=60.0):
        """Graceful shutdown: finish every admitted epoch, drain the
        journal writer, write the final RunReport, stop the HTTP
        listener. Idempotent."""
        self._stopping.set()
        if hasattr(self.source, "close"):
            self.source.close()
        if self._thread.is_alive() or not self._done.is_set():
            if self._thread.ident is not None:
                self._thread.join(timeout=timeout)
        self._loader.close()
        if self._http is not None:
            self._http.close()
        if self._error is not None:
            # lint-ok: lock-discipline: the loop thread is joined
            # above, so its final _error write happens-before this
            # read-and-clear (Thread.join is the synchronisation)
            err, self._error = self._error, None
            raise RuntimeError("serve loop failed") from err
        return self

    close = stop

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def wait_idle(self, timeout=30.0, settle_s=0.05):
        """Block until nothing is queued, loading, or in flight (the
        test-friendly quiesce point; the stream may deliver more
        later). Returns True when idle was reached."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.backlog() == 0 and not self._window:
                time.sleep(settle_s)
                if self.backlog() == 0 and not self._window:
                    return True
            time.sleep(0.01)
        return False

    # ---- ingest loop ------------------------------------------------
    def _fresh_stream(self):
        """The lazy (epoch_id, payload) stream feeding the prefetch
        loader; ends when the stop sentinel arrives."""
        while True:
            item = self._fresh_q.get()
            if item is _STOP:
                return
            yield item

    def _make_heartbeat(self, spec):
        if spec is None or spec is False:
            return None
        kw = {"streaming": True, "event": "serve.heartbeat",
              "stats_fn": self._live_stats}
        if isinstance(spec, dict):
            kw.update(spec)
        elif isinstance(spec, _hb.Heartbeat):
            return spec
        elif spec is not True:
            raise TypeError(
                f"heartbeat must be None/bool/dict/Heartbeat, got "
                f"{type(spec).__name__}")
        return _hb.Heartbeat(**kw)

    def _loop(self):
        try:
            with slog.span("serve.run", workdir=self.workdir):
                self._warmup()
                while True:
                    self._tick()
                    stopping = self._stopping.is_set()
                    if not stopping:
                        self._pull_arrivals()
                    elif not self._stop_sent:
                        self._fresh_q.put(_STOP)
                        self._stop_sent = True
                    busy = self._window or (
                        self._assembler is not None
                        and len(self._assembler))
                    got = self._loader.poll(
                        timeout=0.02 if busy else 0.05)
                    if got is not None:
                        self._route(*got)
                    if self._assembler is not None:
                        self._maybe_assemble(
                            idle=(got is None) or stopping)
                    while len(self._window) > self.inflight:
                        self._consume_one()
                    if got is None and self._window:
                        # idle stream → flush the window now: bounded
                        # ingest→publish latency beats dispatch-ahead
                        self._consume_one()
                    self._update_gauges()
                    if stopping and self._stop_sent \
                            and self._loader.exhausted \
                            and not self._window \
                            and not (self._assembler is not None
                                     and len(self._assembler)):
                        break
            self._writer.close()       # durability barrier (PR-2)
            self._rec.beat(force=True)
            # persist the accumulated cost model next to the results
            # journal: a restarted daemon loads it back and resumes
            # gain scheduling with a warm cost model
            _ledger.save(self._ledger_path)
            if self.report:
                self._builder.finalize(
                    self.workdir, dict(self._rec.tally),
                    list(self._rec.outcomes),
                    timeline=self.timeline.summary(),
                    extra=self._live_stats(),
                    slo=self.slo_snapshot())
        except Exception as e:  # noqa: BLE001 — the loop must die
            # loudly: surfaced by /healthz (loop no longer ticking),
            # re-raised from stop()
            # lint-ok: lock-discipline: single-writer — only the loop
            # thread assigns _error; stop() reads it after join()
            self._error = e
            slog.log_failure("serve.loop_error", stage="loop", error=e)
        finally:
            self._done.set()

    def _warmup(self):
        """Optional device-program warm-up: run ``warmup()`` (e.g. a
        synthetic epoch through ``process``) before serving so
        ``/readyz`` can go ready ahead of the first real epoch; a
        warm-up failure is logged, not fatal — the first real epoch
        warms instead."""
        if self._warmup_fn is None:
            return
        try:
            self._warmup_fn()
            # lint-ok: lock-discipline: _warm is a monotonic
            # False→True latch written only by the loop thread;
            # /readyz reads it racily by design (a stale False is a
            # harmless not-ready-yet)
            self._warm = True
        except Exception as e:  # noqa: BLE001 — warm-up is advisory
            slog.log_failure("serve.warmup_error", stage="warmup",
                             error=e)

    def _tick(self):
        self._last_tick = time.time()

    def _tenant_label(self, tenant):
        """The bounded metric label for a tenant namespace: the first
        ``tenant_label_cap`` distinct tenants keep their own label,
        every later one is ``"other"`` — tenant names come off the
        spool (user-controlled), and an unbounded label set is a
        cardinality leak (JL005). The mapping is sticky for the
        process lifetime. Callers hold ``self._lock``."""
        lbl = self._tenant_labels.get(tenant)
        if lbl is None:
            lbl = str(tenant) \
                if len(self._tenant_labels) < self.tenant_label_cap \
                else "other"
            self._tenant_labels[tenant] = lbl
        return lbl

    def _pull_arrivals(self):
        while self._fresh_q.qsize() < max(2, self.prefetch):
            item = self.source.get(timeout=0.0)
            if item is None:
                return
            self._admit(item)

    def _admit(self, item):
        key = str(item.epoch)
        tenant = getattr(item, "tenant", None) or "default"
        with self._lock:
            if key in self._states:
                return                       # already seen this run
            self._index += 1
            self.timeline.assign_trace(
                key, _runner._trace_id(self._index - 1, key))
            now = time.perf_counter()
            self.timeline.record(key, "ingest", item.t_arrive, now)
            if key in self._done_records:
                self._rec.tally["n_epochs"] += 1
                out = self._rec.resumed(key, self._done_records[key])
                self._states[key] = {
                    "status": "resumed",
                    "result_status": self._done_records[key].get(
                        "status", "ok"),
                    "tier": out.tier}
                return
            # dedupe against published content AND epochs still in
            # flight (two copies arriving back-to-back must not both
            # process just because neither has published yet)
            dup_of = self.store.known_content(item.sha) \
                or (item.sha and self._inflight_sha.get(item.sha))
            if dup_of is not None:
                _metrics.counter(
                    "serve_duplicates_total",
                    help="stream epochs dropped as content "
                         "duplicates").inc()
                slog.log_event("serve.duplicate", epoch=key,
                               duplicate_of=dup_of)
                self._states[key] = {"status": "duplicate",
                                     "duplicate_of": dup_of}
                return
            # tenant admission control (ISSUE 16): an over-quota
            # tenant's arrival is refused BEFORE it costs a load or a
            # lane — neighbours' admission is untouched
            if self.tenant_policy is not None \
                    and not self.tenant_policy.admit(
                        tenant, self._tenant_pending.get(tenant, 0)):
                _metrics.counter(
                    "serve_tenant_rejected_total",
                    help="arrivals refused by per-tenant admission "
                         "control").labels(
                    tenant=self._tenant_label(tenant)).inc()  # lint-ok: metric-hygiene: bounded=tenant
                slog.log_event("serve.tenant_rejected", epoch=key,
                               tenant=tenant,
                               pending=self._tenant_pending.get(
                                   tenant, 0))
                self._states[key] = {"status": "rejected",
                                     "tenant": tenant}
                return
            _metrics.counter(
                "serve_epochs_ingested_total",
                help="fresh epochs admitted into the pipeline").inc()
            _metrics.counter(
                "serve_tenant_ingested_total",
                help="fresh epochs admitted, by tenant namespace"
            ).labels(tenant=self._tenant_label(tenant)).inc()  # lint-ok: metric-hygiene: bounded=tenant
            self._tenant_pending[tenant] = \
                self._tenant_pending.get(tenant, 0) + 1
            self._rec.tally["n_epochs"] += 1
            self._rec.set_sha(key, item.sha)
            if item.sha:
                self._inflight_sha[item.sha] = key
            self._states[key] = {"status": "queued",
                                 "t_ingest": item.t_arrive,
                                 "sha": item.sha,
                                 "tenant": tenant}
        self._fresh_q.put((key, item.payload))

    def _dispatch(self, eid, loaded):
        key = str(eid)
        with self._lock:
            st = self._states.get(key, {})
            st["status"] = "in_flight"
        if not loaded.ok:
            # lint-ok: lock-discipline: the dispatch window is
            # loop-thread-only (single producer AND consumer —
            # _dispatch/_consume_one both run in _loop); wait_idle
            # only reads truthiness
            self._window.append(
                (key, None,
                 _runner._loader_outcome(key, loaded.error), None))
            return
        with _ledger.timed("serve.batch", shape=1), \
                self.timeline.span(key, "dispatch"):
            entry = _runner._dispatch_first(
                key, loaded.payload, self.process, self.tiers,
                self.retries, self.validate)
        # lint-ok: lock-discipline: loop-thread-only window (above)
        self._window.append(entry)

    # ---- batched service mode (ISSUE 16) -----------------------------
    def _route(self, eid, loaded):
        """Loaded-arrival routing: batched mode stages healthy loads
        in the lane assembler; everything else (no assembler, loader
        failure, controller drained to B=1 with nothing staged) takes
        the existing single-epoch dispatch window."""
        if self._assembler is None or not loaded.ok:
            self._dispatch(eid, loaded)
            return
        if self._controller.current <= 1 \
                and not len(self._assembler):
            # drained back to single-epoch dispatch at idle: bounded
            # low-cadence latency, zero staging detour
            self._dispatch(eid, loaded)
            return
        key = str(eid)
        with self._lock:
            st = self._states.get(key, {})
            st["status"] = "staged"
            tenant = st.get("tenant", "default")
        geometry = self.geometry_fn(loaded.payload) \
            if self.geometry_fn is not None else None
        # lint-ok: lock-discipline: the assembler and the staging
        # clock are loop-thread-only (staged by _route, drained by
        # _maybe_assemble/_dispatch_group — all run in _loop)
        self._staged_t[key] = time.perf_counter()
        self._assembler.stage((key, loaded.payload), tenant, geometry)

    def _maybe_assemble(self, idle):
        """Form and dispatch one batched group when the staging
        buffer has reached the controller's target B — or whatever is
        staged, on an idle tick (a lull must flush staged lanes, the
        single-path idle-drain guarantee carried over)."""
        staged = len(self._assembler)
        if not staged:
            return
        b = self._controller.current
        if staged < b and not idle:
            return
        took = self._assembler.take(b)
        if took is None:
            return
        geometry, entries = took
        if len(entries) == 1:
            # B drained to 1: ride the runner's per-epoch engine
            # (identical to non-batched dispatch, window semantics
            # and all)
            key, payload = entries[0]
            # lint-ok: lock-discipline: loop-thread-only staging
            # clock (see _route)
            t_staged = self._staged_t.pop(key, None)
            if t_staged is not None:
                self.timeline.record(key, "assemble", t_staged,
                                     time.perf_counter())
            with self._lock:
                st = self._states.get(key, {})
                st["status"] = "in_flight"
            with _ledger.timed("serve.batch", shape=1), \
                    self.timeline.span(key, "dispatch"):
                entry = _runner._dispatch_first(
                    key, payload, self.process, self.tiers,
                    self.retries, self.validate)
            # lint-ok: lock-discipline: loop-thread-only window (see
            # _dispatch)
            self._window.append(entry)
            return
        self._dispatch_group(geometry, entries)

    def _group_process(self, payloads, tier=None):
        """The assembler-facing ``process_batch`` wrapper: pads the
        group up to its power-of-two bucket with copies of a real
        payload (so the adaptive B never retraces the device program
        in steady state) and slices the padded lanes' results back
        off."""
        if not self.bucket_lanes:
            return self.process_batch(payloads, tier=tier)
        padded, n = _lanes.pad_group(payloads, self.max_batch)
        out = self.process_batch(padded, tier=tier)
        return list(out)[:n]

    def _dispatch_group(self, geometry, entries):
        """ONE batched device program for ``entries`` — the runner's
        shared group engine (robust/runner.py:run_group: ladder,
        batch fallback, per-lane health screening and individual
        descent), then per-lane publish in group order."""
        keys = [k for k, _ in entries]
        payloads = dict(entries)
        now = time.perf_counter()
        for key in keys:
            # lint-ok: lock-discipline: loop-thread-only staging
            # clock (see _route)
            t_staged = self._staged_t.pop(key, None)
            if t_staged is not None:
                self.timeline.record(key, "assemble", t_staged, now)
        with self._lock:
            tenants = {}
            for key in keys:
                st = self._states.get(key, {})
                st["status"] = "in_flight"
                t = st.get("tenant", "default")
                tenants[t] = tenants.get(t, 0) + 1
        bucket = _lanes.bucket_size(len(entries), self.max_batch) \
            if self.bucket_lanes else len(entries)
        _metrics.counter(
            "serve_batches_total",
            help="assembled lane groups dispatched as one batched "
                 "device program").inc()
        _metrics.counter(
            "serve_batch_lanes_total",
            help="real (non-padding) lanes dispatched in batched "
                 "groups").inc(len(entries))
        _metrics.counter(
            "serve_batch_padded_lanes_total",
            help="padding lanes added to reach the power-of-two "
                 "bucket (results discarded)").inc(
            bucket - len(entries))
        slog.log_event(
            "serve.batch", n_lanes=len(entries), bucket=bucket,
            b_target=self._controller.current,
            geometry=repr(geometry) if geometry is not None else None,
            tenants=tenants)
        outs = []
        t0 = time.perf_counter()
        _runner.run_group(
            entries, self._group_process, self.process, self.tiers,
            self.retries, self.validate or _runner.default_lane_validate,
            lambda eid, out: outs.append((eid, out)),
            epoch_label=f"group[{keys[0]}+{len(entries)}]")
        t1 = time.perf_counter()
        # the measured per-bucket batch service time — the gain
        # scheduler's input and the /ledger endpoint's content
        _ledger.record("serve.batch", t1 - t0, "steady", shape=bucket)
        self._buckets_seen.add(int(bucket))
        self._reschedule_controller()
        for key in keys:
            # the batched program is the device stage: dispatch +
            # compute + fetch for every lane in one span
            self.timeline.record(key, "dispatch", t0, t1)
        for eid, out in outs:
            # per-lane fence span: program return → this lane's
            # publish (the lane's wait behind its groupmates)
            self.timeline.record(eid, "fence", t1,
                                 time.perf_counter())
            self._publish(out)
            self._run_hooks(eid, payloads.get(str(eid)), out)
        self._run_group_hooks(entries, dict(outs))

    def _reschedule_controller(self):
        """Gain-schedule the batch controller from the ledger's
        measured per-bucket service time (ISSUE 20, ROADMAP 2d): the
        steady median of a 1-lane dispatch vs the widest observed
        bucket decides how amortised batching actually is, and the
        controller interpolates gain/decay accordingly (compute-bound
        lanes → under-track the backlog, less padding waste, faster
        drain). With no 1-lane samples (sustained load batches
        everything) T(1) is extrapolated from the two observed bucket
        extremes under a linear cost model. Runs once per dispatched
        group — a few ring-buffer median queries, microseconds
        against a batch program."""
        if self._controller is None or not self.gain_schedule \
                or not self._buckets_seen:
            return
        b = max(self._buckets_seen)
        if b <= 1:
            return
        tb = _ledger.steady_median("serve.batch", shape=b)
        t1 = _ledger.steady_median("serve.batch", shape=1)
        if t1 is None and len(self._buckets_seen) >= 2 and tb:
            # a daemon under sustained load never dispatches a single
            # lane, so T(1) may be unmeasured; estimate it from the
            # smallest and widest observed buckets via the linear
            # cost model t(b) = c_fixed + c_lane * b
            b0 = min(self._buckets_seen)
            t0 = _ledger.steady_median("serve.batch", shape=b0)
            if t0 and b > b0:
                c_lane = (tb - t0) / (b - b0)
                t1 = max(t0 - c_lane * (b0 - 1), 1e-9)
        factor = self._controller.reschedule(t1, tb, b)
        if factor is not None:
            _metrics.gauge(
                "serve_controller_gain",
                help="gain-scheduled batch controller gain",
            ).set(self._controller.gain)

    def _consume_one(self):
        # lint-ok: lock-discipline: loop-thread-only window (see
        # _dispatch)
        epoch_id, payload, value, report = self._window.popleft()
        if isinstance(value, EpochOutcome):    # already decided
            out = value
        else:
            with self.timeline.span(epoch_id, "fence"):
                out = _runner._consume_deferred(
                    epoch_id, payload, value, report, self.process,
                    self.tiers, self.retries, self.validate)
        self._publish(out)
        self._run_hooks(epoch_id, payload, out)

    # ---- post-publish hook point (ISSUE 14) --------------------------
    def add_on_published(self, fn):
        """Register a post-publish consumer ``fn(service, epoch_id,
        loaded_payload, outcome)``. Hooks run in the ingest-loop
        thread AFTER the epoch's result is journaled (the epoch's own
        ingest→publish latency is already accounted); each hook call
        is a named span on the epoch's trace (``fn.hook_stage``,
        default ``'on_published'``) and a hook crash is contained —
        logged as ``serve.hook_error``, counted, never fatal to the
        loop. Call before :meth:`start` (single-writer: the loop
        thread is the only reader)."""
        self._hooks.append(fn)
        return fn

    def add_on_published_group(self, fn):
        """Register a post-publish GROUP consumer ``fn(service,
        entries, outcomes)`` for the batched service mode: after a
        whole assembled group publishes, the hook receives the
        group's ``[(key, loaded_payload), ...]`` and its ``{key:
        EpochOutcome}`` map in one call — the spike-grouped
        confirmation hook point (detect/online.py:make_group_hook
        scans all lanes in ONE bank program instead of per-epoch).
        Same containment contract as :meth:`add_on_published`; spans
        land on the group's first lane trace. Call before
        :meth:`start`."""
        self._group_hooks.append(fn)
        return fn

    def _run_group_hooks(self, entries, outcomes):
        if not self._group_hooks or not entries:
            return
        first = str(entries[0][0])
        for fn in self._group_hooks:
            stage = getattr(fn, "hook_stage", "on_published_group")
            try:
                with self.timeline.span(first, stage):
                    fn(self, entries, outcomes)
            except Exception as e:  # noqa: BLE001 — contained like
                # per-epoch hooks: the stream keeps flowing
                slog.log_failure("serve.hook_error", stage=stage,
                                 error=e, epoch=first)
                _metrics.counter(
                    "serve_hook_errors_total",
                    help="post-publish hook failures (epoch "
                         "unaffected, hook skipped)").inc()

    def annotate(self, key, **fields):
        """Merge extra fields into an epoch's ``/state`` entry (hook
        consumers attach their per-epoch results — e.g. the detector's
        ``detect={...}`` record)."""
        with self._lock:
            st = self._states.get(str(key))
            if st is not None:
                st.update(fields)

    def _run_hooks(self, epoch_id, payload, out):
        for fn in self._hooks:
            stage = getattr(fn, "hook_stage", "on_published")
            try:
                with self.timeline.span(epoch_id, stage):
                    fn(self, epoch_id, payload, out)
            except Exception as e:  # noqa: BLE001 — a consumer crash
                # must not take the serving loop down; surfaced via
                # slog + metrics, the stream keeps flowing
                slog.log_failure("serve.hook_error", stage=stage,
                                 error=e, epoch=str(epoch_id))
                _metrics.counter(
                    "serve_hook_errors_total",
                    help="post-publish hook failures (epoch "
                         "unaffected, hook skipped)").inc()

    def _publish(self, out):
        key = str(out.epoch)
        t0 = time.perf_counter()
        with self._lock:
            self._rec.record(out)
            st = self._states.setdefault(key, {})
            st["status"] = out.status
            st["tier"] = out.tier
            if out.status == "quarantined":
                st["error_class"] = out.error_class
            t_pub = time.perf_counter()
            t_in = st.get("t_ingest")
            tenant = st.get("tenant")
            if t_in is not None:
                lat = t_pub - t_in
                st["latency_s"] = round(lat, 6)
                self._lat.append(lat)
                _metrics.histogram(
                    "serve_e2e_latency_seconds",
                    help="ingest-to-published end-to-end latency",
                    buckets=LATENCY_BUCKETS).observe(lat)
                if tenant is not None:
                    # per-tenant SLO view (ISSUE 20): same family,
                    # bounded tenant label (top-K + "other")
                    lbl = self._tenant_label(tenant)
                    _metrics.histogram(
                        "serve_e2e_latency_seconds",
                        help="ingest-to-published end-to-end latency",
                        buckets=LATENCY_BUCKETS).labels(
                        tenant=lbl).observe(lat)  # lint-ok: metric-hygiene: bounded=tenant
                    self._lat_by_tenant.setdefault(
                        lbl, collections.deque(maxlen=1024)).append(lat)
            self.store.note_published(key, st.get("sha"))
            self._inflight_sha.pop(st.get("sha"), None)
            if tenant is not None:
                pend = self._tenant_pending.get(tenant, 0)
                if pend > 0:
                    self._tenant_pending[tenant] = pend - 1
                _metrics.counter(
                    "serve_tenant_published_total",
                    help="published epochs, by tenant namespace"
                ).labels(tenant=self._tenant_label(tenant)).inc()  # lint-ok: metric-hygiene: bounded=tenant
                if out.status == "quarantined":
                    _metrics.counter(
                        "serve_tenant_quarantined_total",
                        help="quarantined epochs, by tenant "
                             "namespace").labels(
                        tenant=self._tenant_label(tenant)).inc()  # lint-ok: metric-hygiene: bounded=tenant
        self.timeline.record(key, "publish", t0, time.perf_counter())
        if out.status == "ok":
            # lint-ok: lock-discipline: monotonic False→True latch,
            # loop-thread-only writer (see _warmup)
            self._warm = True

    def _update_gauges(self):
        backlog = self.backlog()
        _metrics.gauge(
            "serve_backlog_depth",
            help="epochs arrived but not yet published",
        ).set(backlog)
        if self._controller is not None:
            # the feedback loop: the backlog gauge drives the
            # batch-size target every tick
            _metrics.gauge(
                "serve_batch_size",
                help="current adaptive batch-size target B",
            ).set(self._controller.observe(backlog))

    # ---- live surfaces (HTTP handlers + heartbeat) ------------------
    def backlog(self):
        """Epochs arrived but not yet published: source queue +
        admitted-but-unloaded + loaded-or-loading + dispatch window."""
        n = self._fresh_q.qsize() + len(self._window) \
            + self._loader.buffered()
        if self._assembler is not None:
            n += len(self._assembler)
        if hasattr(self.source, "backlog"):
            n += self.source.backlog()
        return n

    def latency_percentiles(self):
        """``{"p50_s":, "p95_s":, "n":}`` over the recent
        ingest→published latencies (None values until the first
        publish)."""
        lat = list(self._lat)
        if not lat:
            return {"p50_s": None, "p95_s": None, "n": 0}
        return {"p50_s": round(float(np.percentile(lat, 50)), 6),
                "p95_s": round(float(np.percentile(lat, 95)), 6),
                "n": len(lat)}

    def tenant_latency_percentiles(self):
        """Per-tenant-label ``{"p50_s":, "p95_s":, "n":}`` over the
        recent latencies — keys are the BOUNDED labels
        (:meth:`_tenant_label`: top-K tenants + ``"other"``), the
        per-tenant SLO view heartbeats and the RunReport carry."""
        # lock-free like latency_percentiles: C-level dict/deque
        # copies under the GIL; heartbeats call this from inside
        # _publish (which holds self._lock), so taking the lock here
        # would self-deadlock
        by = {lbl: list(q) for lbl, q in
              list(self._lat_by_tenant.items()) if q}
        return {lbl: {"p50_s": round(float(np.percentile(lat, 50)), 6),
                      "p95_s": round(float(np.percentile(lat, 95)), 6),
                      "n": len(lat)}
                for lbl, lat in sorted(by.items())}

    def slo_snapshot(self):
        """The RunReport ``slo`` block (ISSUE 20): global + per-tenant
        latency percentiles plus the ledger's per-site steady medians
        (``{"global":, "tenants":, "sites":}``)."""
        return {"global": self.latency_percentiles(),
                "tenants": self.tenant_latency_percentiles(),
                "sites": _ledger.LEDGER.steady_site_medians()}

    def _live_stats(self):
        stats = {"backlog": self.backlog()}
        pct = self.latency_percentiles()
        if pct["n"]:
            stats["latency_p50_s"] = pct["p50_s"]
            stats["latency_p95_s"] = pct["p95_s"]
        tenants = self.tenant_latency_percentiles()
        if tenants:
            stats["tenants"] = tenants
        return stats

    def healthy(self):
        """Liveness: the ingest loop is running and recently ticked,
        and the source's own poll loop (when it has one) is alive.
        The ``/healthz`` answer."""
        detail = {
            "loop_alive": self._thread.is_alive(),
            "loop_staleness_s": round(time.time() - self._last_tick,
                                      3),
            "source_alive": bool(getattr(self.source, "alive",
                                         lambda: True)()),
        }
        if hasattr(self.source, "last_activity"):
            detail["source_staleness_s"] = round(
                time.time() - self.source.last_activity(), 3)
        ok = (detail["loop_alive"] and detail["source_alive"]
              and detail["loop_staleness_s"] < self.stale_after_s
              and detail.get("source_staleness_s",
                             0.0) < self.stale_after_s)
        detail["ok"] = bool(ok)
        return detail

    def ready(self):
        """Readiness: healthy AND the device program is warm (an
        explicit warm-up ran, or at least one epoch published ok) —
        an autoscaler must not route work at a process that would
        stall its first request on a compile. The ``/readyz``
        answer."""
        h = self.healthy()
        detail = {"healthy": h["ok"], "warm": self._warm,
                  "stopping": self._stopping.is_set()}
        detail["ok"] = bool(h["ok"] and self._warm
                            and not detail["stopping"])
        return detail

    def report_snapshot(self):
        """The CURRENT RunReport — schema-valid mid-run (the
        ``/report`` answer)."""
        with self._lock:
            tally = dict(self._rec.tally)
            tally["tier_counts"] = dict(tally.get("tier_counts", {}))
            outcomes = list(self._rec.outcomes)
        return self._builder.snapshot(
            tally, outcomes, timeline=self.timeline.summary(),
            extra={**self._live_stats(),
                   "latency": self.latency_percentiles()},
            in_progress=not self._done.is_set(),
            slo=self.slo_snapshot())

    def state_snapshot(self):
        """Per-epoch status map (the ``/state`` answer):
        queued / in_flight / ok / quarantined / resumed /
        duplicate."""
        with self._lock:
            epochs = {k: dict(v) for k, v in self._states.items()}
        counts = {}
        for st in epochs.values():
            counts[st["status"]] = counts.get(st["status"], 0) + 1
        out = {"epochs": epochs, "counts": counts,
               "backlog": self.backlog(),
               "latency": self.latency_percentiles()}
        det = {"scanned": 0, "triggered": 0, "confirmed": 0}
        for st in epochs.values():
            d = st.get("detect")
            if not isinstance(d, dict):
                continue
            det["scanned"] += 1
            det["triggered"] += bool(d.get("triggered"))
            det["confirmed"] += bool(d.get("confirmed"))
        if det["scanned"]:
            out["detect"] = det
        return out

    def results(self):
        """Published results via the store's atomic read API."""
        return self.store.records()

    def export_trace(self, path):
        """Write the run-so-far stage spans (ingest/load/dispatch/
        fence/journal/publish tracks, per-epoch trace IDs) as
        Chrome-trace JSON."""
        return self.timeline.export_trace(path)

    @property
    def http_port(self):
        """Bound telemetry port (None when HTTP is disabled)."""
        return None if self._http is None else self._http.port
