"""Reference-name compatibility layer.

scintools uses camelCase/legacy names in ``ththmod``; this package
uses snake_case. Users migrating from the reference can
``from scintools_tpu import compat as thth`` (or import the specific
alias) and keep their call sites. Each alias maps to the function
listed in its docstring-of-origin:

===================  ==========================================
reference name        scintools_tpu implementation
===================  ==========================================
Eval_calc             thth.core.eval_calc
VLBI_chunk_retrieval  thth.retrieval.vlbi_chunk_retrieval
errString             thth.retrieval.err_string
errCalc               thth.search.err_calc
rotMos                thth.retrieval.rot_mos
rotInit               thth.retrieval.rot_init
rotFit / rotDer       thth.retrieval.refine_mosaic(mode='rot')
fullMos* family       thth.retrieval.refine_mosaic(mode='full')
svd_model             utils.misc.svd_model
===================  ==========================================

The fullMos/rot hand-derived gradient/Hessian entry points
(ththmod.py:1708-2310) are intentionally collapsed into
``refine_mosaic`` — autodiff supplies the derivatives.
"""

from .thth.core import (eval_calc as Eval_calc,  # noqa: N811
                        thth_map, thth_redmap, rev_map, modeler,
                        chisq_calc, two_curve_map, singularvalue_calc,
                        min_edges, arc_edges, len_arc, ext_find,
                        fft_axis, unit_checks)
from .thth.search import (single_search, single_search_thin, chi_par,
                          err_calc as errCalc)  # noqa: N811
from .thth.retrieval import (
    single_chunk_retrieval,
    vlbi_chunk_retrieval as VLBI_chunk_retrieval,  # noqa: N811
    mosaic, mask_func, gerchberg_saxton, calc_asymmetry,
    err_string as errString,  # noqa: N811
    rot_mos as rotMos,        # noqa: N811
    rot_init as rotInit,      # noqa: N811
    refine_mosaic)
from .thth.plots import plot_func
from .utils.misc import svd_model
from .ops.acf import autocorr_direct as autocorr  # scint_utils.py:67-84

__all__ = [
    "Eval_calc", "VLBI_chunk_retrieval", "errString", "errCalc",
    "rotMos", "rotInit", "refine_mosaic", "thth_map", "thth_redmap",
    "rev_map", "modeler", "chisq_calc", "two_curve_map",
    "singularvalue_calc", "min_edges", "arc_edges", "len_arc",
    "ext_find", "fft_axis", "unit_checks", "single_search",
    "single_search_thin", "chi_par", "single_chunk_retrieval",
    "mosaic", "mask_func", "gerchberg_saxton", "calc_asymmetry",
    "plot_func", "svd_model", "autocorr",
]


def rotFit(chunks, x0=None, maxiter=200):  # noqa: N802
    """rotFit/rotDer equivalent (ththmod.py:1773-1788): global
    per-chunk phase optimisation; derivatives via autodiff. ``x0``
    seeds the per-chunk phases as in the reference."""
    return refine_mosaic(chunks, mode="rot", maxiter=maxiter, x0=x0)


def fullMosFit(chunks, dspec, noise=None, maxiter=200):  # noqa: N802
    """fullMosFit/fullMosGrad/fullMosHess equivalent
    (ththmod.py:1990-2310): joint phase+amplitude fit against the
    dynamic spectrum; derivatives via autodiff."""
    return refine_mosaic(chunks, dspec=dspec, noise=noise, mode="full",
                         maxiter=maxiter)
