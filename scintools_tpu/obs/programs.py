"""Abstract program probes: trace every cached jit site WITHOUT
executing it.

PR 7 found the bench silently timing the staged ``sspec_thth`` path
(stamped 0.31x while the fused path measured 2.36x) — a *formulation*
regression invisible to source-level lints: the source was fine, the
wrong PROGRAM was compiled. The retrace registry (obs/retrace.py)
already names every cached jit site; this module gives each site an
**abstract probe** — a builder returning ``(fn, example_avals)`` — so
the program a site compiles can be traced to a ClosedJaxpr with
``jax.make_jaxpr`` on ``jax.ShapeDtypeStruct`` inputs: no device
execution, no compile, CPU-safe, a few hundred ms per site.

On top of the trace this module derives a per-site **program
summary** (input/output avals, recursive primitive multiset,
closure-constant census, observed buffer donation, the active
per-platform formulations, rough FLOP/byte cost estimates — exported
through :mod:`~scintools_tpu.obs.metrics` as
``program_flops_estimate{site=}`` / ``program_bytes_estimate{site=}``)
and a stable **fingerprint** hash of its structure. The jaxlint
program pass (tools/jaxlint/program.py, rules JP200–JP205) audits the
summaries against per-site contracts and gates fingerprints against a
committed baseline, so "the compiler quietly picked a different
program" fails tier-1 with a readable diff instead of shipping as a
silent 7x slowdown.

Determinism contract (what makes fingerprints comparable across
machines, device counts and test configurations):

- probes trace under an explicit x64 context chosen by their declared
  dtype ``policy`` (``'float32'`` → ``jax.experimental.disable_x64``),
  NOT the ambient flag — the test suite enables x64 globally while
  the CLI does not, and both must see the same program;
- mesh-sharded factories trace over a fixed-shape
  :func:`abstract_mesh` (``jax.sharding.AbstractMesh``, 2 data x
  2 seq), so per-shard aval shapes never depend on the host's real
  device count;
- probe geometry is small and FIXED inside each builder — the probe
  documents the program's structure, not a production shape.

Probes are registered NEXT to the site they audit (the module that
calls ``record_build``), via::

    @register_probe("ops.arc_profile", formulations=("ops.arc_profile_interp",))
    def _probe_arc_profile():
        ...
        return fn, (S((2, 16, 16), np.float32), S((2,), np.float32))

:data:`PROBE_MODULES` lists every module owning a site;
:func:`load_probes` imports them so registration happens on demand. A
new cached site whose module is missing from the list surfaces as a
JP200 probe-coverage finding — the failure is loud, never silent.
"""

from __future__ import annotations

import hashlib
import json
import math
import threading

import numpy as np

_LOCK = threading.Lock()
_PROBES = {}            # site -> ProbeSpec
_SUMMARIES = {}         # site -> summary dict (memoised per process)
_LOADED = False

#: modules that own ``record_build`` sites; :func:`load_probes`
#: imports these so their ``register_probe`` calls run. Forgetting a
#: new site's module here leaves its site probe-less, which the
#: jaxlint JP200 coverage rule turns into a tier-1 failure.
PROBE_MODULES = (
    "scintools_tpu.detect.bank",
    "scintools_tpu.detect.correlate",
    "scintools_tpu.detect.refine",
    "scintools_tpu.detect.trigger",
    "scintools_tpu.ops.normsspec",
    "scintools_tpu.ops.fitarc_device",
    "scintools_tpu.ops.scale",
    "scintools_tpu.ops.xfft",
    "scintools_tpu.fit.acf2d",
    "scintools_tpu.fit.batch",
    "scintools_tpu.mcmc.sampler",
    "scintools_tpu.mcmc.posterior",
    "scintools_tpu.thth.core",
    "scintools_tpu.thth.search",
    "scintools_tpu.thth.retrieval",
    "scintools_tpu.parallel.fft",
    "scintools_tpu.parallel.survey",
    "scintools_tpu.sim.simulation",
    "scintools_tpu.sim.factory",
    "scintools_tpu.sim.scenario",
)

_WIDE_DTYPES = ("float64", "complex128")


class ProbeSpec:
    """Contract + abstract-input builder for one jit-cache site.

    ``build()`` → ``(fn, args)`` where ``args`` are
    ``jax.ShapeDtypeStruct`` (or small concrete arrays) accepted by
    ``jax.make_jaxpr``; it must not execute device code. The
    remaining fields are the site's declared contract, read by the
    JP2xx rules."""

    __slots__ = ("site", "build", "policy", "hot", "donate",
                 "formulations", "const_budget", "f64_const_budget",
                 "path", "lineno", "doc")

    def __init__(self, site, build, policy="float32", hot=True,
                 donate=(), formulations=(), const_budget=512 * 1024,
                 f64_const_budget=4096):
        self.site = site
        self.build = build
        self.policy = policy
        self.hot = bool(hot)
        self.donate = tuple(int(i) for i in donate)
        self.formulations = tuple(formulations)
        self.const_budget = int(const_budget)
        self.f64_const_budget = int(f64_const_budget)
        code = getattr(build, "__code__", None)
        self.path = getattr(code, "co_filename", "<probe>")
        self.lineno = getattr(code, "co_firstlineno", 0)
        self.doc = (build.__doc__ or "").strip()


def register_probe(site, *, policy="float32", hot=True, donate=(),
                   formulations=(), const_budget=512 * 1024,
                   f64_const_budget=4096):
    """Decorator registering ``build`` as the abstract probe for
    ``site``.

    ``policy`` — dtype policy the traced program must satisfy
    ('float32' default: traced under ``disable_x64``, JP201 flags any
    f64/c128 aval and any wide closure constant above
    ``f64_const_budget`` bytes; 'float64': traced under
    ``enable_x64``, wide dtypes allowed). ``hot`` — hot-path site:
    JP203 forbids host-callback primitives. ``donate`` — argnums the
    factory donates WHEN the ``'jit.donate'`` formulation is active
    (JP204 checks the observed donation matches the formulation
    gate). ``formulations`` — backend.py formulation ops this program
    depends on; their resolved choices enter the fingerprint, so a
    formulation-table flip changes the hash even when primitives
    coincide. ``const_budget`` / ``f64_const_budget`` — JP202/JP201
    closure-constant byte thresholds."""

    def deco(build):
        spec = ProbeSpec(site, build, policy=policy, hot=hot,
                         donate=donate, formulations=formulations,
                         const_budget=const_budget,
                         f64_const_budget=f64_const_budget)
        with _LOCK:
            _PROBES[site] = spec
        return build

    return deco


def load_probes():
    """Import every :data:`PROBE_MODULES` module (idempotent) so all
    probe registrations run; returns the number of registered
    probes."""
    global _LOADED
    import importlib

    if not _LOADED:
        for mod in PROBE_MODULES:
            importlib.import_module(mod)
        _LOADED = True
    with _LOCK:
        return len(_PROBES)


def probes():
    """``{site: ProbeSpec}`` after loading the probe modules."""
    load_probes()
    with _LOCK:
        return dict(_PROBES)


def get_probe(site):
    load_probes()
    with _LOCK:
        return _PROBES.get(site)


def abstract_mesh():
    """The canonical fixed-shape mesh every sharded probe traces
    over: 2 'data' x 2 'seq' ``AbstractMesh`` — no real devices, so
    per-shard aval shapes (and therefore fingerprints) are identical
    on a 1-device CLI host, the 8-virtual-device test suite, and a
    TPU pod."""
    from jax.sharding import AbstractMesh

    from ..parallel.mesh import DATA_AXIS, SEQ_AXIS

    return AbstractMesh(((DATA_AXIS, 2), (SEQ_AXIS, 2)))


def _ensure_safe_platform():
    """Pin jax onto CPU when no backend is initialised yet (the
    tunneled-TPU plugin can hang a cold ``jnp.asarray``); a live
    non-CPU backend traces fine, so failures are ignored."""
    from ..backend import get_jax

    try:
        get_jax().config.update("jax_platforms", "cpu")
    # lint-ok: excepts: a live non-CPU backend rejects the update;
    # tracing works on it regardless, so the pin is best-effort
    except Exception:
        pass


def _policy_x64(policy):
    from jax.experimental import disable_x64, enable_x64

    return enable_x64() if policy == "float64" else disable_x64()


def trace_probe(spec):
    """ClosedJaxpr of ``spec``'s program: builder + ``make_jaxpr``
    under the probe's dtype-policy x64 context. No execution."""
    from ..backend import get_jax

    jax = get_jax()
    _ensure_safe_platform()
    with _policy_x64(spec.policy):
        fn, args = spec.build()
        return jax.make_jaxpr(fn)(*args)


def iter_eqns(closed_jaxpr):
    """Yield ``(eqn, scale)`` over the whole program, recursing into
    every sub-jaxpr (pjit/scan/while/cond/custom_* params).
    ``scale`` is the static execution-count multiplier accumulated
    from enclosing ``scan`` lengths (while-loop bodies count once —
    trip counts are dynamic, so derived costs are lower bounds)."""

    def walk(jaxpr, scale):
        for eqn in jaxpr.eqns:
            yield eqn, scale
            inner = scale
            if eqn.primitive.name == "scan":
                inner = scale * int(eqn.params.get("length", 1))
            for sub in _sub_jaxprs(eqn):
                yield from walk(sub, inner)

    yield from walk(closed_jaxpr.jaxpr, 1)


def _sub_jaxprs(eqn):
    for val in eqn.params.values():
        vals = val if isinstance(val, (list, tuple)) else (val,)
        for v in vals:
            if hasattr(v, "eqns"):
                yield v
            elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
                yield v.jaxpr


def gather_consts(closed_jaxpr):
    """Every closure constant in the program, including consts of
    nested ClosedJaxprs — ``make_jaxpr`` over a jitted callable hoists
    the captured arrays into the inner pjit jaxpr, so the top level
    alone usually reports zero."""
    out = list(closed_jaxpr.consts)

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            for val in eqn.params.values():
                vals = (val if isinstance(val, (list, tuple))
                        else (val,))
                for v in vals:
                    if hasattr(v, "consts") and hasattr(v, "jaxpr"):
                        out.extend(v.consts)
                        walk(v.jaxpr)
                    elif hasattr(v, "eqns"):
                        walk(v)

    walk(closed_jaxpr.jaxpr)
    return out


def _aval_str(aval):
    dtype = getattr(aval, "dtype", None)
    shape = getattr(aval, "shape", None)
    if dtype is None:
        return str(aval)
    dims = ",".join(str(d) for d in (shape or ()))
    return f"{dtype}[{dims}]"


def _aval_bytes(aval):
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return 0
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:  # jax extended dtypes (PRNG keys)
        itemsize = int(getattr(dtype, "itemsize", 4))
    return int(itemsize * np.prod(getattr(aval, "shape", ()) or (1,)))


def _eqn_flops(eqn):
    """Rough per-execution FLOP estimate for one equation: 2·N·K for
    contractions, 5·N·log2(n) for FFTs, the output element count for
    everything else — executable documentation of relative cost, not
    a performance model."""
    name = eqn.primitive.name
    out_numel = sum(int(np.prod(getattr(v.aval, "shape", ()) or (1,)))
                    for v in eqn.outvars
                    if hasattr(v.aval, "shape"))
    if name == "dot_general":
        (lc, _), _ = eqn.params["dimension_numbers"]
        lhs_shape = eqn.invars[0].aval.shape
        contracted = int(np.prod([lhs_shape[d] for d in lc]) or 1)
        return 2 * out_numel * contracted
    if name == "fft":
        n = max((max(v.aval.shape) for v in eqn.outvars
                 if getattr(v.aval, "shape", ())), default=2)
        return int(5 * out_numel * math.log2(max(n, 2)))
    return out_numel


def summary(site, refresh=False):
    """Memoised program summary for ``site`` (see module docstring).
    Raises KeyError for an unknown site, and propagates trace errors
    (the jaxlint pass converts both into loud findings)."""
    with _LOCK:
        if not refresh and site in _SUMMARIES:
            return _SUMMARIES[site]
    spec = get_probe(site)
    if spec is None:
        raise KeyError(f"no registered probe for site {site!r} "
                       f"(known: {sorted(_PROBES)})")
    doc = summarize(spec)
    with _LOCK:
        _SUMMARIES[site] = doc
    return doc


def summarize(spec):
    """Un-memoised summary of one :class:`ProbeSpec` (registered or
    not — test fixtures build throwaway specs)."""
    site = spec.site
    closed = trace_probe(spec)

    prims, n_eqns, flops, traffic = {}, 0, 0, 0
    wide_avals = set()
    for eqn, scale in iter_eqns(closed):
        n_eqns += 1
        name = eqn.primitive.name
        prims[name] = prims.get(name, 0) + 1
        flops += scale * _eqn_flops(eqn)
        traffic += scale * sum(_aval_bytes(v.aval) for v in eqn.outvars)
        for v in eqn.outvars:
            d = getattr(v.aval, "dtype", None)
            if d is not None and str(d) in _WIDE_DTYPES:
                wide_avals.add(_aval_str(v.aval))

    consts = []
    for c in gather_consts(closed):
        try:
            dt, nb = str(c.dtype), int(c.nbytes)
        except (AttributeError, TypeError):
            a = np.asarray(c)
            dt, nb = str(a.dtype), int(a.nbytes)
        consts.append((dt, nb))
    const_bytes = sum(nb for _, nb in consts)
    wide_const_bytes = sum(nb for dt, nb in consts
                           if dt in _WIDE_DTYPES)
    const_dtypes = {}
    for dt, nb in consts:
        const_dtypes[dt] = const_dtypes.get(dt, 0) + nb

    donated = []
    for eqn in closed.jaxpr.eqns:
        if eqn.primitive.name == "pjit":
            donated = [i for i, d in
                       enumerate(eqn.params.get("donated_invars", ()))
                       if d]
            break

    from ..backend import _FORMULATIONS, formulation

    forms = {}
    for op in spec.formulations:
        if op in _FORMULATIONS:
            forms[op] = formulation(op, platform="cpu")
        else:
            forms[op] = "<unregistered>"

    doc = {
        "site": site,
        "policy": spec.policy,
        "hot": spec.hot,
        "in_avals": [_aval_str(a) for a in closed.in_avals],
        "out_avals": [_aval_str(a) for a in closed.out_avals],
        "primitives": dict(sorted(prims.items())),
        "n_eqns": n_eqns,
        "wide_avals": sorted(wide_avals),
        "const_count": len(consts),
        "const_bytes": const_bytes,
        "const_dtypes": dict(sorted(const_dtypes.items())),
        "wide_const_bytes": wide_const_bytes,
        "max_const_bytes": max((nb for _, nb in consts), default=0),
        "donated": donated,
        "formulations": forms,
        "flops_est": int(flops),
        "bytes_est": int(traffic),
    }
    doc["fingerprint"] = fingerprint(doc)

    from . import metrics

    metrics.gauge(
        "program_flops_estimate",
        help="rough jaxpr FLOP estimate per cached-program site",
    ).labels(site=site).set(doc["flops_est"])  # lint-ok: metric-hygiene: bounded=site
    metrics.gauge(
        "program_bytes_estimate",
        help="rough jaxpr memory-traffic estimate per site",
    ).labels(site=site).set(doc["bytes_est"])  # lint-ok: metric-hygiene: bounded=site
    return doc


#: summary keys that define a program's identity — what the JP205
#: fingerprint hashes. Cost estimates and eqn counts stay OUT (they
#: are derived views; primitive counts already pin the structure).
FINGERPRINT_FIELDS = ("site", "policy", "in_avals", "out_avals",
                      "primitives", "const_count", "const_dtypes",
                      "donated", "formulations")


def fingerprint(doc):
    """Stable hex digest of a summary's identity fields."""
    payload = {k: doc.get(k) for k in FINGERPRINT_FIELDS}
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def fingerprint_report(sites=None):
    """``{"platform": ..., "sites": {site: fingerprint}}`` over
    ``sites`` (default: every registered probe) — the bench embeds
    this in its JSON so bench-to-bench diffs surface formulation
    flips explicitly (the PR-7 incident class)."""
    from ..backend import formulation_platform

    load_probes()
    names = sorted(sites) if sites is not None else sorted(_PROBES)
    out = {}
    for site in names:
        try:
            out[site] = summary(site)["fingerprint"]
        except Exception as e:  # one broken probe must not hide the
            out[site] = f"error:{type(e).__name__}"  # rest in a diff
    return {"platform": formulation_platform(), "sites": out}


def reset_summaries():
    """Drop the memoised summaries (tests that tamper with
    formulation overrides re-trace)."""
    with _LOCK:
        _SUMMARIES.clear()
