"""Live heartbeat for long survey runs.

A 10³-epoch archival survey on a quiet log is indistinguishable from
a hung one. The heartbeat emits one structured slog event
(``survey.heartbeat``) every N completed epochs or T seconds —
whichever comes first — carrying throughput, ETA, and the
quarantine/fallback tallies, so ``tail -f $SCINTOOLS_LOG | grep
heartbeat`` is a progress bar and a stall detector at once.

Wired into ``robust/runner.py``: ``run_survey(...,
heartbeat=True)`` (or a cadence dict ``{"every_n": 50,
"every_s": 60}``, or a prebuilt :class:`Heartbeat`). Off by default —
the cadence check itself is two comparisons per epoch, but the
*events* are user-visible output a library must not emit unasked.
"""

from __future__ import annotations

import os
import threading
import time

from ..utils import slog
from . import metrics as _metrics


class Heartbeat:
    """Cadence-gated progress emitter.

    ``beat(done, **stats)`` is called once per completed epoch (cheap
    when not due); an event is emitted when ``done`` advanced by
    ``every_n`` since the last emit OR ``every_s`` wall seconds
    passed, and always when ``force=True`` (the runner forces a final
    beat so every run ends with a fresh snapshot). ``total`` enables
    the ETA estimate. Returns the emitted record (or None).

    **Streaming mode** (``streaming=True`` — the serve daemon's
    mode): an open-ended stream has no meaningful epoch total, so a
    ``total``-derived ETA would be a bogus countdown to an arbitrary
    snapshot of the spool. Streaming beats therefore NEVER carry
    ``total``/``eta_s`` (even if a total was set) and instead report
    live stream health: throughput (``epochs_per_sec``) plus whatever
    ``stats_fn`` returns — the daemon supplies backlog depth and the
    ingest→publish latency percentiles there."""

    def __init__(self, every_n=25, every_s=30.0, total=None,
                 event="survey.heartbeat", streaming=False,
                 stats_fn=None):
        self.every_n = max(1, int(every_n))
        self.every_s = float(every_s)
        self.total = None if streaming else total
        self.event = event
        self.streaming = bool(streaming)
        self.stats_fn = stats_fn
        self.emitted = 0
        self._t0 = None
        self._last_t = None
        self._last_n = 0

    def beat(self, done, force=False, **stats):
        now = time.perf_counter()
        if self._t0 is None:
            self._t0 = self._last_t = now
        if force and self.emitted and self._last_n == done:
            return None               # cadence already emitted this n
        due = (force or done - self._last_n >= self.every_n
               or now - self._last_t >= self.every_s)
        if not due:
            return None
        elapsed = now - self._t0
        eps = done / elapsed if elapsed > 0 and done else None
        rec = {"done": int(done), "elapsed_s": round(elapsed, 3)}
        if self.streaming:
            rec["streaming"] = True
        if self.total is not None:
            rec["total"] = int(self.total)
        if eps is not None:
            rec["epochs_per_sec"] = round(eps, 3)
            if self.total is not None:
                rec["eta_s"] = round(
                    max(0, self.total - done) / eps, 1)
        if self.stats_fn is not None:
            rec.update(self.stats_fn())
        rec.update(stats)
        slog.log_event(self.event, **rec)  # obs-event-ok: survey.heartbeat
        self.emitted += 1
        self._last_t = now
        self._last_n = done
        return rec


# ---------------------------------------------------------------------
# file heartbeats — the fleet tier's cross-PROCESS liveness channel
# ---------------------------------------------------------------------
# A worker process can't slog into its coordinator's ring buffer; what
# it CAN do is atomically rewrite one small JSON file that the pod
# coordinator polls. Same guarantees as the queue's lease files: the
# write is temp+rename (a reader never sees a torn heartbeat) and
# staleness is judged against the reader's clock with the caller's
# skew allowance.

def write_heartbeat_file(path, now=None, writer=None, **fields):
    """Atomically (re)write a heartbeat file: ``fields`` plus a ``t``
    wall-clock stamp and the writing ``pid``. Returns the record.

    ``now`` overrides the stamp clock (a fleet worker stamps with
    its fsops clock, so injected skew is visible to the scanner) and
    ``writer`` overrides the atomic-write call (the fleet routes it
    through the retrying fsops seam)."""
    from ..parallel.checkpoint import atomic_write_json

    t = time.time() if now is None else float(now)
    rec = {"t": round(t, 3), "pid": os.getpid(), **fields}
    (writer or atomic_write_json)(os.fspath(path), rec)
    return rec


def read_heartbeat_file(path):
    """The last complete heartbeat record at ``path``, or None when
    missing/torn (a torn read is indistinguishable from a dead
    writer, and is treated the same way)."""
    import json

    try:
        with open(os.fspath(path)) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def heartbeat_age_s(rec, now=None, skew_s=0.0):
    """Seconds since the heartbeat was stamped (``inf`` for a missing
    record) — the staleness input for dead-worker detection.

    ``skew_s`` is the reader's clock-skew allowance, the SAME
    convention the lease stealer uses (fleet/queue.py:_expired): the
    stamp was written by the *worker's* clock and is compared
    against the *reader's*, so up to ``skew_s`` of the raw age is
    forgiven (floored at 0) — a skewed-but-alive worker is not
    reported stale (ISSUE 17 satellite)."""
    if rec is None:
        return float("inf")
    now = time.time() if now is None else now
    try:
        age = now - float(rec.get("t", 0.0))
    except (TypeError, ValueError):
        return float("inf")
    if skew_s:
        age = max(0.0, age - float(skew_s))
    return age


def scan_heartbeat_dir(hb_dir, cache=None):
    """mtime/size-gated incremental scan of one heartbeat directory.

    At O(100) workers a pod monitor that re-reads and re-parses every
    heartbeat file per tick spends its whole budget on JSON; the mtime
    gate makes a quiet tick O(listdir + stat) instead. ``cache`` is a
    dict carried between calls (mutated in place):
    ``{filename: ((mtime_ns, size), record)}``. Only files whose stat
    key changed since the cached entry are re-read; entries for
    removed files are dropped.

    Returns ``(records, stats)``: ``records`` is
    ``{worker_id: record}`` (the :func:`read_heartbeat_file` view),
    ``stats`` counts the scan — ``{"n", "read", "cached",
    "removed"}`` — which is how tests pin that an unchanged file is
    never re-read.
    """
    cache = {} if cache is None else cache
    records = {}
    read = cached = 0
    try:
        names = sorted(os.listdir(os.fspath(hb_dir)))
    except FileNotFoundError:
        removed = len(cache)
        cache.clear()
        return {}, {"n": 0, "read": 0, "cached": 0,
                    "removed": removed}
    seen = set()
    for name in names:
        if not name.endswith(".json"):
            continue
        seen.add(name)
        path = os.path.join(os.fspath(hb_dir), name)
        try:
            st = os.stat(path)
        except OSError:
            continue                     # vanished mid-scan
        key = (st.st_mtime_ns, st.st_size)
        held = cache.get(name)
        if held is not None and held[0] == key:
            rec = held[1]
            cached += 1
        else:
            rec = read_heartbeat_file(path)
            read += 1
            cache[name] = (key, rec)
        if rec is not None:
            records[name[:-5]] = rec
    removed = [n for n in cache if n not in seen]
    for n in removed:
        del cache[n]
    return records, {"n": len(records), "read": read,
                     "cached": cached, "removed": len(removed)}


class HeartbeatScanner:
    """Thread-safe wrapper around :func:`scan_heartbeat_dir` shared
    by the pod monitor loop and the telemetry-plane handler threads:
    one cache, one lock, cumulative read accounting, and per-scan
    staleness export — ``fleet_heartbeat_files_read_total`` (the
    incrementality witness) plus the age-distribution gauges
    ``fleet_heartbeat_age_max_seconds`` /
    ``fleet_heartbeat_age_p50_seconds`` (a dead worker shows up as a
    runaway max while the median stays at the beat cadence).

    ``skew_s`` forgives that much reader-vs-writer clock
    disagreement in every age (see :func:`heartbeat_age_s`) — the
    pod passes its lease ``skew_s`` so the staleness gauges and the
    ``/workers`` stale flags apply the same tolerance the lease
    stealer does."""

    def __init__(self, hb_dir, export_metrics=True, skew_s=0.0):
        self.hb_dir = os.fspath(hb_dir)
        self.export_metrics = bool(export_metrics)
        self.skew_s = float(skew_s)
        self._lock = threading.Lock()
        self._cache = {}
        self.scans = 0
        self.reads = 0
        self.last_stats = {}

    def scan(self, now=None):
        """One incremental pass; returns ``{worker_id: record}``."""
        with self._lock:
            records, stats = scan_heartbeat_dir(self.hb_dir,
                                                self._cache)
            self.scans += 1
            self.reads += stats["read"]
            self.last_stats = stats
        if self.export_metrics:
            _metrics.counter(
                "fleet_heartbeat_files_read_total",
                help="heartbeat files actually (re)read by "
                     "mtime-gated scans").inc(stats["read"])
            ages = sorted(heartbeat_age_s(r, now=now,
                                          skew_s=self.skew_s)
                          for r in records.values())
            if ages:
                _metrics.gauge(
                    "fleet_heartbeat_age_max_seconds",
                    help="staleness of the stalest worker heartbeat"
                ).set(round(ages[-1], 3))
                _metrics.gauge(
                    "fleet_heartbeat_age_p50_seconds",
                    help="median worker heartbeat staleness"
                ).set(round(ages[len(ages) // 2], 3))
        return records


def as_heartbeat(spec, total=None):
    """Normalise the runner's ``heartbeat`` argument: ``None``/False →
    no heartbeat; ``True`` → default cadence; a dict → cadence kwargs;
    a :class:`Heartbeat` → used as-is. ``total`` fills the epoch count
    when the spec didn't set one."""
    if spec is None or spec is False:
        return None
    if spec is True:
        return Heartbeat(total=total)
    if isinstance(spec, dict):
        kw = dict(spec)
        if not kw.get("streaming"):
            kw.setdefault("total", total)
        return Heartbeat(**kw)
    if isinstance(spec, Heartbeat):
        if spec.total is None and not spec.streaming:
            spec.total = total
        return spec
    raise TypeError(f"heartbeat must be None/bool/dict/Heartbeat, "
                    f"got {type(spec).__name__}")
