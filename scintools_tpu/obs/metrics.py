"""Thread-safe metrics registry: counters, gauges, histograms.

The survey stack grew ad-hoc probes — ``ACF2D_CACHE_STATS`` dicts,
bench-only timing splits, slog events carrying one-off numbers. This
module is the one place run-level quantities accumulate: epochs
processed/quarantined, fallback-tier transitions, journal bytes and
fsyncs, prefetch-queue depth, device-idle seconds, jit builds. Two
export views, both schema-stable:

- :meth:`MetricsRegistry.snapshot` — a JSON-able dict (consumed by
  the RunReport, obs/report.py);
- :meth:`MetricsRegistry.to_prometheus` — the Prometheus text
  exposition format, so a long survey can be scraped by dropping the
  string behind any HTTP handler.

Design constraints (docs/observability.md):

- **hot-path cheap** — one lock acquisition per update on the
  metric's own lock; the survey loop updates a handful of metrics per
  epoch, so the cost is microseconds against millisecond epochs (the
  bench gate pins <3% overhead with full observability on);
- **process-wide default** — :data:`REGISTRY` plus the module-level
  :func:`counter`/:func:`gauge`/:func:`histogram` helpers, mirroring
  how ``utils/slog.py`` exposes one process sink;
- **switchable** — :func:`set_enabled` (False) turns every update
  into a no-op without unwiring call sites, which is how the bench
  measures the observability-off baseline;
- **labels** — ``counter(name).labels(tier="jax_fused").inc()``
  keeps per-tier / per-site breakdowns under one metric name, exported
  Prometheus-style as ``name{tier="jax_fused"}``.

No dependencies beyond the standard library.
"""

from __future__ import annotations

import json
import re
import threading
import time

#: default histogram buckets [seconds]: spans the ~0.2 ms journal
#: fsync through multi-second epoch loads.
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0)

#: the Prometheus text exposition content type an HTTP scrape
#: endpoint must answer with (serve/http.py uses it; version 0.0.4 is
#: the text-format version every Prometheus server speaks).
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: process start (import time of the metrics module — the first
#: thing any scintools_tpu entry point pulls in), the epoch of the
#: ``process_uptime_seconds`` gauge.
_PROCESS_START = time.time()


def process_uptime():
    """Seconds since this process imported the metrics module."""
    return time.time() - _PROCESS_START


def touch_process_metrics(registry=None):
    """Refresh the process-level gauges (currently
    ``process_uptime_seconds``) in ``registry`` (default: the
    process-wide one). Scrape handlers call this immediately before
    rendering, so the exposition always carries a fresh uptime."""
    reg = registry if registry is not None else REGISTRY
    reg.gauge("process_uptime_seconds",
              help="seconds since process start").set(process_uptime())


def _label_key(labels):
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _full_name(name, key):
    if not key:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return f"{name}{{{inner}}}"


_FULL_NAME_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


def parse_full_name(full):
    """Split a snapshot full name (``name{a="1",b="2"}``) back into
    ``(name, {label: value})`` — the inverse of the exporter's
    :func:`_full_name`. An unparseable string round-trips as a bare
    name with no labels (aggregation must not crash on a foreign
    snapshot)."""
    m = _FULL_NAME_RE.match(str(full))
    if not m:
        return str(full), {}
    return m.group(1), dict(_LABEL_RE.findall(m.group(2) or ""))


def canonical_full_name(full):
    """Full name with its labels re-sorted into the registry's
    canonical order — the label-collision normaliser: two snapshots
    spelling ``m{a="1",b="2"}`` and ``m{b="2",a="1"}`` must fold into
    ONE sample, not two."""
    name, labels = parse_full_name(full)
    return _full_name(name, _label_key(labels))


def _le_sort_key(le):
    """Numeric sort key of a histogram ``le`` label (``+Inf`` last;
    an unparseable boundary sorts with ``+Inf`` rather than
    raising)."""
    try:
        return float("inf") if le == "+Inf" else float(le)
    except (TypeError, ValueError):
        return float("inf")


def bucket_deltas(buckets):
    """Cumulative ``{le: count}`` → per-bucket increments keyed by
    the same boundaries (ascending). The inverse of cumulation — the
    representation in which histograms from workers with DIFFERENT
    bucket sets merge exactly (each increment stays attached to its
    own upper boundary, so the merged cumulation over the boundary
    union is correct and monotone)."""
    out = {}
    prev = 0
    for le, n in sorted(dict(buckets).items(),
                        key=lambda kv: _le_sort_key(kv[0])):
        n = int(n)
        out[le] = out.get(le, 0) + n - prev
        prev = n
    return out


def cumulate_deltas(deltas):
    """Per-bucket increments → cumulative ``{le: count}`` over the
    boundaries present, ascending (``+Inf`` last)."""
    out = {}
    running = 0
    for le in sorted(deltas, key=_le_sort_key):
        running += int(deltas[le])
        out[le] = running
    return out


def merge_bucket_sets(a, b):
    """Merge two cumulative bucket dicts BY BOUNDARY: both are
    de-cumulated onto their own boundaries, the increments summed
    over the boundary union, and the result re-cumulated. Positional
    merging (the pre-ISSUE-13 behaviour) silently mis-bins when
    worker builds disagree on bucket sets; boundary merging is exact
    because a count ≤ b stays ≤ b in any superset of boundaries."""
    da = bucket_deltas(a)
    for le, n in bucket_deltas(b).items():
        da[le] = da.get(le, 0) + n
    return cumulate_deltas(da)


class _Metric:
    """Base: a named family of label-children sharing one lock."""

    kind = "untyped"

    def __init__(self, name, help="", registry=None):
        self.name = name
        self.help = help
        self._registry = registry
        self._lock = threading.Lock()
        self._children = {}

    def _enabled(self):
        return self._registry is None or self._registry.enabled

    def labels(self, **labels):
        """A child bound to one label set (created on first use)."""
        return _Child(self, _label_key(labels))

    def _items(self):
        with self._lock:
            return sorted(self._children.items())


class _Child:
    """View of one label set of a metric; forwards every update."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric, key):
        self._metric = metric
        self._key = key

    def inc(self, n=1):
        self._metric._inc(self._key, n)

    def dec(self, n=1):
        self._metric._inc(self._key, -n)

    def set(self, value):
        self._metric._set(self._key, value)

    def observe(self, value):
        self._metric._observe(self._key, value)

    @property
    def value(self):
        return self._metric._get(self._key)


class Counter(_Metric):
    """Monotonic counter. ``inc(n)``; negative increments rejected."""

    kind = "counter"

    def inc(self, n=1):
        self._inc((), n)

    def _inc(self, key, n):
        if not self._enabled():
            return
        if n < 0:
            raise ValueError("counters only go up (use a gauge)")
        with self._lock:
            self._children[key] = self._children.get(key, 0) + n

    def _get(self, key=()):
        with self._lock:
            return self._children.get(key, 0)

    @property
    def value(self):
        return self._get()


class Gauge(_Metric):
    """Last-write-wins instantaneous value; ``set``/``inc``/``dec``."""

    kind = "gauge"

    def set(self, value):
        self._set((), value)

    def inc(self, n=1):
        self._inc((), n)

    def dec(self, n=1):
        self._inc((), -n)

    def _set(self, key, value):
        if not self._enabled():
            return
        with self._lock:
            self._children[key] = float(value)

    def _inc(self, key, n):
        if not self._enabled():
            return
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + n

    def _get(self, key=()):
        with self._lock:
            return self._children.get(key, 0.0)

    @property
    def value(self):
        return self._get()


class Histogram(_Metric):
    """Fixed-bucket histogram: per-label ``count``/``sum`` plus
    cumulative bucket counts (Prometheus ``le`` convention, implicit
    ``+Inf`` bucket)."""

    kind = "histogram"

    def __init__(self, name, help="", registry=None,
                 buckets=DEFAULT_BUCKETS):
        super().__init__(name, help=help, registry=registry)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def observe(self, value):
        self._observe((), value)

    def _observe(self, key, value):
        if not self._enabled():
            return
        value = float(value)
        with self._lock:
            st = self._children.get(key)
            if st is None:
                st = self._children[key] = {
                    "count": 0, "sum": 0.0,
                    "bucket_counts": [0] * (len(self.buckets) + 1)}
            st["count"] += 1
            st["sum"] += value
            for i, b in enumerate(self.buckets):
                if value <= b:
                    st["bucket_counts"][i] += 1
                    break
            else:
                st["bucket_counts"][-1] += 1

    def _get(self, key=()):
        with self._lock:
            st = self._children.get(key)
            return dict(st) if st else {"count": 0, "sum": 0.0,
                                        "bucket_counts": []}

    def _cumulative(self, st):
        """``{le_label: cumulative_count}`` including ``+Inf``."""
        out = {}
        running = 0
        for b, n in zip(self.buckets, st["bucket_counts"]):
            running += n
            out[repr(b)] = running
        out["+Inf"] = running + st["bucket_counts"][-1]
        return out


class MetricsRegistry:
    """Process-wide metric store. ``counter``/``gauge``/``histogram``
    return the existing metric for a repeated name (same-kind check),
    so call sites never coordinate creation."""

    def __init__(self, enabled=True):
        self._lock = threading.Lock()
        self._metrics = {}
        self.enabled = bool(enabled)

    def set_enabled(self, flag):
        """Toggle every update under this registry (False = all
        ``inc``/``set``/``observe`` become no-ops; reads still work)."""
        self.enabled = bool(flag)

    def _get_or_create(self, cls, name, help, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help=help,
                                              registry=self, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name, help=""):
        return self._get_or_create(Counter, name, help)

    def gauge(self, name, help=""):
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name, help="", buckets=DEFAULT_BUCKETS):
        return self._get_or_create(Histogram, name, help,
                                   buckets=buckets)

    def reset(self):
        """Drop every metric (test isolation; the enabled flag is
        kept)."""
        with self._lock:
            self._metrics = {}

    def metrics(self):
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def snapshot(self):
        """JSON-able dict of everything:
        ``{"counters": {full_name: value}, "gauges": {...},
        "histograms": {full_name: {"count", "sum", "buckets"}}}``.
        Round-trips through ``json.dumps``/``loads`` unchanged (tests
        pin this)."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for m in self.metrics():
            for key, val in m._items():
                full = _full_name(m.name, key)
                if m.kind == "counter":
                    out["counters"][full] = val
                elif m.kind == "gauge":
                    out["gauges"][full] = val
                else:
                    out["histograms"][full] = {
                        "count": val["count"],
                        "sum": val["sum"],
                        "buckets": m._cumulative(val)}
        return out

    def to_prometheus(self):
        """Prometheus text exposition format: one ``# HELP`` AND one
        ``# TYPE`` header per metric family (HELP falls back to the
        metric name so scrapers that require the pair never see a
        bare family), histogram ``_bucket``/``_sum``/``_count``
        expansion. Serve it with :data:`PROMETHEUS_CONTENT_TYPE`."""
        lines = []
        for m in self.metrics():
            lines.append(f"# HELP {m.name} {m.help or m.name}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for key, val in m._items():
                if m.kind in ("counter", "gauge"):
                    lines.append(f"{_full_name(m.name, key)} {val}")
                    continue
                for le, n in m._cumulative(val).items():
                    lkey = key + (("le", le),)
                    lines.append(
                        f"{_full_name(m.name + '_bucket', lkey)} {n}")
                lines.append(
                    f"{_full_name(m.name + '_sum', key)} {val['sum']}")
                lines.append(
                    f"{_full_name(m.name + '_count', key)} "
                    f"{val['count']}")
        return "\n".join(lines) + ("\n" if lines else "")

    def to_json(self, **kw):
        return json.dumps(self.snapshot(), **kw)


def aggregate_snapshots(snapshots):
    """Fold N :meth:`MetricsRegistry.snapshot` dicts (e.g. one per
    fleet worker process, shipped through their heartbeat files) into
    one pod-level view with the same schema: counters and histogram
    counts/sums/buckets SUM across workers; gauges sum too — the
    per-worker gauges this is used on (backlog, queue depth) are
    additive, and a pod-level "last writer wins" would be
    meaningless across processes. Malformed entries are skipped (a
    heartbeat from an older worker build must not kill the pod
    aggregation).

    Two cross-build hazards are normalised away (ISSUE 13):

    - **label collisions** — full names are canonicalised
      (:func:`canonical_full_name`) before summing, so two snapshots
      spelling the same label set in a different order fold into one
      sample;
    - **mismatched histogram buckets** — bucket dicts merge BY
      BOUNDARY (:func:`merge_bucket_sets`), never positionally, so
      workers built with different bucket tables still produce a
      monotone, exactly-binned merged histogram.
    """
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    for snap in snapshots:
        if not isinstance(snap, dict):
            continue
        for kind in ("counters", "gauges"):
            for name, val in dict(snap.get(kind) or {}).items():
                if not isinstance(val, (int, float)):
                    continue
                name = canonical_full_name(name)
                out[kind][name] = out[kind].get(name, 0) + val
        for name, st in dict(snap.get("histograms") or {}).items():
            if not isinstance(st, dict):
                continue
            name = canonical_full_name(name)
            agg = out["histograms"].setdefault(
                name, {"count": 0, "sum": 0.0, "buckets": {}})
            agg["count"] += int(st.get("count", 0))
            agg["sum"] += float(st.get("sum", 0.0))
            agg["buckets"] = merge_bucket_sets(
                agg["buckets"], dict(st.get("buckets") or {}))
    return out


#: the process-wide default registry every library call site uses.
REGISTRY = MetricsRegistry()


def counter(name, help=""):
    return REGISTRY.counter(name, help=help)


def gauge(name, help=""):
    return REGISTRY.gauge(name, help=help)


def histogram(name, help="", buckets=DEFAULT_BUCKETS):
    return REGISTRY.histogram(name, help=help, buckets=buckets)


def set_enabled(flag):
    REGISTRY.set_enabled(flag)


def enabled():
    return REGISTRY.enabled


def snapshot():
    return REGISTRY.snapshot()
