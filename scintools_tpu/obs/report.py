"""End-of-run RunReport: one machine-readable artifact per survey.

A survey run currently scatters its story across the journal, the
slog stream, and whatever the caller printed. The RunReport collects
the run's outcome into one JSON document (``run_report.json``) plus a
human-rendered markdown table (``run_report.md``), written into the
run's ``workdir`` by ``robust/runner.py:run_survey`` /
``run_survey_batched`` (and therefore by
``dynspec.py:run_psrflux_survey``) and consumed by bench.py, which
schema-validates it in-run.

Schema v1 (validated by :func:`validate_run_report`, pinned in
tier-1):

=================  =======  ==================================
field              type     meaning
=================  =======  ==================================
schema_version     int      always 1
runner             str      producing entry point
generated_t        float    unix time of assembly
n_epochs           int      epochs scanned (incl. resumed)
n_ok               int      fresh successful epochs
n_quarantined      int      quarantined (incl. resumed-quar.)
n_resumed          int      taken verbatim from the journal
retries            int      total failed ladder attempts
tier_counts        dict     fresh completions per tier
wall_s             float    wall-clock of the run loop
epochs_per_sec     float?   fresh epochs / wall_s (None if 0)
quarantined        list     per-epoch {epoch, error_class,
                            error, tier}
timeline           dict?    StageTimeline.summary() or None
jit_builds         dict     per-site {builds, distinct_keys}
metrics            dict?    MetricsRegistry.snapshot() or None
slo                dict     {global, tenants, sites}: global +
                            per-tenant latency p50/p95 and the
                            cost ledger's per-site steady
                            medians (ISSUE 20)
=================  =======  ==================================

Optional extras (``n_batches`` from the batched runner, caller
``extra`` fields, the streaming daemon's ``in_progress``/
``latency``/``backlog``) ride along unvalidated.

The report is **incrementally buildable**: :class:`RunReportBuilder`
produces schema-valid snapshots of a run that is still in flight —
the serving daemon (serve/daemon.py) updates one per published epoch
and its ``/report`` endpoint serves the current snapshot, so the
report is a live surface rather than a write-at-exit artifact (the
batch runners keep calling :func:`build_run_report` once at return).
"""

from __future__ import annotations

import json
import os
import time

from ..utils import slog
from . import ledger as _ledger
from . import metrics as _metrics
from . import retrace as _retrace

SCHEMA_VERSION = 1

_REQUIRED = {
    "schema_version": int,
    "runner": str,
    "generated_t": (int, float),
    "n_epochs": int,
    "n_ok": int,
    "n_quarantined": int,
    "n_resumed": int,
    "retries": int,
    "tier_counts": dict,
    "wall_s": (int, float),
    "epochs_per_sec": (int, float, type(None)),
    "quarantined": list,
    "timeline": (dict, type(None)),
    "jit_builds": dict,
    "metrics": (dict, type(None)),
    "slo": dict,
}


def _slo_block(slo=None):
    """Normalise a caller-supplied SLO view into the schema's
    ``slo`` block; the ledger's per-site steady medians fill in when
    the caller didn't supply ``sites`` (batch runners have no
    per-tenant latency, but every runner has a cost ledger)."""
    slo = dict(slo or {})
    sites = slo.get("sites")
    if sites is None:
        sites = _ledger.LEDGER.steady_site_medians()
    return {
        "global": dict(slo.get("global")
                       or {"p50_s": None, "p95_s": None, "n": 0}),
        "tenants": dict(slo.get("tenants") or {}),
        "sites": dict(sites),
    }


def build_run_report(summary, outcomes=(), wall_s=0.0, timeline=None,
                     runner="run_survey", extra=None, slo=None):
    """Assemble the report dict from the runner's tally ``summary``,
    its ordered ``outcomes`` (:class:`EpochOutcome`-like, for the
    quarantine detail), the run's wall seconds, and an optional
    timeline summary dict. Metrics and jit-build accounting are read
    from the process-wide registries; ``slo`` — the serving daemon's
    latency SLO view (:meth:`SurveyService.slo_snapshot`), defaulted
    to a ledger-only block for batch runners."""
    quarantined = []
    for o in outcomes:
        status = getattr(o, "status", None)
        error_cls = getattr(o, "error_class", "")
        if status == "quarantined" or (status == "resumed"
                                       and error_cls):
            quarantined.append({
                "epoch": str(getattr(o, "epoch", "?")),
                "error_class": error_cls,
                "error": getattr(o, "error", ""),
                "tier": getattr(o, "tier", "")})
    fresh = max(0, int(summary.get("n_epochs", 0))
                - int(summary.get("n_resumed", 0)))
    eps = round(fresh / wall_s, 3) if wall_s > 0 and fresh else None
    rep = {
        "schema_version": SCHEMA_VERSION,
        "runner": str(runner),
        "generated_t": round(time.time(), 3),
        "n_epochs": int(summary.get("n_epochs", 0)),
        "n_ok": int(summary.get("n_ok", 0)),
        "n_quarantined": int(summary.get("n_quarantined", 0)),
        "n_resumed": int(summary.get("n_resumed", 0)),
        "retries": int(summary.get("retries", 0)),
        "tier_counts": {str(k): int(v) for k, v in
                        dict(summary.get("tier_counts", {})).items()},
        "wall_s": round(float(wall_s), 4),
        "epochs_per_sec": eps,
        "quarantined": quarantined,
        "timeline": dict(timeline) if timeline else None,
        "jit_builds": _retrace.snapshot(),
        "metrics": (_metrics.REGISTRY.snapshot()
                    if _metrics.REGISTRY.enabled else None),
        "slo": _slo_block(slo),
    }
    if "n_batches" in summary:
        rep["n_batches"] = int(summary["n_batches"])
    if extra:
        rep.update(extra)
    return rep


class RunReportBuilder:
    """Mid-run RunReport snapshots for a long-lived service.

    ``build_run_report`` needs the run's final wall seconds, which a
    still-running daemon does not have; the builder carries the run's
    start instant instead and stamps each snapshot with the elapsed
    wall time so far, plus an ``in_progress`` marker and any live
    ``extra`` fields (backlog, latency percentiles). Every snapshot
    passes :func:`validate_run_report` — a scraper polling
    ``/report`` sees the same schema the end-of-run artifact has.

    >>> builder = RunReportBuilder(runner="serve_survey")
    >>> rep = builder.snapshot(rec.tally, rec.outcomes,
    ...                        extra={"backlog": 3})
    >>> builder.finalize(workdir, rec.tally, rec.outcomes)
    """

    def __init__(self, runner="serve_survey", extra=None):
        self.runner = str(runner)
        self.extra = dict(extra or {})
        self._t0 = time.perf_counter()

    def wall_s(self):
        return time.perf_counter() - self._t0

    def snapshot(self, summary, outcomes=(), timeline=None,
                 extra=None, in_progress=True, slo=None):
        """A schema-valid report of the run SO FAR (validated before
        it is returned — a malformed snapshot must fail here, not in
        the scraper)."""
        merged = {**self.extra, **(extra or {}),
                  "in_progress": bool(in_progress)}
        return validate_run_report(build_run_report(
            summary, outcomes, wall_s=self.wall_s(),
            timeline=timeline, runner=self.runner, extra=merged,
            slo=slo))

    def finalize(self, workdir, summary, outcomes=(), timeline=None,
                 extra=None, name="run_report", slo=None):
        """Write the closing snapshot (``in_progress: false``) as the
        usual ``run_report.json``/``.md`` pair; returns the JSON
        path."""
        return write_run_report(
            workdir, self.snapshot(summary, outcomes,
                                   timeline=timeline, extra=extra,
                                   in_progress=False, slo=slo),
            name=name)


def validate_run_report(report):
    """Schema-v1 validation (the tier-1 gate and bench.py share it):
    required fields present with the right types, tier counts and
    quarantine entries well-formed, JSON-serialisable. Raises
    :class:`ValueError` listing every problem; returns the report."""
    problems = []
    if not isinstance(report, dict):
        raise ValueError("run report must be a dict")
    for key, typ in _REQUIRED.items():
        if key not in report:
            problems.append(f"missing field {key!r}")
        elif not isinstance(report[key], typ):
            problems.append(
                f"field {key!r} has type "
                f"{type(report[key]).__name__}")
    if isinstance(report.get("schema_version"), int) \
            and report["schema_version"] != SCHEMA_VERSION:
        problems.append(
            f"schema_version {report['schema_version']} != "
            f"{SCHEMA_VERSION}")
    for k, v in dict(report.get("tier_counts") or {}).items():
        if not isinstance(v, int):
            problems.append(f"tier_counts[{k!r}] not an int")
    for i, q in enumerate(report.get("quarantined") or []):
        if not isinstance(q, dict) or "epoch" not in q \
                or "error_class" not in q:
            problems.append(f"quarantined[{i}] malformed: {q!r}")
    slo = report.get("slo")
    if isinstance(slo, dict):
        for part, typ in (("global", dict), ("tenants", dict),
                          ("sites", dict)):
            if not isinstance(slo.get(part), typ):
                problems.append(f"slo[{part!r}] missing or not a "
                                f"{typ.__name__}")
        for field in ("p50_s", "p95_s", "n"):
            if isinstance(slo.get("global"), dict) \
                    and field not in slo["global"]:
                problems.append(f"slo['global'] missing {field!r}")
        if isinstance(slo.get("tenants"), dict):
            for t, pct in slo["tenants"].items():
                if not isinstance(pct, dict) or "p95_s" not in pct:
                    problems.append(f"slo['tenants'][{t!r}] malformed")
    try:
        json.dumps(report)
    except (TypeError, ValueError) as e:
        problems.append(f"not JSON-serialisable: {e}")
    if problems:
        raise ValueError("invalid run report: " + "; ".join(problems))
    return report


def render_markdown(report):
    """Human view of the report: a summary table, the per-tier
    completions, and (when any) the quarantine list."""
    r = report
    lines = [
        f"# Survey run report ({r['runner']})", "",
        "| quantity | value |", "|---|---|",
        f"| epochs | {r['n_epochs']} |",
        f"| ok | {r['n_ok']} |",
        f"| quarantined | {r['n_quarantined']} |",
        f"| resumed | {r['n_resumed']} |",
        f"| retries | {r['retries']} |",
        f"| wall_s | {r['wall_s']} |",
        f"| epochs/s | {r['epochs_per_sec']} |",
    ]
    tl = r.get("timeline") or {}
    if tl:
        lines += [f"| overlap_frac | {tl.get('overlap_frac')} |",
                  f"| device_idle_s | {tl.get('device_idle_s')} |"]
    if r.get("tier_counts"):
        lines += ["", "## Completions per tier", "",
                  "| tier | epochs |", "|---|---|"]
        lines += [f"| {t} | {n} |"
                  for t, n in r["tier_counts"].items()]
    if r.get("jit_builds"):
        lines += ["", "## Compiled programs", "",
                  "| site | builds | distinct keys |", "|---|---|---|"]
        lines += [f"| {s} | {d['builds']} | {d['distinct_keys']} |"
                  for s, d in r["jit_builds"].items()]
    slo = r.get("slo") or {}
    g = slo.get("global") or {}
    if g.get("n"):
        lines += ["", "## Latency SLO", "",
                  "| tenant | p50_s | p95_s | n |", "|---|---|---|---|",
                  f"| (all) | {g.get('p50_s')} | {g.get('p95_s')} | "
                  f"{g.get('n')} |"]
        lines += [f"| {t} | {p.get('p50_s')} | {p.get('p95_s')} | "
                  f"{p.get('n')} |"
                  for t, p in (slo.get("tenants") or {}).items()]
    if slo.get("sites"):
        lines += ["", "## Program cost ledger (steady medians)", "",
                  "| site | median_s |", "|---|---|"]
        lines += [f"| {s} | {m} |"
                  for s, m in slo["sites"].items()]
    if r["quarantined"]:
        lines += ["", "## Quarantined epochs", "",
                  "| epoch | error class | error |", "|---|---|---|"]
        lines += [f"| {q['epoch']} | {q['error_class']} | "
                  f"{str(q['error'])[:80]} |"
                  for q in r["quarantined"]]
    return "\n".join(lines) + "\n"


def write_run_report(workdir, report, name="run_report"):
    """Write ``<workdir>/<name>.json`` (+ ``.md``) atomically (write
    to a temp name, ``os.replace``), emit a ``survey.run_report`` slog
    event, and return the JSON path. Never raises into the survey —
    a report that cannot be written is a warning, the journal already
    holds the results."""
    json_path = os.path.join(os.fspath(workdir), name + ".json")
    try:
        for suffix, text in ((".json", json.dumps(report, indent=1)),
                             (".md", render_markdown(report))):
            path = os.path.join(os.fspath(workdir), name + suffix)
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                fh.write(text)
            os.replace(tmp, path)
    except OSError as e:
        import sys

        print(f"Warning: run report write failed ({e})",
              file=sys.stderr)
        return None
    slog.log_event("survey.run_report", path=json_path,
                   n_ok=report.get("n_ok"),
                   n_quarantined=report.get("n_quarantined"))
    return json_path
