"""Retrace/compile accounting: one registry over every cached jit
entry point.

A silent per-call retrace is the regression that has bitten this repo
twice (thth fused search pre-PR-1, ``fit/batch.py:make_acf1d_batch``
pre-PR-4: a fresh ``jax.jit`` wrapper per epoch cost ~0.3 s/epoch on
the CPU host). The existing probes — ``ACF2D_CACHE_STATS``,
``FUSED_CACHE_STATS`` — are per-module dicts a test must know about
individually. This module generalises the pattern:

- every cached program factory calls :func:`record_build` exactly on
  a cache MISS (``thth.core.keyed_jit_cache(site=...)`` — including
  the retrieval sites ``thth.retrieval_grid`` /
  ``thth.retrieval_vlbi`` / ``thth.mosaic`` —
  ``fit/acf2d.py:_batch_program``, ``fit/batch.py:make_acf1d_batch``,
  the ``parallel/survey.py`` sharded-step factories incl.
  ``parallel.retrieval_sharded``);
- :func:`compile_counts` / :func:`snapshot` expose per-site build
  counts and distinct-geometry counts (also mirrored into the metrics
  registry as ``jit_builds_total{site=...}``, so the RunReport and
  Prometheus export carry them);
- :func:`retrace_guard` is the tier-1 regression gate: wrap a block
  that repeats an already-compiled workload and it raises
  :class:`RetraceRegression` if ANY site (or a named subset) built a
  new program.

Keys are stored as hashes, never retained — geometry keys embed whole
``tau``/``fd`` grids as bytes and must not be kept alive here.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

_LOCK = threading.Lock()
_SITES = {}     # site -> {"builds": int, "keys": set of key hashes}


class RetraceRegression(AssertionError):
    """A workload that should have hit the jit cache built new
    programs (see :func:`retrace_guard`)."""


def record_build(site, key=None, seconds=None):
    """Count one program build at ``site`` (call ONLY on a cache
    miss). ``key`` — the cache key, hashed for the distinct-geometry
    count and then dropped. ``seconds`` — the build's wall time when
    the caller measured it (forwarded to the program cost ledger as
    a ``compile`` sample; sites whose ``jax.jit`` compiles lazily
    record it from the first invocation instead — see
    ``thth.core.keyed_jit_cache``)."""
    site = str(site)
    with _LOCK:
        rec = _SITES.setdefault(site, {"builds": 0, "keys": set()})
        rec["builds"] += 1
        if key is not None:
            try:
                rec["keys"].add(hash(key))
            except TypeError:
                rec["keys"].add(hash(repr(key)))
    from . import metrics

    metrics.counter(
        "jit_builds_total",
        help="compiled-program builds per jit-cache site",
    ).labels(site=site).inc()  # lint-ok: metric-hygiene: bounded=site
    if seconds is not None:
        from . import ledger

        ledger.record(site, seconds, "compile")


def compile_counts():
    """``{site: build_count}`` over every site seen this process."""
    with _LOCK:
        return {s: rec["builds"] for s, rec in sorted(_SITES.items())}


def snapshot():
    """JSON-able per-site view: builds + distinct geometry keys."""
    with _LOCK:
        return {s: {"builds": rec["builds"],
                    "distinct_keys": len(rec["keys"])}
                for s, rec in sorted(_SITES.items())}


def reset():
    with _LOCK:
        _SITES.clear()


@contextmanager
def retrace_guard(sites=None, allow=0):
    """Regression gate: raise :class:`RetraceRegression` if the block
    builds more than ``allow`` new programs (on ``sites`` — an
    iterable of site names — or anywhere when None).

    >>> fn(batch)                      # warm: compiles once
    >>> with retrace_guard():
    ...     fn(batch)                  # must hit every cache

    Yields a dict filled with the per-site new-build counts on exit
    (useful for reporting even when the guard passes)."""
    want = set(map(str, sites)) if sites is not None else None
    before = compile_counts()
    grew = {}
    try:
        yield grew
    finally:
        after = compile_counts()
        for site, n in after.items():
            if want is not None and site not in want:
                continue
            delta = n - before.get(site, 0)
            if delta > 0:
                grew[site] = delta
        total = sum(grew.values())
        if total > int(allow):
            raise RetraceRegression(
                f"{total} unexpected jit program build(s) "
                f"(allow={allow}): {grew} — a cached entry point is "
                f"retracing per call")
