"""Unified observability layer (ISSUE 5 tentpole).

One subsystem replacing the scattered probes that grew through PRs
1–4 (``verbose`` prints, per-module ``*_CACHE_STATS`` dicts,
bench-only timing splits):

- :mod:`~scintools_tpu.obs.metrics` — thread-safe process-wide
  metrics registry (counters/gauges/histograms, JSON snapshot +
  Prometheus text export) fed by the survey runner, the pipeline
  primitives, the fallback ladder, and the journal;
- :mod:`~scintools_tpu.obs.trace` — Chrome-trace/Perfetto JSON export
  of ``StageTimeline`` spans with per-epoch trace IDs
  (``StageTimeline.export_trace``);
- :mod:`~scintools_tpu.obs.retrace` — per-site jit build accounting
  over every cached program factory, with :func:`retrace_guard` as
  the tier-1 retrace-regression gate;
- :mod:`~scintools_tpu.obs.programs` — abstract program probes over
  the same sites: no-execution jaxpr tracing, per-site program
  summaries/FLOP estimates, and the stable fingerprints the jaxlint
  JP2xx program pass (tools/jaxlint/program.py) gates in tier-1;
- :mod:`~scintools_tpu.obs.heartbeat` — cadence-gated live progress
  events for long runs, plus the cross-process file-heartbeat channel
  with its mtime-gated incremental directory scan
  (:class:`~scintools_tpu.obs.heartbeat.HeartbeatScanner`);
- :mod:`~scintools_tpu.obs.ledger` — the program cost ledger
  (ISSUE 20): persistent per-(site, platform, shape, formulation)
  compile/steady wall-time accounting, CRC-JSONL persistence per
  workdir, the ``/ledger`` endpoint's data source, and the measured
  cost model the formulation tables and the serve batch controller's
  gain scheduling read back;
- :mod:`~scintools_tpu.obs.report` — the end-of-run ``run_report``
  artifact (JSON + markdown), schema-validated;
- :mod:`~scintools_tpu.obs.plane` — the pod-level telemetry plane
  (ISSUE 13): the streaming per-worker snapshot merger, Prometheus
  rendering of merged snapshots, and the one-port HTTP surface over
  a whole fleet (``/metrics`` ``/state`` ``/report`` ``/workers``).

See docs/observability.md for the event catalog, metric names, the
trace-viewer walkthrough, and the RunReport schema.
"""

from . import (heartbeat, ledger, metrics, plane,  # noqa: F401
               programs, report, retrace, trace)
from .heartbeat import (Heartbeat, HeartbeatScanner,  # noqa: F401
                        as_heartbeat, scan_heartbeat_dir)
from .ledger import (LEDGER, ProgramLedger)  # noqa: F401
from .metrics import (REGISTRY, Counter, Gauge, Histogram,  # noqa: F401
                      MetricsRegistry, aggregate_snapshots, counter,
                      gauge, histogram, set_enabled)
from .plane import (SnapshotMerger, TelemetryPlane,  # noqa: F401
                    snapshot_to_prometheus)
from .report import (RunReportBuilder, build_run_report,  # noqa: F401
                     render_markdown, validate_run_report,
                     write_run_report)
from .retrace import (RetraceRegression, compile_counts,  # noqa: F401
                      record_build, retrace_guard)
from .trace import (chrome_trace_events,  # noqa: F401
                    load_trace_fragments, merge_traces,
                    validate_chrome_trace, write_chrome_trace,
                    write_merged_trace)
