"""Pod-level telemetry plane: ONE merged observability surface over
N worker/daemon processes (ISSUE 13 tentpole; ROADMAP items 1d + 2).

A fleet run (fleet/pod.py) and a shared-spool daemon fleet
(serve/daemon.py × N) both used to expose telemetry per process: one
``/metrics`` per daemon, one heartbeat file per worker, one Chrome
trace per process. The real-time search pipelines this repo models
on (arXiv:1711.10855) hold their latency budgets only when the
operator sees the WHOLE fleet's health from one scrape; this module
is the process-agnostic half of that surface:

- :class:`SnapshotMerger` — a streaming, incremental generalisation
  of :func:`obs.metrics.aggregate_snapshots`: per-worker metric
  snapshots (shipped through heartbeat files) fold into one pod view
  by DELTA, so an unchanged worker costs a dict compare, not a
  re-aggregation of the whole fleet. Counters and histograms sum
  pod-wide (histograms by bucket boundary); gauges keep a ``worker``
  label — a pod-level "last writer wins" across processes is
  meaningless, per-worker rows are the operable view;
- :func:`snapshot_to_prometheus` — Prometheus text rendering of any
  snapshot-shaped dict (``# HELP`` + ``# TYPE`` per family,
  histogram ``_bucket``/``_sum``/``_count`` expansion), so the
  merged view is scrapeable with the same conformance the
  per-process registry export has;
- :class:`TelemetryPlane` — the HTTP surface: the serve tier's
  :class:`~scintools_tpu.serve.http.TelemetryServer` with the plane
  route table (``/metrics``, ``/state``, ``/report``, ``/workers``)
  over a duck-typed *view* object (fleet/telemetry.py:PodTelemetry
  is the fleet pod's view).

docs/observability.md "Fleet observability plane" is the operator
walkthrough.
"""

from __future__ import annotations

import threading

from . import metrics as _metrics


def _with_label(full, key, value):
    """Inject ``key="value"`` into a snapshot full name. An existing
    label under ``key`` (collision: the source process already
    labelled by worker) is preserved under ``<key>_src`` so neither
    attribution is lost."""
    name, labels = _metrics.parse_full_name(full)
    if key in labels:
        labels[f"{key}_src"] = labels.pop(key)
    labels[key] = str(value)
    return _metrics._full_name(name, _metrics._label_key(labels))


class SnapshotMerger:
    """Incrementally maintained pod-level merge of per-worker metric
    snapshots.

    ``update(worker, snapshot)`` folds ONLY that worker's change: the
    worker's previous contribution is subtracted (counters and
    histogram bucket deltas) and the new one added, so a monitor tick
    over O(100) workers whose heartbeats mostly didn't change does
    O(changed) work — the streaming generalisation of the one-shot
    :func:`obs.metrics.aggregate_snapshots`. A worker whose snapshot
    is unchanged is recognised by equality and skipped.

    ``merged()`` returns the aggregate in snapshot schema:

    - ``counters`` / ``histograms`` — summed pod-wide (label sets
      canonicalised, histogram buckets merged by boundary);
    - ``gauges`` — per-worker families: every sample carries a
      ``worker`` label (collisions renamed ``worker_src``).
    """

    def __init__(self, worker_label="worker"):
        self.worker_label = worker_label
        self._lock = threading.Lock()
        self._held = {}        # worker -> canonicalised snapshot
        self._counters = {}    # full -> running pod sum
        self._hists = {}       # full -> {"count","sum","deltas"}
        self._gauges = {}      # worker -> {full: value}
        self.updates = 0
        self.skipped = 0

    @staticmethod
    def _canonical(snapshot):
        """One-worker snapshot with full names canonicalised and
        malformed entries dropped (a heartbeat from an older worker
        build must not poison the pod view)."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        if not isinstance(snapshot, dict):
            return out
        for kind in ("counters", "gauges"):
            for name, val in dict(snapshot.get(kind) or {}).items():
                if isinstance(val, (int, float)):
                    out[kind][_metrics.canonical_full_name(name)] = val
        for name, st in dict(snapshot.get("histograms") or {}).items():
            if not isinstance(st, dict):
                continue
            out["histograms"][_metrics.canonical_full_name(name)] = {
                "count": int(st.get("count", 0)),
                "sum": float(st.get("sum", 0.0)),
                "buckets": {str(k): int(v) for k, v in
                            dict(st.get("buckets") or {}).items()},
            }
        return out

    def update(self, worker, snapshot):
        """Fold ``worker``'s latest snapshot; returns True when it
        changed the merge (False: identical to the held one)."""
        worker = str(worker)
        snap = self._canonical(snapshot)
        with self._lock:
            held = self._held.get(worker)
            if held == snap:
                self.skipped += 1
                return False
            old = held or {"counters": {}, "gauges": {},
                           "histograms": {}}
            for name, val in old["counters"].items():
                self._counters[name] = self._counters.get(name, 0) \
                    - val
            for name, val in snap["counters"].items():
                self._counters[name] = self._counters.get(name, 0) \
                    + val
            for name, st in old["histograms"].items():
                agg = self._hists.get(name)
                if agg is None:
                    continue
                agg["count"] -= st["count"]
                agg["sum"] -= st["sum"]
                for le, n in _metrics.bucket_deltas(
                        st["buckets"]).items():
                    agg["deltas"][le] = agg["deltas"].get(le, 0) - n
            for name, st in snap["histograms"].items():
                agg = self._hists.setdefault(
                    name, {"count": 0, "sum": 0.0, "deltas": {}})
                agg["count"] += st["count"]
                agg["sum"] += st["sum"]
                for le, n in _metrics.bucket_deltas(
                        st["buckets"]).items():
                    agg["deltas"][le] = agg["deltas"].get(le, 0) + n
            self._gauges[worker] = snap["gauges"]
            self._held[worker] = snap
            self.updates += 1
        return True

    def workers(self):
        with self._lock:
            return sorted(self._held)

    def merged(self):
        """The pod-level aggregate, snapshot-schema (see class
        docstring for the per-kind semantics)."""
        with self._lock:
            out = {"counters": dict(self._counters), "gauges": {},
                   "histograms": {}}
            for worker in sorted(self._gauges):
                for name, val in self._gauges[worker].items():
                    out["gauges"][_with_label(
                        name, self.worker_label, worker)] = val
            for name, st in self._hists.items():
                if st["count"] <= 0 and not any(st["deltas"].values()):
                    continue
                out["histograms"][name] = {
                    "count": st["count"], "sum": st["sum"],
                    "buckets": _metrics.cumulate_deltas(st["deltas"]),
                }
        return out


def snapshot_to_prometheus(snapshot, help_map=None):
    """Prometheus text exposition of a snapshot-schema dict (what
    :meth:`MetricsRegistry.snapshot`, ``aggregate_snapshots`` and
    :meth:`SnapshotMerger.merged` all emit): one ``# HELP`` and one
    ``# TYPE`` header per family (HELP falls back to the family name
    — snapshots don't carry help strings; ``help_map`` restores any
    the caller knows), samples sorted within a family, histogram
    ``_bucket``/``_sum``/``_count`` expansion with ``le`` labels.
    Serve with :data:`obs.metrics.PROMETHEUS_CONTENT_TYPE`."""
    help_map = help_map or {}
    families = {}             # (name, kind) -> [(full, payload)]
    for kind_key, kind in (("counters", "counter"),
                           ("gauges", "gauge"),
                           ("histograms", "histogram")):
        for full, payload in (snapshot.get(kind_key) or {}).items():
            name, _ = _metrics.parse_full_name(full)
            families.setdefault((name, kind), []).append(
                (full, payload))
    lines = []
    for (name, kind) in sorted(families):
        lines.append(f"# HELP {name} {help_map.get(name, name)}")
        lines.append(f"# TYPE {name} {kind}")
        for full, payload in sorted(families[(name, kind)]):
            if kind in ("counter", "gauge"):
                lines.append(f"{full} {payload}")
                continue
            base, labels = _metrics.parse_full_name(full)
            key = _metrics._label_key(labels)
            buckets = dict(payload.get("buckets") or {})
            for le in sorted(buckets,
                             key=_metrics._le_sort_key):
                lines.append(_metrics._full_name(
                    base + "_bucket", key + (("le", le),))
                    + f" {buckets[le]}")
            lines.append(f"{_metrics._full_name(base + '_sum', key)}"
                         f" {payload.get('sum', 0.0)}")
            lines.append(
                f"{_metrics._full_name(base + '_count', key)}"
                f" {payload.get('count', 0)}")
    return "\n".join(lines) + ("\n" if lines else "")


def plane_routes():
    """The plane's handler table — same route contract as
    :func:`serve.http.daemon_routes`, so the two surfaces share one
    dispatch/index/404/metric path and cannot drift."""
    from ..serve.http import ledger_route, snapshot_route

    def metrics_route(view):
        return (200, view.merged_metrics_text(),
                _metrics.PROMETHEUS_CONTENT_TYPE)

    return {
        "/metrics": metrics_route,
        "/state": snapshot_route("state_snapshot"),
        "/report": snapshot_route("report_snapshot"),
        "/workers": snapshot_route("workers_snapshot"),
        "/ledger": ledger_route,
    }


class TelemetryPlane:
    """The pod-level HTTP surface: a
    :class:`~scintools_tpu.serve.http.TelemetryServer` bound to the
    plane route table over a *view* object providing
    ``merged_metrics_text()`` / ``state_snapshot()`` /
    ``report_snapshot()`` / ``workers_snapshot()``
    (fleet/telemetry.py:PodTelemetry is the fleet pod's view; a
    daemon-fleet aggregator can supply its own). ``port=0`` binds an
    ephemeral port readable at :attr:`port` before ``start()``."""

    def __init__(self, view, host="127.0.0.1", port=0):
        # lazy import: obs must stay importable without pulling the
        # serve package (which itself imports obs) at module load
        from ..serve.http import TelemetryServer

        self._server = TelemetryServer(
            view, host=host, port=port, routes=plane_routes(),
            metric_prefix="plane_http", thread_name="plane-http")
        self.view = view

    @property
    def host(self):
        return self._server.host

    @property
    def port(self):
        return self._server.port

    @property
    def url(self):
        return self._server.url

    def start(self):
        self._server.start()
        return self

    def close(self):
        self._server.close()
