"""Chrome-trace (Perfetto-loadable) export of survey stage spans.

``StageTimeline`` (utils/profiling.py) records ``(stage, epoch, t0,
t1)`` wall-clock spans from the prefetch loader threads, the dispatch
loop, the fence points, and the journal writer thread. This module
turns that span list into the Chrome Trace Event JSON format — the
``{"traceEvents": [...]}`` array of ``"ph": "X"`` complete events —
which loads directly in ``chrome://tracing`` and https://ui.perfetto.dev,
so a pipelined survey run is inspectable on a real timeline instead of
through aggregate overlap fractions.

Layout conventions (pinned by tests/test_obs.py):

- one process (``pid`` = the recording process), one *track* (tid)
  per stage — load/dispatch/fence/journal each get their own named
  row, with ``"M"`` (metadata) ``process_name``/``thread_name``
  events emitted first;
- ``ts``/``dur`` are microseconds relative to the earliest span, and
  the ``"X"`` events are sorted by ``ts``;
- each event's ``args`` carries the epoch id and its per-epoch
  ``trace_id`` (threaded through the runner via
  ``StageTimeline.assign_trace``), so every row of one epoch's
  lifecycle is searchable by one string in the trace viewer.
"""

from __future__ import annotations

import json
import os


def chrome_trace_events(spans, trace_ids=None, pid=None,
                        process_name="scintools_tpu survey"):
    """Build the Chrome-trace event list from ``(stage, epoch, t0,
    t1)`` spans (absolute ``perf_counter`` seconds). ``trace_ids``
    optionally maps epoch id → trace-id string. Returns a list of
    event dicts: metadata events first, then the ``"X"`` spans sorted
    by ``ts``."""
    spans = list(spans)
    if pid is None:
        pid = os.getpid()
    stages = sorted({s for s, _, _, _ in spans})
    tids = {stage: i + 1 for i, stage in enumerate(stages)}
    events = [{"name": "process_name", "ph": "M", "pid": pid,
               "tid": 0, "args": {"name": process_name}}]
    for stage in stages:
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tids[stage], "args": {"name": stage}})
    if not spans:
        return events
    t_base = min(t0 for _, _, t0, _ in spans)
    xs = []
    for stage, epoch, t0, t1 in spans:
        args = {"epoch": str(epoch)}
        if trace_ids:
            tid_str = trace_ids.get(epoch, trace_ids.get(str(epoch)))
            if tid_str is not None:
                args["trace_id"] = str(tid_str)
        xs.append({
            "name": stage, "cat": "survey", "ph": "X",
            "ts": round((t0 - t_base) * 1e6, 3),
            "dur": round(max(0.0, t1 - t0) * 1e6, 3),
            "pid": pid, "tid": tids[stage], "args": args})
    xs.sort(key=lambda e: (e["ts"], e["tid"]))
    return events + xs


def write_chrome_trace(path, spans, trace_ids=None, pid=None,
                       process_name="scintools_tpu survey"):
    """Write ``spans`` as a Chrome-trace JSON object file
    (``{"traceEvents": [...], "displayTimeUnit": "ms"}``) and return
    ``path``. The file loads as-is in chrome://tracing / Perfetto."""
    doc = {"traceEvents": chrome_trace_events(
        spans, trace_ids=trace_ids, pid=pid,
        process_name=process_name),
        "displayTimeUnit": "ms"}
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return os.fspath(path)


# ---------------------------------------------------------------------
# cross-process trace merge (ISSUE 13) — fleet workers spool span
# fragments next to their journals; the pod merges them into ONE
# Chrome/Perfetto document for the whole run.
# ---------------------------------------------------------------------
# Fragment format (`<out>/workers/<id>/trace.jsonl`, append-only, one
# JSON object per line, torn tails tolerated):
#
#   {"worker": id, "stage": s, "epoch": e, "t0": unix_s, "t1": unix_s}
#   {"worker": id, "epoch": e, "trace_id": tid}          (id-map line)
#
# Times are WALL-clock seconds (perf_counter spans shifted by a
# once-sampled per-process anchor) so fragments from different
# processes share one timeline. Trace-id assignment travels as its
# own line because a span can be recorded (and flushed) by a loader
# thread before the dispatch loop assigns the epoch's ID — the merge
# resolves IDs last, so late binding is invisible.


def load_trace_fragments(paths):
    """Read per-worker ``.trace.jsonl`` span spools.

    ``paths`` maps worker id → fragment path. Returns
    ``{worker: {"spans": [(stage, epoch, t0, t1)], "trace_ids":
    {epoch: id}}}`` with unparseable lines (a SIGKILLed worker's torn
    tail) skipped — trace data is diagnostics, a lost tail span must
    not fail the merge. Missing files yield no entry."""
    out = {}
    for worker, path in sorted(dict(paths).items()):
        spans, ids = [], {}
        try:
            with open(os.fspath(path)) as fh:
                lines = fh.read().splitlines()
        except OSError:
            continue
        for raw in lines:
            raw = raw.strip()
            if not raw:
                continue
            try:
                rec = json.loads(raw)
            except ValueError:
                continue                   # torn tail line
            if not isinstance(rec, dict):
                continue
            if "trace_id" in rec and "t0" not in rec:
                if rec.get("epoch") is not None:
                    ids[str(rec["epoch"])] = str(rec["trace_id"])
                continue
            try:
                spans.append((str(rec["stage"]), str(rec["epoch"]),
                              float(rec["t0"]), float(rec["t1"])))
            except (KeyError, TypeError, ValueError):
                continue
        out[str(worker)] = {"spans": spans, "trace_ids": ids}
    return out


def merge_traces(fragments, run_name="scintools_tpu fleet"):
    """Deterministically merge per-worker span fragments into ONE
    Chrome-trace document: one *process* (pid) per worker, one named
    track per stage per worker (stage → tid is a GLOBAL table, so the
    same stage sits on the same row of every worker's group), every
    span's ``args`` carrying its epoch and trace ID.

    Trace IDs are stable across steal/resume (the runner derives them
    from the epoch's position within its task), so a stolen epoch's
    spans — journaled by the dead holder before the SIGKILL, re-run
    by the stealer — land on ONE searchable ID across two worker
    tracks: the steal is visible as a track handoff. Exact duplicate
    spans within one worker (a re-exported tail after a crash-restart
    under the same id) are dropped; cross-worker duplicates are the
    signal and are kept.

    ``fragments`` is the :func:`load_trace_fragments` shape. Returns
    the trace document (validate with
    :func:`validate_chrome_trace`)."""
    workers = sorted(fragments)
    stages = sorted({s for w in workers
                     for s, _, _, _ in fragments[w]["spans"]})
    tids = {stage: i + 1 for i, stage in enumerate(stages)}
    pids = {w: i + 1 for i, w in enumerate(workers)}
    events = []
    xs = []
    t_base = min((t0 for w in workers
                  for _, _, t0, _ in fragments[w]["spans"]),
                 default=0.0)
    for w in workers:
        frag = fragments[w]
        pid = pids[w]
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0,
                       "args": {"name": f"{run_name} worker {w}"}})
        used = sorted({s for s, _, _, _ in frag["spans"]})
        for stage in used:
            events.append({"name": "thread_name", "ph": "M",
                           "pid": pid, "tid": tids[stage],
                           "args": {"name": stage}})
        ids = frag["trace_ids"]
        seen = set()
        for stage, epoch, t0, t1 in frag["spans"]:
            key = (stage, epoch, round(t0, 6), round(t1, 6))
            if key in seen:
                continue                  # re-exported duplicate
            seen.add(key)
            args = {"epoch": epoch, "worker": w}
            tid_str = ids.get(epoch)
            if tid_str is not None:
                args["trace_id"] = tid_str
            xs.append({
                "name": stage, "cat": "fleet", "ph": "X",
                "ts": round((t0 - t_base) * 1e6, 3),
                "dur": round(max(0.0, t1 - t0) * 1e6, 3),
                "pid": pid, "tid": tids[stage], "args": args})
    xs.sort(key=lambda e: (e["ts"], e["pid"], e["tid"]))
    return {"traceEvents": events + xs, "displayTimeUnit": "ms"}


def write_merged_trace(path, fragments, run_name="scintools_tpu fleet"):
    """Merge (+ validate) per-worker fragments and write the one pod
    Chrome-trace JSON at ``path``; returns ``(path, stats)`` where
    stats counts workers/stages/events."""
    doc = merge_traces(fragments, run_name=run_name)
    validate_chrome_trace(doc)
    with open(os.fspath(path), "w") as fh:
        json.dump(doc, fh)
    n_x = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
    stats = {"workers": len(fragments), "events": n_x,
             "stages": len({e["name"] for e in doc["traceEvents"]
                            if e.get("ph") == "X"})}
    return os.fspath(path), stats


def validate_chrome_trace(doc):
    """Structural check of a Chrome-trace document (the bench and the
    tier-1 tests share it): ``traceEvents`` present; every ``"X"``
    event carries name/ts/dur/pid/tid with ``ts`` sorted and
    non-negative ``dur``; every (pid, tid) used by an ``"X"`` event
    has a matching ``thread_name`` metadata event. Raises
    :class:`ValueError` on the first problem; returns the event
    list."""
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError("not a Chrome-trace object "
                         "(missing traceEvents)")
    events = doc["traceEvents"]
    named = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            named.add((e["pid"], e["tid"]))
    last_ts = None
    for e in events:
        if e.get("ph") != "X":
            continue
        for k in ("name", "ts", "dur", "pid", "tid"):
            if k not in e:
                raise ValueError(f"X event missing {k!r}: {e}")
        if e["dur"] < 0 or e["ts"] < 0:
            raise ValueError(f"negative ts/dur: {e}")
        if (e["pid"], e["tid"]) not in named:
            raise ValueError(
                f"X event on unnamed track pid={e['pid']} "
                f"tid={e['tid']}")
        if last_ts is not None and e["ts"] < last_ts:
            raise ValueError("X events not sorted by ts")
        last_ts = e["ts"]
    return events
