"""Chrome-trace (Perfetto-loadable) export of survey stage spans.

``StageTimeline`` (utils/profiling.py) records ``(stage, epoch, t0,
t1)`` wall-clock spans from the prefetch loader threads, the dispatch
loop, the fence points, and the journal writer thread. This module
turns that span list into the Chrome Trace Event JSON format — the
``{"traceEvents": [...]}`` array of ``"ph": "X"`` complete events —
which loads directly in ``chrome://tracing`` and https://ui.perfetto.dev,
so a pipelined survey run is inspectable on a real timeline instead of
through aggregate overlap fractions.

Layout conventions (pinned by tests/test_obs.py):

- one process (``pid`` = the recording process), one *track* (tid)
  per stage — load/dispatch/fence/journal each get their own named
  row, with ``"M"`` (metadata) ``process_name``/``thread_name``
  events emitted first;
- ``ts``/``dur`` are microseconds relative to the earliest span, and
  the ``"X"`` events are sorted by ``ts``;
- each event's ``args`` carries the epoch id and its per-epoch
  ``trace_id`` (threaded through the runner via
  ``StageTimeline.assign_trace``), so every row of one epoch's
  lifecycle is searchable by one string in the trace viewer.
"""

from __future__ import annotations

import json
import os


def chrome_trace_events(spans, trace_ids=None, pid=None,
                        process_name="scintools_tpu survey"):
    """Build the Chrome-trace event list from ``(stage, epoch, t0,
    t1)`` spans (absolute ``perf_counter`` seconds). ``trace_ids``
    optionally maps epoch id → trace-id string. Returns a list of
    event dicts: metadata events first, then the ``"X"`` spans sorted
    by ``ts``."""
    spans = list(spans)
    if pid is None:
        pid = os.getpid()
    stages = sorted({s for s, _, _, _ in spans})
    tids = {stage: i + 1 for i, stage in enumerate(stages)}
    events = [{"name": "process_name", "ph": "M", "pid": pid,
               "tid": 0, "args": {"name": process_name}}]
    for stage in stages:
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tids[stage], "args": {"name": stage}})
    if not spans:
        return events
    t_base = min(t0 for _, _, t0, _ in spans)
    xs = []
    for stage, epoch, t0, t1 in spans:
        args = {"epoch": str(epoch)}
        if trace_ids:
            tid_str = trace_ids.get(epoch, trace_ids.get(str(epoch)))
            if tid_str is not None:
                args["trace_id"] = str(tid_str)
        xs.append({
            "name": stage, "cat": "survey", "ph": "X",
            "ts": round((t0 - t_base) * 1e6, 3),
            "dur": round(max(0.0, t1 - t0) * 1e6, 3),
            "pid": pid, "tid": tids[stage], "args": args})
    xs.sort(key=lambda e: (e["ts"], e["tid"]))
    return events + xs


def write_chrome_trace(path, spans, trace_ids=None, pid=None,
                       process_name="scintools_tpu survey"):
    """Write ``spans`` as a Chrome-trace JSON object file
    (``{"traceEvents": [...], "displayTimeUnit": "ms"}``) and return
    ``path``. The file loads as-is in chrome://tracing / Perfetto."""
    doc = {"traceEvents": chrome_trace_events(
        spans, trace_ids=trace_ids, pid=pid,
        process_name=process_name),
        "displayTimeUnit": "ms"}
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return os.fspath(path)


def validate_chrome_trace(doc):
    """Structural check of a Chrome-trace document (the bench and the
    tier-1 tests share it): ``traceEvents`` present; every ``"X"``
    event carries name/ts/dur/pid/tid with ``ts`` sorted and
    non-negative ``dur``; every (pid, tid) used by an ``"X"`` event
    has a matching ``thread_name`` metadata event. Raises
    :class:`ValueError` on the first problem; returns the event
    list."""
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError("not a Chrome-trace object "
                         "(missing traceEvents)")
    events = doc["traceEvents"]
    named = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            named.add((e["pid"], e["tid"]))
    last_ts = None
    for e in events:
        if e.get("ph") != "X":
            continue
        for k in ("name", "ts", "dur", "pid", "tid"):
            if k not in e:
                raise ValueError(f"X event missing {k!r}: {e}")
        if e["dur"] < 0 or e["ts"] < 0:
            raise ValueError(f"negative ts/dur: {e}")
        if (e["pid"], e["tid"]) not in named:
            raise ValueError(
                f"X event on unnamed track pid={e['pid']} "
                f"tid={e['tid']}")
        if last_ts is not None and e["ts"] < last_ts:
            raise ValueError("X events not sorted by ts")
        last_ts = e["ts"]
    return events
