"""Program cost ledger: persistent per-site runtime profiling.

The stack measures itself in many places and remembers almost
nothing: ``backend.measure_formulation`` pins a winner for the life
of one process, the serve batch controller runs a fixed ``gain``
that assumes lane cost is constant, and every bench timing split
dies with its JSON file. This module is the one place wall-time
knowledge accumulates — and the place other subsystems read it back:

- every :func:`obs.retrace.record_build` site reports its compile
  seconds here (kind ``"compile"``), and every formulation-routed or
  repeatedly-dispatched program can report steady-state seconds
  (kind ``"steady"``) via :func:`record` / the :func:`timed` context
  manager;
- entries are keyed ``(site, platform, shape, formulation)`` and hold
  a compile total plus a bounded ring buffer of steady samples —
  recording is O(1), allocation-free after the first sample, and a
  no-op while :func:`obs.metrics.set_enabled` (False) holds (the
  bench pins <3% overhead on the serve_batched workload);
- samples mirror into the metrics registry as
  ``program_steady_seconds{site=,formulation=}`` /
  ``program_compile_seconds{site=}`` histograms, and the full ledger
  is served from ``/ledger`` on both the daemon and fleet-plane
  handler tables;
- the ledger **persists**: :func:`save`/:func:`load` speak the same
  atomic CRC-JSONL dialect as the epoch journal (torn-tail tolerant,
  ``os.replace`` atomic), one file per workdir
  (:func:`workdir_path`), so a restarted daemon resumes its cost
  model instead of relearning it.

Consumers close the loop: ``backend.py`` resolves formulation
winners from committed per-platform tables the ledger's
measurements write (``tools/formulation_tables/<platform>.json``),
and ``serve/lanes.py:AdaptiveBatchController.reschedule`` gain-
schedules the batch law from the measured per-bucket service time
(:func:`steady_median` on the ``serve.batch`` site).
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from collections import deque
from contextlib import contextmanager

#: steady-sample ring size per (site, platform, shape, formulation)
#: entry — bounds memory for any run length while keeping enough
#: samples for a stable median.
RING = 256

#: basename of the per-workdir ledger file (see :func:`workdir_path`).
LEDGER_BASENAME = "program_ledger.jsonl"


def _line_crc(payload):
    """CRC32 of a ledger line's JSON payload (sans the crc field),
    zero-padded hex — same dialect as the epoch journal."""
    return f"{zlib.crc32(payload.encode()):08x}"


def _median(values):
    vals = sorted(values)
    n = len(vals)
    if not n:
        return None
    mid = n // 2
    if n % 2:
        return vals[mid]
    return 0.5 * (vals[mid - 1] + vals[mid])


class ProgramLedger:
    """Process-wide cost ledger; see the module docstring.

    Thread-safe (one lock; the serve daemon loop and worker pools
    record concurrently). Entries are created on first record and
    never dropped within a process — sites are code literals, shapes
    are bucket sizes, formulations come from the registered choice
    tuples, so the key space is bounded by construction.
    """

    def __init__(self, ring=RING):
        self._lock = threading.Lock()
        self._entries = {}   # key tuple -> entry dict
        self._ring = int(ring)
        self._platform = None

    # -- keying ----------------------------------------------------

    def platform(self):
        """The platform label stamped on new samples: the live jax
        backend name, cached after first resolution ('cpu' when jax
        is unavailable or not yet decided)."""
        with self._lock:
            if self._platform is None:
                try:
                    from .. import backend

                    self._platform = backend.formulation_platform()
                except Exception:
                    self._platform = "cpu"
            return self._platform

    def _key(self, site, platform, shape, formulation):
        return (str(site),
                str(platform) if platform is not None else self.platform(),
                "" if shape is None else str(shape),
                "" if formulation is None else str(formulation))

    def _entry_locked(self, key):
        ent = self._entries.get(key)
        if ent is None:
            ent = self._entries[key] = {
                "compile_s": 0.0, "compile_n": 0,
                "steady": deque(maxlen=self._ring)}
        return ent

    # -- recording -------------------------------------------------

    def record(self, site, seconds, kind="steady", *, shape=None,
               formulation=None, platform=None):
        """Record one wall-time sample for ``site``.

        ``kind`` is ``"steady"`` (a post-warm-up program execution;
        ring-buffered, feeds :func:`steady_median`) or ``"compile"``
        (a program build; totalled). No-op while the metrics switch
        is off — the same ``set_enabled`` gate every probe honours.
        """
        from . import metrics

        if not metrics.enabled():
            return
        seconds = float(seconds)
        site = str(site)
        key = self._key(site, platform, shape, formulation)
        with self._lock:
            ent = self._entry_locked(key)
            if kind == "compile":
                ent["compile_s"] += seconds
                ent["compile_n"] += 1
            else:
                ent["steady"].append(seconds)
        if kind == "compile":
            metrics.histogram(
                "program_compile_seconds",
                help="program build wall time per jit-cache site",
            ).labels(site=site).observe(seconds)  # lint-ok: metric-hygiene: bounded=site
        else:
            metrics.histogram(
                "program_steady_seconds",
                help="steady-state program wall time per ledger site",
            ).labels(site=site, formulation=key[3]).observe(seconds)  # lint-ok: metric-hygiene: bounded=site bounded=formulation

    @contextmanager
    def timed(self, site, *, shape=None, formulation=None,
              kind="steady"):
        """Time a block into the ledger (perf_counter; recorded even
        when the block raises — a failing program still cost its
        seconds)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(site, time.perf_counter() - t0, kind,
                        shape=shape, formulation=formulation)

    # -- reading ---------------------------------------------------

    def steady_median(self, site, *, shape=None, formulation=None,
                      platform=None):
        """Median steady seconds over every entry matching ``site``
        (and, when given, ``shape``/``formulation``/``platform``),
        or None with no samples. The gain scheduler's read path."""
        site = str(site)
        shape = None if shape is None else str(shape)
        formulation = None if formulation is None else str(formulation)
        platform = None if platform is None else str(platform)
        samples = []
        with self._lock:
            for (s, p, sh, f), ent in self._entries.items():
                if s != site:
                    continue
                if shape is not None and sh != shape:
                    continue
                if formulation is not None and f != formulation:
                    continue
                if platform is not None and p != platform:
                    continue
                samples.extend(ent["steady"])
        return _median(samples)

    def steady_site_medians(self):
        """``{site: median_steady_seconds}`` aggregated over every
        shape/formulation/platform of each site — the RunReport
        ``slo.sites`` view."""
        sites = {}
        with self._lock:
            for (site, _, _, _), ent in self._entries.items():
                if ent["steady"]:
                    sites.setdefault(site, []).extend(ent["steady"])
        return {s: round(_median(v), 6)
                for s, v in sorted(sites.items())}

    def snapshot(self):
        """JSON-able view: ``{"platform":, "entries": [...]}`` with
        one row per key carrying compile totals and steady-sample
        stats (count / total / best / median). The ``/ledger``
        endpoint and the bench's ``program_ledger`` block serve this
        verbatim."""
        rows = []
        with self._lock:
            items = sorted(self._entries.items())
            for (site, plat, shape, form), ent in items:
                steady = list(ent["steady"])
                rows.append({
                    "site": site, "platform": plat, "shape": shape,
                    "formulation": form,
                    "compile_s": round(ent["compile_s"], 6),
                    "compile_n": ent["compile_n"],
                    "steady_n": len(steady),
                    "steady_total_s": round(sum(steady), 6),
                    "steady_best_s": round(min(steady), 6)
                    if steady else None,
                    "steady_median_s": round(_median(steady), 6)
                    if steady else None,
                })
        return {"platform": self.platform(), "entries": rows}

    # -- persistence (atomic CRC-JSONL) ----------------------------

    def save(self, path):
        """Atomically write the full ledger as CRC-JSONL: one line
        per entry, each carrying its raw steady ring (rounded) and a
        crc over the rest of the record — the epoch-journal dialect,
        so a reader (or a resume after SIGKILL) sees either the old
        ledger or the complete new one."""
        from ..parallel.checkpoint import atomic_write_bytes

        lines = []
        with self._lock:
            for (site, plat, shape, form), ent in sorted(
                    self._entries.items()):
                rec = {"site": site, "platform": plat, "shape": shape,
                       "formulation": form,
                       "compile_s": round(ent["compile_s"], 6),
                       "compile_n": ent["compile_n"],
                       "steady": [round(s, 6) for s in ent["steady"]]}
                payload = json.dumps(rec)
                lines.append(json.dumps(
                    {**rec, "crc": _line_crc(payload)}))
        atomic_write_bytes(os.fspath(path),
                           ("\n".join(lines) + "\n").encode()
                           if lines else b"")

    def load(self, path):
        """Merge a saved ledger back in (compile totals add, steady
        samples append into the rings). Corrupt or torn lines are
        skipped — a ledger truncated mid-line by a crash loses that
        line, never the file. Missing file is an empty ledger.
        Returns the number of entries merged."""
        path = os.fspath(path)
        if not os.path.exists(path):
            return 0
        merged = 0
        with open(path) as fh:
            for raw in fh:
                line = raw.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    crc = rec.pop("crc")
                    if crc != _line_crc(json.dumps(rec)):
                        raise ValueError("crc mismatch")
                    key = (str(rec["site"]), str(rec["platform"]),
                           str(rec.get("shape", "")),
                           str(rec.get("formulation", "")))
                    compile_s = float(rec.get("compile_s", 0.0))
                    compile_n = int(rec.get("compile_n", 0))
                    steady = [float(s) for s in rec.get("steady", [])]
                except (ValueError, KeyError, TypeError):
                    continue
                with self._lock:
                    ent = self._entry_locked(key)
                    ent["compile_s"] += compile_s
                    ent["compile_n"] += compile_n
                    ent["steady"].extend(steady)
                merged += 1
        return merged

    def reset(self):
        with self._lock:
            self._entries.clear()
            self._platform = None


#: the process-wide ledger every call site records into.
LEDGER = ProgramLedger()


def record(site, seconds, kind="steady", **kw):
    LEDGER.record(site, seconds, kind, **kw)


def timed(site, **kw):
    return LEDGER.timed(site, **kw)


def steady_median(site, **kw):
    return LEDGER.steady_median(site, **kw)


def snapshot():
    return LEDGER.snapshot()


def save(path):
    LEDGER.save(path)


def load(path):
    return LEDGER.load(path)


def reset():
    LEDGER.reset()


def workdir_path(workdir):
    """The per-workdir ledger file the serve daemon loads at start
    and saves at stop: ``<workdir>/program_ledger.jsonl``."""
    return os.path.join(os.fspath(workdir), LEDGER_BASENAME)
