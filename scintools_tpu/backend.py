"""Backend dispatch: bit-reproducible numpy default, JAX/TPU fast path.

The reference package (scintools) is numpy-only. Here every hot kernel has a
single generic implementation written against the ``xp`` array namespace
(numpy or jax.numpy), with jitted JAX fast-paths registered where it pays.
The numpy path is the default and is bit-reproducible run-to-run; the jax
path targets TPU via XLA (see BASELINE.json north star).
"""

from __future__ import annotations

import os

import numpy as np

_DEFAULT_BACKEND = os.environ.get("SCINTOOLS_BACKEND", "numpy")

_jax = None
_jnp = None


def _load_jax():
    global _jax, _jnp
    if _jax is None:
        import jax
        import jax.numpy as jnp

        _maybe_enable_compilation_cache(jax)
        _jax = jax
        _jnp = jnp
    return _jax, _jnp


def _maybe_enable_compilation_cache(jax):
    """Point XLA's persistent compilation cache at a per-user dir so
    repeat processes skip recompilation (measured: a θ-θ test module
    re-runs in 3.1 s instead of 7.7 s on CPU; first TPU compiles via
    the tunnel are 20-40 s, so warm processes gain far more there
    when the backend supports executable serialisation).

    ``SCINTOOLS_XLA_CACHE=<dir>`` overrides the location, ``=0``
    disables; an explicit jax-level setting (env or config) wins.
    Failures are swallowed — the cache is an optimisation only.
    """
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return
    path = os.environ.get("SCINTOOLS_XLA_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "scintools_tpu", "xla")
    if path == "0":
        return
    try:
        if jax.config.jax_compilation_cache_dir:
            return
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # export too, so subprocesses (the bench's tunnel probe, pool
        # workers) inherit the cache — a cached executable still has
        # to RUN on the device, so probes keep probing the tunnel
        os.environ["JAX_COMPILATION_CACHE_DIR"] = path
    except Exception:
        return            # no cache, no exports — a consistent state
    # companion knobs: subprocesses read only env, and without the
    # max-size bound their writes would be unbounded (jax default -1
    # = no eviction). Env export comes FIRST and each knob gets its
    # own exception scope, so a jax version without one flag still
    # hands subprocesses the bound via env.
    for env_key, flag, val in (
            ("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
             "jax_persistent_cache_min_compile_time_secs", 0.3),
            # LRU-evict past 2 GB so dev iterations can't grow the
            # dir without bound
            ("JAX_COMPILATION_CACHE_MAX_SIZE",
             "jax_compilation_cache_max_size", 2 * 1024 ** 3)):
        if os.environ.get(env_key):
            continue
        os.environ[env_key] = str(val)
        try:
            jax.config.update(flag, val)
        except (AttributeError, TypeError, ValueError, RuntimeError):
            # older jax without this flag (raises AttributeError or
            # RuntimeError depending on version) — the env var above
            # still applies where supported; the cache is best-effort
            pass


def compilation_cache_dir():
    """The active persistent-XLA-compilation-cache directory, or None
    when disabled (``SCINTOOLS_XLA_CACHE=0`` / jax unavailable /
    wiring failed). The cache is what lets the geometry-keyed θ-θ
    search programs (``thth.core.keyed_jit_cache``) survive process
    restarts: a fresh process pays the retrace but loads the compiled
    executable from disk instead of recompiling — see
    docs/performance.md ("Fused search pipeline"). Touching this
    accessor wires the cache (it loads jax)."""
    try:
        jax = get_jax()
        return jax.config.jax_compilation_cache_dir or None
    except Exception:
        return None


def set_default_backend(backend):
    """Set the process-wide default backend ('numpy' or 'jax')."""
    global _DEFAULT_BACKEND
    if backend not in ("numpy", "jax"):
        raise ValueError("backend must be 'numpy' or 'jax'")
    _DEFAULT_BACKEND = backend


def default_backend():
    return _DEFAULT_BACKEND


def resolve_backend(backend=None):
    return _DEFAULT_BACKEND if backend is None else backend


def get_xp(backend=None):
    """Return the array namespace for a backend name."""
    backend = resolve_backend(backend)
    if backend == "numpy":
        return np
    if backend == "jax":
        return _load_jax()[1]
    raise ValueError(f"unknown backend {backend!r}")


def get_jax():
    return _load_jax()[0]


def to_numpy(x):
    return np.asarray(x)


def complex_transfer_safe():
    """False when the default jax device cannot transfer complex
    buffers across the host↔device boundary (the tunneled 'axon' TPU
    fails with UNIMPLEMENTED and poisons the process). Complex math
    *inside* a single jitted program is always fine; this gates only
    eager helpers that would device_put complex arrays."""
    platforms = os.environ.get("JAX_PLATFORMS", "").lower().split(",")
    return "axon" not in [p.strip() for p in platforms]


def force_cpu_platform(n_devices=None):
    """Pin jax onto host CPU (optionally with ``n_devices`` virtual
    devices for mesh emulation) before any backend touch.

    In this image the axon TPU PJRT plugin is registered by a
    sitecustomize at interpreter startup, and setting
    ``JAX_PLATFORMS=cpu`` in the environment does NOT stop jax from
    initialising it (which hangs indefinitely when the TPU tunnel is
    down) — only ``jax.config.update('jax_platforms', 'cpu')`` after
    import reliably does. Call this before the first jax computation
    in any host-only / virtual-mesh entry point.
    """
    if n_devices:
        import re

        flags = os.environ.get("XLA_FLAGS", "")
        flag = f"--xla_force_host_platform_device_count={n_devices}"
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", flag,
                flags)
        else:
            flags = f"{flags} {flag}".strip()
        os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax = get_jax()
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        if getattr(jax.config, "jax_platforms", None) != "cpu":
            raise RuntimeError(
                "force_cpu_platform() was called after a non-CPU jax "
                "backend was already initialised; call it before the "
                "first jax computation") from None


def eager_backend(backend=None):
    """Backend for eager (non-jitted) complex array helpers: resolves
    'jax' down to 'numpy' when complex transfers are unsafe."""
    backend = resolve_backend(backend)
    if backend == "jax" and not complex_transfer_safe():
        return "numpy"
    return backend


# ---------------------------------------------------------------------------
# Per-platform formulation dispatch
# ---------------------------------------------------------------------------
#
# Several hot kernels have more than one mathematically-equivalent
# *formulation* whose winner depends on the platform: the conjugate
# spectrum as rfft2+Hermitian-gather vs complex fft2 (ops/sspec.py),
# the structure-aware transform lowerings of ops/xfft.py (real-input
# Wiener–Khinchin ACF, halved secondary-spectrum power, real sspec→
# ACF forward — each vs its dense complex oracle), the
# scattered-image / arc-profile interpolation as coalesced gathers
# vs MXU tent/Keys matmuls (ops/scatim.py, ops/normsspec.py), the θ-θ
# eigensolver as a VMEM Pallas squaring kernel vs the XLA warm-start
# η-scan vs a cold power iteration (thth/batch.py, thth/retrieval.py),
# and buffer donation (useful on accelerators, a compile warning on
# CPU). Before this registry each of those was an ad-hoc
# ``jax.default_backend() == ...`` branch buried in its module; the
# registry makes the choice one inspectable, overridable table:
#
# - each op module REGISTERS its formulations and per-platform
#   defaults at import (:func:`register_formulation`);
# - call sites resolve the active choice with :func:`formulation`;
# - an operator can pin a choice process-wide
#   (:func:`set_formulation`) or from the environment
#   (``SCINTOOLS_FORMULATION_<OP>`` with ``.``→``_``, e.g.
#   ``SCINTOOLS_FORMULATION_OPS_CS=fft2``), and
#   :func:`measure_formulation` installs a MEASURED override by
#   timing the candidate closures on the live platform (the bench's
#   gather-vs-matmul splits, promoted to a mechanism);
# - measured winners PERSIST (ISSUE 20): ``measure_formulation(...,
#   persist=True)`` merges the winner into a committable per-platform
#   table (``tools/formulation_tables/<platform>.json``,
#   ``SCINTOOLS_FORMULATION_TABLES`` relocates the directory), which
#   every later process auto-loads on its first resolution for that
#   platform — a measurement run on a TPU host writes the table the
#   fleet resolves from, no code change.
#
# Resolution order: measured/manual override > environment >
# measured per-platform table > registered per-platform table >
# registered default.

_FORMULATIONS = {}            # op -> {default, choices, platforms, doc}
_FORMULATION_OVERRIDES = {}   # op -> choice (set_formulation/measured)
_MEASURED_TABLES = {}         # platform -> op -> {choice, seconds}
_MEASURED_LOADED = set()      # platforms whose table file was read


def register_formulation(op, default, choices, platforms=None, doc=""):
    """Register (idempotently) the formulation table for ``op``.

    ``choices`` is the tuple of valid formulation names, ``default``
    the platform-independent fallback, ``platforms`` an optional
    ``{platform: choice}`` map keyed by jax backend names ('cpu',
    'tpu', 'gpu')."""
    choices = tuple(choices)
    platforms = dict(platforms or {})
    if default not in choices:
        raise ValueError(f"{op}: default {default!r} not in {choices}")
    for plat, choice in platforms.items():
        if choice not in choices:
            raise ValueError(
                f"{op}: platform {plat!r} choice {choice!r} not in "
                f"{choices}")
    _FORMULATIONS[op] = {"default": default, "choices": choices,
                         "platforms": platforms, "doc": doc}


def formulation_platform():
    """The platform key used by :func:`formulation` when none is
    given: the default jax backend name, or 'cpu' when jax is
    unavailable (the numpy fallback runs on the host)."""
    try:
        return get_jax().default_backend()
    except Exception:  # pragma: no cover - jax is baked into the image
        return "cpu"


def _env_formulation(op):
    return os.environ.get(
        "SCINTOOLS_FORMULATION_" + op.replace(".", "_").upper())


def formulation_table_dir():
    """Directory of the committable per-platform measured formulation
    tables: ``SCINTOOLS_FORMULATION_TABLES`` when set (tests, scratch
    measurement runs), else ``tools/formulation_tables`` next to the
    package (the in-repo location the CPU table is committed at)."""
    env = os.environ.get("SCINTOOLS_FORMULATION_TABLES")
    if env:
        return env
    return os.path.normpath(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), os.pardir,
        "tools", "formulation_tables"))


def formulation_table_path(platform):
    """``<table_dir>/<platform>.json`` for a jax backend name."""
    return os.path.join(formulation_table_dir(), f"{platform}.json")


def _measured_table(platform):
    """The measured table for ``platform``, auto-loading the
    committed table file once per process on first use. In-process
    measurements (:func:`record_measured_formulation`) shadow the
    file's entries. A missing or unreadable file is an empty table —
    a stale or foreign table must never brick a build."""
    if platform not in _MEASURED_LOADED:
        _MEASURED_LOADED.add(platform)
        try:
            import json

            with open(formulation_table_path(platform)) as fh:
                data = json.load(fh)
            ops = data.get("ops") or {}
        except (OSError, ValueError, AttributeError):
            ops = {}
        tbl = _MEASURED_TABLES.setdefault(platform, {})
        for op, entry in ops.items():
            if not isinstance(entry, dict):
                entry = {"choice": entry}
            choice = entry.get("choice")
            if choice is not None:
                tbl.setdefault(str(op), {
                    "choice": str(choice),
                    "seconds": entry.get("seconds")})
    return _MEASURED_TABLES.get(platform, {})


def formulation(op, platform=None):
    """Resolve the active formulation name for a registered ``op``.

    Order: :func:`set_formulation`/:func:`measure_formulation`
    override > ``SCINTOOLS_FORMULATION_<OP>`` env var > measured
    per-platform table (:func:`_measured_table`, auto-loaded from
    ``tools/formulation_tables/<platform>.json``) > registered
    per-platform table entry for ``platform`` (default: the live jax
    backend) > registered default. Unknown ops and invalid override
    values raise — a typo'd formulation must be loud, not a silent
    fall-through to the slow path; an invalid MEASURED choice (a
    stale committed table naming a renamed formulation) is skipped
    instead, since the operator may not own the table."""
    rec = _FORMULATIONS.get(op)
    if rec is None:
        raise KeyError(f"unregistered formulation op {op!r} "
                       f"(known: {sorted(_FORMULATIONS)})")
    for source, choice in (("override", _FORMULATION_OVERRIDES.get(op)),
                           ("env", _env_formulation(op))):
        if choice is not None:
            if choice not in rec["choices"]:
                raise ValueError(
                    f"{op}: {source} formulation {choice!r} not one "
                    f"of {rec['choices']}")
            return choice
    if platform is None:
        platform = formulation_platform()
    measured = _measured_table(platform).get(op)
    if measured and measured.get("choice") in rec["choices"]:
        return measured["choice"]
    return rec["platforms"].get(platform, rec["default"])


def set_formulation(op, choice=None):
    """Pin (or with ``choice=None`` clear) a process-wide formulation
    override for ``op``. Validated against the registered choices."""
    rec = _FORMULATIONS.get(op)
    if rec is None:
        raise KeyError(f"unregistered formulation op {op!r}")
    if choice is None:
        _FORMULATION_OVERRIDES.pop(op, None)
        return
    if choice not in rec["choices"]:
        raise ValueError(f"{op}: {choice!r} not one of "
                         f"{rec['choices']}")
    _FORMULATION_OVERRIDES[op] = choice


def record_measured_formulation(op, choice, seconds=None,
                                platform=None, persist=False):
    """Install ``choice`` as the measured winner for ``op`` on
    ``platform`` (default: live). ``seconds`` — the per-candidate
    timing dict to keep alongside it. With ``persist=True`` the
    winner is also merged into the platform's table file
    (:func:`formulation_table_path`, atomic write) so the NEXT
    process resolves it with no pinning — the mechanism ROADMAP item
    4b asks for."""
    rec = _FORMULATIONS.get(op)
    if rec is None:
        raise KeyError(f"unregistered formulation op {op!r}")
    if choice not in rec["choices"]:
        raise ValueError(f"{op}: {choice!r} not one of "
                         f"{rec['choices']}")
    if platform is None:
        platform = formulation_platform()
    _measured_table(platform)      # load the file before shadowing it
    _MEASURED_TABLES.setdefault(platform, {})[op] = {
        "choice": choice,
        "seconds": {k: round(float(v), 6)
                    for k, v in (seconds or {}).items()} or None}
    if persist:
        save_formulation_table(platform)


def save_formulation_table(platform=None, path=None):
    """Atomically write ``platform``'s measured table (file entries
    merged with in-process measurements, in-process wins) to its
    committable JSON file. Returns the path written."""
    import json

    from .parallel.checkpoint import atomic_write_bytes

    if platform is None:
        platform = formulation_platform()
    table = _measured_table(platform)
    if path is None:
        path = formulation_table_path(platform)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    doc = {"platform": platform,
           "ops": {op: dict(entry) for op, entry in
                   sorted(table.items())}}
    atomic_write_bytes(path, (json.dumps(doc, indent=1, sort_keys=True)
                              + "\n").encode())
    return path


def reset_measured_formulations():
    """Drop every measured table AND the loaded-file memo (tests; a
    re-resolution re-reads the table files)."""
    _MEASURED_TABLES.clear()
    _MEASURED_LOADED.clear()


def measure_formulation(op, candidates, repeats=2, persist=False):
    """Install a MEASURED override: time each candidate closure on the
    live platform and pin the fastest.

    ``candidates`` is ``{choice: thunk}`` where each thunk runs one
    representative workload of that formulation end-to-end (including
    its result fetch — the caller owns making the timing honest). Each
    thunk is called once for warm-up (compile) and then ``repeats``
    times; the per-choice time is the best repeat. Returns
    ``(winner, {choice: best_seconds})`` and leaves the winner pinned
    via :func:`set_formulation` (clear with
    ``set_formulation(op, None)``). With ``persist=True`` the winner
    also lands in the platform's measured table and its committable
    file (see :func:`record_measured_formulation`) so later processes
    resolve it with no pinning; without it only the override is set —
    clearing the override restores the registered resolution. Every
    candidate timing is recorded into the program cost ledger under
    site ``formulation.<op>``."""
    import time

    rec = _FORMULATIONS.get(op)
    if rec is None:
        raise KeyError(f"unregistered formulation op {op!r}")
    unknown = set(candidates) - set(rec["choices"])
    if unknown:
        raise ValueError(f"{op}: unknown candidate(s) {sorted(unknown)}")
    timings = {}
    for choice, thunk in candidates.items():
        thunk()                              # warm-up / compile
        best = float("inf")
        for _ in range(max(1, int(repeats))):
            t0 = time.perf_counter()
            thunk()
            best = min(best, time.perf_counter() - t0)
        timings[choice] = best
    winner = min(timings, key=timings.get)
    set_formulation(op, winner)
    if persist:
        record_measured_formulation(op, winner, seconds=timings,
                                    persist=True)
    from .obs import ledger
    from .utils import slog

    for choice, best in timings.items():
        ledger.record(f"formulation.{op}", best, "steady",
                      formulation=choice)
    slog.log_event("backend.formulation_measured", op=op,
                   winner=winner, persist=bool(persist),
                   timings={k: round(v, 6) for k, v in timings.items()})
    return winner, timings


def formulation_snapshot():
    """JSON-able view of every registered op: its choices, table, and
    the choice that would resolve right now (for run reports/bench)."""
    platform = formulation_platform()
    measured = _measured_table(platform)
    out = {}
    for op, rec in sorted(_FORMULATIONS.items()):
        out[op] = {
            "choices": list(rec["choices"]),
            "default": rec["default"],
            "platforms": dict(rec["platforms"]),
            "override": _FORMULATION_OVERRIDES.get(op)
            or _env_formulation(op),
            "measured": (measured.get(op) or {}).get("choice"),
            "active": formulation(op),
        }
    return out


# Buffer donation is itself a per-platform formulation: donated HBM is
# recycled into program intermediates on accelerators, but XLA on CPU
# cannot alias the buffers and warns on every compile.
register_formulation(
    "jit.donate", default="on", choices=("on", "off"),
    platforms={"cpu": "off"},
    doc="donate consumed input stacks to jitted programs")


def donation_argnums(argnums):
    """``argnums`` when the 'jit.donate' formulation is active on this
    platform, else None — the shared gate for every factory that
    donates its input stack."""
    return tuple(argnums) if formulation("jit.donate") == "on" else None


