"""Backend dispatch: bit-reproducible numpy default, JAX/TPU fast path.

The reference package (scintools) is numpy-only. Here every hot kernel has a
single generic implementation written against the ``xp`` array namespace
(numpy or jax.numpy), with jitted JAX fast-paths registered where it pays.
The numpy path is the default and is bit-reproducible run-to-run; the jax
path targets TPU via XLA (see BASELINE.json north star).
"""

from __future__ import annotations

import os

import numpy as np

_DEFAULT_BACKEND = os.environ.get("SCINTOOLS_BACKEND", "numpy")

_jax = None
_jnp = None


def _load_jax():
    global _jax, _jnp
    if _jax is None:
        import jax
        import jax.numpy as jnp

        _maybe_enable_compilation_cache(jax)
        _jax = jax
        _jnp = jnp
    return _jax, _jnp


def _maybe_enable_compilation_cache(jax):
    """Point XLA's persistent compilation cache at a per-user dir so
    repeat processes skip recompilation (measured: a θ-θ test module
    re-runs in 3.1 s instead of 7.7 s on CPU; first TPU compiles via
    the tunnel are 20-40 s, so warm processes gain far more there
    when the backend supports executable serialisation).

    ``SCINTOOLS_XLA_CACHE=<dir>`` overrides the location, ``=0``
    disables; an explicit jax-level setting (env or config) wins.
    Failures are swallowed — the cache is an optimisation only.
    """
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return
    path = os.environ.get("SCINTOOLS_XLA_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "scintools_tpu", "xla")
    if path == "0":
        return
    try:
        if jax.config.jax_compilation_cache_dir:
            return
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # export too, so subprocesses (the bench's tunnel probe, pool
        # workers) inherit the cache — a cached executable still has
        # to RUN on the device, so probes keep probing the tunnel
        os.environ["JAX_COMPILATION_CACHE_DIR"] = path
    except Exception:
        return            # no cache, no exports — a consistent state
    # companion knobs: subprocesses read only env, and without the
    # max-size bound their writes would be unbounded (jax default -1
    # = no eviction). Env export comes FIRST and each knob gets its
    # own exception scope, so a jax version without one flag still
    # hands subprocesses the bound via env.
    for env_key, flag, val in (
            ("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
             "jax_persistent_cache_min_compile_time_secs", 0.3),
            # LRU-evict past 2 GB so dev iterations can't grow the
            # dir without bound
            ("JAX_COMPILATION_CACHE_MAX_SIZE",
             "jax_compilation_cache_max_size", 2 * 1024 ** 3)):
        if os.environ.get(env_key):
            continue
        os.environ[env_key] = str(val)
        try:
            jax.config.update(flag, val)
        except (AttributeError, TypeError, ValueError, RuntimeError):
            # older jax without this flag (raises AttributeError or
            # RuntimeError depending on version) — the env var above
            # still applies where supported; the cache is best-effort
            pass


def compilation_cache_dir():
    """The active persistent-XLA-compilation-cache directory, or None
    when disabled (``SCINTOOLS_XLA_CACHE=0`` / jax unavailable /
    wiring failed). The cache is what lets the geometry-keyed θ-θ
    search programs (``thth.core.keyed_jit_cache``) survive process
    restarts: a fresh process pays the retrace but loads the compiled
    executable from disk instead of recompiling — see
    docs/performance.md ("Fused search pipeline"). Touching this
    accessor wires the cache (it loads jax)."""
    try:
        jax = get_jax()
        return jax.config.jax_compilation_cache_dir or None
    except Exception:
        return None


def set_default_backend(backend):
    """Set the process-wide default backend ('numpy' or 'jax')."""
    global _DEFAULT_BACKEND
    if backend not in ("numpy", "jax"):
        raise ValueError("backend must be 'numpy' or 'jax'")
    _DEFAULT_BACKEND = backend


def default_backend():
    return _DEFAULT_BACKEND


def resolve_backend(backend=None):
    return _DEFAULT_BACKEND if backend is None else backend


def get_xp(backend=None):
    """Return the array namespace for a backend name."""
    backend = resolve_backend(backend)
    if backend == "numpy":
        return np
    if backend == "jax":
        return _load_jax()[1]
    raise ValueError(f"unknown backend {backend!r}")


def get_jax():
    return _load_jax()[0]


def to_numpy(x):
    return np.asarray(x)


def complex_transfer_safe():
    """False when the default jax device cannot transfer complex
    buffers across the host↔device boundary (the tunneled 'axon' TPU
    fails with UNIMPLEMENTED and poisons the process). Complex math
    *inside* a single jitted program is always fine; this gates only
    eager helpers that would device_put complex arrays."""
    platforms = os.environ.get("JAX_PLATFORMS", "").lower().split(",")
    return "axon" not in [p.strip() for p in platforms]


def force_cpu_platform(n_devices=None):
    """Pin jax onto host CPU (optionally with ``n_devices`` virtual
    devices for mesh emulation) before any backend touch.

    In this image the axon TPU PJRT plugin is registered by a
    sitecustomize at interpreter startup, and setting
    ``JAX_PLATFORMS=cpu`` in the environment does NOT stop jax from
    initialising it (which hangs indefinitely when the TPU tunnel is
    down) — only ``jax.config.update('jax_platforms', 'cpu')`` after
    import reliably does. Call this before the first jax computation
    in any host-only / virtual-mesh entry point.
    """
    if n_devices:
        import re

        flags = os.environ.get("XLA_FLAGS", "")
        flag = f"--xla_force_host_platform_device_count={n_devices}"
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", flag,
                flags)
        else:
            flags = f"{flags} {flag}".strip()
        os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax = get_jax()
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        if getattr(jax.config, "jax_platforms", None) != "cpu":
            raise RuntimeError(
                "force_cpu_platform() was called after a non-CPU jax "
                "backend was already initialised; call it before the "
                "first jax computation") from None


def eager_backend(backend=None):
    """Backend for eager (non-jitted) complex array helpers: resolves
    'jax' down to 'numpy' when complex transfers are unsafe."""
    backend = resolve_backend(backend)
    if backend == "jax" and not complex_transfer_safe():
        return "numpy"
    return backend


