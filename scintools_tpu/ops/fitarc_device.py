"""Whole-survey arc fit as ONE device program.

Re-design of the peak/parabola stage of ``Dynspec.fit_arc``
(/root/reference/scintools/dynspec.py:1182-1311). The batched host
path (ops/fitarc.py:fit_arc_batch) already runs the expensive
arc-normalised profile on device, but then fetches every epoch's
folded profile ([B, numsteps/2] floats — ~0.5 MB per survey batch
over a tunneled link) and walks the peak on host in python loops.
Here the ENTIRE per-epoch tail — savgol smoothing, peak walk-out,
masked parabola fit, noise-error walk — is fixed-shape masked device
math appended to the profile program, so a survey batch returns ten
scalars per epoch and the fetch is one ~5 KB transfer.

Semantics are pinned to the host path index-for-index:

- savgol_filter(window, polyorder=1, mode='interp'): interior is the
  uniform moving mean (the order-1 Savitzky–Golay centre weight);
  the first/last ``window//2`` points come from a linear LS fit over
  the first/last ``window`` valid points (scipy's edge polyfit).
- the peak walk-outs replicate the HOST path's while-loops
  (ops/fitarc.py:_peak_parabola) — including their quirks (the scan
  starts at ``ind±2``; the noise walk's left scan stops at index 2
  and over-counts by one; a fully-walked-out left edge lands on
  index -1, which python wraps to the last valid element) — see
  _fit_one below. NOTE one deliberate host-pinned deviation from the
  reference: the reference's LEFT power walk loops ``while power >
  threshold and ind + ind1 < len(smoothed) - 1`` (dynspec.py:
  1216-1218) — bounding the left scan by the RIGHT edge, so a peak
  near the start can walk to negative indices and python-wrap — while
  the host path here (and therefore this program) bounds it at the
  array start (``ind - i1 > 0``). See docs/migrating.md.
- the parabola fit reproduces ``fit_parabola``
  (fit/models.py:221-233 → reference scint_models.py:300-328):
  x is scaled by 1000/ptp, the deg-2 LS solve runs in centred
  coordinates for f32 conditioning, and the covariance is
  np.polyfit(cov=True)'s — inv(AᵀA)·resid/(n-3), with the reference's
  sqrt-of-abs-diagonal error propagation.

The profile crop length per epoch (the host path's ``_prep_profile``
η-range selection — a pure function of etamin/etamax and the fdop
grid *when the folded profile is finite*) is computed on host by
:func:`eta_crop_lengths` and passed in as a traced int per epoch.
When an epoch's secondary spectrum carries non-finite pixels (−inf
dB from ``10·log10(0)``), the host path's finite mask would change
the η grid point-by-point — a data-dependent shape the fixed-shape
device program cannot follow. Such epochs are NaN-QUARANTINED
instead: ``eta_crop_lengths`` forces their length to 0 (via the
``profile_finite`` argument, wired by ``ops.fitarc.fit_arc_batch``),
so the device fit returns NaN η rather than silently disagreeing
with the host about which η each sample belongs to. See
docs/migrating.md.
"""

from __future__ import annotations

import numpy as np

from ..backend import get_jax


def eta_grid(numsteps):
    """The ascending per-epoch η grid factor: ``eta_array =
    etamin · eta_grid(numsteps)`` after the host path's finite-mask
    flip (ops/fitarc.py:_prep_profile). Also returns the folded
    (fdop ≥ 0) normalised-Doppler axis."""
    numsteps = int(numsteps) + int(numsteps) % 2
    fdopnew = np.linspace(-1.0, 1.0, numsteps)
    pos = fdopnew >= 0
    with np.errstate(divide="ignore"):
        etafrac = 1.0 / fdopnew[pos]
    return np.flip(etafrac) ** 2, fdopnew


def eta_crop_lengths(numsteps, etamins, etamaxs, profile_finite=None):
    """Per-epoch valid-prefix length L of the flipped folded profile:
    the count of ``etamin·etafrac² < etamax`` — evaluated with the
    identical float expression the host crop uses.

    This length is only the host crop when the folded profile is
    all-finite (``_prep_profile`` masks non-finite points BEFORE the
    η crop, which would change the grid shape per epoch).
    ``profile_finite`` — per-epoch bool (or scalar), e.g.
    ``np.isfinite(sspecs).all(axis=(1, 2))`` — marks epochs whose
    profile is guaranteed finite; epochs flagged False get L = 0, so
    the device fit NaN-quarantines them (module docstring) instead of
    fitting against a silently different η grid than the host would.
    """
    ef2, _ = eta_grid(numsteps)
    etamins = np.atleast_1d(np.asarray(etamins, dtype=float))
    etamaxs = np.atleast_1d(np.asarray(etamaxs, dtype=float))
    L = (etamins[:, None] * ef2[None, :]
         < etamaxs[:, None]).sum(axis=1).astype(np.int32)
    if profile_finite is not None:
        ok = np.broadcast_to(
            np.atleast_1d(np.asarray(profile_finite, dtype=bool)),
            L.shape)
        L = np.where(ok, L, 0).astype(np.int32)
    return L


def make_savgol_interp(nsmooth, H):
    """Fixed-shape masked ``savgol_filter(q[:L], nsmooth, 1,
    mode='interp')``: interior is the uniform moving mean (the
    order-1 Savitzky–Golay centre weight); the first/last
    ``nsmooth//2`` valid points come from a linear LS fit over the
    first/last ``nsmooth`` valid points (scipy's edge polyfit).
    Returns ``smooth(q[H], L) → [H]`` (entries at j >= L are unused
    garbage); pinned against scipy in tests/test_arc.py."""
    get_jax()                            # jax import guard
    import jax
    import jax.numpy as jnp

    w = int(nsmooth)
    half = w // 2
    tc = (w - 1) / 2.0
    t_rel = np.arange(w, dtype=float) - tc
    den_t = float(np.sum(t_rel ** 2))
    idx = np.arange(H, dtype=np.int32)

    def smooth(q, L):
        mov = jnp.convolve(q, jnp.ones(w, q.dtype) / w, mode="same")
        yl = q[:w]
        bl = jnp.dot(jnp.asarray(t_rel, q.dtype), yl) / den_t
        al = jnp.mean(yl)
        val_l = al + bl * (idx - tc)
        yr = jax.lax.dynamic_slice(q, (L - w,), (w,))
        br = jnp.dot(jnp.asarray(t_rel, q.dtype), yr) / den_t
        ar = jnp.mean(yr)
        val_r = ar + br * ((idx - (L - w)) - tc)
        return jnp.where(idx < half, val_l,
                         jnp.where(idx >= L - half, val_r, mov))

    return smooth


def make_arc_fit_batch_fn(tdel, fdop, delmax=None, startbin=3, cutmid=3,
                          numsteps=10000, nsmooth=5,
                          low_power_diff=-1.0, high_power_diff=-0.5,
                          constraint=(0.0, np.inf), noise_error=True,
                          pallas=None):
    """Build the jitted whole-fit program.

    Returns ``fn(sspecs[B, ntdel, nfdop], etamins[B], Ls[B]) →
    (out[B, 10], folded[B, numsteps//2])`` where the packed columns
    are ``(eta, etaerr, etaerr2, noise, lo, n, a2, a1, a0, scale)`` —
    the last six reconstruct the fit_parabola diagnostics (window
    start/length in the cropped array; parabola coefficients and the
    1000/ptp scale in the xs parameterisation). NaN η marks an epoch
    the host path would quarantine (profile too short, no grid point
    inside the constraint, too few window points for the covariance
    polyfit, or a forward parabola). ``folded`` is the
    device-resident folded profile (only fetch it when diagnostics
    are wanted).
    """
    jax = get_jax()
    import jax.numpy as jnp

    from .normsspec import make_arc_profile_batch_fn

    tdel = np.asarray(tdel, dtype=float)
    fdop = np.asarray(fdop, dtype=float)
    numsteps = int(numsteps) + int(numsteps) % 2
    H = numsteps // 2
    # every call builds a fresh program (callers cache per geometry —
    # ops/fitarc.py:_ARC_PROFILE_CACHE), so each entry is one
    # accounted build for the retrace gate
    from ..obs import retrace as _retrace

    _retrace.record_build(
        "ops.arc_fit_device",
        (tdel.tobytes(), fdop.tobytes(),
         None if delmax is None else float(delmax), int(startbin),
         int(cutmid), numsteps, int(nsmooth), float(low_power_diff),
         float(high_power_diff), tuple(map(float, constraint)),
         bool(noise_error)))
    if nsmooth % 2 != 1 or nsmooth < 3:
        raise ValueError("nsmooth must be an odd window >= 3 "
                         "(scipy savgol_filter requirement)")
    delmax = np.max(tdel) if delmax is None else float(delmax)
    n_rows = int(np.argmin(np.abs(tdel - delmax)))  # noise divisor

    profile_fn = make_arc_profile_batch_fn(
        tdel, fdop, delmax=delmax, startbin=startbin, cutmid=cutmid,
        numsteps=numsteps, fold=True, pallas=pallas)

    ef2, _ = eta_grid(numsteps)
    c0, c1 = float(constraint[0]), float(constraint[1])
    w = int(nsmooth)
    idx = np.arange(H, dtype=np.int32)
    smooth_one = make_savgol_interp(w, H)

    def _noise_batch(s):
        """sspec_noise over the batch, on device: the SAME pooled
        two-pass moment combination as the host path, via its
        xp-parameterised implementation (fitarc.py:sspec_noise_batch
        with xp=jnp)."""
        from .fitarc import sspec_noise_batch

        return sspec_noise_batch(s, cutmid, n_rows=n_rows, xp=jnp)

    def _fit_one(q, sm, L, eta_row, noise):
        valid = idx < L
        BIG = jnp.asarray(np.inf, q.dtype)

        # peak index: max of smoothed inside the constraint, then the
        # reference's argmin(|smoothed - max|) over the WHOLE cropped
        # array (dynspec.py:1205-1213)
        inr = valid & (eta_row > c0) & (eta_row < c1)
        has_inr = jnp.any(inr)
        max_in = jnp.max(jnp.where(inr, sm, -BIG))
        ind = jnp.argmin(jnp.where(valid, jnp.abs(sm - max_in), BIG))
        max_power = sm[ind]

        # power walk-outs (host path ops/fitarc.py:_peak_parabola —
        # NOT the raw reference, whose left loop is bounded by the
        # right edge `ind + ind1 < len-1`; module docstring +
        # docs/migrating.md): the while-loops scan smoothed[ind-2],
        # ind-3, … (resp. ind+2, ind+3, …) until the first value at
        # or below threshold; the boundary stops at index 0 (resp.
        # L-1). Loop never entered when ind < 2 (resp. ind+1 >= L-1):
        # i stays 1.
        t_lo = max_power + low_power_diff
        t_hi = max_power + high_power_diff
        if low_power_diff < 0:           # loop never entered otherwise
            ml = valid & (idx <= ind - 2) & (sm <= t_lo)
            jl = jnp.max(jnp.where(ml, idx, -1))
            i1 = jnp.where(ind >= 2,
                           jnp.where(jl >= 0, ind - jl, ind), 1)
        else:
            i1 = jnp.asarray(1, idx.dtype)
        if high_power_diff < 0:
            mr = valid & (idx >= ind + 2) & (sm <= t_hi)
            jr = jnp.min(jnp.where(mr, idx, H + 1))
            i2 = jnp.where(ind + 1 < L - 1,
                           jnp.where(jr <= H, jr - ind, L - 1 - ind),
                           1)
        else:
            i2 = jnp.asarray(1, idx.dtype)

        # masked parabola fit over [ind-i1, ind+i2) — fit_parabola
        # (fit/models.py:221-233): xs = x·1000/ptp, deg-2 LS, polyfit
        # covariance = inv(AᵀA)·resid/(n-3). Solved in centred/scaled
        # u = (xs - mean)/500 (u ∈ ~[-2, 2]) so the normal equations
        # stay f32-conditioned, then mapped back to the xs
        # parameterisation for the reference's error formula.
        lo, hi = ind - i1, ind + i2
        wm = valid & (idx >= lo) & (idx < hi)
        n = jnp.sum(wm)
        nf_ = n.astype(q.dtype)
        xmin = jnp.min(jnp.where(wm, eta_row, BIG))
        xmax = jnp.max(jnp.where(wm, eta_row, -BIG))
        scale = 1000.0 / (xmax - xmin)
        xs = eta_row * scale
        m = jnp.sum(jnp.where(wm, xs, 0.0)) / nf_
        h = 500.0
        u = jnp.where(wm, (xs - m) / h, 0.0)
        # centre y too: the constant term absorbs any shift, so the
        # LS residuals are invariant — but in f32 they'd otherwise be
        # tiny differences of O(|y|) numbers (measured ~5% noise on
        # etaerr2 without this)
        ym = jnp.sum(jnp.where(wm, q, 0.0)) / nf_
        y = jnp.where(wm, q - ym, 0.0)
        u2 = u * u
        S1 = jnp.sum(u)
        S2 = jnp.sum(u2)
        S3 = jnp.sum(u2 * u)
        S4 = jnp.sum(u2 * u2)
        G = jnp.array([[S4, S3, S2], [S3, S2, S1], [S2, S1, nf_]])
        r = jnp.array([jnp.sum(u2 * y), jnp.sum(u * y), jnp.sum(y)])
        c = jnp.linalg.solve(G, r)
        c2, c1_, c0_ = c[0], c[1], c[2]
        fitv = c2 * u2 + c1_ * u + c0_
        resid = jnp.sum(jnp.where(wm, (y - fitv) ** 2, 0.0))
        fac = resid / (nf_ - 3.0)        # np.polyfit cov scale: n-dof
        Ginv = jnp.linalg.inv(G)
        var_c2 = Ginv[0, 0] * fac
        var_c1 = Ginv[1, 1] * fac
        cov12 = Ginv[0, 1] * fac
        a2 = c2 / h ** 2
        a1 = c1_ / h - 2.0 * m * c2 / h ** 2
        var_a2 = var_c2 / h ** 4
        var_a1 = (var_c1 / h ** 2 + 4.0 * m ** 2 / h ** 4 * var_c2
                  - 4.0 * m / h ** 3 * cov12)
        err_a1 = jnp.sqrt(jnp.abs(var_a1))
        err_a2 = jnp.sqrt(jnp.abs(var_a2))
        eta_fit = (-a1 / (2.0 * a2)) / scale
        etaerr2 = jnp.sqrt(err_a1 ** 2 * (1.0 / (2.0 * a2)) ** 2
                           + err_a2 ** 2 * (a1 / 2.0) ** 2) / scale

        # noise-error walk (dynspec.py:1232-1247): left scan reads
        # smoothed[ind-1] … smoothed[2] and lands one PAST the
        # crossing (i1 = ind - j* + 1); right scan mirrors the power
        # walk with threshold max-noise. ind <= 2 (resp.
        # ind+1 >= L-1) skips the loop: i stays 1.
        t_n = max_power - noise
        walk = noise > 0                 # noise <= 0: loop not entered
        mln = valid & (idx >= 2) & (idx <= ind - 1) & (sm <= t_n)
        jln = jnp.max(jnp.where(mln, idx, -1))
        i1n = jnp.where(walk & (ind > 2),
                        jnp.where(jln >= 0, ind - jln + 1, ind - 1), 1)
        mrn = valid & (idx >= ind + 2) & (sm <= t_n)
        jrn = jnp.min(jnp.where(mrn, idx, H + 1))
        i2n = jnp.where(walk & (ind + 1 < L - 1),
                        jnp.where(jrn <= H, jrn - ind, L - 1 - ind), 1)
        il = jnp.mod(ind - i1n, L)       # python wrap: eta_array[-1]
        ir = jnp.minimum(ind + i2n, L - 1)
        err_noise = jnp.abs(eta_row[il] - eta_row[ir]) / 2.0

        # host-path quarantine conditions → NaN η (fit_arc_batch
        # catches the equivalent ValueErrors)
        # lo < 0 (peak on the first grid point): the host slice
        # eta_array[-1:hi] is empty and fit_parabola's ptp raises →
        # quarantine, matching here
        ok = ((L > w) & has_inr & (n > 3) & (lo >= 0) & ~(a2 > 0)
              & jnp.isfinite(eta_fit))
        nan = jnp.asarray(np.nan, q.dtype)
        sq2 = np.sqrt(2.0)
        etaerr = (err_noise if noise_error else etaerr2) / sq2
        # window + xs-parameterisation coefficients so the host can
        # rebuild the fit_parabola diagnostics (yfit over xdata =
        # eta_array[lo:lo+n]) without fetching the profile
        a0 = ym + c0_ - c1_ * m / h + c2 * m ** 2 / h ** 2
        return (jnp.where(ok, eta_fit, nan),
                jnp.where(ok, etaerr, nan),
                jnp.where(ok, etaerr2 / sq2, nan),
                lo.astype(q.dtype), n.astype(q.dtype),
                a2, a1, a0, scale)

    def program(sspecs, etamins, Ls):
        folded = profile_fn(sspecs, etamins)
        q = jnp.flip(folded, axis=1)
        eta_rows = etamins[:, None] * jnp.asarray(ef2, folded.dtype)
        noises = _noise_batch(sspecs)
        sm = jax.vmap(smooth_one)(q, Ls)
        cols = jax.vmap(_fit_one)(q, sm, Ls, eta_rows, noises)
        packed = jnp.stack(cols[:3] + (noises,) + cols[3:], axis=1)
        return packed, folded

    return jax.jit(program)


# ---------------------------------------------------------------------
# abstract program probe (obs/programs.py) — audited by the jaxlint
# JP2xx program pass (tools/jaxlint/program.py)
# ---------------------------------------------------------------------

from ..obs.programs import register_probe as _register_probe  # noqa: E402


@_register_probe("ops.arc_fit_device",
                 formulations=("ops.arc_profile_interp",))
def _probe_arc_fit_device():
    """Fixed small geometry: 2 epochs, 16x16 secondary spectrum, 32
    profile steps; ``Ls`` is the per-epoch valid profile length
    (int32, as the host driver passes it)."""
    import jax

    tdel = np.linspace(0.0, 1.0, 16)
    fdop = np.linspace(-1.0, 1.0, 16)
    fn = make_arc_fit_batch_fn(tdel, fdop, numsteps=32, pallas=False)
    S = jax.ShapeDtypeStruct
    return fn, (S((2, 16, 16), np.float32), S((2,), np.float32),
                S((2,), np.int32))
