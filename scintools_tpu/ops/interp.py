"""Interpolation utilities: NaN infill and batched 1-D resampling.

Hosts the equivalents of ``interp_nan_2d`` (/root/reference/scintools/
scint_utils.py:769-784) and the cubic-interpolation loops used by
``scale_dyn`` (dynspec.py:3949-3956, :4062-4074), vectorised.
"""

from __future__ import annotations

import numpy as np
from scipy.interpolate import griddata, interp1d


def interp_nan_2d(array, method="linear"):
    """Fill NaNs of a 2-D array by interpolation from valid neighbours
    (scint_utils.py:769-784)."""
    array = np.array(array, dtype=float).squeeze()
    x = np.arange(array.shape[1])
    y = np.arange(array.shape[0])
    marr = np.ma.masked_invalid(array)
    xx, yy = np.meshgrid(x, y)
    x1 = xx[~marr.mask]
    y1 = yy[~marr.mask]
    newarr = np.ravel(array[~marr.mask])
    return griddata((x1, y1), newarr, (xx, yy), method=method)


def columnwise_cubic_interp(arr, x_src, x_new, axis=0):
    """Cubic interpolation of each 1-D slice of ``arr`` along ``axis``
    from coordinates x_src onto x_new (the reference's per-column
    interp1d loop, vectorised via scipy's axis support)."""
    f = interp1d(x_src, arr, kind="cubic", axis=axis)
    x_new = np.clip(x_new, np.min(x_src), np.max(x_src))
    return f(x_new)


