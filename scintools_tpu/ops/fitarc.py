"""Scintillation-arc curvature measurement (Hough-style η grid search).

Re-design of ``Dynspec.fit_arc`` (/root/reference/scintools/
dynspec.py:970-1346): normalise the secondary spectrum for a trial
curvature, delay-scrunch to a Doppler profile, and fit a parabola to
the profile peak over a √η grid. The batched row interpolation (the
hot part) lives in :mod:`normsspec`; the peak search and parabola fit
are cheap 1-D host work.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
from scipy.signal import savgol_filter

from .normsspec import normalise_sspec
from ..fit.models import fit_parabola, fit_log_parabola

# compiled arc-profile programs keyed on (geometry, mesh) — see
# fit_arc_batch
_ARC_PROFILE_CACHE = {}


@dataclass
class ArcFit:
    """Result of a single arc-curvature fit."""

    eta: float
    etaerr: float          # noise-based error (or parabola error)
    etaerr2: float         # parabola-fit error
    eta_array: np.ndarray  # η grid searched
    profile: np.ndarray    # delay-scrunched power profile over η grid
    norm_fdop: np.ndarray  # normalised fdop axis of the profile
    noise: float
    prob_eta_peak: np.ndarray = None
    yfit: np.ndarray = None
    xdata: np.ndarray = None


def sspec_noise(sspec, cutmid, n_rows):
    """Noise estimate from the outer quadrants of the secondary
    spectrum (dynspec.py:1091-1109)."""
    nr, nc = np.shape(sspec)
    a = np.asarray(sspec)[int(nr / 2):,
                          int(nc / 2 + np.ceil(cutmid / 2)):].ravel()
    b = np.asarray(sspec)[int(nr / 2):,
                          0:int(nc / 2 - np.floor(cutmid / 2))].ravel()
    noise = np.std(np.concatenate((a, b)))
    return noise / np.sqrt(n_rows * 2)


def sspec_noise_batch(sspecs, cutmid, n_rows, xp=np):
    """:func:`sspec_noise` over an epoch batch ``[B, nr, nc]`` in one
    vectorised pass (one std per epoch instead of B python calls).
    The two quadrant slices stay views — their first/second moments
    combine into the concatenated population std without the copy.
    ``xp`` selects the array namespace: the device fit program
    (ops/fitarc_device.py) runs the SAME implementation with
    ``xp=jax.numpy`` so the two paths cannot drift."""
    sspecs = xp.asarray(sspecs)
    _, nr, nc = sspecs.shape
    a = sspecs[:, int(nr / 2):, int(nc / 2 + np.ceil(cutmid / 2)):]
    b = sspecs[:, int(nr / 2):, 0:int(nc / 2 - np.floor(cutmid / 2))]
    # pooled-variance combination of the two slices' (stable, two-pass)
    # per-epoch moments — NOT the one-pass E[x²]−E[x]² form, which
    # cancels catastrophically when std ≪ |mean|
    na = a.shape[1] * a.shape[2]
    nb = b.shape[1] * b.shape[2]
    n = na + nb
    if n == 0:
        # BOTH quadrants zero-width (cutmid >= Doppler width): the
        # serial path stds an empty concat → NaN with a RuntimeWarning;
        # return the same NaNs without the raw divide warning
        return xp.full(sspecs.shape[0], np.nan)
    # an empty quadrant (narrow Doppler axis + large cutmid)
    # contributes nothing — mirror the serial path's concatenation,
    # where the empty slice simply vanishes
    zeros = xp.zeros(sspecs.shape[0], dtype=sspecs.dtype)
    mu_a = a.mean(axis=(1, 2)) if na else zeros
    mu_b = b.mean(axis=(1, 2)) if nb else zeros
    var_a = a.var(axis=(1, 2)) if na else zeros
    var_b = b.var(axis=(1, 2)) if nb else zeros
    mu = (na * mu_a + nb * mu_b) / n
    var = (na * (var_a + (mu_a - mu) ** 2)
           + nb * (var_b + (mu_b - mu) ** 2)) / n
    return xp.sqrt(var) / np.sqrt(n_rows * 2)


def _profile_from_norm(ns, asymm=False):
    """Fold the scrunched profile about fdop=0 (dynspec.py:1166-1180)."""
    prof = np.asarray(ns.normsspecavg).squeeze()
    fdopnew = np.asarray(ns.fdop).squeeze()
    pos = fdopnew >= 0
    neg = fdopnew < 0
    p_pos = prof[pos]
    p_neg = np.flip(prof[neg])
    etafrac = 1.0 / fdopnew[pos]
    if asymm:
        return [p_pos, p_neg], etafrac
    return [(p_pos + p_neg) / 2], etafrac


def fit_arc_profile(spec, etafrac, etamin, etamax, constraint=(0, np.inf),
                    nsmooth=5, low_power_diff=-1, high_power_diff=-0.5,
                    noise=0.0, noise_error=True, log_parabola=False,
                    efac=1):
    """Peak search + parabola fit on one folded profile
    (dynspec.py:1182-1282)."""
    spec, eta_array = _prep_profile(spec, etafrac, etamin, etamax)
    if len(spec) <= nsmooth:
        raise ValueError(
            f"profile has only {len(spec)} valid points — too few for "
            f"smoothing window nsmooth={nsmooth}")
    smoothed = savgol_filter(spec, nsmooth, 1)
    return _peak_parabola(spec, smoothed, eta_array,
                          constraint=constraint,
                          low_power_diff=low_power_diff,
                          high_power_diff=high_power_diff, noise=noise,
                          noise_error=noise_error,
                          log_parabola=log_parabola, efac=efac)


def _prep_profile(spec, etafrac, etamin, etamax):
    """Shared profile prep (dynspec.py:1182-1203): finite mask, flip
    to ascending η, crop at etamax. One definition for the serial and
    batch paths so their semantics cannot drift."""
    spec = np.asarray(spec).squeeze()
    etafrac = np.asarray(etafrac).squeeze()

    valid = np.isfinite(spec)
    spec = np.flip(spec[valid])
    etafrac = np.flip(etafrac[valid])

    eta_array = float(etamin) * etafrac ** 2
    sel = eta_array < float(etamax)
    return spec[sel], eta_array[sel]


def _peak_parabola(spec, smoothed, eta_array, constraint=(0, np.inf),
                   low_power_diff=-1, high_power_diff=-0.5, noise=0.0,
                   noise_error=True, log_parabola=False, efac=1):
    """Peak walk-out + parabola fit on an already-smoothed profile
    (dynspec.py:1205-1282). Split from :func:`fit_arc_profile` so the
    batch path can smooth whole epoch groups in one savgol call."""
    inrange = np.flatnonzero((eta_array > constraint[0])
                             & (eta_array < constraint[1]))
    if len(inrange) == 0:
        raise ValueError("no η grid points inside constraint range")
    max_in = np.max(smoothed[inrange])
    ind = int(np.argmin(np.abs(smoothed - max_in)))

    max_power = smoothed[ind]
    power = max_power
    i1 = 1
    while (power > max_power + low_power_diff
           and ind - i1 > 0):
        i1 += 1
        power = smoothed[ind - i1]
    power = max_power
    i2 = 1
    while (power > max_power + high_power_diff
           and ind + i2 < len(smoothed) - 1):
        i2 += 1
        power = smoothed[ind + i2]

    xdata = eta_array[int(ind - i1):int(ind + i2)]
    ydata = spec[int(ind - i1):int(ind + i2)]
    if log_parabola:
        yfit, eta, etaerr = fit_log_parabola(xdata, ydata)
    else:
        yfit, eta, etaerr = fit_parabola(xdata, ydata)
    if np.mean(np.gradient(np.diff(yfit))) > 0:
        raise ValueError("Fit returned a forward parabola.")

    etaerr2 = etaerr
    if noise_error:
        power = max_power
        i1 = 1
        while power > (max_power - noise) and (ind - i1 > 1):
            power = smoothed[ind - i1]
            i1 += 1
        power = max_power
        i2 = 1
        while (power > (max_power - noise)
               and (ind + i2 < len(smoothed) - 1)):
            i2 += 1
            power = smoothed[ind + i2]
        etaerr = np.abs(eta_array[int(ind - i1)]
                        - eta_array[int(ind + i2)]) / 2

    sigma = noise * efac
    with np.errstate(divide="ignore", invalid="ignore"):
        prob = (1 / (sigma * np.sqrt(2 * np.pi))
                * np.exp(-0.5 * ((spec - np.max(spec)) / sigma) ** 2))

    # the reference stores every curvature error divided by sqrt(2)
    # (dynspec.py:1288-1311)
    return ArcFit(eta=float(eta), etaerr=float(etaerr) / np.sqrt(2),
                  etaerr2=float(etaerr2) / np.sqrt(2),
                  eta_array=eta_array,
                  profile=spec, norm_fdop=None, noise=noise,
                  prob_eta_peak=prob, yfit=yfit, xdata=xdata)


def fit_arc(sspec, yaxis, fdop, asymm=False, delmax=None, numsteps=1e4,
            startbin=3, cutmid=3, etamax=None, etamin=None,
            low_power_diff=-1, high_power_diff=-0.5,
            constraint=(0, np.inf), nsmooth=5, efac=1, noise_error=True,
            log_parabola=False, logsteps=False, fit_spectrum=False,
            subtract_artefacts=False, weighted=False, backend=None):
    """Arc-curvature measurement on a (dB) secondary spectrum.

    Works in a single consistent curvature convention: ``yaxis`` is the
    delay-like axis (β [m^-1] for λ-scaled spectra, else tdel [us]) and
    η relates them by yaxis = η·fdop². Unit conversions between the
    β and tdel conventions are the caller's (façade's) responsibility
    — the reference interleaves them with the search
    (dynspec.py:1140-1148).

    Returns a list of :class:`ArcFit` (two entries when ``asymm``).
    """
    sspec = np.array(sspec, dtype=float)
    yaxis = np.asarray(yaxis, dtype=float)
    if etamin is not None and np.any(np.asarray(etamin) <= 0):
        raise ValueError("etamin must be positive (curvature is η > 0)")
    if etamax is not None and np.any(np.asarray(etamax) <= 0):
        raise ValueError("etamax must be positive (curvature is η > 0)")
    if int(numsteps) <= 2 * nsmooth:
        raise ValueError(
            f"numsteps={int(numsteps)} too coarse for the smoothing "
            f"window (nsmooth={nsmooth}); increase numsteps")
    delmax = np.max(yaxis) if delmax is None else delmax

    ind = int(np.argmin(np.abs(yaxis - delmax)))
    ymax = yaxis[ind]

    noise = sspec_noise(sspec, cutmid, n_rows=ind)

    if etamax is None:
        etamax = ymax / ((fdop[1] - fdop[0]) * cutmid) ** 2
    if etamin is None:
        etamin = (yaxis[1] - yaxis[0]) * startbin / np.max(fdop) ** 2

    etamin_array = np.atleast_1d(np.asarray(etamin, dtype=float))
    etamax_array = np.atleast_1d(np.asarray(etamax, dtype=float))

    sqrt_eta_all = np.linspace(np.sqrt(np.min(etamin_array)),
                               np.sqrt(np.max(etamax_array)),
                               int(numsteps))

    fits = []
    for iarc in range(len(etamin_array)):
        emin = float(etamin_array[iarc])
        emax = float(etamax_array[iarc])
        sqrt_eta = sqrt_eta_all[(sqrt_eta_all <= np.sqrt(emax))
                                & (sqrt_eta_all >= np.sqrt(emin))]
        numsteps_new = len(sqrt_eta)

        ns = normalise_sspec(sspec, yaxis, fdop, eta=emin, delmax=delmax,
                             startbin=startbin, maxnormfac=1,
                             cutmid=cutmid, numsteps=numsteps_new,
                             logsteps=logsteps, weighted=weighted,
                             fit_spectrum=fit_spectrum,
                             subtract_artefacts=subtract_artefacts,
                             backend=backend)
        specs, etafrac = _profile_from_norm(ns, asymm=asymm)
        for spec in specs:
            fit = fit_arc_profile(
                spec, etafrac, emin, emax, constraint=constraint,
                nsmooth=nsmooth, low_power_diff=low_power_diff,
                high_power_diff=high_power_diff, noise=noise,
                noise_error=noise_error, log_parabola=log_parabola,
                efac=efac)
            fit.norm_fdop = ns.fdop
            fits.append(fit)
    return fits


def fit_arc_batch(sspecs, yaxis, fdop, delmax=None, numsteps=1e4,
                  startbin=3, cutmid=3, etamax=None, etamin=None,
                  low_power_diff=-1, high_power_diff=-0.5,
                  constraint=(0, np.inf), nsmooth=5, efac=1,
                  noise_error=True, log_parabola=False, mesh=None,
                  sspecs_device=None, on_device=None,
                  full_output=True):
    """Arc-curvature fit over a whole batch of same-geometry epochs.

    The reference runs ``fit_arc`` serially per epoch inside its
    survey loop (dynspec.py:4357 → :970-1311); here the expensive
    part — the arc-normalised row interpolation and delay scrunch —
    is ONE jitted program over the epoch batch
    (ops/normsspec.py:make_arc_profile_batch_fn), optionally sharded
    over a device ``mesh`` (parallel/survey.py:
    make_arc_profile_sharded), and only the cheap peak/parabola fit
    runs per epoch on host. Covers the reference's default single-arc
    search (``asymm/logsteps/weighted/fit_spectrum`` off) — for those
    variants call :func:`fit_arc` per epoch.

    ``sspecs[B, ntdel, nfdop]`` in dB with shared axes ``yaxis`` (us
    or m⁻¹) and ``fdop`` (mHz); ``etamin``/``etamax`` may be scalars
    (shared) or per-epoch arrays. Returns a list of B
    :class:`ArcFit`.

    ``sspecs_device`` optionally supplies the SAME spectra as an
    already-staged device array (any float dtype) — a steady-state
    survey pipeline keeps epochs resident on device, and re-uploading
    them per call would time the host link instead of the program.

    ``on_device`` selects where the post-profile tail (savgol, peak
    walk-out, parabola fit, noise estimate) runs. The default (None →
    True unless ``log_parabola``) appends it to the profile program
    (ops/fitarc_device.py) so the whole fit is ONE dispatch returning
    ten scalars per epoch (η, errors, noise, plus the peak window and
    parabola coefficients); ``on_device=False`` keeps the f64 host
    tail (the parity oracle, and the only path for ``log_parabola``).
    With the device path, ``full_output=False`` skips fetching the
    folded profiles — the ArcFit diagnostics fields (profile,
    eta_array, prob_eta_peak, xdata, yfit) are then None, which is
    what a survey driver that only consumes eta/etaerr wants on a
    tunneled link; with ``full_output=True`` every diagnostic is
    rebuilt host-side from the packed columns.
    """
    import jax.numpy as jnp

    from .normsspec import make_arc_profile_batch_fn

    sspecs = np.asarray(sspecs, dtype=float)
    B = len(sspecs)
    yaxis = np.asarray(yaxis, dtype=float)
    fdop = np.asarray(fdop, dtype=float)
    if etamin is not None and np.any(np.asarray(etamin) <= 0):
        raise ValueError("etamin must be positive (curvature is η > 0)")
    if etamax is not None and np.any(np.asarray(etamax) <= 0):
        raise ValueError("etamax must be positive (curvature is η > 0)")
    # even grid (normalise_sspec's nfdop rounding): the ±fdop fold
    # below pairs bins about zero, and the profile fn applies the
    # same rounding
    numsteps = int(numsteps) + int(numsteps) % 2
    if numsteps <= 2 * nsmooth:
        raise ValueError(
            f"numsteps={numsteps} too coarse for the smoothing "
            f"window (nsmooth={nsmooth}); increase numsteps")
    delmax = np.max(yaxis) if delmax is None else delmax
    ind = int(np.argmin(np.abs(yaxis - delmax)))
    ymax = yaxis[ind]
    if etamax is None:
        etamax = ymax / ((fdop[1] - fdop[0]) * cutmid) ** 2
    if etamin is None:
        etamin = (yaxis[1] - yaxis[0]) * startbin / np.max(fdop) ** 2
    etamin_b = np.broadcast_to(np.asarray(etamin, dtype=float),
                               (B,)).copy()
    etamax_b = np.broadcast_to(np.asarray(etamax, dtype=float),
                               (B,)).copy()
    if on_device is None:
        on_device = not log_parabola
    if on_device and log_parabola:
        raise ValueError("log_parabola is host-only — pass "
                         "on_device=False")

    # cache the compiled program per (geometry, fit params, mesh): a
    # survey driver calls this per epoch batch, and a rebuilt jax.jit
    # retraces+recompiles every time (~200× the warm run). Same
    # pattern as dynspec._SHARDED_GRID_CACHE.
    mesh_key = None
    if mesh is not None:
        mesh_key = (tuple(d.id for d in np.ravel(mesh.devices)),
                    tuple(mesh.axis_names),
                    tuple(mesh.shape.values()))
    fit_key = (int(nsmooth), float(low_power_diff),
               float(high_power_diff), tuple(map(float, constraint)),
               bool(noise_error)) if on_device else None
    from .arc_pallas import arc_profile_pallas_enabled
    key = (yaxis.tobytes(), fdop.tobytes(), float(delmax),
           int(startbin), int(cutmid), int(numsteps), mesh_key,
           fit_key, arc_profile_pallas_enabled())
    entry = _ARC_PROFILE_CACHE.get(key)
    if entry is None:
        if len(_ARC_PROFILE_CACHE) >= 8:
            _ARC_PROFILE_CACHE.pop(next(iter(_ARC_PROFILE_CACHE)))
        if on_device:
            if mesh is not None:
                from ..parallel.survey import make_arc_fit_sharded

                entry = make_arc_fit_sharded(
                    mesh, yaxis, fdop, delmax=delmax,
                    startbin=startbin, cutmid=cutmid,
                    numsteps=int(numsteps), nsmooth=nsmooth,
                    low_power_diff=low_power_diff,
                    high_power_diff=high_power_diff,
                    constraint=constraint, noise_error=noise_error)
            else:
                from .fitarc_device import make_arc_fit_batch_fn

                entry = (make_arc_fit_batch_fn(
                    yaxis, fdop, delmax=delmax, startbin=startbin,
                    cutmid=cutmid, numsteps=int(numsteps),
                    nsmooth=nsmooth, low_power_diff=low_power_diff,
                    high_power_diff=high_power_diff,
                    constraint=constraint,
                    noise_error=noise_error), 1)
        elif mesh is not None:
            from ..parallel.survey import make_arc_profile_sharded

            entry = make_arc_profile_sharded(
                mesh, yaxis, fdop, delmax=delmax, startbin=startbin,
                cutmid=cutmid, numsteps=int(numsteps), fold=True)
        else:
            entry = (make_arc_profile_batch_fn(
                yaxis, fdop, delmax=delmax, startbin=startbin,
                cutmid=cutmid, numsteps=int(numsteps), fold=True), 1)
        _ARC_PROFILE_CACHE[key] = entry
    fn, ndev = entry

    pad = (-B) % ndev
    e_in = np.concatenate([etamin_b] + [etamin_b[-1:]] * pad) \
        if pad else etamin_b
    if sspecs_device is not None:
        if tuple(sspecs_device.shape) != sspecs.shape:
            raise ValueError(
                f"sspecs_device shape {tuple(sspecs_device.shape)} "
                f"!= host sspecs shape {sspecs.shape} — the device "
                "copy must be the same epoch batch")
        s_dev = sspecs_device
        if pad:
            s_dev = jnp.concatenate([s_dev] + [s_dev[-1:]] * pad)
    else:
        s_in = np.concatenate([sspecs] + [sspecs[-1:]] * pad) \
            if pad else sspecs
        s_dev = jnp.asarray(s_in)

    if on_device:
        from .fitarc_device import eta_crop_lengths, eta_grid

        emax_in = np.concatenate([etamax_b] + [etamax_b[-1:]] * pad) \
            if pad else etamax_b
        # −inf dB pixels (10·log10(0)) would make the host path's
        # finite mask reshape the η grid per epoch — a data-dependent
        # shape the device program cannot follow. Flag those epochs so
        # eta_crop_lengths zeroes their length and the device fit
        # NaN-quarantines them (fitarc_device module docstring).
        fin_b = np.isfinite(sspecs).all(axis=(1, 2))
        fin_in = np.concatenate([fin_b] + [fin_b[-1:]] * pad) \
            if pad else fin_b
        Ls = eta_crop_lengths(numsteps, e_in, emax_in,
                              profile_finite=fin_in)
        packed, folded_dev = fn(s_dev, jnp.asarray(e_in),
                                jnp.asarray(Ls))
        out = np.asarray(packed)[:B]     # ONE tiny fetch: [B, 10]
        ef2, fdopnew = eta_grid(numsteps)
        with np.errstate(divide="ignore"):
            # the UNflipped profile-order etafrac (_prep_profile
            # flips internally); ef2 is already flipped-ascending
            etafrac_f = 1.0 / fdopnew[fdopnew >= 0]
        folded = np.asarray(folded_dev)[:B] if full_output else None
        fits = []
        for b in range(B):
            (eta_b, err_b, err2_b, noise_b, lo_b, n_b, a2_b, a1_b,
             a0_b, scale_b) = out[b]
            fit = ArcFit(eta=float(eta_b), etaerr=float(err_b),
                         etaerr2=float(err2_b),
                         eta_array=None, profile=None,
                         norm_fdop=fdopnew, noise=float(noise_b))
            if full_output:
                spec = folded[b]
                spec_s, eta_s = _prep_profile(
                    spec, etafrac_f, etamin_b[b], etamax_b[b])
                if np.isfinite(eta_b):
                    fit.profile, fit.eta_array = spec_s, eta_s
                    sigma = float(noise_b) * efac
                    with np.errstate(divide="ignore",
                                     invalid="ignore"):
                        fit.prob_eta_peak = (
                            1 / (sigma * np.sqrt(2 * np.pi))
                            * np.exp(-0.5 * ((spec_s - np.max(spec_s))
                                             / sigma) ** 2))
                    # fit_parabola diagnostics from the packed window
                    # + xs-parameterisation coefficients
                    lo_i, n_i = int(lo_b), int(n_b)
                    fit.xdata = eta_s[lo_i:lo_i + n_i]
                    xs = fit.xdata * float(scale_b)
                    fit.yfit = (float(a2_b) * xs ** 2
                                + float(a1_b) * xs + float(a0_b))
                else:           # quarantined: _nan_fit's shape — the
                    # UNflipped profile paired with its descending
                    # eta axis (profile order, not crop order)
                    fit.profile = spec
                    fit.eta_array = (float(etamin_b[b])
                                     * etafrac_f ** 2)
            fits.append(fit)
        return fits

    noises = sspec_noise_batch(sspecs, cutmid, n_rows=ind)
    # device program returns the ±fdop-folded profile (fold=True):
    # half the fetch over the tunnel, and the fold rides the chip
    folded = np.asarray(fn(s_dev, jnp.asarray(e_in)))[:B]  # sync-ok:
    # result-consumption boundary — the host parabola tail needs it

    fdopnew = np.linspace(-1.0, 1.0, int(numsteps))
    pos = fdopnew >= 0
    with np.errstate(divide="ignore"):
        etafrac = 1.0 / fdopnew[pos]

    # Per-epoch prep (finite mask, η-range crop) is cheap numpy; the
    # expensive savgol smoothing — dominated by its edge polyfits —
    # runs ONCE per group of equal-length profiles (one 2-D call),
    # which in the common survey case (shared geometry and η range,
    # geometry-determined NaN pattern) is a single call for all B
    # epochs. Row-wise it is the same computation scipy performs on a
    # 1-D input, so the result matches fit_arc_profile exactly.
    prepped = {}
    fits = [None] * B

    def _nan_fit(b, spec):
        # one arc-free epoch must not kill the whole survey batch
        # (the reference's per-epoch loop raises; its survey sorter
        # quarantines — NaN is the batch-API equivalent)
        return ArcFit(eta=np.nan, etaerr=np.nan, etaerr2=np.nan,
                      eta_array=float(etamin_b[b]) * etafrac ** 2,
                      profile=spec, norm_fdop=fdopnew,
                      noise=noises[b])

    for b in range(B):
        spec = folded[b]
        spec_s, eta_s = _prep_profile(spec, etafrac, etamin_b[b],
                                      etamax_b[b])
        if len(spec_s) <= nsmooth:
            fits[b] = _nan_fit(b, spec)
            continue
        prepped.setdefault(len(spec_s), []).append(
            (b, spec, spec_s, eta_s))

    for _, items in prepped.items():
        smoothed = savgol_filter(
            np.stack([it[2] for it in items]), nsmooth, 1, axis=-1)
        for (b, spec, spec_s, eta_s), sm_row in zip(items, smoothed):
            try:
                fit = _peak_parabola(
                    spec_s, sm_row, eta_s, constraint=constraint,
                    low_power_diff=low_power_diff,
                    high_power_diff=high_power_diff, noise=noises[b],
                    noise_error=noise_error,
                    log_parabola=log_parabola, efac=efac)
                fit.norm_fdop = fdopnew
                fits[b] = fit
            except ValueError:
                fits[b] = _nan_fit(b, spec)
    return fits
