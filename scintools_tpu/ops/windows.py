"""Edge-taper windows for spectral analysis.

Reproduces the semantics of the reference ``get_window``
(/root/reference/scintools/scint_utils.py:810-832): a window of
``floor(frac*n)`` points is split at its midpoint and the two halves are
placed at the array edges with ones in between, so only the outer
``frac`` fraction of pixels is tapered.

Windows are built host-side in numpy (cheap, one-time) and fed to the
device kernels as constants.
"""

from __future__ import annotations

import numpy as np

_WINDOW_FUNCS = {
    "hanning": np.hanning,
    "hamming": np.hamming,
    "blackman": np.blackman,
    "bartlett": np.bartlett,
}


def edge_taper(n, window="hanning", frac=0.1):
    """1-D edge-taper window of length ``n``.

    Matches ``np.insert(w, ceil(len(w)/2), ones(n-len(w)))`` of the
    reference: the first ceil(m/2) window samples, then ones, then the
    remaining floor(m/2) samples.
    """
    if window is None:
        return np.ones(n)
    try:
        wfunc = _WINDOW_FUNCS[window.lower()]
    except KeyError:
        raise ValueError(
            f"Window {window!r} unknown; options: {sorted(_WINDOW_FUNCS)}"
        )
    m = int(np.floor(frac * n))
    w = wfunc(m)
    return np.insert(w, int(np.ceil(len(w) / 2)), np.ones(n - len(w)))


def get_window(nt, nf, window="hanning", frac=0.1):
    """(chan_window[nt], subint_window[nf]) pair, reference-compatible."""
    return edge_taper(nt, window, frac), edge_taper(nf, window, frac)


def apply_window(dyn, chan_window, subint_window, xp=np):
    """Apply time (last-axis) and frequency (first-axis) tapers to
    ``dyn[..., nf, nt]``."""
    return dyn * xp.asarray(chan_window) * xp.asarray(subint_window)[..., :, None]
