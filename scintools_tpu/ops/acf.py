"""2-D autocovariance kernel.

Functional re-design of ``Dynspec.calc_acf`` (direct method,
/root/reference/scintools/dynspec.py:3780-3797): zero-padded
``fft2 → |·|² → ifft2 → fftshift``, normalised to peak. The slow
O(N^4) direct autocorrelation (scint_utils.py:67-84) is kept in
tests as the oracle.

The transform core routes through the structure-aware layer
(ops/xfft.py): the input is declared REAL, so the default
``'xfft.acf'`` formulation computes the Wiener–Khinchin round trip
as ``rfft2 → |·|² → irfft2`` — the discarded Hermitian half is never
computed and the inverse is real — with the complex ``fft2/ifft2``
path kept as the dense parity oracle.
"""

from __future__ import annotations

import numpy as np

from ..backend import get_xp, resolve_backend
from . import xfft


def autocovariance(dyn, normalise=True, mean_sub=True, backend=None,
                   variant=None):
    """2-D ACF of ``dyn[..., nf, nt]`` → shape (..., 2*nf, 2*nt).

    Batch dimensions vmap/broadcast transparently (the FFTs act on the
    last two axes). ``variant=None`` resolves the ``'xfft.acf'``
    formulation (backend.py registry): ``'real'`` is the declared
    real-input Wiener–Khinchin lowering, ``'dense'`` the complex
    oracle (bit-identical to the pre-layer formulation).
    """
    backend = resolve_backend(backend)
    xp = get_xp(backend)
    dyn = xp.asarray(dyn)
    nf, nt = dyn.shape[-2:]
    if mean_sub:
        # reference subtracts the mean over valid (finite) points; invalid
        # points then contribute zero (per batch slice, both backends)
        finite = xp.isfinite(dyn)
        dyn0 = xp.where(finite, dyn, 0.0)
        nvalid = xp.sum(finite, axis=(-2, -1), keepdims=True)
        mean = xp.sum(dyn0, axis=(-2, -1), keepdims=True) / nvalid
        dyn = xp.where(finite, dyn - mean, 0.0)
    p = xfft.plan((nf, nt), (2 * nf, 2 * nt), real_input=True,
                  layout="shifted", op="xfft.acf")
    arr = p.acf(dyn, xp=xp, variant=variant)
    if normalise:
        arr = arr / xp.max(arr, axis=(-2, -1), keepdims=True)
    return arr


def acf_from_sspec(sspec_db, normalise=True, backend=None,
                   variant=None):
    """ACF via the secondary spectrum ('sspec' method,
    dynspec.py:3798-3807). ``sspec_db`` must be the full-frame (not
    halved) spectrum in dB.

    The linear-power frame is REAL, so ``variant=None`` (the
    ``'xfft.acf_sspec'`` formulation) lowers the forward transform to
    a half-spectrum ``rfft2`` + Hermitian completion (ops/xfft.py);
    ``'dense'`` keeps the complex ``fft2`` as the parity oracle."""
    from ..backend import formulation

    backend = resolve_backend(backend)
    xp = get_xp(backend)
    s = xp.fft.fftshift(xp.asarray(sspec_db))
    lin = 10 ** (s / 10)
    if variant is None:
        variant = formulation("xfft.acf_sspec")
    p = xfft.plan(lin.shape, real_input=True, layout="shifted")
    arr = p.forward(lin, xp=xp,
                    variant="rfft" if variant == "real" else "fft2")
    arr = arr.real
    if normalise:
        arr = arr / xp.max(arr)
    return arr


def autocorr_direct(arr, mask=None):
    """Slow masked O(N^4) 2-D autocorrelation — test oracle
    (scint_utils.py:67-84 semantics, numpy only). A masked-array
    input keeps its mask (the reference's documented input type)."""
    in_mask = np.ma.getmaskarray(arr) if np.ma.isMaskedArray(arr) \
        else None
    arr = np.ma.masked_invalid(np.asarray(arr, dtype=float))
    if in_mask is not None:
        arr = np.ma.masked_array(arr, mask=arr.mask | in_mask)
    if mask is not None:
        arr = np.ma.masked_array(arr, mask=mask)
    mean = np.ma.mean(arr)
    std = np.ma.std(arr)
    nr, nc = arr.shape
    out = np.zeros((2 * nr, 2 * nc))
    for x in range(-nr, nr):
        for y in range(-nc, nc):
            seg = (arr[max(0, x):min(x + nr, nr), max(0, y):min(y + nc, nc)]
                   - mean) * (arr[max(0, -x):min(-x + nr, nr),
                                  max(0, -y):min(-y + nc, nc)] - mean)
            out[x + nr][y + nc] = np.ma.sum(seg) / (std ** 2)
    out /= np.nanmax(out)
    return out
