"""2-D autocovariance kernel.

Functional re-design of ``Dynspec.calc_acf`` (direct method,
/root/reference/scintools/dynspec.py:3780-3797): zero-padded
``fft2 → |·|² → ifft2 → fftshift``, normalised to peak. The slow
O(N^4) direct autocorrelation (scint_utils.py:67-84) is kept in
tests as the oracle.
"""

from __future__ import annotations

import numpy as np

from ..backend import get_xp, resolve_backend


def autocovariance(dyn, normalise=True, mean_sub=True, backend=None):
    """2-D ACF of ``dyn[..., nf, nt]`` → shape (..., 2*nf, 2*nt).

    Batch dimensions vmap/broadcast transparently (the FFTs act on the
    last two axes).
    """
    backend = resolve_backend(backend)
    xp = get_xp(backend)
    dyn = xp.asarray(dyn)
    nf, nt = dyn.shape[-2:]
    if mean_sub:
        # reference subtracts the mean over valid (finite) points; invalid
        # points then contribute zero (per batch slice, both backends)
        finite = xp.isfinite(dyn)
        dyn0 = xp.where(finite, dyn, 0.0)
        nvalid = xp.sum(finite, axis=(-2, -1), keepdims=True)
        mean = xp.sum(dyn0, axis=(-2, -1), keepdims=True) / nvalid
        dyn = xp.where(finite, dyn - mean, 0.0)
    arr = xp.fft.fft2(dyn, s=(2 * nf, 2 * nt))
    arr = xp.abs(arr) ** 2
    arr = xp.fft.ifft2(arr)
    arr = xp.fft.fftshift(arr, axes=(-2, -1))
    arr = arr.real
    if normalise:
        arr = arr / xp.max(arr, axis=(-2, -1), keepdims=True)
    return arr


def acf_from_sspec(sspec_db, normalise=True, backend=None):
    """ACF via the secondary spectrum ('sspec' method,
    dynspec.py:3798-3807). ``sspec_db`` must be the full-frame (not
    halved) spectrum in dB."""
    backend = resolve_backend(backend)
    xp = get_xp(backend)
    s = xp.fft.fftshift(xp.asarray(sspec_db))
    arr = xp.fft.fft2(10 ** (s / 10))
    arr = xp.fft.fftshift(arr).real
    if normalise:
        arr = arr / xp.max(arr)
    return arr


def autocorr_direct(arr, mask=None):
    """Slow masked O(N^4) 2-D autocorrelation — test oracle
    (scint_utils.py:67-84 semantics, numpy only). A masked-array
    input keeps its mask (the reference's documented input type)."""
    in_mask = np.ma.getmaskarray(arr) if np.ma.isMaskedArray(arr) \
        else None
    arr = np.ma.masked_invalid(np.asarray(arr, dtype=float))
    if in_mask is not None:
        arr = np.ma.masked_array(arr, mask=arr.mask | in_mask)
    if mask is not None:
        arr = np.ma.masked_array(arr, mask=mask)
    mean = np.ma.mean(arr)
    std = np.ma.std(arr)
    nr, nc = arr.shape
    out = np.zeros((2 * nr, 2 * nc))
    for x in range(-nr, nr):
        for y in range(-nc, nc):
            seg = (arr[max(0, x):min(x + nr, nr), max(0, y):min(y + nc, nc)]
                   - mean) * (arr[max(0, -x):min(-x + nr, nr),
                                  max(0, -y):min(-y + nc, nc)] - mean)
            out[x + nr][y + nc] = np.ma.sum(seg) / (std ** 2)
    out /= np.nanmax(out)
    return out
