"""Scattered-image interpolation: sspec power → (θx, θy) plane.

Re-design of the interpolation stage of ``Dynspec.calc_scattered_image``
(/root/reference/scintools/dynspec.py:3412-3582; the spline evaluation
is :3538-3547). The reference evaluates a FITPACK bicubic spline
(``RectBivariateSpline.ev``) at every (tdel_est, fdop) query point on
the host. Both secondary-spectrum axes come from ``fft_axis`` and are
uniform, so the same mapping here is a **Keys cubic-convolution
(Catmull–Rom) interpolation** — C¹, interpolating, and expressible as
dense per-axis weight matrices:

    val[q] = Σ_r Wt[q, r] · (Wf @ linᵀ)[q, r]

i.e. one matmul over the Doppler axis plus a row-wise contraction over
the delay axis — the ``ops/normsspec.py`` tent-matmul trick at cubic
order, which rides the MXU where a 16-point gather crawls. Queries are
processed one image row at a time (``lax.map``) so the weight slabs
stay O(nx · n_src).

Not bit-identical to FITPACK (different cubic family, and queries
outside the grid clamp to the edge instead of spline extrapolation) —
the parity budget is physical, not bitwise; see
tests/test_scatim.py for the spline-agreement tolerance on smooth
golden data. Non-uniform axes (no FFT grid) are the caller's cue to
fall back to the host spline.
"""

from __future__ import annotations

import numpy as np

from ..backend import get_jax, register_formulation, resolve_backend
from ..backend import formulation as _formulation

# compiled query programs keyed on (grid shape, query shape, method)
_SCATIM_CACHE = {}

# formulation table (backend.py registry): the dense Keys weights ride
# the MXU; on CPU they are pure overhead (measured 0.130 s matmul vs
# 0.0016 s gather on the bench 512×256 grid / 33k queries)
register_formulation(
    "ops.scatim_interp", default="matmul",
    choices=("matmul", "gather"), platforms={"cpu": "gather"},
    doc="scattered-image cubic interpolation: MXU Keys-weight matmuls "
        "vs fused coalesced 16-tap gathers")


def _resolve_method(method, jax):
    """Formulation policy: ``'matmul'`` builds dense per-axis Keys
    weight matrices that ride the MXU; ``'gather'`` stages the 16-tap
    cubic-convolution stencil as ONE fused program of coalesced flat
    gathers with float32 accumulation. ``'auto'`` resolves through the
    per-platform formulation registry
    (``backend.formulation('ops.scatim_interp')``)."""
    if method in ("matmul", "gather"):
        return method
    if method not in (None, "auto"):
        raise ValueError(f"method must be 'auto', 'matmul' or "
                         f"'gather', got {method!r}")
    return _formulation("ops.scatim_interp")


def _keys_1d(u, xp=np):
    """The Keys (a=-0.5) cubic-convolution kernel, elementwise."""
    au = xp.abs(u)
    au2 = au * au
    au3 = au2 * au
    near = 1.5 * au3 - 2.5 * au2 + 1.0
    far = -0.5 * au3 + 2.5 * au2 - 4.0 * au + 2.0
    return xp.where(au <= 1.0, near,
                    xp.where(au < 2.0, far, xp.zeros_like(au)))


def _keys_weights(pos, n_src, xp):
    """Dense Keys weights on the edge-padded source grid (the
    MXU-matmul form). ``pos[nq]`` are float index coordinates clamped
    to [0, n_src-1]; returns ``[nq, n_src+2]`` weights against the
    padded axis (one replicated sample each side), rows summing to 1.
    """
    u = (pos[:, None] + 1.0) - xp.arange(n_src + 2, dtype=pos.dtype)
    return _keys_1d(u, xp)


def _pad_edge(lin, xp):
    """Replicate-pad one row/column each side (the clamped-query
    boundary condition)."""
    lin = xp.concatenate([lin[:1], lin, lin[-1:]], axis=0)
    return xp.concatenate([lin[:, :1], lin, lin[:, -1:]], axis=1)


def cubic_interp2d(lin, tpos, fpos, backend=None, method="auto"):
    """Cubic-convolution interpolation of ``lin[nr, nc]`` at float
    index coordinates ``tpos``/``fpos`` (each ``[ny, nx]``, delay and
    Doppler axes respectively). Coordinates are clamped to the grid.
    Returns ``[ny, nx]`` (numpy for the numpy backend, device array
    for jax). ``method`` selects the jax formulation
    (:func:`_resolve_method`)."""
    backend = resolve_backend(backend)
    nr, nc = np.shape(lin)
    if backend == "jax":
        return _cubic_interp2d_jax(lin, tpos, fpos, method=method)

    # numpy: 16-tap stencil gather — O(nq·16), where the dense-weight
    # matmul form (the jax path, built for the MXU) would be
    # O(nq·nc·nr) on host
    lin = _pad_edge(np.asarray(lin, dtype=float), np)
    tpos = np.clip(np.asarray(tpos, dtype=float), 0, nr - 1)
    fpos = np.clip(np.asarray(fpos, dtype=float), 0, nc - 1)
    # clamp the base cell so taps stay inside the padded grid; at the
    # top edge frac hits exactly 1.0, where the Keys weights reduce to
    # the pure node value — identical to the dense form
    it = np.clip(np.floor(tpos).astype(int), 0, nr - 2)
    jf = np.clip(np.floor(fpos).astype(int), 0, nc - 2)
    ft = tpos - it
    ff = fpos - jf
    out = np.zeros(tpos.shape)
    for a in range(-1, 3):
        wt = _keys_1d(ft - a)
        for b in range(-1, 3):
            out += wt * _keys_1d(ff - b) \
                * lin[it + 1 + a, jf + 1 + b]
    return out


def _cubic_interp2d_jax(lin, tpos, fpos, method="auto"):
    jax = get_jax()
    import jax.numpy as jnp

    nr, nc = np.shape(lin)
    method = _resolve_method(method, jax)
    key = (nr, nc, np.shape(tpos), method)
    fn = _SCATIM_CACHE.get(key)
    if fn is None:
        if len(_SCATIM_CACHE) >= 8:
            _SCATIM_CACHE.pop(next(iter(_SCATIM_CACHE)))

        def program_matmul(lin_d, tq, fq):
            lin_p = _pad_edge(lin_d, jnp)
            tq = jnp.clip(tq, 0, nr - 1)
            fq = jnp.clip(fq, 0, nc - 1)
            hi = jax.lax.Precision.HIGHEST

            def row(args):
                tp, fp = args
                wf = _keys_weights(fp, nc, jnp)
                wt = _keys_weights(tp, nr, jnp)
                m = jnp.dot(wf, lin_p.T, precision=hi)
                return jnp.sum(wt * m, axis=1)

            return jax.lax.map(row, (tq, fq))

        def program_gather(lin_d, tq, fq):
            # the 16-tap Keys stencil as coalesced flat gathers: one
            # base index per query, 16 static offsets, float32
            # accumulation — the same taps as the numpy reference
            # path, fused into one program
            lin_p = _pad_edge(lin_d, jnp)
            flat = lin_p.ravel()
            ncp = nc + 2
            tq = jnp.clip(tq, 0, nr - 1)
            fq = jnp.clip(fq, 0, nc - 1)
            it = jnp.clip(jnp.floor(tq).astype(jnp.int32), 0, nr - 2)
            jf = jnp.clip(jnp.floor(fq).astype(jnp.int32), 0, nc - 2)
            ft = tq - it
            ff = fq - jf
            base = (it + 1) * ncp + (jf + 1)
            out = jnp.zeros(tq.shape, flat.dtype)
            for a in range(-1, 3):
                wt = _keys_1d(ft - a, jnp)
                for b in range(-1, 3):
                    out = out + wt * _keys_1d(ff - b, jnp) \
                        * flat[base + a * ncp + b]
            return out

        fn = jax.jit(program_matmul if method == "matmul"
                     else program_gather)
        _SCATIM_CACHE[key] = fn
    return fn(jnp.asarray(lin), jnp.asarray(tpos),
              jnp.asarray(fpos))


def is_uniform(axis, rtol=1e-6):
    """True when ``axis`` is an (ascending) uniform grid — the
    precondition for index-arithmetic interpolation."""
    axis = np.asarray(axis, dtype=float)
    d = np.diff(axis)
    return d.size > 0 and np.all(d > 0) and np.allclose(d, d[0],
                                                        rtol=rtol)


def scattered_image_interp(linsspec, tdel, fdop, tdel_q, fdop_q,
                           backend=None):
    """The calc_scattered_image query: interpolate the linear-power
    secondary spectrum at (tdel_q, fdop_q) grids. Axes must be
    uniform (fft_axis grids are); raises ValueError otherwise so the
    caller can fall back to a host spline."""
    tdel = np.asarray(tdel, dtype=float)
    fdop = np.asarray(fdop, dtype=float)
    if not (is_uniform(tdel) and is_uniform(fdop)):
        raise ValueError("non-uniform axis — host-spline territory")
    tpos = (np.asarray(tdel_q, dtype=float) - tdel[0]) \
        / (tdel[1] - tdel[0])
    fpos = (np.asarray(fdop_q, dtype=float) - fdop[0]) \
        / (fdop[1] - fdop[0])
    return cubic_interp2d(linsspec, tpos, fpos, backend=backend)
