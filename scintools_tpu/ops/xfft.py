"""xfft — structure-aware 2-D transform layer.

Five PRs of Fourier wins lived as one-off formulations buried in their
call sites: the rfft2 half-spectrum + Hermitian gather of the chunk
conjugate spectrum (ops/sspec.py), the pruned mean-padded forward and
split cropped ifft2 of the batched retrieval (thth/retrieval.py), and
the rank-1 separable column-projected Fresnel propagation
(sim/factory.py). Meanwhile other hot paths kept paying full complex
transforms on real input. This module makes the structure a *declared
property* (the FFTArray shape, arXiv:2508.03697) and owns the
lowering:

====================  =================================================
declared property     exact lowering
====================  =================================================
``real_input``        forward: ``rfft`` half spectrum + Hermitian
                      gather/completion (half the FFT flops);
                      round-trip power (Wiener–Khinchin): the power
                      spectrum is Hermitian, so ``irfft`` replaces the
                      complex inverse and the imaginary half is never
                      computed
``pruned rows``       zero-pad along an axis: only the data rows enter
(zero/mean pad)       that axis' transform (zero rows transform to
                      zero — appended, not computed); mean-padding is
                      ``zeropad(x − µ)`` plus one DC scalar
``cropped_output``    the 2-D transform splits per axis with the row
                      crop folded between them, so only the surviving
                      fraction reaches the second axis
``separable_kernel``  a rank-1 filter ``fx ⊗ fy`` collapses
                      ``fft2 → filter → ifft2`` to one matvec and two
                      1-D transforms (column projection)
``shift``/layout      ``fftshift``/``ifftshift`` are pure
                      permutations: consumers whose access pattern is
                      an index gather fold them into the index map
                      instead of materialising a full-array pass
====================  =================================================

Variant selection (structured vs dense-oracle) routes through the
backend.py formulation registry — override > env > platform table >
measured (``backend.measure_formulation``) — so every choice is one
inspectable table, and each cached program variant is traced by an
``obs/programs.py`` abstract probe and pinned in the jaxprcheck
fingerprint baseline (a silent lowering flip fails JP205 with a
readable primitive diff).

Everything here is ``xp``-generic (numpy or jax.numpy) and
trace-safe; the lowerings used by the migrated call sites reproduce
their original op sequences **bit-identically** (pinned in
tests/test_xfft.py).
"""

from __future__ import annotations

import numpy as np

from ..backend import formulation, register_formulation

# ---------------------------------------------------------------------
# formulation tables (backend.py registry)
# ---------------------------------------------------------------------

register_formulation(
    "xfft.acf", default="real", choices=("real", "dense"),
    doc="autocovariance Wiener–Khinchin: real-input rfft2 → |·|² → "
        "irfft2 (imaginary half never computed) vs the complex "
        "fft2/ifft2 oracle")

register_formulation(
    "xfft.sspec", default="half", choices=("half", "dense"),
    doc="secondary-spectrum power: rfft over the halved delay axis "
        "with the crop folded before the second-axis transform (the "
        "discarded half is never computed) vs the full fft2 oracle")

register_formulation(
    "xfft.acf_sspec", default="real", choices=("real", "dense"),
    doc="sspec→ACF forward transform: real-input rfft2 + Hermitian "
        "completion vs the complex fft2 oracle")

register_formulation(
    "xfft.zoom", default="czt", choices=("czt", "dense"),
    doc="band-limited (zoom) DFT: Bluestein chirp-Z — pre-chirp ⊙ → "
        "one FFT-sized convolution → post-chirp, output grid fully "
        "decoupled from the input grid, O((M+N)·log) per row at any "
        "zoom factor — vs the dense plane-wave DFT matmul oracle")

register_formulation(
    "xfft.offgrid", default="taylor", choices=("taylor", "dense"),
    doc="off-grid (scattered-point) DFT: on-grid oversampled FFT + "
        "k-term Taylor derivative expansion from the nearest bin "
        "(arXiv:physics/0610057) vs the dense point-DFT matmul oracle")

register_formulation(
    "xfft.profile", default="real", choices=("real", "dense"),
    doc="1-D profile spectrum real(fft(x))[:keep] of a real profile "
        "(the sspec 1-D fit models): rfft half spectrum (the "
        "discarded imaginary/negative half never computed) vs the "
        "full complex fft oracle")


def _is_real(x):
    """Declared-structure guard: True when ``x`` carries a real dtype
    (dense fallback for complex inputs, as the CS path always did)."""
    return not np.issubdtype(
        np.dtype(getattr(x, "dtype", np.float64)), np.complexfloating)


# ---------------------------------------------------------------------
# real-input forward: half spectrum + Hermitian completion / gather
# ---------------------------------------------------------------------

def hermitian_full_from_half(H, n2, xp=np):
    """Reconstruct the FULL 2-D spectrum of a real input from its
    ``rfft2`` half ``H[..., n1, n2//2+1]`` via Hermitian symmetry:
    ``F[k1, k2] = conj(F[(-k1) % n1, n2 - k2])`` for the missing
    columns ``k2 = n2//2+1 .. n2-1``. Pure gather + conj — jits,
    vmaps, and works for odd and even ``n2``."""
    n1 = H.shape[-2]
    m = H.shape[-1]                       # n2 // 2 + 1
    # columns still needed: k2 = m .. n2-1  →  n2-k2 = n2-m .. 1
    idx1 = (-np.arange(n1)) % n1          # negate the k1 axis
    tail = xp.conj(H[..., idx1, 1:n2 - m + 1][..., ::-1])
    return xp.concatenate([H, tail], axis=-1)


def hermitian_half_gather(H, n2, rows, cols, xp=np):
    """Point-gather full-spectrum entries from the half spectrum
    ``H[n1, n2//2+1]`` of a real input: entries in the missing
    columns (``cols >= n2//2+1``) read the conjugate of the mirrored
    half-plane entry, so the full complex spectrum never
    materialises. ``rows``/``cols`` index the RAW (unshifted) full
    spectrum — fold any fftshift into them first (shift/layout
    property)."""
    n1 = H.shape[-2]
    m = n2 // 2 + 1
    tail = cols >= m
    v = H[xp.where(tail, (n1 - rows) % n1, rows),
          xp.where(tail, n2 - cols, cols)]
    return xp.where(tail, xp.conj(v), v)


def fft2_full(x, *, variant="fft2", s=None, xp=np):
    """Full complex 2-D spectrum of the trailing axes (optionally
    zero-padded to ``s``).

    ``variant='rfft'`` exploits declared real input: a half-spectrum
    ``rfft2`` plus :func:`hermitian_full_from_half` replaces the full
    complex ``fft2`` (~half the FFT flops). ``variant='fft2'`` is the
    dense complex oracle; complex inputs always take it."""
    if variant == "rfft" and _is_real(x):
        n2 = x.shape[-1] if s is None else s[-1]
        H = xp.fft.rfft2(x) if s is None else xp.fft.rfft2(x, s=s)
        return hermitian_full_from_half(H, n2, xp=xp)
    return xp.fft.fft2(x) if s is None else xp.fft.fft2(x, s=s)


# ---------------------------------------------------------------------
# pruned / mean-padded forward (the retrieval front end)
# ---------------------------------------------------------------------

def pruned_meanpad_half(x, pad_to, xp=np):
    """Half spectrum of real 2-D ``x`` mean-padded to ``pad_to``, with
    the pruned-rows split: mean-padding is ``zeropad(x − µ) + µ`` and
    the FFT of the constant µ-canvas is a pure DC term, so (a) the
    axis-1 rfft runs on the data rows only (the zero rows transform
    to zero — appended, not computed), (b) µ re-enters as one scalar
    at ``H[0, 0]``. Exact up to one float rounding of the data
    region; ~``pad_to[0]/x.shape[0]``× less axis-1 FFT work.

    Single-frame contract (2-D ``x``; vmap any batch axis — the
    batched retrieval does)."""
    N1, N2 = pad_to
    mu = xp.mean(x)
    r1 = xp.fft.rfft(x - mu, n=N2, axis=1)
    r1 = xp.pad(r1, ((0, N1 - x.shape[0]), (0, 0)))
    H = xp.fft.fft(r1, axis=0)
    if hasattr(H, "at"):                  # jax in-place-expression
        return H.at[0, 0].add(mu * N1 * N2)
    H[0, 0] += mu * N1 * N2
    return H


# ---------------------------------------------------------------------
# cropped split inverse (the retrieval back end)
# ---------------------------------------------------------------------

def ifft2_cropped(X, crop, xp=np, variant="split"):
    """Inverse 2-D transform with a declared output crop
    ``(rows, cols)`` over the trailing axes.

    ``variant='split'`` folds the row crop between the per-axis
    transforms: only ``crop[0]`` of the axis-0 outputs reach the
    axis-1 transform (exact — the crop commutes with the remaining
    per-row transform). ``variant='dense'`` is the ``ifft2``-then-
    crop oracle."""
    r, c = crop
    if variant == "dense":
        return xp.fft.ifft2(X)[..., :r, :c]
    # row crop as an explicit slice tuple: `[..., :r, :]` lowers to
    # a gather on the jax backend; the full tuple keeps it a slice
    # (the migrated sites' bit-identity depends on it)
    rows = (slice(None),) * (X.ndim - 2) + (slice(None, r),
                                            slice(None))
    Y = xp.fft.ifft(X, axis=-2)[rows]
    return xp.fft.ifft(Y, axis=-1)[..., :c]


# ---------------------------------------------------------------------
# separable-kernel filtering (the factory column projection)
# ---------------------------------------------------------------------

def column_phase(n, col):
    """Host-precomputed column-extraction phase vector
    ``exp(2πi·k·col/n)``: multiplying an axis spectrum by it and
    summing is the single-column inverse transform (the
    ``separable_kernel`` property's projection operand)."""
    return np.exp(2j * np.pi * np.arange(n) * col / n)


def separable_filter_column(E, fx, fy, gph, xp=np):
    """``ifft2(fft2(E) · fx ⊗ fy)[..., col]`` via the rank-1
    separability of the filter: ``g = fft(fy · gph)/ny`` projects the
    filtered axis-1 inverse transform onto the sampled column (one
    matvec), leaving one filtered 1-D round trip along axis 0 — no
    2-D FFT. ``gph`` is :func:`column_phase` ``(ny, col)`` cast to
    the working complex dtype; exact, not approximate."""
    ny = fy.shape[-1]
    g = xp.fft.fft(fy * gph) / ny
    v = E @ g
    return xp.fft.ifft(fx[None] * xp.fft.fft(v, axis=-1), axis=-1)


# ---------------------------------------------------------------------
# real-input round trips (the new fast paths)
# ---------------------------------------------------------------------

def wiener_khinchin(x, pad_to, *, variant=None, xp=np):
    """Circular autocovariance of ``x`` over the trailing axes
    zero-padded to ``pad_to``: ``F⁻¹|F x|²`` (raw layout — callers
    fold/apply their own fftshift).

    ``variant='real'`` (declared real input): the power spectrum of a
    real signal is the rfft2 of its (real, even) autocorrelation, so
    ``rfft2 → |·|² → irfft2`` computes the same array with the
    discarded Hermitian half never computed and a real inverse. The
    per-axis split keeps the pruned-rows structure: the axis-1 rffts
    run on the data rows only. ``variant='dense'`` is the complex
    ``fft2 → |·|² → ifft2`` oracle (the pre-layer formulation,
    bit-identical to it)."""
    if variant is None:
        variant = formulation("xfft.acf")
    N1, N2 = pad_to
    if variant == "real" and _is_real(x):
        H = xp.fft.rfft(x, n=N2, axis=-1)      # data rows only
        H = xp.fft.fft(H, n=N1, axis=-2)
        P = (H * xp.conj(H)).real
        return xp.fft.irfft2(P, s=(N1, N2))
    arr = xp.fft.fft2(x, s=(N1, N2))
    arr = xp.abs(arr) ** 2
    return xp.fft.ifft2(arr).real


def halfrow_power(x, pad_to, *, xp=np):
    """Power of the 2-D spectrum of real ``x`` padded to ``pad_to``
    with the declared row crop ``N1//2`` folded INTO the transform:
    rfft over the halved (delay) axis on the data columns only
    (pruned), crop to the surviving rows, then the second-axis
    transform runs on half the rows — the discarded half is never
    computed. Returns rows in RAW order (= the kept half of the
    shifted frame) with the column axis fftshifted: exactly
    ``fftshift(|fft2(x, s)|²)[N1//2:]``."""
    N1, N2 = pad_to
    S = xp.fft.rfft(x, n=N1, axis=-2)
    rows = (slice(None),) * (S.ndim - 2) + (slice(None, N1 // 2),
                                            slice(None))
    S = xp.fft.fft(S[rows], n=N2, axis=-1)
    p = (S * xp.conj(S)).real
    return xp.fft.fftshift(p, axes=-1)


# ---------------------------------------------------------------------
# band-limited (zoom) and off-grid transforms — the chirp-Z /
# Taylor-interpolation formulation family (ROADMAP item 4)
# ---------------------------------------------------------------------

def czt_fft_length(M, N):
    """Static ``(fft_len, N)`` pair for :func:`czt_1d`: the smallest
    power-of-two convolution length ≥ M+N−1."""
    L = 1
    while L < M + N - 1:
        L *= 2
    return (L, N)


def czt_1d(u, a, phi0, L, xp=np):
    """Bluestein chirp-Z evaluation of ``X[n] = Σ_m u[..., m] ·
    exp(-i·(a·m·n + phi0·n))`` for n = 0..N-1 over the last axis,
    with TRACED chirp rate ``a`` and per-output phase ``phi0``
    (static shapes only: M = u.shape[-1] and N are baked via the
    precomputed FFT length ``L`` ≥ M+N-1, :func:`czt_fft_length`).

    m·n = (m² + n² − (n−m)²)/2 turns the sum into a convolution of
    ``u·e^{-i·a·m²/2}`` with the conjugate chirp, done with
    zero-padded FFTs — O((M+N)·log) per output row instead of the
    O(M·N) plane-wave GEMM. This is the ONE chirp implementation in
    the codebase: the zoom lowerings here and the acf2d
    ``fresnel_method='czt'`` rows (sim/acf_model.py) both ride it."""
    M = u.shape[-1]
    N = L[1]
    Lf = L[0]
    m = xp.arange(M)
    n = xp.arange(N)
    k = xp.arange(-(M - 1), N)                 # conv kernel support
    wm = xp.exp(-0.5j * a * m ** 2)
    wn = xp.exp(-0.5j * a * n ** 2 - 1j * phi0 * n)
    v = xp.exp(0.5j * a * k ** 2)              # conjugate chirp
    uf = xp.fft.fft(u * wm, n=Lf, axis=-1)
    vf = xp.fft.fft(v, n=Lf)
    conv = xp.fft.ifft(uf * vf, axis=-1)
    # conv index k0 + n with k0 = M-1 aligns (n-m) = k
    return conv[..., M - 1:M - 1 + N] * wn


def zoom_dft_1d(x, n_grid, f0, df, n_out, *, xp=np, variant=None,
                fft_len=None):
    """Band-limited DFT over the last axis: ``X[j] = Σ_m x[..., m] ·
    exp(-2πi·m·(f0 + j·df)/n_grid)`` for j = 0..n_out-1.

    ``f0``/``df`` are in (fractional) FFT-bin units of an
    ``n_grid``-point transform and may be TRACED — the output band is
    fully decoupled from the input grid, so one compiled program
    serves any band at a given geometry. Integer ``f0``/``df=1``
    reproduce the corresponding ``fft(x, n=n_grid)`` bins exactly;
    ``df=1/z`` samples the z×-padded grid without ever building it.
    Negative/aliased frequencies are fine (m is integer, so the
    kernel is N-periodic in f).

    ``variant='czt'`` lowers to :func:`czt_1d` with the band start
    folded into the pre-chirp (pre ⊙ → one FFT-length convolution →
    post-chirp); ``'dense'`` is the plane-wave DFT matmul oracle
    (exact for arbitrary fractional bands, O(M·n_out))."""
    if variant is None:
        variant = formulation("xfft.zoom")
    M = x.shape[-1]
    w = 2.0 * np.pi / n_grid
    m = xp.arange(M)
    if variant == "czt":
        if fft_len is None:
            fft_len = czt_fft_length(M, n_out)
        pre = xp.exp(-1j * w * f0 * m)
        return czt_1d(x * pre, w * df, 0.0, fft_len, xp)
    freqs = f0 + df * xp.arange(n_out)
    E = xp.exp(-1j * w * m[:, None] * freqs[None, :])
    return x @ E


def zoom_power_2d(x, pad_to, band_r, band_c, *, xp=np, variant=None):
    """Band-limited 2-D spectral power of ``x`` over the trailing
    axes: ``out[..., j1, j2] = |F(r0 + j1·dr, c0 + j2·dc)|²`` where F
    is the DFT on the ``pad_to = (N1, N2)`` grid and each band is a
    ``(f0, f1, n_out)`` triple in (fractional, signed) bin units of
    its axis — samples at ``f0 + j·(f1-f0)/n_out`` (endpoint-
    exclusive, like fft bins). Band edges may be traced; ``n_out``
    must be static.

    Only the n_out_r × n_out_c band pixels are ever computed: the
    row-axis zoom runs first, so the column transform sees n_out_r
    rows instead of N1 (the crop is folded *between* the per-axis
    transforms, like :func:`halfrow_power` — at any zoom factor)."""
    if variant is None:
        variant = formulation("xfft.zoom")
    N1, N2 = pad_to
    r0, r1, nr = band_r
    c0, c1, nc = band_c
    dr = (r1 - r0) / nr
    dc = (c1 - c0) / nc
    F = zoom_dft_1d(xp.swapaxes(x, -1, -2), N1, r0, dr, int(nr),
                    xp=xp, variant=variant)
    F = zoom_dft_1d(xp.swapaxes(F, -1, -2), N2, c0, dc, int(nc),
                    xp=xp, variant=variant)
    return (F * xp.conj(F)).real


def offgrid_taylor_bound(order, oversample):
    """Analytic remainder coefficient of :func:`offgrid_taylor`: the
    truncation error is ≤ ``bound · Σ|x|`` with
    ``bound = r^k/k! · 1/(1 − r/(k+1))``, r = π/oversample (the
    worst-case |phase-derivative·δ| at δ = half an oversampled bin).
    tests/test_xfft.py pins the measured error under it per order."""
    import math
    r = np.pi / oversample
    k = int(order)
    return float(r ** k / math.factorial(k) / (1.0 - r / (k + 1)))


def offgrid_taylor(x, pts, n_grid, *, order=8, oversample=4, xp=np):
    """Off-grid DFT samples ``X(p) = Σ_m x[..., m] ·
    exp(-2πi·m·p/n_grid)`` at scattered (traced) frequency points
    ``pts`` (fractional bin units), via the Taylor-interpolation-
    through-FFT formulation (arXiv:physics/0610057): one on-grid FFT
    per derivative order t on the ``oversample``×-oversampled grid
    (``F_t = FFT(x·(-2πi·m/n_grid)^t)``), then a k-term Taylor
    expansion from the nearest oversampled bin, Horner-evaluated in
    the offset δ ∈ [-½, ½] oversampled bins. Error ≤
    :func:`offgrid_taylor_bound```(order, oversample)·Σ|x|``."""
    M = x.shape[-1]
    Nq = int(oversample) * int(n_grid)
    c = -2j * np.pi / n_grid
    m = xp.arange(M)
    pw = (c * m)[None, :] ** xp.arange(order)[:, None]    # (k, M)
    F = xp.fft.fft(x[..., None, :] * pw, n=Nq, axis=-1)   # (k, Nq)
    g = xp.round(pts * oversample)
    delta = pts - g / oversample                          # grid bins
    idx = (g % Nq).astype(xp.int32) if hasattr(xp, "int32") \
        else np.asarray(g % Nq, dtype=np.int64)
    Fp = F[..., idx]                                      # (k, P)
    acc = Fp[..., order - 1, :]
    for t in range(order - 1, 0, -1):                     # Horner:
        acc = Fp[..., t - 1, :] + acc * (delta / t)       # δ^t/t!
    return acc


def offgrid_dft_1d(x, pts, n_grid, *, order=8, oversample=4, xp=np,
                   variant=None):
    """Scattered-point DFT over the last axis under the
    ``xfft.offgrid`` formulation: ``'taylor'`` is
    :func:`offgrid_taylor` (O(k·qN·log qN) + O(k·P) — independent of
    where the points fall); ``'dense'`` is the exact point-DFT
    matmul oracle (O(M·P))."""
    if variant is None:
        variant = formulation("xfft.offgrid")
    if variant == "taylor":
        return offgrid_taylor(x, pts, n_grid, order=order,
                              oversample=oversample, xp=xp)
    m = xp.arange(x.shape[-1])
    E = xp.exp(-2j * np.pi / n_grid * m[:, None] * pts[None, :])
    return x @ E


def real_spectrum_1d(x, keep, *, xp=np, variant=None):
    """``real(fft(x))[..., :keep]`` — the 1-D secondary-spectrum
    profile transform (fit/models.py ``_sspec_1d``). Declared real
    input with ``keep ≤ n//2+1`` lowers to the rfft half spectrum
    (the discarded negative-frequency half is never computed — for
    the mirrored length-(2L−1) profiles, keep = L = n//2+1 exactly);
    ``'dense'`` is the full complex fft oracle."""
    if variant is None:
        variant = formulation("xfft.profile")
    n = x.shape[-1]
    if variant == "real" and _is_real(x) and keep <= n // 2 + 1:
        return xp.real(xp.fft.rfft(x))[..., :keep]
    return xp.real(xp.fft.fft(x))[..., :keep]


# ---------------------------------------------------------------------
# plan(): the declarative front door
# ---------------------------------------------------------------------

class Plan:
    """Declared structure for a 2-D transform over the trailing axes,
    resolved to the cheapest exact lowering at call time.

    Built by :func:`plan`. The declared properties select among the
    module's lowerings; the active variant (structured vs dense
    oracle) resolves through the formulation registry ``op`` unless a
    call pins ``variant=`` explicitly. Plans are cheap, stateless
    descriptors — hot jitted code may also call the lowering
    functions directly (the batched retrieval does)."""

    __slots__ = ("shape", "pad_to", "real_input", "mean_pad", "crop",
                 "layout", "op", "band")

    def __init__(self, shape, pad_to, real_input, mean_pad, crop,
                 layout, op, band=None):
        self.shape = tuple(int(n) for n in shape)
        self.pad_to = tuple(int(n) for n in (pad_to or shape))
        self.real_input = bool(real_input)
        self.mean_pad = bool(mean_pad)
        self.crop = crop
        self.layout = layout
        self.op = op
        self.band = band

    def variant(self, pinned=None):
        """The active formulation choice: an explicit ``pinned``
        value wins, else the registry resolves ``op``; plans with no
        ``op`` are dense."""
        if pinned is not None:
            return pinned
        return formulation(self.op) if self.op else "dense"

    def structured(self, pinned=None):
        return self.variant(pinned) not in ("dense", "fft2")

    def describe(self):
        """JSON-able view: declared properties + the variant that
        would resolve right now (run reports, docs, bench)."""
        def _band(b):
            try:
                return [float(b[0]), float(b[1]), int(b[2])]
            except TypeError:          # traced edges: shape-only view
                return ["traced", "traced", int(b[2])]

        return {
            "shape": list(self.shape), "pad_to": list(self.pad_to),
            "real_input": self.real_input, "mean_pad": self.mean_pad,
            "crop": list(self.crop) if self.crop else None,
            "layout": self.layout, "op": self.op,
            "band": ([_band(b) for b in self.band]
                     if self.band else None),
            "variant": self.variant(),
        }

    # -- lowerings -----------------------------------------------------

    def forward(self, x, *, xp=np, variant=None):
        """Full complex forward spectrum. Declared real input lowers
        to the half-spectrum + Hermitian completion; 'shifted' layout
        applies the final fftshift (raw-layout consumers fold it into
        their index maps instead)."""
        want_rfft = self.real_input and self.structured(variant)
        pad = self.pad_to if self.pad_to != tuple(x.shape[-2:]) \
            else None
        F = fft2_full(x, variant="rfft" if want_rfft else "fft2",
                      s=pad, xp=xp)
        if self.layout == "shifted":
            F = xp.fft.fftshift(F, axes=(-2, -1))
        return F

    def half(self, x, *, xp=np):
        """Half spectrum for gather consumers (raw layout only).
        Declared mean-pad folds the padding into a DC scalar via
        :func:`pruned_meanpad_half`."""
        if self.mean_pad:
            return pruned_meanpad_half(x, self.pad_to, xp=xp)
        return xp.fft.rfft2(x, s=self.pad_to)

    def power(self, x, *, xp=np, variant=None):
        """Spectral power with the declared row crop. A declared
        ``band`` lowers to :func:`zoom_power_2d` — only the band
        pixels are computed, at any zoom factor, under the
        'xfft.zoom' czt|dense choice. A half-row crop on real input
        lowers to :func:`halfrow_power` (the discarded half never
        computed); dense computes the full frame, shifts and crops."""
        if self.band is not None:
            return zoom_power_2d(x, self.pad_to, self.band[0],
                                 self.band[1], xp=xp,
                                 variant=self.variant(variant))
        N1, N2 = self.pad_to
        halved = (self.crop is not None
                  and self.crop[0] == N1 // 2)
        if (halved and self.real_input and self.structured(variant)
                and _is_real(x)):
            return halfrow_power(x, self.pad_to, xp=xp)
        simf = xp.fft.fft2(x, s=(N1, N2))
        simf = (simf * xp.conj(simf)).real
        sec = xp.fft.fftshift(simf)
        if halved:
            sec = sec[N1 // 2:]
        return sec

    def acf(self, x, *, xp=np, variant=None):
        """Wiener–Khinchin autocovariance (|F|² inverse-transformed);
        'shifted' layout centres the zero lag."""
        arr = wiener_khinchin(x, self.pad_to,
                              variant=self.variant(variant), xp=xp)
        if self.layout == "shifted":
            arr = xp.fft.fftshift(arr, axes=(-2, -1))
        return arr

    def inverse(self, X, *, xp=np, variant=None):
        """Inverse transform with the declared output crop folded
        between the split per-axis transforms."""
        crop = self.crop or self.pad_to
        v = "split" if self.structured(variant) else "dense"
        return ifft2_cropped(X, crop, xp=xp, variant=v)


def plan(shape, pad_to=None, *, real_input=False, mean_pad=False,
         crop=None, layout="raw", op=None, band=None):
    """Declare the structure of a 2-D transform; returns a
    :class:`Plan` that lowers to the cheapest exact program.

    ``shape`` — trailing-2-axes data shape. ``pad_to`` — transform
    lengths (zero-pad; default: no padding). ``real_input`` — the
    data dtype is real: forwards take half-spectrum lowerings,
    round-trip power takes the real inverse. ``mean_pad`` — padding
    fills with the data mean (lowered to zeropad(x−µ) + a DC
    scalar). ``crop`` — ``(rows, cols)`` output crop folded into the
    split transforms (``None`` entries keep the axis). ``layout`` —
    ``'raw'`` or ``'shifted'`` output frame; raw lets gather
    consumers fold the shift into their index maps. ``band`` — a
    ``(band_rows, band_cols)`` pair of ``(f0, f1, n_out)`` triples in
    (fractional, signed) RAW bin units of the ``pad_to`` grid: power
    lowers to the band-limited zoom transform
    (:func:`zoom_power_2d`), computing ONLY the declared band at any
    output density (edges may be traced; n_out is static; the band
    is its own layout, so ``layout`` must stay 'raw'). ``op`` — the
    backend.py formulation-registry op that routes this plan's
    structured-vs-dense choice (override > env > platform table >
    measured); band plans default to ``'xfft.zoom'``."""
    if layout not in ("raw", "shifted"):
        raise ValueError(f"unknown layout {layout!r} "
                         "(want 'raw' or 'shifted')")
    if band is not None:
        if layout != "raw":
            raise ValueError("band plans are raw-layout (the band IS "
                             "the output frame)")
        if len(band) != 2 or any(len(b) != 3 for b in band):
            raise ValueError("band wants ((f0, f1, n_out) rows, "
                             "(f0, f1, n_out) cols)")
        if op is None:
            op = "xfft.zoom"
    return Plan(shape, pad_to, real_input, mean_pad, crop, layout, op,
                band)


# ---------------------------------------------------------------------
# cached jitted programs (bench + eager-jax entry points)
# ---------------------------------------------------------------------

# keyed program cache: a fresh jax.jit per call would retrace every
# call (the JL101 per-call wrapper trap); keys pin shape AND variant
# so a formulation flip builds a new program instead of silently
# reusing the old one
_PROGRAM_CACHE = {}


def _cached_jit(key, builder, site):
    """FIFO-bounded jit cache with retrace accounting — every MISS is
    one recorded build at ``site`` (obs/retrace.py), which the tier-1
    ``retrace_guard`` pins and the RunReport's jit_builds table
    reads."""
    fn = _PROGRAM_CACHE.get(key)
    if fn is None:
        from ..backend import get_jax
        from ..obs import retrace as _retrace

        _retrace.record_build(site, key)
        if len(_PROGRAM_CACHE) >= 16:
            _PROGRAM_CACHE.pop(next(iter(_PROGRAM_CACHE)))
        fn = _PROGRAM_CACHE[key] = get_jax().jit(builder())
    return fn


def acf_program(nf, nt, *, variant=None, normalise=True):
    """Cached jitted batched autocovariance
    ``fn(dyn[B, nf, nt]) → acf[B, 2nf, 2nt]`` under the declared
    ('real') or dense formulation — one compile per
    (shape, variant), site ``xfft.acf``."""
    if variant is None:
        variant = formulation("xfft.acf")
    key = ("acf", int(nf), int(nt), variant, bool(normalise))

    def build():
        from .acf import autocovariance

        def fn(dyn):
            return autocovariance(dyn, normalise=normalise,
                                  backend="jax", variant=variant)

        return fn

    return _cached_jit(key, build, site="xfft.acf")


def sspec_power_program(nf, nt, *, variant=None):
    """Cached jitted batched halved secondary-spectrum power
    ``fn(dyn[B, nf, nt]) → sec[B, nrfft//2, ncfft]`` under the
    declared ('half') or dense formulation — one compile per
    (shape, variant), site ``xfft.sspec``."""
    if variant is None:
        variant = formulation("xfft.sspec")
    key = ("sspec", int(nf), int(nt), variant)

    def build():
        from ..backend import get_jax
        from .sspec import secondary_spectrum_power

        jax = get_jax()

        def fn(dyn):
            return jax.vmap(
                lambda d: secondary_spectrum_power(
                    d, backend="jax", variant=variant))(dyn)

        return fn

    return _cached_jit(key, build, site="xfft.sspec")


def zoom_power_program(nf, nt, pad_to, n_r, n_c, *, variant=None):
    """Cached jitted batched band-limited spectral power
    ``fn(dyn[B, nf, nt], band_r[2], band_c[2]) → sec[B, n_r, n_c]``
    where ``band_* = (f0, f1)`` edges in (fractional, signed) bin
    units of the ``pad_to`` grid — TRACED, so one compiled program
    serves every band at this geometry (a trigger stream zooming
    into different arcs never retraces). One compile per
    (shape, pad_to, n_out, variant), site ``xfft.zoom``."""
    if variant is None:
        variant = formulation("xfft.zoom")
    pad_to = tuple(int(n) for n in pad_to)
    key = ("zoom", int(nf), int(nt), pad_to, int(n_r), int(n_c),
           variant)

    def build():
        from ..backend import get_jax

        jnp = get_jax().numpy
        nr, nc = int(n_r), int(n_c)

        def fn(dyn, band_r, band_c):
            return zoom_power_2d(
                dyn, pad_to, (band_r[0], band_r[1], nr),
                (band_c[0], band_c[1], nc), xp=jnp, variant=variant)

        return fn

    return _cached_jit(key, build, site="xfft.zoom")


def offgrid_program(n, n_pts, *, n_grid=None, order=8, oversample=4,
                    variant=None):
    """Cached jitted batched scattered-point DFT
    ``fn(x[B, n], pts[n_pts]) → X[B, n_pts]`` with TRACED sample
    points (fractional bin units of the ``n_grid``-point transform,
    default n) — one compile per (shape, order, oversample, variant),
    site ``xfft.offgrid``."""
    if variant is None:
        variant = formulation("xfft.offgrid")
    ng = int(n_grid if n_grid is not None else n)
    key = ("offgrid", int(n), int(n_pts), ng, int(order),
           int(oversample), variant)

    def build():
        from ..backend import get_jax

        jnp = get_jax().numpy

        def fn(x, pts):
            return offgrid_dft_1d(x, pts, ng, order=order,
                                  oversample=oversample, xp=jnp,
                                  variant=variant)

        return fn

    return _cached_jit(key, build, site="xfft.offgrid")


# ---------------------------------------------------------------------
# abstract program probes (obs/programs.py) — audited by the jaxlint
# JP2xx program pass; the 'xfft.*' formulations enter the
# fingerprints, so a silent structured↔dense flip fails JP205
# ---------------------------------------------------------------------

from ..obs.programs import register_probe as _register_probe  # noqa: E402


@_register_probe("xfft.acf", formulations=("xfft.acf",))
def _probe_acf():
    """The batched Wiener–Khinchin autocovariance program at a fixed
    12×10 geometry under the active 'xfft.acf' formulation."""
    import jax

    fn = acf_program(12, 10)
    S = jax.ShapeDtypeStruct
    return fn, (S((2, 12, 10), np.float32),)


@_register_probe("xfft.sspec", formulations=("xfft.sspec",))
def _probe_sspec():
    """The batched halved secondary-spectrum power program at a fixed
    12×10 geometry under the active 'xfft.sspec' formulation."""
    import jax

    fn = sspec_power_program(12, 10)
    S = jax.ShapeDtypeStruct
    return fn, (S((2, 12, 10), np.float32),)


@_register_probe("xfft.zoom", formulations=("xfft.zoom",))
def _probe_zoom():
    """The batched band-limited zoom-power program (traced band
    edges) at a fixed 12×10 → 6×8-pixel geometry under the active
    'xfft.zoom' formulation."""
    import jax

    fn = zoom_power_program(12, 10, (16, 16), 6, 8)
    S = jax.ShapeDtypeStruct
    return fn, (S((2, 12, 10), np.float32),
                S((2,), np.float32), S((2,), np.float32))


@_register_probe("xfft.offgrid", formulations=("xfft.offgrid",))
def _probe_offgrid():
    """The batched scattered-point DFT program (traced sample
    points) at a fixed 16-sample → 5-point geometry under the active
    'xfft.offgrid' formulation."""
    import jax

    fn = offgrid_program(16, 5)
    S = jax.ShapeDtypeStruct
    return fn, (S((2, 16), np.float32), S((5,), np.float32))
