"""xfft — structure-aware 2-D transform layer.

Five PRs of Fourier wins lived as one-off formulations buried in their
call sites: the rfft2 half-spectrum + Hermitian gather of the chunk
conjugate spectrum (ops/sspec.py), the pruned mean-padded forward and
split cropped ifft2 of the batched retrieval (thth/retrieval.py), and
the rank-1 separable column-projected Fresnel propagation
(sim/factory.py). Meanwhile other hot paths kept paying full complex
transforms on real input. This module makes the structure a *declared
property* (the FFTArray shape, arXiv:2508.03697) and owns the
lowering:

====================  =================================================
declared property     exact lowering
====================  =================================================
``real_input``        forward: ``rfft`` half spectrum + Hermitian
                      gather/completion (half the FFT flops);
                      round-trip power (Wiener–Khinchin): the power
                      spectrum is Hermitian, so ``irfft`` replaces the
                      complex inverse and the imaginary half is never
                      computed
``pruned rows``       zero-pad along an axis: only the data rows enter
(zero/mean pad)       that axis' transform (zero rows transform to
                      zero — appended, not computed); mean-padding is
                      ``zeropad(x − µ)`` plus one DC scalar
``cropped_output``    the 2-D transform splits per axis with the row
                      crop folded between them, so only the surviving
                      fraction reaches the second axis
``separable_kernel``  a rank-1 filter ``fx ⊗ fy`` collapses
                      ``fft2 → filter → ifft2`` to one matvec and two
                      1-D transforms (column projection)
``shift``/layout      ``fftshift``/``ifftshift`` are pure
                      permutations: consumers whose access pattern is
                      an index gather fold them into the index map
                      instead of materialising a full-array pass
====================  =================================================

Variant selection (structured vs dense-oracle) routes through the
backend.py formulation registry — override > env > platform table >
measured (``backend.measure_formulation``) — so every choice is one
inspectable table, and each cached program variant is traced by an
``obs/programs.py`` abstract probe and pinned in the jaxprcheck
fingerprint baseline (a silent lowering flip fails JP205 with a
readable primitive diff).

Everything here is ``xp``-generic (numpy or jax.numpy) and
trace-safe; the lowerings used by the migrated call sites reproduce
their original op sequences **bit-identically** (pinned in
tests/test_xfft.py).
"""

from __future__ import annotations

import numpy as np

from ..backend import formulation, register_formulation

# ---------------------------------------------------------------------
# formulation tables (backend.py registry)
# ---------------------------------------------------------------------

register_formulation(
    "xfft.acf", default="real", choices=("real", "dense"),
    doc="autocovariance Wiener–Khinchin: real-input rfft2 → |·|² → "
        "irfft2 (imaginary half never computed) vs the complex "
        "fft2/ifft2 oracle")

register_formulation(
    "xfft.sspec", default="half", choices=("half", "dense"),
    doc="secondary-spectrum power: rfft over the halved delay axis "
        "with the crop folded before the second-axis transform (the "
        "discarded half is never computed) vs the full fft2 oracle")

register_formulation(
    "xfft.acf_sspec", default="real", choices=("real", "dense"),
    doc="sspec→ACF forward transform: real-input rfft2 + Hermitian "
        "completion vs the complex fft2 oracle")


def _is_real(x):
    """Declared-structure guard: True when ``x`` carries a real dtype
    (dense fallback for complex inputs, as the CS path always did)."""
    return not np.issubdtype(
        np.dtype(getattr(x, "dtype", np.float64)), np.complexfloating)


# ---------------------------------------------------------------------
# real-input forward: half spectrum + Hermitian completion / gather
# ---------------------------------------------------------------------

def hermitian_full_from_half(H, n2, xp=np):
    """Reconstruct the FULL 2-D spectrum of a real input from its
    ``rfft2`` half ``H[..., n1, n2//2+1]`` via Hermitian symmetry:
    ``F[k1, k2] = conj(F[(-k1) % n1, n2 - k2])`` for the missing
    columns ``k2 = n2//2+1 .. n2-1``. Pure gather + conj — jits,
    vmaps, and works for odd and even ``n2``."""
    n1 = H.shape[-2]
    m = H.shape[-1]                       # n2 // 2 + 1
    # columns still needed: k2 = m .. n2-1  →  n2-k2 = n2-m .. 1
    idx1 = (-np.arange(n1)) % n1          # negate the k1 axis
    tail = xp.conj(H[..., idx1, 1:n2 - m + 1][..., ::-1])
    return xp.concatenate([H, tail], axis=-1)


def hermitian_half_gather(H, n2, rows, cols, xp=np):
    """Point-gather full-spectrum entries from the half spectrum
    ``H[n1, n2//2+1]`` of a real input: entries in the missing
    columns (``cols >= n2//2+1``) read the conjugate of the mirrored
    half-plane entry, so the full complex spectrum never
    materialises. ``rows``/``cols`` index the RAW (unshifted) full
    spectrum — fold any fftshift into them first (shift/layout
    property)."""
    n1 = H.shape[-2]
    m = n2 // 2 + 1
    tail = cols >= m
    v = H[xp.where(tail, (n1 - rows) % n1, rows),
          xp.where(tail, n2 - cols, cols)]
    return xp.where(tail, xp.conj(v), v)


def fft2_full(x, *, variant="fft2", s=None, xp=np):
    """Full complex 2-D spectrum of the trailing axes (optionally
    zero-padded to ``s``).

    ``variant='rfft'`` exploits declared real input: a half-spectrum
    ``rfft2`` plus :func:`hermitian_full_from_half` replaces the full
    complex ``fft2`` (~half the FFT flops). ``variant='fft2'`` is the
    dense complex oracle; complex inputs always take it."""
    if variant == "rfft" and _is_real(x):
        n2 = x.shape[-1] if s is None else s[-1]
        H = xp.fft.rfft2(x) if s is None else xp.fft.rfft2(x, s=s)
        return hermitian_full_from_half(H, n2, xp=xp)
    return xp.fft.fft2(x) if s is None else xp.fft.fft2(x, s=s)


# ---------------------------------------------------------------------
# pruned / mean-padded forward (the retrieval front end)
# ---------------------------------------------------------------------

def pruned_meanpad_half(x, pad_to, xp=np):
    """Half spectrum of real 2-D ``x`` mean-padded to ``pad_to``, with
    the pruned-rows split: mean-padding is ``zeropad(x − µ) + µ`` and
    the FFT of the constant µ-canvas is a pure DC term, so (a) the
    axis-1 rfft runs on the data rows only (the zero rows transform
    to zero — appended, not computed), (b) µ re-enters as one scalar
    at ``H[0, 0]``. Exact up to one float rounding of the data
    region; ~``pad_to[0]/x.shape[0]``× less axis-1 FFT work.

    Single-frame contract (2-D ``x``; vmap any batch axis — the
    batched retrieval does)."""
    N1, N2 = pad_to
    mu = xp.mean(x)
    r1 = xp.fft.rfft(x - mu, n=N2, axis=1)
    r1 = xp.pad(r1, ((0, N1 - x.shape[0]), (0, 0)))
    H = xp.fft.fft(r1, axis=0)
    if hasattr(H, "at"):                  # jax in-place-expression
        return H.at[0, 0].add(mu * N1 * N2)
    H[0, 0] += mu * N1 * N2
    return H


# ---------------------------------------------------------------------
# cropped split inverse (the retrieval back end)
# ---------------------------------------------------------------------

def ifft2_cropped(X, crop, xp=np, variant="split"):
    """Inverse 2-D transform with a declared output crop
    ``(rows, cols)`` over the trailing axes.

    ``variant='split'`` folds the row crop between the per-axis
    transforms: only ``crop[0]`` of the axis-0 outputs reach the
    axis-1 transform (exact — the crop commutes with the remaining
    per-row transform). ``variant='dense'`` is the ``ifft2``-then-
    crop oracle."""
    r, c = crop
    if variant == "dense":
        return xp.fft.ifft2(X)[..., :r, :c]
    # row crop as an explicit slice tuple: `[..., :r, :]` lowers to
    # a gather on the jax backend; the full tuple keeps it a slice
    # (the migrated sites' bit-identity depends on it)
    rows = (slice(None),) * (X.ndim - 2) + (slice(None, r),
                                            slice(None))
    Y = xp.fft.ifft(X, axis=-2)[rows]
    return xp.fft.ifft(Y, axis=-1)[..., :c]


# ---------------------------------------------------------------------
# separable-kernel filtering (the factory column projection)
# ---------------------------------------------------------------------

def column_phase(n, col):
    """Host-precomputed column-extraction phase vector
    ``exp(2πi·k·col/n)``: multiplying an axis spectrum by it and
    summing is the single-column inverse transform (the
    ``separable_kernel`` property's projection operand)."""
    return np.exp(2j * np.pi * np.arange(n) * col / n)


def separable_filter_column(E, fx, fy, gph, xp=np):
    """``ifft2(fft2(E) · fx ⊗ fy)[..., col]`` via the rank-1
    separability of the filter: ``g = fft(fy · gph)/ny`` projects the
    filtered axis-1 inverse transform onto the sampled column (one
    matvec), leaving one filtered 1-D round trip along axis 0 — no
    2-D FFT. ``gph`` is :func:`column_phase` ``(ny, col)`` cast to
    the working complex dtype; exact, not approximate."""
    ny = fy.shape[-1]
    g = xp.fft.fft(fy * gph) / ny
    v = E @ g
    return xp.fft.ifft(fx[None] * xp.fft.fft(v, axis=-1), axis=-1)


# ---------------------------------------------------------------------
# real-input round trips (the new fast paths)
# ---------------------------------------------------------------------

def wiener_khinchin(x, pad_to, *, variant=None, xp=np):
    """Circular autocovariance of ``x`` over the trailing axes
    zero-padded to ``pad_to``: ``F⁻¹|F x|²`` (raw layout — callers
    fold/apply their own fftshift).

    ``variant='real'`` (declared real input): the power spectrum of a
    real signal is the rfft2 of its (real, even) autocorrelation, so
    ``rfft2 → |·|² → irfft2`` computes the same array with the
    discarded Hermitian half never computed and a real inverse. The
    per-axis split keeps the pruned-rows structure: the axis-1 rffts
    run on the data rows only. ``variant='dense'`` is the complex
    ``fft2 → |·|² → ifft2`` oracle (the pre-layer formulation,
    bit-identical to it)."""
    if variant is None:
        variant = formulation("xfft.acf")
    N1, N2 = pad_to
    if variant == "real" and _is_real(x):
        H = xp.fft.rfft(x, n=N2, axis=-1)      # data rows only
        H = xp.fft.fft(H, n=N1, axis=-2)
        P = (H * xp.conj(H)).real
        return xp.fft.irfft2(P, s=(N1, N2))
    arr = xp.fft.fft2(x, s=(N1, N2))
    arr = xp.abs(arr) ** 2
    return xp.fft.ifft2(arr).real


def halfrow_power(x, pad_to, *, xp=np):
    """Power of the 2-D spectrum of real ``x`` padded to ``pad_to``
    with the declared row crop ``N1//2`` folded INTO the transform:
    rfft over the halved (delay) axis on the data columns only
    (pruned), crop to the surviving rows, then the second-axis
    transform runs on half the rows — the discarded half is never
    computed. Returns rows in RAW order (= the kept half of the
    shifted frame) with the column axis fftshifted: exactly
    ``fftshift(|fft2(x, s)|²)[N1//2:]``."""
    N1, N2 = pad_to
    S = xp.fft.rfft(x, n=N1, axis=-2)
    rows = (slice(None),) * (S.ndim - 2) + (slice(None, N1 // 2),
                                            slice(None))
    S = xp.fft.fft(S[rows], n=N2, axis=-1)
    p = (S * xp.conj(S)).real
    return xp.fft.fftshift(p, axes=-1)


# ---------------------------------------------------------------------
# plan(): the declarative front door
# ---------------------------------------------------------------------

class Plan:
    """Declared structure for a 2-D transform over the trailing axes,
    resolved to the cheapest exact lowering at call time.

    Built by :func:`plan`. The declared properties select among the
    module's lowerings; the active variant (structured vs dense
    oracle) resolves through the formulation registry ``op`` unless a
    call pins ``variant=`` explicitly. Plans are cheap, stateless
    descriptors — hot jitted code may also call the lowering
    functions directly (the batched retrieval does)."""

    __slots__ = ("shape", "pad_to", "real_input", "mean_pad", "crop",
                 "layout", "op")

    def __init__(self, shape, pad_to, real_input, mean_pad, crop,
                 layout, op):
        self.shape = tuple(int(n) for n in shape)
        self.pad_to = tuple(int(n) for n in (pad_to or shape))
        self.real_input = bool(real_input)
        self.mean_pad = bool(mean_pad)
        self.crop = crop
        self.layout = layout
        self.op = op

    def variant(self, pinned=None):
        """The active formulation choice: an explicit ``pinned``
        value wins, else the registry resolves ``op``; plans with no
        ``op`` are dense."""
        if pinned is not None:
            return pinned
        return formulation(self.op) if self.op else "dense"

    def structured(self, pinned=None):
        return self.variant(pinned) not in ("dense", "fft2")

    def describe(self):
        """JSON-able view: declared properties + the variant that
        would resolve right now (run reports, docs, bench)."""
        return {
            "shape": list(self.shape), "pad_to": list(self.pad_to),
            "real_input": self.real_input, "mean_pad": self.mean_pad,
            "crop": list(self.crop) if self.crop else None,
            "layout": self.layout, "op": self.op,
            "variant": self.variant(),
        }

    # -- lowerings -----------------------------------------------------

    def forward(self, x, *, xp=np, variant=None):
        """Full complex forward spectrum. Declared real input lowers
        to the half-spectrum + Hermitian completion; 'shifted' layout
        applies the final fftshift (raw-layout consumers fold it into
        their index maps instead)."""
        want_rfft = self.real_input and self.structured(variant)
        pad = self.pad_to if self.pad_to != tuple(x.shape[-2:]) \
            else None
        F = fft2_full(x, variant="rfft" if want_rfft else "fft2",
                      s=pad, xp=xp)
        if self.layout == "shifted":
            F = xp.fft.fftshift(F, axes=(-2, -1))
        return F

    def half(self, x, *, xp=np):
        """Half spectrum for gather consumers (raw layout only).
        Declared mean-pad folds the padding into a DC scalar via
        :func:`pruned_meanpad_half`."""
        if self.mean_pad:
            return pruned_meanpad_half(x, self.pad_to, xp=xp)
        return xp.fft.rfft2(x, s=self.pad_to)

    def power(self, x, *, xp=np, variant=None):
        """Spectral power with the declared row crop. A half-row crop
        on real input lowers to :func:`halfrow_power` (the discarded
        half never computed); dense computes the full frame, shifts
        and crops."""
        N1, N2 = self.pad_to
        halved = (self.crop is not None
                  and self.crop[0] == N1 // 2)
        if (halved and self.real_input and self.structured(variant)
                and _is_real(x)):
            return halfrow_power(x, self.pad_to, xp=xp)
        simf = xp.fft.fft2(x, s=(N1, N2))
        simf = (simf * xp.conj(simf)).real
        sec = xp.fft.fftshift(simf)
        if halved:
            sec = sec[N1 // 2:]
        return sec

    def acf(self, x, *, xp=np, variant=None):
        """Wiener–Khinchin autocovariance (|F|² inverse-transformed);
        'shifted' layout centres the zero lag."""
        arr = wiener_khinchin(x, self.pad_to,
                              variant=self.variant(variant), xp=xp)
        if self.layout == "shifted":
            arr = xp.fft.fftshift(arr, axes=(-2, -1))
        return arr

    def inverse(self, X, *, xp=np, variant=None):
        """Inverse transform with the declared output crop folded
        between the split per-axis transforms."""
        crop = self.crop or self.pad_to
        v = "split" if self.structured(variant) else "dense"
        return ifft2_cropped(X, crop, xp=xp, variant=v)


def plan(shape, pad_to=None, *, real_input=False, mean_pad=False,
         crop=None, layout="raw", op=None):
    """Declare the structure of a 2-D transform; returns a
    :class:`Plan` that lowers to the cheapest exact program.

    ``shape`` — trailing-2-axes data shape. ``pad_to`` — transform
    lengths (zero-pad; default: no padding). ``real_input`` — the
    data dtype is real: forwards take half-spectrum lowerings,
    round-trip power takes the real inverse. ``mean_pad`` — padding
    fills with the data mean (lowered to zeropad(x−µ) + a DC
    scalar). ``crop`` — ``(rows, cols)`` output crop folded into the
    split transforms (``None`` entries keep the axis). ``layout`` —
    ``'raw'`` or ``'shifted'`` output frame; raw lets gather
    consumers fold the shift into their index maps. ``op`` — the
    backend.py formulation-registry op that routes this plan's
    structured-vs-dense choice (override > env > platform table >
    measured)."""
    if layout not in ("raw", "shifted"):
        raise ValueError(f"unknown layout {layout!r} "
                         "(want 'raw' or 'shifted')")
    return Plan(shape, pad_to, real_input, mean_pad, crop, layout, op)


# ---------------------------------------------------------------------
# cached jitted programs (bench + eager-jax entry points)
# ---------------------------------------------------------------------

# keyed program cache: a fresh jax.jit per call would retrace every
# call (the JL101 per-call wrapper trap); keys pin shape AND variant
# so a formulation flip builds a new program instead of silently
# reusing the old one
_PROGRAM_CACHE = {}


def _cached_jit(key, builder, site):
    """FIFO-bounded jit cache with retrace accounting — every MISS is
    one recorded build at ``site`` (obs/retrace.py), which the tier-1
    ``retrace_guard`` pins and the RunReport's jit_builds table
    reads."""
    fn = _PROGRAM_CACHE.get(key)
    if fn is None:
        from ..backend import get_jax
        from ..obs import retrace as _retrace

        _retrace.record_build(site, key)
        if len(_PROGRAM_CACHE) >= 16:
            _PROGRAM_CACHE.pop(next(iter(_PROGRAM_CACHE)))
        fn = _PROGRAM_CACHE[key] = get_jax().jit(builder())
    return fn


def acf_program(nf, nt, *, variant=None, normalise=True):
    """Cached jitted batched autocovariance
    ``fn(dyn[B, nf, nt]) → acf[B, 2nf, 2nt]`` under the declared
    ('real') or dense formulation — one compile per
    (shape, variant), site ``xfft.acf``."""
    if variant is None:
        variant = formulation("xfft.acf")
    key = ("acf", int(nf), int(nt), variant, bool(normalise))

    def build():
        from .acf import autocovariance

        def fn(dyn):
            return autocovariance(dyn, normalise=normalise,
                                  backend="jax", variant=variant)

        return fn

    return _cached_jit(key, build, site="xfft.acf")


def sspec_power_program(nf, nt, *, variant=None):
    """Cached jitted batched halved secondary-spectrum power
    ``fn(dyn[B, nf, nt]) → sec[B, nrfft//2, ncfft]`` under the
    declared ('half') or dense formulation — one compile per
    (shape, variant), site ``xfft.sspec``."""
    if variant is None:
        variant = formulation("xfft.sspec")
    key = ("sspec", int(nf), int(nt), variant)

    def build():
        from ..backend import get_jax
        from .sspec import secondary_spectrum_power

        jax = get_jax()

        def fn(dyn):
            return jax.vmap(
                lambda d: secondary_spectrum_power(
                    d, backend="jax", variant=variant))(dyn)

        return fn

    return _cached_jit(key, build, site="xfft.sspec")


# ---------------------------------------------------------------------
# abstract program probes (obs/programs.py) — audited by the jaxlint
# JP2xx program pass; the 'xfft.*' formulations enter the
# fingerprints, so a silent structured↔dense flip fails JP205
# ---------------------------------------------------------------------

from ..obs.programs import register_probe as _register_probe  # noqa: E402


@_register_probe("xfft.acf", formulations=("xfft.acf",))
def _probe_acf():
    """The batched Wiener–Khinchin autocovariance program at a fixed
    12×10 geometry under the active 'xfft.acf' formulation."""
    import jax

    fn = acf_program(12, 10)
    S = jax.ShapeDtypeStruct
    return fn, (S((2, 12, 10), np.float32),)


@_register_probe("xfft.sspec", formulations=("xfft.sspec",))
def _probe_sspec():
    """The batched halved secondary-spectrum power program at a fixed
    12×10 geometry under the active 'xfft.sspec' formulation."""
    import jax

    fn = sspec_power_program(12, 10)
    S = jax.ShapeDtypeStruct
    return fn, (S((2, 12, 10), np.float32),)
