"""Dynamic-spectrum rescaling: equal-wavelength, equal-velocity and
trapezoid resampling.

Re-design of ``Dynspec.scale_dyn`` (/root/reference/scintools/
dynspec.py:3872-4128). The reference loops over columns calling
scipy ``interp1d`` per time step (dynspec.py:3949-3956); here the cubic
interpolation is applied along the axis in one vectorised call.
"""

from __future__ import annotations

import numpy as np

from .interp import columnwise_cubic_interp
from .windows import get_window

SPEED_OF_LIGHT = 299792458.0  # m/s


def lambda_rescale(dyn, freqs, spacing="auto"):
    """Resample the frequency axis onto an equal-wavelength grid.

    dyn[nf, nt] with ascending ``freqs`` [MHz] →
    (lamdyn[nlam, nt] with *descending* wavelength rows matching the
    ascending-frequency convention, lam [m] descending, dlam [m]).
    Mirrors dynspec.py:3928-3959 including the edge-snap.
    """
    dyn = np.asarray(dyn)
    freqs = np.asarray(freqs, dtype=float)
    lams = SPEED_OF_LIGHT / (freqs * 1e6)
    dl = np.abs(np.diff(lams))
    if spacing == "max":
        dlam = np.max(dl)
    elif spacing == "median":
        dlam = np.median(dl)
    elif spacing == "mean":
        dlam = np.mean(dl)
    elif spacing == "min":
        dlam = np.min(dl)
    elif spacing == "auto":
        dlam = (np.max(lams) - np.min(lams)) / len(freqs)
    else:
        raise ValueError(f"unknown spacing {spacing!r}")
    lam_eq = np.arange(np.min(lams) + 1e-10, np.max(lams) - 1e-10, dlam)
    feq = np.round(SPEED_OF_LIGHT / lam_eq / 1e6, 6)
    # snap rounded endpoints back into the valid range
    feq[np.argmax(feq)] = min(feq.max(), freqs.max())
    feq[np.argmin(feq)] = max(feq.min(), freqs.min())
    arout = columnwise_cubic_interp(dyn, freqs, feq, axis=0)
    return np.flipud(arout), np.flip(lam_eq), float(dlam)


def velocity_rescale(dyn, veff):
    """Resample the time axis onto an equal cumulative-|veff| grid
    (dynspec.py:4055-4074). ``veff[nt]`` is the effective-velocity
    magnitude per subint."""
    dyn = np.asarray(dyn)
    vc_orig = np.cumsum(np.asarray(veff, dtype=float))
    vc_new = np.linspace(np.min(vc_orig), np.max(vc_orig), len(vc_orig))
    return columnwise_cubic_interp(dyn, vc_orig, vc_new, axis=1)


# jitted row-resample program per time axis: a fresh
# jax.jit(jax.vmap(row)) closure per call would retrace every rescale
# (the wrapper closes over the interp grid, and jax.jit caches on
# function identity — the fit/batch.py PR-4 trap)
_TRAPEZOID_CACHE = {}


def _trapezoid_program(times):
    """Cached jitted ``fn(X[nf, nt], dyn[nf, nt], valid[nf, nt])`` —
    the vmapped masked row interpolation of :func:`trapezoid_rescale`,
    keyed on the (concrete) time axis it closes over; jit's own
    per-signature cache handles shape changes."""
    key = times.tobytes()
    fn = _TRAPEZOID_CACHE.get(key)
    if fn is None:
        from ..backend import get_jax
        from ..obs import retrace as _retrace

        _retrace.record_build("ops.trapezoid_rescale", key)
        jax = get_jax()
        import jax.numpy as jnp

        t_j = jnp.asarray(times)

        def row(x, d, v):
            return jnp.where(v, jnp.interp(x, t_j, d), 0.0)

        if len(_TRAPEZOID_CACHE) >= 8:
            _TRAPEZOID_CACHE.pop(next(iter(_TRAPEZOID_CACHE)))
        fn = _TRAPEZOID_CACHE[key] = jax.jit(jax.vmap(row))
    return fn


def trapezoid_rescale(dyn, times, freqs, window="hanning",
                      window_frac=0.1, backend=None):
    """Trapezoid scaling: per-frequency-row time resampling with
    trailing zeros (dynspec.py:4081-4128).

    The per-row sample counts depend only on the (concrete) time and
    frequency axes, so on the jax backend the whole rescale is one
    fixed-shape program: a vmapped ``jnp.interp`` over rows with a
    per-row validity mask instead of the reference's python row loop.
    """
    from ..backend import resolve_backend

    backend = resolve_backend(backend)
    dyn = np.asarray(dyn, dtype=float)
    dyn = dyn - np.mean(dyn)
    nf, nt = dyn.shape
    if window is not None:
        cw, sw = get_window(nt, nf, window=window, frac=window_frac)
        dyn = cw * dyn
        dyn = (sw * dyn.T).T
    times = np.asarray(times, dtype=float)
    scalefrac = 1 / (np.max(freqs) / np.min(freqs))
    timestep = np.max(times) * (1 - scalefrac) / (nf + 1)
    maxtimes = np.max(times) - (nf - (np.arange(nf) + 1)) * timestep
    n_in = (times[None, :] <= maxtimes[:, None]).sum(axis=1)

    if backend == "numpy":
        out = np.empty_like(dyn)
        for ii in range(nf):
            newline = np.interp(
                np.linspace(np.min(times), np.max(times), n_in[ii]),
                times, dyn[ii, :])
            out[ii, :] = np.concatenate(
                [newline, np.zeros(nt - n_in[ii])])
        return out

    import jax.numpy as jnp

    j = np.arange(nt)
    # row-wise resample positions (linspace(min, max, n_in) padded)
    denom = np.maximum(n_in - 1, 1)[:, None]
    X = np.min(times) + j[None, :] * (np.max(times)
                                      - np.min(times)) / denom
    valid = j[None, :] < n_in[:, None]

    fn = _trapezoid_program(times)
    return np.asarray(fn(  # sync-ok: eager host
        # API — the resampled dynspec is this function's return value
        jnp.asarray(X), jnp.asarray(dyn), jnp.asarray(valid)))


# ---------------------------------------------------------------------
# abstract program probe (obs/programs.py) — audited by the jaxlint
# JP2xx program pass (tools/jaxlint/program.py)
# ---------------------------------------------------------------------

from ..obs.programs import register_probe as _register_probe  # noqa: E402


@_register_probe("ops.trapezoid_rescale")
def _probe_trapezoid_rescale():
    """The cached vmapped masked row interpolation at a fixed 16-bin
    time axis (the real entry: ``_trapezoid_program(times)``)."""
    import jax

    fn = _trapezoid_program(np.linspace(0.0, 30.0, 16))
    S = jax.ShapeDtypeStruct
    return fn, (S((8, 16), np.float32), S((8, 16), np.float32),
                S((8, 16), np.bool_))
