"""Pallas TPU kernel: the arc-normalised profile without HBM tents.

The XLA arc-profile program (ops/normsspec.py:make_arc_profile_batch_fn)
formulates each delay row's linear interpolation as a tent-kernel
matmul. That rides the MXU, but XLA materialises every (numsteps, nc)
tent slab in HBM — for a 128-epoch survey batch at numsteps=2000 that
is ~16 GB of HBM traffic for ~16 GFLOP of work: bandwidth-bound, and
the dominant cost of the whole survey arc fit on chip.

This kernel keeps the tent entirely in VMEM:

- grid over (epoch, delay row); each program loads ONE masked sspec
  row (a few KB), builds its tent tile in VMEM, contracts value and
  NaN-weight in one 2-row matmul, and accumulates the masked
  row-mean numerator/denominator in VMEM scratch;
- the profile leaves the kernel once per epoch (the last row writes
  num/den), so HBM traffic is rows-in + profiles-out (~tens of MB
  per batch instead of ~16 GB).

Semantics are pinned to the XLA path bit-for-bit-modulo-f32: same
clipped index arithmetic, endpoint clamping, local NaN poisoning via
the tent-weighted bad mask, support mask on the UNclipped query, and
0.0 fill for fully-masked bins (tests/test_arc_pallas.py).

Opt-in: ``SCINTOOLS_ARC_PALLAS=1`` (or ``pallas=True`` to
``make_arc_profile_batch_fn``); ``interpret=True`` runs on CPU for
tests. The q axis is padded to a lane multiple with far-out queries
whose support mask is always False.
"""

from __future__ import annotations

import os

import numpy as np

_PAD_Q = 1e30          # padded-query sentinel: |xq| > fmax always


def pad_to_multiple(n, m=128):
    return int(-(-n // m) * m)


def arc_profile_pallas_enabled():
    """True when the opt-in env knob asks for the Pallas profile
    kernel (the caller still checks the backend can run Mosaic)."""
    return os.environ.get("SCINTOOLS_ARC_PALLAS", "") == "1"


def make_arc_profile_pallas_fn(tdel_c, fdop, fdopnew, interpret=False):
    """Build ``fn(s_masked[B, R, ncp], good[B, R, ncp], scales[B, R])
    → profiles[B, Qp]`` where ``scales[b, r] = sqrt(tdel_c[r]/eta_b)``
    and ncp/Qp are the 128-padded column/query counts. The caller
    pre-masks NaNs (s_masked has 0 where NaN, ``good`` carries the
    finite mask) and crops the output to the true query count."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    tdel_c = np.asarray(tdel_c, dtype=float)
    fdop = np.asarray(fdop, dtype=float)
    fdopnew = np.asarray(fdopnew, dtype=float)
    R = len(tdel_c)
    nc = len(fdop)
    ncp = pad_to_multiple(nc)
    Q = len(fdopnew)
    Qp = pad_to_multiple(Q)
    f0 = float(fdop[0])
    dfd = float(np.mean(np.diff(fdop)))
    fmax = float(np.max(np.abs(fdop)))
    fq_pad = np.full(Qp, _PAD_Q)
    fq_pad[:Q] = fdopnew

    def kernel(scale_ref, fq_ref, s_ref, g_ref, out_ref, num_scr,
               den_scr):
        r = pl.program_id(1)
        sc = scale_ref[0, 0]
        fq = fq_ref[...]                       # (1, Qp)
        row = s_ref[0]                         # (1, ncp)
        bad = 1.0 - g_ref[0]
        xq = fq * sc
        pos = jnp.clip((xq - f0) / dfd, 0.0, nc - 1.0)
        # tent built column-major so the contraction is
        # (2, ncp) @ (ncp, Qp) and everything stays in (row, lane)
        # orientation — no sublane↔lane transposes for Mosaic
        k = jax.lax.broadcasted_iota(jnp.float32, (ncp, Qp), 0)
        tent = jnp.maximum(0.0, 1.0 - jnp.abs(pos - k))
        lhs = jnp.concatenate([row, bad], axis=0)      # (2, ncp)
        # precision=HIGHEST: same reason as the XLA tent matmul
        # (normsspec.py) — default MXU bf16 operand rounding would
        # eat into the <1% η parity budget
        out2 = jnp.dot(lhs, tent,
                       precision=jax.lax.Precision.HIGHEST,
                       preferred_element_type=jnp.float32)
        val = out2[0:1, :]
        nanw = out2[1:2, :]
        ok = ((jnp.abs(xq) <= fmax) & (nanw <= 0.0)) \
            .astype(jnp.float32)

        @pl.when(r == 0)
        def _init():
            num_scr[:] = jnp.zeros_like(num_scr)
            den_scr[:] = jnp.zeros_like(den_scr)

        num_scr[:] = num_scr[:] + val * ok
        den_scr[:] = den_scr[:] + ok

        @pl.when(r == R - 1)
        def _emit():
            den = den_scr[:]
            prof = jnp.where(den > 0, num_scr[:] / den, 0.0)
            out_ref[0] = jnp.broadcast_to(prof, (8, Qp))

    def fn(s_masked, good, scales):
        B = s_masked.shape[0]
        out = pl.pallas_call(
            kernel,
            grid=(B, R),
            in_specs=[
                pl.BlockSpec((1, 1), lambda b, r: (b, r),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((1, Qp), lambda b, r: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1, ncp), lambda b, r: (b, r, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1, ncp), lambda b, r: (b, r, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, 8, Qp), lambda b, r: (b, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((B, 8, Qp), jnp.float32),
            scratch_shapes=[pltpu.VMEM((1, Qp), jnp.float32),
                            pltpu.VMEM((1, Qp), jnp.float32)],
            interpret=interpret,
        )(scales.astype(jnp.float32),
          jnp.asarray(fq_pad, jnp.float32)[None, :],
          s_masked.astype(jnp.float32), good.astype(jnp.float32))
        return out[:, 0, :Q]

    return fn
