"""Secondary-spectrum kernel (2-D power spectrum of a dynamic spectrum).

Functional re-design of ``Dynspec.calc_sspec``
(/root/reference/scintools/dynspec.py:3584-3748): mean-subtract →
edge-taper window → zero-pad to next-pow2+1 → fft2 → power → fftshift →
keep positive delays → optional prewhiten (first-difference) /
post-darken → 10·log10.

All shapes are static given the input shape, so the jax path jits and
vmaps cleanly (BASELINE.json north-star kernel #1).
"""

from __future__ import annotations

import numpy as np

from ..backend import get_xp, register_formulation, resolve_backend
from ..backend import formulation as _formulation
from . import xfft
from .windows import get_window, apply_window

# formulation table (backend.py registry): the chunk conjugate
# spectrum of REAL input as a half-spectrum rfft2 + Hermitian gather
# (~half the FFT flops; PR-4 measurement ~2.8× the CS kernel on CPU)
# vs the complex fft2 oracle. Every platform currently picks 'rfft';
# the registry entry exists so a measured override
# (backend.measure_formulation) or env pin can flip it per host.
register_formulation(
    "ops.cs", default="rfft", choices=("rfft", "fft2"),
    doc="chunk conjugate spectrum: rfft2+Hermitian-gather vs complex "
        "fft2")


def fft_shapes(nf, nt):
    """FFT lengths used by the reference: next power of two, doubled."""
    nrfft = int(2 ** (np.ceil(np.log2(nf)) + 1))
    ncfft = int(2 ** (np.ceil(np.log2(nt)) + 1))
    return nrfft, ncfft


def sspec_axes(nf, nt, dt, df, halve=True, dlam=None):
    """(fdop [mHz], tdel [us], beta [m^-1] or None) axes for the sspec."""
    nrfft, ncfft = fft_shapes(nf, nt)
    td = np.arange(nrfft // 2 if halve else nrfft)
    fd = np.arange(-ncfft // 2, ncfft // 2)
    fdop = fd * 1e3 / (ncfft * dt)
    tdel = td / (nrfft * df)
    beta = td / (nrfft * dlam) if dlam is not None else None
    return fdop, tdel, beta


def _prewhite_diff(dyn):
    """2-D first-difference prewhitening: 'valid' convolution with
    [[1,-1],[-1,1]] (dynspec.py:3680-3682)."""
    return (dyn[1:, 1:] - dyn[1:, :-1] - dyn[:-1, 1:] + dyn[:-1, :-1])


def zoom_band(nf, nt, dt, df, tdel_band, fdop_band, n_tdel, n_fdop):
    """Convert a physical sspec window into the ``zoom=`` band pair:
    ``tdel_band`` (µs) and ``fdop_band`` (mHz, signed) become
    ``((r0, r1, n_tdel), (c0, c1, n_fdop))`` in the (fractional,
    signed) FFT-bin units of the padded frame that
    :func:`secondary_spectrum_power` and the xfft zoom programs take
    (tdel = td/(nrfft·df) → td = tdel·nrfft·df; fdop = fd·1e3/(ncfft·dt)
    → fd = fdop·ncfft·dt/1e3, :func:`sspec_axes` inverted)."""
    nrfft, ncfft = fft_shapes(nf, nt)
    r = (float(tdel_band[0]) * nrfft * df,
         float(tdel_band[1]) * nrfft * df, int(n_tdel))
    c = (float(fdop_band[0]) * ncfft * dt / 1e3,
         float(fdop_band[1]) * ncfft * dt / 1e3, int(n_fdop))
    return r, c


def secondary_spectrum_power(dyn, window_arrays=None, prewhite=False,
                             halve=True, backend=None, variant=None,
                             zoom=None):
    """Linear-power secondary spectrum of ``dyn[nf, nt]``.

    window_arrays: optional (chan_window[nt], subint_window[nf]) from
    :func:`get_window`; None to skip windowing.

    Returns power (not dB) with shape (nrfft//2 if halve else nrfft, ncfft).

    ``variant=None`` resolves the ``'xfft.sspec'`` formulation
    (backend.py registry): ``'half'`` declares the real input and the
    ``halve`` row crop to the transform layer so only the kept half
    of the spectrum is ever computed (ops/xfft.py); ``'dense'`` is
    the full complex-fft2 oracle (parity rtol-pinned in
    tests/test_xfft.py).

    ``zoom`` — an optional ``(band_rows, band_cols)`` pair of
    ``(f0, f1, n_out)`` triples in (fractional, signed) bin units of
    the padded frame (:func:`zoom_band` converts physical µs/mHz
    windows): the transform computes ONLY those band pixels, at any
    output density, through the band-limited 'xfft.zoom' lowering
    (Bluestein chirp-Z; 'dense' = the DFT-matmul oracle). Low-η /
    wide-arc regimes get full Doppler–delay resolution inside the
    arc region at a fraction of the frame FLOPs. The returned array
    runs f0→f1 per axis (the band is its own layout — no fftshift,
    and ``halve``/``prewhite`` don't apply). ``variant`` then means
    czt|dense.
    """
    backend = resolve_backend(backend)
    xp = get_xp(backend)
    dyn = xp.asarray(dyn)
    nf, nt = dyn.shape
    nrfft, ncfft = fft_shapes(nf, nt)

    if zoom is not None and prewhite:
        raise RuntimeError("prewhite post-darkening is defined on the "
                           "native frame — not with zoom=")

    dyn = dyn - xp.mean(dyn)
    if window_arrays is not None:
        dyn = apply_window(dyn, window_arrays[0], window_arrays[1], xp)
    dyn = dyn - xp.mean(dyn)

    if zoom is not None:
        p = xfft.plan((nf, nt), (nrfft, ncfft), real_input=True,
                      band=zoom, op="xfft.zoom")
        return p.power(dyn, xp=xp, variant=variant)

    if prewhite:
        if not halve:
            raise RuntimeError("Cannot apply prewhite to full frame")
        dyn = _prewhite_diff(dyn)

    # declared structure (ops/xfft.py): real input, zero-pad to the
    # FFT frame, and — when halving — the row crop nrfft//2 folded
    # INTO the transform, so on the 'half' formulation the discarded
    # half of the spectrum is never computed. 'dense' (the
    # pre-layer fft2 → |·|² → fftshift → crop) stays the oracle; the
    # full-frame (halve=False) output always takes it.
    p = xfft.plan((nf, nt), (nrfft, ncfft), real_input=True,
                  crop=(nrfft // 2, None) if halve else None,
                  layout="shifted", op="xfft.sspec")
    sec = p.power(dyn, xp=xp, variant=variant)

    if prewhite:  # post-darken
        fd = np.arange(-ncfft // 2, ncfft // 2)
        td = np.arange(nrfft // 2)
        postdark = np.outer(np.sin(np.pi / nrfft * td) ** 2,
                            np.sin(np.pi / ncfft * fd) ** 2)
        postdark[:, ncfft // 2] = 1
        postdark[0, :] = 1
        sec = sec / xp.asarray(postdark)
    return sec


def pad_chunk_batch(dspecs, npad, xp=np):
    """Mean-pad a batch of θ-θ chunks: ``(B, nf, nt) →
    (B, (1+npad)·nf, (1+npad)·nt)``, each chunk padded with its own
    mean (the per-chunk counterpart of ``thth.search.pad_chunk`` with
    ``fill='mean'``).

    Written as one static-shape expression — zero-pad the
    mean-subtracted chunk and add the mean back, equal to
    constant-padding with the chunk mean up to one float rounding of
    the data region — so it jits/vmaps and shards over the chunk
    batch. ``xp=jnp`` works on traced values.
    """
    dspecs = xp.asarray(dspecs)
    _, nf, nt = dspecs.shape
    mu = xp.mean(dspecs, axis=(1, 2), keepdims=True)
    return xp.pad(dspecs - mu,
                  ((0, 0), (0, npad * nf), (0, npad * nt))) + mu


# the Hermitian completion moved into the transform layer
# (ops/xfft.py — the shared real-input lowering); this alias keeps
# the historical name importable for its pre-layer call sites
_full_from_rfft2 = xfft.hermitian_full_from_half


def chunk_conjugate_spectrum_batch(dspecs, npad=3, tau_keep=None,
                                   xp=np, method=None, shift=True):
    """Batched device-capable chunk conjugate spectrum: per-chunk mean
    pad → ``fft2`` → ``fftshift`` (the θ-θ search's
    ``chunk_conjugate_spectrum`` for a whole same-geometry chunk stack
    with static shapes, /root/reference/scintools/ththmod.py:772-787).

    ``dspecs[B, nf, nt]`` real → ``CS[B, (1+npad)nf, (1+npad)nt]``
    complex. ``tau_keep`` is an optional host-computed bool mask over
    the (fftshifted) delay axis — rows with ``|tau| < tau_mask`` are
    zeroed, matching the host path's ``CS[|tau| < tau_mask] = 0``.
    The fused search path (thth/batch.py:make_fused_search_fn) calls
    this with ``xp=jnp`` inside one jitted program, so raw chunks are
    the only host→device transfer.

    ``method=None`` (default) resolves through the per-platform
    formulation registry (``backend.formulation('ops.cs')`` — 'rfft'
    everywhere unless overridden). ``method="rfft"`` exploits the
    chunks being REAL: a half-spectrum ``rfft2`` plus a
    Hermitian-symmetry gather (ops/xfft.py
    :func:`~scintools_tpu.ops.xfft.hermitian_full_from_half`) replaces the
    full complex ``fft2`` — roughly half the FFT flops of the
    dominant kernel in the staged sspec_thth path, with
    bit-level-close output (parity rtol-pinned in tests/test_ops.py).
    ``method="fft2"`` keeps the complex transform as the oracle;
    complex-valued inputs (wavefield chunks) always take the ``fft2``
    path.

    ``shift=False`` skips the final ``fftshift`` and returns the CS
    in RAW fft layout: the shift is a pure permutation, so a consumer
    whose access pattern is an index gather (the batched retrieval,
    thth/retrieval.py) folds it into its index map instead of paying
    a full-array memory pass — ``tau_keep`` (defined on the shifted
    axis) is not supported in that mode.
    """
    if not shift and tau_keep is not None:
        raise ValueError("tau_keep indexes the SHIFTED delay axis — "
                         "fold the mask into the consumer's gather "
                         "when shift=False")
    if method is None:
        method = _formulation("ops.cs")
    if method not in ("rfft", "fft2"):
        raise ValueError(f"unknown conjugate-spectrum method "
                         f"{method!r} (want 'rfft' or 'fft2')")
    padded = pad_chunk_batch(dspecs, npad, xp=xp)
    # declared structure (ops/xfft.py): the padded chunks are REAL
    # (complex wavefield chunks auto-fall-back to the dense oracle
    # inside the layer), so 'rfft' lowers to the half-spectrum rfft2
    # + Hermitian completion — bit-identical to the pre-layer
    # formulation (pinned in tests/test_xfft.py)
    CS = xfft.fft2_full(padded, variant=method, xp=xp)
    if not shift:
        return CS
    CS = xp.fft.fftshift(CS, axes=(-2, -1))
    if tau_keep is not None:
        CS = xp.where(xp.asarray(tau_keep)[None, :, None], CS,
                      xp.zeros((), dtype=CS.dtype))
    return CS


def secondary_spectrum(dyn, dt, df, window="hanning", window_frac=0.1,
                       prewhite=False, halve=True, dlam=None, db=True,
                       backend=None, variant=None):
    """Full sspec pipeline → (fdop [mHz], yaxis, sec[dB]).

    yaxis is beta [m^-1] when ``dlam`` is given (wavelength-rescaled
    input), else tdel [us]. ``variant`` routes the transform-layer
    formulation (see :func:`secondary_spectrum_power`).
    """
    backend = resolve_backend(backend)
    xp = get_xp(backend)
    nf, nt = np.shape(dyn)
    wins = None
    if window is not None:
        wins = get_window(nt, nf, window=window, frac=window_frac)
    sec = secondary_spectrum_power(dyn, window_arrays=wins,
                                   prewhite=prewhite, halve=halve,
                                   backend=backend, variant=variant)
    if db:
        with np.errstate(divide="ignore"):
            sec = 10 * xp.log10(sec)
    fdop, tdel, beta = sspec_axes(nf, nt, dt, df, halve=halve, dlam=dlam)
    yaxis = beta if dlam is not None else tdel
    return fdop, yaxis, sec
