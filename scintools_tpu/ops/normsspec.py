"""Arc-normalised secondary spectrum (the η-search workhorse).

Re-design of ``Dynspec.norm_sspec`` (/root/reference/scintools/
dynspec.py:1920-2281). The reference loops over delay rows in python,
renormalising each row's Doppler axis by the arc (fdop/√(tdel/η)) and
interpolating onto a common grid. Here that is one batched linear
interpolation: row i is sampled at fdopnew·√(tdel_i/η) — vmappable and
static-shaped, so the whole η grid search becomes a single device
kernel (north-star kernel #3).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..backend import get_xp, register_formulation, resolve_backend, \
    get_jax
from ..backend import formulation as _formulation

# formulation table (backend.py registry): arc-profile row resampling
# as MXU tent-weight slabs vs index-arithmetic gather interpolation
register_formulation(
    "ops.arc_profile_interp", default="tent",
    choices=("tent", "gather"), platforms={"cpu": "gather"},
    doc="arc-normalised profile interpolation: tent-weight matmul "
        "slabs vs the uniform-grid gather interp")


@dataclass
class NormSspec:
    """Result record for a normalised secondary spectrum."""

    normsspecavg: np.ndarray     # delay-scrunched Doppler profile
    normsspec: np.ndarray        # (ntdel, nfdop) normalised spectrum
    mask: np.ndarray             # True where outside data support / NaN
    tdel: np.ndarray             # delay axis used (cropped)
    fdop: np.ndarray             # normalised fdop axis
    powerspectrum: np.ndarray    # masked mean linear power per delay row
    weights: np.ndarray          # per-row weights used for the average
    ps_wn: float = None
    ps_amp: float = None
    ps_alpha: float = None
    ps_wn_err: float = None
    ps_amp_err: float = None
    ps_alpha_err: float = None


def _interp_rows_np(sspec, x_src, xq):
    """Rows sspec[i] sampled at xq[i] over source axis x_src (numpy)."""
    out = np.empty((sspec.shape[0], xq.shape[1]))
    for i in range(sspec.shape[0]):
        out[i] = np.interp(xq[i], x_src, sspec[i])
    return out


def scaled_row_interp(sspec, fdop, tdel, eta, fdopnew, backend=None):
    """Sample each delay row at original Doppler fdopnew·√(tdel_i/η).

    Returns (norm[ntdel, nq], mask[ntdel, nq]); mask marks points
    outside each row's renormalised data support (|fdopnew| beyond the
    largest available normalised Doppler for that row) or NaN output.
    """
    backend = resolve_backend(backend)
    xp = get_xp(backend)
    sspec = xp.asarray(sspec)
    scale = xp.sqrt(xp.asarray(tdel) / eta)  # (ntdel,)
    xq = xp.asarray(fdopnew)[None, :] * scale[:, None]
    fmax = float(np.max(np.abs(fdop)))
    if backend == "jax":
        jax = get_jax()
        dfd = np.diff(np.asarray(fdop, dtype=float))
        if dfd.size and np.allclose(dfd, dfd[0], rtol=1e-6):
            # uniform Doppler grid (fft_axis always is): linear interp
            # as direct index arithmetic + two row gathers. jnp.interp
            # runs a searchsorted binary-search per query point, which
            # on TPU costs seconds for a survey batch (measured 5.45 s
            # for 128×62×2000 queries); this form is pure vector math.
            # Endpoint clamping and local NaN propagation match
            # np.interp: w=0/1 at the edges selects y[0]/y[-1], and a
            # NaN neighbour poisons exactly the spans np.interp would.
            f0 = xp.asarray(fdop)[0]
            pos = (xq - f0) / dfd[0]
            i0 = xp.clip(xp.floor(pos).astype(int), 0,
                         len(fdop) - 2)
            w = xp.clip(pos - i0, 0.0, 1.0)
            y0 = xp.take_along_axis(sspec, i0, axis=1)
            y1 = xp.take_along_axis(sspec, i0 + 1, axis=1)
            norm = y0 * (1 - w) + y1 * w
        else:
            # NaN-aware linear interpolation: NaNs propagate locally
            norm = jax.vmap(
                lambda q, row: xp.interp(q, xp.asarray(fdop), row)
            )(xq, sspec)
    else:
        norm = _interp_rows_np(np.asarray(sspec), np.asarray(fdop),
                               np.asarray(xq))
    # support mask: reference masks |fdopnew| > max(|selected fdop|)/scale
    sup = xp.abs(xp.asarray(fdopnew))[None, :] * scale[:, None] > fmax
    mask = sup | xp.isnan(norm)
    return norm, mask


def make_arc_profile_batch_fn(tdel, fdop, delmax=None, startbin=1,
                              cutmid=0, numsteps=10000, maxnormfac=1,
                              fold=False, pallas=None):
    """Batched arc-normalised Doppler profile: ONE jitted program
    computing, for every epoch of a same-geometry survey batch, the
    delay-scrunched normalised profile that ``fit_arc`` peak-fits
    (the reference computes it serially per epoch through
    ``norm_sspec``, dynspec.py:970-1180 → :1920-2281; here the row
    interpolation AND the masked mean are vmapped over epochs).

    Geometry (axes, crop, cutmid, fdopnew grid) is baked; the
    normalising curvature is a traced per-epoch scalar. Matches
    ``normalise_sspec(..., maxnormfac=1, weighted=False)`` — the
    fit_arc defaults (single arc, no log steps, unweighted average).

    Returns jitted ``fn(sspecs[B, ntdel, nfdop], etas[B]) →
    profiles[B, numsteps]`` (0.0 where no delay row contributes —
    the serial path's ``np.ma.average`` fill, reference-pinned).

    With ``fold=True`` the ±fdop halves are averaged about zero
    INSIDE the program (fit_arc's folding, dynspec.py:1166-1180) and
    the output is ``[B, numsteps//2]`` over the fdopnew ≥ 0 bins —
    halving the device→host fetch, which matters on a tunneled link.

    ``pallas`` selects the VMEM-resident tent kernel
    (ops/arc_pallas.py — same semantics, ~1000× less HBM traffic
    than the XLA tent slabs; uniform Doppler grids only). Default
    (None): on when ``SCINTOOLS_ARC_PALLAS=1``; runs in interpret
    mode off-TPU so tests exercise the identical kernel.
    """
    jax = get_jax()
    # every call builds a fresh program (callers cache per geometry —
    # ops/fitarc.py:_ARC_PROFILE_CACHE), so each entry is one
    # accounted build for the retrace gate
    from ..obs import retrace as _retrace

    _retrace.record_build(
        "ops.arc_profile",
        (np.asarray(tdel).tobytes(), np.asarray(fdop).tobytes(),
         None if delmax is None else float(delmax), int(startbin),
         int(cutmid), int(numsteps), float(maxnormfac), bool(fold),
         None if pallas is None else bool(pallas)))
    import jax.numpy as jnp

    tdel = np.asarray(tdel, dtype=float)
    fdop = np.asarray(fdop, dtype=float)
    delmax = np.max(tdel) if delmax is None else delmax
    ind = int(np.argmin(np.abs(tdel - delmax)))
    tdel_c = tdel[startbin:ind]
    nc = len(fdop)
    cut_sl = (int(nc / 2 - np.floor(cutmid / 2)),
              int(nc / 2 + np.floor(cutmid / 2))) if cutmid > 0 \
        else None
    # even grid, like normalise_sspec's nfdop rounding — the caller's
    # ±fdop fold pairs bins about zero
    numsteps = int(numsteps) + int(numsteps) % 2
    fdopnew = np.linspace(-maxnormfac, maxnormfac, numsteps)

    nc_src = len(fdop)
    f0 = float(fdop[0])
    dfd_all = np.diff(fdop)
    uniform = dfd_all.size > 0 and np.allclose(dfd_all, dfd_all[0],
                                               rtol=1e-6)
    dfd0 = float(np.mean(dfd_all)) if dfd_all.size else 1.0
    fmax = float(np.max(np.abs(fdop)))
    k_idx = np.arange(nc_src, dtype=float)

    def one_any_grid(sspec, eta):
        # non-uniform Doppler axis: the tent-matmul below would
        # silently use the mean spacing — fall back to the general
        # per-row interp (scaled_row_interp), which handles any grid
        s = sspec[startbin:ind, :]
        if cut_sl is not None:
            s = s.at[:, cut_sl[0]:cut_sl[1]].set(jnp.nan)
        norm, mask = scaled_row_interp(s, fdop, tdel_c, eta, fdopnew,
                                       backend="jax")
        good = ~mask
        num = jnp.sum(jnp.where(good, norm, 0.0), axis=0)
        den = jnp.sum(good, axis=0)
        return jnp.where(den > 0, num / den, 0.0)

    def one(sspec, eta):
        s = sspec[startbin:ind, :]
        if cut_sl is not None:
            s = s.at[:, cut_sl[0]:cut_sl[1]].set(jnp.nan)
        # Per-row linear interp onto fdopnew·√(tdel_r/η) — the serial
        # path's scaled_row_interp — formulated as a tent-kernel
        # matmul: on a uniform source grid, np.interp(q, x, y) ≡
        # tent(pos_q − k) @ y with tent(u) = max(0, 1−|u|), and a
        # matmul rides the MXU where a per-point gather crawls
        # (measured 1.5 s → ~0.1 s for a 128-epoch survey batch on
        # TPU). lax.map walks the rows so the tent tensor stays one
        # (numsteps, nc) slab; the epoch axis stays a vmap, which
        # GSPMD can shard (parallel/survey.py). NOTE this is a second
        # uniform-grid linear-interp implementation next to
        # scaled_row_interp's gather branch (which cannot use the
        # tent form: without the row-blocked lax.map the tent tensor
        # is O(ntdel·nq·nc) at once) — keep their edge/NaN semantics
        # aligned when touching either.
        scale = jnp.sqrt(jnp.asarray(tdel_c) / eta)
        fq = jnp.asarray(fdopnew)

        def row_interp(row_and_scale):
            row, sc = row_and_scale
            xq = fq * sc
            pos = jnp.clip((xq - f0) / dfd0, 0.0, nc_src - 1.0)
            tent = jnp.maximum(
                0.0, 1.0 - jnp.abs(pos[:, None] - jnp.asarray(k_idx)))
            good_src = ~jnp.isnan(row)
            # precision=highest: the TPU MXU's default bf16 operand
            # rounding (~3 digits) would eat into the <1% η parity
            # budget; the FLOPs here are trivial next to the tent's
            # HBM traffic, so full f32 passes cost nothing
            hi = jax.lax.Precision.HIGHEST
            val = jnp.dot(tent, jnp.where(good_src, row, 0.0),
                          precision=hi)
            # a query is poisoned iff a NaN source bin gets weight —
            # np.interp's local-NaN propagation (reference-pinned)
            nanw = jnp.dot(tent, (~good_src).astype(row.dtype),
                           precision=hi)
            m = (jnp.abs(xq) > fmax) | (nanw > 0)
            return val, m

        norm, mask = jax.lax.map(row_interp, (s, scale))
        good = ~mask
        num = jnp.sum(jnp.where(good, norm, 0.0), axis=0)
        den = jnp.sum(good, axis=0)
        # fully-masked bins are 0.0, NOT NaN: the serial path's
        # np.ma.average fills them with 0.0 (reference-pinned,
        # tests/test_golden_reference.py) and the downstream peak fit
        # must see the identical profile
        return jnp.where(den > 0, num / den, 0.0)

    explicit_pallas = pallas is True
    if pallas is None:
        from .arc_pallas import arc_profile_pallas_enabled

        pallas = arc_profile_pallas_enabled()
    if pallas and not uniform:
        if explicit_pallas:
            raise ValueError(
                "pallas=True needs a uniform Doppler grid (the tent "
                "kernel assumes index arithmetic) — this axis is "
                "non-uniform")
        pallas = False               # env knob: quiet XLA fallback
    # formulation policy (profiled on the 16×256² survey_arc bench
    # geometry): the tent slabs ride the MXU on TPU, but on CPU they
    # are pure overhead — the same batch measured 2.57 s as tent
    # matmuls vs 0.12 s as the index-arithmetic gather interp
    # (scaled_row_interp's uniform branch, identical np.interp
    # semantics). One geometry-keyed compiled program either way
    # (ops/fitarc.py:_ARC_PROFILE_CACHE). Dispatched through the
    # per-platform formulation registry (backend.py).
    if _formulation("ops.arc_profile_interp") == "gather":
        uniform = False              # route through the gather interp
    if pallas:
        from .arc_pallas import (make_arc_profile_pallas_fn,
                                 pad_to_multiple)

        interp = jax.default_backend() != "tpu"
        kfn = make_arc_profile_pallas_fn(tdel_c, fdop, fdopnew,
                                         interpret=interp)
        ncp = pad_to_multiple(nc_src)

        def base(sspecs, etas):
            s = sspecs[:, startbin:ind, :]
            if cut_sl is not None:
                s = s.at[:, :, cut_sl[0]:cut_sl[1]].set(jnp.nan)
            good = ~jnp.isnan(s)
            s_m = jnp.where(good, s, 0.0)
            padc = ncp - nc_src
            if padc:
                s_m = jnp.pad(s_m, ((0, 0), (0, 0), (0, padc)))
                good = jnp.pad(good, ((0, 0), (0, 0), (0, padc)))
            scales = jnp.sqrt(jnp.asarray(tdel_c)[None, :]
                              / etas[:, None])
            return kfn(s_m, good.astype(jnp.float32), scales)
    else:
        base = jax.vmap(one if uniform else one_any_grid)
    if not fold:
        return jax.jit(base)
    pos = fdopnew >= 0

    def folded(sspecs, etas):
        profs = base(sspecs, etas)
        return (profs[:, pos] + jnp.flip(profs[:, ~pos], axis=1)) / 2

    return jax.jit(folded)


def normalise_sspec(sspec, tdel, fdop, eta, delmax=None, startbin=1,
                    maxnormfac=5, minnormfac=0, cutmid=0, numsteps=None,
                    logsteps=False, weighted=True, interp_nan=False,
                    fit_spectrum=False, powerspec_cut=False,
                    subtract_artefacts=False, backend=None):
    """Full norm_sspec pipeline on a (dB) secondary spectrum.

    sspec[ntdel, nfdop] in dB with delay axis ``tdel`` (us or m^-1) and
    Doppler axis ``fdop`` (mHz); ``eta`` in the matching curvature
    convention. Returns :class:`NormSspec`.
    """
    backend = resolve_backend(backend)
    sspec = np.array(sspec, dtype=float)
    tdel_full = np.asarray(tdel, dtype=float)
    fdop = np.asarray(fdop, dtype=float)

    delmax = np.max(tdel_full) if delmax is None else delmax
    ind = int(np.argmin(np.abs(tdel_full - delmax)))
    sspec = sspec[startbin:ind, :]
    tdel_c = tdel_full[startbin:ind]
    nr, nc = sspec.shape
    if cutmid > 0:
        sspec[:, int(nc / 2 - np.floor(cutmid / 2)):
              int(nc / 2 + np.floor(cutmid / 2))] = np.nan

    if subtract_artefacts:
        # delay response estimated from outer 10% in Doppler
        outer = np.abs(fdop) > 0.9 * np.max(fdop)
        delay_response = np.nanmean(sspec[:, outer], axis=1)
        delay_response = delay_response - np.median(delay_response)
        sspec = sspec - delay_response[:, None]

    maxfdop = maxnormfac * np.sqrt(tdel_c[-1] / eta)
    maxfdop = min(maxfdop, np.max(fdop))
    nfdop = (2 * np.sum(np.abs(fdop) <= maxfdop) if numsteps is None
             else int(numsteps))
    if nfdop % 2 != 0:
        nfdop += 1

    if logsteps:
        fdoplin = np.abs(np.linspace(-maxnormfac, maxnormfac, int(nfdop)))
        fdop_pos = 10 ** np.linspace(np.log10(np.min(fdoplin)),
                                     np.log10(np.max(fdoplin)),
                                     int(nfdop / 2))
        fdopnew = np.concatenate((-np.flip(fdop_pos), fdop_pos))
    else:
        fdopnew = np.linspace(-maxnormfac, maxnormfac, nfdop)
    if minnormfac > 0:
        fdopnew = fdopnew[np.abs(fdopnew) > minnormfac]

    norm, mask = scaled_row_interp(sspec, fdop, tdel_c, eta, fdopnew,
                                   backend=backend)
    norm = np.asarray(norm)
    mask = np.asarray(mask)

    if interp_nan:
        from ..ops.interp import interp_nan_2d
        norm = interp_nan_2d(norm)
        mask = mask & ~np.isfinite(norm) | (np.abs(fdopnew)[None, :]
                                            * np.sqrt(tdel_c / eta)[:, None]
                                            > np.max(np.abs(fdop)))

    mnorm = np.ma.array(norm, mask=mask)
    if logsteps:
        # the reference computes the delay power spectrum from a
        # parallel *linear*-grid interpolation (dynspec.py:2088-2127) so
        # log-spaced oversampling of the arc core doesn't bias it
        # (reference samples |linspace|, i.e. the positive side twice)
        fdoplin = np.abs(np.linspace(-maxnormfac, maxnormfac, int(nfdop)))
        nlin, mlin = scaled_row_interp(sspec, fdop, tdel_c, eta, fdoplin,
                                       backend=backend)
        mlin_arr = np.ma.array(np.asarray(nlin), mask=np.asarray(mlin))
        powerspectrum = np.asarray(np.ma.mean(10 ** (mlin_arr / 10),
                                              axis=1))
    else:
        powerspectrum = np.asarray(np.ma.mean(10 ** (mnorm / 10), axis=1))

    # arc power-spectrum model: wn + amp·x^alpha over x=√tdel
    xdata = np.sqrt(tdel_c)
    ydata = xdata * powerspectrum
    valid = np.isfinite(xdata) & np.isfinite(ydata)
    xdata, ydata = xdata[valid], ydata[valid]
    alpha = -11 / 3
    index = int(np.argmin(np.abs(xdata - 10)))
    amp = ydata[index] * xdata[index] ** -alpha
    wn = np.min(ydata)
    ps = {}
    if fit_spectrum:
        from ..fit.parameters import Parameters
        from ..fit.fitter import fitter
        from ..fit.models import powerspectrum_model

        params = Parameters()
        params.add("wn", value=wn, vary=True, min=np.min(ydata), max=np.inf)
        params.add("alpha", value=alpha, vary=True, min=-np.inf, max=0)
        params.add("amp", value=amp, vary=True, min=0.0, max=np.inf)
        results = fitter(powerspectrum_model, params, (xdata, ydata))
        wn = results.params["wn"].value
        amp = results.params["amp"].value
        alpha = results.params["alpha"].value
        ps = dict(ps_wn=wn, ps_amp=amp, ps_alpha=alpha,
                  ps_wn_err=results.params["wn"].stderr,
                  ps_amp_err=results.params["amp"].stderr,
                  ps_alpha_err=results.params["alpha"].stderr)

    arc_spectrum = amp * xdata ** alpha
    if weighted:
        weights = 10 * np.log10(arc_spectrum)
    else:
        weights = np.ones(np.shape(arc_spectrum))

    if powerspec_cut:
        sel = (arc_spectrum > wn)
        avg = np.ma.average(mnorm[sel, :], axis=0, weights=weights[sel])
    else:
        avg = np.ma.average(mnorm, axis=0, weights=weights)
    avg = np.asarray(avg)

    return NormSspec(normsspecavg=avg, normsspec=norm, mask=mask,
                     tdel=tdel_c, fdop=fdopnew,
                     powerspectrum=powerspectrum, weights=weights, **ps)


# ---------------------------------------------------------------------
# abstract program probe (obs/programs.py) — audited by the jaxlint
# JP2xx program pass (tools/jaxlint/program.py)
# ---------------------------------------------------------------------

from ..obs.programs import register_probe as _register_probe  # noqa: E402


@_register_probe("ops.arc_profile",
                 formulations=("ops.arc_profile_interp",))
def _probe_arc_profile():
    """Fixed small geometry: 2 epochs, 16x16 secondary spectrum, 32
    profile steps, XLA base (pallas=False — the formulation the
    sharded path compiles)."""
    import jax

    tdel = np.linspace(0.0, 1.0, 16)
    fdop = np.linspace(-1.0, 1.0, 16)
    fn = make_arc_profile_batch_fn(tdel, fdop, numsteps=32,
                                   pallas=False)
    S = jax.ShapeDtypeStruct
    return fn, (S((2, 16, 16), np.float32), S((2,), np.float32))
