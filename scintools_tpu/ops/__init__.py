"""Array-transform kernels: secondary spectra, ACFs, windows,
rescaling, normalised sspec, arc fitting, inpainting — FFT-shaped
kernels declare their structure to the transform layer
(:mod:`~scintools_tpu.ops.xfft`)."""

from . import xfft
from .sspec import secondary_spectrum, secondary_spectrum_power
from .acf import autocovariance, acf_from_sspec, autocorr_direct
from .windows import get_window
from .fitarc import fit_arc, ArcFit
from .normsspec import normalise_sspec
from .inpaint import inpaint_biharmonic

__all__ = ["secondary_spectrum", "secondary_spectrum_power",
           "autocovariance", "acf_from_sspec", "autocorr_direct", "get_window", "fit_arc", "ArcFit",
           "normalise_sspec", "inpaint_biharmonic", "xfft"]
