"""Biharmonic inpainting (skimage-free).

Replaces the reference's ``skimage.restoration.inpaint_biharmonic``
dependency (dynspec.py:3301-3307) with a direct sparse solve of the
biharmonic equation ∇⁴u = 0 over the masked region with the observed
pixels as boundary conditions — the same PDE skimage solves.
"""

from __future__ import annotations

import numpy as np
from scipy.sparse import coo_matrix
from scipy.sparse.linalg import spsolve

# 13-point biharmonic stencil (discrete ∇⁴)
_STENCIL = [
    ((0, 0), 20.0),
    ((-1, 0), -8.0), ((1, 0), -8.0), ((0, -1), -8.0), ((0, 1), -8.0),
    ((-1, -1), 2.0), ((-1, 1), 2.0), ((1, -1), 2.0), ((1, 1), 2.0),
    ((-2, 0), 1.0), ((2, 0), 1.0), ((0, -2), 1.0), ((0, 2), 1.0),
]


def inpaint_biharmonic(image, mask):
    """Fill ``mask`` pixels of ``image`` by solving ∇⁴u = 0.

    Stencil points falling outside the grid are dropped (free/natural
    boundary), matching skimage's behaviour closely.
    """
    image = np.asarray(image, dtype=float)
    mask = np.asarray(mask, dtype=bool)
    out = np.array(image)
    if not mask.any():
        return out
    ny, nx = image.shape
    unknown = np.flatnonzero(mask.ravel())
    index_of = -np.ones(ny * nx, dtype=int)
    index_of[unknown] = np.arange(len(unknown))

    n = len(unknown)
    b = np.zeros(n)
    filled = np.where(mask, 0.0, image)
    flat_mask = mask.ravel()
    flat_img = filled.ravel()

    # one vectorised pass per stencil offset (13 passes total) instead
    # of a python loop over masked pixels
    ys, xs = np.unravel_index(unknown, (ny, nx))
    rows_acc, cols_acc, vals_acc = [], [], []
    row_idx = np.arange(n)
    for (dy, dx), w in _STENCIL:
        yy, xx = ys + dy, xs + dx
        ok = (yy >= 0) & (yy < ny) & (xx >= 0) & (xx < nx)
        flat = yy[ok] * nx + xx[ok]
        rows = row_idx[ok]
        isunk = flat_mask[flat]
        rows_acc.append(rows[isunk])
        cols_acc.append(index_of[flat[isunk]])
        vals_acc.append(np.full(int(isunk.sum()), w))
        np.subtract.at(b, rows[~isunk], w * flat_img[flat[~isunk]])
    A = coo_matrix((np.concatenate(vals_acc),
                    (np.concatenate(rows_acc), np.concatenate(cols_acc))),
                   shape=(n, n)).tocsr()
    out[mask] = spsolve(A, b)
    return out


def median_filter_2d(arr, kernel_size=5, backend=None):
    """2-D median filter with ``scipy.signal.medfilt`` semantics
    (zero padding, odd square kernel) — the refill 'median' method's
    smoother (reference dynspec.py:3308-3315), formulated as a
    fixed-shape neighbourhood sort so it runs on either backend (the
    jax path is one jitted sort on device instead of the host scipy
    loop).

    ``kernel_size`` may be an int or an (kf, kt) pair of odd ints.
    """
    from ..backend import get_xp, resolve_backend

    backend = resolve_backend(backend)
    xp = get_xp(backend)
    if np.isscalar(kernel_size):
        kf = kt = int(kernel_size)
    else:
        kf, kt = (int(k) for k in kernel_size)
    if kf % 2 == 0 or kt % 2 == 0:
        raise ValueError("kernel_size must be odd (medfilt semantics)")
    a = xp.asarray(arr)
    H, W = np.shape(arr)
    pf, pt = kf // 2, kt // 2
    pad = xp.zeros((H + 2 * pf, W + 2 * pt), dtype=a.dtype)
    if backend == "jax":
        pad = pad.at[pf:pf + H, pt:pt + W].set(a)
    else:
        pad[pf:pf + H, pt:pt + W] = a
    stack = xp.stack([pad[i:i + H, j:j + W]
                      for i in range(kf) for j in range(kt)])
    srt = xp.sort(stack, axis=0)
    return srt[(kf * kt) // 2]


def refill_median(dyn, kernel_size=5, backend=None):
    """The reference's median refill (dynspec.py:3308-3315): replace
    NaNs by the kernel median of the mean-filled array."""
    arr = np.array(dyn, dtype=float)
    nanmask = np.isnan(arr)
    if not nanmask.any():
        return arr
    # finite-only mean (the façade's is_valid mask): a stray ±inf
    # pixel must not poison every filled value
    arr[nanmask] = np.mean(arr[np.isfinite(arr)])
    med = np.asarray(median_filter_2d(arr, kernel_size,
                                      backend=backend))
    out = np.array(dyn, dtype=float)
    out[nanmask] = med[nanmask]
    return out
