"""Biharmonic inpainting (skimage-free).

Replaces the reference's ``skimage.restoration.inpaint_biharmonic``
dependency (dynspec.py:3301-3307) with a direct sparse solve of the
biharmonic equation ∇⁴u = 0 over the masked region with the observed
pixels as boundary conditions — the same PDE skimage solves.
"""

from __future__ import annotations

import numpy as np
from scipy.sparse import lil_matrix
from scipy.sparse.linalg import spsolve

# 13-point biharmonic stencil (discrete ∇⁴)
_STENCIL = [
    ((0, 0), 20.0),
    ((-1, 0), -8.0), ((1, 0), -8.0), ((0, -1), -8.0), ((0, 1), -8.0),
    ((-1, -1), 2.0), ((-1, 1), 2.0), ((1, -1), 2.0), ((1, 1), 2.0),
    ((-2, 0), 1.0), ((2, 0), 1.0), ((0, -2), 1.0), ((0, 2), 1.0),
]


def inpaint_biharmonic(image, mask):
    """Fill ``mask`` pixels of ``image`` by solving ∇⁴u = 0.

    Stencil points falling outside the grid are dropped (free/natural
    boundary), matching skimage's behaviour closely.
    """
    image = np.asarray(image, dtype=float)
    mask = np.asarray(mask, dtype=bool)
    out = np.array(image)
    if not mask.any():
        return out
    ny, nx = image.shape
    unknown = np.flatnonzero(mask.ravel())
    index_of = -np.ones(ny * nx, dtype=int)
    index_of[unknown] = np.arange(len(unknown))

    A = lil_matrix((len(unknown), len(unknown)))
    b = np.zeros(len(unknown))
    filled = np.where(mask, 0.0, image)

    ys, xs = np.unravel_index(unknown, (ny, nx))
    for row, (y, x) in enumerate(zip(ys, xs)):
        for (dy, dx), w in _STENCIL:
            yy, xx = y + dy, x + dx
            if not (0 <= yy < ny and 0 <= xx < nx):
                continue
            flat = yy * nx + xx
            if mask[yy, xx]:
                A[row, index_of[flat]] += w
            else:
                b[row] -= w * filled[yy, xx]
    vals = spsolve(A.tocsr(), b)
    out[mask] = vals
    return out
