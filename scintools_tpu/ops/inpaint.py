"""Biharmonic inpainting (skimage-free).

Replaces the reference's ``skimage.restoration.inpaint_biharmonic``
dependency (dynspec.py:3301-3307) with a direct sparse solve of the
biharmonic equation ∇⁴u = 0 over the masked region with the observed
pixels as boundary conditions — the same PDE skimage solves.
"""

from __future__ import annotations

import numpy as np
from scipy.sparse import coo_matrix
from scipy.sparse.linalg import spsolve

# 13-point biharmonic stencil (discrete ∇⁴)
_STENCIL = [
    ((0, 0), 20.0),
    ((-1, 0), -8.0), ((1, 0), -8.0), ((0, -1), -8.0), ((0, 1), -8.0),
    ((-1, -1), 2.0), ((-1, 1), 2.0), ((1, -1), 2.0), ((1, 1), 2.0),
    ((-2, 0), 1.0), ((2, 0), 1.0), ((0, -2), 1.0), ((0, 2), 1.0),
]


def inpaint_biharmonic(image, mask):
    """Fill ``mask`` pixels of ``image`` by solving ∇⁴u = 0.

    Stencil points falling outside the grid are dropped (free/natural
    boundary), matching skimage's behaviour closely.
    """
    image = np.asarray(image, dtype=float)
    mask = np.asarray(mask, dtype=bool)
    out = np.array(image)
    if not mask.any():
        return out
    ny, nx = image.shape
    unknown = np.flatnonzero(mask.ravel())
    index_of = -np.ones(ny * nx, dtype=int)
    index_of[unknown] = np.arange(len(unknown))

    n = len(unknown)
    b = np.zeros(n)
    filled = np.where(mask, 0.0, image)
    flat_mask = mask.ravel()
    flat_img = filled.ravel()

    # one vectorised pass per stencil offset (13 passes total) instead
    # of a python loop over masked pixels
    ys, xs = np.unravel_index(unknown, (ny, nx))
    rows_acc, cols_acc, vals_acc = [], [], []
    row_idx = np.arange(n)
    for (dy, dx), w in _STENCIL:
        yy, xx = ys + dy, xs + dx
        ok = (yy >= 0) & (yy < ny) & (xx >= 0) & (xx < nx)
        flat = yy[ok] * nx + xx[ok]
        rows = row_idx[ok]
        isunk = flat_mask[flat]
        rows_acc.append(rows[isunk])
        cols_acc.append(index_of[flat[isunk]])
        vals_acc.append(np.full(int(isunk.sum()), w))
        np.subtract.at(b, rows[~isunk], w * flat_img[flat[~isunk]])
    A = coo_matrix((np.concatenate(vals_acc),
                    (np.concatenate(rows_acc), np.concatenate(cols_acc))),
                   shape=(n, n)).tocsr()
    out[mask] = spsolve(A, b)
    return out
