"""Tiered per-epoch fallback ladder for the survey path.

One epoch's θ-θ search can fail three distinct ways, each wanting a
different response:

1. **transient environment errors** — an XLA compile failure or OOM
   (``RuntimeError``) on one geometry. Response: bounded retries,
   then *batch-halving* (an OOM on a B-chunk stack often clears at
   B/2), then the next tier.
2. **tier-specific bugs/limits** — the fused program rejects a
   geometry the staged path handles. Response: drop a tier. The
   ladder is fused jax → staged jax (``fused=False`` parity oracle)
   → numpy reference path, i.e. each tier is strictly simpler and
   closer to the reference semantics than the one above it.
3. **corrupt data** — non-finite inputs, malformed files. No tier
   can fix those: the device guards (robust/guards.py) NaN the epoch
   and the runner quarantines it; the ladder does NOT descend (the
   numpy path would just burn minutes refusing identically).

Every transition emits one structured slog failure record with the
canonical fields (epoch id, stage, error class, tier, retry count —
utils/slog.py:log_failure), so a run summary is a grep. The
fault-injection hook (robust/faults.py:maybe_fail) is consulted
before every attempt, which is how the tests drive tiers to fail
deterministically.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from . import faults
from ..obs import metrics as _metrics
from ..utils import slog

TIER_FUSED = "jax_fused"
TIER_STAGED = "jax_staged"
TIER_NUMPY = "numpy"

# substrings marking a RuntimeError as a transient environment fault
# (XLA compile/OOM/tunnel) — worth retrying and batch-halving. JAX
# raises XlaRuntimeError (a RuntimeError subclass) with these codes.
_TRANSIENT_MARKERS = ("resource_exhausted", "out of memory", "oom",
                      "compile", "compilation", "deadline_exceeded",
                      "unavailable", "internal:", "injected fault")


class LadderError(RuntimeError):
    """Every tier of the fallback ladder failed for one epoch. Carries
    the per-attempt records so the caller can quarantine with a full
    explanation instead of a bare traceback. ``fatal`` marks an abort
    on a corrupt input (:func:`_is_fatal`) — no further tier may be
    tried for it (the pipelined runner checks this before descending
    the remaining tiers on a deferred tier-0 failure)."""

    def __init__(self, epoch, stage, attempts, fatal=False):
        self.epoch = epoch
        self.stage = stage
        self.fatal = bool(fatal)
        self.attempts = list(attempts)
        last = attempts[-1] if attempts else None
        super().__init__(
            f"all {len({a['tier'] for a in attempts})} tiers failed "
            f"for epoch {epoch!r} (stage {stage!r}); last: "
            f"{last['error_class'] if last else '?'}: "
            f"{last['error'] if last else '?'}")


def _is_fatal(exc):
    """Errors no tier can fix (corrupt/malformed input): the ladder
    aborts instead of burning the slower tiers on the same file."""
    from ..io import MalformedInputError

    return isinstance(exc, MalformedInputError)


def is_transient(exc):
    """True for RuntimeErrors that look like transient environment
    faults (compile/OOM/tunnel) — the class the ladder retries and
    batch-halves. Everything else (ValueError from bad geometry,
    MalformedInputError from a bad file, ...) fails the tier at
    once."""
    if not isinstance(exc, RuntimeError):
        return False
    msg = str(exc).lower()
    return any(m in msg for m in _TRANSIENT_MARKERS)


@dataclass
class LadderReport:
    """What it took to produce one epoch's result."""

    tier: str = ""            # tier that finally succeeded
    retries: int = 0          # total failed attempts across tiers
    halved: bool = False      # batch-halving was needed
    attempts: list = field(default_factory=list)  # failure records


def _record(report, epoch, stage, tier, exc, retry):
    rec = {"epoch": epoch, "stage": stage, "tier": tier,
           "error_class": type(exc).__name__,
           "error": str(exc)[:300], "retry": retry}
    report.attempts.append(rec)
    report.retries += 1
    _metrics.counter(
        "survey_fallback_transitions_total",
        help="failed ladder attempts (per tier that failed)",
    ).labels(tier=str(tier)).inc()  # lint-ok: metric-hygiene: bounded=tier
    slog.log_failure("robust.fallback", epoch=epoch, stage=stage,
                     error=exc, tier=tier, retry=retry)


def run_ladder(tiers, epoch=None, stage="search", retries=1,
               report=None):
    """Run ``tiers`` — an ordered list of ``(name, callable)`` — until
    one succeeds. Transient failures (:func:`is_transient`) are
    retried up to ``retries`` extra times on the SAME tier before
    descending; non-transient failures descend immediately. Returns
    ``(value, LadderReport)``; raises :class:`LadderError` when every
    tier is exhausted."""
    report = report or LadderReport()
    for name, fn in tiers:
        attempt = 0
        while True:
            try:
                faults.maybe_fail(name, epoch=epoch, stage=stage)
                value = fn()
            except Exception as exc:  # noqa: BLE001 — ladder boundary
                _record(report, epoch, stage, name, exc, attempt)
                if _is_fatal(exc):
                    raise LadderError(epoch, stage, report.attempts,
                                      fatal=True)
                if is_transient(exc) and attempt < int(retries):
                    attempt += 1
                    continue
                break  # next tier
            report.tier = name
            return value, report
    raise LadderError(epoch, stage, report.attempts)


def _halved(fn_batch, dspecs, times, depth=3):
    """Run ``fn_batch(dspecs, times)`` with recursive batch-halving on
    transient errors: an OOM on B chunks often clears at B/2 (half
    the θ-θ batch resident per program). Depth-bounded; re-raises
    when halving bottoms out at single chunks."""
    try:
        return fn_batch(dspecs, times)
    except Exception as exc:  # noqa: BLE001 — halving boundary
        if not is_transient(exc) or depth <= 0 or len(dspecs) <= 1:
            raise
        mid = len(dspecs) // 2
        left = _halved(fn_batch, dspecs[:mid], times[:mid],
                       depth=depth - 1)
        right = _halved(fn_batch, dspecs[mid:], times[mid:],
                        depth=depth - 1)
        return list(left) + list(right)


def thth_search_ladder(dspecs, freq, times, etas, edges, fw=0.1,
                       npad=3, coher=True, tau_mask=0.0, epoch=None,
                       retries=1, halve=True, tiers=None):
    """The θ-θ chunk-batch search behind the full fallback ladder:
    fused jax program → staged jax (``fused=False`` oracle) → numpy
    reference path, with bounded retries and batch-halving on
    transient compile/OOM RuntimeErrors. Same signature semantics as
    ``thth.search.multi_chunk_search``; returns
    ``(results, LadderReport)`` where ``results`` is the usual list of
    ``ChunkSearchResult``. ``tiers`` restricts the ladder (default:
    all three, in order)."""
    from ..thth.search import multi_chunk_search

    kw = dict(fw=fw, npad=npad, coher=coher, tau_mask=tau_mask)

    def batch_fn(fused, backend):
        def run(ds, ts):
            return multi_chunk_search(list(ds), freq, list(ts), etas,
                                      edges, backend=backend,
                                      fused=fused, **kw)

        return run

    def tier_call(fused, backend):
        fn = batch_fn(fused, backend)
        if halve:
            return lambda: _halved(fn, list(dspecs), list(times))
        return lambda: fn(list(dspecs), list(times))

    all_tiers = [
        (TIER_FUSED, tier_call(True, "jax")),
        (TIER_STAGED, tier_call(False, "jax")),
        (TIER_NUMPY, tier_call(True, "numpy")),
    ]
    if tiers is not None:
        want = list(tiers)
        all_tiers = [t for t in all_tiers if t[0] in want]
    return run_ladder(all_tiers, epoch=epoch, stage="thth_search",
                      retries=retries)
