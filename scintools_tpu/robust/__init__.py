"""Fault tolerance for survey-scale runs.

A production survey (thousands of dynamic-spectrum epochs sharded over
a mesh) must survive the failure modes the reference's pool fan-out
cannot: one non-finite epoch poisoning a batch, a malformed input file
killing the run, a compile/OOM error on one geometry, or the whole
process dying mid-survey. This package is that layer (survey-scale GPU
pulsar searches hold up in production only because per-candidate
failures are isolated and runs are restartable — Dimoudi et al. 2017,
arXiv:1711.10855; Adámek & Armour 2018, arXiv:1804.05335):

- :mod:`.guards` — device-side health flags: every chunk of a fused
  θ-θ program gets an ``ok`` bitmask (non-finite input, non-finite CS
  power, degenerate eigen curve, refused peak fit) so bad epochs are
  quarantined in-batch instead of silently fitting garbage;
- :mod:`.ladder` — tiered per-epoch fallback (fused jax → staged jax
  oracle → numpy reference) with bounded retries and batch-halving on
  transient compile/OOM errors, every transition a structured slog
  record;
- :mod:`.faults` — the deterministic fault-injection harness the
  robustness tests drive (NaN pixels, −inf dB epochs, truncated chunk
  stacks, simulated per-tier failures via a monkeypatchable hook);
- :mod:`.runner` — the journaled survey runner: per-epoch quarantine,
  ladder dispatch, and resume from the completion journal
  (parallel/checkpoint.py:EpochJournal) so a SIGKILL mid-run loses at
  most the in-flight epoch.

See docs/robustness.md for the failure model and resume workflow.
"""

from .guards import (OK, BAD_INPUT, BAD_CS, BAD_CURVE, BAD_PEAKFIT,
                     BAD_FIT, describe_health, chunk_finite_ok,
                     sanitize_chunks, curve_health, health_code)
from .ladder import (TIER_FUSED, TIER_STAGED, TIER_NUMPY, LadderError,
                     is_transient, run_ladder, thth_search_ladder)
from .faults import (inject_nan_pixels, inject_neginf_db,
                     truncate_chunk_stack, corrupt_file_tail,
                     tier_failure_hook, maybe_fail)
from .runner import EpochOutcome, run_survey, run_survey_batched
from ..parallel.checkpoint import EpochJournal

__all__ = [
    "OK", "BAD_INPUT", "BAD_CS", "BAD_CURVE", "BAD_PEAKFIT",
    "BAD_FIT", "describe_health", "chunk_finite_ok",
    "sanitize_chunks", "curve_health", "health_code",
    "TIER_FUSED", "TIER_STAGED", "TIER_NUMPY", "LadderError",
    "is_transient", "run_ladder", "thth_search_ladder",
    "inject_nan_pixels", "inject_neginf_db", "truncate_chunk_stack",
    "corrupt_file_tail", "tier_failure_hook", "maybe_fail",
    "EpochOutcome", "run_survey", "run_survey_batched",
    "EpochJournal",
]
