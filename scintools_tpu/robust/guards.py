"""Device-side per-chunk health flags for the fused θ-θ programs.

The fused search (thth/batch.py) runs a whole chunk batch as one
vmapped device program. The lanes are mathematically independent, but
before this module a corrupt epoch failed *silently*: a NaN chunk was
zeroed by the gather's ``nan_to_num``, a −inf dB epoch turned into a
finite-but-meaningless eigen curve, and a singular peak-fit system
produced NaN with no machine-readable cause. Every fused program now
also returns an ``ok[B]`` int32 bitmask per chunk (0 = healthy), built
from traced-safe reductions that add two cheap per-lane ``all``\\ s and
change nothing for healthy lanes:

====================  =====  ==============================================
flag                  bit    meaning
====================  =====  ==============================================
``BAD_INPUT``         1      raw chunk had non-finite pixels (NaN / ±inf)
``BAD_CS``            2      conjugate-spectrum power went non-finite
``BAD_CURVE``         4      eigen curve degenerate (<3 finite, or flat)
``BAD_PEAKFIT``       8      peak fit refused (singular 3×3 normal
                             equations, <3 window points, vertex gate)
====================  =====  ==============================================

Quarantine semantics: lanes with input-level corruption (``BAD_INPUT``
or ``BAD_CS``) get their fitted ``(eta, eta_sig, popt)`` forced to NaN
inside the program — a finite-looking fit of a corrupt epoch must
never reach the global η(f) fit. ``BAD_CURVE``/``BAD_PEAKFIT`` are
*diagnostic*: the peak fit's own refusal gates already NaN those
outputs exactly where the host path would (tests/test_fused_search.py
pins that parity), so the bits only say *why*. Non-finite input pixels
are zeroed (:func:`sanitize_chunks`) before the FFT so a single NaN
cannot grow into an all-NaN CS whose downstream cost is paid by every
consumer of the batch.

Host-side counterparts of the same bits are computed by the staged and
numpy search paths (thth/search.py) so a
:class:`~scintools_tpu.thth.search.ChunkSearchResult` carries the same
``ok`` code on every tier of the fallback ladder (robust/ladder.py).
"""

from __future__ import annotations

import numpy as np

OK = 0
BAD_INPUT = 1
BAD_CS = 2
BAD_CURVE = 4
BAD_PEAKFIT = 8
# the batched acf2d LM (fit/acf2d.py) reuses bit 8 for its own
# fit-refusal condition — a singular / non-finite damped
# normal-equation solve — which is the same failure class the θ-θ
# peak fit's 3×3 normal equations flag; BAD_FIT is the
# domain-neutral name
BAD_FIT = BAD_PEAKFIT

_NAMES = {BAD_INPUT: "input_nonfinite", BAD_CS: "cs_nonfinite",
          BAD_CURVE: "curve_degenerate", BAD_PEAKFIT: "peakfit_refused"}


def describe_health(code):
    """Human/slog-readable decode of an ``ok`` bitmask: ``0 → ['ok']``,
    ``5 → ['input_nonfinite', 'curve_degenerate']``."""
    code = int(code)
    if code == OK:
        return ["ok"]
    return [name for bit, name in sorted(_NAMES.items())
            if code & bit]


def chunk_finite_ok(arrs, xp=np):
    """Per-chunk all-finite reduction: ``arrs[B, ...] → ok[B]`` bool.
    Traced-safe (pass ``xp=jnp`` inside a program)."""
    a = xp.asarray(arrs)
    return xp.all(xp.isfinite(a), axis=tuple(range(1, a.ndim)))


def sanitize_chunks(arrs, xp=np):
    """Zero non-finite pixels so one corrupt lane cannot blow up the
    batched FFT (NaN·0 = NaN spreads through every fft2 output of its
    own lane; ±inf additionally overflows the f32 accumulator). The
    lane is already condemned by its ``BAD_INPUT`` bit — the zeros
    just make its downstream cost bounded and deterministic."""
    a = xp.asarray(arrs)
    return xp.where(xp.isfinite(a), a, xp.zeros((), dtype=a.dtype))


def curve_health(eigs, xp=np):
    """Per-chunk eigen-curve health: ``eigs[B, neta] → ok[B]`` bool.
    A curve is degenerate when fewer than 3 finite points survive (the
    peak fit's own minimum) or when it is flat (max == min over finite
    points — an all-zero θ-θ batch from a blanked chunk), which would
    make the 3×3 normal equations singular."""
    e = xp.asarray(eigs)
    finite = xp.isfinite(e)
    n_fin = xp.sum(finite, axis=1)
    big = xp.asarray(np.inf, e.dtype)
    hi = xp.max(xp.where(finite, e, -big), axis=1)
    lo = xp.min(xp.where(finite, e, big), axis=1)
    return (n_fin >= 3) & (hi > lo)


def health_code(input_ok=None, cs_ok=None, curve_ok=None, fit_ok=None,
                xp=np):
    """Combine per-chunk boolean health flags into the int32 bitmask
    (``None`` stages contribute nothing). All arguments are ``[B]``
    bool arrays (traced-safe)."""
    code = None
    for ok, bit in ((input_ok, BAD_INPUT), (cs_ok, BAD_CS),
                    (curve_ok, BAD_CURVE), (fit_ok, BAD_PEAKFIT)):
        if ok is None:
            continue
        term = xp.where(xp.asarray(ok), 0, bit).astype("int32")
        code = term if code is None else code | term
    if code is None:
        raise ValueError("health_code needs at least one stage flag")
    return code
