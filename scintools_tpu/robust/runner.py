"""Fault-tolerant, journaled survey runner.

The production shape of a survey is "for each of ~10³ epochs: load →
search → fit → append results". The naive loop dies with the first
malformed file, poisons its batch with the first non-finite epoch, and
loses everything to a preemption. This runner wraps the loop with the
three robustness layers of this package:

- **per-epoch quarantine** — an epoch whose loader raises
  :class:`~scintools_tpu.io.MalformedInputError`, whose every
  fallback tier fails, or whose result a validator rejects is recorded
  as quarantined (structured slog record + journal line) and the
  survey moves on. Healthy epochs are never touched by a bad
  neighbour: each epoch is processed independently and journaled
  results are bitwise what ``process`` returned.
- **tiered fallback** — ``process(payload, tier=...)`` is dispatched
  through the ladder (robust/ladder.py): fused jax → staged jax →
  numpy, bounded retries on transient compile/OOM errors, every
  transition one slog failure record.
- **journaled resume** — every completed epoch is one fsynced
  CRC-stamped JSONL line (parallel/checkpoint.py:EpochJournal). A
  rerun after SIGKILL takes journaled records verbatim and processes
  only unfinished epochs, so the resumed run's results are identical
  to an uninterrupted run (tests/test_robust.py pins this, including
  a real SIGKILL).

Use :class:`~scintools_tpu.parallel.checkpoint.SurveyCheckpointer`
alongside when the loop also carries large array state; the journal
covers the per-epoch scalar results and progress cursor.
"""

from __future__ import annotations

import os
from dataclasses import asdict, dataclass, field

from . import ladder as _ladder
from ..parallel.checkpoint import EpochJournal
from ..utils import slog

_DEFAULT_TIERS = (_ladder.TIER_FUSED, _ladder.TIER_STAGED,
                  _ladder.TIER_NUMPY)


@dataclass
class EpochOutcome:
    """One epoch's fate: ``status`` is 'ok', 'quarantined', or
    'resumed' (taken verbatim from the journal)."""

    epoch: object
    status: str
    tier: str = ""
    retries: int = 0
    error: str = ""
    error_class: str = ""
    result: dict = field(default_factory=dict)


def _is_malformed(exc):
    from ..io import MalformedInputError

    return isinstance(exc, MalformedInputError)


def run_survey(epochs, process, workdir, tiers=_DEFAULT_TIERS,
               retries=1, validate=None, journal_name="journal.jsonl",
               resume=True):
    """Process ``epochs`` — an iterable of ``(epoch_id, payload)`` —
    fault-tolerantly, journaling each completion to
    ``workdir/journal_name``.

    ``process(payload, tier=<name>)`` produces one epoch's result as
    a dict of JSON-able scalars; it is attempted through the fallback
    ``tiers`` in order (bounded ``retries`` on transient
    compile/OOM RuntimeErrors per tier, robust/ladder.py semantics).
    A :class:`~scintools_tpu.io.MalformedInputError` quarantines the
    epoch immediately (no tier can fix a corrupt file); exhaustion of
    every tier quarantines it with the full attempt trail. A
    ``validate(result) -> bool`` hook (optional) rejects a tier's
    result — e.g. require the device health bitmask be clean — and
    sends the epoch down to the next tier.

    Returns ``{"results": {epoch_id: result_dict},
    "outcomes": [EpochOutcome...], "summary": {...}}`` where summary
    counts ok/quarantined/resumed epochs, per-tier completions, and
    total retries. With ``resume=True`` (default), epochs already in
    the journal are not reprocessed — their journaled results are
    returned verbatim."""
    os.makedirs(workdir, exist_ok=True)
    journal = EpochJournal(os.path.join(workdir, journal_name))
    done = journal.records() if resume else {}

    outcomes = []
    results = {}
    tally = {"n_epochs": 0, "n_ok": 0, "n_quarantined": 0,
             "n_resumed": 0, "retries": 0,
             "tier_counts": {t: 0 for t in tiers}}
    epochs = list(epochs)
    with slog.span("survey.robust_run", n_epochs=len(epochs),
                   workdir=os.fspath(workdir)):
        for epoch_id, payload in epochs:
            tally["n_epochs"] += 1
            key = str(epoch_id)
            if key in done:
                rec = done[key]
                out = EpochOutcome(
                    epoch=epoch_id, status="resumed",
                    tier=rec.get("tier", ""),
                    result=rec.get("result") or {})
                if rec.get("status") == "quarantined":
                    tally["n_quarantined"] += 1
                    out.error = rec.get("error", "")
                    out.error_class = rec.get("error_class", "")
                else:
                    results[key] = out.result
                tally["n_resumed"] += 1
                outcomes.append(out)
                continue
            out = _run_one(epoch_id, payload, process, tiers, retries,
                           validate)
            tally["retries"] += out.retries
            if out.status == "ok":
                tally["n_ok"] += 1
                tally["tier_counts"][out.tier] = \
                    tally["tier_counts"].get(out.tier, 0) + 1
                results[key] = out.result
                journal.append(key, status="ok", tier=out.tier,
                               retries=out.retries, result=out.result)
            else:
                tally["n_quarantined"] += 1
                journal.append(key, status="quarantined",
                               tier=out.tier, retries=out.retries,
                               error=out.error,
                               error_class=out.error_class)
            outcomes.append(out)
        slog.log_event("survey.robust_summary", **{
            k: v for k, v in tally.items() if k != "tier_counts"},
            tier_counts=dict(tally["tier_counts"]))
    return {"results": results, "outcomes": outcomes,
            "summary": tally}


def run_survey_batched(epochs, process_batch, workdir, process=None,
                       batch_size=32, tiers=_DEFAULT_TIERS, retries=1,
                       validate=None, journal_name="journal.jsonl",
                       resume=True):
    """Batched counterpart of :func:`run_survey` for device programs
    that fit a whole epoch stack at once (e.g.
    ``fit/acf2d.py:fit_acf2d_batch`` — one compile, one H2D, one
    program for N epochs).

    Pending (non-journaled) epochs are grouped into stacks of
    ``batch_size`` and dispatched as ``process_batch(payloads,
    tier=<tiers[0]>) -> list of per-epoch result dicts`` (one dict per
    payload, in order). The batch attempt runs through the ladder's
    bounded transient retries; if the whole batch fails, every lane
    falls back to the per-epoch path. Per-lane screening uses the
    device health flags: a lane is accepted when ``validate(result)``
    is true (default: its ``"ok"`` bitmask — the fused-program /
    batched-LM health code — is 0/absent). Rejected lanes are retried
    INDIVIDUALLY through the remaining tiers via ``process(payload,
    tier=...)`` (:func:`run_survey` semantics) when ``process`` is
    given, else quarantined — so one poisoned epoch never takes its
    batch down, and a healthy batch costs one device program instead
    of N.

    Journal format, resume semantics, and the return structure are
    shared with :func:`run_survey` (same ``workdir`` journal resumes
    either entry); the summary additionally counts ``n_batches``.
    """
    os.makedirs(workdir, exist_ok=True)
    journal = EpochJournal(os.path.join(workdir, journal_name))
    done = journal.records() if resume else {}

    if validate is None:
        def validate(result):                 # noqa: ANN001
            return int(result.get("ok", 0) or 0) == 0

    outcomes = {}
    results = {}
    tally = {"n_epochs": 0, "n_ok": 0, "n_quarantined": 0,
             "n_resumed": 0, "retries": 0, "n_batches": 0,
             "tier_counts": {t: 0 for t in tiers}}

    def _record(epoch_id, out):
        key = str(epoch_id)
        outcomes[key] = out
        tally["retries"] += out.retries
        if out.status == "ok":
            tally["n_ok"] += 1
            tally["tier_counts"][out.tier] = \
                tally["tier_counts"].get(out.tier, 0) + 1
            results[key] = out.result
            journal.append(key, status="ok", tier=out.tier,
                           retries=out.retries, result=out.result)
        else:
            tally["n_quarantined"] += 1
            journal.append(key, status="quarantined", tier=out.tier,
                           retries=out.retries, error=out.error,
                           error_class=out.error_class)

    epochs = list(epochs)
    pending = []
    with slog.span("survey.robust_run_batched", n_epochs=len(epochs),
                   batch_size=batch_size,
                   workdir=os.fspath(workdir)):
        for epoch_id, payload in epochs:
            tally["n_epochs"] += 1
            key = str(epoch_id)
            if key in done:
                rec = done[key]
                out = EpochOutcome(
                    epoch=epoch_id, status="resumed",
                    tier=rec.get("tier", ""),
                    result=rec.get("result") or {})
                if rec.get("status") == "quarantined":
                    tally["n_quarantined"] += 1
                    out.error = rec.get("error", "")
                    out.error_class = rec.get("error_class", "")
                else:
                    results[key] = out.result
                tally["n_resumed"] += 1
                outcomes[key] = out
                continue
            pending.append((epoch_id, payload))

        rest_tiers = tuple(tiers[1:])
        for i in range(0, len(pending), batch_size):
            group = pending[i:i + batch_size]
            tally["n_batches"] += 1
            try:
                value, report = _ladder.run_ladder(
                    [(tiers[0], lambda: process_batch(
                        [p for _, p in group], tier=tiers[0]))],
                    epoch=f"batch[{i}:{i + len(group)}]",
                    stage="process_batch", retries=retries)
                batch_results = list(value)
                if len(batch_results) != len(group):
                    raise ValueError(
                        f"process_batch returned {len(batch_results)} "
                        f"results for {len(group)} epochs")
            except (_ladder.LadderError, ValueError) as exc:
                slog.log_failure("robust.batch_fallback",
                                 epoch=f"batch[{i}]",
                                 stage="process_batch", error=exc,
                                 tier=tiers[0], retry=0)
                # whole-batch failure: every lane takes the per-epoch
                # ladder (quarantine isolation unchanged)
                for epoch_id, payload in group:
                    if process is None:
                        _record(epoch_id, EpochOutcome(
                            epoch=epoch_id, status="quarantined",
                            tier=tiers[0], error=str(exc),
                            error_class=type(exc).__name__))
                    else:
                        _record(epoch_id, _run_one(
                            epoch_id, payload, process, tiers,
                            retries, None))
                continue
            for (epoch_id, payload), result in zip(group,
                                                   batch_results):
                if validate(result):
                    _record(epoch_id, EpochOutcome(
                        epoch=epoch_id, status="ok", tier=tiers[0],
                        result=dict(result)))
                    continue
                slog.log_failure(
                    "robust.lane_reject", epoch=epoch_id,
                    stage="process_batch", tier=tiers[0],
                    error=ValueError(
                        f"lane health rejected (ok="
                        f"{result.get('ok', 'validator')!r})"),
                    retry=0)
                if process is None or not rest_tiers:
                    _record(epoch_id, EpochOutcome(
                        epoch=epoch_id, status="quarantined",
                        tier=tiers[0],
                        error="lane health rejected",
                        error_class="LaneRejected"))
                else:
                    _record(epoch_id, _run_one(
                        epoch_id, payload, process, rest_tiers,
                        retries, None))
        slog.log_event("survey.robust_batched_summary", **{
            k: v for k, v in tally.items() if k != "tier_counts"},
            tier_counts=dict(tally["tier_counts"]))
    ordered = [outcomes[str(e)] for e, _ in epochs]
    return {"results": results, "outcomes": ordered,
            "summary": tally}


def _run_one(epoch_id, payload, process, tiers, retries, validate):
    """Dispatch one epoch through the ladder; never raises."""

    def tier_fn(name):
        def run():
            result = process(payload, tier=name)
            if validate is not None and not validate(result):
                raise ValueError(
                    f"validator rejected tier {name} result for "
                    f"epoch {epoch_id!r}")
            return result

        return run

    try:
        value, report = _ladder.run_ladder(
            [(t, tier_fn(t)) for t in tiers], epoch=epoch_id,
            stage="process", retries=retries)
    except _ladder.LadderError as exc:
        slog.log_failure("robust.quarantine", epoch=epoch_id,
                         stage="process", error=exc,
                         tier=exc.attempts[-1]["tier"]
                         if exc.attempts else None,
                         retry=len(exc.attempts))
        last = exc.attempts[-1] if exc.attempts else {}
        # a malformed input shows up as the same error on every tier;
        # collapse the trail to the first record's class
        return EpochOutcome(
            epoch=epoch_id, status="quarantined",
            retries=len(exc.attempts),
            error=last.get("error", str(exc)),
            error_class=last.get("error_class", "LadderError"))
    return EpochOutcome(epoch=epoch_id, status="ok", tier=report.tier,
                        retries=report.retries, result=dict(value))


def outcome_dicts(outcomes):
    """JSON-able view of a list of :class:`EpochOutcome` (for result
    files / bench records)."""
    return [asdict(o) for o in outcomes]
