"""Fault-tolerant, journaled, PIPELINED survey runner.

The production shape of a survey is "for each of ~10³ epochs: load →
search → fit → append results". The naive loop dies with the first
malformed file, poisons its batch with the first non-finite epoch, and
loses everything to a preemption; and even the robust sequential loop
leaves the accelerator idle during every host load/parse and every
fsynced journal line. This runner wraps the loop with the three
robustness layers of this package AND the pipelined execution engine
(parallel/pipeline.py):

- **per-epoch quarantine** — an epoch whose loader raises
  :class:`~scintools_tpu.io.MalformedInputError` (or any loader
  exception — captured per epoch, never a pipeline crash), whose
  every fallback tier fails, or whose result a validator rejects is
  recorded as quarantined (structured slog record + journal line) and
  the survey moves on. Healthy epochs are never touched by a bad
  neighbour: each epoch is processed independently and journaled
  results are bitwise what ``process`` returned.
- **tiered fallback** — ``process(payload, tier=...)`` is dispatched
  through the ladder (robust/ladder.py): fused jax → staged jax →
  numpy, bounded retries on transient compile/OOM errors, every
  transition one slog failure record.
- **journaled resume** — every completed epoch is one fsynced
  CRC-stamped JSONL line (parallel/checkpoint.py:EpochJournal). A
  rerun after SIGKILL takes journaled records verbatim and processes
  only unfinished epochs, so the resumed run's results are identical
  to an uninterrupted run (tests/test_robust.py pins this, including
  a real SIGKILL).
- **pipelining** (default; ``pipeline=False`` keeps the strictly
  sequential oracle) — epoch loading/preprocessing runs in a bounded
  background prefetch queue, up to ``inflight`` dispatched epochs
  stay un-fenced so JAX async dispatch keeps the device busy (results
  are only fetched when consumed — ``process`` may return a
  :class:`~scintools_tpu.parallel.pipeline.DeferredResult` or a dict
  of still-in-flight device values), and journal CRC/fsync runs on a
  writer thread with group commit. Epoch order, quarantine semantics,
  journal bytes, and resume behaviour are IDENTICAL to the sequential
  oracle (tests/test_pipeline.py pins byte-identical journals on
  clean, fault-injected, and SIGKILL-resumed runs).

Use :class:`~scintools_tpu.parallel.checkpoint.SurveyCheckpointer`
alongside when the loop also carries large array state; the journal
covers the per-epoch scalar results and progress cursor. Pass a
:class:`~scintools_tpu.utils.profiling.StageTimeline` as ``timeline``
to account load/dispatch/fence/journal overlap per epoch.

The per-epoch engine here (``_Recorder`` bookkeeping,
``_dispatch_first`` dispatch-ahead, ``_consume_deferred`` fencing,
``_run_one`` ladder dispatch, ``_loader_outcome`` quarantine,
``_trace_id``) is shared with the STREAMING daemon
(serve/daemon.py): the batch entries below own the
"full epoch list up front" loop shape, the daemon drives the same
pieces incrementally off a spool watcher — so quarantine semantics,
journal line bytes, and resume behaviour are identical across the
batch and serving tiers.
"""

from __future__ import annotations

import collections
import os
import time
from dataclasses import asdict, dataclass, field

from . import ladder as _ladder
from ..obs import heartbeat as _hb
from ..obs import metrics as _metrics
from ..obs import report as _report
from ..parallel.checkpoint import EpochJournal
from ..utils import slog

_DEFAULT_TIERS = (_ladder.TIER_FUSED, _ladder.TIER_STAGED,
                  _ladder.TIER_NUMPY)


@dataclass
class EpochOutcome:
    """One epoch's fate: ``status`` is 'ok', 'quarantined', or
    'resumed' (taken verbatim from the journal)."""

    epoch: object
    status: str
    tier: str = ""
    retries: int = 0
    error: str = ""
    error_class: str = ""
    result: dict = field(default_factory=dict)


def _is_malformed(exc):
    from ..io import MalformedInputError

    return isinstance(exc, MalformedInputError)


def _loader_outcome(epoch_id, exc):
    """Quarantine outcome for an epoch whose LOADER failed (malformed
    file, truncated read, preprocessing crash). The exception class is
    preserved; non-:class:`MalformedInputError` loader failures are
    still per-epoch quarantines — a bad file must never crash the
    pipeline — but keep their own class for the post-mortem."""
    slog.log_failure("robust.quarantine", epoch=epoch_id, stage="load",
                     error=exc, tier=None, retry=0)
    return EpochOutcome(
        epoch=epoch_id, status="quarantined", tier="", retries=0,
        error=str(exc)[:300], error_class=type(exc).__name__)


def _load_inline(payload, load_fn):
    """The sequential oracle's load stage: same semantics as the
    background prefetch loader, on the calling thread."""
    if load_fn is not None:
        return load_fn(payload)
    if callable(payload):
        return payload()
    return payload


class _Recorder:
    """Shared bookkeeping for both runner entries: tallies, ordered
    outcomes, results, journal appends (direct or via the async
    writer), per-epoch metrics, and the heartbeat cadence."""

    def __init__(self, journal, writer, tiers, heartbeat=None,
                 journal_extra=None):
        self.journal = journal
        self.writer = writer
        self.heartbeat = heartbeat
        self.journal_extra = journal_extra
        self.outcomes = []
        self.results = {}
        self.tally = {"n_epochs": 0, "n_ok": 0, "n_quarantined": 0,
                      "n_resumed": 0, "retries": 0,
                      "tier_counts": {t: 0 for t in tiers}}

    def _append(self, key, **fields):
        # worker-attribution columns (fleet/): constant fields — or a
        # callable producing them per record (commit stamps) — ride at
        # the END of every journal line, so stripping them restores
        # the exact single-process line bytes (fleet/merge.py relies
        # on this ordering)
        extra = self.journal_extra() if callable(self.journal_extra) \
            else self.journal_extra
        if extra:
            fields.update(extra)
        if self.writer is not None:
            self.writer.append(key, **fields)
        else:
            self.journal.append(key, **fields)

    def beat(self, force=False):
        """One heartbeat tick (emits only when the cadence is due)."""
        if self.heartbeat is None:
            return
        t = self.tally
        self.heartbeat.beat(
            len(self.outcomes), force=force, ok=t["n_ok"],
            quarantined=t["n_quarantined"], resumed=t["n_resumed"],
            retries=t["retries"])

    def resumed(self, epoch_id, rec):
        out = EpochOutcome(epoch=epoch_id, status="resumed",
                           tier=rec.get("tier", ""),
                           result=rec.get("result") or {})
        if rec.get("status") == "quarantined":
            self.tally["n_quarantined"] += 1
            out.error = rec.get("error", "")
            out.error_class = rec.get("error_class", "")
        else:
            self.results[str(epoch_id)] = out.result
        self.tally["n_resumed"] += 1
        _metrics.counter("survey_epochs_resumed_total",
                         help="epochs taken verbatim from the journal"
                         ).inc()
        self.outcomes.append(out)
        self.beat()
        return out

    def record(self, out):
        """Tally + journal one fresh (non-resumed) outcome."""
        key = str(out.epoch)
        self.tally["retries"] += out.retries
        if out.status == "ok":
            self.tally["n_ok"] += 1
            self.tally["tier_counts"][out.tier] = \
                self.tally["tier_counts"].get(out.tier, 0) + 1
            self.results[key] = out.result
            self._append(key, status="ok", tier=out.tier,
                         retries=out.retries, result=out.result)
            _metrics.counter("survey_epochs_ok_total",
                             help="fresh successful epochs").inc()
        else:
            self.tally["n_quarantined"] += 1
            self._append(key, status="quarantined", tier=out.tier,
                         retries=out.retries, error=out.error,
                         error_class=out.error_class)
            _metrics.counter("survey_epochs_quarantined_total",
                             help="fresh quarantined epochs").inc()
        self.outcomes.append(out)
        self.beat()
        return out


def run_survey(epochs, process, workdir, tiers=_DEFAULT_TIERS,
               retries=1, validate=None, journal_name="journal.jsonl",
               resume=True, pipeline=True, prefetch=4, inflight=2,
               loader_workers=2, load_fn=None, defer_validate=False,
               timeline=None, heartbeat=None, report=True,
               journal_extra=None):
    """Process ``epochs`` — an iterable of ``(epoch_id, payload)`` —
    fault-tolerantly, journaling each completion to
    ``workdir/journal_name``.

    ``process(payload, tier=<name>)`` produces one epoch's result as
    a dict of JSON-able scalars; it is attempted through the fallback
    ``tiers`` in order (bounded ``retries`` on transient
    compile/OOM RuntimeErrors per tier, robust/ladder.py semantics).
    A :class:`~scintools_tpu.io.MalformedInputError` quarantines the
    epoch immediately (no tier can fix a corrupt file); exhaustion of
    every tier quarantines it with the full attempt trail. A
    ``validate(result) -> bool`` hook (optional) rejects a tier's
    result — e.g. require the device health bitmask be clean — and
    sends the epoch down to the next tier.

    **Pipelined by default** (``pipeline=True``): a payload that is
    CALLABLE is a lazy loader run in ``loader_workers`` background
    threads at most ``prefetch`` epochs ahead (``load_fn`` instead
    maps every payload in the background); up to ``inflight`` epochs
    stay dispatched-but-un-fenced so the device queue never drains —
    ``process`` may return a dict of in-flight device values or a
    :class:`~scintools_tpu.parallel.pipeline.DeferredResult`, fenced
    only at consumption; journal fsyncs run on a writer thread
    (group commit, drained before return). Epoch order, quarantine
    semantics, journal bytes, and resume behaviour match the
    ``pipeline=False`` sequential oracle exactly. A ``validate`` hook
    disables dispatch-ahead (results fence immediately, in order)
    unless ``defer_validate=True`` declares it stateless. ``timeline``
    (a :class:`~scintools_tpu.utils.profiling.StageTimeline`) records
    per-epoch load/dispatch/fence/journal spans.

    **Observability** (scintools_tpu/obs, docs/observability.md):
    per-epoch counters and journal/prefetch metrics accumulate in the
    process metrics registry; ``heartbeat`` (True, a cadence dict
    ``{"every_n":, "every_s":}``, or a prebuilt
    :class:`~scintools_tpu.obs.heartbeat.Heartbeat`) emits live
    ``survey.heartbeat`` progress events; with a ``timeline``, each
    epoch is assigned a deterministic trace ID and the spans export
    as Chrome-trace JSON via ``timeline.export_trace(path)``; and
    ``report=True`` (default) writes the schema-validated
    ``run_report.json`` + ``run_report.md`` artifact into
    ``workdir``.

    ``journal_extra`` (a dict, or a zero-arg callable returning one)
    appends constant attribution fields to the END of every journal
    line — the fleet tier (fleet/) stamps ``worker``/``t_commit``
    there so per-worker journals merge deterministically
    (fleet/merge.py strips them to recover the single-process line
    bytes).

    Returns ``{"results": {epoch_id: result_dict},
    "outcomes": [EpochOutcome...], "summary": {...}}`` where summary
    counts ok/quarantined/resumed epochs, per-tier completions, and
    total retries. With ``resume=True`` (default), epochs already in
    the journal are not reprocessed — their journaled results are
    returned verbatim."""
    os.makedirs(workdir, exist_ok=True)
    journal = EpochJournal(os.path.join(workdir, journal_name))
    done = journal.records() if resume else {}
    epochs = list(epochs)
    heartbeat = _hb.as_heartbeat(heartbeat, total=len(epochs))

    t_run0 = time.perf_counter()
    with slog.span("survey.robust_run", n_epochs=len(epochs),
                   workdir=os.fspath(workdir),
                   pipeline=bool(pipeline)):
        if pipeline:
            rec = _run_pipelined(
                epochs, process, journal, done, tiers, retries,
                validate, prefetch, inflight, loader_workers, load_fn,
                defer_validate, timeline, heartbeat, journal_extra)
        else:
            rec = _run_sequential(epochs, process, journal, done,
                                  tiers, retries, validate, load_fn,
                                  timeline, heartbeat, journal_extra)
        slog.log_event("survey.robust_summary", **{
            k: v for k, v in rec.tally.items() if k != "tier_counts"},
            tier_counts=dict(rec.tally["tier_counts"]))
    wall_s = time.perf_counter() - t_run0
    rec.beat(force=True)              # final fresh progress snapshot
    tl_summary = _finish_timeline(timeline)
    if report:
        _report.write_run_report(workdir, _report.build_run_report(
            rec.tally, rec.outcomes, wall_s=wall_s,
            timeline=tl_summary, runner="run_survey"))
    return {"results": rec.results, "outcomes": rec.outcomes,
            "summary": rec.tally}


def _finish_timeline(timeline):
    """Emit the timeline's slog summary and mirror its headline
    numbers into the metrics registry; returns the summary dict (None
    without a timeline)."""
    if timeline is None:
        return None
    s = timeline.log_summary()
    _metrics.gauge("survey_device_idle_seconds",
                   help="wall time no device-stage span covered"
                   ).set(s.get("device_idle_s", 0.0))
    _metrics.gauge("survey_overlap_frac",
                   help="pipeline stage-overlap fraction"
                   ).set(s.get("overlap_frac", 0.0))
    return s


def _trace_id(index, epoch_id):
    """Deterministic per-epoch trace ID: stable across reruns and
    across pipelined/sequential modes (resume byte-identity must not
    depend on when a run happened), unique within a run."""
    return f"{index:05d}/{epoch_id}"


def _run_sequential(epochs, process, journal, done, tiers, retries,
                    validate, load_fn, timeline, heartbeat=None,
                    journal_extra=None):
    """The strictly sequential oracle: load, process, fsync — one
    epoch at a time on the calling thread (the pre-pipeline PR-2
    loop; kept as the parity/throughput baseline)."""
    rec = _Recorder(journal, None, tiers, heartbeat=heartbeat,
                    journal_extra=journal_extra)
    for epoch_id, payload in epochs:
        rec.tally["n_epochs"] += 1
        if timeline is not None:
            timeline.assign_trace(
                epoch_id, _trace_id(rec.tally["n_epochs"] - 1,
                                    epoch_id))
        key = str(epoch_id)
        if key in done:
            rec.resumed(epoch_id, done[key])
            continue
        try:
            if timeline is not None:
                with timeline.span(epoch_id, "load"):
                    payload = _load_inline(payload, load_fn)
            else:
                payload = _load_inline(payload, load_fn)
        except Exception as e:  # noqa: BLE001 — per-epoch quarantine
            rec.record(_loader_outcome(epoch_id, e))
            continue
        rec.record(_run_one(epoch_id, payload, process, tiers,
                            retries, validate))
    return rec


def _run_pipelined(epochs, process, journal, done, tiers, retries,
                   validate, prefetch, inflight, loader_workers,
                   load_fn, defer_validate, timeline, heartbeat=None,
                   journal_extra=None):
    """The pipelined engine: bounded prefetch loader feeding a
    dispatch-ahead window of un-fenced epochs, results consumed (and
    journaled via the threaded writer) in strict epoch order.

    A ``validate`` hook forces immediate fencing (the window is
    consumed right after each dispatch) unless ``defer_validate``:
    validators may be stateful — closed over the last-dispatched
    tier, a call counter — and deferring them would change what they
    observe relative to the sequential oracle. ``defer_validate=True``
    opts a STATELESS validator (e.g. the device health-bitmask check)
    back into the full dispatch-ahead window."""
    from ..parallel.pipeline import AsyncJournalWriter, PrefetchLoader

    inflight = max(1, int(inflight))
    if validate is not None and not defer_validate:
        inflight = 0
    writer = AsyncJournalWriter(journal, timeline=timeline)
    rec = _Recorder(journal, writer, tiers, heartbeat=heartbeat,
                    journal_extra=journal_extra)
    window = collections.deque()   # (epoch_id, payload, value, report)

    def consume_one():
        epoch_id, payload, value, report = window.popleft()
        if isinstance(value, EpochOutcome):   # already decided
            rec.record(value)
            return
        if timeline is not None:
            with timeline.span(epoch_id, "fence"):
                out = _consume_deferred(epoch_id, payload, value,
                                        report, process, tiers,
                                        retries, validate)
        else:
            out = _consume_deferred(epoch_id, payload, value, report,
                                    process, tiers, retries, validate)
        rec.record(out)

    loader = PrefetchLoader(
        ((eid, p) for eid, p in epochs if str(eid) not in done),
        depth=prefetch, workers=loader_workers, load_fn=load_fn,
        timeline=timeline)
    try:
        with loader:
            loaded = iter(loader)
            for epoch_id, payload in epochs:
                rec.tally["n_epochs"] += 1
                if timeline is not None:
                    timeline.assign_trace(
                        epoch_id, _trace_id(rec.tally["n_epochs"] - 1,
                                            epoch_id))
                key = str(epoch_id)
                if key in done:
                    # strict order: everything dispatched before this
                    # resumed epoch is consumed first, so outcome and
                    # journal order match the sequential oracle
                    while window:
                        consume_one()
                    rec.resumed(epoch_id, done[key])
                    continue
                eid, item = next(loaded)
                assert str(eid) == key, (eid, epoch_id)
                if not item.ok:
                    window.append((epoch_id, None,
                                   _loader_outcome(epoch_id,
                                                   item.error), None))
                else:
                    if timeline is not None:
                        with timeline.span(epoch_id, "dispatch"):
                            entry = _dispatch_first(
                                epoch_id, item.payload, process,
                                tiers, retries, validate)
                    else:
                        entry = _dispatch_first(
                            epoch_id, item.payload, process, tiers,
                            retries, validate)
                    window.append(entry)
                while len(window) > inflight:
                    consume_one()
            while window:
                consume_one()
    finally:
        # durability barrier: every journal line fsynced before the
        # summary is trusted (PR-2 resume guarantee)
        writer.close()
    return rec


def _dispatch_first(epoch_id, payload, process, tiers, retries,
                    validate):
    """Dispatch the FIRST tier without fencing: on success the raw
    (possibly still in-flight) value enters the window; validation
    and host conversion wait for consumption. Tier-0 exhaustion falls
    through the remaining tiers synchronously with the attempt trail
    carried over (ladder semantics identical to the sequential
    path)."""
    report = _ladder.LadderReport()
    try:
        value, report = _ladder.run_ladder(
            [(tiers[0], lambda: process(payload, tier=tiers[0]))],
            epoch=epoch_id, stage="process", retries=retries,
            report=report)
        return (epoch_id, payload, value, report)
    except _ladder.LadderError as exc:
        if exc.fatal or len(tiers) == 1:
            return (epoch_id, None,
                    _quarantined_outcome(epoch_id, exc), None)
        out = _run_one(epoch_id, payload, process, tiers[1:], retries,
                       validate, report=report)
        return (epoch_id, None, out, None)


def _consume_deferred(epoch_id, payload, value, report, process,
                      tiers, retries, validate):
    """Fence + validate a deferred tier-0 result; a validator
    rejection descends the remaining tiers exactly as the sequential
    ladder would (same attempt records, same retry counts)."""
    from ..parallel.pipeline import finalize_result

    try:
        result = finalize_result(value)
        if validate is not None and not validate(result):
            raise ValueError(
                f"validator rejected tier {tiers[0]} result for "
                f"epoch {epoch_id!r}")
    except Exception as exc:  # noqa: BLE001 — a fence/validate
        # failure is one failed attempt on tier 0 (with its usual
        # slog robust.fallback record, emitted by _record); the
        # remaining tiers run synchronously with the trail carried
        _ladder._record(report, epoch_id, "process", tiers[0], exc, 0)
        if len(tiers) == 1:
            return _quarantined_outcome(epoch_id, _ladder.LadderError(
                epoch_id, "process", report.attempts))
        return _run_one(epoch_id, payload, process, tiers[1:],
                        retries, validate, report=report)
    return EpochOutcome(epoch=epoch_id, status="ok", tier=report.tier,
                        retries=report.retries, result=dict(result))


def default_lane_validate(result):
    """The batched entries' default per-lane screen: a lane is
    healthy when its device health bitmask (``"ok"`` — the
    fused-program / batched-LM guards code) is 0 or absent."""
    return int(result.get("ok", 0) or 0) == 0


def run_group(group, process_batch, process, tiers, retries,
              validate, record, epoch_label, span_key=None,
              timeline=None):
    """Dispatch ONE group of ``(epoch_id, loaded_payload)`` pairs as
    a single batched device program — the per-group engine shared by
    :func:`run_survey_batched` (full epoch list up front) and the
    streaming daemon's lane assembler (serve/daemon.py: arrivals
    grouped into lanes by backlog pressure). Semantics are the batch
    entry's, verbatim:

    - the batch attempt runs ``process_batch(payloads, tier=tiers[0])``
      through the ladder's bounded transient retries; a whole-batch
      failure sends every lane down the per-epoch ladder (``process``;
      quarantined outright when ``process`` is None);
    - per-lane screening: a lane whose ``validate(result)`` is false
      (guards health bitmask, by default) is retried INDIVIDUALLY
      through the remaining tiers — one poisoned epoch never takes
      its batch down;
    - ``record(epoch_id, EpochOutcome)`` is called exactly once per
      lane, in group order for the healthy path.

    ``epoch_label`` names the group in ladder/slog records (e.g.
    ``batch[0:32]``); ``span_key`` + ``timeline`` wrap the batch
    attempt in a ``compute`` stage span."""
    rest_tiers = tuple(tiers[1:])
    try:
        if timeline is not None and span_key is not None:
            with timeline.span(span_key, "compute"):
                value, report = _ladder.run_ladder(
                    [(tiers[0], lambda: process_batch(
                        [p for _, p in group], tier=tiers[0]))],
                    epoch=epoch_label, stage="process_batch",
                    retries=retries)
        else:
            value, report = _ladder.run_ladder(
                [(tiers[0], lambda: process_batch(
                    [p for _, p in group], tier=tiers[0]))],
                epoch=epoch_label, stage="process_batch",
                retries=retries)
        batch_results = list(value)
        if len(batch_results) != len(group):
            raise ValueError(
                f"process_batch returned {len(batch_results)} "
                f"results for {len(group)} epochs")
    except (_ladder.LadderError, ValueError) as exc:
        slog.log_failure("robust.batch_fallback", epoch=epoch_label,
                         stage="process_batch", error=exc,
                         tier=tiers[0], retry=0)
        # whole-batch failure: every lane takes the per-epoch ladder
        # (quarantine isolation unchanged)
        for epoch_id, payload in group:
            if process is None:
                record(epoch_id, EpochOutcome(
                    epoch=epoch_id, status="quarantined",
                    tier=tiers[0], error=str(exc),
                    error_class=type(exc).__name__))
            else:
                record(epoch_id, _run_one(epoch_id, payload, process,
                                          tiers, retries, None))
        return
    for (epoch_id, payload), result in zip(group, batch_results):
        if validate(result):
            record(epoch_id, EpochOutcome(
                epoch=epoch_id, status="ok", tier=tiers[0],
                result=dict(result)))
            continue
        slog.log_failure(
            "robust.lane_reject", epoch=epoch_id,
            stage="process_batch", tier=tiers[0],
            error=ValueError(
                f"lane health rejected (ok="
                f"{result.get('ok', 'validator')!r})"),
            retry=0)
        if process is None or not rest_tiers:
            record(epoch_id, EpochOutcome(
                epoch=epoch_id, status="quarantined", tier=tiers[0],
                error="lane health rejected",
                error_class="LaneRejected"))
        else:
            record(epoch_id, _run_one(epoch_id, payload, process,
                                      rest_tiers, retries, None))


def run_survey_batched(epochs, process_batch, workdir, process=None,
                       batch_size=32, tiers=_DEFAULT_TIERS, retries=1,
                       validate=None, journal_name="journal.jsonl",
                       resume=True, pipeline=True, prefetch=4,
                       loader_workers=2, load_fn=None, timeline=None,
                       heartbeat=None, report=True,
                       journal_extra=None):
    """Batched counterpart of :func:`run_survey` for device programs
    that fit a whole epoch stack at once (e.g.
    ``fit/acf2d.py:fit_acf2d_batch`` — one compile, one H2D, one
    program for N epochs).

    Pending (non-journaled) epochs are grouped into stacks of
    ``batch_size`` and dispatched as ``process_batch(payloads,
    tier=<tiers[0]>) -> list of per-epoch result dicts`` (one dict per
    payload, in order). The batch attempt runs through the ladder's
    bounded transient retries; if the whole batch fails, every lane
    falls back to the per-epoch path. Per-lane screening uses the
    device health flags: a lane is accepted when ``validate(result)``
    is true (default: its ``"ok"`` bitmask — the fused-program /
    batched-LM health code — is 0/absent). Rejected lanes are retried
    INDIVIDUALLY through the remaining tiers via ``process(payload,
    tier=...)`` (:func:`run_survey` semantics) when ``process`` is
    given, else quarantined — so one poisoned epoch never takes its
    batch down, and a healthy batch costs one device program instead
    of N.

    With ``pipeline=True`` (default) callable payloads load in a
    bounded background prefetch queue (``prefetch`` deep,
    ``loader_workers`` threads; loader failures quarantine that epoch
    only) and journal fsyncs run on the threaded writer, which DRAINS
    at every batch boundary — the PR-2 SIGKILL-resume guarantee is
    unchanged. ``pipeline=False`` is the sequential oracle.

    Journal format, resume semantics, observability wiring
    (``heartbeat``/``report``/metrics — see :func:`run_survey`), the
    ``journal_extra`` attribution hook (the fleet tier's
    worker/commit columns, see :func:`run_survey`), and the return
    structure are shared with :func:`run_survey` (same ``workdir``
    journal resumes either entry); the summary additionally counts
    ``n_batches``.
    """
    from ..parallel.pipeline import AsyncJournalWriter, PrefetchLoader

    os.makedirs(workdir, exist_ok=True)
    journal = EpochJournal(os.path.join(workdir, journal_name))
    done = journal.records() if resume else {}

    if validate is None:
        validate = default_lane_validate

    writer = AsyncJournalWriter(journal, timeline=timeline) \
        if pipeline else None
    rec = _Recorder(journal, writer, tiers, heartbeat=None,
                    journal_extra=journal_extra)
    rec.tally["n_batches"] = 0
    outcomes_by_key = {}

    def _record(epoch_id, out):
        # the ordered outcome view is rebuilt from this map at return
        # (lane rejects complete out of epoch order)
        outcomes_by_key[str(epoch_id)] = out
        rec.record(out)

    epochs = list(epochs)
    rec.heartbeat = _hb.as_heartbeat(heartbeat, total=len(epochs))
    pending = []
    t_run0 = time.perf_counter()
    try:
        with slog.span("survey.robust_run_batched",
                       n_epochs=len(epochs), batch_size=batch_size,
                       workdir=os.fspath(workdir),
                       pipeline=bool(pipeline)):
            loader = None
            scan = iter(epochs)
            if pipeline:
                loader = PrefetchLoader(
                    ((eid, p) for eid, p in epochs
                     if str(eid) not in done),
                    depth=prefetch, workers=loader_workers,
                    load_fn=load_fn, timeline=timeline)
                loaded = iter(loader)
            for epoch_id, payload in scan:
                rec.tally["n_epochs"] += 1
                if timeline is not None:
                    timeline.assign_trace(
                        epoch_id,
                        _trace_id(rec.tally["n_epochs"] - 1,
                                  epoch_id))
                key = str(epoch_id)
                if key in done:
                    outcomes_by_key[key] = rec.resumed(epoch_id,
                                                       done[key])
                    continue
                if pipeline:
                    eid, item = next(loaded)
                    assert str(eid) == key, (eid, epoch_id)
                    if not item.ok:
                        _record(epoch_id,
                                _loader_outcome(epoch_id, item.error))
                        continue
                    payload = item.payload
                else:
                    try:
                        payload = _load_inline(payload, load_fn)
                    except Exception as e:  # noqa: BLE001 — per-epoch
                        _record(epoch_id, _loader_outcome(epoch_id, e))
                        continue
                pending.append((epoch_id, payload))
            if loader is not None:
                loader.close()

            for i in range(0, len(pending), batch_size):
                group = pending[i:i + batch_size]
                rec.tally["n_batches"] += 1
                run_group(group, process_batch, process, tiers,
                          retries, validate, _record,
                          epoch_label=f"batch[{i}:{i + len(group)}]",
                          span_key=f"batch[{i}]", timeline=timeline)
                if writer is not None:
                    # batch-boundary durability barrier (PR-2
                    # guarantee: at most the in-flight batch redone)
                    writer.drain()
            slog.log_event("survey.robust_batched_summary", **{
                k: v for k, v in rec.tally.items()
                if k != "tier_counts"},
                tier_counts=dict(rec.tally["tier_counts"]))
    finally:
        if writer is not None:
            writer.close()
    wall_s = time.perf_counter() - t_run0
    rec.beat(force=True)
    tl_summary = _finish_timeline(timeline)
    ordered = [outcomes_by_key[str(e)] for e, _ in epochs]
    if report:
        _report.write_run_report(workdir, _report.build_run_report(
            rec.tally, ordered, wall_s=wall_s, timeline=tl_summary,
            runner="run_survey_batched"))
    return {"results": rec.results, "outcomes": ordered,
            "summary": rec.tally}


def _quarantined_outcome(epoch_id, exc):
    """Quarantine outcome from an exhausted ladder, with the slog
    record :func:`_run_one` has always emitted."""
    slog.log_failure("robust.quarantine", epoch=epoch_id,
                     stage="process", error=exc,
                     tier=exc.attempts[-1]["tier"]
                     if exc.attempts else None,
                     retry=len(exc.attempts))
    last = exc.attempts[-1] if exc.attempts else {}
    # a malformed input shows up as the same error on every tier;
    # collapse the trail to the first record's class
    return EpochOutcome(
        epoch=epoch_id, status="quarantined",
        retries=len(exc.attempts),
        error=last.get("error", str(exc)),
        error_class=last.get("error_class", "LadderError"))


def _run_one(epoch_id, payload, process, tiers, retries, validate,
             report=None):
    """Dispatch one epoch through the ladder; never raises. A seeded
    ``report`` carries earlier attempts (the pipelined path's
    deferred tier-0 failure) into the retry count and quarantine
    trail."""

    def tier_fn(name):
        def run():
            result = process(payload, tier=name)
            if validate is not None and not validate(result):
                raise ValueError(
                    f"validator rejected tier {name} result for "
                    f"epoch {epoch_id!r}")
            return result

        return run

    try:
        value, report = _ladder.run_ladder(
            [(t, tier_fn(t)) for t in tiers], epoch=epoch_id,
            stage="process", retries=retries, report=report)
    except _ladder.LadderError as exc:
        return _quarantined_outcome(epoch_id, exc)
    return EpochOutcome(epoch=epoch_id, status="ok", tier=report.tier,
                        retries=report.retries, result=dict(value))


def outcome_dicts(outcomes):
    """JSON-able view of a list of :class:`EpochOutcome` (for result
    files / bench records)."""
    return [asdict(o) for o in outcomes]
