"""Fault-tolerant, journaled survey runner.

The production shape of a survey is "for each of ~10³ epochs: load →
search → fit → append results". The naive loop dies with the first
malformed file, poisons its batch with the first non-finite epoch, and
loses everything to a preemption. This runner wraps the loop with the
three robustness layers of this package:

- **per-epoch quarantine** — an epoch whose loader raises
  :class:`~scintools_tpu.io.MalformedInputError`, whose every
  fallback tier fails, or whose result a validator rejects is recorded
  as quarantined (structured slog record + journal line) and the
  survey moves on. Healthy epochs are never touched by a bad
  neighbour: each epoch is processed independently and journaled
  results are bitwise what ``process`` returned.
- **tiered fallback** — ``process(payload, tier=...)`` is dispatched
  through the ladder (robust/ladder.py): fused jax → staged jax →
  numpy, bounded retries on transient compile/OOM errors, every
  transition one slog failure record.
- **journaled resume** — every completed epoch is one fsynced
  CRC-stamped JSONL line (parallel/checkpoint.py:EpochJournal). A
  rerun after SIGKILL takes journaled records verbatim and processes
  only unfinished epochs, so the resumed run's results are identical
  to an uninterrupted run (tests/test_robust.py pins this, including
  a real SIGKILL).

Use :class:`~scintools_tpu.parallel.checkpoint.SurveyCheckpointer`
alongside when the loop also carries large array state; the journal
covers the per-epoch scalar results and progress cursor.
"""

from __future__ import annotations

import os
from dataclasses import asdict, dataclass, field

from . import ladder as _ladder
from ..parallel.checkpoint import EpochJournal
from ..utils import slog

_DEFAULT_TIERS = (_ladder.TIER_FUSED, _ladder.TIER_STAGED,
                  _ladder.TIER_NUMPY)


@dataclass
class EpochOutcome:
    """One epoch's fate: ``status`` is 'ok', 'quarantined', or
    'resumed' (taken verbatim from the journal)."""

    epoch: object
    status: str
    tier: str = ""
    retries: int = 0
    error: str = ""
    error_class: str = ""
    result: dict = field(default_factory=dict)


def _is_malformed(exc):
    from ..io import MalformedInputError

    return isinstance(exc, MalformedInputError)


def run_survey(epochs, process, workdir, tiers=_DEFAULT_TIERS,
               retries=1, validate=None, journal_name="journal.jsonl",
               resume=True):
    """Process ``epochs`` — an iterable of ``(epoch_id, payload)`` —
    fault-tolerantly, journaling each completion to
    ``workdir/journal_name``.

    ``process(payload, tier=<name>)`` produces one epoch's result as
    a dict of JSON-able scalars; it is attempted through the fallback
    ``tiers`` in order (bounded ``retries`` on transient
    compile/OOM RuntimeErrors per tier, robust/ladder.py semantics).
    A :class:`~scintools_tpu.io.MalformedInputError` quarantines the
    epoch immediately (no tier can fix a corrupt file); exhaustion of
    every tier quarantines it with the full attempt trail. A
    ``validate(result) -> bool`` hook (optional) rejects a tier's
    result — e.g. require the device health bitmask be clean — and
    sends the epoch down to the next tier.

    Returns ``{"results": {epoch_id: result_dict},
    "outcomes": [EpochOutcome...], "summary": {...}}`` where summary
    counts ok/quarantined/resumed epochs, per-tier completions, and
    total retries. With ``resume=True`` (default), epochs already in
    the journal are not reprocessed — their journaled results are
    returned verbatim."""
    os.makedirs(workdir, exist_ok=True)
    journal = EpochJournal(os.path.join(workdir, journal_name))
    done = journal.records() if resume else {}

    outcomes = []
    results = {}
    tally = {"n_epochs": 0, "n_ok": 0, "n_quarantined": 0,
             "n_resumed": 0, "retries": 0,
             "tier_counts": {t: 0 for t in tiers}}
    epochs = list(epochs)
    with slog.span("survey.robust_run", n_epochs=len(epochs),
                   workdir=os.fspath(workdir)):
        for epoch_id, payload in epochs:
            tally["n_epochs"] += 1
            key = str(epoch_id)
            if key in done:
                rec = done[key]
                out = EpochOutcome(
                    epoch=epoch_id, status="resumed",
                    tier=rec.get("tier", ""),
                    result=rec.get("result") or {})
                if rec.get("status") == "quarantined":
                    tally["n_quarantined"] += 1
                    out.error = rec.get("error", "")
                    out.error_class = rec.get("error_class", "")
                else:
                    results[key] = out.result
                tally["n_resumed"] += 1
                outcomes.append(out)
                continue
            out = _run_one(epoch_id, payload, process, tiers, retries,
                           validate)
            tally["retries"] += out.retries
            if out.status == "ok":
                tally["n_ok"] += 1
                tally["tier_counts"][out.tier] = \
                    tally["tier_counts"].get(out.tier, 0) + 1
                results[key] = out.result
                journal.append(key, status="ok", tier=out.tier,
                               retries=out.retries, result=out.result)
            else:
                tally["n_quarantined"] += 1
                journal.append(key, status="quarantined",
                               tier=out.tier, retries=out.retries,
                               error=out.error,
                               error_class=out.error_class)
            outcomes.append(out)
        slog.log_event("survey.robust_summary", **{
            k: v for k, v in tally.items() if k != "tier_counts"},
            tier_counts=dict(tally["tier_counts"]))
    return {"results": results, "outcomes": outcomes,
            "summary": tally}


def _run_one(epoch_id, payload, process, tiers, retries, validate):
    """Dispatch one epoch through the ladder; never raises."""

    def tier_fn(name):
        def run():
            result = process(payload, tier=name)
            if validate is not None and not validate(result):
                raise ValueError(
                    f"validator rejected tier {name} result for "
                    f"epoch {epoch_id!r}")
            return result

        return run

    try:
        value, report = _ladder.run_ladder(
            [(t, tier_fn(t)) for t in tiers], epoch=epoch_id,
            stage="process", retries=retries)
    except _ladder.LadderError as exc:
        slog.log_failure("robust.quarantine", epoch=epoch_id,
                         stage="process", error=exc,
                         tier=exc.attempts[-1]["tier"]
                         if exc.attempts else None,
                         retry=len(exc.attempts))
        last = exc.attempts[-1] if exc.attempts else {}
        # a malformed input shows up as the same error on every tier;
        # collapse the trail to the first record's class
        return EpochOutcome(
            epoch=epoch_id, status="quarantined",
            retries=len(exc.attempts),
            error=last.get("error", str(exc)),
            error_class=last.get("error_class", "LadderError"))
    return EpochOutcome(epoch=epoch_id, status="ok", tier=report.tier,
                        retries=report.retries, result=dict(value))


def outcome_dicts(outcomes):
    """JSON-able view of a list of :class:`EpochOutcome` (for result
    files / bench records)."""
    return [asdict(o) for o in outcomes]
