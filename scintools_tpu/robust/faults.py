"""Deterministic fault-injection harness for the robustness tests.

Production surveys see four broad failure classes; each has a
deterministic injector here so tests (tests/test_robust.py) and the
bench's robustness config can reproduce them bit-for-bit:

- **corrupt pixels** — :func:`inject_nan_pixels` (RFI blanking that
  leaked NaN through a resampler);
- **corrupt epochs** — :func:`inject_neginf_db` (an all-zero
  pass-band turned into −inf by a dB conversion upstream);
- **truncated inputs** — :func:`truncate_chunk_stack` (a chunk stack
  cut short by a dying writer) and :func:`corrupt_file_tail` (a
  journal/checkpoint/result file whose tail a SIGKILL tore);
- **environment faults** — :func:`tier_failure_hook` /
  :func:`maybe_fail`, a monkeypatchable process-wide hook the
  fallback ladder consults before running each tier, so a compile or
  OOM ``RuntimeError`` can be simulated per (tier, epoch, stage)
  without a real accelerator failure;
- **filesystem faults** (ISSUE 17 satellite) —
  :func:`torn_write` (a partially visible write that a crashed or
  EIO'd writer left), :func:`delayed_visibility` /
  :func:`reveal` (a file hidden from readers until "the rename
  becomes visible" — NFS-style close-to-open laxity), and
  :func:`eio_reads` (an ``open()`` patch raising ``EIO`` on matching
  paths for the first N attempts). tests/test_serve.py drives the
  spool watcher through these; tests/test_chaos.py uses the same
  shapes via the fleet's seeded :class:`~..fleet.chaos.ChaosEngine`.

All randomised injectors take an explicit ``seed`` and never touch
global RNG state.
"""

from __future__ import annotations

import contextlib
import os

import numpy as np

# process-wide injection hook consulted by robust/ladder.py before
# each tier attempt: ``hook(tier=..., epoch=..., stage=...)`` — raise
# from it to simulate that tier failing. None → no injection.
TIER_FAIL_HOOK = None


def maybe_fail(tier, epoch=None, stage=None):
    """Consult the process-wide injection hook (no-op when unset).
    The fallback ladder calls this before every tier attempt; a test
    installs a hook (directly or via :func:`tier_failure_hook`) that
    raises e.g. ``RuntimeError('RESOURCE_EXHAUSTED ...')`` to drive
    the ladder down a tier."""
    if TIER_FAIL_HOOK is not None:
        TIER_FAIL_HOOK(tier=tier, epoch=epoch, stage=stage)


@contextlib.contextmanager
def tier_failure_hook(fail_tiers, exc=None, max_failures=None):
    """Context manager installing a deterministic per-tier failure:
    every attempt on a tier named in ``fail_tiers`` raises ``exc``
    (default: a transient-looking compile ``RuntimeError``), up to
    ``max_failures`` injections in total (None = unlimited). Yields
    the mutable list of (tier, epoch, stage) injection records."""
    global TIER_FAIL_HOOK
    if exc is None:
        exc = RuntimeError("XLA compile failed (injected fault)")
    fail_tiers = set(fail_tiers)
    records = []

    def hook(tier=None, epoch=None, stage=None):
        if tier in fail_tiers and (max_failures is None
                                   or len(records) < max_failures):
            records.append((tier, epoch, stage))
            raise exc

    prev = TIER_FAIL_HOOK
    TIER_FAIL_HOOK = hook
    try:
        yield records
    finally:
        TIER_FAIL_HOOK = prev


def inject_nan_pixels(dyn, frac=0.01, seed=0):
    """Copy of ``dyn`` with ``frac`` of its pixels NaN'd at
    deterministic positions (``seed``)."""
    out = np.array(dyn, dtype=float, copy=True)
    rng = np.random.default_rng(seed)
    n = max(1, int(frac * out.size))
    idx = rng.choice(out.size, size=n, replace=False)
    out.flat[idx] = np.nan
    return out


def inject_neginf_db(dyn, rows=None):
    """Copy of ``dyn`` with whole frequency rows at −inf (default:
    every row — the classic dead-epoch signature of ``10·log10(0)``
    from an upstream dB conversion)."""
    out = np.array(dyn, dtype=float, copy=True)
    if rows is None:
        out[:] = -np.inf
    else:
        out[np.asarray(rows)] = -np.inf
    return out


def truncate_chunk_stack(stack, keep):
    """First ``keep`` chunks of a stacked chunk batch — the shape a
    survey sees when a writer died mid-stack. ``keep`` must be ≥ 1
    (an empty stack is a malformed input, not a truncation)."""
    keep = int(keep)
    if keep < 1:
        raise ValueError("truncate_chunk_stack: keep must be >= 1")
    return np.asarray(stack)[:keep]


def corrupt_file_tail(path, drop_bytes=16):
    """Truncate ``drop_bytes`` off the end of a file in place — the
    torn-write state a SIGKILL leaves behind mid-append. Returns the
    new size."""
    size = os.path.getsize(path)
    new = max(0, size - int(drop_bytes))
    with open(path, "rb+") as fh:
        fh.truncate(new)
    return new


# ---------------------------------------------------------------------
# filesystem-fault injectors (ISSUE 17 satellite) — the test-side
# twins of the faults fleet/chaos.py injects beneath the fsops seam
# ---------------------------------------------------------------------

def torn_write(path, data, frac=0.5):
    """Write only the first ``frac`` of ``data`` to ``path``,
    NON-atomically — the visible-but-incomplete file a writer that
    died (or hit EIO) mid-``write()`` leaves behind. At least one
    byte is written so the file exists and is non-empty (the
    hard-to-detect shape; a zero-byte file is trivially torn).
    Returns the number of bytes written."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    n = max(1, int(len(data) * float(frac))) if data else 0
    with open(os.fspath(path), "wb") as fh:
        fh.write(data[:n])
    return n


def delayed_visibility(path, suffix=".invisible"):
    """Hide ``path`` from readers by renaming it aside — the
    NFS-style window where a completed rename is not yet visible to
    another client. Returns the hidden path to hand to
    :func:`reveal`. The pair is atomic at each end, so a watcher
    never sees a torn file — only a late one."""
    path = os.fspath(path)
    hidden = path + suffix
    os.replace(path, hidden)
    return hidden


def reveal(hidden, suffix=".invisible"):
    """Complete a :func:`delayed_visibility` window: rename the
    hidden file back into place and return the visible path."""
    hidden = os.fspath(hidden)
    if not hidden.endswith(suffix):
        raise ValueError(f"not a hidden path: {hidden!r}")
    path = hidden[:-len(suffix)]
    os.replace(hidden, path)
    return path


@contextlib.contextmanager
def eio_reads(match, times=1):
    """Patch ``builtins.open`` so the first ``times`` opens of a
    path containing ``match`` raise ``OSError(EIO)`` — a flaky disk
    under a reader. Yields the mutable list of faulted paths; other
    opens pass through untouched."""
    import builtins
    import errno

    real_open = builtins.open
    faulted = []

    def flaky_open(file, *args, **kwargs):
        try:
            name = os.fspath(file)
        except TypeError:
            name = ""
        if (isinstance(name, str) and match in name
                and len(faulted) < int(times)):
            faulted.append(name)
            raise OSError(errno.EIO, "injected EIO", name)
        return real_open(file, *args, **kwargs)

    builtins.open = flaky_open
    try:
        yield faulted
    finally:
        builtins.open = real_open
