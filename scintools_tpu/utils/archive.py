"""Pulsar-archive cleaning hook (host-side, optional external deps).

Capability-parity stub for the reference's psrchive + coast_guard
cleaning step (scint_utils.py:27-64). Both dependencies are external
C++/Python tools that are not part of this framework; the hook keeps
the same call surface and degrades with a clear error when they are
absent, so survey pipelines can gate on :func:`archive_tools_available`.
"""

from __future__ import annotations


def archive_tools_available():
    """True when psrchive's python bindings and coast_guard import."""
    try:
        import psrchive  # noqa: F401
        from coast_guard import cleaners  # noqa: F401
    except Exception:
        return False
    return True


def make_dynspec(archive, template=None, phasebin=1):
    """Create a psrflux-format dynamic spectrum from a pulsar archive
    by invoking the external ``psrflux`` tool
    (``psrflux -s template -e dynspec archive``) — the reference's
    stub documents the command without running it
    (scint_utils.py:894-899); here it is executed when psrflux is on
    PATH and raises with the exact command otherwise."""
    import shutil
    import subprocess

    if phasebin != 1:
        # psrflux has no phase-binning option; the reference's stub
        # carries the parameter but never uses it either
        raise ValueError("phasebin != 1 is not supported by psrflux")
    cmd = ["psrflux"]
    if template is not None:
        cmd += ["-s", str(template)]
    cmd += ["-e", "dynspec", str(archive)]
    if shutil.which("psrflux") is None:
        raise RuntimeError(
            "psrflux (psrchive) is not installed; run manually: "
            + " ".join(cmd))
    subprocess.run(cmd, check=True)
    return f"{archive}.dynspec"


def clean_archive(archive, template=None, bandwagon=0.99, channel_threshold=5,
                  subint_threshold=5, output_directory=None):
    """Clean RFI from a psrchive archive with coast_guard's surgical and
    bandwagon cleaners, then unload the cleaned archive
    (scint_utils.py:27-64 behaviour).

    Raises ImportError with installation guidance when the external
    tools are missing.
    """
    try:
        import psrchive
        from coast_guard import cleaners
    except ImportError as e:
        raise ImportError(
            "clean_archive requires the external 'psrchive' python "
            "bindings and 'coast_guard' (neither ships with "
            "scintools_tpu); install them or pre-clean archives before "
            "loading") from e

    if isinstance(archive, str):
        archive = psrchive.Archive_load(archive)

    cleaner = cleaners.load_cleaner("surgical")
    surgical_parameters = (
        f"chan_numpieces=1,subint_numpieces=1,"
        f"chanthresh={channel_threshold},subintthresh={subint_threshold}")
    if template is not None:
        surgical_parameters += f",template={template}"
    cleaner.parse_config_string(surgical_parameters)
    cleaner.run(archive)

    if bandwagon:
        cleaner = cleaners.load_cleaner("bandwagon")
        cleaner.parse_config_string(
            f"badchantol={bandwagon},badsubtol=1.0")
        cleaner.run(archive)

    unload_name = archive.get_filename().split("/")[-1]
    if output_directory is not None:
        unload_name = f"{output_directory.rstrip('/')}/{unload_name}"
    archive.unload(unload_name)
    return archive
