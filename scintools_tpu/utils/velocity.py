"""Scintillation-velocity and curvature-likelihood utilities
(scint_utils.py:732-766, :835-957)."""

from __future__ import annotations

import numpy as np
from scipy.ndimage import gaussian_filter1d


def scint_velocity(params, dnu, tau, freq, dnuerr=None, tauerr=None,
                   a=2.53e4):
    """viss = a·√(2d(1−s)/s)·√Δν/(f·τ) ± error
    (scint_utils.py:732-766)."""
    freq = freq / 1e3  # GHz
    if params is not None:
        p = params
        d = p["d"].value if hasattr(p["d"], "value") else p["d"]
        s = p["s"].value if hasattr(p["s"], "value") else p["s"]
        d_err = (p["d"].stderr if hasattr(p["d"], "stderr")
                 else p.get("derr", 0)) or 0
        s_err = (p["s"].stderr if hasattr(p["s"], "stderr")
                 else p.get("serr", 0)) or 0
        coeff = a * np.sqrt(2 * d * (1 - s) / s)
        coeff_err = (dnu / s) * ((1 - s) * d_err ** 2 / (2 * d)
                                 + (d * s_err ** 2
                                    / (2 * s ** 2 * (1 - s))))
    else:
        coeff, coeff_err = a, 0
    viss = coeff * np.sqrt(dnu) / (freq * tau)
    if dnuerr is not None and tauerr is not None:
        viss_err = (1 / (freq * tau)) * np.sqrt(
            coeff ** 2 * ((dnuerr ** 2 / (4 * dnu))
                          + (dnu * tauerr ** 2 / tau ** 2)) + coeff_err)
        return viss, viss_err
    return viss


def calculate_curvature_peak_probability(power_data, noise_level,
                                         smooth=True, curvatures=None,
                                         log=False):
    """Gaussian probability of the Doppler-profile peak
    (scint_utils.py:835-854). ``curvatures`` is accepted for API
    parity and unused — the reference notes it "currently doesn't
    normalise using curvatures" (scint_utils.py:853)."""
    power_data = np.asarray(power_data, dtype=float)
    if smooth:
        power_data = gaussian_filter1d(power_data, noise_level)
    if np.shape(noise_level) == ():
        max_power = np.max(power_data)
    else:
        noise_level = np.reshape(noise_level, (len(noise_level), 1))
        max_power = np.max(power_data, axis=1).reshape(
            (len(power_data), 1))
    if log:
        return (np.log(1 / (noise_level * np.sqrt(2 * np.pi)))
                - 0.5 * ((power_data - max_power) / noise_level) ** 2)
    return (1 / (noise_level * np.sqrt(2 * np.pi))
            * np.exp(-0.5 * ((power_data - max_power)
                             / noise_level) ** 2))


def curvature_log_likelihood(power, nfdop, noise, model_nfdop):
    """Log likelihood of model nfdop against Doppler-profile densities
    (scint_utils.py:902-957)."""
    nfdop = np.asarray(nfdop, dtype=float)
    dim = len(np.shape(nfdop))
    eta_prob = calculate_curvature_peak_probability(power, noise,
                                                    log=True)
    integral = np.sum(np.exp(eta_prob[..., :-1])
                      * np.diff(nfdop, axis=dim - 1), axis=dim - 1)
    if dim == 2:
        integral = integral.reshape((len(integral), 1))
    eta_prob_norm = eta_prob - np.log(integral)

    if dim == 2:
        like = np.zeros(len(nfdop))
        outside = np.argwhere(
            (model_nfdop > np.max(nfdop, axis=1))
            | (model_nfdop < np.min(nfdop, axis=1))).flatten()
        inside = np.argwhere(
            (model_nfdop < np.max(nfdop, axis=1))
            & (model_nfdop > np.min(nfdop, axis=1))).flatten()
        like[outside] = -200
        model_in = np.reshape(np.asarray(model_nfdop)[inside],
                              (len(inside), 1))
        inds = np.argmin(np.abs(nfdop[inside] - model_in), axis=1)
        like[inside] = eta_prob_norm[inside, inds]
        return np.sum(like)
    if dim == 1:
        if np.min(nfdop) < model_nfdop < np.max(nfdop):
            return eta_prob_norm[np.argmin(np.abs(nfdop - model_nfdop))]
        return -200
    raise ValueError("Invalid input array dimension. Must be either 1D "
                     "(single observation) or 2D (multiple observations)")


def save_curvature_data(dyn, filename=None):
    """Save power-vs-curvature + noise to npz
    (scint_utils.py:857-875)."""
    if filename is None:
        filename = dyn.name + "curvature_data"
    sup_data = np.array([dyn.name, dyn.mjd])
    if hasattr(dyn, "normsspecavg"):
        np.savez(filename, sup_data, dyn.normsspec_fdop,
                 dyn.normsspecavg, dyn.noise)
    elif hasattr(dyn, "norm_sspec_avg1"):
        np.savez(filename, sup_data, dyn.eta_array, dyn.norm_sspec_avg1,
                 dyn.norm_sspec_avg2, dyn.noise)
    else:
        np.savez(filename, sup_data, dyn.eta_array, dyn.norm_sspec_avg,
                 dyn.noise)
