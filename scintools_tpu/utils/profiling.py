"""Profiling/tracing harness (SURVEY.md §5 'tracing/profiling' plan).

The reference's only instrumentation is ad-hoc wall-clock prints
around file loading (/root/reference/scintools/dynspec.py:170-172,
227-229). Here profiling is a small first-class utility:

- :class:`Timer` — ``block_until_ready``-aware wall-clock sections
  that accumulate into a table (jax async dispatch makes naive
  ``time.time()`` spans meaningless; every section exit synchronises
  the device queue before reading the clock).
- :func:`trace` — context manager around ``jax.profiler.trace`` for
  XLA/TensorBoard traces (the hook previously private to bench.py's
  ``SCINTOOLS_BENCH_TRACE``).
- :func:`timeit_fn` — best-of-N timing of a jitted callable with a
  separate (reported) compile/warmup time.

Used by examples/ and bench.py; no dependency outside jax/numpy.
"""

from __future__ import annotations

import time
from contextlib import contextmanager

import numpy as np


def _block(x):
    """block_until_ready on any pytree-ish value; numpy passes through."""
    try:
        import jax

        return jax.block_until_ready(x)
    except Exception:
        return x


def _device_fence():
    """Drain the default device's dispatch queue: devices execute
    in-order, so blocking on a freshly enqueued trivial op implies
    every previously dispatched op has completed. No-op without jax."""
    try:
        import jax

        jax.block_until_ready(jax.device_put(0.0))
    except Exception:  # broad-except-ok: best-effort timing fence —
        # any backend failure here must not break the code being
        # profiled (the timings just lose the fence)
        pass


class Timer:
    """Accumulating section timer.

    >>> tm = Timer()
    >>> with tm("sspec"):
    ...     out = jitted_sspec(dyn)      # implicit device sync on exit
    >>> with tm("search"):
    ...     eigs = search(cs)
    >>> print(tm.report())

    jax dispatch is asynchronous, so on entry AND exit the timer
    fences the default device queue (in-order execution makes a
    block on a trailing trivial op a full fence); a section may also
    append its result to the yielded box for an explicit
    block_until_ready on that value.
    """

    def __init__(self, sync=True):
        self.sync = sync
        self.sections = {}          # name → list of seconds

    @contextmanager
    def __call__(self, name):
        if self.sync:
            _device_fence()
        t0 = time.perf_counter()
        box = []
        try:
            yield box
        finally:
            if self.sync:
                _block(box[-1]) if box else _device_fence()
            self.sections.setdefault(name, []).append(
                time.perf_counter() - t0)

    def add(self, name, seconds):
        self.sections.setdefault(name, []).append(float(seconds))

    def total(self, name):
        return float(np.sum(self.sections.get(name, [])))

    def report(self):
        """Fixed-width table: name, calls, total, mean, best."""
        rows = [f"{'section':<24}{'calls':>6}{'total_s':>10}"
                f"{'mean_s':>10}{'best_s':>10}"]
        for name, vals in self.sections.items():
            v = np.asarray(vals)
            rows.append(f"{name:<24}{len(v):>6}{v.sum():>10.4f}"
                        f"{v.mean():>10.4f}{v.min():>10.4f}")
        return "\n".join(rows)


def _interval_union(intervals):
    """Total length of the union of ``[(t0, t1), ...]`` intervals."""
    total = 0.0
    end = -np.inf
    for t0, t1 in sorted(intervals):
        if t1 <= end:
            continue
        total += t1 - max(t0, end)
        end = t1
    return total


class StageTimeline:
    """Per-epoch stage-span recorder with overlap accounting — the
    observability half of the pipelined survey engine
    (parallel/pipeline.py + robust/runner.py).

    Each pipeline stage of each epoch records one wall-clock span:

    >>> tl = StageTimeline()
    >>> with tl.span("e0", "load"):
    ...     payload = load(path)          # in a prefetch worker
    >>> with tl.span("e0", "compute"):
    ...     out = program(payload)
    >>> tl.summary()["overlap_frac"]

    Spans may be recorded from any thread (`record` appends under a
    lock); the clock is ``time.perf_counter`` so spans from the
    loader threads, the main dispatch loop, and the journal writer
    share one timeline.

    :meth:`summary` reports:

    - ``wall_s`` — last span end − first span start;
    - ``stage_busy_s`` — per-stage union of that stage's intervals
      (concurrent loads of two epochs count once where they overlap);
    - ``busy_s`` — union of ALL spans (time at least one stage was
      active);
    - ``overlap_frac`` — ``1 − busy_s / Σ stage_busy_s``: 0 for a
      strictly sequential run (stages never coincide), → 0.5 when two
      stages are perfectly hidden behind each other, higher with more
      stages overlapped;
    - ``device_idle_s`` — wall time NOT covered by a
      ``device_stage`` span (default ``"compute"``): what an
      accelerator would have wasted waiting on the host.

    ``log_summary()`` emits the summary as one structured slog event
    (utils/slog.py) so a survey run's pipeline efficiency is
    greppable next to its quarantine/fallback records, and
    ``export_trace(path)`` writes the raw spans as Chrome-trace JSON
    (obs/trace.py) for chrome://tracing / Perfetto, one named track
    per stage, each span tagged with its epoch's trace ID
    (:meth:`assign_trace` — the runner assigns deterministic per-epoch
    IDs and threads them through loader/dispatch/fence/journal spans).
    """

    def __init__(self, device_stage="compute"):
        import threading

        self.device_stage = device_stage
        self._spans = []                # (stage, epoch, t0, t1)
        self._trace_ids = {}            # epoch -> trace-id string
        self._lock = threading.Lock()

    def record(self, epoch, stage, t0, t1):
        """Record one finished span (absolute perf_counter times)."""
        with self._lock:
            self._spans.append((str(stage), epoch, float(t0),
                                float(t1)))

    def assign_trace(self, epoch, trace_id):
        """Bind ``epoch`` to a trace-id string: every span of that
        epoch (whichever thread recorded it) carries the ID in the
        exported trace."""
        with self._lock:
            self._trace_ids[epoch] = str(trace_id)

    def trace_ids(self):
        with self._lock:
            return dict(self._trace_ids)

    def spans(self):
        """Snapshot of the recorded ``(stage, epoch, t0, t1)`` spans."""
        with self._lock:
            return list(self._spans)

    def export_trace(self, path):
        """Write the recorded spans as a Chrome-trace JSON file
        (loads in chrome://tracing and ui.perfetto.dev); returns the
        path. See obs/trace.py for the format conventions."""
        from ..obs.trace import write_chrome_trace

        return write_chrome_trace(path, self.spans(),
                                  trace_ids=self.trace_ids())

    @contextmanager
    def span(self, epoch, stage):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(epoch, stage, t0, time.perf_counter())

    def stages(self):
        return sorted({s for s, _, _, _ in self._spans})

    def summary(self):
        if not self._spans:
            return {"n_spans": 0, "n_epochs": 0, "wall_s": 0.0,
                    "busy_s": 0.0, "overlap_frac": 0.0,
                    "device_idle_s": 0.0, "stage_busy_s": {}}
        spans = list(self._spans)
        t_start = min(t0 for _, _, t0, _ in spans)
        t_end = max(t1 for _, _, _, t1 in spans)
        wall = t_end - t_start
        by_stage = {}
        for stage, _, t0, t1 in spans:
            by_stage.setdefault(stage, []).append((t0, t1))
        stage_busy = {s: _interval_union(v)
                      for s, v in by_stage.items()}
        busy = _interval_union([(t0, t1) for _, _, t0, t1 in spans])
        total = sum(stage_busy.values())
        device_busy = _interval_union(
            by_stage.get(self.device_stage, []))
        return {
            "n_spans": len(spans),
            "n_epochs": len({e for _, e, _, _ in spans}),
            "wall_s": round(wall, 4),
            "busy_s": round(busy, 4),
            "stage_busy_s": {s: round(v, 4)
                             for s, v in sorted(stage_busy.items())},
            "overlap_frac": round(1.0 - busy / total, 4)
            if total > 0 else 0.0,
            "device_idle_s": round(max(0.0, wall - device_busy), 4),
        }

    def log_summary(self, event="survey.pipeline_timeline", **extra):
        """Emit :meth:`summary` as one structured slog event; returns
        the summary dict."""
        from . import slog

        out = self.summary()
        slog.log_event(event, **out, **extra)
        return out

    def report(self):
        """Fixed-width per-stage table (cf. :class:`Timer.report`)."""
        s = self.summary()
        rows = [f"{'stage':<12}{'busy_s':>10}",
                *(f"{name:<12}{busy:>10.4f}"
                  for name, busy in s["stage_busy_s"].items()),
                f"{'wall':<12}{s['wall_s']:>10.4f}",
                f"overlap_frac {s['overlap_frac']:.3f}  "
                f"device_idle_s {s['device_idle_s']:.4f}"]
        return "\n".join(rows)


@contextmanager
def trace(trace_dir):
    """jax.profiler trace context (view with TensorBoard / xprof).
    No-op (with a warning) when the profiler is unavailable; the
    traced body's own exceptions propagate untouched."""
    try:
        import jax

        ctx = jax.profiler.trace(str(trace_dir))
        ctx.__enter__()
    except Exception as e:  # profiler missing on exotic backends
        print(f"Warning: jax profiler trace unavailable ({e}); "
              f"running untraced")
        yield
        return
    try:
        yield
    finally:
        ctx.__exit__(None, None, None)


def timeit_fn(fn, *args, repeats=3, **kwargs):
    """Time a (possibly jitted) callable: returns a dict with the
    first-call (compile+run) time and best-of-``repeats`` steady-state
    wall time, synchronising the device after every call."""
    t0 = time.perf_counter()
    out = _block(fn(*args, **kwargs))
    compile_s = time.perf_counter() - t0
    best = np.inf
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = _block(fn(*args, **kwargs))
        best = min(best, time.perf_counter() - t0)
    return {"first_call_s": compile_s, "best_s": float(best),
            "repeats": repeats, "result": out}
