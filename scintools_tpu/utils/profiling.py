"""Profiling/tracing harness (SURVEY.md §5 'tracing/profiling' plan).

The reference's only instrumentation is ad-hoc wall-clock prints
around file loading (/root/reference/scintools/dynspec.py:170-172,
227-229). Here profiling is a small first-class utility:

- :class:`Timer` — ``block_until_ready``-aware wall-clock sections
  that accumulate into a table (jax async dispatch makes naive
  ``time.time()`` spans meaningless; every section exit synchronises
  the device queue before reading the clock).
- :func:`trace` — context manager around ``jax.profiler.trace`` for
  XLA/TensorBoard traces (the hook previously private to bench.py's
  ``SCINTOOLS_BENCH_TRACE``).
- :func:`timeit_fn` — best-of-N timing of a jitted callable with a
  separate (reported) compile/warmup time.

Used by examples/ and bench.py; no dependency outside jax/numpy.
"""

from __future__ import annotations

import time
from contextlib import contextmanager

import numpy as np


def _block(x):
    """block_until_ready on any pytree-ish value; numpy passes through."""
    try:
        import jax

        return jax.block_until_ready(x)
    except Exception:
        return x


def _device_fence():
    """Drain the default device's dispatch queue: devices execute
    in-order, so blocking on a freshly enqueued trivial op implies
    every previously dispatched op has completed. No-op without jax."""
    try:
        import jax

        jax.block_until_ready(jax.device_put(0.0))
    except Exception:  # broad-except-ok: best-effort timing fence —
        # any backend failure here must not break the code being
        # profiled (the timings just lose the fence)
        pass


class Timer:
    """Accumulating section timer.

    >>> tm = Timer()
    >>> with tm("sspec"):
    ...     out = jitted_sspec(dyn)      # implicit device sync on exit
    >>> with tm("search"):
    ...     eigs = search(cs)
    >>> print(tm.report())

    jax dispatch is asynchronous, so on entry AND exit the timer
    fences the default device queue (in-order execution makes a
    block on a trailing trivial op a full fence); a section may also
    append its result to the yielded box for an explicit
    block_until_ready on that value.
    """

    def __init__(self, sync=True):
        self.sync = sync
        self.sections = {}          # name → list of seconds

    @contextmanager
    def __call__(self, name):
        if self.sync:
            _device_fence()
        t0 = time.perf_counter()
        box = []
        try:
            yield box
        finally:
            if self.sync:
                _block(box[-1]) if box else _device_fence()
            self.sections.setdefault(name, []).append(
                time.perf_counter() - t0)

    def add(self, name, seconds):
        self.sections.setdefault(name, []).append(float(seconds))

    def total(self, name):
        return float(np.sum(self.sections.get(name, [])))

    def report(self):
        """Fixed-width table: name, calls, total, mean, best."""
        rows = [f"{'section':<24}{'calls':>6}{'total_s':>10}"
                f"{'mean_s':>10}{'best_s':>10}"]
        for name, vals in self.sections.items():
            v = np.asarray(vals)
            rows.append(f"{name:<24}{len(v):>6}{v.sum():>10.4f}"
                        f"{v.mean():>10.4f}{v.min():>10.4f}")
        return "\n".join(rows)


@contextmanager
def trace(trace_dir):
    """jax.profiler trace context (view with TensorBoard / xprof).
    No-op (with a warning) when the profiler is unavailable; the
    traced body's own exceptions propagate untouched."""
    try:
        import jax

        ctx = jax.profiler.trace(str(trace_dir))
        ctx.__enter__()
    except Exception as e:  # profiler missing on exotic backends
        print(f"Warning: jax profiler trace unavailable ({e}); "
              f"running untraced")
        yield
        return
    try:
        yield
    finally:
        ctx.__exit__(None, None, None)


def timeit_fn(fn, *args, repeats=3, **kwargs):
    """Time a (possibly jitted) callable: returns a dict with the
    first-call (compile+run) time and best-of-``repeats`` steady-state
    wall time, synchronising the device after every call."""
    t0 = time.perf_counter()
    out = _block(fn(*args, **kwargs))
    compile_s = time.perf_counter() - t0
    best = np.inf
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = _block(fn(*args, **kwargs))
        best = min(best, time.perf_counter() - t0)
    return {"first_call_s": compile_s, "best_s": float(best),
            "repeats": repeats, "result": out}
