"""Binary-orbit utilities: true anomaly and binary phase.

Re-design of scint_utils.py:509-572. The reference solves Kepler's
equation with a python loop of scipy ``fsolve`` per epoch; here a
vectorised Newton iteration handles all epochs at once (and jits on
the jax backend for batched survey pipelines).
"""

from __future__ import annotations

import numpy as np

from ..backend import get_xp, resolve_backend


def kepler_solve(M, ecc, iters=30, backend=None):
    """Solve E − e·sin E = M for arrays of mean anomaly (Newton)."""
    xp = get_xp(resolve_backend(backend))
    M = xp.asarray(M, dtype=float)
    E = M + ecc * xp.sin(M)
    for _ in range(iters):
        E = E - (E - ecc * xp.sin(E) - M) / (1 - ecc * xp.cos(E))
    return E


def get_true_anomaly(mjds, pars, backend=None):
    """True anomalies for barycentric MJDs + parameter dict
    (scint_utils.py:509-554)."""
    xp = get_xp(resolve_backend(backend))
    p = pars.valuesdict() if hasattr(pars, "valuesdict") else pars
    if "TASC" in p:
        T0 = p["TASC"]
        ECC = np.sqrt(p["EPS1"] ** 2 + p["EPS2"] ** 2)
    else:
        T0 = p["T0"]
        ECC = p["ECC"]
    PB = p["PB"]
    PBDOT = p.get("PBDOT", 0)
    if np.abs(PBDOT) > 1e-10:
        PBDOT *= 1e-12  # tempo format

    nb = 2 * np.pi / PB
    mjds = xp.asarray(mjds, dtype=float)
    M = nb * ((mjds - T0) - 0.5 * (PBDOT / PB) * (mjds - T0) ** 2)

    if ECC < 1e-4:
        E = M  # circular-orbit approximation (reference behaviour)
    else:
        E = kepler_solve(M, ECC, backend=backend)

    U = 2 * xp.arctan2(np.sqrt(1 + ECC) * xp.sin(E / 2),
                       np.sqrt(1 - ECC) * xp.cos(E / 2))
    U = xp.where(U < 0, U + 2 * np.pi, U)
    return U


def get_binphase(mjds, pars, backend=None):
    """Binary phase = true anomaly + ω(t) (scint_utils.py:557-572)."""
    p = pars.valuesdict() if hasattr(pars, "valuesdict") else pars
    U = get_true_anomaly(mjds, p, backend=backend)
    if "TASC" in p:
        OM = 0.0
    else:
        OM = p["OM"] * np.pi / 180
        if "OMDOT" in p:
            OM = OM + (p["OMDOT"] * (np.pi / 180) / 365.2425
                       * (np.asarray(mjds) - p["T0"]))
    return U + OM
