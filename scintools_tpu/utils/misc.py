"""General utilities (re-design of scint_utils.py helpers)."""

from __future__ import annotations

import os
import pickle
import sys

import numpy as np


def is_valid(array):
    """Finite-and-not-NaN boolean mask (scint_utils.py:87-91)."""
    return np.isfinite(array) & ~np.isnan(array)


def svd_model(arr, nmodes=1):
    """Divide out the rank-``nmodes`` SVD model
    (scint_utils.py:705-729)."""
    u, s, w = np.linalg.svd(arr)
    s = np.array(s)
    s[nmodes:] = 0.0
    S = np.zeros((len(u), len(w)), dtype=complex)
    S[: len(s), : len(s)] = np.diag(s)
    model = u @ S @ w
    return arr / np.abs(model), model


def difference(x):
    """Centred differences, same length as x (scint_utils.py:270-283)."""
    x = np.asarray(x, dtype=float)
    dx = np.empty_like(x)
    dx[0] = (x[1] - x[0]) / 2
    dx[-1] = (x[-1] - x[-2]) / 2
    dx[1:-1] = (x[2:] - x[:-2]) / 2
    return dx


def find_nearest(arr, val):
    """Index of the element nearest ``val`` (scint_utils.py:462-468)."""
    return int(np.argmin(np.abs(np.asarray(arr) - val)))


def longest_run_of_zeros(arr):
    """(scint_utils.py:471-477)"""
    count = max_count = 0
    for num in arr:
        count = count + 1 if num == 0 else 0
        max_count = max(max_count, count)
    return max_count


def centres_to_edges(arr):
    """Pixel centres → pixel edges, assuming even spacing
    (scint_utils.py:787-794)."""
    arr = np.asarray(arr, dtype=float)
    darr = np.abs(arr[1] - arr[0])
    edges = arr - darr / 2
    return np.append(edges, edges[-1] + darr)


def cov_to_corr(cov):
    """Covariance → correlation matrix (scint_utils.py:234-242)."""
    std = np.sqrt(np.diag(cov))
    outer_std = np.outer(std, std)
    with np.errstate(divide="ignore", invalid="ignore"):
        corr = cov / outer_std
    corr[cov == 0] = 0
    return corr


def mjd_to_year(mjd):
    """MJD → Besselian-style decimal year (scint_utils.py:453-459 role;
    Julian-epoch formula, no astropy)."""
    return 2000.0 + (np.asarray(mjd, dtype=float) - 51544.5) / 365.25


def acor(arr):
    """Characteristic (50%) autocorrelation length
    (scint_utils.py:575-597)."""
    from scipy.signal import correlate

    arr = np.asarray(arr, dtype=float) - np.mean(arr)
    ac = correlate(arr, arr, mode="full")
    ac = ac[ac.size // 2:]
    ac = ac / ac[0]
    idx = np.where(ac < 0.5)[0]
    return int(idx[0]) if len(idx) > 0 else 0


def make_pickle(obj, filepath):
    """Chunked pickle write for >2 GB objects
    (scint_utils.py:797-807)."""
    max_bytes = 2 ** 31 - 1
    bytes_out = pickle.dumps(obj)
    n_bytes = sys.getsizeof(bytes_out)
    with open(filepath, "wb") as f_out:
        for idx in range(0, n_bytes, max_bytes):
            f_out.write(bytes_out[idx:idx + max_bytes])


def load_pickle(filepath):
    """Chunked pickle read (scint_utils.py:878-889)."""
    max_bytes = 2 ** 31 - 1
    input_size = os.path.getsize(filepath)
    bytes_in = bytearray(0)
    with open(filepath, "rb") as f_in:
        for _ in range(0, input_size, max_bytes):
            bytes_in += f_in.read(max_bytes)
    return pickle.loads(bytes_in)


def search_and_replace(filename, search, replace):
    """(scint_utils.py:221-231)"""
    with open(filename, "r") as fh:
        data = fh.read()
    with open(filename, "w") as fh:
        fh.write(data.replace(search, replace))


def slow_FT(dynspec, freqs):
    """DFT along scaled t·(f/fref) paths (scint_utils.py:655-702),
    einsum-vectorised. Reference frequency is the middle of the band.

    Note: the upstream function is unrunnable as published (it passes
    ``axis=`` to np.fft.fftshift at scint_utils.py:679); this is the
    intended computation with that call corrected to ``axes=``."""
    dynspec = np.asarray(dynspec, dtype=np.float64)
    ntime = dynspec.shape[0]
    src = np.arange(ntime, dtype=np.float64)
    freqs = np.asarray(freqs, dtype=np.float64)
    fref = freqs[len(freqs) // 2]
    fscale = freqs / fref
    ft = np.fft.fftfreq(ntime, 1)
    # phase[t, k, f] = -2πi · t·(f/fref) · ft_k
    tscale = src[:, None] * fscale[None, :]
    phase = np.exp(-2j * np.pi * tscale[:, None, :]
                   * ft[None, :, None])
    SS = np.einsum("tf,tkf->kf", dynspec, phase)
    SS = np.fft.fftshift(SS, axes=0)
    SS = np.fft.fft(SS, axis=1)
    return np.fft.fftshift(SS, axes=1)
