"""Structured (JSON-lines) logging for pipelines and surveys.

The reference's observability is print-based ``verbose`` flags and an
``info()`` summary (/root/reference/scintools/dynspec.py:1521-1537,
:4130-4143); results accumulate only into the CSV schema. For
survey-scale runs (thousands of epochs, sharded over a mesh) that is
not greppable or machine-readable, so this module adds a minimal
structured logger:

- ``log_event(event, **fields)`` — one JSON object per line with a
  wall-clock timestamp and the emitting ``pid`` (multi-process survey
  logs stay attributable), to stderr and/or a file;
- ``configure(path=None, echo=True)`` — process-wide sink;
  ``SCINTOOLS_LOG=<path>`` enables file logging from the environment;
- ``span(event, **fields)`` — context manager that logs start/end
  with duration and error status;
- ``reset()`` — clear the in-memory tail and restore the sink to its
  environment defaults (test isolation; tests/conftest.py applies it
  around every test).

The file sink keeps ONE cached append handle (reopened when the path
changes or after a fork) instead of reopening per event — at survey
rates the open/close pair dominated the write. Writes are flushed per
line and serialised under a lock, so records from the prefetch-loader
threads and the journal writer interleave whole-line.

No dependencies; safe to call from pool workers (single-write
appends are atomic enough for JSONL at this scale).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from contextlib import contextmanager


def _env_state():
    return {
        "path": os.environ.get("SCINTOOLS_LOG") or None,
        "echo": bool(int(os.environ.get("SCINTOOLS_LOG_ECHO", "0"))),
    }


_STATE = _env_state()

# cached file-sink handle: {"fh", "path", "pid"} — reopened when the
# configured path changes, on reset(), or when the pid changed (a
# fork must not share the parent's buffered handle position)
_SINK = {"fh": None, "path": None, "pid": None}
_LOCK = threading.Lock()

# in-memory tail of recent events, kept even with no sink configured:
# the robust survey layer reads failure records back for its run
# summary, and a post-mortem can inspect the last events of a run
# that never configured a log file. Bounded, so never a leak.
_RECENT = deque(maxlen=512)


def _close_sink_locked():
    fh = _SINK["fh"]
    _SINK.update(fh=None, path=None, pid=None)
    if fh is not None:
        try:
            fh.close()
        except OSError:  # broad is fine: a failed close of a log
            # handle must never propagate into the survey
            pass


def configure(path=None, echo=None):
    """Set the process-wide log sink. ``path=None`` keeps the current
    file (env ``SCINTOOLS_LOG`` by default); ``echo`` mirrors events
    to stderr. Changing the path closes the cached handle so the next
    event reopens the new file."""
    with _LOCK:
        if path is not None:
            _STATE["path"] = path
            _close_sink_locked()
        if echo is not None:
            _STATE["echo"] = bool(echo)


def reset():
    """Restore the logger to a fresh state: close the cached sink
    handle, clear the in-memory tail, and re-read the environment
    defaults. The per-test isolation hook (tests/conftest.py) — a
    test that filters :func:`recent` sees only its own events."""
    with _LOCK:
        _close_sink_locked()
        _RECENT.clear()
        _STATE.clear()
        _STATE.update(_env_state())


def enabled():
    return bool(_STATE["path"] or _STATE["echo"])


def recent(n=None, event=None):
    """The last ``n`` in-memory event records (all when None),
    optionally filtered by exact event name. Records are kept even
    when no sink is configured."""
    recs = list(_RECENT)
    if event is not None:
        recs = [r for r in recs if r.get("event") == event]
    return recs if n is None else recs[-int(n):]


def log_failure(event="robust.failure", epoch=None, stage=None,
                error=None, tier=None, retry=0, **extra):
    """Structured failure record with the canonical field set the
    robust survey layer emits on every quarantine / fallback-ladder
    transition (docs/robustness.md): epoch id, pipeline stage, error
    class + message, the tier that failed (or None before dispatch),
    and the retry count. ``error`` may be an exception instance or a
    string."""
    fields = {"epoch": epoch, "stage": stage, "tier": tier,
              "retry": int(retry)}
    if error is not None:
        if isinstance(error, BaseException):
            fields["error_class"] = type(error).__name__
            fields["error"] = str(error)[:300]
        else:
            fields["error_class"] = "str"
            fields["error"] = str(error)[:300]
    fields.update(extra)
    log_event(event, **fields)


def _sink_handle_locked():
    """The cached append handle for the configured path, (re)opened
    when the path or pid changed. Caller holds ``_LOCK``."""
    path, pid = _STATE["path"], os.getpid()
    if _SINK["fh"] is None or _SINK["path"] != path \
            or _SINK["pid"] != pid:
        _close_sink_locked()
        _SINK.update(fh=open(path, "a"), path=path, pid=pid)
    return _SINK["fh"]


def log_event(event, **fields):
    """Emit one structured event. Always recorded in the in-memory
    tail (:func:`recent`); written to stderr/file only when a sink is
    configured. Each record is stamped with the emitting ``pid``."""
    rec = {"t": round(time.time(), 3), "pid": os.getpid(),
           "event": event, **fields}
    # lint-ok: lock-discipline: deque.append is atomic under the GIL
    # (single C-level op, bounded maxlen); _LOCK only serialises the
    # file-sink handle, and taking it here would put every event on
    # the survey hot path behind the writer
    _RECENT.append(rec)
    if not enabled():
        return
    line = json.dumps(rec, default=str)
    if _STATE["echo"]:
        print(line, file=sys.stderr)
    if _STATE["path"]:
        try:
            with _LOCK:
                fh = _sink_handle_locked()
                fh.write(line + "\n")
                fh.flush()
        except OSError as e:  # never let logging kill a survey
            print(f"Warning: structured log write failed ({e})",
                  file=sys.stderr)


@contextmanager
def span(event, **fields):
    """Log ``<event>.start`` / ``<event>.end`` around a block, with
    wall-clock duration and error capture (the error propagates)."""
    log_event(event + ".start", **fields)
    t0 = time.perf_counter()
    try:
        yield
    except Exception as e:
        log_event(event + ".end", ok=False, error=repr(e),
                  secs=round(time.perf_counter() - t0, 4), **fields)
        raise
    log_event(event + ".end", ok=True,
              secs=round(time.perf_counter() - t0, 4), **fields)
