"""Structured (JSON-lines) logging for pipelines and surveys.

The reference's observability is print-based ``verbose`` flags and an
``info()`` summary (/root/reference/scintools/dynspec.py:1521-1537,
:4130-4143); results accumulate only into the CSV schema. For
survey-scale runs (thousands of epochs, sharded over a mesh) that is
not greppable or machine-readable, so this module adds a minimal
structured logger:

- ``log_event(event, **fields)`` — one JSON object per line with a
  wall-clock timestamp, to stderr and/or a file;
- ``configure(path=None, echo=True, enabled=None)`` — process-wide
  sink; ``SCINTOOLS_LOG=<path>`` enables file logging from the
  environment;
- ``span(event, **fields)`` — context manager that logs start/end
  with duration and error status.

No dependencies; safe to call from pool workers (line-buffered append
writes are atomic enough for JSONL at this scale).
"""

from __future__ import annotations

import json
import os
import sys
import time
from contextlib import contextmanager

_STATE = {
    "path": os.environ.get("SCINTOOLS_LOG") or None,
    "echo": bool(int(os.environ.get("SCINTOOLS_LOG_ECHO", "0"))),
}


def configure(path=None, echo=None):
    """Set the process-wide log sink. ``path=None`` keeps the current
    file (env ``SCINTOOLS_LOG`` by default); ``echo`` mirrors events
    to stderr."""
    if path is not None:
        _STATE["path"] = path
    if echo is not None:
        _STATE["echo"] = bool(echo)


def enabled():
    return bool(_STATE["path"] or _STATE["echo"])


def log_event(event, **fields):
    """Emit one structured event. No-op unless a sink is configured."""
    if not enabled():
        return
    rec = {"t": round(time.time(), 3), "event": event, **fields}
    line = json.dumps(rec, default=str)
    if _STATE["echo"]:
        print(line, file=sys.stderr)
    if _STATE["path"]:
        try:
            with open(_STATE["path"], "a") as fh:
                fh.write(line + "\n")
        except OSError as e:  # never let logging kill a survey
            print(f"Warning: structured log write failed ({e})",
                  file=sys.stderr)


@contextmanager
def span(event, **fields):
    """Log ``<event>.start`` / ``<event>.end`` around a block, with
    wall-clock duration and error capture (the error propagates)."""
    log_event(event + ".start", **fields)
    t0 = time.perf_counter()
    try:
        yield
    except Exception as e:
        log_event(event + ".end", ok=False, error=repr(e),
                  secs=round(time.perf_counter() - t0, 4), **fields)
        raise
    log_event(event + ".end", ok=True,
              secs=round(time.perf_counter() - t0, 4), **fields)
