"""Structured (JSON-lines) logging for pipelines and surveys.

The reference's observability is print-based ``verbose`` flags and an
``info()`` summary (/root/reference/scintools/dynspec.py:1521-1537,
:4130-4143); results accumulate only into the CSV schema. For
survey-scale runs (thousands of epochs, sharded over a mesh) that is
not greppable or machine-readable, so this module adds a minimal
structured logger:

- ``log_event(event, **fields)`` — one JSON object per line with a
  wall-clock timestamp, to stderr and/or a file;
- ``configure(path=None, echo=True, enabled=None)`` — process-wide
  sink; ``SCINTOOLS_LOG=<path>`` enables file logging from the
  environment;
- ``span(event, **fields)`` — context manager that logs start/end
  with duration and error status.

No dependencies; safe to call from pool workers (line-buffered append
writes are atomic enough for JSONL at this scale).
"""

from __future__ import annotations

import json
import os
import sys
import time
from collections import deque
from contextlib import contextmanager

_STATE = {
    "path": os.environ.get("SCINTOOLS_LOG") or None,
    "echo": bool(int(os.environ.get("SCINTOOLS_LOG_ECHO", "0"))),
}

# in-memory tail of recent events, kept even with no sink configured:
# the robust survey layer reads failure records back for its run
# summary, and a post-mortem can inspect the last events of a run
# that never configured a log file. Bounded, so never a leak.
_RECENT = deque(maxlen=512)


def configure(path=None, echo=None):
    """Set the process-wide log sink. ``path=None`` keeps the current
    file (env ``SCINTOOLS_LOG`` by default); ``echo`` mirrors events
    to stderr."""
    if path is not None:
        _STATE["path"] = path
    if echo is not None:
        _STATE["echo"] = bool(echo)


def enabled():
    return bool(_STATE["path"] or _STATE["echo"])


def recent(n=None, event=None):
    """The last ``n`` in-memory event records (all when None),
    optionally filtered by exact event name. Records are kept even
    when no sink is configured."""
    recs = list(_RECENT)
    if event is not None:
        recs = [r for r in recs if r.get("event") == event]
    return recs if n is None else recs[-int(n):]


def log_failure(event="robust.failure", epoch=None, stage=None,
                error=None, tier=None, retry=0, **extra):
    """Structured failure record with the canonical field set the
    robust survey layer emits on every quarantine / fallback-ladder
    transition (docs/robustness.md): epoch id, pipeline stage, error
    class + message, the tier that failed (or None before dispatch),
    and the retry count. ``error`` may be an exception instance or a
    string."""
    fields = {"epoch": epoch, "stage": stage, "tier": tier,
              "retry": int(retry)}
    if error is not None:
        if isinstance(error, BaseException):
            fields["error_class"] = type(error).__name__
            fields["error"] = str(error)[:300]
        else:
            fields["error_class"] = "str"
            fields["error"] = str(error)[:300]
    fields.update(extra)
    log_event(event, **fields)


def log_event(event, **fields):
    """Emit one structured event. Always recorded in the in-memory
    tail (:func:`recent`); written to stderr/file only when a sink is
    configured."""
    rec = {"t": round(time.time(), 3), "event": event, **fields}
    _RECENT.append(rec)
    if not enabled():
        return
    line = json.dumps(rec, default=str)
    if _STATE["echo"]:
        print(line, file=sys.stderr)
    if _STATE["path"]:
        try:
            with open(_STATE["path"], "a") as fh:
                fh.write(line + "\n")
        except OSError as e:  # never let logging kill a survey
            print(f"Warning: structured log write failed ({e})",
                  file=sys.stderr)


@contextmanager
def span(event, **fields):
    """Log ``<event>.start`` / ``<event>.end`` around a block, with
    wall-clock duration and error capture (the error propagates)."""
    log_event(event + ".start", **fields)
    t0 = time.perf_counter()
    try:
        yield
    except Exception as e:
        log_event(event + ".end", ok=False, error=repr(e),
                  secs=round(time.perf_counter() - t0, 4), **fields)
        raise
    log_event(event + ".end", ok=True,
              secs=round(time.perf_counter() - t0, 4), **fields)
