"""Utilities: ephemerides, orbits, velocities, archive hook, misc
(scint_utils.py re-design)."""

from . import ephemeris, orbit, velocity, misc, archive

__all__ = ["ephemeris", "orbit", "velocity", "misc", "archive"]
