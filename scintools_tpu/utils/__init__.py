"""Utilities: ephemerides, orbits, velocities, archive hook, misc
(scint_utils.py re-design)."""

from . import ephemeris, orbit, velocity, misc, archive, profiling
from .profiling import Timer, timeit_fn

__all__ = ["ephemeris", "orbit", "velocity", "misc", "archive",
           "profiling", "Timer", "timeit_fn"]
