"""Utilities: ephemerides, orbits, velocities, archive hook, misc
(scint_utils.py re-design)."""

from . import ephemeris, orbit, velocity, misc, archive, profiling, slog
from .profiling import Timer, timeit_fn

__all__ = ["ephemeris", "orbit", "velocity", "misc", "archive",
           "profiling", "slog", "Timer", "timeit_fn"]
