"""Self-contained solar-system ephemeris (astropy-free).

Replaces the astropy-based helpers of the reference
(scint_utils.py:286-395). Earth's barycentric position/velocity come
from the JPL approximate Keplerian elements (valid 1800–2050 AD,
"Keplerian Elements for Approximate Positions of the Major Planets"):
the Earth–Moon barycenter orbit plus the Sun's barycentric wobble from
the four giant planets. Accuracy: position ~1e-4 AU (Roemer delay good
to ~0.05 s), velocity ~15 m/s (limited by the neglected Earth–Moon
orbit) — ample for scintillation velocity models where Earth's motion
enters at 30 km/s scale.

Note one deliberate divergence: the reference's ``get_ssb_delay``
builds the pulsar direction by feeding RA/DEC into an *ecliptic* frame
(scint_utils.py:295-297), mixing frames; here the pulsar unit vector is
correctly equatorial.
"""

from __future__ import annotations

import numpy as np

from ..io.parfile import _hms_to_rad, _dms_to_rad
from .orbit import kepler_solve

AU_M = 149597870700.0          # m
C_M_S = 299792458.0            # m/s
DAY_S = 86400.0
OBLIQUITY_DEG = 23.43928

# JPL approximate elements at J2000 + rates per Julian century:
# (a [AU], e, I [deg], L [deg], varpi [deg], Omega [deg]) and rates.
_ELEMENTS = {
    "embary": ((1.00000261, 0.01671123, -0.00001531, 100.46457166,
                102.93768193, 0.0),
               (0.00000562, -0.00004392, -0.01294668, 35999.37244981,
                0.32327364, 0.0)),
    "jupiter": ((5.20288700, 0.04838624, 1.30439695, 34.39644051,
                 14.72847983, 100.47390909),
                (-0.00011607, -0.00013253, -0.00183714, 3034.74612775,
                 0.21252668, 0.20469106)),
    "saturn": ((9.53667594, 0.05386179, 2.48599187, 49.95424423,
                92.59887831, 113.66242448),
               (-0.00125060, -0.00050991, 0.00193609, 1222.49362201,
                -0.41897216, -0.28867794)),
    "uranus": ((19.18916464, 0.04725744, 0.77263783, 313.23810451,
                170.95427630, 74.01692503),
               (-0.00196176, -0.00004397, -0.00242939, 428.48202785,
                0.40805281, 0.04240589)),
    "neptune": ((30.06992276, 0.00859048, 1.77004347, -55.12002969,
                 44.96476227, 131.78422574),
                (0.00026291, 0.00005105, 0.00035372, 218.45945325,
                 -0.32241464, -0.00508664)),
}

# reciprocal masses M_sun/M_planet
_RMASS = {"jupiter": 1047.3486, "saturn": 3497.898,
          "uranus": 22902.98, "neptune": 19412.24}


def _helio_ecliptic(body, T):
    """Heliocentric ecliptic xyz [AU] of ``body`` at Julian centuries
    ``T`` past J2000 (JPL approximate-elements algorithm)."""
    el0, elr = _ELEMENTS[body]
    a = el0[0] + elr[0] * T
    e = el0[1] + elr[1] * T
    I = np.deg2rad(el0[2] + elr[2] * T)
    L = np.deg2rad(el0[3] + elr[3] * T)
    varpi = np.deg2rad(el0[4] + elr[4] * T)
    Omega = np.deg2rad(el0[5] + elr[5] * T)
    omega = varpi - Omega
    M = np.mod(L - varpi + np.pi, 2 * np.pi) - np.pi
    E = kepler_solve(M, e, backend="numpy")
    xp = a * (np.cos(E) - e)
    yp = a * np.sqrt(1 - e ** 2) * np.sin(E)
    co, so = np.cos(omega), np.sin(omega)
    cO, sO = np.cos(Omega), np.sin(Omega)
    cI, sI = np.cos(I), np.sin(I)
    x = (co * cO - so * sO * cI) * xp + (-so * cO - co * sO * cI) * yp
    y = (co * sO + so * cO * cI) * xp + (-so * sO + co * cO * cI) * yp
    z = (so * sI) * xp + (co * sI) * yp
    return np.stack([x, y, z], axis=-1)


def _ecl_to_equ(xyz):
    eps = np.deg2rad(OBLIQUITY_DEG)
    x, y, z = xyz[..., 0], xyz[..., 1], xyz[..., 2]
    return np.stack([x,
                     y * np.cos(eps) - z * np.sin(eps),
                     y * np.sin(eps) + z * np.cos(eps)], axis=-1)


def earth_position_bary(mjd):
    """Barycentric equatorial position of Earth(-Moon barycenter)
    [AU] at MJD (scalar or array)."""
    T = (np.asarray(mjd, dtype=float) - 51544.5) / 36525.0
    r = _helio_ecliptic("embary", T)
    # Sun's barycentric offset from the giant planets
    total = 1.0 + sum(1.0 / m for m in _RMASS.values())
    r_sun = 0.0
    for body, rmass in _RMASS.items():
        r_sun = r_sun - _helio_ecliptic(body, T) / rmass
    r_sun = r_sun / total
    return _ecl_to_equ(r + r_sun)


def earth_velocity_bary(mjd, dt_days=0.25):
    """Barycentric equatorial velocity of Earth [AU/day] by central
    differences of the analytic position."""
    mjd = np.asarray(mjd, dtype=float)
    return ((earth_position_bary(mjd + dt_days)
             - earth_position_bary(mjd - dt_days)) / (2 * dt_days))


def _psr_unit_equatorial(raj, decj):
    ra = raj if isinstance(raj, (int, float)) else _hms_to_rad(raj)
    dec = decj if isinstance(decj, (int, float)) else _dms_to_rad(decj)
    return np.array([np.cos(dec) * np.cos(ra),
                     np.cos(dec) * np.sin(ra),
                     np.sin(dec)]), ra, dec


def get_ssb_delay(mjds, raj, decj, message=False):
    """Roemer delay [s] to the solar-system barycentre
    (scint_utils.py:286-311 role). Positive values should be ADDED to
    site arrival times."""
    psr, _, _ = _psr_unit_equatorial(raj, decj)
    pos = earth_position_bary(np.atleast_1d(mjds))
    t = pos @ psr * AU_M / C_M_S
    if message:
        print("Returned SSB Roemer delays (in seconds) should be "
              "ADDED to site arrival times")
    return np.asarray(t)


def get_earth_velocity(mjds, raj, decj, radial=False):
    """Earth velocity transverse to the line of sight in RA/DEC [km/s]
    (scint_utils.py:349-395)."""
    _, ra, dec = _psr_unit_equatorial(raj, decj)
    v = earth_velocity_bary(np.atleast_1d(mjds))  # AU/day equatorial
    vx, vy, vz = v[..., 0], v[..., 1], v[..., 2]
    vearth_ra = -vx * np.sin(ra) + vy * np.cos(ra)
    vearth_dec = (-vx * np.sin(dec) * np.cos(ra)
                  - vy * np.sin(dec) * np.sin(ra) + vz * np.cos(dec))
    scale = AU_M / 1e3 / DAY_S  # AU/day → km/s
    if radial:
        vearth_r = (vx * np.cos(dec) * np.cos(ra)
                    + vy * np.cos(dec) * np.sin(ra) + vz * np.sin(dec))
        return (vearth_ra * scale).squeeze(), \
            (vearth_dec * scale).squeeze(), (vearth_r * scale).squeeze()
    return (vearth_ra * scale).squeeze(), (vearth_dec * scale).squeeze()


# --------------------------------------------------------------------------
# Galactic-frame helpers (for make_lsr / differential_velocity)
# --------------------------------------------------------------------------

# ICRS → Galactic rotation (IAU 1958 pole/centre, standard matrix)
_ICRS_TO_GAL = np.array([
    [-0.0548755604, -0.8734370902, -0.4838350155],
    [0.4941094279, -0.4448296300, 0.7469822445],
    [-0.8676661490, -0.1980763734, 0.4559837762],
])

# Solar peculiar motion w.r.t. LSR [km/s] in galactic (U, V, W)
_V_SUN_LSR = np.array([11.1, 12.24, 7.25])

KM_PER_KPC = 3.085677581e16
MASYR_TO_KMS_KPC = 4.740470446  # v[km/s] = 4.7405 · mu[mas/yr] · d[kpc]


def icrs_to_galactic(ra, dec):
    """(l, b) radians from equatorial radians."""
    u = np.array([np.cos(dec) * np.cos(ra), np.cos(dec) * np.sin(ra),
                  np.sin(dec)])
    g = _ICRS_TO_GAL @ u
    return np.arctan2(g[1], g[0]) % (2 * np.pi), np.arcsin(g[2])


def make_lsr(d, raj, decj, pmra, pmdec, vr=0):
    """Proper motion corrected to the LSR frame
    (scint_utils.py:314-346 role): μ_LSR = μ + (v☉·ê)/(4.74·d).

    ``vr`` is accepted for signature parity; a pure frame-velocity
    offset changes the returned proper motion only through its
    tangential projection, so the source radial velocity drops out
    (it would only matter for the returned RV, which the reference
    also discards — it returns ``proper_motion`` alone)."""
    _, ra, dec = _psr_unit_equatorial(raj, decj)
    e_ra = np.array([-np.sin(ra), np.cos(ra), 0.0])
    e_dec = np.array([-np.sin(dec) * np.cos(ra),
                      -np.sin(dec) * np.sin(ra), np.cos(dec)])
    v_sun_eq = _ICRS_TO_GAL.T @ _V_SUN_LSR  # galactic → equatorial
    dmu_ra = (v_sun_eq @ e_ra) / (MASYR_TO_KMS_KPC * d)
    dmu_dec = (v_sun_eq @ e_dec) / (MASYR_TO_KMS_KPC * d)
    return np.array([pmra + dmu_ra, pmdec + dmu_dec])


def differential_velocity(params, sun_velocity=220, screen_velocity=220,
                          radius=8):
    """Differential galactic-rotation velocity between screen and Sun
    (scint_utils.py:600-652), assuming flat rotation and circular
    zero-inclination orbits."""
    raj = params["RAJ"]
    decj = params["DECJ"]
    ra = raj.value if hasattr(raj, "value") else raj
    dec = decj.value if hasattr(decj, "value") else decj
    if isinstance(ra, str):
        ra = _hms_to_rad(ra)
        dec = _dms_to_rad(dec)
    s = params["s"].value if hasattr(params["s"], "value") else params["s"]
    d = params["d"].value if hasattr(params["d"], "value") else params["d"]

    gal_l, gal_b = icrs_to_galactic(ra, dec)
    long = 2 * np.pi - gal_l
    dscr = (1 - s) * d
    rscr = np.sqrt(dscr ** 2 + radius ** 2
                   - 2 * dscr * radius * np.cos(long))
    costheta = radius / rscr - dscr * np.cos(long) / rscr
    phi = long + np.arccos(np.clip(costheta, -1, 1))
    vtrans_scr = screen_velocity * np.cos(phi)
    vtrans_sun = sun_velocity * np.cos(long)
    diff_vel = vtrans_scr - vtrans_sun

    # direction of increasing galactic longitude on the sky, in RA/DEC
    gal2 = np.array([gal_l + np.deg2rad(1), gal_b])
    u2 = np.array([np.cos(gal2[1]) * np.cos(gal2[0]),
                   np.cos(gal2[1]) * np.sin(gal2[0]),
                   np.sin(gal2[1])])
    eq2 = _ICRS_TO_GAL.T @ u2
    ra2 = np.arctan2(eq2[1], eq2[0])
    dec2 = np.arcsin(eq2[2])
    angle = np.pi / 2 - np.arctan((dec2 - dec) / (ra2 - ra))
    return diff_vel * np.sin(angle), diff_vel * np.cos(angle)
