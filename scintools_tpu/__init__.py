"""scintools_tpu — TPU-native pulsar-scintillation analysis & simulation.

A brand-new JAX/XLA re-design with the capabilities of scintools
(github.com/danielreardon/scintools): dynamic-spectrum loading and
preprocessing, ACFs and secondary spectra, scintillation-parameter
fitting (least-squares and MCMC), arc-curvature measurement, the θ-θ
transform with phase retrieval and wavefield mosaicking, electromagnetic
simulation, analytic forward models and pulsar velocity models.

Backends: ``numpy`` (default, bit-reproducible) and ``jax`` (TPU).
"""

from .backend import set_default_backend, default_backend, get_xp

__version__ = "0.1.0"

__all__ = [
    "set_default_backend",
    "default_backend",
    "get_xp",
    "Simulation",
]


def __getattr__(name):
    # Lazy imports keep `import scintools_tpu` light.
    try:
        if name in ("Dynspec", "BasicDyn", "MatlabDyn", "SimDyn", "HoloDyn",
                    "sort_dyn", "run_psrflux_survey",
                    "serve_psrflux_survey", "run_wavefield_survey"):
            from . import dynspec as _d
            return getattr(_d, name)
        if name == "Simulation":
            from .sim.simulation import Simulation
            return Simulation
        if name == "ACF":
            from .sim.acf_model import ACF
            return ACF
        if name == "Brightness":
            from .sim.brightness import Brightness
            return Brightness
    except ImportError as e:
        raise AttributeError(
            f"scintools_tpu.{name} unavailable: {e}") from e
    raise AttributeError(f"module 'scintools_tpu' has no attribute {name!r}")
