"""Streaming template-bank arc detection (ISSUE 14 tentpole;
ROADMAP item 5).

The GPU Fourier-domain acceleration-search pipelines
(arXiv:1711.10855, arXiv:1804.05335) reach real-time throughput by
correlating incoming Fourier blocks against a precomputed template
bank with overlap-save convolution. This package is the
scintillation-arc analog, run ONLINE inside the serve daemon:

- :mod:`~scintools_tpu.detect.bank` — the device-resident
  curvature/η template bank: a log-spaced η grid over the
  scenario-factory regime range, templates as normalised parabolic
  matched filters in conjugate-spectrum space, built as one cached
  jitted program per geometry (``detect.bank``);
- :mod:`~scintools_tpu.detect.correlate` — the overlap-save engine:
  each epoch (or 50 %-overlapping time block of a longer one) is
  transformed once through the declared-structure xfft lowering
  (real-input forward, halved-row crop folded — ``detect.correlate``
  formulation, dense oracle kept) and matched against the WHOLE bank
  as one batched FFT + matmul program;
- :mod:`~scintools_tpu.detect.refine` — sub-grid η refinement
  (ISSUE 18): on a trigger, the conjugate spectrum is band-limited
  to the hit template's (f_D, τ) region through the shared
  ``xfft.zoom`` chirp-Z lowering and rescored on a ~16× denser
  LOCAL η grid as one cached program (``detect.refine``) — looking
  harder where the hit is instead of widening the device-resident
  bank; the refined η seeds the θ-θ confirmation window;
- :mod:`~scintools_tpu.detect.trigger` — peak extraction with
  per-template noise-floor normalisation, a significance threshold,
  the guards-pattern per-lane health mask, and the θ-θ confirmation
  entry (the bank prunes the η space; ``fit_thetatheta``'s engine
  runs on hits only);
- :mod:`~scintools_tpu.detect.online` — :class:`ArcDetector`, the
  serve-daemon ``on_published`` hook: ``detect.trigger`` /
  ``detect.confirmed`` events, ``detect_*`` metrics, per-epoch
  ``/state`` annotations and a ``detect`` span on the epoch trace.

docs/detection.md is the operator walkthrough.
"""

from .bank import TemplateBank, build_bank, eta_grid  # noqa: F401
from .correlate import (correlate_bank, correlate_program,  # noqa: F401
                        extract_blocks, time_blocks)
from .online import ArcDetector  # noqa: F401
from .refine import (refine_band, refine_eta,  # noqa: F401
                     refine_program, refine_window)
from .trigger import (calibrate_noise_floor, confirm_eta,  # noqa: F401
                      extract_triggers, trigger_program)
