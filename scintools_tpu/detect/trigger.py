"""Trigger extraction + θ-θ confirmation for bank hits.

The back half of the matched-filter chain (detect/correlate.py feeds
it device-resident scores):

1. **per-template noise-floor normalisation** — each template ``k``
   carries its own measured noise floor ``(µ_k, σ_k)``: at detector
   init, a deterministic batch of pure-noise frames runs through the
   SAME correlation program (:func:`calibrate_noise_floor`) and the
   per-template score mean/std become the floor. This is the matched
   filter's honest significance: window/taper leakage correlates
   sspec pixels differently under wide and narrow templates, so a
   shared analytic σ would over-trigger the wide ones — the measured
   ``σ_k`` absorbs exactly that. ``z_k = (s_k − µ_k)/σ_k``, and the
   correlator's input standardisation makes the calibration
   scale-free (no per-telescope re-tuning).
2. **significance threshold** — a lane triggers when its best
   template clears BOTH the relative threshold (``z ≥ threshold``)
   and an absolute score floor (``s ≥ score_min``, guarding against
   a pathological all-flat score vector where MAD → 0).
3. **guards-pattern health mask** — the correlator's per-lane
   ``ok[B]`` bitmask (robust/guards.py) gates triggering: a lane
   with ``BAD_INPUT``/``BAD_CS`` can NEVER trigger, exactly the
   quarantine semantics of the fused θ-θ search.

Steps 1–3 run as one small cached jitted program (``detect.trigger``
retrace site).

4. **θ-θ confirmation** (:func:`confirm_eta`) — the bank is a
   PRUNER: a hit hands its coarse ``η_bank`` to the existing
   high-precision θ-θ machinery (thth/search.py — the same engine
   ``Dynspec.fit_thetatheta`` drives) over a narrow η window around
   the hit. θ-θ runs on HITS only, not every epoch, which is what
   makes in-daemon detection affordable; the confirmation program is
   geometry-keyed (η values are traced), so a stream of hits at
   different curvatures reuses one compiled program.
"""

from __future__ import annotations

import numpy as np

from ..backend import get_jax

#: defaults calibrated on the scenario-factory closed loop
#: (tests/test_detect.py): against the measured per-template noise
#: floor, pure-noise epochs peak at z ≈ 3 across a 48-template bank
#: while factory arcs score z ≳ 20 (score ≳ 35 raw).
DEFAULT_THRESHOLD = 7.0
DEFAULT_SCORE_MIN = 8.0

#: noise-calibration batch: enough frames that σ_k is stable to
#: ~±12 %, cheap enough to run at detector init (one batched
#: correlate program call per geometry).
DEFAULT_CAL_FRAMES = 32


def calibrate_noise_floor(bank, *, n_frames=DEFAULT_CAL_FRAMES,
                          seed=0, variant=None, window="hanning",
                          window_frac=0.1):
    """Measure each template's noise floor ``(µ_k[K], σ_k[K])`` by
    running a deterministic batch of pure-noise frames through the
    SAME correlation program real epochs take. The correlator
    standardises its input, so the floor is scale-free — one
    calibration per geometry, reused for the life of the process."""
    from .correlate import correlate_bank

    rng = np.random.default_rng(seed)
    nf, nt = bank.geometry[0], bank.geometry[1]
    frames = rng.standard_normal(
        (int(n_frames), nf, nt)).astype(np.float32)
    scores, _ = correlate_bank(frames, bank, variant=variant,
                               window=window,
                               window_frac=window_frac)
    s = np.asarray(scores)
    mu = s.mean(axis=0)
    sigma = np.maximum(s.std(axis=0), 0.5)   # degenerate-σ guard
    return mu.astype(np.float32), sigma.astype(np.float32)


_TRIGGER_CACHE = {}

_MAX_CACHED = 16


def trigger_program(n_batch, n_templates, *, threshold=None,
                    score_min=None):
    """Cached jitted peak extraction ``fn(scores[B, K], ok[B],
    mu[K], sigma[K]) → (z[B, K], best[B] int32, score_best[B],
    z_best[B], hit[B])`` — site ``detect.trigger``. ``mu``/``sigma``
    are the measured per-template noise floor
    (:func:`calibrate_noise_floor`); they ride as traced arguments so
    a re-calibration never retraces."""
    threshold = DEFAULT_THRESHOLD if threshold is None \
        else float(threshold)
    score_min = DEFAULT_SCORE_MIN if score_min is None \
        else float(score_min)
    key = (int(n_batch), int(n_templates), threshold, score_min)
    fn = _TRIGGER_CACHE.get(key)
    if fn is None:
        from ..obs import retrace as _retrace

        _retrace.record_build("detect.trigger", key)
        jax = get_jax()
        import jax.numpy as jnp

        def run(scores, ok, mu, sigma):
            z = (scores - mu[None]) / sigma[None]
            best = jnp.argmax(z, axis=1).astype(jnp.int32)
            z_best = jnp.take_along_axis(
                z, best[:, None], axis=1)[:, 0]
            s_best = jnp.take_along_axis(
                scores, best[:, None], axis=1)[:, 0]
            hit = ((z_best >= jnp.float32(threshold))
                   & (s_best >= jnp.float32(score_min))
                   & (ok == 0))
            return z, best, s_best, z_best, hit

        fn = jax.jit(run)
        if len(_TRIGGER_CACHE) >= _MAX_CACHED:
            _TRIGGER_CACHE.pop(next(iter(_TRIGGER_CACHE)))
        _TRIGGER_CACHE[key] = fn
    return fn


def extract_triggers(scores, ok, etas, *, noise_floor=None,
                     threshold=None, score_min=None):
    """Run the trigger program on a (device or host) score stack and
    unpack per-lane host dicts.

    ``noise_floor`` is the measured ``(µ[K], σ[K])`` pair
    (:func:`calibrate_noise_floor`); without one, scores are already
    ~unit-variance by construction and ``(0, 1)`` is used. Returns a
    list of ``{"hit", "eta_bank", "z", "score", "ok", "template"}``
    — ``eta_bank`` is the best template's curvature (NaN for
    unhealthy lanes, which can never hit)."""
    import jax.numpy as jnp

    scores_d = jnp.asarray(scores)
    ok_d = jnp.asarray(ok, dtype=jnp.int32)
    B, K = scores_d.shape
    if noise_floor is None:
        mu = jnp.zeros((K,), dtype=jnp.float32)
        sigma = jnp.ones((K,), dtype=jnp.float32)
    else:
        mu = jnp.asarray(noise_floor[0], dtype=jnp.float32)
        sigma = jnp.asarray(noise_floor[1], dtype=jnp.float32)
    fn = trigger_program(B, K, threshold=threshold,
                         score_min=score_min)
    z, best, s_best, z_best, hit = fn(scores_d, ok_d, mu, sigma)
    best = np.asarray(best)
    s_best = np.asarray(s_best)
    z_best = np.asarray(z_best)
    hit = np.asarray(hit)
    ok_h = np.asarray(ok_d)
    etas = np.asarray(etas, dtype=float)
    out = []
    for b in range(B):
        healthy = int(ok_h[b]) == 0
        out.append({
            "hit": bool(hit[b]),
            "eta_bank": float(etas[best[b]]) if healthy else
            float("nan"),
            "z": float(z_best[b]),
            "score": float(s_best[b]),
            "ok": int(ok_h[b]),
            "template": int(best[b]),
        })
    return out


def confirm_eta(dyn, freqs, times, eta_seed, *, window=2.5,
                n_eta=31, npad=1, n_edges=96, fw=0.2,
                backend="jax", eta_edges=None):
    """High-precision confirmation of one bank hit: a θ-θ eigenvalue
    search (thth/search.py:single_search — the ``fit_thetatheta``
    engine) over the PRUNED η window ``[η_seed/window,
    η_seed·window]``.

    ``eta_seed`` centres the η search window. Seed with the
    SUB-GRID refined η (detect/refine.py) when available: windows
    sized from the bank-grid η are ~2× biased near the 2η harmonic —
    an off-centre window whose upper edge grazes 2η lets the
    harmonic's rising eigen curve drag the parabola vertex
    (regression-pinned in tests/test_detect.py); a refined-centred
    window starts tight on truth.

    The θ edges are sized for the window's largest curvature
    (``η·θ² < τ_max`` and ``|θ| < f_D,max/2`` — the
    ``thth.search.chunk_geometry`` rule): sizing them for the whole
    BANK range instead measurably biases the peak (the θ-θ map then
    under-resolves small-η arcs). ``eta_edges`` (default: the seed)
    pins the edge sizing to a DISCRETE η — pass the hit's bank
    template η when seeding with a continuous refined value, so the
    geometry-keyed θ-θ program cache stays bounded by the bank size
    (the η grid itself is traced and free to move per hit; in steady
    state a source's hits cluster on one template and reuse one
    program).

    Returns the :class:`~scintools_tpu.thth.search.ChunkSearchResult`
    — its ``eta``/``eta_sig`` are the confirmed measurement, its
    ``ok`` health code follows the guards convention, and a refused
    fit (NaN η) means the hit did NOT confirm. θ-θ assumes an
    effectively 1-D (anisotropic) screen; on isotropic epochs the
    eigenvalue curve has no sharp peak and confirmation drifts — the
    bank trigger still localises η, the confirmation gate is what
    becomes loose (docs/detection.md)."""
    from ..thth.core import fft_axis
    from ..thth.search import single_search

    freqs = np.asarray(freqs, dtype=float)
    times = np.asarray(times, dtype=float)
    etas = np.geomspace(float(eta_seed) / window,
                        float(eta_seed) * window, int(n_eta))
    fd = fft_axis(times, pad=npad, scale=1e3)
    tau = fft_axis(freqs, pad=npad, scale=1.0)
    eta_edge_max = float(eta_edges) * window \
        if eta_edges is not None else etas.max()
    th_lim = 0.95 * min(np.sqrt(tau.max() / eta_edge_max),
                        fd.max() / 2)
    edges = np.linspace(-th_lim, th_lim, int(n_edges))
    return single_search(np.asarray(dyn), freqs, times, etas, edges,
                         fw=fw, npad=npad, backend=backend)


# ---------------------------------------------------------------------
# abstract program probe (obs/programs.py) — JP2xx audited
# ---------------------------------------------------------------------

from ..obs.programs import register_probe as _register_probe  # noqa: E402


@_register_probe("detect.trigger")
def _probe_trigger():
    """The peak-extraction/normalisation program at 2 lanes × 4
    templates, default thresholds."""
    import jax

    fn = trigger_program(2, 4)
    S = jax.ShapeDtypeStruct
    return fn, (S((2, 4), np.float32), S((2,), np.int32),
                S((4,), np.float32), S((4,), np.float32))
