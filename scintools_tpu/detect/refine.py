"""Sub-grid η refinement: zoom in on a trigger instead of widening
the bank (ISSUE 18 tentpole; ROADMAP item 4).

The template bank (detect/bank.py) is a log-spaced PRUNER — its grid
step (~7 % for the default 48-template span) is the η resolution of a
raw trigger, and the only pre-zoom way to sharpen it was to widen the
device-resident bank (16× the templates for 16× the resolution, paid
on EVERY epoch). This module looks harder only where the hit is: on a
trigger it band-limits the conjugate-spectrum transform to the hit
template's (f_D, τ) region through the shared ``xfft.zoom`` chirp-Z
lowering (ops/xfft.py — only the band pixels are ever computed) and
rescores parabola templates on a ~16× denser LOCAL η grid — ±4
bank-grid steps around the trigger η — as ONE cached jitted program
(``detect.refine`` site).

Everything that varies per hit is TRACED — the band edges and the η
grid — so a stream of triggers at different curvatures reuses one
compiled program per geometry (zero steady retraces, pinned in
tests/test_detect.py). The refined sub-grid η then seeds the θ-θ
``confirm_eta`` window (detect/trigger.py): windows sized from the
bank-grid η were ~2× biased near the 2η harmonic, and a confirmation
window centred on the refined estimate starts tight on truth
(regression-pinned against the scenario factory's closed-form
truths).

The matched-filter recipe deliberately mirrors the correlator
(detect/correlate.py): dB relative to the frame peak, robust
median/MAD standardisation over the valid region, zero-mean
unit-norm Gaussian-band parabola templates (detect/bank.py width
law) — a refined score is comparable to the bank score that
triggered it.
"""

from __future__ import annotations

import numpy as np

from ..backend import formulation, get_jax
from ..ops.sspec import fft_shapes, sspec_axes, zoom_band

#: default local η grid: 129 points across ±4 bank grid steps is
#: ~16× the bank's η density (the bank prunes, the zoom refines).
#: Measured on the factory recall set: ±4 steps is wide enough that
#: an off-by-a-few-templates trigger still reaches its true local
#: peak, and the refined η is strictly tighter than the bank grid on
#: >90 % of closed-form truths (tests/test_detect.py).
DEFAULT_N_ETA = 129

#: default refinement window half-width, in bank grid steps
DEFAULT_SPAN_STEPS = 4

# keyed program cache (the JL101 per-call wrapper trap): one compiled
# refinement program per (geometry, zoom frame, n_eta, variant); the
# band edges and η grid ride as traced arguments, so a trigger stream
# at different curvatures never retraces.
_REFINE_CACHE = {}

_MAX_CACHED = 8


def refine_program(nf, nt, dt, df, *, n_eta=DEFAULT_N_ETA, n_r=None,
                   n_c=None, tau_min=None, fd_min=None, sigma0=1.0,
                   rel_width=0.1, variant=None, window="hanning",
                   window_frac=0.1):
    """Cached jitted sub-grid refinement
    ``fn(dyn[nf, nt], band_r[2], band_c[2], etas[n_eta]) →
    scores[n_eta]`` — one compile per geometry, site
    ``detect.refine``.

    ``band_r``/``band_c`` are (f0, f1) band edges in (fractional,
    signed) bin units of the padded sspec frame
    (:func:`~scintools_tpu.ops.sspec.zoom_band` converts physical
    µs/mHz windows) — TRACED, like the η grid. Inside the program:
    band-limited secondary-spectrum power on the ``n_r × n_c`` zoom
    frame (the shared 'xfft.zoom' chirp-Z lowering; ``variant``
    czt|dense), correlator-recipe standardisation, and bank-recipe
    parabola templates evaluated on the traced zoomed (τ, f_D) axes
    with the NATIVE width law (``sigma0·Δτ_native + rel_width·arc``,
    so refined scores stay comparable to bank scores).
    """
    if variant is None:
        variant = formulation("xfft.zoom")
    nrfft, ncfft = fft_shapes(nf, nt)
    fdop, tdel, _ = sspec_axes(nf, nt, dt, df, halve=True)
    if n_r is None:
        n_r = nrfft // 4
    if n_c is None:
        n_c = ncfft // 4
    if tau_min is None:
        tau_min = float(tdel[1])
    if fd_min is None:
        fd_min = 1.5 * float(fdop[1] - fdop[0])
    key = (int(nf), int(nt), float(dt), float(df), int(n_eta),
           int(n_r), int(n_c), float(tau_min), float(fd_min),
           float(sigma0), float(rel_width), variant, window,
           float(window_frac))
    fn = _REFINE_CACHE.get(key)
    if fn is None:
        from ..obs import retrace as _retrace

        _retrace.record_build("detect.refine", key)
        jax = get_jax()
        import jax.numpy as jnp

        from ..ops.sspec import secondary_spectrum_power
        from ..ops.windows import get_window

        wins = None
        if window is not None:
            wins = get_window(int(nt), int(nf), window=window,
                              frac=window_frac)
        nr, nc = int(n_r), int(n_c)
        dtau = float(tdel[1] - tdel[0])     # NATIVE delay bin width
        tau_scale = 1.0 / (nrfft * df)      # bin → µs (sspec_axes)
        fd_scale = 1e3 / (ncfft * dt)       # bin → mHz (sspec_axes)

        def run(dyn, band_r, band_c, etas):
            sec = secondary_spectrum_power(
                dyn.astype(jnp.float32), window_arrays=wins,
                backend="jax", variant=variant,
                zoom=((band_r[0], band_r[1], nr),
                      (band_c[0], band_c[1], nc)))
            # traced physical axes of the zoom frame
            r = band_r[0] + (band_r[1] - band_r[0]) / nr \
                * jnp.arange(nr)
            c = band_c[0] + (band_c[1] - band_c[0]) / nc \
                * jnp.arange(nc)
            tau_z = r * jnp.float32(tau_scale)
            fd_z = c * jnp.float32(fd_scale)
            valid = ((tau_z[:, None] >= tau_min)
                     & (jnp.abs(fd_z)[None, :] >= fd_min)
                     ).astype(jnp.float32)
            n_valid = jnp.maximum(jnp.sum(valid), jnp.float32(1.0))
            # correlator-recipe input standardisation
            smax = jnp.max(sec)
            smax = jnp.where(smax > 0, smax, jnp.float32(1.0))
            x = 10.0 * jnp.log10(sec / smax + jnp.float32(1e-12))
            xv = jnp.where(valid > 0, x, jnp.nan)
            med = jnp.nanmedian(xv)
            mad = jnp.nanmedian(jnp.abs(xv - med))
            xhat = (x - med) / (jnp.float32(1.4826) * mad
                                + jnp.float32(1e-6))
            xhat = xhat * valid
            # bank-recipe templates on the traced zoomed axes
            arc = etas[:, None, None] * fd_z[None, None, :] ** 2
            sig = sigma0 * dtau + jnp.float32(rel_width) * arc
            w = jnp.exp(-0.5 * ((tau_z[None, :, None] - arc)
                                / sig) ** 2)
            w = w * valid[None]
            mu = (jnp.sum(w, axis=(1, 2), keepdims=True) / n_valid)
            t = (w - mu) * valid[None]
            nrm = jnp.sqrt(jnp.sum(t * t, axis=(1, 2),
                                   keepdims=True))
            t = t / jnp.maximum(nrm, jnp.float32(1e-20))
            return jnp.sum(t * xhat[None], axis=(1, 2))

        fn = jax.jit(run)
        if len(_REFINE_CACHE) >= _MAX_CACHED:
            _REFINE_CACHE.pop(next(iter(_REFINE_CACHE)))
        _REFINE_CACHE[key] = fn
    return fn


def refine_window(bank, eta_bank, span=None):
    """The local refinement η window ``(eta_lo, eta_hi)``:
    ``DEFAULT_SPAN_STEPS`` bank grid-step ratios around the trigger
    template (``span`` overrides the total ratio). Wider than the
    bank's half-step quantisation on purpose: on self-noise-heavy
    epochs the bank's best template can sit a few steps off the true
    local peak, and a one-step window would clip the refined η at
    its edge instead of reaching it."""
    etas = np.asarray(bank.etas, dtype=float)
    if span is None:
        step = (etas[-1] / etas[0]) ** (1.0 / max(len(etas) - 1, 1))
        span = step ** DEFAULT_SPAN_STEPS
    span = float(span)
    return float(eta_bank) / span, float(eta_bank) * span


def refine_band(bank, eta_lo, eta_hi):
    """Physical ``(tdel_band [µs], fdop_band [mHz])`` window covering
    every arc ``τ = η·f_D²`` with η ∈ [eta_lo, eta_hi] inside the
    bank's sspec frame: Doppler out to where the SHALLOWEST arc
    leaves the top of the frame, delay up to where the STEEPEST arc
    sits at that Doppler limit."""
    tau_max = float(bank.tdel[-1])
    fd_max = float(bank.fdop[-1])
    fd_lim = min(fd_max, float(np.sqrt(tau_max / eta_lo)))
    tau_hi = min(tau_max, float(eta_hi) * fd_lim ** 2)
    return (0.0, tau_hi), (-fd_lim, fd_lim)


def refine_eta(dyn, bank, eta_bank, *, n_eta=DEFAULT_N_ETA, span=None,
               variant=None, window="hanning", window_frac=0.1):
    """Refine a trigger's η below the bank grid: zoom the conjugate
    spectrum into the hit's (f_D, τ) band and rescore a ~16×-denser
    local η grid as one cached program, then parabola-interpolate the
    score peak in log η (sub-GRID, not just sub-step).

    ``dyn[nf, nt]`` — the triggering frame (bank geometry);
    ``eta_bank`` — the best bank template's η. Returns a dict:
    ``eta_refined`` (s³), ``eta_lo``/``eta_hi`` (the local window),
    ``etas``/``scores`` (the local grid, host arrays), ``band``
    (physical (τ, f_D) zoom window). All per-hit variation is traced
    — repeated calls at any curvature reuse one compiled program.
    """
    import jax.numpy as jnp

    nf, nt, dt, df = bank.geometry
    eta_lo, eta_hi = refine_window(bank, eta_bank, span=span)
    etas = np.geomspace(eta_lo, eta_hi, int(n_eta))
    tdel_band, fdop_band = refine_band(bank, eta_lo, eta_hi)
    nrfft, ncfft = fft_shapes(nf, nt)
    # the zoom frame: quarter-resolution COUNTS concentrated inside
    # the local band — denser than the native grid there, ~4× fewer
    # pixels than the bank's cropped frame (measured: equally tight
    # refined η at a quarter of the rescoring FLOPs)
    n_r, n_c = nrfft // 4, ncfft // 4
    band_r, band_c = zoom_band(nf, nt, dt, df, tdel_band, fdop_band,
                               n_r, n_c)
    fn = refine_program(
        nf, nt, dt, df, n_eta=int(n_eta), n_r=n_r, n_c=n_c,
        tau_min=bank.params["tau_min"], fd_min=bank.params["fd_min"],
        sigma0=bank.params["sigma0"],
        rel_width=bank.params["rel_width"], variant=variant,
        window=window, window_frac=window_frac)
    # lint-ok: syncpoints: consumption boundary — the vertex interp
    # and the confirm-stage seeding need host scalars this call
    scores = np.asarray(fn(
        jnp.asarray(dyn, dtype=jnp.float32),
        jnp.asarray(band_r[:2], dtype=jnp.float32),
        jnp.asarray(band_c[:2], dtype=jnp.float32),
        jnp.asarray(etas, dtype=jnp.float32)))
    i = int(np.argmax(scores))
    eta_refined = float(etas[i])
    if 0 < i < len(etas) - 1:
        # parabolic vertex on the uniform log-η grid
        num = scores[i - 1] - scores[i + 1]
        den = scores[i - 1] - 2.0 * scores[i] + scores[i + 1]
        if den < 0:
            step = np.log(etas[1] / etas[0])
            off = float(np.clip(0.5 * num / den, -0.5, 0.5))
            eta_refined = float(np.exp(np.log(etas[i]) + off * step))
    return {"eta_refined": eta_refined, "eta_lo": eta_lo,
            "eta_hi": eta_hi, "etas": etas, "scores": scores,
            "band": {"tdel": list(tdel_band),
                     "fdop": list(fdop_band)},
            "score": float(scores[i])}


# ---------------------------------------------------------------------
# abstract program probe (obs/programs.py) — JP2xx audited; the
# 'xfft.zoom' formulation enters the fingerprint, so a silent
# czt↔dense flip of the refinement transform fails JP205
# ---------------------------------------------------------------------

from ..obs.programs import register_probe as _register_probe  # noqa: E402


@_register_probe("detect.refine", formulations=("xfft.zoom",))
def _probe_refine():
    """The sub-grid refinement program at a fixed 12×10 epoch
    geometry, 8×8 zoom frame, 5-point local η grid (band edges and
    η grid traced — a trigger stream never retraces)."""
    import jax

    fn = refine_program(12, 10, 2.0, 0.05, n_eta=5, n_r=8, n_c=8)
    S = jax.ShapeDtypeStruct
    return fn, (S((12, 10), np.float32), S((2,), np.float32),
                S((2,), np.float32), S((5,), np.float32))
