"""Device-resident curvature/η template bank.

The GPU Fourier-domain acceleration searches (Dimoudi et al.
arXiv:1711.10855; Adámek & Armour arXiv:1804.05335) hold a
precomputed bank of matched-filter templates on device and correlate
every incoming Fourier block against the WHOLE bank at once. The
scintillation analog: an arc of curvature η is the parabolic ridge
``τ = η·f_D²`` in conjugate-spectrum space, so a template is a
normalised parabolic band mask over the halved secondary-spectrum
frame (positive delays τ, fftshifted Doppler f_D — exactly the frame
``ops/sspec.py:secondary_spectrum_power`` emits), and the bank is a
log-spaced η grid covering the scenario-factory regime range
(sim/scenario.py:scenario_truths η values; ROADMAP item 5).

Template construction, per η:

- a Gaussian band around the parabola, width
  ``σ(f_D) = σ₀·Δτ + rel_width·η·f_D²`` — the relative term keeps
  adjacent log-grid templates overlapping as the arc steepens, the
  absolute term keeps the band at least one delay bin wide;
- both Doppler arms (the band depends on ``f_D²``);
- a **validity mask** excluding the zero-Doppler column(s) and the
  zero-delay row(s): the DC ridge carries power in every epoch and
  would light every template;
- zero mean over the valid region and unit L2 norm, so a template is
  a CONTRAST filter: flat (noise-floor) spectra score ~0, and under
  the correlator's standardised input a score is directly a
  significance (detect/correlate.py, detect/trigger.py).

The whole bank builds as ONE cached jitted device program
(``detect.bank`` retrace site, probed by obs/programs.py) keyed on
the epoch geometry — the daemon pays it once per geometry, never per
epoch — and the resulting ``T[K, R·C]`` matrix stays device-resident
for the life of the process (the matched-filter matmul operand).
"""

from __future__ import annotations

import numpy as np

from ..backend import get_jax
from ..ops.sspec import fft_shapes, sspec_axes

#: default η span factor around the scenario-factory regime range —
#: the bank is a PRUNER, not a fitter: it only has to land within the
#: θ-θ confirmation window (detect/trigger.py:confirm_eta) of truth.
DEFAULT_N_TEMPLATES = 48


class TemplateBank:
    """One geometry's template bank: the η grid, the device-resident
    template matrix, and the frame bookkeeping the correlator needs.

    ``templates`` is ``f32[K, R·C]`` (flattened halved-sspec frame,
    raw delay rows × fftshifted Doppler columns), zero-mean over the
    valid region and unit-norm per row. ``valid`` is ``f32[R·C]``
    (1.0 = pixel participates in scoring). Instances are cheap
    descriptors over cached device arrays — build through
    :func:`build_bank`, which caches per geometry."""

    __slots__ = ("etas", "templates", "valid", "tdel", "fdop",
                 "shape", "geometry", "params")

    def __init__(self, etas, templates, valid, tdel, fdop, shape,
                 geometry, params):
        self.etas = etas                    # host f64 [K]
        self.templates = templates          # device f32 [K, P]
        self.valid = valid                  # device f32 [P]
        self.tdel = tdel                    # host f64 [R] (µs)
        self.fdop = fdop                    # host f64 [C] (mHz)
        self.shape = shape                  # (R, C) sspec frame
        self.geometry = geometry            # (nf, nt, dt, df)
        self.params = params                # build knobs (JSON-able)

    @property
    def n_templates(self):
        return len(self.etas)

    @property
    def n_pixels(self):
        return int(self.shape[0] * self.shape[1])

    def describe(self):
        """JSON-able view for reports/telemetry/bench records."""
        return {
            "n_templates": int(self.n_templates),
            "eta_range": [float(self.etas[0]), float(self.etas[-1])],
            "frame": list(self.shape),
            "geometry": {"nf": self.geometry[0],
                         "nt": self.geometry[1],
                         "dt": self.geometry[2],
                         "df": self.geometry[3]},
            **self.params,
        }


def eta_grid(eta_min, eta_max, n=DEFAULT_N_TEMPLATES):
    """Log-spaced curvature grid [s³ ≡ µs/mHz² on the sspec axes] —
    log spacing matches the templates' relative band width, so bank
    resolution is a constant factor across the whole range."""
    if not (0 < eta_min < eta_max):
        raise ValueError(f"need 0 < eta_min < eta_max, got "
                         f"({eta_min}, {eta_max})")
    return np.geomspace(float(eta_min), float(eta_max), int(n))


# keyed program cache (the JL101 per-call wrapper trap): one compiled
# bank-builder program per sspec frame + width parameters; the η grid
# rides as a traced argument so re-spanning the bank never retraces.
_BANK_PROGRAM_CACHE = {}

_MAX_CACHED = 8


def _bank_program(tdel, fdop, tau_min, fd_min, sigma0, rel_width):
    key = (tdel.tobytes(), fdop.tobytes(), float(tau_min),
           float(fd_min), float(sigma0), float(rel_width))
    fn = _BANK_PROGRAM_CACHE.get(key)
    if fn is None:
        from ..obs import retrace as _retrace

        _retrace.record_build("detect.bank", key)
        jax = get_jax()
        import jax.numpy as jnp

        tdel32 = jnp.asarray(tdel, dtype=jnp.float32)
        fdop32 = jnp.asarray(fdop, dtype=jnp.float32)
        dtau = float(tdel[1] - tdel[0])
        valid2d = ((np.abs(fdop)[None, :] >= fd_min)
                   & (tdel[:, None] >= tau_min)).astype(np.float32)
        valid_c = jnp.asarray(valid2d)
        n_valid = float(valid2d.sum())

        def build(etas):
            # arc band: |τ − η·f_D²| against a widening Gaussian
            arc = etas[:, None, None] * fdop32[None, None, :] ** 2
            sig = (sigma0 * dtau
                   + jnp.float32(rel_width) * arc)
            w = jnp.exp(-0.5 * ((tdel32[None, :, None] - arc)
                                / sig) ** 2)
            w = w * valid_c[None]
            # contrast filter: zero mean over the valid region …
            mu = (jnp.sum(w, axis=(1, 2), keepdims=True)
                  / jnp.float32(n_valid))
            t = (w - mu) * valid_c[None]
            # … and unit L2 norm per template
            nrm = jnp.sqrt(jnp.sum(t * t, axis=(1, 2),
                                   keepdims=True))
            t = t / jnp.maximum(nrm, jnp.float32(1e-20))
            return t.reshape(t.shape[0], -1)

        fn = jax.jit(build)
        if len(_BANK_PROGRAM_CACHE) >= _MAX_CACHED:
            _BANK_PROGRAM_CACHE.pop(next(iter(_BANK_PROGRAM_CACHE)))
        _BANK_PROGRAM_CACHE[key] = fn
    return fn


_BANK_CACHE = {}


def build_bank(nf, nt, dt, df, eta_min, eta_max,
               n_templates=DEFAULT_N_TEMPLATES, tau_min=None,
               fd_min=None, sigma0=1.0, rel_width=0.1):
    """Build (or return the cached) :class:`TemplateBank` for one
    epoch geometry.

    ``nf, nt`` — dynspec shape (frequency channels × time subints);
    ``dt`` [s] / ``df`` [MHz] — axis spacings (they set the sspec
    τ/f_D axes via :func:`~scintools_tpu.ops.sspec.sspec_axes`);
    ``eta_min, eta_max`` [s³] — the log η span;
    ``tau_min`` [µs] / ``fd_min`` [mHz] — DC exclusions (defaults:
    one delay bin, 1.5 Doppler bins); ``sigma0``/``rel_width`` — the
    band width law (module docstring).

    Banks are cached per full parameter set; templates land on device
    once and are reused by every correlation program.
    """
    nrfft, ncfft = fft_shapes(nf, nt)
    fdop, tdel, _ = sspec_axes(nf, nt, dt, df, halve=True)
    if tau_min is None:
        tau_min = float(tdel[1])            # exclude the τ=0 row
    if fd_min is None:
        fd_min = 1.5 * float(fdop[1] - fdop[0])
    etas = eta_grid(eta_min, eta_max, n_templates)
    key = (int(nf), int(nt), float(dt), float(df), etas.tobytes(),
           float(tau_min), float(fd_min), float(sigma0),
           float(rel_width))
    bank = _BANK_CACHE.get(key)
    if bank is not None:
        return bank

    import jax.numpy as jnp

    fn = _bank_program(tdel, fdop, tau_min, fd_min, sigma0,
                       rel_width)
    T = fn(jnp.asarray(etas, dtype=jnp.float32))
    valid2d = ((np.abs(fdop)[None, :] >= fd_min)
               & (tdel[:, None] >= tau_min)).astype(np.float32)
    bank = TemplateBank(
        etas=etas, templates=T,
        valid=jnp.asarray(valid2d.ravel()),
        tdel=tdel, fdop=fdop, shape=(nrfft // 2, ncfft),
        geometry=(int(nf), int(nt), float(dt), float(df)),
        params={"tau_min": float(tau_min), "fd_min": float(fd_min),
                "sigma0": float(sigma0),
                "rel_width": float(rel_width)})
    if len(_BANK_CACHE) >= _MAX_CACHED:
        _BANK_CACHE.pop(next(iter(_BANK_CACHE)))
    _BANK_CACHE[key] = bank
    return bank


# ---------------------------------------------------------------------
# abstract program probe (obs/programs.py) — audited by the jaxlint
# JP2xx program pass; a silent change to the bank construction
# program fails JP205 with a readable primitive diff
# ---------------------------------------------------------------------

from ..obs.programs import register_probe as _register_probe  # noqa: E402


@_register_probe("detect.bank")
def _probe_bank():
    """The template-bank builder at a fixed 12×10 epoch geometry,
    4 templates (η grid traced — re-spanning never retraces)."""
    import jax

    nrfft, ncfft = fft_shapes(12, 10)
    fdop, tdel, _ = sspec_axes(12, 10, 2.0, 0.05, halve=True)
    fn = _bank_program(tdel, fdop, float(tdel[1]),
                       1.5 * float(fdop[1] - fdop[0]), 1.0, 0.1)
    S = jax.ShapeDtypeStruct
    return fn, (S((4,), np.float32),)
