"""Online arc detection inside the serve daemon.

:class:`ArcDetector` is the per-epoch detection hook ROADMAP item 5
asks for: every epoch the daemon publishes is scanned against the
device-resident template bank within the ingest→publish latency
budget, bank hits escalate to the θ-θ confirmation stage, and the
whole chain is observable — ``detect.trigger`` / ``detect.confirmed``
slog events, ``detect_*`` metrics on ``/metrics``, per-epoch
``detect`` annotations and trigger counts on ``/state``, and a
``detect`` span on each epoch's trace.

Wiring (docs/detection.md):

    det = ArcDetector(nf=64, nt=128, dt=30.0, df=1.1,
                      eta_range=(1e-3, 3e-2))
    svc = SurveyService(source, process, workdir)
    svc.add_on_published(det.make_hook(extract=lambda p, out: p))
    svc.start()

The hook runs in the daemon's loop thread AFTER the epoch's result
is journaled (the ``on_published`` hook point, serve/daemon.py), so
a slow confirmation can never delay that epoch's publish — it only
back-pressures the stream, which the backlog gauge and the
``arc_detect`` bench config measure (in-daemon detection holds
ingest→publish p95 within 2× the no-detection baseline at the
``survey_service`` arrival cadence).
"""

from __future__ import annotations

import time

import numpy as np

from ..obs import metrics as _metrics
from ..utils import slog
from .bank import DEFAULT_N_TEMPLATES, build_bank
from .correlate import correlate_bank, extract_blocks
from .refine import DEFAULT_N_ETA, refine_eta
from .trigger import (calibrate_noise_floor, confirm_eta,
                      extract_triggers)


class ArcDetector:
    """Streaming template-bank arc detector for one epoch geometry.

    ``nf, nt`` — the bank frame (frequency channels × time subints);
    epochs longer in time are cut into 50 %-overlap-save blocks.
    ``dt`` [s] / ``df`` [MHz] — axis spacings; ``eta_range`` [s³] —
    the log-spaced bank span (cover the expected regime range — the
    bank prunes, θ-θ confirms). ``threshold`` / ``score_min`` — the
    trigger significances (detect/trigger.py). ``confirm=False``
    skips the θ-θ stage (bank-only triage).

    The detector is single-threaded by design: the daemon's loop
    thread is the only caller (`make_hook`), and standalone use
    (`examine`/`scan_batch`) is sequential — no internal locks.
    """

    def __init__(self, nf, nt, dt, df, eta_range,
                 n_templates=DEFAULT_N_TEMPLATES, threshold=None,
                 score_min=None, variant=None, window="hanning",
                 window_frac=0.1, confirm=True, confirm_window=2.25,
                 confirm_window_refined=1.8, confirm_n_eta=31,
                 confirm_npad=1, confirm_fw=0.2,
                 confirm_edges=96, refine=True,
                 refine_n_eta=DEFAULT_N_ETA, refine_span=None,
                 refine_variant=None, f0=1400.0, hop=None,
                 cal_frames=None, cal_seed=0):
        self.nf, self.nt = int(nf), int(nt)
        self.dt, self.df = float(dt), float(df)
        self.eta_range = (float(eta_range[0]), float(eta_range[1]))
        self.threshold = threshold
        self.score_min = score_min
        self.variant = variant
        self.window = window
        self.window_frac = float(window_frac)
        self.confirm = bool(confirm)
        self.confirm_window = float(confirm_window)
        # a SUB-GRID refined seed deserves a tighter θ-θ window than
        # the bank-grid 2.25×: 1.8× covers the refined-η error
        # distribution (median ~0.11 on the factory recall set) while
        # keeping the 2η harmonic OUTSIDE the searched grid whenever
        # the refined seed is within ~10 % of truth — the PR-14 "~2×
        # bias near the harmonic" fix (tests/test_detect.py pins the
        # live harmonic-capture epoch re-confirming near truth).
        self.confirm_window_refined = float(confirm_window_refined)
        self.confirm_n_eta = int(confirm_n_eta)
        self.confirm_npad = int(confirm_npad)
        self.confirm_fw = float(confirm_fw)
        self.confirm_edges = int(confirm_edges)
        # sub-grid η refinement between trigger and θ-θ confirm
        # (detect/refine.py): zoom the conjugate spectrum around the
        # hit instead of widening the bank; the refined η seeds the
        # confirmation window. refine_variant routes 'xfft.zoom'
        # (czt|dense).
        self.refine = bool(refine)
        self.refine_n_eta = int(refine_n_eta)
        self.refine_span = refine_span
        self.refine_variant = refine_variant
        self.hop = hop
        self.bank = build_bank(self.nf, self.nt, self.dt, self.df,
                               self.eta_range[0], self.eta_range[1],
                               n_templates=n_templates)
        # measured per-template noise floor (detect/trigger.py): one
        # deterministic batched correlate at init, scale-free
        cal_kw = {} if cal_frames is None else \
            {"n_frames": int(cal_frames)}
        self.noise_floor = calibrate_noise_floor(
            self.bank, seed=cal_seed, variant=self.variant,
            window=self.window, window_frac=self.window_frac,
            **cal_kw)
        self._freqs = float(f0) + np.arange(self.nf) * self.df
        self._times = np.arange(self.nt) * self.dt

    # ---- core scan ---------------------------------------------------
    def warmup(self):
        """Compile the correlate/trigger programs (and the θ-θ
        confirmation program when enabled) ahead of the first real
        epoch — the daemon's ``warmup=`` hook can call this so
        ``/readyz`` covers detection too."""
        blank = np.zeros((self.nf, self.nt), dtype=np.float32)
        self.examine("<warmup>", blank, _quiet=True)
        if self.refine:
            eta_mid = float(np.sqrt(self.eta_range[0]
                                    * self.eta_range[1]))
            refine_eta(blank, self.bank, eta_mid,
                       n_eta=self.refine_n_eta,
                       span=self.refine_span,
                       variant=self.refine_variant,
                       window=self.window,
                       window_frac=self.window_frac)
        if self.confirm:
            eta_mid = float(np.sqrt(self.eta_range[0]
                                    * self.eta_range[1]))
            confirm_eta(blank, self._freqs, self._times, eta_mid,
                        window=self.confirm_window,
                        n_eta=self.confirm_n_eta,
                        npad=self.confirm_npad, fw=self.confirm_fw,
                        n_edges=self.confirm_edges)
        return self

    def scan_batch(self, dyns):
        """Bank-correlate a same-geometry epoch stack
        ``[B, nf, nt]`` and extract per-lane triggers (no θ-θ
        stage). Returns the list of trigger dicts
        (detect/trigger.py:extract_triggers)."""
        scores, ok = correlate_bank(
            dyns, self.bank, variant=self.variant,
            window=self.window, window_frac=self.window_frac)
        return extract_triggers(scores, ok, self.bank.etas,
                                noise_floor=self.noise_floor,
                                threshold=self.threshold,
                                score_min=self.score_min)

    def examine(self, epoch_id, dyn, _quiet=False):
        """Scan ONE epoch (overlap-save blocked when its time axis
        exceeds the bank frame): correlate → trigger → θ-θ confirm on
        a hit. Returns the JSON-able detection record the daemon
        annotates ``/state`` with."""
        t0 = time.perf_counter()
        dyn = np.asarray(dyn)
        blocks = extract_blocks(dyn, self.nt, self.hop) \
            if dyn.shape[-1] != self.nt else dyn[None]
        lanes = self.scan_batch(blocks)
        # overlap-save reduction: the epoch's detection is its best
        # block's (an arc split by a block edge is whole in the
        # neighbouring block)
        bi = int(np.argmax([r["z"] for r in lanes]))
        best = lanes[bi]
        rec = dict(best, n_blocks=len(lanes),
                   triggered=bool(best["hit"]), confirmed=False,
                   eta=None, eta_sig=None, eta_refined=None)
        del rec["hit"]
        _metrics.counter(
            "detect_epochs_scanned_total",
            help="epochs scanned against the template bank").inc()
        if rec["ok"] != 0:
            from ..robust.guards import describe_health

            rec["health"] = describe_health(rec["ok"])
            _metrics.counter(
                "detect_epochs_unhealthy_total",
                help="epochs whose detection lanes failed the "
                     "health guards (quarantined, never "
                     "triggered)").inc()
        if rec["triggered"]:
            _metrics.counter(
                "detect_triggers_total",
                help="bank hits above the significance "
                     "threshold").inc()
            if not _quiet:
                slog.log_event("detect.trigger", epoch=str(epoch_id),
                               eta_bank=rec["eta_bank"],
                               z=round(rec["z"], 2),
                               score=round(rec["score"], 2),
                               n_blocks=rec["n_blocks"])
            if self.refine:
                self._refine(epoch_id, blocks[bi], rec, _quiet)
            if self.confirm:
                self._confirm(epoch_id, blocks[bi], rec, _quiet)
        _metrics.histogram(
            "detect_scan_seconds",
            help="per-epoch bank scan + confirmation wall time",
        ).observe(time.perf_counter() - t0)
        return rec

    def examine_group(self, epoch_ids, dyns, _quiet=False):
        """Scan a same-geometry epoch GROUP ``[B, nf, nt]`` in ONE
        bank program (the batched-service shape, ISSUE 16): epochs
        that arrived as lanes of one device fit are confirmed as
        lanes of one bank correlate — the spike-grouped confirmation
        only escalates per-epoch (θ-θ) for actual hits. Returns
        ``{epoch_id: detection record}`` with :meth:`examine`'s
        record schema (``n_blocks`` is 1: group epochs are already
        bank-framed)."""
        t0 = time.perf_counter()
        dyns = np.asarray(dyns)
        lanes = self.scan_batch(dyns)
        out = {}
        for epoch_id, lane, dyn in zip(epoch_ids, lanes, dyns):
            rec = dict(lane, n_blocks=1, triggered=bool(lane["hit"]),
                       confirmed=False, eta=None, eta_sig=None,
                       eta_refined=None)
            del rec["hit"]
            _metrics.counter(
                "detect_epochs_scanned_total",
                help="epochs scanned against the template bank").inc()
            if rec["ok"] != 0:
                from ..robust.guards import describe_health

                rec["health"] = describe_health(rec["ok"])
                _metrics.counter(
                    "detect_epochs_unhealthy_total",
                    help="epochs whose detection lanes failed the "
                         "health guards (quarantined, never "
                         "triggered)").inc()
            if rec["triggered"]:
                _metrics.counter(
                    "detect_triggers_total",
                    help="bank hits above the significance "
                         "threshold").inc()
                if not _quiet:
                    slog.log_event("detect.trigger",
                                   epoch=str(epoch_id),
                                   eta_bank=rec["eta_bank"],
                                   z=round(rec["z"], 2),
                                   score=round(rec["score"], 2),
                                   n_blocks=1)
                if self.refine:
                    self._refine(epoch_id, dyn, rec, _quiet)
                if self.confirm:
                    self._confirm(epoch_id, dyn, rec, _quiet)
            out[str(epoch_id)] = rec
        _metrics.histogram(
            "detect_scan_seconds",
            help="per-epoch bank scan + confirmation wall time",
        ).observe(time.perf_counter() - t0)
        return out

    def _refine(self, epoch_id, frame, rec, _quiet):
        """Sub-grid η refinement of a hit (detect/refine.py): rescore
        the best block on a ~16× denser LOCAL η grid through the
        zoomed conjugate spectrum. Advisory like the θ-θ stage — a
        failed refinement leaves ``eta_refined`` None and the
        confirmation seeds from the bank η."""
        frame = np.asarray(frame)
        try:
            res = refine_eta(frame, self.bank, rec["eta_bank"],
                             n_eta=self.refine_n_eta,
                             span=self.refine_span,
                             variant=self.refine_variant,
                             window=self.window,
                             window_frac=self.window_frac)
        except Exception as e:  # noqa: BLE001 — refinement is
            # advisory: a crashed zoom rescoring must not take the
            # daemon loop down; confirm falls back to the bank η
            slog.log_failure("detect.error", stage="refine",
                             error=e, epoch=str(epoch_id))
            return
        rec["eta_refined"] = float(res["eta_refined"])
        rec["refine_score"] = float(res["score"])
        _metrics.counter(
            "detect_refined_total",
            help="bank hits rescored on the zoomed sub-grid η "
                 "stage").inc()
        if not _quiet:
            slog.log_event("detect.refine", epoch=str(epoch_id),
                           eta_refined=rec["eta_refined"],
                           eta_bank=rec["eta_bank"],
                           score=round(rec["refine_score"], 2))

    def _confirm(self, epoch_id, frame, rec, _quiet):
        """θ-θ confirmation of a hit, on the best block's frame.
        Seeds the pruned η window from the SUB-GRID refined η when
        the refinement stage produced one (the bank-grid seed is ~2×
        biased near the 2η harmonic — detect/trigger.py:confirm_eta);
        the θ-edge sizing stays pinned to the discrete bank η so the
        geometry-keyed θ-θ program cache stays bounded."""
        frame = np.asarray(frame)
        seed = rec.get("eta_refined") or rec["eta_bank"]
        window = self.confirm_window_refined \
            if rec.get("eta_refined") else self.confirm_window
        try:
            res = confirm_eta(frame, self._freqs, self._times,
                              seed,
                              window=window,
                              n_eta=self.confirm_n_eta,
                              npad=self.confirm_npad,
                              fw=self.confirm_fw,
                              n_edges=self.confirm_edges,
                              eta_edges=rec["eta_bank"])
        except Exception as e:  # noqa: BLE001 — confirmation is
            # advisory: a crashed θ-θ stage must not take the daemon
            # loop down; the hit stays unconfirmed and is surfaced
            slog.log_failure("detect.error", stage="confirm",
                             error=e, epoch=str(epoch_id))
            return
        # a vertex outside the searched window is extrapolation (an
        # eigen curve still rising at the grid edge — e.g. the 2η
        # harmonic just beyond it), not a measurement: refuse, leave
        # the trigger standing as a follow-up candidate
        lo = seed / window
        hi = seed * window
        in_window = (res.healthy and np.isfinite(res.eta)
                     and lo <= res.eta <= hi)
        if in_window:
            rec.update(confirmed=True, eta=float(res.eta),
                       eta_sig=float(res.eta_sig))
            _metrics.counter(
                "detect_confirmed_total",
                help="bank hits confirmed by the θ-θ stage").inc()
            if not _quiet:
                slog.log_event("detect.confirmed",
                               epoch=str(epoch_id),
                               eta=float(res.eta),
                               eta_sig=float(res.eta_sig),
                               eta_bank=rec["eta_bank"],
                               eta_refined=rec.get("eta_refined"))
        else:
            rec.update(confirmed=False, eta=None, eta_sig=None,
                       confirm_ok=int(res.ok))

    # ---- daemon wiring ----------------------------------------------
    def make_hook(self, extract=None):
        """Build the ``on_published`` hook for
        :meth:`~scintools_tpu.serve.daemon.SurveyService.add_on_published`.

        ``extract(payload, outcome) → dyn[nf, nt] | None`` maps the
        daemon's loaded payload to the dynspec array (default: the
        payload itself when it is array-like). Quarantined /
        duplicate epochs are skipped — detection only sees published
        results, matching the "triggered follow-up on live data"
        contract."""

        def hook(service, epoch_id, payload, outcome):
            if getattr(outcome, "status", None) != "ok":
                return
            try:
                dyn = extract(payload, outcome) if extract \
                    else payload
                if dyn is None:
                    return
                dyn = np.asarray(dyn)
                if dyn.ndim != 2:
                    return
                rec = self.examine(epoch_id, dyn)
            except Exception as e:  # noqa: BLE001 — detection is a
                # consumer of published results, never a reason to
                # kill the serving loop; surfaced via slog + metric
                slog.log_failure("detect.error", stage="hook",
                                 error=e, epoch=str(epoch_id))
                _metrics.counter(
                    "detect_errors_total",
                    help="detection hook failures (epoch skipped, "
                         "daemon unaffected)").inc()
                return
            service.annotate(epoch_id, detect=rec)

        hook.hook_stage = "detect"
        return hook

    def make_group_hook(self, extract=None):
        """Build the ``on_published_group`` hook for the batched
        service mode
        (:meth:`~scintools_tpu.serve.daemon.SurveyService.add_on_published_group`):
        the group's ok lanes are stacked and scanned in ONE bank
        correlate (:meth:`examine_group` — detection rides the same
        lanes the fit did), epochs whose frame doesn't match the bank
        take the per-epoch overlap-save path, and every scanned epoch
        gets its ``detect`` annotation exactly as the per-epoch
        hook's."""

        def hook(service, entries, outcomes):
            ids, dyns = [], []
            for key, payload in entries:
                out = outcomes.get(str(key))
                if getattr(out, "status", None) != "ok":
                    continue
                try:
                    dyn = extract(payload, out) if extract \
                        else payload
                    if dyn is None:
                        continue
                    dyn = np.asarray(dyn)
                except Exception as e:  # noqa: BLE001 — see make_hook
                    slog.log_failure("detect.error", stage="hook",
                                     error=e, epoch=str(key))
                    _metrics.counter(
                        "detect_errors_total",
                        help="detection hook failures (epoch "
                             "skipped, daemon unaffected)").inc()
                    continue
                if dyn.ndim != 2:
                    continue
                if dyn.shape == (self.nf, self.nt):
                    ids.append(str(key))
                    dyns.append(dyn)
                else:
                    try:
                        service.annotate(key, detect=self.examine(
                            key, dyn))
                    except Exception as e:  # noqa: BLE001
                        slog.log_failure("detect.error", stage="hook",
                                         error=e, epoch=str(key))
                        _metrics.counter(
                            "detect_errors_total",
                            help="detection hook failures (epoch "
                                 "skipped, daemon unaffected)").inc()
            if not ids:
                return
            try:
                recs = self.examine_group(ids, np.stack(dyns))
            except Exception as e:  # noqa: BLE001 — see make_hook
                slog.log_failure("detect.error", stage="hook",
                                 error=e, epoch=ids[0])
                _metrics.counter(
                    "detect_errors_total",
                    help="detection hook failures (epoch skipped, "
                         "daemon unaffected)").inc()
                return
            for key, rec in recs.items():
                service.annotate(key, detect=rec)

        hook.hook_stage = "detect"
        return hook

    def describe(self):
        """JSON-able detector configuration (reports, bench)."""
        return {
            "bank": self.bank.describe(),
            "threshold": self.threshold,
            "score_min": self.score_min,
            "variant": self.variant,
            "confirm": self.confirm,
            "confirm_window": self.confirm_window,
            "confirm_window_refined": self.confirm_window_refined,
            "refine": self.refine,
            "refine_n_eta": self.refine_n_eta,
            "refine_span": self.refine_span,
            "refine_variant": self.refine_variant,
        }
