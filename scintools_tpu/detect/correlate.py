"""Overlap-save whole-bank correlation engine.

The streaming pattern of the GPU acceleration searches
(arXiv:1711.10855 §4, arXiv:1804.05335): incoming data is cut into
overlapping Fourier blocks, each block is transformed ONCE, the
block spectrum is correlated against the whole template bank as one
batched device program, and the block-edge transients are discarded
(overlap-save). Here a "block" is a dynspec frame of the bank's
geometry:

- an epoch exactly the bank frame is one block (the serve daemon's
  per-epoch hot path — one program invocation per epoch);
- a LONGER epoch (or a rolling observation) is cut into
  50 %-overlapping time blocks (:func:`time_blocks`); every block
  rides the batch axis of the SAME compiled program, each block's
  spectrum is matched against the whole bank, and the per-block
  scores are max-reduced by the trigger stage — an arc straddling a
  block boundary is fully inside the neighbouring block, which is
  exactly the transient-discard guarantee overlap-save provides.

The per-block transform is built ON the declared-structure transform
layer (ops/xfft.py, ROADMAP item 4d) from day one:
``secondary_spectrum_power`` declares real input + the halved row
crop, so under the ``'half'`` lowering the discarded half of the
spectrum is never computed (real-input forward, crop folded before
the second-axis transform). The structured-vs-dense choice routes
through the backend.py formulation registry as the ``detect.correlate``
op — the dense complex-fft2 oracle is kept as a choice and parity is
pinned in tests/test_detect.py.

Inside the one jitted program (``detect.correlate`` retrace site):

1. per-lane health (robust/guards.py): non-finite input pixels set
   ``BAD_INPUT`` and are zeroed (``sanitize_chunks``) so one corrupt
   lane can never poison the batched FFT — neighbouring lanes are
   bitwise untouched (pinned in tests);
2. halved secondary-spectrum power per lane (xfft-lowered);
3. per-lane dB scaling relative to the lane peak and ROBUST
   standardisation (median/MAD over the bank's valid region) — the
   input side of the matched filter's noise-floor normalisation;
4. ONE matmul of the standardised spectra against the whole bank:
   ``scores[B, K] = x̂[B, P] @ T[K, P]ᵀ``.

Templates are traced arguments (not closure constants): the bank can
be megabytes, and baking it into the program would blow the JP202
const budget and re-hash it per compile.
"""

from __future__ import annotations

import numpy as np

from ..backend import formulation, get_jax, register_formulation

register_formulation(
    "detect.correlate", default="half", choices=("half", "dense"),
    doc="template-bank correlation front transform: halved-spectrum "
        "xfft lowering (real-input rfft, crop folded — the discarded "
        "half never computed) vs the full complex-fft2 oracle")


def time_blocks(nt_epoch, nt_block, hop=None):
    """Overlap-save block starts for an ``nt_epoch``-long time axis
    cut into ``nt_block`` frames at ``hop`` (default 50 % overlap).
    The final block is right-aligned so the epoch tail is always
    covered by a full frame (the saved region of the last block)."""
    nt_epoch, nt_block = int(nt_epoch), int(nt_block)
    if nt_epoch < nt_block:
        raise ValueError(f"epoch shorter than the bank frame "
                         f"({nt_epoch} < {nt_block})")
    hop = int(hop) if hop else max(1, nt_block // 2)
    starts = list(range(0, nt_epoch - nt_block + 1, hop))
    if starts[-1] != nt_epoch - nt_block:
        starts.append(nt_epoch - nt_block)
    return starts


def extract_blocks(dyn, nt_block, hop=None):
    """Cut ``dyn[nf, nt]`` into the overlap-save block stack
    ``[n_blocks, nf, nt_block]`` (host-side view assembly; the stack
    is the single host→device transfer of the scan)."""
    dyn = np.asarray(dyn)
    starts = time_blocks(dyn.shape[-1], nt_block, hop)
    return np.stack([dyn[..., s:s + int(nt_block)] for s in starts])


# keyed program cache — one compiled correlation program per
# (bank frame, block batch width, formulation variant, window); a
# formulation flip builds a NEW program instead of silently reusing
# the old one (the PR-7 incident class).
_CORRELATE_CACHE = {}

_MAX_CACHED = 16


def correlate_program(nf, nt, n_batch, n_templates, *, variant=None,
                      window="hanning", window_frac=0.1):
    """Cached jitted whole-bank correlation
    ``fn(dyns[B, nf, nt], T[K, P], valid[P]) → (scores[B, K],
    ok[B] int32)`` — one compile per (geometry, batch, K, variant),
    site ``detect.correlate``."""
    if variant is None:
        variant = formulation("detect.correlate")
    if variant not in ("half", "dense"):
        raise ValueError(f"unknown detect.correlate variant "
                         f"{variant!r} (want 'half' or 'dense')")
    key = (int(nf), int(nt), int(n_batch), int(n_templates), variant,
           window, float(window_frac))
    fn = _CORRELATE_CACHE.get(key)
    if fn is None:
        from ..obs import retrace as _retrace

        _retrace.record_build("detect.correlate", key)
        jax = get_jax()
        import jax.numpy as jnp

        from ..ops.sspec import secondary_spectrum_power
        from ..ops.windows import get_window
        from ..robust import guards

        wins = None
        if window is not None:
            wins = get_window(int(nt), int(nf), window=window,
                              frac=window_frac)

        def run(dyns, T, valid):
            in_ok = guards.chunk_finite_ok(dyns, xp=jnp)
            d = guards.sanitize_chunks(dyns.astype(jnp.float32),
                                       xp=jnp)
            sec = jax.vmap(lambda x: secondary_spectrum_power(
                x, window_arrays=wins, backend="jax",
                variant=variant))(d)
            cs_ok = guards.chunk_finite_ok(sec, xp=jnp)
            # dB relative to the lane peak (scale-free), floored so a
            # blanked lane stays finite end-to-end
            smax = jnp.max(sec, axis=(1, 2), keepdims=True)
            smax = jnp.where(smax > 0, smax, jnp.float32(1.0))
            x = 10.0 * jnp.log10(sec / smax + jnp.float32(1e-12))
            x = x.reshape(x.shape[0], -1)
            # robust standardisation over the valid region: the input
            # side of the per-template noise-floor normalisation
            xv = jnp.where(valid > 0, x, jnp.nan)
            med = jnp.nanmedian(xv, axis=1, keepdims=True)
            mad = jnp.nanmedian(jnp.abs(xv - med), axis=1,
                                keepdims=True)
            xhat = (x - med) / (jnp.float32(1.4826) * mad
                                + jnp.float32(1e-6))
            xhat = xhat * valid[None]
            scores = xhat @ T.T
            ok = guards.health_code(input_ok=in_ok, cs_ok=cs_ok,
                                    xp=jnp)
            return scores, ok

        fn = jax.jit(run)
        if len(_CORRELATE_CACHE) >= _MAX_CACHED:
            _CORRELATE_CACHE.pop(next(iter(_CORRELATE_CACHE)))
        _CORRELATE_CACHE[key] = fn
    return fn


def correlate_bank(dyns, bank, *, variant=None, window="hanning",
                   window_frac=0.1):
    """Correlate a block/epoch stack ``dyns[B, nf, nt]`` against the
    whole ``bank`` as one device program. Returns device
    ``(scores[B, K], ok[B])`` — leave them in flight for the trigger
    program (detect/trigger.py) or fetch for host inspection."""
    import jax.numpy as jnp

    dyns = jnp.asarray(dyns)
    if dyns.ndim == 2:
        dyns = dyns[None]
    B, nf, nt = dyns.shape
    gnf, gnt = bank.geometry[0], bank.geometry[1]
    if (nf, nt) != (gnf, gnt):
        raise ValueError(
            f"stack geometry ({nf}, {nt}) does not match the bank's "
            f"({gnf}, {gnt}) — rebuild the bank or re-block the "
            f"epoch (detect.correlate.extract_blocks)")
    fn = correlate_program(nf, nt, B, bank.n_templates,
                           variant=variant, window=window,
                           window_frac=window_frac)
    return fn(dyns, bank.templates, bank.valid)


# ---------------------------------------------------------------------
# abstract program probe (obs/programs.py) — JP2xx audited; the
# 'detect.correlate' formulation enters the fingerprint, so a silent
# half↔dense flip fails JP205
# ---------------------------------------------------------------------

from ..obs.programs import register_probe as _register_probe  # noqa: E402


@_register_probe("detect.correlate",
                 formulations=("detect.correlate", "xfft.sspec"))
def _probe_correlate():
    """The whole-bank correlation program at a fixed 12×10 epoch
    geometry, 2 blocks × 4 templates, active formulation."""
    import jax

    from ..ops.sspec import fft_shapes

    nrfft, ncfft = fft_shapes(12, 10)
    P = (nrfft // 2) * ncfft
    fn = correlate_program(12, 10, 2, 4)
    S = jax.ShapeDtypeStruct
    return fn, (S((2, 12, 10), np.float32), S((4, P), np.float32),
                S((P,), np.float32))
