"""Host-side matplotlib plotting (reference: dynspec.py:442-968 and the
plot branches throughout). Plots are a presentation layer only — all
numerics live in the ops/ kernels."""

from __future__ import annotations

import numpy as np

from .utils.misc import is_valid, centres_to_edges


def _mpl():
    import matplotlib
    if matplotlib.get_backend().lower() != "agg" and not hasattr(
            _mpl, "_interactive"):
        try:
            matplotlib.use("Agg", force=False)
        except Exception:
            pass
    import matplotlib.pyplot as plt
    return plt


def _finish(plt, fig, filename, display, dpi):
    if filename is not None:
        fig.savefig(filename, dpi=dpi, bbox_inches="tight",
                    pad_inches=0.1)
        plt.close(fig)
    elif display:
        plt.show()
    return fig


def plot_dyn(ds, lamsteps=False, input_dyn=None, filename=None,
             input_x=None, input_y=None, trap=False, display=True,
             figsize=(9, 9), dpi=200, title=None, velocity=False):
    """Dynamic spectrum (dynspec.py:442-545)."""
    plt = _mpl()
    if input_dyn is None:
        if lamsteps:
            if not hasattr(ds, "lamdyn"):
                ds.scale_dyn()
            dyn = ds.lamdyn
            yaxis = ds.lam
            ylabel = "Wavelength (m)"
        elif trap:
            if not hasattr(ds, "trapdyn"):
                ds.scale_dyn(scale="trapezoid")
            dyn = ds.trapdyn
            yaxis = ds.freqs
            ylabel = "Frequency (MHz)"
        else:
            dyn = ds.vdyn if velocity else ds.dyn
            yaxis = ds.freqs
            ylabel = "Frequency (MHz)"
        xaxis = ds.times / 60
    else:
        dyn = input_dyn
        xaxis = input_x
        yaxis = input_y
        ylabel = ""
    fig = plt.figure(figsize=figsize)
    valid = dyn[is_valid(dyn)]
    if valid.size:
        medval = np.median(valid[np.abs(valid) > 0])
        minval = np.min(valid)
        std = np.std(valid)
        vmin, vmax = max(minval, medval - 5 * std), medval + 5 * std
    else:
        vmin = vmax = None
    plt.pcolormesh(centres_to_edges(xaxis), centres_to_edges(yaxis),
                   dyn, vmin=vmin, vmax=vmax, linewidth=0,
                   rasterized=True, shading="auto")
    plt.xlabel("Time (mins)")
    plt.ylabel(ylabel)
    if title:
        plt.title(title)
    return _finish(plt, fig, filename, display, dpi)


def plot_acf(ds, method="acf1d", alpha=5 / 3, contour=False,
             filename=None, input_acf=None, input_t=None, input_f=None,
             nscale=4, mcmc=False, display=True, crop=False, tlim=None,
             flim=None, figsize=(9, 9), verbose=False, dpi=200):
    """ACF with fitted scintillation-scale axes
    (dynspec.py:547-691): white-noise spike subtracted, optional crop
    to ``nscale`` scales (or explicit tlim/flim), and — when plotting
    the object's own ACF — twin axes in units of the fitted τ_d/Δν_d
    (running ``get_scint_params(method, mcmc=...)`` first if needed)."""
    plt = _mpl()
    if input_acf is None:
        if not hasattr(ds, "acf"):
            ds.calc_acf()
        if not hasattr(ds, "tau"):
            try:
                ds.get_scint_params(method=method, alpha=alpha,
                                    mcmc=mcmc, verbose=verbose)
            except Exception as e:
                print(e)
                print("Could not determine scintillation scales "
                      "for plot")
        arr = np.array(ds.acf)
        tspan, fspan = ds.tobs, ds.bw
    else:
        arr = np.array(input_acf)
        tspan = max(input_t) - min(input_t)
        fspan = max(input_f) - min(input_f)
    # subtract the white-noise spike (dynspec.py:626-630)
    arr = np.fft.ifftshift(arr)
    wn = arr[0][0] - max(arr[1][0], arr[0][1])
    arr[0][0] = arr[0][0] - wn
    arr = np.fft.fftshift(arr)

    t_delays = np.linspace(-tspan / 60, tspan / 60, arr.shape[1])
    f_shifts = np.linspace(-fspan, fspan, arr.shape[0])

    has_scales = hasattr(ds, "tau") and hasattr(ds, "dnu")
    if crop and tlim is None and not has_scales:
        # the fit failed above; honour the printed warning and plot
        # the full frame instead of crashing on ds.tau
        crop = False
    if crop or (tlim is not None):
        if tlim is None:
            tlim = nscale * ds.tau / 60
        if flim is None:
            flim = (nscale * ds.dnu if has_scales
                    else np.abs(f_shifts).max())
        tlim = min(tlim, ds.tobs / 60) if input_acf is None else tlim
        flim = min(flim, ds.bw) if input_acf is None else flim
        t_inds = np.flatnonzero(np.abs(t_delays) <= tlim)
        f_inds = np.flatnonzero(np.abs(f_shifts) <= flim)
        t_delays = t_delays[t_inds]
        f_shifts = f_shifts[f_inds]
        arr = arr[np.ix_(f_inds, t_inds)]

    fig, ax1 = plt.subplots(figsize=figsize)
    if contour:
        ax1.contourf(t_delays, f_shifts, arr)
    else:
        ax1.pcolormesh(centres_to_edges(t_delays),
                       centres_to_edges(f_shifts), arr, linewidth=0,
                       rasterized=True, shading="auto")
    if input_acf is None:
        ax1.set_ylabel(r"Frequency shift, $\Delta\nu$ (MHz)")
        ax1.set_xlabel(r"Time lag, $\tau$ (mins)")
        if hasattr(ds, "tau") and hasattr(ds, "dnu"):
            # twin axes in units of the fitted scales
            # (dynspec.py:663-673)
            miny, maxy = ax1.get_ylim()
            ax2 = ax1.twinx()
            ax2.set_ylim(miny / ds.dnu, maxy / ds.dnu)
            ax2.set_ylabel(r"$\Delta\nu$ / ($\Delta\nu_d = "
                           + f"{round(ds.dnu, 2)}" + r"\,$MHz)")
            ax3 = ax1.twiny()
            minx, maxx = ax1.get_xlim()
            ax3.set_xlim(minx / (ds.tau / 60), maxx / (ds.tau / 60))
            ax3.set_xlabel(r"$\tau$/($\tau_d="
                           + f"{round(ds.tau / 60, 2)}" + r"\,$min)")
    else:
        ax1.set_ylabel("Frequency lag (MHz)")
        ax1.set_xlabel("Time lag (mins)")
    return _finish(plt, fig, filename, display, dpi)


def _split_filename(filename, tag):
    """'x.png' → 'x_<tag>.png' (reference suffix convention,
    dynspec.py:2417-2419)."""
    name = "".join(filename.split(".")[:-1])
    ext = filename.split(".")[-1]
    return f"{name}_{tag}.{ext}"


def plot_acf_tilt(ds, peaks, peakerrs, ys, yfit, nscaleplot=2,
                  tmaxplot=None, fmaxplot=None, filename=None,
                  display=True, figsize=(9, 9), dpi=200):
    """Two tilt diagnostics (dynspec.py:2415-2462): the per-row peak
    measurements with the weighted line fit, and the ACF with the
    fitted tilt overlaid."""
    plt = _mpl()
    figs = []

    fig = plt.figure(figsize=figsize)
    plt.errorbar(peaks, ys, xerr=np.asarray(peakerrs).squeeze(),
                 marker=".")
    plt.plot(peaks, yfit)
    plt.ylabel("Frequency lag (MHz)")
    plt.xlabel("Time lag (mins)")
    plt.title("Peak measurements, and weighted fit")
    figs.append(_finish(plt, fig,
                        filename and _split_filename(filename,
                                                     "tilt_fit"),
                        display, dpi))

    acf = np.array(ds.acf)
    # same lag-axis convention as the peak measurements in
    # get_acf_tilt (dynspec.py) so the overlay aligns with the pixels
    t_delays = np.linspace(-ds.tobs / 60, ds.tobs / 60,
                           acf.shape[1] + 1)[:-1]
    f_shifts = np.linspace(-ds.bw, ds.bw, acf.shape[0] + 1)[:-1]
    fig = plt.figure(figsize=figsize)
    plt.pcolormesh(centres_to_edges(t_delays),
                   centres_to_edges(f_shifts), acf, linewidth=0,
                   rasterized=True, shading="auto")
    plt.plot(peaks, ys, "r", alpha=0.5)
    plt.plot(peaks, yfit, "k", alpha=0.5)
    yl = plt.ylim()
    if yl[1] > nscaleplot * ds.dnu:
        plt.ylim(-nscaleplot * ds.dnu, nscaleplot * ds.dnu)
    if fmaxplot is not None and yl[1] > fmaxplot:
        plt.ylim(-fmaxplot, fmaxplot)
    xl = plt.xlim()
    if xl[1] > nscaleplot * ds.tau / 60:
        plt.xlim(-nscaleplot * ds.tau / 60, nscaleplot * ds.tau / 60)
    if tmaxplot is not None and xl[1] > tmaxplot:
        plt.xlim(-tmaxplot, tmaxplot)
    plt.ylabel("Frequency lag (MHz)")
    plt.xlabel("Time lag (mins)")
    err = np.sqrt(ds.acf_tilt_err ** 2 + ds.fse_tilt ** 2)
    plt.title(f"Tilt = {round(ds.acf_tilt, 3)} $\\pm$ "
              f"{round(err, 3)} (min/MHz)")
    figs.append(_finish(plt, fig,
                        filename and _split_filename(filename,
                                                     "tilt_acf"),
                        display, dpi))
    return figs


def plot_cut_tiles(ds, lamsteps=False, maxfdop=np.inf, filename=None,
                   display=True, figsize=(8, 13), dpi=200):
    """Tiled dynspec / ACF / sspec figures for ``cut_dyn``
    (dynspec.py:3211-3268): one subplot per tile, three figures saved
    with the reference's ``_dynspec``/``_acf``/``_sspec`` suffixes."""
    plt = _mpl()
    nfc, ntc = ds.cutdyn.shape[:2]
    figs = []
    for tag, plot_tile in (
            ("dynspec", lambda ii, jj: plt.pcolormesh(
                centres_to_edges(ds.cut_times[jj] / 60),
                centres_to_edges(ds.cut_freqs[ii]),
                ds.cutdyn[ii, jj], linewidth=0, rasterized=True,
                shading="auto")),
            ("acf", lambda ii, jj: plt.pcolormesh(
                ds.cutacf[ii, jj], linewidth=0, rasterized=True,
                shading="auto")),
            ("sspec", lambda ii, jj: _tile_sspec(
                plt, ds.cutsspec[ii, jj], ds.cut_sspec_x,
                ds.cut_sspec_y, maxfdop))):
        fig = plt.figure(figsize=figsize)
        plotnum = 1
        for ii in range(nfc):
            for jj in range(ntc):
                plt.subplot(nfc, ntc, plotnum)
                plot_tile(ii, jj)
                plotnum += 1
        figs.append(_finish(plt, fig,
                            filename and _split_filename(filename, tag),
                            display, dpi))
    return figs


def _tile_sspec(plt, sspec, x, y, maxfdop):
    valid = sspec[is_valid(sspec) & (np.abs(sspec) > 0)]
    vmin = np.median(valid) - 3 if valid.size else None
    vmax = np.max(valid) - 3 if valid.size else None
    sel = np.abs(x) <= maxfdop
    plt.pcolormesh(centres_to_edges(x[sel]), centres_to_edges(y),
                   sspec[:, sel], vmin=vmin, vmax=vmax, linewidth=0,
                   rasterized=True, shading="auto")


def plot_sspec(ds, lamsteps=False, input_sspec=None, filename=None,
               input_x=None, input_y=None, trap=False, plotarc=False,
               maxfdop=np.inf, delmax=None, cutmid=0, startbin=0,
               display=True, colorbar=True, title=None, figsize=(9, 9),
               dpi=200, velocity=False):
    """Secondary spectrum (dynspec.py:693-853 core)."""
    plt = _mpl()
    if input_sspec is None:
        sspec, yaxis = ds._select_sspec(lamsteps=lamsteps, trap=trap,
                                        velocity=velocity)
        xaxis = ds.fdop
    else:
        sspec = input_sspec
        xaxis = input_x
        yaxis = input_y
    sspec = np.asarray(sspec)
    fig = plt.figure(figsize=figsize)
    valid = sspec[is_valid(sspec) & (np.abs(sspec) > 0)]
    vmin = np.median(valid) - 3 if valid.size else None
    vmax = np.max(valid) - 3 if valid.size else None
    sel = np.abs(xaxis) <= maxfdop
    plt.pcolormesh(centres_to_edges(xaxis[sel]),
                   centres_to_edges(yaxis[startbin:]),
                   sspec[startbin:, sel], vmin=vmin, vmax=vmax,
                   linewidth=0, rasterized=True, shading="auto")
    if plotarc:
        eta = ds.betaeta if lamsteps else ds.eta
        x = np.linspace(max(-maxfdop, np.min(xaxis)),
                        min(maxfdop, np.max(xaxis)), 200)
        plt.plot(x, eta * x ** 2, "r--", alpha=0.7)
        plt.ylim(yaxis[startbin], np.max(yaxis))
    plt.xlabel(r"$f_t$ (mHz)")
    plt.ylabel(r"$f_\lambda$ (m$^{-1}$)" if lamsteps
               else r"$f_\nu$ ($\mu$s)")
    if colorbar:
        plt.colorbar()
    if title:
        plt.title(title)
    return _finish(plt, fig, filename, display, dpi)


def plot_arc_fit(fit, lamsteps=False, filename=None, display=True,
                 figsize=(9, 9), dpi=200):
    """Curvature-fit diagnostic (dynspec.py:1315-1346)."""
    plt = _mpl()
    fig = plt.figure(figsize=figsize)
    plt.plot(fit.eta_array[10:], fit.profile[10:])
    if fit.xdata is not None:
        plt.plot(fit.xdata, fit.yfit, "k")
    plt.axvspan(xmin=fit.eta - fit.etaerr, xmax=fit.eta + fit.etaerr,
                facecolor="C2", alpha=0.5)
    plt.xscale("log")
    if lamsteps:
        plt.xlabel(r"Arc curvature, "
                   r"$\eta$ (${\rm m}^{-1}\,{\rm mHz}^{-2}$)")
    else:
        plt.xlabel("eta (tdel)")
    plt.ylabel("Mean power (dB)")
    return _finish(plt, fig, filename, display, dpi)


def plot_norm_sspec(ds, scrunched=True, unscrunched=True, powerspec=True,
                    plot_fit=True, maxnormfac=5, lamsteps=True,
                    filename=None, display=True, figsize=(9, 9),
                    dpi=200):
    """Normalised sspec panels (dynspec.py:2185-2279)."""
    plt = _mpl()
    figs = []
    if scrunched:
        fig = plt.figure(figsize=figsize)
        plt.plot(ds.normsspec_fdop, ds.normsspecavg)
        if plot_fit:
            for x in (-1, 1):
                plt.axvline(x, color="r", linestyle="--", alpha=0.5)
        plt.xlabel(r"Normalised $f_t$")
        plt.ylabel("Mean power (dB)")
        plt.xlim(-maxnormfac, maxnormfac)
        figs.append(_finish(plt, fig, filename and
                            filename.replace(".", "_1d.", 1), display,
                            dpi))
    if unscrunched:
        fig = plt.figure(figsize=figsize)
        arr = np.ma.filled(np.ma.array(ds.normsspec, mask=ds.mask),
                           np.nan)
        plt.pcolormesh(centres_to_edges(ds.normsspec_fdop),
                       centres_to_edges(ds.normsspec_tdel), arr,
                       linewidth=0, rasterized=True, shading="auto")
        plt.xlabel(r"Normalised $f_t$")
        plt.ylabel(r"$f_\lambda$ (m$^{-1}$)" if lamsteps
                   else r"$f_\nu$ ($\mu$s)")
        plt.colorbar()
        figs.append(_finish(plt, fig, filename, display, dpi))
    if powerspec:
        fig = plt.figure(figsize=figsize)
        x = np.sqrt(ds.normsspec_tdel)
        y = x * ds.powerspectrum
        plt.loglog(x, y)
        plt.xlabel(r"$f_\lambda^{1/2}$" if lamsteps
                   else r"$f_\nu^{1/2}$")
        plt.ylabel(r"$f^{1/2} D(f^{1/2})$")
        plt.grid(which="both", axis="both")
        figs.append(_finish(plt, fig, filename and
                            filename.replace(".", "_power.", 1),
                            display, dpi))
    return figs


def plot_scattered_image(ds, input_scattered_image=None, input_fdop=None,
                         display=True, plot_log=True, filename=None,
                         figsize=(9, 9), dpi=200):
    """Scattered image (dynspec.py:855-968 core)."""
    plt = _mpl()
    im = (input_scattered_image if input_scattered_image is not None
          else ds.scattered_image)
    ax = input_fdop if input_fdop is not None else ds.scattered_image_ax
    fig = plt.figure(figsize=figsize)
    data = 10 * np.log10(np.abs(im) + 1e-30) if plot_log else im
    plt.pcolormesh(centres_to_edges(ax), centres_to_edges(ax), data,
                   linewidth=0, rasterized=True, shading="auto")
    plt.xlabel(r"$f_t$ (mHz)")
    plt.ylabel(r"$f_t$ (mHz)")
    plt.colorbar()
    return _finish(plt, fig, filename, display, dpi)


def plot_all(ds, lamsteps=False, filename=None, display=True,
             figsize=(9, 9), dpi=200):
    """Composite 2×2 summary (dynspec.py role of plot_all)."""
    plt = _mpl()
    fig, axes = plt.subplots(2, 2, figsize=figsize)
    plt.sca(axes[0, 0])
    plt.pcolormesh(centres_to_edges(ds.times / 60),
                   centres_to_edges(ds.freqs), ds.dyn, shading="auto")
    plt.title("Dynamic spectrum")
    if not hasattr(ds, "acf"):
        ds.calc_acf()
    plt.sca(axes[0, 1])
    plt.pcolormesh(ds.acf, shading="auto")
    plt.title("ACF")
    sspec, yaxis = ds._select_sspec(lamsteps=lamsteps)
    plt.sca(axes[1, 0])
    valid = sspec[is_valid(sspec) & (np.abs(sspec) > 0)]
    plt.pcolormesh(centres_to_edges(ds.fdop), centres_to_edges(yaxis),
                   sspec, vmin=np.median(valid) - 3,
                   vmax=np.max(valid) - 3, shading="auto")
    plt.title("Secondary spectrum")
    axes[1, 1].axis("off")
    plt.tight_layout()
    return _finish(plt, fig, filename, display, dpi)
