"""Host-side matplotlib plotting (reference: dynspec.py:442-968 and the
plot branches throughout). Plots are a presentation layer only — all
numerics live in the ops/ kernels."""

from __future__ import annotations

import numpy as np

from .utils.misc import is_valid, centres_to_edges


def _mpl():
    import matplotlib
    if matplotlib.get_backend().lower() != "agg" and not hasattr(
            _mpl, "_interactive"):
        try:
            matplotlib.use("Agg", force=False)
        except (ImportError, ValueError):
            # backend already initialised interactively — keep it
            pass
    import matplotlib.pyplot as plt
    return plt


def _finish(plt, fig, filename, display, dpi):
    if filename is not None:
        fig.savefig(filename, dpi=dpi, bbox_inches="tight",
                    pad_inches=0.1)
        plt.close(fig)
    elif display:
        plt.show()
    return fig


def plot_dyn(ds, lamsteps=False, input_dyn=None, filename=None,
             input_x=None, input_y=None, trap=False, display=True,
             figsize=(9, 9), dpi=200, title=None, velocity=False):
    """Dynamic spectrum (dynspec.py:442-545)."""
    plt = _mpl()
    if input_dyn is None:
        if lamsteps:
            if not hasattr(ds, "lamdyn"):
                ds.scale_dyn()
            dyn = ds.lamdyn
            yaxis = ds.lam
            ylabel = "Wavelength (m)"
        elif trap:
            if not hasattr(ds, "trapdyn"):
                ds.scale_dyn(scale="trapezoid")
            dyn = ds.trapdyn
            yaxis = ds.freqs
            ylabel = "Frequency (MHz)"
        else:
            dyn = ds.vdyn if velocity else ds.dyn
            yaxis = ds.freqs
            ylabel = "Frequency (MHz)"
        xaxis = ds.times / 60
    else:
        dyn = input_dyn
        xaxis = input_x
        yaxis = input_y
        ylabel = ""
    fig = plt.figure(figsize=figsize)
    valid = dyn[is_valid(dyn)]
    if valid.size:
        medval = np.median(valid[np.abs(valid) > 0])
        minval = np.min(valid)
        std = np.std(valid)
        vmin, vmax = max(minval, medval - 5 * std), medval + 5 * std
    else:
        vmin = vmax = None
    plt.pcolormesh(centres_to_edges(xaxis), centres_to_edges(yaxis),
                   dyn, vmin=vmin, vmax=vmax, linewidth=0,
                   rasterized=True, shading="auto")
    plt.xlabel("Time (mins)")
    plt.ylabel(ylabel)
    if title:
        plt.title(title)
    return _finish(plt, fig, filename, display, dpi)


def plot_acf(ds, method="acf1d", alpha=5 / 3, contour=False,
             filename=None, input_acf=None, input_t=None, input_f=None,
             nscale=4, mcmc=False, display=True, crop=False, tlim=None,
             flim=None, figsize=(9, 9), verbose=False, dpi=200):
    """ACF with fitted scintillation-scale axes
    (dynspec.py:547-691): white-noise spike subtracted, optional crop
    to ``nscale`` scales (or explicit tlim/flim), and — when plotting
    the object's own ACF — twin axes in units of the fitted τ_d/Δν_d
    (running ``get_scint_params(method, mcmc=...)`` first if needed)."""
    plt = _mpl()
    if input_acf is None:
        if not hasattr(ds, "acf"):
            ds.calc_acf()
        if not hasattr(ds, "tau"):
            try:
                ds.get_scint_params(method=method, alpha=alpha,
                                    mcmc=mcmc, verbose=verbose)
            except Exception as e:
                print(e)
                print("Could not determine scintillation scales "
                      "for plot")
        arr = np.array(ds.acf)
        tspan, fspan = ds.tobs, ds.bw
    else:
        arr = np.array(input_acf)
        tspan = max(input_t) - min(input_t)
        fspan = max(input_f) - min(input_f)
    # subtract the white-noise spike (dynspec.py:626-630)
    arr = np.fft.ifftshift(arr)
    wn = arr[0][0] - max(arr[1][0], arr[0][1])
    arr[0][0] = arr[0][0] - wn
    arr = np.fft.fftshift(arr)

    t_delays = np.linspace(-tspan / 60, tspan / 60, arr.shape[1])
    f_shifts = np.linspace(-fspan, fspan, arr.shape[0])

    has_scales = hasattr(ds, "tau") and hasattr(ds, "dnu")
    if crop and tlim is None and not has_scales:
        # the fit failed above; honour the printed warning and plot
        # the full frame instead of crashing on ds.tau
        crop = False
    if crop or (tlim is not None):
        if tlim is None:
            tlim = nscale * ds.tau / 60
        if flim is None:
            flim = (nscale * ds.dnu if has_scales
                    else np.abs(f_shifts).max())
        tlim = min(tlim, ds.tobs / 60) if input_acf is None else tlim
        flim = min(flim, ds.bw) if input_acf is None else flim
        t_inds = np.flatnonzero(np.abs(t_delays) <= tlim)
        f_inds = np.flatnonzero(np.abs(f_shifts) <= flim)
        t_delays = t_delays[t_inds]
        f_shifts = f_shifts[f_inds]
        arr = arr[np.ix_(f_inds, t_inds)]

    fig, ax1 = plt.subplots(figsize=figsize)
    if contour:
        ax1.contourf(t_delays, f_shifts, arr)
    else:
        ax1.pcolormesh(centres_to_edges(t_delays),
                       centres_to_edges(f_shifts), arr, linewidth=0,
                       rasterized=True, shading="auto")
    if input_acf is None:
        ax1.set_ylabel(r"Frequency shift, $\Delta\nu$ (MHz)")
        ax1.set_xlabel(r"Time lag, $\tau$ (mins)")
        if hasattr(ds, "tau") and hasattr(ds, "dnu"):
            # twin axes in units of the fitted scales
            # (dynspec.py:663-673)
            miny, maxy = ax1.get_ylim()
            ax2 = ax1.twinx()
            ax2.set_ylim(miny / ds.dnu, maxy / ds.dnu)
            ax2.set_ylabel(r"$\Delta\nu$ / ($\Delta\nu_d = "
                           + f"{round(ds.dnu, 2)}" + r"\,$MHz)")
            ax3 = ax1.twiny()
            minx, maxx = ax1.get_xlim()
            ax3.set_xlim(minx / (ds.tau / 60), maxx / (ds.tau / 60))
            ax3.set_xlabel(r"$\tau$/($\tau_d="
                           + f"{round(ds.tau / 60, 2)}" + r"\,$min)")
    else:
        ax1.set_ylabel("Frequency lag (MHz)")
        ax1.set_xlabel("Time lag (mins)")
    return _finish(plt, fig, filename, display, dpi)


def _split_filename(filename, tag):
    """'x.png' → 'x_<tag>.png' (reference suffix convention,
    dynspec.py:2417-2419)."""
    name = "".join(filename.split(".")[:-1])
    ext = filename.split(".")[-1]
    return f"{name}_{tag}.{ext}"


def plot_acf_tilt(ds, peaks, peakerrs, ys, yfit, nscaleplot=2,
                  tmaxplot=None, fmaxplot=None, filename=None,
                  display=True, figsize=(9, 9), dpi=200):
    """Two tilt diagnostics (dynspec.py:2415-2462): the per-row peak
    measurements with the weighted line fit, and the ACF with the
    fitted tilt overlaid."""
    plt = _mpl()
    figs = []

    fig = plt.figure(figsize=figsize)
    plt.errorbar(peaks, ys, xerr=np.asarray(peakerrs).squeeze(),
                 marker=".")
    plt.plot(peaks, yfit)
    plt.ylabel("Frequency lag (MHz)")
    plt.xlabel("Time lag (mins)")
    plt.title("Peak measurements, and weighted fit")
    figs.append(_finish(plt, fig,
                        filename and _split_filename(filename,
                                                     "tilt_fit"),
                        display, dpi))

    acf = np.array(ds.acf)
    # same lag-axis convention as the peak measurements in
    # get_acf_tilt (dynspec.py) so the overlay aligns with the pixels
    t_delays = np.linspace(-ds.tobs / 60, ds.tobs / 60,
                           acf.shape[1] + 1)[:-1]
    f_shifts = np.linspace(-ds.bw, ds.bw, acf.shape[0] + 1)[:-1]
    fig = plt.figure(figsize=figsize)
    plt.pcolormesh(centres_to_edges(t_delays),
                   centres_to_edges(f_shifts), acf, linewidth=0,
                   rasterized=True, shading="auto")
    plt.plot(peaks, ys, "r", alpha=0.5)
    plt.plot(peaks, yfit, "k", alpha=0.5)
    yl = plt.ylim()
    if yl[1] > nscaleplot * ds.dnu:
        plt.ylim(-nscaleplot * ds.dnu, nscaleplot * ds.dnu)
    if fmaxplot is not None and yl[1] > fmaxplot:
        plt.ylim(-fmaxplot, fmaxplot)
    xl = plt.xlim()
    if xl[1] > nscaleplot * ds.tau / 60:
        plt.xlim(-nscaleplot * ds.tau / 60, nscaleplot * ds.tau / 60)
    if tmaxplot is not None and xl[1] > tmaxplot:
        plt.xlim(-tmaxplot, tmaxplot)
    plt.ylabel("Frequency lag (MHz)")
    plt.xlabel("Time lag (mins)")
    err = np.sqrt(ds.acf_tilt_err ** 2 + ds.fse_tilt ** 2)
    plt.title(f"Tilt = {round(ds.acf_tilt, 3)} $\\pm$ "
              f"{round(err, 3)} (min/MHz)")
    figs.append(_finish(plt, fig,
                        filename and _split_filename(filename,
                                                     "tilt_acf"),
                        display, dpi))
    return figs


def plot_cut_tiles(ds, lamsteps=False, maxfdop=np.inf, filename=None,
                   display=True, figsize=(8, 13), dpi=200):
    """Tiled dynspec / ACF / sspec figures for ``cut_dyn``
    (dynspec.py:3211-3268): one subplot per tile, three figures saved
    with the reference's ``_dynspec``/``_acf``/``_sspec`` suffixes."""
    plt = _mpl()
    nfc, ntc = ds.cutdyn.shape[:2]
    figs = []
    for tag, plot_tile in (
            ("dynspec", lambda ii, jj: plt.pcolormesh(
                centres_to_edges(ds.cut_times[jj] / 60),
                centres_to_edges(ds.cut_freqs[ii]),
                ds.cutdyn[ii, jj], linewidth=0, rasterized=True,
                shading="auto")),
            ("acf", lambda ii, jj: plt.pcolormesh(
                ds.cutacf[ii, jj], linewidth=0, rasterized=True,
                shading="auto")),
            ("sspec", lambda ii, jj: _tile_sspec(
                plt, ds.cutsspec[ii, jj], ds.cut_sspec_x,
                ds.cut_sspec_y, maxfdop, lamsteps))):
        fig = plt.figure(figsize=figsize)
        plotnum = 1
        for ii in range(nfc):
            for jj in range(ntc):
                plt.subplot(nfc, ntc, plotnum)
                plot_tile(ii, jj)
                plotnum += 1
        figs.append(_finish(plt, fig,
                            filename and _split_filename(filename, tag),
                            display, dpi))
    return figs


def _tile_sspec(plt, sspec, x, y, maxfdop, lamsteps=False):
    valid = sspec[is_valid(sspec) & (np.abs(sspec) > 0)]
    vmin = np.median(valid) - 3 if valid.size else None
    vmax = np.max(valid) - 3 if valid.size else None
    sel = np.abs(x) <= maxfdop
    plt.pcolormesh(centres_to_edges(x[sel]), centres_to_edges(y),
                   sspec[:, sel], vmin=vmin, vmax=vmax, linewidth=0,
                   rasterized=True, shading="auto")
    plt.ylabel(r"$f_\lambda$ (m$^{-1}$)" if lamsteps
               else r"$f_\nu$ ($\mu$s)")


def plot_sspec(ds, lamsteps=False, input_sspec=None, filename=None,
               input_x=None, input_y=None, trap=False, prewhite=False,
               plotarc=False, maxfdop=np.inf, delmax=None, cutmid=0,
               startbin=0, display=True, colorbar=True, title=None,
               figsize=(9, 9), subtract_artefacts=False,
               overplot_curvature=None, dpi=200, velocity=False,
               vmin=None, vmax=None):
    """Secondary spectrum (dynspec.py:693-853): every reference kwarg
    is honoured — prewhitened recompute, constant-delay artefact
    subtraction, central-Doppler ``cutmid`` / low-delay ``startbin``
    masking, ``delmax`` crop, explicit colour limits, and arc
    overlays (fitted via ``plotarc`` or explicit curvature via
    ``overplot_curvature``)."""
    plt = _mpl()
    if input_sspec is None:
        if prewhite:
            # reference semantics (dynspec.py:756-772): prewhite only
            # affects a FRESH computation — an existing stored sspec
            # is plotted as-is, never overwritten
            attr = ("vlamsspec" if lamsteps and velocity else
                    "lamsspec" if lamsteps else
                    "vsspec" if velocity else
                    "trapsspec" if trap else "sspec")
            if not hasattr(ds, attr):
                ds.calc_sspec(lamsteps=lamsteps, trap=trap,
                              velocity=velocity, prewhite=True)
        sspec, yaxis = ds._select_sspec(lamsteps=lamsteps, trap=trap,
                                        velocity=velocity)
        xaxis = np.asarray(ds.fdop)
    else:
        sspec = input_sspec
        xaxis = np.asarray(input_x)
        yaxis = np.asarray(input_y)
    sspec = np.array(sspec, dtype=float)

    if subtract_artefacts:
        # constant-in-Doppler delay response from the outer 10%
        # (dynspec.py:780-787)
        outer = np.abs(xaxis) > 0.9 * np.max(np.abs(xaxis))
        delay_response = np.nanmean(sspec[:, outer], axis=1)
        delay_response = delay_response - np.median(delay_response)
        sspec = sspec - delay_response[:, None]

    valid = sspec[is_valid(sspec) & (np.abs(sspec) > 0)]
    if valid.size:
        vmin = np.median(valid) - 3 if vmin is None else vmin
        vmax = np.max(valid) - 3 if vmax is None else vmax

    sel = np.abs(xaxis) <= maxfdop
    xplot = xaxis[sel]
    sspec = sspec[:, sel]
    nc = sspec.shape[1]
    if cutmid:
        sspec[:, int(nc / 2 - np.floor(cutmid / 2)):
              int(nc / 2 + np.ceil(cutmid / 2))] = np.nan
    if startbin:
        sspec[:startbin, :] = np.nan
    if delmax is None:
        ind = len(yaxis)
    else:
        # delmax is defined on the tdel axis (µs) like the reference
        tdel = np.asarray(getattr(ds, "tdel", yaxis))
        ind = max(int(np.argmin(np.abs(tdel[:len(yaxis)] - delmax))),
                  1)

    fig = plt.figure(figsize=figsize)
    plt.pcolormesh(centres_to_edges(xplot),
                   centres_to_edges(yaxis[:ind]), sspec[:ind, :],
                   vmin=vmin, vmax=vmax, linewidth=0, rasterized=True,
                   shading="auto")
    bottom, top = plt.ylim()
    if overplot_curvature is not None:
        plt.plot(xplot, overplot_curvature * xplot ** 2, "r--")
    if plotarc:
        eta = ds.betaeta if lamsteps else ds.eta
        plt.plot(xplot, eta * xplot ** 2, "r--", alpha=0.5)
    plt.ylim(bottom, top)
    plt.xlabel(r"$f_t$ (mHz)")
    plt.ylabel(r"$f_\lambda$ (m$^{-1}$)" if lamsteps
               else r"$f_\nu$ ($\mu$s)")
    if colorbar:
        plt.colorbar()
    if title:
        plt.title(title)
    return _finish(plt, fig, filename, display, dpi)


def plot_arc_fit(fit, lamsteps=False, filename=None, display=True,
                 figsize=(9, 9), dpi=200, figN=None):
    """Curvature-fit diagnostic (dynspec.py:1315-1346). ``figN``
    selects an existing figure number (dynspec.py:1316-1319)."""
    plt = _mpl()
    fig = (plt.figure(figsize=figsize) if figN is None
           else plt.figure(figN, figsize=figsize))
    plt.plot(fit.eta_array[10:], fit.profile[10:])
    if fit.xdata is not None:
        plt.plot(fit.xdata, fit.yfit, "k")
    plt.axvspan(xmin=fit.eta - fit.etaerr, xmax=fit.eta + fit.etaerr,
                facecolor="C2", alpha=0.5)
    plt.xscale("log")
    if lamsteps:
        plt.xlabel(r"Arc curvature, "
                   r"$\eta$ (${\rm m}^{-1}\,{\rm mHz}^{-2}$)")
    else:
        plt.xlabel("eta (tdel)")
    plt.ylabel("Mean power (dB)")
    return _finish(plt, fig, filename, display, dpi)


def plot_norm_sspec(ds, scrunched=True, unscrunched=True, powerspec=True,
                    plot_fit=True, maxnormfac=5, lamsteps=True,
                    filename=None, display=True, figsize=(9, 9),
                    dpi=200):
    """Normalised sspec panels (dynspec.py:2185-2279)."""
    plt = _mpl()
    figs = []
    if scrunched:
        fig = plt.figure(figsize=figsize)
        plt.plot(ds.normsspec_fdop, ds.normsspecavg)
        if plot_fit:
            for x in (-1, 1):
                plt.axvline(x, color="r", linestyle="--", alpha=0.5)
        plt.xlabel(r"Normalised $f_t$")
        plt.ylabel("Mean power (dB)")
        plt.xlim(-maxnormfac, maxnormfac)
        figs.append(_finish(plt, fig, filename and
                            filename.replace(".", "_1d.", 1), display,
                            dpi))
    if unscrunched:
        fig = plt.figure(figsize=figsize)
        arr = np.ma.filled(np.ma.array(ds.normsspec, mask=ds.mask),
                           np.nan)
        plt.pcolormesh(centres_to_edges(ds.normsspec_fdop),
                       centres_to_edges(ds.normsspec_tdel), arr,
                       linewidth=0, rasterized=True, shading="auto")
        plt.xlabel(r"Normalised $f_t$")
        plt.ylabel(r"$f_\lambda$ (m$^{-1}$)" if lamsteps
                   else r"$f_\nu$ ($\mu$s)")
        plt.colorbar()
        figs.append(_finish(plt, fig, filename, display, dpi))
    if powerspec:
        fig = plt.figure(figsize=figsize)
        x = np.sqrt(ds.normsspec_tdel)
        y = x * ds.powerspectrum
        plt.loglog(x, y)
        plt.xlabel(r"$f_\lambda^{1/2}$" if lamsteps
                   else r"$f_\nu^{1/2}$")
        plt.ylabel(r"$f^{1/2} D(f^{1/2})$")
        plt.grid(which="both", axis="both")
        figs.append(_finish(plt, fig, filename and
                            filename.replace(".", "_power.", 1),
                            display, dpi))
    return figs


def plot_scattered_image(ds, input_scattered_image=None, input_fdop=None,
                         display=True, plot_log=True, colorbar=True,
                         title=None, use_angle=False, use_spatial=False,
                         s=None, veff=None, d=None, filename=None,
                         figsize=(9, 9), dpi=200):
    """Scattered image (dynspec.py:855-968): optional on-sky angle
    (arcsec, needs fractional screen distance ``s`` and effective
    velocity ``veff`` km/s) or spatial (AU, additionally distance
    ``d`` kpc) axes — dynspec.py:916-928."""
    plt = _mpl()
    c = 299792458.0
    im = np.array(input_scattered_image
                  if input_scattered_image is not None
                  else ds.scattered_image, dtype=float)
    xyaxes = np.asarray(input_fdop if input_fdop is not None
                        else ds.scattered_image_ax, dtype=float)
    if use_angle or use_spatial:
        if s is None or veff is None:
            raise ValueError("use_angle/use_spatial need s and veff")
        thetarad = (xyaxes / (1e9 * ds.freq)) * (c * s / (veff * 1000))
        thetaas = (thetarad * 180 / np.pi) * 3600
        if use_angle:
            xyaxes = thetaas
        else:
            if d is None:
                raise ValueError("use_spatial needs the distance d")
            xyaxes = thetaas * (1 - s) * d * 1000

    if plot_log:
        im = im - np.min(im)
        im = im + 1e-10
        im = 10 * np.log10(im)
    valid = im[is_valid(im) & (np.abs(im) > 0)]
    vmin = np.median(valid) - 3 if valid.size else None
    vmax = np.max(valid) - 3 if valid.size else None

    fig = plt.figure(figsize=figsize)
    plt.pcolormesh(centres_to_edges(xyaxes), centres_to_edges(xyaxes),
                   im, vmin=vmin, vmax=vmax, linewidth=0,
                   rasterized=True, shading="auto")
    if use_angle:
        plt.xlabel("Angle parallel to velocity (as)")
        plt.ylabel("Angle perpendicular to velocity (as)")
    elif use_spatial:
        plt.xlabel("Distance parallel to velocity (AU)")
        plt.ylabel("Distance perpendicular to velocity (AU)")
    else:
        plt.xlabel("Angle parallel to velocity")
        plt.ylabel("Angle perpendicular to velocity")
    plt.title(title if title else "Scattered image")
    if colorbar:
        plt.colorbar()
    return _finish(plt, fig, filename, display, dpi)


def plot_eta_evolution(ds, time_avg=False, filename=None, display=True,
                       figsize=(9, 9), dpi=200):
    """η(f) per-chunk datapoints + the fitted η ∝ f⁻² curve after
    ``fit_thetatheta`` (dynspec.py:1746-1764)."""
    from .thth.retrieval import err_string

    plt = _mpl()
    fig = plt.figure(figsize=figsize)
    label = err_string(ds.ththeta * ds.fref ** 2,
                       ds.ththetaerr * ds.fref ** 2)
    if time_avg:
        eta_avg = np.nanmean(ds.eta_evo, 1)
        avg_err = (np.nanstd(ds.eta_evo, 1)
                   / np.sqrt(max(ds.eta_evo.shape[1] - 1, 1)))
        plt.errorbar(ds.f0s, eta_avg, yerr=avg_err, fmt=".")
    else:
        plt.errorbar(
            np.ravel(ds.f0s[:, None] * np.ones(ds.eta_evo.shape)),
            np.ravel(ds.eta_evo), yerr=np.ravel(ds.eta_evo_err),
            fmt=".")
    A = ds.ththeta * ds.fref ** 2
    plt.plot(ds.f0s, A / ds.f0s ** 2,
             label=rf"$\eta$ = {label} $s^3$")
    plt.xlabel(r"$\rm{Freq}~\left(\rm{MHz}\right)$")
    plt.ylabel(r"$\eta~\left(\rm{s}^3\right)$")
    plt.legend()
    return _finish(plt, fig, filename, display, dpi)


def plot_scint_fit_1d(ds, results, xdata_t, ydata_t, t_errors,
                      xdata_f, ydata_f, f_errors, filename=None,
                      display=True, dpi=200):
    """acf1d fit diagnostic: data ± error with the fitted model and
    the ±1/√n white-noise bands (dynspec.py:3051-3109)."""
    from .fit import models as mdl

    plt = _mpl()
    fig, axes = plt.subplots(2, 1, figsize=(8, 6))
    panels = [
        (xdata_t, ydata_t, t_errors, mdl.tau_acf_model_values,
         ds.nsub, r"$\tau$ (s)", r"$\pm 1/\sqrt{n_\mathrm{sub}}$"),
        (xdata_f, ydata_f, f_errors, mdl.dnu_acf_model_values,
         ds.nchan, r"$\Delta\nu$ (MHz)",
         r"$\pm 1/\sqrt{n_\mathrm{chan}}$"),
    ]
    for ax, (x, y, err, model, n, xlabel, wnlabel) in zip(axes,
                                                          panels):
        xm = np.linspace(min(x), max(x), 1000)
        ym = np.asarray(model(results.params, xm))
        ax.plot(x, y, label="data")
        ax.fill_between(x, y + err, y - err, color="C0", alpha=0.4,
                        label="error")
        ax.plot(xm, ym, label="model")
        xl = ax.get_xlim()
        ax.plot([0, xl[1]], [0, 0], "k--")
        wn = 1 / np.sqrt(n)
        ax.plot([0, xl[1]], [wn, wn], ":", color="crimson",
                label=wnlabel)
        ax.plot([0, xl[1]], [-wn, -wn], ":", color="crimson")
        ax.set_xlabel(xlabel)
        ax.legend()
    fig.tight_layout()
    return _finish(plt, fig,
                   filename and _split_filename(filename, "1Dfit"),
                   display, dpi)


def plot_scint_fit_2d(ds, results, method, tdata, fdata, ydata_2d,
                      filename=None, display=True, dpi=200):
    """acf2d fit diagnostic: data / model / residual panels with the
    white-noise spike subtracted (dynspec.py:3111-3155)."""
    from .fit import models as mdl

    plt = _mpl()
    if method == "acf2d_approx":
        model = np.asarray(mdl.scint_acf_model_2d_approx_values(
            results.params, tdata, fdata))
    else:
        model = np.asarray(mdl.scint_acf_model_2d_values(
            results.params, np.shape(ydata_2d)))
    residuals = ydata_2d - model
    fig, axes = plt.subplots(1, 3, sharey=True, figsize=(15, 5))
    for i, (arr, name) in enumerate([(ydata_2d, "data"),
                                     (model, "model"),
                                     (residuals, "residuals")]):
        arr = np.array(arr, dtype=float)
        if name != "residuals":
            arr = np.fft.ifftshift(arr)
            arr[0][0] -= ds.wn
            arr = np.fft.fftshift(arr)
        mesh = axes[i].pcolormesh(centres_to_edges(tdata / 60),
                                  centres_to_edges(fdata), arr,
                                  linewidth=0, rasterized=True,
                                  shading="auto")
        if name == "residuals":
            mesh.set_clim(vmin=-1, vmax=1)
        axes[i].set_title(name)
        axes[i].set_xlabel(r"$\tau$ (mins)")
        if i == 0:
            axes[i].set_ylabel(r"$\Delta\nu$ (MHz)")
    fig.tight_layout()
    return _finish(plt, fig,
                   filename and _split_filename(filename, "2Dfit"),
                   display, dpi)


def plot_all(ds, dyn=1, sspec=3, acf=2, norm_sspec=4, colorbar=True,
             lamsteps=False, filename=None, display=True,
             figsize=(9, 9), dpi=200):
    """Composite summary (dynspec.py plot_all role). The reference
    renders four NUMBERED figures (``dyn``/``sspec``/``acf``/
    ``norm_sspec`` are figure numbers); here the same integers pick
    the subplot ordering of one composite figure — pass 0 to omit a
    panel."""
    plt = _mpl()
    if not hasattr(ds, "acf"):
        ds.calc_acf()
    sec, yaxis = ds._select_sspec(lamsteps=lamsteps)
    valid = sec[is_valid(sec) & (np.abs(sec) > 0)]

    def draw_dyn():
        plt.pcolormesh(centres_to_edges(ds.times / 60),
                       centres_to_edges(ds.freqs), ds.dyn,
                       shading="auto")
        plt.title("Dynamic spectrum")

    def draw_acf():
        plt.pcolormesh(ds.acf, shading="auto")
        plt.title("ACF")

    def draw_sspec():
        plt.pcolormesh(centres_to_edges(ds.fdop),
                       centres_to_edges(yaxis), sec,
                       vmin=np.median(valid) - 3,
                       vmax=np.max(valid) - 3, shading="auto")
        if colorbar:
            plt.colorbar()
        plt.title("Secondary spectrum")

    def draw_norm():
        if hasattr(ds, "normsspecavg"):
            plt.plot(ds.normsspec_fdop, ds.normsspecavg)
            plt.title("Normalised sspec")
        else:
            plt.gca().axis("off")

    panels = sorted([(dyn, draw_dyn), (acf, draw_acf),
                     (sspec, draw_sspec), (norm_sspec, draw_norm)],
                    key=lambda p: p[0])
    panels = [p for p in panels if p[0]]
    fig, axes = plt.subplots(2, 2, figsize=figsize)
    for ax, (_, draw) in zip(axes.ravel(), panels):
        plt.sca(ax)
        draw()
    for ax in axes.ravel()[len(panels):]:
        ax.axis("off")
    plt.tight_layout()
    return _finish(plt, fig, filename, display, dpi)
