"""Device-native batched scenario factory (ISSUE 10 tentpole).

The simulator is the scenario generator behind every robustness and
accuracy claim this repo makes, and until this module it was the last
hot path running the pre-batch shape: host-side RNG, a dense Fresnel
filter re-materialised per frequency, and one compile per parameter
set. This factory rebuilds the batch path as ONE geometry-keyed jitted
program — screens → Fresnel filter → propagate → intensity → dynamic
spectrum — with:

- **epoch batch axis + traced per-lane physics**: ``mb2 / ar / psi /
  alpha`` ride the batch axis as traced inputs (the PR-7 trick of
  folding per-epoch scalars into the batch), so one compile serves a
  whole regime sweep: strong/weak scattering, anisotropy, spectral
  index, all in one program, zero retraces across sweep values. The
  spectral normalisation (``set_constants``: Γ-function factors) is
  evaluated in-program via ``gammaln`` on the traced lane params.
- **on-device PRNG**: lanes are keyed by ``jax.random`` keys split /
  folded on device; no host RNG anywhere in the loop. Per-lane keys
  mean an epoch's screen is independent of how the batch was grouped
  — a quarantined neighbour or a resume regroup never changes the
  data of a healthy lane.
- **column-projected propagation**: the Fresnel filter
  ``exp(-i q2 s)`` is exactly rank-1 separable (``q2 = q2x ⊕ q2y``),
  and only the centre COLUMN of the observer plane is sampled
  (scint_sim.py:226-230) — so the per-frequency ``fft2 → filter →
  ifft2`` collapses to ``ifft(fx ⊙ fft(E @ g))``: one (nx, ny)
  matvec and two LENGTH-nx transforms per frequency instead of two
  full 2-D FFTs. Exact, not approximate (formulation ``'column'``);
  the legacy full-plane path survives as ``'dense'``.
- **incremental phasor** (default formulation ``'phasor'``): the per
  frequency ``exp(i φ s)`` — the remaining dominant cost — becomes a
  carried recurrence ``E_{i+1} = E_i · R̄ · corr(δ_i)`` (one complex
  multiply per step; ``R̄ = exp(i φ Δs̄)`` paid once), with a 3-term
  Taylor correction for the non-uniform frequency grid and a bounded
  exact re-sync every ``PHASOR_RESYNC`` steps so phase error cannot
  accumulate in strong-scattering regimes. This is the throughput
  policy (PR-3 precedent); ``precision='highest'`` keeps the exact
  ambient-dtype path as the parity oracle.
- **compensated screens** (formulation ``sim.screen``): FFT phase
  screens under-represent spectral power below the fundamental
  ``dq = 2π/L`` — the classic fix is a 2× oversized screen cropped
  down, at 4× the FFT area. Following the compensation program of
  arXiv:2208.06060 (the low-frequency residual phase autocorrelation
  is smooth/Gaussian-like, so a cheap low-rank auxiliary field fixes
  it), the ``'compensated'`` formulation adds the missing sub-
  fundamental modes explicitly: a half-lattice refinement of the
  central spectral cells (weights halved exactly as a 2× oversized
  grid would weight them, existing overlapping cells down-weighted to
  match), synthesised as a rank-M correction ``Re(Ex @ C @ Eyᵀ)``
  with M ≈ 16 modes — accuracy of the oversized oracle at ≤ 1/4 its
  FFT area (pinned by tests/test_sim_factory.py against the
  ``'oversized'`` formulation's ensemble phase structure function).
- **in-program quarantine** (PR-2 guards pattern): invalid lane
  params (non-finite, ``mb2 ≤ 0``, ``ar ≤ 0``, ``alpha`` outside
  (0, 2)) flag ``BAD_INPUT`` and the lane's dynspec is NaN'd inside
  the program; a non-finite propagated lane flags bit 2. Neighbour
  lanes are bitwise untouched.
- **execution grouping**: the batch axis is walked in
  ``SIM_GROUP_SIZE`` groups by ``lax.map`` (the fit/acf2d.py
  ``ACF2D_GROUP_SIZE`` discipline) so HBM holds one group of complex
  fields, not the whole epoch stack.

Programs are cached per geometry (``record_build('sim.factory')`` on
every miss — the retrace_guard gate covers the factory), and the
un-jitted builder is exported for the sharded SPMD wrapper
(parallel/survey.py:make_scenario_factory_sharded).
"""

from __future__ import annotations

import numpy as np

from ..backend import get_jax, register_formulation, formulation
from ..ops import xfft
from ..robust.guards import BAD_INPUT
from .simulation import hermitian_fill

#: lanes propagated together per ``lax.map`` step — bounds the live
#: complex-field working set the way ``ACF2D_GROUP_SIZE`` bounds the
#: acf2d solver fleet.
SIM_GROUP_SIZE = 8

#: exact re-sync cadence of the incremental-phasor recurrence: every
#: N-th frequency step recomputes ``exp(i φ s)`` outright, bounding
#: Taylor-drift at ~1e-6 even for multi-hundred-radian screens.
PHASOR_RESYNC = 16

#: ``ok`` bit 2: the propagated lane went non-finite (the sim-side
#: analogue of guards.BAD_CS — input params were fine, output is not).
BAD_OUTPUT = 2

register_formulation(
    "sim.screen", default="compensated",
    choices=("compensated", "oversized", "plain"),
    doc="phase-screen low-frequency treatment: 'compensated' adds the "
        "sub-fundamental spectral modes as a rank-M correction "
        "(arXiv:2208.06060 compensation program; oversized-oracle "
        "accuracy at <=1/4 the FFT area), 'oversized' synthesises a "
        "2x screen and crops (the 4x-FFT-area oracle), 'plain' is the "
        "uncompensated reference screen")

register_formulation(
    "sim.propagate", default="phasor",
    choices=("phasor", "column", "dense"),
    doc="per-frequency Fresnel propagation: 'phasor' = column-"
        "projected transform + incremental exp(i*phi*s) recurrence "
        "(throughput policy), 'column' = column-projected with exact "
        "exp per scale (exact math), 'dense' = legacy full-plane "
        "fft2/ifft2 (staged oracle)")


def effective_wavenumbers(nx, ny, dqx, dqy):
    """Per-cell effective ``(kx, ky)`` grids + filled-cell mask of the
    reference's hermitian fill — recovered by running the fill with
    extractor functions instead of the spectral weight, so every
    value-copy quirk of the reference's mirror indexing is carried
    into the grids exactly. ``screen_weights(...) ==
    mask * swdsp(KX, KY)`` bit-for-bit (pinned in tests)."""
    kx = hermitian_fill(nx, ny, dqx, dqy, lambda a, b: a + 0 * b)
    ky = hermitian_fill(nx, ny, dqx, dqy, lambda a, b: b + 0 * a)
    mask = hermitian_fill(nx, ny, dqx, dqy,
                          lambda a, b: 1 + 0 * a + 0 * b) > 0
    return kx, ky, mask


def compensator_modes(dqx, dqy, levels=1):
    """Sub-fundamental mode lattice of the ``'compensated'`` screen
    formulation: for each refinement level ``l`` the central spectral
    cells are split on the ``dq/2^l`` half-lattice (points already on
    the parent lattice excluded), each mode weighted ``2^-l`` — the
    amplitude a ``2^l``-oversized FFT grid would give that exact
    wavenumber. Returns ``(qx[M], qy[M], scale[M])`` host arrays
    (geometry-only; the spectral weight itself is evaluated in-program
    from the traced per-lane parameters)."""
    qx, qy, scale = [], [], []
    for lev in range(1, levels + 1):
        sx, sy = dqx / 2 ** lev, dqy / 2 ** lev
        for mx in range(-2, 3):
            for my in range(-2, 3):
                if mx % 2 == 0 and my % 2 == 0:
                    continue          # on the parent lattice already
                qx.append(mx * sx)
                qy.append(my * sy)
                scale.append(0.5 ** lev)
    qx, qy = np.asarray(qx), np.asarray(qy)
    scale = np.asarray(scale)
    # deeper levels refine the inner square of the level above: a
    # shallower mode landing inside it loses another factor of 2
    # (its cell is split again), mirroring the nested refinement
    for lev in range(2, levels + 1):
        inner = ((np.abs(qx) <= dqx / 2 ** (lev - 1) + 1e-12)
                 & (np.abs(qy) <= dqy / 2 ** (lev - 1) + 1e-12)
                 & (scale > 0.5 ** lev))
        scale = np.where(inner, scale / 2, scale)
    return qx, qy, scale


def frequency_scale_grid(nf, dlam, lamsteps=False):
    """The per-channel Fresnel scale factors (host, float64):
    uniform in wavelength (``lamsteps=True``, scint_sim.py:216-219)
    or the reference's default reciprocal-frequency grid."""
    ifreq = np.arange(nf)
    if lamsteps:
        return 1.0 + dlam * (ifreq - 1 - nf / 2) / nf
    return 1.0 / (1.0 + dlam * (-0.5 + ifreq / nf))


def build_scenario_fn(ns=128, nf=128, dlam=0.25, rf=1.0, ds=0.01,
                      inner=0.001, nscreens=64, group_size=None,
                      precision=None, screen=None, propagate=None,
                      levels=1, lamsteps=False, output="dynspec"):
    """Un-jitted factory program
    ``fn(keys[B,2]u32, mb2[B], ar[B], psi[B], alpha[B]) →
    (dynspec[B, ns, nf], ok[B]i32)`` (see module docstring).

    ``precision=None`` (the throughput policy) computes in
    float32/complex64 regardless of the ambient x64 flag;
    ``'highest'`` keeps the ambient dtype and forces the exact
    ``'column'`` propagation — the parity oracle. ``screen`` /
    ``propagate`` override the registered ``sim.screen`` /
    ``sim.propagate`` formulations. The sharded SPMD wrapper
    (parallel/survey.py) jits this builder itself; plain callers use
    :func:`make_scenario_factory`."""
    jax = get_jax()
    import jax.numpy as jnp
    from jax.scipy.special import gammaln

    B = int(nscreens)
    G = min(int(group_size or SIM_GROUP_SIZE), B)
    if B % G:
        raise ValueError(f"nscreens={B} not divisible by "
                         f"group_size={G} (pad the lane stack)")
    highest = precision == "highest"
    screen_f = screen or formulation("sim.screen")
    prop_f = propagate or ("column" if highest
                           else formulation("sim.propagate"))
    fdt = jnp.float64 if (highest and jax.config.jax_enable_x64) \
        else jnp.float32
    cdt = jnp.complex128 if fdt == jnp.float64 else jnp.complex64

    # ---- geometry (host precompute, lane-independent) ---------------
    nx = ny = int(ns)
    dx = dy = float(ds)
    lenx, leny = nx * dx, ny * dy
    dqx, dqy = 2 * np.pi / lenx, 2 * np.pi / leny
    ffconx = (2.0 / (lenx * lenx)) * (np.pi * rf) ** 2
    ffcony = (2.0 / (leny * leny)) * (np.pi * rf) ** 2
    column = int(np.floor(ny / 2))
    scales_np = frequency_scale_grid(nf, dlam, lamsteps=lamsteps)

    kxg, kyg, maskg = effective_wavenumbers(nx, ny, dqx, dqy)
    KX2 = jnp.asarray(kxg ** 2, dtype=fdt)
    KY2 = jnp.asarray(kyg ** 2, dtype=fdt)
    KXY = jnp.asarray(kxg * kyg, dtype=fdt)
    K2 = jnp.asarray(kxg ** 2 + kyg ** 2, dtype=fdt)
    MASK = jnp.asarray(maskg)

    if screen_f == "oversized":
        os_ = 2 ** levels
        kxo, kyo, masko = effective_wavenumbers(
            os_ * nx, os_ * ny, dqx / os_, dqy / os_)
        OKX2 = jnp.asarray(kxo ** 2, dtype=fdt)
        OKY2 = jnp.asarray(kyo ** 2, dtype=fdt)
        OKXY = jnp.asarray(kxo * kyo, dtype=fdt)
        OK2 = jnp.asarray(kxo ** 2 + kyo ** 2, dtype=fdt)
        OMASK = jnp.asarray(masko)
    elif screen_f == "compensated":
        mqx, mqy, mscale = compensator_modes(dqx, dqy, levels=levels)
        MQX2 = jnp.asarray(mqx ** 2, dtype=fdt)
        MQY2 = jnp.asarray(mqy ** 2, dtype=fdt)
        MQXY = jnp.asarray(mqx * mqy, dtype=fdt)
        MQ2 = jnp.asarray(mqx ** 2 + mqy ** 2, dtype=fdt)
        MSCALE = jnp.asarray(mscale, dtype=fdt)
        # mode-evaluation matrices: Ex[n, m] = exp(-i qx_m x_n); the
        # compensator field is the rank-M product Re(Ex @ C @ Ey^T)
        xs = (np.arange(nx) * dx)[:, None]
        ys = (np.arange(ny) * dy)[:, None]
        EX = jnp.asarray(np.exp(-1j * xs * mqx[None, :]), dtype=cdt)
        EY = jnp.asarray(np.exp(-1j * ys * mqy[None, :]), dtype=cdt)
        # cells the half-lattice refinement covers lose half their
        # amplitude (their spectral cell shrinks to the refined size),
        # exactly as the oversized grid would weight them
        ringg = (maskg & (np.abs(kxg) <= dqx + 1e-9 * dqx)
                 & (np.abs(kyg) <= dqy + 1e-9 * dqy))
        RING = jnp.asarray(np.where(ringg, 0.5, 1.0), dtype=fdt)

    # ---- propagation constants --------------------------------------
    q2x = jnp.asarray(
        ffconx * np.minimum(np.arange(nx), nx - np.arange(nx))
        .astype(float) ** 2, dtype=fdt)
    q2y = jnp.asarray(
        ffcony * np.minimum(np.arange(ny), ny - np.arange(ny))
        .astype(float) ** 2, dtype=fdt)
    # column-extraction phase (ops/xfft.py separable-kernel
    # property): g = fft(fy * GPH)/ny projects the filtered axis-1
    # inverse transform onto the sampled column
    GPH = jnp.asarray(xfft.column_phase(ny, column), dtype=cdt)
    SCALES = jnp.asarray(scales_np, dtype=fdt)
    if nf > 1:
        diffs = np.diff(scales_np)
        dbar = float(diffs.mean())
        deltas_np = np.concatenate([[0.0], diffs - dbar])
    else:
        dbar, deltas_np = 0.0, np.zeros(1)
    DELTAS = jnp.asarray(deltas_np, dtype=fdt)
    DBAR = jnp.asarray(dbar, dtype=fdt)
    # step indices at which the recurrence re-syncs to an exact exp
    RESYNC = jnp.asarray(
        (np.arange(nf) % PHASOR_RESYNC) == 0)
    q2grid = q2x[:, None] + q2y[None, :]

    def lane_spectrum(kx2, ky2, kxky, k2, mb2, ar, psi, alpha, con):
        """Traced anisotropic-Kolmogorov sqrt-spectrum on arbitrary
        wavenumber grids — the per-lane counterpart of
        simulation._swdsp, broadcast over leading lane axes."""
        cs = jnp.cos(psi * jnp.pi / 180)
        sn = jnp.sin(psi * jnp.pi / 180)
        alf = -(alpha + 2) / 4
        a = cs ** 2 / ar + ar * sn ** 2
        b = ar * cs ** 2 + sn ** 2 / ar
        c = 2 * cs * sn * (1 / ar - ar)
        # lane scalars broadcast over (1, nx, ny) grids or (M,) modes
        ex = (..., None, None) if kx2.ndim == 3 else (..., None)
        q2 = (a[ex] * kx2 + b[ex] * ky2 + c[ex] * kxky)
        return (con[ex] * q2 ** alf[ex]
                * jnp.exp(-k2 * (inner ** 2) / 2))

    def lane_con(mb2, alpha):
        """sqrt(consp) per lane (set_constants, scint_sim.py:137-167)
        — Γ via gammaln so the lane spectral index stays traced."""
        ab = 1.0 - alpha * 0.5
        cmb2 = alpha * mb2 / (4 * jnp.pi * jnp.exp(gammaln(ab))
                              * jnp.cos(alpha * jnp.pi * 0.25))
        consp = cmb2 * dqx * dqy / (rf ** alpha)
        return jnp.sqrt(consp)

    def draw_screens(keys, mb2, ar, psi, alpha, con):
        """(G,) lane keys + params → phase screens (G, nx, ny)."""
        if screen_f == "oversized":
            w = jnp.where(
                OMASK[None],
                lane_spectrum(OKX2[None], OKY2[None], OKXY[None],
                              OK2[None], mb2, ar, psi, alpha,
                              con / (2 ** levels)),
                0.0)
            shape = OMASK.shape
        else:
            w = jnp.where(
                MASK[None],
                lane_spectrum(KX2[None], KY2[None], KXY[None],
                              K2[None], mb2, ar, psi, alpha, con),
                0.0)
            if screen_f == "compensated":
                w = w * RING[None]
            shape = (nx, ny)

        def draw(key):
            # same split + draw-order recipe as the single-epoch
            # _jax_screen_program, so a lane keyed by PRNGKey(seed)
            # reproduces Simulation(seed=seed, backend='jax')'s screen
            # exactly (batched-vs-looped parity, test_sim_factory.py);
            # the compensator stream is folded off the parent key
            k1, k2 = jax.random.split(key)
            z = (jax.random.normal(k1, shape, dtype=fdt)
                 + 1j * jax.random.normal(k2, shape, dtype=fdt))
            return z, jax.random.fold_in(key, 7)

        z, k3 = jax.vmap(draw)(keys)
        phi = jnp.real(jnp.fft.fft2(w * z))
        if screen_f == "oversized":
            phi = phi[:, :nx, :ny]
        elif screen_f == "compensated":
            wm = (lane_spectrum(MQX2, MQY2, MQXY, MQ2, mb2, ar, psi,
                                alpha, con) * MSCALE[None])

            def draw_modes(key):
                zm = jax.random.normal(key, (MSCALE.shape[0], 2),
                                       dtype=fdt)
                return zm[:, 0] + 1j * zm[:, 1]

            zm = jax.vmap(draw_modes)(k3)
            comp = jnp.real(jnp.einsum(
                "xm,gm,ym->gxy", EX, (wm * zm).astype(cdt), EY))
            phi = phi + comp
        return phi.astype(fdt)

    def project_column(E, s):
        """ifft2(fft2(E) * exp(-i q2 s))[:, :, col] via the declared
        rank-1 separability of the Fresnel filter (ops/xfft.py
        ``separable_kernel`` lowering): one (nx, ny) matvec and two
        length-nx transforms — no 2-D FFT (module docstring).
        Bit-identical to the pre-layer inline formulation (pinned in
        tests/test_xfft.py)."""
        fy = jnp.exp(-1j * (q2y * s).astype(fdt)).astype(cdt)
        fx = jnp.exp(-1j * (q2x * s).astype(fdt)).astype(cdt)
        return xfft.separable_filter_column(E, fx, fy, GPH, xp=jnp)

    def propagate_group(xyp):
        """Phase screens (G, nx, ny) → complex field column
        spe (G, nx, nf) by the active propagation formulation."""
        xyp = xyp.astype(fdt)
        if prop_f == "dense":
            def one(s):
                xye = jnp.fft.fft2(jnp.exp(1j * (xyp * s).astype(cdt)))
                xye = xye * jnp.exp(
                    -1j * (q2grid * s).astype(cdt))[None]
                return jnp.fft.ifft2(xye)[:, :, column]

            spe = jax.lax.map(one, SCALES)
        elif prop_f == "column":
            def one(s):
                E = jnp.exp(1j * (xyp * s)).astype(cdt)
                return project_column(E, s)

            spe = jax.lax.map(one, SCALES)
        else:                                         # phasor
            R = jnp.exp(1j * (xyp * DBAR)).astype(cdt)

            def step(E_prev, inp):
                s, d, sync = inp
                pd = (xyp * d).astype(fdt)
                corr = (1 + 1j * pd - 0.5 * pd * pd
                        - (1j / 6) * pd * pd * pd).astype(cdt)
                E = jax.lax.cond(
                    sync,
                    lambda: jnp.exp(1j * (xyp * s)).astype(cdt),
                    lambda: E_prev * R * corr)
                return E, project_column(E, s)

            _, spe = jax.lax.scan(
                step, jnp.zeros(xyp.shape, dtype=cdt),
                (SCALES, DELTAS, RESYNC))
        return jnp.transpose(spe, (1, 2, 0))          # (G, nx, nf)

    def run_group(args):
        keys, mb2, ar, psi, alpha = args
        lane_ok = (jnp.isfinite(mb2) & jnp.isfinite(ar)
                   & jnp.isfinite(psi) & jnp.isfinite(alpha)
                   & (mb2 > 0) & (ar > 0)
                   & (alpha > 0) & (alpha < 2))
        mb2 = jnp.where(lane_ok, mb2, 2.0).astype(fdt)
        ar = jnp.where(lane_ok, ar, 1.0).astype(fdt)
        psi = jnp.where(lane_ok, psi, 0.0).astype(fdt)
        alpha = jnp.where(lane_ok, alpha, 5 / 3).astype(fdt)
        con = lane_con(mb2, alpha)
        phi = draw_screens(keys, mb2, ar, psi, alpha, con)
        if output == "screens":
            spi = phi
        else:
            spe = propagate_group(phi)
            spi = (spe.real ** 2 + spe.imag ** 2).astype(fdt)
        out_ok = jnp.all(jnp.isfinite(spi), axis=(1, 2))
        code = jnp.where(lane_ok,
                         jnp.where(out_ok, 0, BAD_OUTPUT),
                         BAD_INPUT).astype(jnp.int32)
        spi = jnp.where((code == 0)[:, None, None], spi, jnp.nan)
        return spi, code

    def run(keys, mb2, ar, psi, alpha):
        grp = (B // G, G)
        spi, code = jax.lax.map(run_group, (
            keys.reshape(grp + keys.shape[1:]),
            mb2.reshape(grp).astype(fdt),
            ar.reshape(grp).astype(fdt),
            psi.reshape(grp).astype(fdt),
            alpha.reshape(grp).astype(fdt)))
        return (spi.reshape((B,) + spi.shape[2:]),
                code.reshape(B))

    return run


# geometry-keyed program cache (retrace_guard-visible: every miss is
# one record_build('sim.factory') — a regime sweep over traced lane
# params is exactly one entry)
_SCENARIO_CACHE = {}


def make_scenario_factory(ns=128, nf=128, dlam=0.25, rf=1.0, ds=0.01,
                          inner=0.001, nscreens=64, group_size=None,
                          precision=None, screen=None, propagate=None,
                          levels=1, lamsteps=False, output="dynspec"):
    """Cached jitted scenario factory — :func:`build_scenario_fn`
    under one geometry-keyed ``jax.jit``. The key includes the
    RESOLVED formulations, so an operator flipping
    ``SCINTOOLS_FORMULATION_SIM_SCREEN`` gets a fresh program, not a
    stale cache hit."""
    highest = precision == "highest"
    screen_f = screen or formulation("sim.screen")
    prop_f = propagate or ("column" if highest
                           else formulation("sim.propagate"))
    key = (int(ns), int(nf), float(dlam), float(rf), float(ds),
           float(inner), int(nscreens),
           int(min(group_size or SIM_GROUP_SIZE, nscreens)),
           precision, screen_f, prop_f, int(levels), bool(lamsteps),
           output)
    fn = _SCENARIO_CACHE.get(key)
    if fn is None:
        jax = get_jax()
        from ..obs import retrace as _retrace

        _retrace.record_build("sim.factory", key)
        fn = jax.jit(build_scenario_fn(
            ns=ns, nf=nf, dlam=dlam, rf=rf, ds=ds, inner=inner,
            nscreens=nscreens, group_size=group_size,
            precision=precision, screen=screen_f, propagate=prop_f,
            levels=levels, lamsteps=lamsteps, output=output))
        if len(_SCENARIO_CACHE) >= 32:
            _SCENARIO_CACHE.pop(next(iter(_SCENARIO_CACHE)))
        _SCENARIO_CACHE[key] = fn
    return fn


def lane_keys_from_seeds(seeds):
    """Per-lane legacy PRNG keys from integer lane seeds, built on
    device (vmapped ``PRNGKey``; no host RNG). Stable per seed — an
    epoch keyed by its seed generates the same screen no matter how
    the surrounding batch was grouped or resumed."""
    jax = get_jax()
    import jax.numpy as jnp

    seeds = jnp.asarray(seeds, dtype=jnp.uint32)
    return jax.vmap(
        lambda s: jax.random.PRNGKey(s).astype(jnp.uint32))(seeds)


def simulate_scenarios(nscreens, mb2=2.0, ar=1.0, psi=0.0,
                       alpha=5 / 3, ns=128, nf=128, dlam=0.25,
                       rf=1.0, ds=0.01, inner=0.001, seed=0,
                       keys=None, group_size=None, precision=None,
                       screen=None, propagate=None, levels=1,
                       lamsteps=False, with_ok=False,
                       device_out=False, output="dynspec"):
    """Batched scenario generation through the device-native factory:
    ``nscreens`` dynamic spectra ``(B, ns, nf)`` in one program.

    ``mb2 / ar / psi / alpha`` may be scalars (broadcast) or
    per-lane arrays — a multi-regime sweep rides one compile. Lanes
    are keyed by on-device splits of ``PRNGKey(seed)`` (or explicit
    ``keys[B, 2]``). ``with_ok`` also returns the per-lane int32
    health code (0 healthy, 1 bad params, 2 non-finite output);
    ``device_out`` skips the host fetch so downstream device programs
    consume the stack in flight."""
    jax = get_jax()
    import jax.numpy as jnp

    B = int(nscreens)
    G = min(int(group_size or SIM_GROUP_SIZE), B)
    pad = (-B) % G
    Bp = B + pad

    def lanes(v):
        arr = np.broadcast_to(np.asarray(v, dtype=float), (B,))
        if pad:
            arr = np.concatenate([arr, np.repeat(arr[-1:], pad)])
        return jnp.asarray(arr)

    if keys is None:
        keys = jax.random.split(jax.random.PRNGKey(seed), Bp)
    elif pad:
        keys = jnp.concatenate([jnp.asarray(keys),
                                jnp.asarray(keys)[-1:].repeat(pad, 0)])
    fn = make_scenario_factory(
        ns=ns, nf=nf, dlam=dlam, rf=rf, ds=ds, inner=inner,
        nscreens=Bp, group_size=G, precision=precision, screen=screen,
        propagate=propagate, levels=levels, lamsteps=lamsteps,
        output=output)
    dyn, ok = fn(jnp.asarray(keys), lanes(mb2), lanes(ar),
                 lanes(psi), lanes(alpha))
    dyn, ok = dyn[:B], ok[:B]
    if not device_out:
        dyn, ok = np.asarray(dyn), np.asarray(ok)
    return (dyn, ok) if with_ok else dyn


def simulate_screens(nscreens, **kw):
    """Phase screens only — :func:`simulate_scenarios` with the
    propagation stage skipped (``(B, ns, ns)`` float): the entry the
    compensated-vs-oversized structure-function oracle tests and any
    screen-statistics consumer use."""
    return simulate_scenarios(nscreens, output="screens", **kw)


# ---------------------------------------------------------------------
# abstract program probes (obs/programs.py) — audited by the jaxlint
# JP2xx program pass (tools/jaxlint/program.py)
# ---------------------------------------------------------------------

from ..obs.programs import register_probe as _register_probe  # noqa: E402


@_register_probe("sim.factory",
                 formulations=("sim.screen", "sim.propagate"))
def _probe_sim_factory():
    """The cached device-native scenario factory at a fixed 8x8
    screen, 4 frequencies, 2 lanes (legacy uint32 lane keys; lane
    physics params traced)."""
    import jax

    fn = make_scenario_factory(ns=8, nf=4, nscreens=2, group_size=2)
    S = jax.ShapeDtypeStruct
    lane = S((2,), np.float32)
    return fn, (S((2, 2), np.uint32), lane, lane, lane, lane)
