"""Simulation subpackage: Coles-2010 EM simulation, Rickett-2014
analytic ACF, Yao-2020 brightness (scint_sim.py re-design), and the
device-native batched scenario factory + closed-loop scenario survey
(ISSUE 10)."""

from .simulation import Simulation, simulate_dynspec_batch
from .factory import (make_scenario_factory, simulate_scenarios,
                      simulate_screens, lane_keys_from_seeds,
                      SIM_GROUP_SIZE)
from .scenario import (run_scenario_survey, scenario_truths,
                       recovery_summary, DEFAULT_REGIMES)
from .acf_model import ACF
from .brightness import Brightness

__all__ = ["Simulation", "simulate_dynspec_batch",
           "make_scenario_factory", "simulate_scenarios",
           "simulate_screens", "lane_keys_from_seeds",
           "SIM_GROUP_SIZE", "run_scenario_survey", "scenario_truths",
           "recovery_summary", "DEFAULT_REGIMES", "ACF", "Brightness"]
