"""Simulation subpackage: Coles-2010 EM simulation, Rickett-2014
analytic ACF, Yao-2020 brightness (scint_sim.py re-design)."""

from .simulation import Simulation, simulate_dynspec_batch
from .acf_model import ACF
from .brightness import Brightness

__all__ = ["Simulation", "simulate_dynspec_batch", "ACF", "Brightness"]
