"""Electromagnetic scintillation simulator (Coles et al. 2010).

TPU-first re-design of ``Simulation`` (/root/reference/scintools/
scint_sim.py:23-414): a Kolmogorov phase screen is drawn in the spectral
domain and propagated to the observer plane with a Fresnel
quadratic-phase filter, once per frequency channel.

Design notes (vs the reference):

- The spectral weight array ``w`` is built once host-side in numpy with
  exactly the reference's hermitian fill pattern (scint_sim.py:169-198),
  so the numpy backend is bit-identical to the reference given the same
  numpy seed.
- The Fresnel filter is applied in closed form over the whole FFT grid
  using index symmetry q_i = min(i, n-i) — mathematically identical to
  the reference's four-quadrant slicing (scint_sim.py:294-311).
- The per-frequency python loop (scint_sim.py:214-230) becomes a
  ``vmap`` over the frequency axis on the jax path; batches of
  simulations vmap over seeds (BASELINE config #4).
- RNG: numpy backend uses numpy's global-free ``default_rng``-style
  seeding identical in call order to the reference (``np.random.seed``
  then two ``randn(nx, ny)``); jax backend uses ``jax.random`` with an
  explicit key. Cross-backend equality is statistical, not bitwise.
"""

from __future__ import annotations

import os

import numpy as np
from numpy import random as nprandom
from scipy.special import gamma as _gamma

from ..backend import get_xp, resolve_backend, get_jax

SPEED_OF_LIGHT = 299792458.0  # m/s


def _swdsp(kx, ky, psi, ar, alpha, inner, consp):
    """Anisotropic Kolmogorov spectral weight √P(kx,ky)
    (scint_sim.py:276-292)."""
    cs = np.cos(psi * np.pi / 180)
    sn = np.sin(psi * np.pi / 180)
    r = ar
    con = np.sqrt(consp)
    alf = -(alpha + 2) / 4
    a = (cs ** 2) / r + r * sn ** 2
    b = r * cs ** 2 + sn ** 2 / r
    c = 2 * cs * sn * (1 / r - r)
    q2 = a * kx ** 2 + b * ky ** 2 + c * kx * ky
    with np.errstate(divide="ignore"):
        out = con * q2 ** alf * np.exp(-(kx ** 2 + ky ** 2)
                                       * inner ** 2 / 2)
    return out


def hermitian_fill(nx, ny, dqx, dqy, swdsp):
    """The reference's exact hermitian fill pattern
    (scint_sim.py:175-198), vectorised, with the spectral function
    abstracted out: ``swdsp(kx, ky)`` is evaluated on the reference's
    wavenumber arguments and its VALUES are mirrored into the
    conjugate cells (value copies, so the reference's one-off mirror
    indexing quirks are reproduced bit-for-bit).

    Because only values are copied, calling this with extractor
    functions (``lambda kx, ky: kx + 0 * ky``) recovers the EFFECTIVE
    per-cell wavenumber grids — which is how the batched factory
    (sim/factory.py:effective_wavenumbers) rebuilds the same w from
    traced per-lane spectral parameters."""
    nx2 = int(nx / 2 + 1)
    ny2 = int(ny / 2 + 1)
    w = np.zeros([nx, ny])

    # ky=0 line
    k = np.arange(2, nx2 + 1)
    w[k - 1, 0] = swdsp((k - 1) * dqx, np.zeros(len(k)))
    w[nx + 1 - k, 0] = w[k, 0]
    # kx=0 line
    ll = np.arange(2, ny2 + 1)
    w[0, ll - 1] = swdsp(np.zeros(len(ll)), (ll - 1) * dqy)
    w[0, ny + 1 - ll] = w[0, ll - 1]
    # rest of the field (vectorised over the reference's il loop)
    kp = np.arange(2, nx2 + 1)
    k = np.arange(nx2 + 1, nx + 1)
    km = -(nx - k + 1)
    il = np.arange(2, ny2 + 1)
    w[np.ix_(kp - 1, il - 1)] = swdsp(((kp - 1) * dqx)[:, None]
                                      + 0 * il[None, :],
                                      ((il - 1) * dqy)[None, :]
                                      + 0 * kp[:, None])
    w[np.ix_(k - 1, il - 1)] = swdsp((km * dqx)[:, None]
                                     + 0 * il[None, :],
                                     ((il - 1) * dqy)[None, :]
                                     + 0 * km[:, None])
    w[np.ix_(nx + 1 - kp, ny + 1 - il)] = w[np.ix_(kp - 1, il - 1)]
    w[np.ix_(nx + 1 - k, ny + 1 - il)] = w[np.ix_(k - 1, il - 1)]
    return w


def screen_weights(nx, ny, dx, dy, psi, ar, alpha, inner, consp):
    """Spectral weight array ``w[nx, ny]`` with the reference's exact
    hermitian fill (scint_sim.py:175-198), vectorised."""
    dqx = 2 * np.pi / (dx * nx)
    dqy = 2 * np.pi / (dy * ny)
    return hermitian_fill(
        nx, ny, dqx, dqy,
        lambda kx, ky: _swdsp(kx, ky, psi, ar, alpha, inner, consp))


def fresnel_filter_q2(nx, ny, ffconx, ffcony):
    """Quadratic-phase exponent grid q2[i,j] = ffconx·min(i,nx−i)² +
    ffcony·min(j,ny−j)² — closed form of the reference's quadrant
    filter (scint_sim.py:294-311)."""
    ix = np.minimum(np.arange(nx), nx - np.arange(nx)).astype(float)
    iy = np.minimum(np.arange(ny), ny - np.arange(ny)).astype(float)
    return ffconx * ix[:, None] ** 2 + ffcony * iy[None, :] ** 2


def propagate(xyp, q2, scales, xp, column):
    """Fresnel-propagate phase screen to the observer plane for each
    frequency scale; returns complex field spe[nx, nf] as a **host
    numpy** array on both backends.

    xye(f) = ifft2( fft2(exp(i·φ·scale)) · exp(−i·q2·scale) ), sampled
    along the centre column (scint_sim.py:226-230).

    TPU note: the jax path runs as ONE jitted program whose outputs are
    the stacked (real, imag) floats — complex buffers must not cross
    program boundaries on TPU runtimes that can't transfer them (the
    tunneled-TPU transfer of complex arrays is UNIMPLEMENTED).
    """
    if xp is np:
        def one_freq(scale):
            xye = np.fft.fft2(np.exp(1j * xyp * scale))
            xye = xye * np.exp(-1j * q2 * scale)
            return np.fft.ifft2(xye)[:, column]

        nf = len(scales)
        spe = np.zeros((xyp.shape[0], nf), dtype=complex)
        for i, s in enumerate(scales):
            spe[:, i] = one_freq(s)
        return spe
    fn = _jax_propagate_program()
    sre, sim_ = fn(xp.asarray(xyp), xp.asarray(q2),
                   xp.asarray(np.asarray(scales)), column)
    return np.asarray(sre) + 1j * np.asarray(sim_)


_PROP_JIT = None
_SCREEN_JIT = None


def _jax_screen_program():
    """Cached jitted phase-screen draw: (w, key) → φ = Re fft2(w·(N+iN))
    (scint_sim.py:199-207), real output."""
    global _SCREEN_JIT
    if _SCREEN_JIT is None:
        jax = get_jax()
        import jax.numpy as jnp

        from ..obs import retrace as _retrace

        _retrace.record_build("sim.screen")

        def run(w, key):
            k1, k2 = jax.random.split(key)
            re = jax.random.normal(k1, w.shape)
            im = jax.random.normal(k2, w.shape)
            return jnp.real(jnp.fft.fft2(w * (re + 1j * im)))

        _SCREEN_JIT = jax.jit(run)
    return _SCREEN_JIT


def _jax_propagate_program():
    """Cached jitted Fresnel propagation: (xyp, q2, scales, column) →
    (spe.real, spe.imag). Real-only program boundaries (see propagate)."""
    global _PROP_JIT
    if _PROP_JIT is None:
        jax = get_jax()
        import jax.numpy as jnp

        from ..obs import retrace as _retrace

        _retrace.record_build("sim.propagate")

        def run(xyp, q2, scales, column):
            def one_freq(scale):
                xye = jnp.fft.fft2(jnp.exp(1j * xyp * scale))
                xye = xye * jnp.exp(-1j * q2 * scale)
                col = jnp.fft.ifft2(xye)[:, column]
                return col.real, col.imag

            return jax.vmap(one_freq, out_axes=1)(scales)

        _PROP_JIT = jax.jit(run, static_argnames=("column",))
    return _PROP_JIT


class Simulation:
    """Drop-in equivalent of the reference ``Simulation`` class.

    Parameters follow scint_sim.py:25-45. ``backend`` selects numpy
    (default, bit-reproducible) or jax (TPU).
    """

    def __init__(self, mb2=2, rf=1, ds=0.01, alpha=5 / 3, ar=1, psi=0,
                 inner=0.001, ns=256, nf=256, dlam=0.25, lamsteps=False,
                 seed=None, nx=None, ny=None, dx=None, dy=None,
                 plot=False, verbose=False, freq=1400, dt=30, mjd=60000,
                 nsub=None, efield=False, noise=None, backend=None):
        self.mb2 = mb2
        self.rf = rf
        self.ds = ds
        self.dx = dx if dx is not None else ds
        self.dy = dy if dy is not None else ds
        self.alpha = alpha
        self.ar = ar
        self.psi = psi
        self.inner = inner
        self.nx = nx if nx is not None else ns
        self.ny = ny if ny is not None else ns
        self.nf = nf
        self.dlam = dlam
        self.lamsteps = lamsteps
        self.seed = seed
        self.noise = noise  # accepted-and-unused upstream too
        self.backend = resolve_backend(backend)

        self.set_constants()
        if verbose:
            print("Computing screen phase")
        self.get_screen()
        if verbose:
            print("Getting intensity...")
        self.get_intensity()
        if nf > 1:
            if verbose:
                print("Computing dynamic spectrum")
            self.get_dynspec()
        if verbose:
            print("Getting impulse response...")
        self.get_pulse()
        if plot:
            self.plot_all()  # scint_sim.py:78-79

        # physical-units packaging (scint_sim.py:81-134)
        self.name = "sim:mb2={0},ar={1},psi={2},dlam={3}".format(
            self.mb2, self.ar, self.psi, self.dlam)
        if lamsteps:
            self.name += ",lamsteps"
        self.header = [self.name, "MJD0: {}".format(mjd)]
        dyn = np.real(np.asarray(self.spe)) if efield else np.asarray(self.spi)

        self.dt = dt
        self.freq = freq
        self.nsub = int(np.shape(dyn)[0]) if nsub is None else nsub
        self.nchan = int(np.shape(dyn)[1])
        if not lamsteps:
            self.df = self.freq * self.dlam / (self.nchan - 1)
            self.freqs = self.freq + np.arange(-self.nchan / 2,
                                               self.nchan / 2, 1) * self.df
        else:
            self.lam = SPEED_OF_LIGHT / (self.freq * 10 ** 6)
            self.dl = self.lam * self.dlam / (self.nchan - 1)
            self.lams = self.lam + np.arange(-self.nchan / 2,
                                             self.nchan / 2, 1) * self.dl
            self.freqs = SPEED_OF_LIGHT / self.lams / 10 ** 6
            self.freq = (np.max(self.freqs) - np.min(self.freqs)) / 2
        self.bw = max(self.freqs) - min(self.freqs)
        self.times = self.dt * np.arange(0, self.nsub)
        self.df = self.bw / self.nchan
        self.tobs = float(self.times[-1] - self.times[0])
        self.mjd = mjd
        if nsub is not None:
            dyn = dyn[0:nsub, :]
        self.dyn = np.transpose(dyn)

        # theoretical arc curvature oracle (scint_sim.py:123-133)
        V = self.ds / self.dt
        k_wave = 2 * np.pi / self.freq
        L = self.rf ** 2 * k_wave
        self.eta = (L / (2 * V ** 2) / 10 ** 6
                    / np.cos(psi * np.pi / 180) ** 2)
        beta_to_eta = SPEED_OF_LIGHT * 1e6 / ((self.freq * 10 ** 6) ** 2)
        self.betaeta = self.eta / beta_to_eta

    # ------------------------------------------------------------------
    def set_constants(self):
        """Normalisation constants (scint_sim.py:137-167)."""
        ns = 1
        lenx = self.nx * self.dx
        leny = self.ny * self.dy
        self.ffconx = (2.0 / (ns * lenx * lenx)) * (np.pi * self.rf) ** 2
        self.ffcony = (2.0 / (ns * leny * leny)) * (np.pi * self.rf) ** 2
        dqx = 2 * np.pi / lenx
        dqy = 2 * np.pi / leny
        a2 = self.alpha * 0.5
        aa = 1.0 + a2
        ab = 1.0 - a2
        cdrf = (2.0 ** self.alpha * np.cos(self.alpha * np.pi * 0.25)
                * _gamma(aa) / self.mb2)
        self.s0 = self.rf * cdrf ** (1.0 / self.alpha)
        cmb2 = self.alpha * self.mb2 / (
            4 * np.pi * _gamma(ab) * np.cos(self.alpha * np.pi * 0.25) * ns)
        self.consp = cmb2 * dqx * dqy / (self.rf ** self.alpha)
        self.scnorm = 1.0 / (self.nx * self.ny)
        self.sref = self.rf ** 2 / self.s0

    def get_screen(self):
        """Phase screen φ(x,y) = Re fft2(w·(N + iN))
        (scint_sim.py:169-207).

        Reproducibility contract: an explicit integer ``seed`` (≥ 0)
        is deterministic on both backends — same seed, same screen,
        run to run. ``seed=None`` (and the reference's ``-1``
        sentinel) draws FRESH entropy at this driver level on every
        call — two unseeded simulations differ. (Before ISSUE 10 the
        jax path silently mapped None/-1 to ``PRNGKey(0)``, so every
        "unseeded" simulation was the same deterministic screen; the
        numpy path already drew fresh entropy via
        ``np.random.seed(None)``.) The seed actually used is recorded
        as ``self.seed_used`` so an interesting unseeded run can be
        reproduced afterwards."""
        w = screen_weights(self.nx, self.ny, self.dx, self.dy, self.psi,
                           self.ar, self.alpha, self.inner, self.consp)
        self.w = w
        self.seed_used = (int.from_bytes(os.urandom(4), "little")
                          & 0x7FFFFFFF) \
            if self.seed in (None, -1) else int(self.seed)
        if self.backend == "jax":
            jax = get_jax()
            import jax.numpy as jnp
            key = jax.random.PRNGKey(self.seed_used)
            # one jitted program, real in / real out (complex buffers
            # cannot cross program boundaries on the tunneled TPU);
            # real buffers can, so keep the device copy for propagate
            self._xyp_dev = _jax_screen_program()(jnp.asarray(w), key)
            xyp = np.asarray(self._xyp_dev)
        else:
            nprandom.seed(self.seed_used)
            xyp = np.real(np.fft.fft2(
                w * (nprandom.randn(self.nx, self.ny)
                     + 1j * nprandom.randn(self.nx, self.ny))))
        self.xyp = xyp

    def frfilt3(self, xye, scale):
        """Apply the Fresnel quadratic-phase filter in place — parity
        entry for the reference's quadrant-sliced method
        (scint_sim.py:294-311). The closed-form q2 grid
        (fresnel_filter_q2) is mathematically identical to the four
        quadrant multiplies."""
        q2 = fresnel_filter_q2(self.nx, self.ny, self.ffconx,
                               self.ffcony)
        xye *= np.exp(-1j * q2 * scale)
        return xye

    def frequency_scales(self):
        ifreq = np.arange(self.nf)
        if self.lamsteps:
            return 1.0 + self.dlam * (ifreq - 1 - self.nf / 2) / self.nf
        frfreq = 1.0 + self.dlam * (-0.5 + ifreq / self.nf)
        return 1.0 / frfreq

    def get_intensity(self):
        """Fresnel propagation per frequency → spe[nx, nf]
        (scint_sim.py:209-236)."""
        xp = get_xp(self.backend)
        q2 = fresnel_filter_q2(self.nx, self.ny, self.ffconx, self.ffcony)
        scales = self.frequency_scales()
        column = int(np.floor(self.ny / 2))
        # use the device-resident screen if get_screen just made one
        # (skips a host→device re-upload), then drop it: it is only
        # needed here, and keeping it would pin HBM and go stale if
        # the caller redraws or edits self.xyp
        xyp = self.__dict__.pop("_xyp_dev", self.xyp)
        self.spe = propagate(xyp, q2, scales, xp, column)
        self._q2 = q2

    @property
    def xyi(self):
        """Intensity image at the last frequency (the reference keeps the
        loop's final plane, scint_sim.py:232-234). Computed lazily —
        only plotting uses it (host numpy; one plane)."""
        if not hasattr(self, "_xyi"):
            scale = self.frequency_scales()[-1]
            xye = np.fft.ifft2(
                np.fft.fft2(np.exp(1j * self.xyp * scale))
                * np.exp(-1j * self._q2 * scale))
            self._xyi = np.real(xye * np.conj(xye))
        return self._xyi

    def get_dynspec(self):
        """spi = |spe|² plus normalised axes (scint_sim.py:238-252).
        ``spe`` is always a host array after get_intensity."""
        self.spi = np.real(self.spe * np.conj(self.spe))
        self.x = np.linspace(0, self.dx * self.nx, self.nx)
        ifreq = np.linspace(0, self.nf - 1, self.nf)
        lam_norm = 1.0 + self.dlam * (ifreq - 1 - self.nf / 2) / self.nf
        self.lams = lam_norm / np.mean(lam_norm)
        frfreq = 1.0 + self.dlam * (-0.5 + ifreq / self.nf)
        self.freqs = frfreq / np.mean(frfreq)

    def get_pulse(self):
        """Intensity impulse response vs position (scint_sim.py:254-274).
        Host-side: ``spe`` is a host array and this is a one-shot small
        FFT (complex buffers can't cross TPU program boundaries)."""
        p = np.fft.fft(self.spe * np.blackman(self.nf), 2 * self.nf)
        p = np.real(p * np.conj(p))
        self.pulsewin = np.transpose(np.roll(p, self.nf, axis=-1))
        self.dm = np.asarray(self.xyp)[:, int(self.ny / 2)] * self.dlam / np.pi

    # -- plotting (scint_sim.py:313-415) -------------------------------
    def plot_screen(self, subplot=False, **kwargs):
        from .plots import plot_screen
        return plot_screen(self, subplot=subplot, **kwargs)

    def plot_intensity(self, subplot=False, **kwargs):
        from .plots import plot_intensity
        return plot_intensity(self, subplot=subplot, **kwargs)

    def plot_dynspec(self, subplot=False, **kwargs):
        from .plots import plot_sim_dynspec
        return plot_sim_dynspec(self, subplot=subplot, **kwargs)

    def plot_efield(self, subplot=False, **kwargs):
        from .plots import plot_efield
        return plot_efield(self, subplot=subplot, **kwargs)

    def plot_delay(self, **kwargs):
        from .plots import plot_delay
        return plot_delay(self, **kwargs)

    def plot_pulse(self, **kwargs):
        from .plots import plot_pulse
        return plot_pulse(self, **kwargs)

    def plot_all(self, **kwargs):
        from .plots import plot_sim_all
        return plot_sim_all(self, **kwargs)


def make_dynspec_batch_fn(mb2=2, rf=1, ds=0.01, alpha=5 / 3,
                          ar=1, psi=0, inner=0.001, ns=128, nf=128,
                          dlam=0.25):
    """Batched simulator ``fn(keys[B]) → dynspecs[B, ns, nf]`` — an
    API-continuity wrapper over the device-native scenario factory
    (sim/factory.py, ISSUE 10): the fixed scalar parameters ride the
    batch axis as traced per-lane inputs, so every parameter set
    shares ONE compiled program per geometry (``sim.factory`` retrace
    site) instead of one per parameter tuple, and screens default to
    the ``'compensated'`` low-frequency formulation."""
    from .factory import simulate_scenarios

    def fn(keys):
        return simulate_scenarios(
            int(np.shape(keys)[0]), mb2=mb2, ar=ar, psi=psi,
            alpha=alpha, ns=ns, nf=nf, dlam=dlam, rf=rf, ds=ds,
            inner=inner, keys=keys, device_out=True)

    return fn


def simulate_dynspec_batch(nscreens, mb2=2, rf=1, ds=0.01, alpha=5 / 3,
                           ar=1, psi=0, inner=0.001, ns=128, nf=128,
                           dlam=0.25, seed=0):
    """Batched screens → dynspecs on the jax backend (BASELINE config
    #4): one geometry-keyed program, batch dimension over on-device
    key splits of ``PRNGKey(seed)`` (sim/factory.py)."""
    from .factory import simulate_scenarios

    return simulate_scenarios(
        nscreens, mb2=mb2, ar=ar, psi=psi, alpha=alpha, ns=ns, nf=nf,
        dlam=dlam, rf=rf, ds=ds, inner=inner, seed=seed,
        device_out=True)


# ---------------------------------------------------------------------
# abstract program probes (obs/programs.py) — audited by the jaxlint
# JP2xx program pass (tools/jaxlint/program.py)
# ---------------------------------------------------------------------

from ..obs.programs import register_probe as _register_probe  # noqa: E402


@_register_probe("sim.screen")
def _probe_sim_screen():
    """The cached phase-screen draw at a fixed 8x8 screen (legacy
    uint32 PRNG key, as the Simulation driver passes it)."""
    import jax

    fn = _jax_screen_program()
    S = jax.ShapeDtypeStruct
    return fn, (S((8, 8), np.float32), S((2,), np.uint32))


@_register_probe("sim.propagate")
def _probe_sim_propagate():
    """The cached Fresnel propagation at a fixed 8x8 screen over 4
    frequencies (the ``column`` extraction index is static)."""
    import jax

    fn = _jax_propagate_program()
    S = jax.ShapeDtypeStruct
    return (lambda xyp, q2, scales: fn(xyp, q2, scales, column=4)), (
        S((8, 8), np.float32), S((8, 8), np.float32),
        S((4,), np.float32))


# (the former ``sim.dynspec_batch`` site/probe is gone: the batch
# path is the ``sim.factory`` program now — probed in sim/factory.py)
