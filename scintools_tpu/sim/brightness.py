"""Delay-Doppler spectrum from a scattered angular spectrum
(Yao et al. 2020; Coles' original Matlab).

Re-design of the reference ``Brightness`` class
(/root/reference/scintools/scint_sim.py:768-1065). The double python
loop over (delay, doppler) building θx/θy and the Jacobian
(scint_sim.py:911-925) is fully vectorised, and the scattered-image
lookup uses bilinear interpolation on the regular brightness grid
(the reference uses Delaunay-based ``griddata(method='linear')``,
which agrees with bilinear up to the triangulation's in-cell split).
"""

from __future__ import annotations

import numpy as np

from ..backend import get_xp, resolve_backend


def _bilinear(B, x0, dx, qx, qy, xp):
    """Sample B on the regular grid origin x0/step dx at points
    (qx, qy); NaN outside (griddata-compatible)."""
    fx = (qx - x0) / dx
    fy = (qy - x0) / dx
    n = B.shape[0]
    ix = xp.clip(xp.floor(fx).astype(int), 0, n - 2)
    iy = xp.clip(xp.floor(fy).astype(int), 0, n - 2)
    tx = fx - ix
    ty = fy - iy
    # B indexed [y, x] (meshgrid convention)
    v = (B[iy, ix] * (1 - tx) * (1 - ty) + B[iy, ix + 1] * tx * (1 - ty)
         + B[iy + 1, ix] * (1 - tx) * ty + B[iy + 1, ix + 1] * tx * ty)
    inside = ((fx >= 0) & (fx <= n - 1) & (fy >= 0) & (fy <= n - 1))
    return xp.where(inside, v, xp.nan)


class Brightness:
    """Analytic brightness distribution → secondary spectrum → ACF."""

    def __init__(self, ar=1.0, psi=0, alpha=1.67, thetagx=0, thetagy=0,
                 thetarx=0, thetary=0, df=0.02, dt=0.08, dx=0.1,
                 nf=10, nt=80, nx=30, ncuts=5, plot=False, contour=True,
                 figsize=(10, 8), calc_sspec=True, calc_acf=True,
                 backend=None):
        self.ar = ar
        self.alpha = alpha
        self.thetagx = thetagx
        self.thetagy = thetagy
        self.thetarx = thetarx
        self.thetary = thetary
        self.psi = psi
        self.df = df
        self.dt = dt
        self.dx = dx
        self.nf = nf
        self.nt = nt
        self.nx = nx
        self.ncuts = ncuts
        self.backend = resolve_backend(backend)

        self.calc_brightness()
        if plot:
            self.plot_acf_efield(figsize=figsize)
            self.plot_brightness(figsize=figsize)
        if calc_sspec:
            self.calc_SS()
            if plot:
                self.plot_sspec(figsize=figsize)
                self.plot_cuts(figsize=figsize)
        if calc_acf:
            self.calc_acf()
            if plot:
                self.plot_acf(figsize=figsize, contour=contour)

    def calc_brightness(self):
        """E-field ACF → fft2 → brightness B(θx, θy)
        (scint_sim.py:838-869)."""
        x = np.arange(-self.nx, self.nx, self.dx)
        self.X, self.Y = np.meshgrid(x, x)
        R = (self.ar ** 2 - 1) / (self.ar ** 2 + 1)
        cosa = np.cos(2 * (90 - self.psi) * np.pi / 180)
        sina = np.sin(2 * (90 - self.psi) * np.pi / 180)
        a = (1 - R * cosa) / np.sqrt(1 - R ** 2)
        b = (1 + R * cosa) / np.sqrt(1 - R ** 2)
        c = -2 * R * sina / np.sqrt(1 - R ** 2)
        Rho = np.exp(-0.5 * (a * self.X ** 2 + b * self.Y ** 2
                             + c * self.X * self.Y) ** (self.alpha / 2))
        self.x = x
        self.acf_efield = Rho
        B = np.fft.ifftshift(np.fft.fft2(np.fft.fftshift(Rho)))
        self.B = np.abs(B)

    def calc_SS(self):
        """Map brightness to (fd, td) with bounded Jacobian
        (scint_sim.py:871-951), vectorised."""
        xp = get_xp(self.backend)
        fd = np.arange(-self.nf, self.nf, self.df)
        td = np.arange(-self.nt, self.nt, self.dt)
        self.fd = fd
        self.td = td

        FD = xp.asarray(fd)[None, :]
        TD = xp.asarray(td)[:, None]
        thetax = (FD - self.thetagx + self.thetarx) * xp.ones_like(TD)
        typ_sq = (TD - (thetax + self.thetagx) ** 2
                  + self.thetarx ** 2 + self.thetary ** 2)
        pos = typ_sq > 0
        thymthgy = xp.sqrt(xp.where(pos, typ_sq, 1.0))  # thetay − thetagy
        thetay = xp.where(pos, thymthgy - self.thetagy, 0.0)
        amp = xp.where(
            pos,
            xp.where(thymthgy < 0.5 * self.df, 2 / self.df, 1 / thymthgy),
            1e-6)

        self.thetax = np.asarray(thetax)
        self.thetay = np.asarray(thetay)
        self.jacobian = np.asarray(amp)

        B = xp.asarray(self.B)
        x0, dx = float(self.x[0]), float(self.dx)
        SS = (_bilinear(B, x0, dx, thetax, thetay, xp) * amp
              + _bilinear(B, x0, dx, thetax, -thetay, xp) * amp)
        SS = np.array(SS)  # writable host copy

        # add the point-mirrored spectrum (scint_sim.py:943-948)
        SSrev = np.flip(np.flip(SS[1:, 1:], axis=0), axis=1)
        SS[1:, 1:] += SSrev
        self.SS = SS
        with np.errstate(divide="ignore", invalid="ignore"):
            self.LSS = 10 * np.log10(SS)

    def calc_acf(self):
        """ACF as fft2 of the secondary spectrum (scint_sim.py:953-958)."""
        SS = np.nan_to_num(self.SS)
        acf = np.fft.fftshift(np.fft.fft2(np.fft.fftshift(SS)))
        acf = np.real(acf)
        acf /= np.max(acf)
        self.acf = acf

    # -- plotting (scint_sim.py:960-1065) ------------------------------
    def plot_acf_efield(self, figsize=(6, 6), **kwargs):
        from .plots import plot_brightness_efield
        return plot_brightness_efield(self, figsize=figsize, **kwargs)

    def plot_brightness(self, figsize=(6, 6), **kwargs):
        from .plots import plot_brightness_dist
        return plot_brightness_dist(self, figsize=figsize, **kwargs)

    def plot_sspec(self, figsize=(6, 6), **kwargs):
        from .plots import plot_brightness_sspec
        return plot_brightness_sspec(self, figsize=figsize, **kwargs)

    def plot_acf(self, figsize=(6, 6), contour=True, **kwargs):
        from .plots import plot_brightness_acf
        return plot_brightness_acf(self, figsize=figsize,
                                   contour=contour, **kwargs)

    def plot_cuts(self, figsize=(6, 6), **kwargs):
        from .plots import plot_brightness_cuts
        return plot_brightness_cuts(self, figsize=figsize, **kwargs)
