"""Closed-loop scenario survey: generate → search → fit, on device.

The device-native factory (sim/factory.py) makes synthetic epochs
cheap enough to be a SURVEY PRODUCT: this module flows
factory-generated epochs straight into the existing batched
search/fit through the full robustness stack
(robust/runner.py:run_survey_batched — ladder fallback, CRC journal,
SIGKILL resume, RunReport), pinning η / τ_d / Δν_d recovery against
each lane's closed-form ground truth across regime sweeps. It is the
fuzzing loop ROADMAP item 4 calls for (≥10⁴ synthetic epochs per run;
the bench `scenario_loop` config runs ≥10³ on the 1-core CPU host)
and the workload that makes a multi-host fleet worth scaling.

Shape of one batch (one device program each stage, epochs resident in
HBM throughout — the dynspec stack never round-trips the host on the
fused tier):

1. **generate** — ``simulate_scenarios(device_out=True)``: per-lane
   regime params (mb2/ar/psi/alpha) ride the batch axis of ONE
   compiled factory program; lanes are keyed by their epoch seed
   (``lane_keys_from_seeds``), so an epoch's data is independent of
   batch grouping, quarantined neighbours, and resume boundaries.
2. **search** — batched secondary spectra (cached ``sim.scenario_sspec``
   program) → ``ops/fitarc.py:fit_arc_batch`` arc-curvature
   measurement, with the per-lane η search window derived from the
   lane's theoretical curvature.
3. **fit** — ``fit/batch.py:scint_params_batch(device=stack)``:
   vmapped LM over the whole stack for (τ_d, Δν_d, amp).

Fallback ladder: a lane the batch path rejects descends to the STAGED
tier (single-lane factory at ``precision='highest'`` + the same jax
fits) and finally to the NUMPY tier (the reference ``Simulation``
class + host scipy fits) — the closed loop exercises every tier the
real surveys use.

Ground truths (per lane, closed form — scint_sim.py:123-134 and the
``set_constants`` normalisation): the arc curvature η is exact; the
scintillation timescale is ``τ_d = s0/V`` (s0 the diffractive scale,
V = ds/dt the effective velocity); the decorrelation bandwidth scales
as ``Δν_d ∝ f · (s0/rf)²`` with an O(1) constant calibrated once
against the simulator's own convention (``DNU_CAL``, measured on the
f64 oracle path and pinned in tests/test_sim_factory.py).
"""

from __future__ import annotations

import numpy as np
from scipy.special import gamma as _gamma

from ..backend import get_jax
from ..utils import slog

#: τ_d / Δν_d calibration of the Fresnel↔diffractive crossover to
#: THIS simulator's convention, measured on the f64 oracle path at
#: ns=256 (and cross-checked at ns=128/ds=0.02 and ns=64/ds=0.04 —
#: recovered/true ratios 0.8–1.2 across mb2 ∈ [0.5, 32] and ar ∈
#: [1, 2]): the intensity decorrelation scale saturates at
#: ``TAU_FRES·rf`` in weak scattering and follows ``TAU_DIFF·s0`` in
#: strong scattering; the decorrelation bandwidth saturates at
#: ``DNU_FRES`` of the band and falls as ``DNU_DIFF·(s0/rf)`` of it
#: when diffractive. Harmonic (inverse-quadrature) crossover between
#: the limits; ``ar``'s calibrated effect is ``τ ∝ ar^-1/2``,
#: ``Δν ∝ ar^1/4`` at ψ=30°.
TAU_FRES = 0.19
TAU_DIFF = 1.3
DNU_FRES = 0.65
DNU_DIFF = 1.95

#: default regime sweep: weak (Fresnel-limited) / strong
#: (diffractive) scattering and anisotropy — one compiled factory
#: program serves all of them (traced lane params).
DEFAULT_REGIMES = (
    {"name": "weak", "mb2": 0.5, "ar": 1.0, "psi": 0.0,
     "alpha": 5 / 3},
    {"name": "strong", "mb2": 16.0, "ar": 1.0, "psi": 0.0,
     "alpha": 5 / 3},
    {"name": "aniso", "mb2": 16.0, "ar": 2.0, "psi": 30.0,
     "alpha": 5 / 3},
)


def scenario_truths(mb2, ar, psi, alpha, rf=1.0, ds=0.02, dt=30.0,
                    freq=1400.0, dlam=0.05):
    """Closed-form per-lane ground truths ``{eta, tau, dnu}`` (host
    numpy, broadcastable lane arrays).

    ``eta`` [s³] is the reference's exact theoretical arc curvature
    (scint_sim.py:123-133; numerically identical to us/mHz² on the
    sspec axes ``sspec_axes`` builds). ``tau`` [s] and ``dnu`` [MHz]
    are the calibrated Fresnel↔diffractive crossover forms (constants
    above): the diffractive scale is ``s0 = rf·cdrf^(1/α)``
    (``set_constants``), the effective velocity ``V = ds/dt``."""
    mb2, ar, psi, alpha = np.broadcast_arrays(
        *(np.asarray(v, dtype=float) for v in (mb2, ar, psi, alpha)))
    a2 = alpha * 0.5
    cdrf = (2.0 ** alpha * np.cos(alpha * np.pi * 0.25)
            * _gamma(1.0 + a2) / mb2)
    s0 = rf * cdrf ** (1.0 / alpha)
    V = ds / dt
    k_wave = 2 * np.pi / freq
    eta = (rf ** 2 * k_wave / (2 * V ** 2) / 1e6
           / np.cos(psi * np.pi / 180) ** 2)
    tau = 1.0 / (V * np.sqrt((1 / (TAU_FRES * rf)) ** 2
                             + (1 / (TAU_DIFF * s0)) ** 2)
                 * np.sqrt(ar))
    band = freq * dlam
    dnu = (band / np.sqrt(1 / DNU_FRES ** 2
                          + (rf / (DNU_DIFF * s0)) ** 2)
           * ar ** 0.25)
    return {"eta": eta, "tau": tau, "dnu": dnu}


_SSPEC_DB_CACHE = {}


def make_sspec_db_batch(nt, nf, window="hanning", window_frac=0.1):
    """Cached jitted batched secondary spectrum in dB:
    ``fn(dyns[B, nf, nt]) → sec_db[B, ntdel, nfdop]`` — the search
    stage's front half, one program per epoch geometry
    (``sim.scenario_sspec`` retrace site)."""
    from ..ops.sspec import secondary_spectrum_power
    from ..ops.windows import get_window

    key = (int(nt), int(nf), window, float(window_frac))
    fn = _SSPEC_DB_CACHE.get(key)
    if fn is None:
        jax = get_jax()
        import jax.numpy as jnp

        from ..obs import retrace as _retrace

        _retrace.record_build("sim.scenario_sspec", key)
        wins = get_window(nt, nf, window=window, frac=window_frac)

        def run(dyns):
            power = jax.vmap(lambda d: secondary_spectrum_power(
                d, window_arrays=wins, backend="jax"))(dyns)
            return 10.0 * jnp.log10(power)

        fn = jax.jit(run)
        if len(_SSPEC_DB_CACHE) >= 16:
            _SSPEC_DB_CACHE.pop(next(iter(_SSPEC_DB_CACHE)))
        _SSPEC_DB_CACHE[key] = fn
    return fn


def _lane_table(regimes, epochs_per_regime, seed):
    """The survey's epoch list: ``(epoch_id, payload)`` with tiny
    host payloads carrying the lane's regime params and its
    deterministic integer seed (the device key derives from it)."""
    epochs = []
    for ri, reg in enumerate(regimes):
        for i in range(epochs_per_regime):
            lane_seed = int(seed) * 1000003 + ri * 100003 + i
            epochs.append((f"{reg['name']}/{i:05d}", {
                "regime": reg["name"],
                "mb2": float(reg.get("mb2", 2.0)),
                "ar": float(reg.get("ar", 1.0)),
                "psi": float(reg.get("psi", 0.0)),
                "alpha": float(reg.get("alpha", 5 / 3)),
                "seed": lane_seed & 0x7FFFFFFF,
            }))
    return epochs


def scenario_workload(regimes=DEFAULT_REGIMES, epochs_per_regime=128,
                      ns=128, nf=64, dlam=0.05, rf=1.0, ds=0.02,
                      dt=30.0, freq=1400.0, inner=0.001, seed=0,
                      numsteps=1500, n_iter=60,
                      eta_window=(0.2, 5.0)):
    """The closed-loop scenario survey as a WORKLOAD: the epoch table
    plus the batched/per-epoch process functions, without a runner
    attached. :func:`run_scenario_survey` feeds it to the batched
    runner in-process; the fleet tier resolves it by spec
    (``{"target": "scintools_tpu.sim.scenario:scenario_workload",
    "params": {...}}`` — every parameter here is JSON-able) in each
    worker process, so N workers compile the same geometry-keyed
    programs against the same deterministic per-epoch lanes. Returns
    ``{"epochs", "process_batch", "process"}``."""
    jax = get_jax()
    import jax.numpy as jnp

    from ..fit.batch import scint_params_batch
    from ..ops.fitarc import fit_arc, fit_arc_batch
    from ..ops.sspec import sspec_axes
    from ..robust.ladder import TIER_NUMPY
    from .factory import lane_keys_from_seeds, simulate_scenarios
    from .simulation import Simulation

    nt = ns                                   # factory: (ns time, nf)
    df = freq * dlam / (nf - 1)
    fdop, tdel, _ = sspec_axes(nf, nt, dt, df)
    sspec_db = make_sspec_db_batch(nt, nf)
    epochs = _lane_table(regimes, epochs_per_regime, seed)

    def _truths(p):
        t = scenario_truths(p["mb2"], p["ar"], p["psi"], p["alpha"],
                            rf=rf, ds=ds, dt=dt, freq=freq, dlam=dlam)
        return {k: float(v) for k, v in t.items()}

    def _result(p, eta, etaerr, fits, i, code):
        t = _truths(p)
        return {
            "ok": int(code), "regime": p["regime"],
            "eta": float(eta), "etaerr": float(etaerr),
            "tau": float(fits["tau"][i]),
            "tauerr": float(fits["tauerr"][i]),
            "dnu": float(fits["dnu"][i]),
            "dnuerr": float(fits["dnuerr"][i]),
            "eta_true": t["eta"], "tau_true": t["tau"],
            "dnu_true": t["dnu"],
        }

    def _fit_stack(dyns_dev, payloads):
        """Search + fit a device-resident epoch stack (B, nf, nt):
        batched sspec → arc fit, batched acf1d LM."""
        sec_db = sspec_db(dyns_dev)
        truths = [_truths(p) for p in payloads]
        etas_t = np.array([t["eta"] for t in truths])
        arcs = fit_arc_batch(
            np.asarray(sec_db), tdel, fdop, numsteps=numsteps,
            etamin=eta_window[0] * etas_t,
            etamax=eta_window[1] * etas_t,
            sspecs_device=sec_db, full_output=False)
        fits = scint_params_batch(dyns_dev, dt, df, n_iter=n_iter)
        return arcs, fits

    def process_batch(payloads, tier=None):
        B = len(payloads)
        keys = lane_keys_from_seeds([p["seed"] for p in payloads])
        dyn, code = simulate_scenarios(
            B, mb2=[p["mb2"] for p in payloads],
            ar=[p["ar"] for p in payloads],
            psi=[p["psi"] for p in payloads],
            alpha=[p["alpha"] for p in payloads],
            ns=ns, nf=nf, dlam=dlam, rf=rf, ds=ds, inner=inner,
            keys=keys, with_ok=True, device_out=True)
        dyns = jnp.transpose(dyn, (0, 2, 1))          # (B, nf, nt)
        arcs, fits = _fit_stack(dyns, payloads)
        code = np.asarray(code)
        out = []
        for i, p in enumerate(payloads):
            eta = getattr(arcs[i], "eta", np.nan)
            err = getattr(arcs[i], "etaerr", np.nan)
            lane = int(code[i])
            if lane == 0 and not (np.isfinite(eta)
                                  and np.isfinite(fits["tau"][i])
                                  and np.isfinite(fits["dnu"][i])):
                lane = 8                    # fit refused (guards.BAD_FIT)
            out.append(_result(p, eta, err, fits, i, lane))
        return out

    def _params_ok(p):
        vals = (p["mb2"], p["ar"], p["psi"], p["alpha"])
        return (all(np.isfinite(v) for v in vals) and p["mb2"] > 0
                and p["ar"] > 0 and 0 < p["alpha"] < 2)

    def process(p, tier=None):
        """Per-epoch fallback tiers: STAGED = single-lane factory on
        the exact oracle path + jax fits; NUMPY = the reference
        ``Simulation`` + host scipy arc fit. Invalid lane params are
        the sim-side malformed input — no tier can fix them, so the
        ladder aborts straight to quarantine."""
        from ..io import MalformedInputError

        if not _params_ok(p):
            raise MalformedInputError(
                f"<lane seed={p['seed']}>",
                "invalid regime params (non-finite or out of range)")
        if tier == TIER_NUMPY:
            sim = Simulation(ns=ns, nf=nf, dlam=dlam, seed=p["seed"],
                             mb2=p["mb2"], ar=p["ar"], psi=p["psi"],
                             alpha=p["alpha"], rf=rf, ds=ds,
                             inner=inner, dt=dt, freq=freq,
                             backend="numpy")
            dyn1 = np.asarray(sim.dyn, dtype=float)[None]
            from ..ops.sspec import secondary_spectrum

            _, _, sec = secondary_spectrum(dyn1[0], dt, df,
                                           backend="numpy")
            t = _truths(p)
            arc = fit_arc(np.asarray(sec), tdel, fdop,
                          numsteps=numsteps,
                          etamin=eta_window[0] * t["eta"],
                          etamax=eta_window[1] * t["eta"],
                          backend="numpy")[0]
            fits = scint_params_batch(dyn1, dt, df, n_iter=n_iter,
                                      backend="numpy")
            return _result(p, arc.eta, arc.etaerr, fits, 0, 0)
        # staged oracle tier: exact-exp column propagation, highest
        # precision, single lane
        keys = lane_keys_from_seeds([p["seed"]])
        dyn, code = simulate_scenarios(
            1, mb2=p["mb2"], ar=p["ar"], psi=p["psi"],
            alpha=p["alpha"], ns=ns, nf=nf, dlam=dlam, rf=rf, ds=ds,
            inner=inner, keys=keys, precision="highest",
            with_ok=True, device_out=True)
        lane = int(np.asarray(code)[0])
        if lane != 0:
            # a flagged staged lane is a FAILED attempt, not a result
            # — raise so the ladder descends to the numpy tier
            raise ValueError(f"staged lane unhealthy (code {lane})")
        dyns = jnp.transpose(dyn, (0, 2, 1)).astype(jnp.float32)
        arcs, fits = _fit_stack(dyns, [p])
        return _result(p, getattr(arcs[0], "eta", np.nan),
                       getattr(arcs[0], "etaerr", np.nan), fits, 0,
                       lane)

    return {"epochs": epochs, "process_batch": process_batch,
            "process": process}


def run_scenario_survey(workdir, regimes=DEFAULT_REGIMES,
                        epochs_per_regime=128, ns=128, nf=64,
                        dlam=0.05, rf=1.0, ds=0.02, dt=30.0,
                        freq=1400.0, inner=0.001, batch_size=64,
                        seed=0, numsteps=1500, n_iter=60,
                        eta_window=(0.2, 5.0), resume=True,
                        heartbeat=None, report=True, retries=1):
    """The closed generate → search → fit loop as a journaled survey
    (module docstring). Returns the :func:`run_survey_batched` result
    extended with ``"recovery"``: per-regime median relative errors
    of η / τ_d / Δν_d against the closed-form truths, over healthy
    lanes.

    Every per-epoch result dict carries the recovered AND true
    parameter values plus the lane health code, so the journal (and
    therefore resume, the RunReport, and any downstream reader) is a
    self-contained record of the recovery experiment."""
    from ..robust import run_survey_batched

    wl = scenario_workload(
        regimes=regimes, epochs_per_regime=epochs_per_regime, ns=ns,
        nf=nf, dlam=dlam, rf=rf, ds=ds, dt=dt, freq=freq,
        inner=inner, seed=seed, numsteps=numsteps, n_iter=n_iter,
        eta_window=eta_window)
    epochs = wl["epochs"]
    with slog.span("sim.scenario_survey", n_epochs=len(epochs),
                   n_regimes=len(regimes), ns=ns, nf=nf,
                   batch_size=batch_size):
        out = run_survey_batched(
            epochs, wl["process_batch"], workdir,
            process=wl["process"], batch_size=batch_size,
            retries=retries, resume=resume, heartbeat=heartbeat,
            report=report)
    out["recovery"] = recovery_summary(out["results"])
    slog.log_event("sim.scenario_summary",
                   n_epochs=len(epochs),
                   recovery={r: {k: round(v, 4) for k, v in d.items()}
                             for r, d in out["recovery"].items()})
    return out


def run_scenario_fleet(workdir, n_workers=3, batch_size=48,
                       timeout=900.0, pod_options=None,
                       plane_port=None, **workload_params):
    """The scenario survey DISTRIBUTED: the same closed
    generate → search → fit loop, run by ``n_workers`` independent
    worker processes coordinating through the fleet work queue
    (fleet/pod.py) — epoch-batch tasks, lease-based work-stealing,
    per-worker journals merged deterministically into one canonical
    survey journal + merged RunReport. ``workload_params`` are
    :func:`scenario_workload` parameters (JSON-able — they travel to
    the worker processes by spec file). Returns the pod result
    extended with the per-regime ``"recovery"`` summary, exactly like
    :func:`run_scenario_survey`.

    ``plane_port`` (0 = ephemeral, advertised in
    ``<workdir>/plane.json``) starts the fleet observability plane
    alongside the pod: one port serving the merged ``/metrics`` /
    ``/state`` / ``/report`` / ``/workers`` view of the whole run,
    live (docs/observability.md "Fleet observability plane")."""
    from ..fleet.pod import run_pod

    spec = {"target": "scintools_tpu.sim.scenario:scenario_workload",
            "params": dict(workload_params)}
    options = dict(pod_options or {})
    if plane_port is not None:
        options.setdefault("plane_port", plane_port)
    out = run_pod(workdir, spec, n_workers=n_workers,
                  batch_size=batch_size, timeout=timeout,
                  **options)
    out["recovery"] = recovery_summary(out["results"])
    slog.log_event("sim.scenario_summary",
                   n_epochs=out["summary"]["n_epochs"],
                   recovery={r: {k: round(v, 4) for k, v in d.items()}
                             for r, d in out["recovery"].items()})
    return out


def recovery_summary(results):
    """Per-regime median relative recovery errors (and lane counts)
    over the healthy lanes of a scenario-survey result map."""
    by_regime = {}
    for rec in results.values():
        if not isinstance(rec, dict) or "eta_true" not in rec:
            continue
        by_regime.setdefault(rec.get("regime", "?"), []).append(rec)
    out = {}
    for regime, recs in sorted(by_regime.items()):
        rel = {"eta": [], "tau": [], "dnu": []}
        n_ok = 0
        for r in recs:
            if int(r.get("ok", 1)) != 0:
                continue
            n_ok += 1
            for k in rel:
                truth = r[f"{k}_true"]
                if np.isfinite(r[k]) and truth:
                    rel[k].append(abs(r[k] - truth) / abs(truth))
        out[regime] = {
            "n": len(recs), "n_ok": n_ok,
            **{f"{k}_med_rel": float(np.median(v)) if v else np.nan
               for k, v in rel.items()},
        }
    return out


# ---------------------------------------------------------------------
# abstract program probe (obs/programs.py) — audited by the jaxlint
# JP2xx program pass (tools/jaxlint/program.py)
# ---------------------------------------------------------------------

from ..obs.programs import register_probe as _register_probe  # noqa: E402


@_register_probe("sim.scenario_sspec")
def _probe_scenario_sspec():
    """The cached batched sspec-dB program (search-stage front half)
    at a fixed 16x16 epoch geometry, 2 lanes."""
    import jax

    fn = make_sspec_db_batch(16, 16)
    S = jax.ShapeDtypeStruct
    return fn, (S((2, 16, 16), np.float32),)
