"""Plotting for the simulation classes.

Host-side presentation layer for :class:`~scintools_tpu.sim.Simulation`
(reference plot methods scint_sim.py:313-415), :class:`ACF`
(scint_sim.py:680-765) and :class:`Brightness` (scint_sim.py:960-1065).
All numerics live in the sim kernels; these functions only render the
arrays the classes already hold, so they take the sim object first and
are also attached as methods for reference-API parity.
"""

from __future__ import annotations

import os

import numpy as np

from ..plotting import _mpl, _finish
from ..utils.misc import is_valid, centres_to_edges


# ---------------------------------------------------------------- Simulation

def plot_screen(sim, subplot=False, filename=None, display=True, dpi=200):
    """Phase-screen image (scint_sim.py:313-324)."""
    plt = _mpl()
    fig = plt.gcf() if subplot else plt.figure()
    x_steps = np.linspace(0, sim.dx * sim.nx, sim.nx)
    y_steps = np.linspace(0, sim.dy * sim.ny, sim.ny)
    plt.pcolormesh(x_steps, y_steps, np.transpose(sim.xyp),
                   shading="auto")
    plt.title("Screen phase")
    plt.ylabel(r"$y/r_f$")
    plt.xlabel(r"$x/r_f$")
    if subplot:
        return fig
    return _finish(plt, fig, filename, display, dpi)


def plot_intensity(sim, subplot=False, filename=None, display=True,
                   dpi=200):
    """Observer-plane intensity image (scint_sim.py:326-338)."""
    plt = _mpl()
    fig = plt.gcf() if subplot else plt.figure()
    x_steps = np.linspace(0, sim.dx * sim.nx, sim.nx)
    y_steps = np.linspace(0, sim.dy * sim.ny, sim.ny)
    plt.pcolormesh(x_steps, y_steps, np.transpose(sim.xyi),
                   shading="auto")
    plt.title("Intensity / Mean")
    plt.ylabel(r"$y/r_f$")
    plt.xlabel(r"$x/r_f$")
    if subplot:
        return fig
    return _finish(plt, fig, filename, display, dpi)


def plot_sim_dynspec(sim, subplot=False, filename=None, display=True,
                     dpi=200):
    """Simulated dynamic spectrum in sim-normalised axes
    (scint_sim.py:340-354)."""
    plt = _mpl()
    fig = plt.gcf() if subplot else plt.figure()
    if not hasattr(sim, "spi"):  # nf=1 runs skip get_dynspec
        sim.get_dynspec()        # (scint_sim.py:341-342)
    yaxis = sim.lams if sim.lamsteps else sim.freqs
    plt.pcolormesh(sim.x, yaxis, np.transpose(sim.spi), shading="auto")
    plt.ylabel(r"Wavelength $\lambda$" if sim.lamsteps
               else "Frequency f")
    plt.title("Dynamic Spectrum (Intensity/Mean)")
    plt.xlabel(r"$x/r_f$")
    if subplot:
        return fig
    return _finish(plt, fig, filename, display, dpi)


def plot_efield(sim, subplot=False, filename=None, display=True,
                dpi=200):
    """Real part of the propagated electric field
    (scint_sim.py:356-372)."""
    plt = _mpl()
    fig = plt.gcf() if subplot else plt.figure()
    if not hasattr(sim, "x"):    # axes come from get_dynspec
        sim.get_dynspec()        # (scint_sim.py:357-358 guard role)
    yaxis = sim.lams if sim.lamsteps else sim.freqs
    plt.pcolormesh(sim.x, yaxis, np.real(np.transpose(sim.spe)),
                   shading="auto")
    plt.ylabel(r"Wavelength $\lambda$" if sim.lamsteps
               else "Frequency f")
    plt.title("Electric field (Intensity/Mean)")
    plt.xlabel(r"$x/r_f$")
    if subplot:
        return fig
    return _finish(plt, fig, filename, display, dpi)


def plot_delay(sim, filename=None, display=True, dpi=200):
    """Group delay along the screen + mean impulse response
    (scint_sim.py:374-387)."""
    plt = _mpl()
    fig = plt.figure()
    freq_ghz = sim.freq / 1000
    plt.subplot(2, 1, 1)
    plt.plot(np.linspace(0, sim.dx * sim.nx, sim.nx),
             -sim.dm / (2 * sim.dlam * freq_ghz))
    plt.ylabel("Group delay (ns)")
    plt.xlabel(r"$x/r_f$")
    plt.subplot(2, 1, 2)
    plt.plot(np.mean(sim.pulsewin, axis=1))
    plt.ylabel("Intensity (arb)")
    plt.xlabel("Delay (arb)")
    return _finish(plt, fig, filename, display, dpi)


def plot_pulse(sim, filename=None, display=True, dpi=200):
    """Log pulse-response waterfall with the group-delay overlay
    (scint_sim.py:389-404)."""
    plt = _mpl()
    fig = plt.figure()
    freq_ghz = sim.freq / 1000
    with np.errstate(divide="ignore"):
        lpw = np.log10(sim.pulsewin)
    vmax = np.max(lpw[np.isfinite(lpw)])
    vmin = np.median(lpw[np.isfinite(lpw)]) - 3
    x = np.linspace(0, sim.dx * sim.nx, sim.nx)
    delay = (np.arange(0, 3 * sim.nf / 2, 1) - sim.nf / 2) / (
        2 * sim.dlam * freq_ghz)
    plt.pcolormesh(x, delay, lpw[int(sim.nf / 2):, :], vmin=vmin,
                   vmax=vmax, shading="auto")
    plt.ylabel("Delay (ns)")
    plt.xlabel(r"$x/r_f$")
    # group delay = -phase delay
    plt.plot(x, -sim.dm / (2 * sim.dlam * freq_ghz), "k")
    return _finish(plt, fig, filename, display, dpi)


def plot_sim_all(sim, filename=None, display=True, dpi=200):
    """2×2 summary figure: screen, intensity, dynspec
    (scint_sim.py:406-414)."""
    plt = _mpl()
    fig = plt.figure(figsize=(9, 7))
    plt.subplot(2, 2, 1)
    plot_screen(sim, subplot=True)
    plt.subplot(2, 2, 2)
    plot_intensity(sim, subplot=True)
    plt.subplot(2, 1, 2)
    plot_sim_dynspec(sim, subplot=True)
    fig.tight_layout()
    return _finish(plt, fig, filename, display, dpi)


# ----------------------------------------------------------------------- ACF

def plot_acf_model(acf, display=True, contour=True, filled=False,
                   filename=None, dpi=200):
    """Model intensity ACF with optional 0.2–0.8 contours
    (scint_sim.py:680-709)."""
    plt = _mpl()
    fig = plt.figure()
    tn_edges = centres_to_edges(acf.tn)
    fn_edges = centres_to_edges(acf.fn)
    levels = acf.amp * np.array([0.2, 0.4, 0.6, 0.8])
    if not filled:
        plt.pcolormesh(tn_edges, fn_edges, acf.acf, shading="auto")
        if contour:
            plt.contour(acf.tn, acf.fn, acf.acf, levels, colors="k")
    else:
        plt.contourf(acf.tn, acf.fn, acf.acf,
                     acf.amp * np.arange(0, 1.05, 0.1))
    plt.xlabel(r"Time lag ($\tau/\tau_{d,\rm{iso}}$)")
    plt.ylabel(r"Frequency lag ($\Delta\nu/\Delta\nu_{d,\rm{iso}}$)")
    if display or filename:
        plt.title("ACF of intensity")
    return _finish(plt, fig, filename, display, dpi)


def plot_acf_efield_model(acf, display=True, filename=None, dpi=200):
    """Electric-field ACF on the spatial integration grid
    (scint_sim.py:711-726)."""
    plt = _mpl()
    fig = plt.figure()
    snp_edges = centres_to_edges(acf.snp)
    plt.pcolormesh(snp_edges, snp_edges, acf.acf_efield, shading="auto")
    plt.xlabel(r"$S_x$ ($x/s_{d,\rm{iso}}$)")
    plt.ylabel(r"$S_y$ ($y/s_{d,\rm{iso}}$)")
    plt.title("ACF of electric field")
    return _finish(plt, fig, filename, display, dpi)


def plot_acf_sspec(acf, display=True, vmin=None, vmax=None,
                   filename=None, dpi=200):
    """Secondary spectrum of the model ACF (scint_sim.py:744-765)."""
    plt = _mpl()
    fig = plt.figure()
    if not hasattr(acf, "sspec"):
        acf.calc_sspec()
    sspec = acf.sspec
    good = is_valid(sspec) & (np.abs(sspec) > 0)
    medval = np.median(sspec[good])
    maxval = np.max(sspec[good])
    vmin = medval - 3 if vmin is None else vmin
    vmax = maxval - 3 if vmax is None else vmax
    plt.pcolormesh(acf.tn, acf.fn, sspec, vmin=vmin, vmax=vmax,
                   shading="auto")
    plt.colorbar()
    plt.xlabel("Delay")
    plt.ylabel("Doppler")
    plt.title("Secondary spectrum (dB)")
    return _finish(plt, fig, filename, display, dpi)


# ---------------------------------------------------------------- Brightness

def _bright_title(br, what):
    return ("{0} for ar={1}, psi={2}, alpha={3}".format(
        what, br.ar, br.psi, br.alpha)
        + "\n Gradient Angle ({0}, {1}) Reference Angle ({2}, {3})"
        .format(br.thetagx, br.thetagy, br.thetarx, br.thetary))


def plot_brightness_efield(br, figsize=(6, 6), filename=None,
                           display=True, dpi=200):
    """E-field ACF on the (x, y) grid (scint_sim.py:960-969)."""
    plt = _mpl()
    fig = plt.figure(figsize=figsize)
    plt.pcolormesh(br.x, br.x, br.acf_efield, shading="auto")
    plt.grid(linewidth=0.2)
    plt.colorbar()
    plt.title("ACF of E-field for ar={0}, psi={1}, alpha={2}".format(
        br.ar, br.psi, br.alpha))
    plt.xlabel("X = velocity axis")
    plt.ylabel("Y axis")
    return _finish(plt, fig, filename, display, dpi)


def plot_brightness_dist(br, figsize=(6, 6), filename=None,
                         display=True, dpi=200):
    """Brightness distribution in dB (scint_sim.py:971-980)."""
    plt = _mpl()
    fig = plt.figure(figsize=figsize)
    with np.errstate(divide="ignore"):
        db = 10 * np.log10(br.B)
    plt.pcolormesh(br.x, br.x, db, shading="auto")
    plt.grid(linewidth=0.2)
    plt.colorbar()
    plt.title(_bright_title(br, "Brightness (dB)"))
    plt.xlabel(r"$\theta_x$ = velocity axis")
    plt.ylabel(r"$\theta_y$ axis")
    return _finish(plt, fig, filename, display, dpi)


def plot_brightness_sspec(br, figsize=(6, 6), filename=None,
                          display=True, dpi=200):
    """Delay-Doppler spectrum in dB (scint_sim.py:982-998)."""
    plt = _mpl()
    fig = plt.figure(figsize=figsize)
    plt.pcolormesh(br.fd, br.td, br.LSS, shading="auto")
    plt.colorbar()
    good = br.SS > 1e-6
    medval = np.median(br.LSS[good])
    maxval = np.max(br.LSS[good])
    plt.clim((medval - 3, maxval - 3))
    plt.title(_bright_title(br, "Delay-Doppler Spectrum (dB)"))
    plt.ylabel("Delay")
    plt.xlabel("Doppler")
    return _finish(plt, fig, filename, display, dpi)


def plot_brightness_acf(br, figsize=(6, 6), contour=True, filename=None,
                        display=True, dpi=200):
    """Intensity ACF from the brightness distribution
    (scint_sim.py:1000-1020)."""
    plt = _mpl()
    fig = plt.figure(figsize=figsize)
    plt.pcolormesh(br.fd, br.td, br.acf, shading="auto")
    plt.colorbar()
    if contour:
        plt.contour(br.fd, br.td, br.acf, [0.2, 0.4, 0.6, 0.8],
                    colors="k")
        plt.contour(br.fd, br.td, br.acf, [0.0], colors="r",
                    linestyles="dotted")
    plt.title(_bright_title(br, "ACF (Time, Freq)"))
    plt.ylim((-4, 4))
    plt.xlim((-1, 1))
    plt.xlabel("Time")
    plt.ylabel("Frequency")
    return _finish(plt, fig, filename, display, dpi)


def _suffixed(filename, tag):
    """Insert ``tag`` before the file extension."""
    if filename is None:
        return None
    root, ext = os.path.splitext(filename)
    return root + tag + ext


def plot_brightness_cuts(br, figsize=(6, 6), filename=None,
                         display=True, dpi=200):
    """Constant-delay Doppler cuts and the zero-Doppler delay cut
    (scint_sim.py:1022-1065). Returns (fig_cuts, fig_delay)."""
    plt = _mpl()
    fig1 = plt.figure(figsize=figsize)
    nt = len(br.td)
    # clamp: for ncuts values that don't divide nt/2 the reference's
    # index walk steps past the end of LSS (scint_sim.py:1035), and
    # ncuts > nt/2 would make the step zero
    step = max(int((nt / 2) / br.ncuts), 1)
    for itdp in range(int(nt / 2) + step - 1, nt + step - 1, step):
        plt.plot(br.fd, br.LSS[min(itdp, nt - 1), :])
    mn = np.min(br.LSS[nt - 1, round(len(br.fd) / 2 - 1)])
    yl = plt.ylim()
    plt.ylim((mn - 10, yl[1]))
    plt.title(_bright_title(br, "{0} Cuts in Doppler at constant Delay"
                            .format(br.ncuts)))
    plt.xlabel("Doppler")
    plt.ylabel("Log Power")
    plt.grid()
    f1 = _finish(plt, fig1, _suffixed(filename, "_doppler"),
                 display, dpi)

    fig2 = plt.figure(figsize=figsize)
    fi = int(np.argmin(np.abs(br.fd)))
    ti = np.flatnonzero(br.td >= 0)
    # semilogx drops td==0 silently; keep strictly positive delays
    pos = ti[br.td[ti] > 0]
    plt.semilogx(br.td[pos], br.LSS[pos, fi])
    plt.grid()
    plt.title(_bright_title(br, "Cut in Delay at Doppler=0"))
    plt.xlabel("Delay")
    plt.ylabel("Log Power")
    f2 = _finish(plt, fig2, _suffixed(filename, "_delay"),
                 display, dpi)
    return f1, f2
